"""Per-PR perf trajectory: diff committed BENCH_*.json rounds into a table.

Every bench round commits one JSON record (bench.py, last-JSON-line-wins).
This module — stdlib-only, importable by both ``scripts/perf_delta.py`` and
``prime bench delta`` — loads every committed round, labels each with its
record schema (schema 1: the pre-loadgen rounds, headline-only fields;
schema 2: adds the loadgen SLO report under ``loadgen``), and renders the
metric-by-round delta table that answers the only question a perf PR has to
answer: which headline moved, by how much, since the previous round.

Zero-valued headlines are real data (five rounds of ``0.0 tok/s — backend
unresponsive`` ARE the trajectory this tooling exists to end) and render as
written; deltas are computed against the latest previous round with a
usable value so one dead round doesn't blind the comparison.
"""

from __future__ import annotations

import glob
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any

# record keys → table rows, in display order. Ratios render raw; everything
# else is a rate where bigger is better.
HEADLINE_METRICS: tuple[tuple[str, str], ...] = (
    ("headline tok/s", "value"),
    ("decode-only tok/s", "decode_only_tok_s"),
    ("eval samples/s", "eval_samples_per_sec"),
    ("serve tok/s", "serve_tok_s"),
    ("serve overlap ratio", "serve_overlap_ratio"),
    ("serve int8 tok/s", "serve_int8_tok_s"),
    ("serve spec tok/s", "serve_spec_tok_s"),
    ("serve spec-off tok/s", "serve_spec_off_tok_s"),
    ("serve spec speedup", "serve_spec_speedup"),
    ("serve spec accept ratio", "serve_spec_accept_ratio"),
    ("prefixburst tok/s", "serve_prefixburst_tok_s"),
    ("prefixburst hit ratio", "serve_prefixburst_hit_ratio"),
    # paged-gather hit seeding (own keys: paged and copy numbers come from
    # one dedicated longprefix comparison and only delta against
    # themselves; seed-ms rows are the seeding-path wall time per hit)
    ("longprefix tok/s", "serve_longprefix_tok_s"),
    ("longprefix copy tok/s", "serve_longprefix_copy_tok_s"),
    ("longprefix seed ms", "serve_longprefix_seed_ms"),
    ("longprefix copy seed ms", "serve_longprefix_copy_seed_ms"),
    # kernel autotune round-trip (kernels with a winner + sweep wall time)
    ("autotune kernels", "autotune_kernels"),
    ("autotune sweep s", "autotune_sweep_s"),
    ("fleet tok/s", "serve_fleet_tok_s"),
    ("fleet affinity ratio", "serve_fleet_affinity_ratio"),
    # batched multi-LoRA serving (own keys: mixed-adapter and base-only
    # numbers come from one dedicated comparison and only delta against
    # themselves — the ratio row is the ≥0.8x acceptance gate's evidence)
    ("multilora tok/s", "serve_multilora_tok_s"),
    ("multilora base tok/s", "serve_multilora_base_tok_s"),
    ("multilora ratio", "serve_multilora_ratio"),
    ("multilora fairness", "serve_multilora_fairness"),
    # elastic fleet (own keys: the autoscaler's live 1→N→1 rate_storm leg —
    # peak/final counts are the control-loop evidence, tok/s the final
    # post-scale round's throughput; only ever deltas against itself)
    ("elastic tok/s", "serve_elastic_tok_s"),
    ("elastic peak replicas", "serve_elastic_peak_replicas"),
    ("elastic scale ups", "serve_elastic_scale_ups"),
    ("elastic scale downs", "serve_elastic_scale_downs"),
    # disaggregated prefill/decode (own keys, never folded into the serve/
    # fleet rows above: the phase-split and colocated numbers come from a
    # dedicated scenario and must only ever delta against themselves)
    ("disagg tok/s", "serve_disagg_tok_s"),
    ("disagg colocated tok/s", "serve_disagg_colo_tok_s"),
    ("disagg speedup", "serve_disagg_speedup"),
    ("disagg ttft p50 ms", "serve_disagg_ttft_p50_ms"),
    ("disagg colocated ttft p50 ms", "serve_disagg_colo_ttft_p50_ms"),
    ("disagg ttft p95 ms", "serve_disagg_ttft_p95_ms"),
    ("disagg colocated ttft p95 ms", "serve_disagg_colo_ttft_p95_ms"),
    ("disagg migrate bytes", "serve_disagg_migrate_bytes"),
    ("sharded tok/s", "serve_sharded_tok_s"),
    ("int8 tok/s", "int8_weights_tok_s"),
    ("int4 tok/s", "int4_weights_tok_s"),
    ("longctx pallas speedup", "longctx_pallas_speedup"),
    ("trainstep tok/s", "trainstep_tok_s"),
)

_ROUND_RE = re.compile(r"BENCH_(?:(?P<kind>[a-z_]+)_)?r(?P<num>\d+)\.json$")
_MULTICHIP_RE = re.compile(r"MULTICHIP_(?:(?P<kind>[a-z_]+)_)?r(?P<num>\d+)\.json$")


@dataclass
class Round:
    label: str
    path: str
    order: tuple
    schema: int
    record: dict[str, Any]
    metrics: dict[str, float] = field(default_factory=dict)

    @property
    def error(self) -> str | None:
        return self.record.get("error")


def _slo_metrics(report: dict) -> dict[str, float]:
    """Flatten a loadgen SLO report (schema 2 records carry one under
    ``loadgen``) into table rows: the aggregate headline plus per-scenario
    throughput and TTFT p50/p95."""
    out: dict[str, float] = {}
    headline = report.get("headline") or {}
    if isinstance(headline.get("tok_s"), (int, float)):
        out["loadgen tok/s"] = float(headline["tok_s"])
    for row in report.get("scenarios") or []:
        # "slo:" prefix keeps SLO-row names disjoint from HEADLINE_METRICS
        # labels — a scenario named "serve" must not silently overwrite the
        # record-field "serve tok/s" cell (different rounding, different
        # sourcing era)
        name = f"slo:{row.get('scenario', '?')}"
        if isinstance(row.get("tok_s"), (int, float)):
            out[f"{name} tok/s"] = float(row["tok_s"])
        for family, unit in (("ttft_s", "ttft"), ("tpot_s", "tpot")):
            quantiles = row.get(family) or {}
            for q in ("p50", "p95"):
                value = quantiles.get(q)
                if isinstance(value, (int, float)):
                    out[f"{name} {unit} {q} ms"] = round(value * 1e3, 3)
        ratio = row.get("spec_accept_ratio")
        if isinstance(ratio, (int, float)) and not isinstance(ratio, bool):
            out[f"{name} accept ratio"] = float(ratio)
    return out


def _device_profile_metrics(profile: dict) -> dict[str, float]:
    """Flatten a record's ``device_profile`` section (the profiler summary
    bench.py embeds: per-phase step seconds, cost-model MFU, compile totals)
    into "dp:"-prefixed rows — disjoint from HEADLINE_METRICS labels like
    the "slo:" rows. Rounds without the section (every pre-profiler
    baseline) simply render "—" for these rows, never an error."""
    out: dict[str, float] = {}
    for phase, entry in sorted((profile.get("phases") or {}).items()):
        if not isinstance(entry, dict):
            continue
        mean = entry.get("mean_s")
        if isinstance(mean, (int, float)) and not isinstance(mean, bool):
            out[f"dp:{phase} step ms"] = round(float(mean) * 1e3, 3)
        for key, label in (
            ("mfu", "mfu"),
            ("achieved_tflops", "tflops"),
            ("achieved_gbps", "gb/s"),
        ):
            value = entry.get(key)
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                out[f"dp:{phase} {label}"] = float(value)
    compiles = profile.get("compiles")
    if isinstance(compiles, dict):
        for key, label in (("total", "compiles"), ("seconds", "compile s")):
            value = compiles.get(key)
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                out[f"dp:{label}"] = float(value)
    return out


def _is_sharded_smoke_record(record: dict[str, Any]) -> bool:
    """The dedicated sharded loadgen smoke record (run_smoke --mesh) is
    recognizable by its OWN evidence — top-level ``mesh_devices`` plus the
    ``serve_sharded_tok_s`` metric name — so committing one under a
    BENCH_*.json name still routes it to the mc rows instead of rendering
    its sharded headline in the single-chip 'cpu-smoke tok/s' trajectory.
    Full bench.py records are NOT matched (their sharded section fields are
    ``serve_``-prefixed and their metric is the decode headline): those are
    genuinely mixed records whose family the filename decides."""
    inner = (
        record.get("parsed")
        if ("parsed" in record and "rc" in record)
        else record
    )
    if not isinstance(inner, dict):
        return False
    return bool(inner.get("mesh_devices")) and str(
        inner.get("metric", "")
    ).startswith("serve_sharded_tok_s")


def _round_from_record(path: str, record: dict[str, Any]) -> Round:
    # family is inferred from the FILENAME or the record's own sharded
    # stamps, not a caller flag: an explicit --pattern 'MULTICHIP_*.json'
    # must parse multichip rounds identically to the merged default view,
    # and a sharded smoke record committed under a BENCH name must not
    # contaminate the single-chip rows
    if os.path.basename(path).startswith("MULTICHIP_") or _is_sharded_smoke_record(
        record
    ):
        return _multichip_round(path, record)
    m = _ROUND_RE.search(os.path.basename(path))
    kind = (m.group("kind") if m else None) or ""
    # no r<N> in the name: sort AFTER every numbered round (it must never
    # become r01's delta baseline) and label it by its filename stem
    num = int(m.group("num")) if m else None
    # the driver wraps each round's bench record: {"n", "cmd", "rc", "tail",
    # "parsed": <last JSON line or null>}. Unwrap it; a null parse (the
    # round-3 mid-preflight kill) becomes an explicit error record rather
    # than a skipped round — a dead round is part of the trajectory.
    if "parsed" in record and "rc" in record:
        num, record = _unwrap_driver_record(num, record)
    if num is None:
        label = os.path.basename(path)[: -len(".json")]
        order: tuple = (float("inf"), label)
    else:
        label = f"r{num:02d}" + (f"-{kind}" if kind else "")
        order = (num, kind)
    # schema 1: every round before the loadgen era (no "schema" key). The
    # labeling here is what lets a delta across nine historical rounds parse
    # without guessing which fields can exist.
    schema = int(record.get("schema", 1))
    metrics: dict[str, float] = {}
    for row_label, key in HEADLINE_METRICS:
        value = record.get(key)
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            if key == "value" and not str(
                record.get("metric", "decode_tokens_per_sec")
            ).startswith("decode_tokens_per_sec"):
                # a CPU loadgen smoke's headline is not the TPU decode
                # headline — same row would render a nonsense cross-backend
                # delta; give it its own trajectory row
                row_label = "cpu-smoke tok/s"
            metrics[row_label] = float(value)
    if schema >= 2 and isinstance(record.get("loadgen"), dict):
        metrics.update(_slo_metrics(record["loadgen"]))
    if isinstance(record.get("device_profile"), dict):
        metrics.update(_device_profile_metrics(record["device_profile"]))
    # opportunistic/secondary records sort after the driver record of the
    # same round number
    return Round(
        label=label, path=path, order=order, schema=schema,
        record=record, metrics=metrics,
    )


def _unwrap_driver_record(
    num: int | None, record: dict[str, Any]
) -> tuple[int | None, dict[str, Any]]:
    """Unwrap the driver's ``{"n", "cmd", "rc", "tail", "parsed"}`` wrapper
    (shared by BENCH and MULTICHIP rounds). A null parse (e.g. a
    mid-preflight kill) becomes an explicit error record rather than a
    skipped round — a dead round is part of the trajectory."""
    num = int(record.get("n") or num or 0)
    parsed = record["parsed"]
    if isinstance(parsed, dict):
        return num, parsed
    return num, {
        "value": 0.0,
        "error": f"record unparseable (driver rc={record.get('rc')})",
    }


def _multichip_round(path: str, record: dict[str, Any]) -> Round:
    """A committed MULTICHIP_*.json round: the multi-chip trajectory rendered
    NEXT TO the BENCH rounds, never against them. Every row name is
    ``mc``-prefixed, so the delta math (which compares a metric against the
    latest previous round carrying the same name) can never compute a
    cross-backend delta between a TPU BENCH headline and a multichip round.

    Two shapes exist: the historical dryrun wrapper (``{"n_devices", "rc",
    "ok", "tail"}`` from the 8-virtual-device compile/execute smoke) renders
    as a pass/fail row; schema-2 records (the sharded-replica loadgen smoke)
    contribute a real throughput row plus their SLO scenario rows."""
    # a sharded smoke record routed here by content may carry a BENCH_rNN
    # name — fall back to the BENCH pattern so it keeps its round number
    # (and its place in the timeline) instead of sorting last unnumbered
    m = _MULTICHIP_RE.search(os.path.basename(path)) or _ROUND_RE.search(
        os.path.basename(path)
    )
    kind = (m.group("kind") if m else None) or ""
    num = int(m.group("num")) if m else None
    if "parsed" in record and "rc" in record:  # driver wrapper, like BENCH
        num, record = _unwrap_driver_record(num, record)
    if num is None:
        label = "mc-" + os.path.basename(path)[: -len(".json")]
        order: tuple = (float("inf"), label)
    else:
        label = f"mc{num:02d}" + (f"-{kind}" if kind else "")
        # "~" sorts after every [a-z_] kind: the multichip column of round N
        # lands right of round N's BENCH columns
        order = (num, "~" + kind)
    metrics: dict[str, float] = {}
    schema = int(record.get("schema", 1))
    if "value" not in record and "n_devices" in record:
        # legacy dryrun wrapper: no throughput was ever measured — the row
        # records that the sharding programs compiled and executed
        metrics["mc dryrun ok"] = 1.0 if record.get("ok") else 0.0
        if not record.get("ok") and not record.get("error"):
            record = {**record, "error": f"dryrun failed (rc={record.get('rc')})"}
    else:
        # the sharded headline: a full bench.py record committed as a
        # MULTICHIP round carries it under serve_sharded_tok_s (its "value"
        # is the single-chip decode headline — the wrong trajectory here);
        # the dedicated loadgen --mesh smoke record carries it as "value"
        # and stamps top-level mesh/mesh_devices as the evidence. A bench
        # record whose sharded section failed has neither — no row, never
        # the single-chip headline masquerading as the multichip number.
        value = record.get("serve_sharded_tok_s")
        if (
            value is None
            and (record.get("mesh_devices") or record.get("mesh"))
            and str(record.get("metric", "")).startswith("serve_sharded_tok_s")
        ):
            # only the dedicated sharded smoke's own headline may take this
            # row — a mesh-stamped record measuring something else (e.g. the
            # role-preset disagg round) must not masquerade as the sharded
            # fleet number
            value = record.get("value")
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            metrics["mc sharded tok/s"] = float(value)
        # role-preset disaggregation rounds (run_disagg_mesh_round): their
        # serve_disagg_* keys render as mc-prefixed rows, disjoint from the
        # single-chip disagg rows exactly like every other mc metric
        for row_label, key in (
            ("mc disagg tok/s", "serve_disagg_tok_s"),
            ("mc disagg colo tok/s", "serve_disagg_colo_tok_s"),
            ("mc disagg speedup", "serve_disagg_speedup"),
            ("mc disagg ttft p50 ms", "serve_disagg_ttft_p50_ms"),
            ("mc disagg colo ttft p50 ms", "serve_disagg_colo_ttft_p50_ms"),
            ("mc disagg ttft p95 ms", "serve_disagg_ttft_p95_ms"),
            ("mc disagg colo ttft p95 ms", "serve_disagg_colo_ttft_p95_ms"),
            ("mc disagg migrate bytes", "serve_disagg_migrate_bytes"),
        ):
            mc_value = record.get(key)
            if isinstance(mc_value, (int, float)) and not isinstance(mc_value, bool):
                metrics[row_label] = float(mc_value)
        devices = (
            record.get("serve_mesh_devices")
            or record.get("mesh_devices")
            or record.get("n_devices")
        )
        if isinstance(devices, (int, float)) and not isinstance(devices, bool):
            metrics["mc mesh devices"] = float(devices)
        if schema >= 2 and isinstance(record.get("loadgen"), dict):
            metrics.update(
                {f"mc-{k}": v for k, v in _slo_metrics(record["loadgen"]).items()}
            )
        if isinstance(record.get("device_profile"), dict):
            metrics.update(
                {
                    f"mc-{k}": v
                    for k, v in _device_profile_metrics(
                        record["device_profile"]
                    ).items()
                }
            )
    return Round(
        label=label, path=path, order=order, schema=schema,
        record=record, metrics=metrics,
    )


def round_from_report(report: dict[str, Any], *, label: str = "candidate") -> Round:
    """A synthetic Round from a fresh loadgen SLO report (smoke's
    slo_report.json) — what `prime bench sentinel --report` appends as the
    gate's candidate round before any record is committed. Carries the same
    "loadgen tok/s" + "slo:" rows a committed schema-2 record would, so the
    candidate gates against exactly the history those rows accumulated."""
    metrics = _slo_metrics(report if isinstance(report, dict) else {})
    return Round(
        label=label, path="<report>", order=(float("inf"), label),
        schema=2, record={"loadgen": report}, metrics=metrics,
    )


def load_rounds(root: str = ".", pattern: str = "BENCH_*.json") -> list[Round]:
    """Every parseable committed round under ``root``, oldest first.
    Unparseable files are skipped (a half-written record must not take the
    delta table down); files without a BENCH_r<N> name sort last by name.
    ``MULTICHIP_*``-named files parse as multichip rounds whatever the
    pattern that matched them."""
    rounds: list[Round] = []
    for path in sorted(glob.glob(os.path.join(root, pattern))):
        try:
            with open(path) as f:
                record = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(record, dict):
            rounds.append(_round_from_record(path, record))
    rounds.sort(key=lambda r: (r.order, r.label))
    return rounds


def load_all_rounds(root: str = ".") -> list[Round]:
    """BENCH and MULTICHIP rounds merged into one timeline: multichip rounds
    interleave by round number (sorting after the same-numbered BENCH round)
    but keep disjoint ``mc``-prefixed metric rows — own rows, no
    cross-backend deltas."""
    rounds = load_rounds(root, "BENCH_*.json") + load_rounds(root, "MULTICHIP_*.json")
    rounds.sort(key=lambda r: (r.order, r.label))
    return rounds


def _fmt(value: float) -> str:
    if value == int(value) and abs(value) >= 100:
        return str(int(value))
    return f"{value:.3g}" if abs(value) < 10 else f"{value:.1f}"


def delta_table(rounds: list[Round], *, min_rounds: int = 2) -> str:
    """Render the metric-by-round table with per-round deltas vs the latest
    previous round that measured the same metric (Δ% for rates/ratios)."""
    if len(rounds) < min_rounds:
        return (
            f"need at least {min_rounds} BENCH_*.json rounds for a delta "
            f"table; found {len(rounds)}"
        )
    metric_names: list[str] = []
    for r in rounds:
        for name in r.metrics:
            if name not in metric_names:
                metric_names.append(name)
    if not metric_names:
        return "no numeric metrics found in any round"
    label_w = max(len(n) for n in metric_names + ["sentinel verdict"]) + 2
    headers = [
        r.label + (f" (s{r.schema})" if r.schema == 1 else "") for r in rounds
    ]
    col_w = max(16, max(len(h) for h in headers) + 2)
    lines = ["".join([" " * label_w] + [f"{h:>{col_w}}" for h in headers])]
    for name in metric_names:
        cells = [f"{name:<{label_w}}"]
        prev: float | None = None
        for r in rounds:
            value = r.metrics.get(name)
            if value is None:
                cells.append(f"{'—':>{col_w}}")
                continue
            cell = _fmt(value)
            if prev not in (None, 0.0):
                pct = (value - prev) / prev * 100.0
                cell += f" ({pct:+.0f}%)"
            elif prev == 0.0 and value > 0:
                cell += " (∅→live)"
            cells.append(f"{cell:>{col_w}}")
            prev = value
        lines.append("".join(cells))
    # sentinel verdict row: same implementation as the `prime bench
    # sentinel` CI gate (obs/sentinel.trajectory_verdicts), so the table a
    # human reads and the exit code CI trusts can never disagree
    for verdict in _sentinel_rows(rounds):
        cells = [f"{'sentinel verdict':<{label_w}}"]
        for cell in verdict:
            cells.append(f"{cell:>{col_w}}")
        lines.append("".join(cells))
    notes = [
        f"{r.label}: {r.error}" for r in rounds if r.error
    ]
    if notes:
        lines.append("")
        lines.append("round errors:")
        lines.extend(f"  {n}" for n in notes)
    return "\n".join(lines)


def _sentinel_verdicts(rounds: list[Round]) -> list[dict[str, Any]]:
    """Per-round sentinel verdicts, or [] when the sentinel can't run
    (import trouble must not take the delta table down)."""
    try:
        from prime_tpu.obs.sentinel import trajectory_verdicts
    except Exception:  # noqa: BLE001 — the table renders without the row
        return []
    try:
        return trajectory_verdicts(rounds)
    except Exception:  # noqa: BLE001
        return []


def _sentinel_rows(rounds: list[Round]) -> list[list[str]]:
    """The `sentinel verdict` table row (one cell per round) as a
    single-row list, or [] when verdicts are unavailable."""
    verdicts = _sentinel_verdicts(rounds)
    if not verdicts:
        return []
    cells = []
    for v in verdicts:
        if v["verdict"] == "regressed":
            cells.append(f"REGRESSED({len(v['regressions'])})")
        elif v["verdict"] == "ok":
            cells.append("ok")
        else:
            cells.append("no-history")
    return [cells]


def delta_json(rounds: list[Round]) -> dict[str, Any]:
    """Machine form of the same table (CI step summaries, dashboards)."""
    verdicts = _sentinel_verdicts(rounds)
    by_label: dict[str, dict[str, Any]] = {v["label"]: v for v in verdicts}
    return {
        "rounds": [
            {
                "label": r.label,
                "path": os.path.basename(r.path),
                "schema": r.schema,
                "error": r.error,
                "metrics": r.metrics,
                "sentinel": by_label.get(r.label),
            }
            for r in rounds
        ]
    }
