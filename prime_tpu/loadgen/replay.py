"""Trace replay: re-drive request timelines the obs spine already recorded.

Two recorded forms reconstruct a schedule:

- **Flight recorder** (``GET /debug/requests`` on a server or router, or
  ``FlightRecorder.summaries()`` in process): each summary carries
  ``start_unix_s``, ``duration_s``, ``outcome``, and the admission metadata
  (``prompt_tokens``, ``max_new_tokens``) the engine stamped at submit.
- **PRIME_TRACE JSONL**: every retirement emits a ``serve.request`` span
  whose start IS the submit time (duration = submit → retire), and the
  ``serve.prefill`` span for the same request carries ``prompt_len``.

Either way the reconstruction pins what a replay needs to reproduce load:
arrival order and relative offsets, per-request prompt sizes, decode
budgets, and cancel points (a ``cancelled`` timeline cancels at its
recorded duration). Prompt *content* is synthesized deterministically from
``seed`` — the recorders keep token counts, not tokens, by design (prompt
text in a debug endpoint would be a data leak) — so a replayed schedule is
shape-faithful and byte-reproducible, not content-identical.
"""

from __future__ import annotations

import json
import random
from typing import Any, Iterable

from prime_tpu.loadgen.scenario import RESERVED_IDS, PlannedRequest

DEFAULT_PROMPT_TOKENS = 32
DEFAULT_MAX_NEW_TOKENS = 16


def _synth_prompt(seed: int, index: int, n_tokens: int, vocab: int) -> tuple[int, ...]:
    # int mix, not a tuple seed: tuple seeding is deprecated and hash-based
    rng = random.Random(seed * 1_000_003 + index * 8191 + n_tokens)
    n_tokens = max(1, n_tokens)
    return (1,) + tuple(
        rng.randrange(RESERVED_IDS, vocab) for _ in range(n_tokens - 1)
    )


def _timelines_from_flight(payload: Any) -> list[dict]:
    """Accept the several shapes the debug surfaces produce: a raw summary
    list, a ``{"inflight": [...], "recent": [...]}`` dict, or the router's
    ``{"router": {...}}`` wrapper. Completed timelines only — an in-flight
    request has no outcome to replay yet."""
    if isinstance(payload, dict) and "router" in payload and isinstance(payload["router"], dict):
        payload = payload["router"]
    if isinstance(payload, dict):
        # "recent" only: an in-flight timeline has no outcome to replay yet
        # (the state filter below is a guard for caller-provided lists)
        entries = list(payload.get("recent", []))
    else:
        entries = list(payload)
    return [
        t for t in entries
        if isinstance(t, dict) and t.get("start_unix_s") is not None
        and t.get("state") != "inflight"
    ]


def schedule_from_flight(
    payload: Any,
    *,
    seed: int = 0,
    vocab: int = 1000,
    max_prompt_tokens: int | None = None,
) -> list[PlannedRequest]:
    """Rebuild a schedule from flight-recorder summaries. Ordering follows
    recorded submit times (``start_unix_s``), offsets are relative to the
    earliest; ``max_prompt_tokens`` clamps outlier prompts so a replay fits
    a smaller engine's slot capacity."""
    timelines = _timelines_from_flight(payload)
    if not timelines:
        return []
    timelines.sort(key=lambda t: (t["start_unix_s"], str(t.get("id"))))
    t0 = timelines[0]["start_unix_s"]
    out: list[PlannedRequest] = []
    for index, timeline in enumerate(timelines):
        arrival = round(float(timeline["start_unix_s"]) - t0, 6)
        n_prompt = int(timeline.get("prompt_tokens") or DEFAULT_PROMPT_TOKENS)
        if max_prompt_tokens is not None:
            n_prompt = min(n_prompt, max_prompt_tokens)
        cancel = None
        if timeline.get("outcome") == "cancelled":
            cancel = round(arrival + float(timeline.get("duration_s") or 0.0), 6)
        out.append(
            PlannedRequest(
                index=index,
                tenant=f"replay-{timeline.get('trace_id') or timeline.get('id')}",
                arrival_s=arrival,
                prompt_ids=_synth_prompt(seed, index, n_prompt, vocab),
                max_new_tokens=int(
                    timeline.get("max_new_tokens") or DEFAULT_MAX_NEW_TOKENS
                ),
                cancel_after_s=cancel,
            )
        )
    return out


def _iter_spans(path: str) -> Iterable[dict]:
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                span = json.loads(line)
            except ValueError:
                continue
            if isinstance(span, dict) and "name" in span:
                yield span


def schedule_from_trace(
    paths: str | list[str],
    *,
    seed: int = 0,
    vocab: int = 1000,
    max_prompt_tokens: int | None = None,
) -> list[PlannedRequest]:
    """Rebuild a schedule from PRIME_TRACE JSONL file(s). ``serve.request``
    spans define the request set and timing (their start is the submit
    time); ``serve.prefill`` spans sharing the request id + trace id supply
    prompt lengths. Multiple files (router + replicas) merge naturally —
    only the engine-side span names matter here."""
    if isinstance(paths, str):
        paths = [paths]
    requests: list[dict] = []
    prompt_lens: dict[tuple[str | None, Any], int] = {}
    for path in paths:
        for span in _iter_spans(path):
            attrs = span.get("attrs") or {}
            key = (span.get("trace_id"), attrs.get("request"))
            if span["name"] == "serve.request":
                submit_unix = float(span.get("start_unix_s") or 0.0)
                requests.append(
                    {
                        "key": key,
                        "submit_unix_s": submit_unix,
                        "duration_s": float(span.get("duration_s") or 0.0),
                        "outcome": attrs.get("outcome"),
                        "tokens": int(attrs.get("tokens") or 0),
                    }
                )
            elif span["name"] == "serve.prefill" and attrs.get("prompt_len"):
                prompt_lens[key] = int(attrs["prompt_len"])
    if not requests:
        return []
    requests.sort(key=lambda r: (r["submit_unix_s"], str(r["key"])))
    t0 = requests[0]["submit_unix_s"]
    out: list[PlannedRequest] = []
    for index, rec in enumerate(requests):
        arrival = round(rec["submit_unix_s"] - t0, 6)
        n_prompt = prompt_lens.get(rec["key"], DEFAULT_PROMPT_TOKENS)
        if max_prompt_tokens is not None:
            n_prompt = min(n_prompt, max_prompt_tokens)
        cancel = None
        if rec["outcome"] == "cancelled":
            cancel = round(arrival + rec["duration_s"], 6)
        out.append(
            PlannedRequest(
                index=index,
                tenant=f"replay-{rec['key'][0] or index}",
                arrival_s=arrival,
                prompt_ids=_synth_prompt(seed, index, n_prompt, vocab),
                # the recorded emission is the floor for the decode budget:
                # a completed request decoded exactly its `tokens`, so replay
                # asks for that many (cancelled ones keep their recorded cap
                # semantics via the cancel point)
                max_new_tokens=max(1, rec["tokens"]) if rec["tokens"] else DEFAULT_MAX_NEW_TOKENS,
                cancel_after_s=cancel,
            )
        )
    return out
