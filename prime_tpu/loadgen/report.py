"""Versioned SLO report assembled from registry snapshot deltas.

The report's claim to honesty: every latency/throughput number is computed
from the *serving stack's own* observability — deltas between the registry
snapshots the runner took before and after the run (each stamped with the
monotonic ``captured_at`` that :meth:`Registry.snapshot` embeds, so the
throughput denominator is the same process's clock that counted the
tokens), with percentiles interpolated from obs ``Histogram`` buckets via
:func:`quantile_from_snapshot`. The client contributes only what no server
registry can know: outcome counts (a 429-rejected request never reaches an
engine histogram) and the schedule digest that pins what was asked.

Schema: ``slo_schema`` versions the report; a consumer seeing a bigger
number than it knows should fail loud, not guess. Field catalog in
docs/benchmarking.md.
"""

from __future__ import annotations

import math
from typing import Any

from prime_tpu.obs.metrics import (
    counter_delta,
    hist_delta,
    hist_series_from_snapshot,
    merge_hists,
    quantile_from_snapshot,
    snapshot_captured_at,
)

SLO_SCHEMA = 1

# the delta/merge arithmetic itself lives in obs/metrics.py (shared with the
# observatory time-series — one implementation, two consumers); this module
# keeps only the report-shaped selection logic on top of it
_captured_at = snapshot_captured_at
_hist_series = hist_series_from_snapshot
_hist_delta = hist_delta
_merge_hists = merge_hists


def _family(snapshot: dict, name: str) -> dict | None:
    family = snapshot.get(name)
    return family if isinstance(family, dict) else None


def _scalar(snapshot: dict, name: str, labels: dict | None = None) -> float:
    """A counter/gauge series value (0.0 when absent)."""
    family = _family(snapshot, name)
    if family is None:
        return 0.0
    want = labels or {}
    for series in family.get("series", []):
        if series.get("labels", {}) == want:
            return float(series.get("value", 0.0))
    return 0.0


def _scalar_sum(snapshot: dict, name: str, **fixed: str) -> float:
    """Sum of every series of a labeled counter matching ``fixed``."""
    family = _family(snapshot, name)
    if family is None:
        return 0.0
    total = 0.0
    for series in family.get("series", []):
        labels = series.get("labels", {})
        if all(labels.get(k) == v for k, v in fixed.items()):
            total += float(series.get("value", 0.0))
    return total


def _labeled_values(snapshot: dict, name: str, label: str) -> dict[str, float]:
    family = _family(snapshot, name)
    out: dict[str, float] = {}
    if family is None:
        return out
    for series in family.get("series", []):
        key = series.get("labels", {}).get(label)
        if key is not None:
            out[key] = out.get(key, 0.0) + float(series.get("value", 0.0))
    return out


def _quantiles(hist: dict | None, qs: tuple[float, ...] = (0.5, 0.95)) -> dict[str, float | None]:
    out: dict[str, float | None] = {}
    for q in qs:
        key = f"p{int(q * 100)}"
        if hist is None or hist["count"] <= 0:
            out[key] = None
        else:
            value = quantile_from_snapshot(hist["buckets"], hist["counts"], q)
            out[key] = None if math.isnan(value) else round(value, 6)
    return out


def snapshot_delta_seconds(before: dict, after: dict) -> float | None:
    """Wall seconds between two snapshots of the SAME registry, from the
    embedded monotonic ``captured_at`` — the report's only throughput
    denominator (never a client stopwatch)."""
    b, a = _captured_at(before), _captured_at(after)
    if b is None or a is None:
        return None
    return max(0.0, a - b)


def _engine_components(snapshots: dict[str, dict]) -> list[str]:
    """Components holding engine registries: the in-process ``engine`` key
    or any HTTP-scraped ``<label>.engine`` section."""
    return [
        name
        for name in snapshots
        if name == "engine" or name.endswith(".engine")
    ]


def _router_components(snapshots: dict[str, dict]) -> list[str]:
    return [
        name
        for name in snapshots
        if name == "router" or name.endswith(".router")
    ]


def scenario_row(result) -> dict[str, Any]:
    """One scenario's SLO row from a :class:`RunResult`'s snapshot pair."""
    before, after = result.before, result.after
    engines = _engine_components(after)
    routers = _router_components(after)
    warnings: list[str] = []
    if not engines:
        # loud, not a silent 0.0: a router-only scrape has no token counters
        # or latency histograms to window — the caller forgot the replica
        # URLs in HTTPTarget(scrape_urls=...), and a zero here would be
        # indistinguishable from the dead-backend trajectory
        warnings.append(
            "no engine registries in the scrape (pass replica URLs via "
            "HTTPTarget scrape_urls) — tok_s/latency fields are undefined"
        )
    if getattr(result, "timed_out", False):
        warnings.append(
            "run hit its deadline and was truncated — numbers cover only "
            "the completed portion of the schedule"
        )

    durations = [
        snapshot_delta_seconds(before.get(name, {}), after[name])
        for name in engines
        if name in before
    ]
    durations = [d for d in durations if d]
    duration_s = max(durations) if durations else None
    if engines and duration_s is None:
        warnings.append(
            "engine snapshots carry no captured_at window (pre-schema "
            "registry?) — tok_s is undefined, not zero"
        )
    if result.outcomes.get("failed", 0):
        warnings.append(
            f"{result.outcomes['failed']} request(s) FAILED client-side — "
            "throughput covers only the survivors"
        )

    def edelta(metric: str, labels: dict | None = None) -> float:
        # reset-aware (obs/metrics.counter_delta): a replica restarting
        # mid-run must clamp to its post-reset count, not subtract negative
        return sum(
            counter_delta(
                _scalar(before.get(name, {}), metric, labels),
                _scalar(after[name], metric, labels),
            )[0]
            for name in engines
        )

    def ehist(metric: str, labels: dict | None = None) -> dict | None:
        return _merge_hists(
            _hist_delta(
                _hist_series(before.get(name, {}), metric, labels),
                _hist_series(after[name], metric, labels),
            )
            for name in engines
        )

    tokens = edelta("serve_tokens_emitted_total")
    admitted = edelta("serve_requests_admitted_total")
    hits = edelta("serve_prefix_hits_total")
    stall = edelta("serve_host_stall_seconds_total")
    window = edelta("serve_chunk_window_seconds_total")
    spec_accepted = (ehist("serve_spec_accepted_tokens") or {"sum": 0.0})["sum"]
    spec_proposed = edelta("serve_spec_draft_tokens_total")

    row: dict[str, Any] = {
        "scenario": result.scenario,
        "seed": result.seed,
        "schedule_digest": result.digest,
        "requests": result.requests,
        "outcomes": dict(result.outcomes),
        "client_tokens": result.client_tokens,
        "duration_s": round(duration_s, 6) if duration_s else None,
        "tokens": int(tokens),
        "tok_s": round(tokens / duration_s, 2) if duration_s else 0.0,
        "admitted": int(admitted),
        "completed": int(edelta("serve_requests_completed_total")),
        "cancelled": int(edelta("serve_requests_cancelled_total")),
        "failed": int(edelta("serve_requests_failed_total")),
        "overlap_ratio": (
            round(max(0.0, min(1.0, 1.0 - stall / window)), 4) if window > 0 else None
        ),
        "prefix_hit_ratio": round(hits / admitted, 4) if admitted else None,
        "prefix_hit_tokens": {
            tier: int(
                sum(
                    (_hist_series(after[name], "serve_prefix_hit_tokens", {"tier": tier}) or {"sum": 0.0})["sum"]
                    - (_hist_series(before.get(name, {}), "serve_prefix_hit_tokens", {"tier": tier}) or {"sum": 0.0})["sum"]
                    for name in engines
                )
            )
            for tier in ("device", "host")
        },
        "prefix_spills": int(edelta("serve_prefix_spills_total")),
        "prefix_reuploads": int(edelta("serve_prefix_reuploads_total")),
        "wasted_decode_tokens": int(edelta("serve_wasted_decode_tokens_total")),
        # speculative decoding (registry-windowed, like everything else):
        # accepted drafts from the histogram's sum delta, the ratio against
        # the proposed-draft counter delta. None when no verify window ran
        # in this scenario's bracket (spec off, or an idle window).
        "spec_accepted_tokens": int(spec_accepted),
        "spec_accept_ratio": (
            round(spec_accepted / spec_proposed, 4) if spec_proposed else None
        ),
        "ttft_s": _quantiles(ehist("serve_ttft_seconds")),
        "tpot_s": _quantiles(ehist("serve_tpot_seconds")),
        "queue_wait_s": _quantiles(ehist("serve_queue_wait_seconds")),
        "rejected_429": int(result.outcomes.get("rejected_429", 0)),
    }
    # multi-LoRA splits (windowed like everything else): per-adapter token
    # and TTFT attribution from the adapter-labeled engine families — the
    # evidence the fairness ratio and the ≥0.8x-of-base acceptance gate are
    # computed from. Absent entirely on bankless engines (no series).
    adapter_tokens: dict[str, float] = {}
    for name in engines:
        prev = _labeled_values(
            before.get(name, {}), "serve_adapter_tokens_total", "adapter"
        )
        for key, value in _labeled_values(
            after[name], "serve_adapter_tokens_total", "adapter"
        ).items():
            adapter_tokens[key] = (
                adapter_tokens.get(key, 0.0) + value - prev.get(key, 0.0)
            )
    adapter_tokens = {k: v for k, v in adapter_tokens.items() if v > 0}
    if adapter_tokens:
        row["adapters"] = {
            key: {
                "tokens": int(value),
                "tok_s": round(value / duration_s, 2) if duration_s else 0.0,
                "ttft_s": _quantiles(
                    ehist("serve_adapter_ttft_seconds", {"adapter": key})
                ),
                "queue_wait_s": _quantiles(
                    ehist("serve_adapter_queue_wait_seconds", {"adapter": key})
                ),
            }
            for key, value in sorted(adapter_tokens.items())
        }
    if warnings:
        row["warning"] = "; ".join(warnings)

    if routers:
        def rdelta(metric: str, **fixed: str) -> float:
            return sum(
                _scalar_sum(after[name], metric, **fixed)
                - _scalar_sum(before.get(name, {}), metric, **fixed)
                for name in routers
            )

        affinity_requests = rdelta("fleet_affinity_requests_total")
        affinity_hits = rdelta("fleet_affinity_hits_total")
        reroutes: dict[str, float] = {}
        for name in routers:
            for reason, value in _labeled_values(
                after[name], "fleet_reroutes_total", "reason"
            ).items():
                prev = _labeled_values(
                    before.get(name, {}), "fleet_reroutes_total", "reason"
                ).get(reason, 0.0)
                reroutes[reason] = reroutes.get(reason, 0.0) + value - prev
        # phase-split migrations (disaggregated serving), windowed like the
        # reroutes — a long-lived router's lifetime migration totals (warmup
        # traffic included) must not be misattributed to this scenario
        migrations: dict[str, float] = {}
        for name in routers:
            prev_m = _labeled_values(
                before.get(name, {}), "fleet_migrations_total", "outcome"
            )
            for outcome, value in _labeled_values(
                after[name], "fleet_migrations_total", "outcome"
            ).items():
                migrations[outcome] = (
                    migrations.get(outcome, 0.0) + value - prev_m.get(outcome, 0.0)
                )
        # per-replica split as a WINDOWED delta, like every other field in
        # the row — a long-lived router's lifetime totals must not be
        # misattributed to this scenario
        by_replica: dict[str, float] = {}
        for name in routers:
            prev = _labeled_values(
                before.get(name, {}), "fleet_requests_total", "replica"
            )
            for replica, value in _labeled_values(
                after[name], "fleet_requests_total", "replica"
            ).items():
                by_replica[replica] = (
                    by_replica.get(replica, 0.0) + value - prev.get(replica, 0.0)
                )
        row["fleet"] = {
            "affinity_ratio": (
                round(affinity_hits / affinity_requests, 4)
                if affinity_requests
                else None
            ),
            "cache_routed": int(rdelta("fleet_cache_routed_total")),
            "migrations": {k: int(v) for k, v in migrations.items() if v},
            "migrate_bytes": int(rdelta("fleet_migrate_bytes_total")),
            "reroutes": {k: int(v) for k, v in reroutes.items() if v},
            "admission_rejected": int(rdelta("fleet_admission_rejected_total")),
            "requests_by_replica": {
                replica: int(value) for replica, value in by_replica.items() if value
            },
        }
    return row


def spec_comparison_record(
    off_row: dict[str, Any], on_row: dict[str, Any], *, digits: int | None = None
) -> dict[str, Any]:
    """The ONE owner of the spec-on/off record keys both producers publish
    (bench.py's spec section and the loadgen smoke's): spec-on/off tok/s,
    the speedup, the accept ratio, and the TPOT p50 pair — computed from
    two :func:`scenario_row` results over the same schedule. ``digits``
    rounds the tok/s values (bench's historical 1-decimal style)."""
    def _toks(row):
        value = row["tok_s"]
        return round(value, digits) if digits is not None else value

    record: dict[str, Any] = {
        "serve_spec_off_tok_s": _toks(off_row),
        "serve_spec_tok_s": _toks(on_row),
    }
    if off_row["tok_s"]:
        record["serve_spec_speedup"] = round(on_row["tok_s"] / off_row["tok_s"], 3)
    if on_row.get("spec_accept_ratio") is not None:
        record["serve_spec_accept_ratio"] = on_row["spec_accept_ratio"]
    for key, row in (("serve_spec", on_row), ("serve_spec_off", off_row)):
        p50 = (row.get("tpot_s") or {}).get("p50")
        if isinstance(p50, (int, float)):
            record[f"{key}_tpot_p50_ms"] = round(p50 * 1e3, 3)
    return record


def build_report(
    results, *, meta: dict | None = None, device_profile: dict | None = None
) -> dict[str, Any]:
    """The versioned SLO report: one row per scenario plus the aggregate
    headline. ``meta`` merges into the top level (backend identity, git
    rev, CI round). ``device_profile`` (a DeviceProfiler.summary() dict:
    per-phase step seconds, compile totals, cost-model MFU) rides under its
    own key — perf_delta tolerates rounds without it."""
    if not isinstance(results, (list, tuple)):
        results = [results]
    rows = [scenario_row(r) for r in results]
    total_tokens = sum(r["tokens"] for r in rows)
    total_duration = sum(r["duration_s"] or 0.0 for r in rows)
    report: dict[str, Any] = {
        "slo_schema": SLO_SCHEMA,
        "scenarios": rows,
        "headline": {
            "tok_s": round(total_tokens / total_duration, 2) if total_duration else 0.0,
            "tokens": int(total_tokens),
            "duration_s": round(total_duration, 6),
            "requests": sum(r["requests"] for r in rows),
            "rejected_429": sum(r["rejected_429"] for r in rows),
        },
    }
    if device_profile:
        report["device_profile"] = device_profile
    if meta:
        report.update(meta)
    return report
