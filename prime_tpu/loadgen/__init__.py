"""Deterministic load generation + SLO observatory (docs/benchmarking.md).

The harness that makes the perf trajectory real (ROADMAP Open item 5): a
seeded scenario DSL expands into byte-identical request schedules, backend
adapters drive them against an in-process engine or any OpenAI-compatible
HTTP surface (single server, fleet router — including ``JAX_PLATFORMS=cpu``
in CI), and the SLO report derives every number from the obs registry
snapshots and flight-recorder timelines the serving stack already keeps —
never from client stopwatches. Recorded runs replay via
:mod:`prime_tpu.loadgen.replay`; committed rounds diff via
:mod:`prime_tpu.loadgen.perf_delta`.

Import surface is lazy where it matters: the scenario/report/perf_delta
layers are stdlib-only (the CLI imports them without jax); the backends
pull httpx/engine modules only when constructed.
"""

from prime_tpu.loadgen.backends import (
    EngineTarget,
    HTTPTarget,
    NumericTokenizer,
    prompt_text,
)
from prime_tpu.loadgen.perf_delta import delta_json, delta_table, load_rounds
from prime_tpu.loadgen.replay import schedule_from_flight, schedule_from_trace
from prime_tpu.loadgen.report import SLO_SCHEMA, build_report, scenario_row
from prime_tpu.loadgen.runner import RunResult, run_schedule
from prime_tpu.loadgen.scenario import (
    SCENARIOS,
    Phase,
    PlannedRequest,
    Scenario,
    build_schedule,
    schedule_digest,
    schedule_from_prompts,
)

__all__ = [
    "SCENARIOS",
    "SLO_SCHEMA",
    "EngineTarget",
    "HTTPTarget",
    "NumericTokenizer",
    "Phase",
    "PlannedRequest",
    "RunResult",
    "Scenario",
    "build_report",
    "build_schedule",
    "delta_json",
    "delta_table",
    "load_rounds",
    "prompt_text",
    "run_schedule",
    "scenario_row",
    "schedule_digest",
    "schedule_from_flight",
    "schedule_from_prompts",
    "schedule_from_trace",
]
