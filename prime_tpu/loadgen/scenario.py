"""Scenario DSL: seeded, deterministic multi-tenant traffic schedules.

A :class:`Scenario` is a declarative description of a traffic shape — a
name, a seed, and a list of :class:`Phase` blocks (shared-prefix chat
bursts, long-context outliers, cancel storms, 429 storms, mixed-adapter
tenants). :func:`build_schedule` expands it into a flat, fully materialized
list of :class:`PlannedRequest` — every prompt token, tenant, arrival
offset, and cancel point pinned — using nothing but ``random.Random(seed)``,
so the same scenario yields a byte-identical schedule on every machine and
every run (:func:`schedule_digest` is the test anchor for that claim).

The schedule is backend-agnostic: prompts are token-id tuples, and the
:mod:`prime_tpu.loadgen.backends` adapters turn them into direct engine
submissions or OpenAI-style HTTP bodies (via the numeric tokenizer that
round-trips ids through text). Determinism is a property of the SCHEDULE,
not the run — wall-clock arrival jitter, server-side batching, and thread
interleaving still vary, which is exactly why the SLO report reads the obs
registry instead of client stopwatches (docs/benchmarking.md).
"""

from __future__ import annotations

import hashlib
import json
import random
from dataclasses import asdict, dataclass

from prime_tpu.utils.env import env_flag, env_int

# Matches tiny-test's vocab comfortably; scenario builders clamp into
# [RESERVED_IDS, vocab) so pad/BOS/EOS ids never appear mid-prompt.
DEFAULT_VOCAB = 1000
RESERVED_IDS = 3

PHASE_KINDS = (
    "chat_burst",      # shared-prefix multi-tenant chat wave
    "longctx",         # rare long-context outlier prompts
    "cancel_storm",    # clients that abandon mid-decode
    "rate_storm",      # oversubscription wave aimed at the 429 admission gate
    "mixed",           # per-tenant adapters riding the OpenAI `model` field
    "spec_friendly",   # repetitive/templated prompts where n-gram drafts accept
)


def loadgen_seed_default() -> int:
    """The ``PRIME_LOADGEN_SEED`` knob: default seed for scenario builders
    (0 when unset) — CI and the bench set it to pin or vary a round."""
    return env_int("PRIME_LOADGEN_SEED", 0)


def bench_smoke_scale() -> bool:
    """The ``PRIME_BENCH_SMOKE`` knob as loadgen sees it: builders shrink
    their request counts/lengths to CPU-minutes scale when it is set (the
    same flag bench.py uses for its own smoke mode)."""
    return env_flag("PRIME_BENCH_SMOKE", False)


@dataclass(frozen=True)
class PlannedRequest:
    """One fully materialized request in a schedule. ``arrival_s`` is the
    offset from run start in *schedule time* (the runner may compress it
    with ``time_scale``); ``cancel_after_s`` is the client-abandon point in
    the same clock, ``None`` for requests that run to completion."""

    index: int
    tenant: str
    arrival_s: float
    prompt_ids: tuple[int, ...]
    max_new_tokens: int
    cancel_after_s: float | None = None
    adapter: str | None = None

    def to_dict(self) -> dict:
        out = asdict(self)
        out["prompt_ids"] = list(self.prompt_ids)
        return out


@dataclass(frozen=True)
class Phase:
    """One traffic block. ``shared_prefix`` tokens are drawn once per tenant
    and shared by every request of that tenant in the phase — the shape the
    radix prefix cache and affinity router exist for. ``spread_s`` spreads
    arrivals uniformly over the window starting at ``start_s`` (0 = one
    simultaneous burst)."""

    kind: str
    n: int
    start_s: float = 0.0
    spread_s: float = 0.0
    tenants: int = 1
    shared_prefix: int = 0
    prompt_tokens: int = 32
    max_new_tokens: int = 8
    cancel_frac: float = 0.0
    cancel_after_s: float = 0.1
    adapters: tuple[str, ...] = ()
    # > 0: each request's tail tiles a freshly drawn cycle of this many
    # tokens instead of i.i.d. draws — the templated/repetitive shape where
    # greedy continuations loop and prompt-lookup drafts accept (the
    # spec_friendly phase kind's default; any kind may opt in)
    cycle_tokens: int = 0

    def __post_init__(self) -> None:
        if self.kind not in PHASE_KINDS:
            raise ValueError(f"unknown phase kind {self.kind!r}; one of {PHASE_KINDS}")
        if self.n <= 0:
            raise ValueError("phase n must be positive")
        if self.shared_prefix >= self.prompt_tokens:
            raise ValueError("shared_prefix must leave room for a unique tail")
        if self.cycle_tokens < 0:
            raise ValueError("cycle_tokens must be non-negative")
        if self.cycle_tokens >= self.prompt_tokens:
            raise ValueError("cycle_tokens must be shorter than the prompt")


@dataclass(frozen=True)
class Scenario:
    name: str
    seed: int
    phases: tuple[Phase, ...]
    vocab: int = DEFAULT_VOCAB
    description: str = ""

    def __post_init__(self) -> None:
        if not self.phases:
            raise ValueError("a scenario needs at least one phase")
        if self.vocab <= RESERVED_IDS + 1:
            raise ValueError("vocab too small for prompt synthesis")


def _draw_tokens(rng: random.Random, n: int, vocab: int) -> tuple[int, ...]:
    return tuple(rng.randrange(RESERVED_IDS, vocab) for _ in range(n))


def build_schedule(
    scenario: Scenario, vocab: int | None = None
) -> list[PlannedRequest]:
    """Expand a scenario into its deterministic request schedule. All
    randomness flows from ONE ``random.Random(seed)`` consumed in a fixed
    order (phase by phase, request by request), so equality of (scenario,
    vocab) implies equality of every schedule byte. ``vocab`` overrides the
    scenario's vocab (e.g. clamp to a real model's vocab_size) — it is part
    of the determinism key, not ambient state.

    Prompts lead with token 1 (a stable BOS stand-in) so schedules never
    start on the pad id; per-tenant shared preambles are drawn once per
    (phase, tenant) and shared verbatim across that tenant's requests."""
    vocab = scenario.vocab if vocab is None else vocab
    if vocab <= RESERVED_IDS + 1:
        raise ValueError("vocab too small for prompt synthesis")
    rng = random.Random(scenario.seed)
    out: list[PlannedRequest] = []
    index = 0
    for phase in scenario.phases:
        preambles = {
            t: (1,) + _draw_tokens(rng, max(0, phase.shared_prefix - 1), vocab)
            for t in range(phase.tenants)
        }
        for i in range(phase.n):
            tenant_slot = i % phase.tenants
            tenant = f"{phase.kind}-t{tenant_slot}"
            preamble = preambles[tenant_slot] if phase.shared_prefix else (1,)
            need = phase.prompt_tokens - len(preamble)
            if phase.cycle_tokens > 0:
                # repetitive tail: one short cycle tiled to length, so the
                # sequence's own history is full of repeated bigrams
                cycle = _draw_tokens(rng, phase.cycle_tokens, vocab)
                tail = (cycle * -(-need // len(cycle)))[:need]
            else:
                tail = _draw_tokens(rng, need, vocab)
            arrival = phase.start_s + (
                rng.uniform(0.0, phase.spread_s) if phase.spread_s > 0 else 0.0
            )
            cancel = None
            if phase.cancel_frac > 0 and rng.random() < phase.cancel_frac:
                cancel = round(arrival + phase.cancel_after_s, 6)
            adapter = None
            if phase.adapters:
                adapter = phase.adapters[tenant_slot % len(phase.adapters)]
            out.append(
                PlannedRequest(
                    index=index,
                    tenant=tenant,
                    arrival_s=round(arrival, 6),
                    prompt_ids=preamble + tail,
                    max_new_tokens=phase.max_new_tokens,
                    cancel_after_s=cancel,
                    adapter=adapter,
                )
            )
            index += 1
    # stable order: arrival time, then submission index as the tie-break —
    # a simultaneous burst keeps its within-phase order
    out.sort(key=lambda r: (r.arrival_s, r.index))
    return out


def schedule_digest(schedule: list[PlannedRequest]) -> str:
    """SHA-256 over the canonical JSON of a schedule — the determinism
    anchor: two runs agree on the digest iff they agree on every prompt
    token, tenant, arrival offset, and cancel point."""
    canonical = json.dumps(
        [r.to_dict() for r in schedule], sort_keys=True, separators=(",", ":")
    )
    return hashlib.sha256(canonical.encode()).hexdigest()


def schedule_from_prompts(
    name: str,
    prompts: list[list[int]],
    max_new_tokens: int,
    *,
    tenant: str = "bench",
) -> list[PlannedRequest]:
    """Wrap an explicit prompt list as a zero-offset burst schedule —
    bench.py's serve sections keep their historical prompt sets (tuned to
    exercise specific cache shapes) while riding the loadgen runner/report
    machinery like every other scenario."""
    return [
        PlannedRequest(
            index=i,
            tenant=tenant,
            arrival_s=0.0,
            prompt_ids=tuple(ids),
            max_new_tokens=max_new_tokens,
        )
        for i, ids in enumerate(prompts)
    ]


# ---- builtin scenarios -------------------------------------------------------

def _scale(small: int, large: int) -> int:
    return small if bench_smoke_scale() else large


def chat_burst(seed: int | None = None, **overrides) -> Scenario:
    """Shared-prefix multi-tenant chat wave: every tenant's requests open
    with that tenant's system preamble and diverge after it."""
    seed = loadgen_seed_default() if seed is None else seed
    phase = dict(
        kind="chat_burst", n=_scale(6, 16), tenants=3, shared_prefix=16,
        prompt_tokens=_scale(24, 96), max_new_tokens=_scale(6, 32),
        spread_s=0.2,
    )
    phase.update(overrides)
    return Scenario(
        "chat_burst", seed, (Phase(**phase),),
        description="shared-prefix multi-tenant chat wave",
    )


def longctx_outliers(seed: int | None = None, **overrides) -> Scenario:
    """Mostly short chat traffic with rare long-context outliers mixed in —
    the head-of-line-blocking shape that punishes naive admission."""
    seed = loadgen_seed_default() if seed is None else seed
    short = dict(
        kind="chat_burst", n=_scale(5, 12), tenants=2, shared_prefix=8,
        prompt_tokens=_scale(20, 64), max_new_tokens=_scale(4, 16),
        spread_s=0.3,
    )
    longp = dict(
        kind="longctx", n=_scale(2, 3), prompt_tokens=_scale(72, 768),
        max_new_tokens=_scale(4, 16), start_s=0.05, spread_s=0.2,
    )
    longp.update(overrides)
    return Scenario(
        "longctx_outliers", seed, (Phase(**short), Phase(**longp)),
        description="short chat traffic with long-context outliers",
    )


def cancel_storm(seed: int | None = None, **overrides) -> Scenario:
    """A wave of clients that abandon mid-decode: exercises cancel sweeps,
    slot retirement, and wasted-decode accounting."""
    seed = loadgen_seed_default() if seed is None else seed
    phase = dict(
        kind="cancel_storm", n=_scale(6, 16), tenants=2, shared_prefix=8,
        prompt_tokens=_scale(20, 48), max_new_tokens=_scale(8, 64),
        cancel_frac=0.5, cancel_after_s=0.05, spread_s=0.1,
    )
    phase.update(overrides)
    return Scenario(
        "cancel_storm", seed, (Phase(**phase),),
        description="clients abandoning requests mid-decode",
    )


def rate_storm(seed: int | None = None, **overrides) -> Scenario:
    """An oversubscription burst aimed at the admission gate: more
    simultaneous arrivals than the queue bound, so the 429 path (and the
    client's Retry-After handling) actually fires."""
    seed = loadgen_seed_default() if seed is None else seed
    phase = dict(
        kind="rate_storm", n=_scale(10, 48), tenants=4, shared_prefix=8,
        prompt_tokens=_scale(16, 48), max_new_tokens=_scale(4, 16),
    )
    phase.update(overrides)
    return Scenario(
        "rate_storm", seed, (Phase(**phase),),
        description="simultaneous burst past the admission gate (429 storm)",
    )


def mixed_tenants(seed: int | None = None, **overrides) -> Scenario:
    """Tenants pinned to different adapters via the OpenAI ``model`` field —
    the multi-model routing shape (ROADMAP Open item 4). Backends without a
    model registry serve them all from the base model; the schedule still
    pins which request WOULD go where."""
    seed = loadgen_seed_default() if seed is None else seed
    phase = dict(
        kind="mixed", n=_scale(6, 24), tenants=3, shared_prefix=8,
        prompt_tokens=_scale(20, 64), max_new_tokens=_scale(4, 16),
        adapters=("base", "adapter-a", "adapter-b"), spread_s=0.2,
    )
    phase.update(overrides)
    return Scenario(
        "mixed_tenants", seed, (Phase(**phase),),
        description="per-tenant adapters behind one endpoint",
    )


def spec_friendly(seed: int | None = None, **overrides) -> Scenario:
    """Repetitive/templated completions — the favorable regime for
    prompt-lookup speculative decoding: each prompt tiles a short token
    cycle, so greedy continuations settle into loops the n-gram drafter
    predicts and verify windows accept several tokens per dispatch. Run it
    spec-on vs spec-off (bench.py's spec section, the loadgen smoke) to
    publish the speedup and accept ratio."""
    seed = loadgen_seed_default() if seed is None else seed
    phase = dict(
        kind="spec_friendly", n=_scale(4, 12), tenants=2,
        cycle_tokens=8, prompt_tokens=_scale(49, 97),
        max_new_tokens=_scale(24, 64), spread_s=0.1,
    )
    phase.update(overrides)
    return Scenario(
        "spec_friendly", seed, (Phase(**phase),),
        description="repetitive/templated completions where n-gram drafts accept",
    )


def disagg(seed: int | None = None, **overrides) -> Scenario:
    """Long-prompt-heavy traffic — the phase-split shape (ROADMAP Open item
    4): a continuous wave of long-prefill requests with real decode tails.
    On a colocated fleet every admission's long prefill blocks the engine
    loop, stalling the decode ticks of every slot sharing the replica; a
    disaggregated fleet prefills on one replica (whose slots free at
    admission, so waves batch) and decodes on another (whose loop only ever
    pays the assemble + unaligned-tail suffix per migrated request).
    A short per-tenant preamble keeps the prefix-affinity/cache machinery
    in play (each admission still prefills ≥ 95% of its prompt cold, so
    the interference the scenario exists to create survives)."""
    seed = loadgen_seed_default() if seed is None else seed
    phase = dict(
        kind="longctx", n=_scale(8, 16), tenants=2, shared_prefix=16,
        prompt_tokens=_scale(192, 384), max_new_tokens=16,
        spread_s=_scale(1, 2) * 1.0,
    )
    phase.update(overrides)
    return Scenario(
        "disagg", seed, (Phase(**phase),),
        description="long-prompt-heavy wave for the phase-split fleet",
    )


def longprefix(seed: int | None = None, **overrides) -> Scenario:
    """Long-shared-prefix traffic — the paged-gather seeding shape
    (docs/kernels.md "paged_gather"): each tenant's requests open with a
    LONG common preamble (several radix blocks) and diverge only in a short
    tail, so after the first admission per tenant every request is
    dominated by hit seeding, not cold prefill. The arrival spread splits
    the schedule into an effective seed wave (first request per tenant
    stores the preamble) and a hit wave (everything after reuses it) —
    run it paged vs copy (the loadgen smoke's longprefix section) to
    publish the seeding-path comparison."""
    seed = loadgen_seed_default() if seed is None else seed
    phase = dict(
        kind="chat_burst", n=_scale(8, 24), tenants=2,
        shared_prefix=_scale(48, 192), prompt_tokens=_scale(56, 224),
        max_new_tokens=_scale(4, 16), spread_s=0.6,
    )
    phase.update(overrides)
    return Scenario(
        "longprefix", seed, (Phase(**phase),),
        description="long shared prefixes where hit seeding dominates",
    )


def smoke(seed: int | None = None) -> Scenario:
    """The CI scenario: one tiny composite touching every phase kind in
    seconds on CPU — shared-prefix burst, one long outlier, a couple of
    cancels, and a small oversubscription wave."""
    seed = loadgen_seed_default() if seed is None else seed
    return Scenario(
        "smoke",
        seed,
        (
            # 16-token shared preambles span one MIN_BUCKET block, so the
            # radix cache can actually hit; the spread staggers admissions
            # past the first store
            Phase(kind="chat_burst", n=6, tenants=2, shared_prefix=16,
                  prompt_tokens=28, max_new_tokens=6, spread_s=0.3),
            Phase(kind="longctx", n=1, prompt_tokens=48, max_new_tokens=4,
                  start_s=0.02),
            Phase(kind="cancel_storm", n=2, prompt_tokens=16, max_new_tokens=24,
                  cancel_frac=1.0, cancel_after_s=0.4, start_s=0.04),
            Phase(kind="rate_storm", n=4, prompt_tokens=16, max_new_tokens=4,
                  start_s=0.06),
        ),
        description="tiny composite of every phase kind (CI smoke)",
    )


SCENARIOS = {
    "chat_burst": chat_burst,
    "longctx_outliers": longctx_outliers,
    "cancel_storm": cancel_storm,
    "rate_storm": rate_storm,
    "mixed_tenants": mixed_tenants,
    "spec_friendly": spec_friendly,
    "disagg": disagg,
    "longprefix": longprefix,
    "smoke": smoke,
}
