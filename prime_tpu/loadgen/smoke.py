"""The loadgen CPU smoke: an in-process 2-replica fleet, one tiny scenario,
one nonzero headline.

This is the run that ends the era of empty trajectories: no TPU, no axon
tunnel, no checkpoint — two tiny continuous-batching engines behind real
``InferenceServer`` processes-worth of HTTP and a real ``FleetRouter``,
driven by the deterministic ``smoke`` scenario over the wire. It produces:

- ``slo_report.json`` — the versioned SLO report (registry-derived tok/s,
  TTFT/TPOT percentiles, hit/overlap/affinity ratios);
- ``bench_record.json`` — the same headline in BENCH record schema 2, so
  ``perf_delta.py`` folds CI smokes into the same trajectory as TPU rounds;
- an exposition lint verdict over every surface's ``/metrics`` text
  (checked against the docs/observability.md catalog);
- the router's flight-recorder scrape (the replay seed).

Shared by ``scripts/loadgen_smoke.py`` (CI job ``loadgen-smoke``) and
``prime bench smoke``. Import cost: jax and the serve stack load inside
:func:`run_smoke`, not at module import — the CLI stays light.
"""

from __future__ import annotations

import json
import os
from typing import Any

from prime_tpu.loadgen.scenario import SCENARIOS, loadgen_seed_default


def _spec_section(
    config, params_fn, *, seed: int, mesh: str | None, log
) -> tuple[dict[str, Any], list]:
    """The speculative on/off comparison: drive the ``spec_friendly``
    scenario (repetitive completions where n-gram drafts accept) through
    one in-process engine with speculation off, then on — same schedule,
    same registry-windowed measurement as every other section. Returns the
    BENCH-record keys (spec on/off tok/s, TPOT deltas, accept ratio,
    speedup) plus the two SLO scenario rows. With ``mesh`` set the engines
    are SHARDED, so the committed MULTICHIP round carries the
    spec × mesh evidence."""
    from prime_tpu.loadgen.backends import EngineTarget
    from prime_tpu.loadgen.report import scenario_row, spec_comparison_record
    from prime_tpu.loadgen.runner import run_schedule
    from prime_tpu.loadgen.scenario import SCENARIOS, build_schedule
    from prime_tpu.serve.engine import ContinuousBatchingEngine

    schedule = build_schedule(SCENARIOS["spec_friendly"](seed), vocab=config.vocab_size)
    rows = []
    for speculative in (False, True):
        name = "spec_friendly" if speculative else "spec_friendly_off"
        engine = ContinuousBatchingEngine(
            params_fn(), config, pad_id=0, max_slots=4, capacity=256, chunk=4,
            prefix_cache_mb=8, speculative=speculative, mesh_config=mesh or None,
        )
        try:
            # warm the shapes in play (incl. the second-admission prefix
            # hit), then measure through the registry-windowed runner —
            # time_scale=0 drives the whole burst immediately
            for _ in range(2):
                warm = engine.submit(
                    list(schedule[0].prompt_ids),
                    max_new_tokens=schedule[0].max_new_tokens,
                )
                while not warm.done:
                    engine.tick()
            engine.tick()
            result = run_schedule(
                schedule, EngineTarget(engine), scenario=name, seed=seed,
                time_scale=0.0,
            )
            rows.append(scenario_row(result))
        finally:
            engine.shutdown()
    off_row, on_row = rows
    record = spec_comparison_record(off_row, on_row)
    log(
        f"# loadgen-smoke: spec_friendly spec-on {record['serve_spec_tok_s']} "
        f"vs spec-off {record['serve_spec_off_tok_s']} tok/s "
        f"(accept ratio {record.get('serve_spec_accept_ratio')})"
    )
    return record, rows


def _longprefix_section(
    config, params_fn, *, seed: int, mesh: str | None, log
) -> tuple[dict[str, Any], list]:
    """The paged-vs-copy seeding comparison (docs/kernels.md
    "paged_gather"): the ``longprefix`` scenario — long shared preambles
    where hit seeding dominates — through one engine seeding hits from the
    page pool, then the SAME schedule through one seeding via the
    contiguous copy path. Record keys: tok/s both ways plus the mean
    hit-seed wall time per path straight from the
    ``serve_prefix_seed_seconds{path=...}`` histogram — the paging win's
    direct evidence (on CPU the gather runs the XLA fallback; the numbers
    prove the path and its accounting, the TPU round proves the speed)."""
    from prime_tpu.loadgen.backends import EngineTarget
    from prime_tpu.loadgen.report import scenario_row
    from prime_tpu.loadgen.runner import run_schedule
    from prime_tpu.loadgen.scenario import SCENARIOS, build_schedule
    from prime_tpu.serve.engine import ContinuousBatchingEngine

    schedule = build_schedule(
        SCENARIOS["longprefix"](seed), vocab=config.vocab_size
    )
    rows = []
    record: dict[str, Any] = {}
    for paged in (False, True):
        name = "longprefix" if paged else "longprefix_copy"
        engine = ContinuousBatchingEngine(
            params_fn(), config, pad_id=0, max_slots=4, capacity=256, chunk=4,
            prefix_cache_mb=8, paged_prefix=paged, mesh_config=mesh or None,
        )
        try:
            # warm the shapes in play (incl. the second-admission hit-seed),
            # then measure through the registry-windowed runner
            for _ in range(2):
                warm = engine.submit(
                    list(schedule[0].prompt_ids),
                    max_new_tokens=schedule[0].max_new_tokens,
                )
                while not warm.done:
                    engine.tick()
            engine.tick()
            result = run_schedule(
                schedule, EngineTarget(engine), scenario=name, seed=seed,
                time_scale=0.0,
            )
            rows.append(scenario_row(result))
            key = "serve_longprefix" if paged else "serve_longprefix_copy"
            record[f"{key}_tok_s"] = rows[-1]["tok_s"]
            path = "paged" if paged else "copy"
            hist = engine.registry.get(
                "serve_prefix_seed_seconds"
            ).series_snapshot(path=path)
            if hist and hist.get("count"):
                record[f"{key}_seed_ms"] = round(
                    hist["sum"] / hist["count"] * 1e3, 3
                )
            if paged:
                record["serve_longprefix_paged_seeds"] = engine.stats()[
                    "prefix_paged_seeds"
                ]
        finally:
            engine.shutdown()
    log(
        f"# loadgen-smoke: longprefix paged {record.get('serve_longprefix_tok_s')} "
        f"vs copy {record.get('serve_longprefix_copy_tok_s')} tok/s "
        f"(seed-ms {record.get('serve_longprefix_seed_ms')} vs "
        f"{record.get('serve_longprefix_copy_seed_ms')}, "
        f"{record.get('serve_longprefix_paged_seeds')} paged seeds)"
    )
    return record, rows


def _autotune_section(*, log) -> dict[str, Any]:
    """The autotune round-trip leg (docs/kernels.md "Kernel campaign &
    autotune"): a dry-run sweep over every kernel's trimmed candidate grid,
    winners saved to a throwaway artifact dir and loaded back through the
    production resolution path. Record keys ``autotune_kernels`` (kernels
    that produced a winner) and ``autotune_sweep_s`` (sweep wall time) —
    trajectory evidence that the sweep → artifact → resolve loop stays
    alive on every push."""
    import tempfile
    import time

    from prime_tpu.ops import kernel_configs
    from prime_tpu.ops.autotune import run_autotune

    t0 = time.perf_counter()
    winners = run_autotune(dry_run=True, log=None)
    sweep_s = time.perf_counter() - t0
    with tempfile.TemporaryDirectory(prefix="prime-autotune-") as tmp:
        kind = kernel_configs.device_kind()
        kernel_configs.save_artifact(winners, directory=tmp, kind=kind)
        # save/restore of the raw env var, not a config read: the section
        # must leave the process knob exactly as it found it
        saved = os.environ.get("PRIME_TPU_KERNEL_CONFIG_DIR")  # prime-lint: ignore[knob-direct-read] env save/restore, not a config read
        os.environ["PRIME_TPU_KERNEL_CONFIG_DIR"] = tmp
        kernel_configs.invalidate_cache()
        try:
            loaded = kernel_configs.load_tuned(kind)
            source = kernel_configs.source()
        finally:
            if saved is None:
                os.environ.pop("PRIME_TPU_KERNEL_CONFIG_DIR", None)
            else:
                os.environ["PRIME_TPU_KERNEL_CONFIG_DIR"] = saved
            kernel_configs.invalidate_cache()
    record: dict[str, Any] = {
        "autotune_kernels": len(winners),
        "autotune_sweep_s": round(sweep_s, 3),
    }
    if loaded is None or source != "tuned":
        record["autotune_error"] = (
            f"artifact failed to round-trip: loaded={loaded is not None} "
            f"source={source}"
        )
    log(
        f"# loadgen-smoke: autotune dry-run swept {record['autotune_kernels']} "
        f"kernels in {record['autotune_sweep_s']}s (source after load: {source})"
    )
    return record


def _multilora_section(
    config, params_fn, *, seed: int, mesh: str | None, log
) -> tuple[dict[str, Any], list]:
    """The batched multi-LoRA comparison (docs/architecture.md "Multi-LoRA
    serving"): the ``mixed_tenants`` scenario — tenants pinned to two LoRA
    adapters plus base via the OpenAI ``model`` field — through ONE engine
    holding the unmerged adapter bank, against the SAME schedule stripped to
    base-only on a bankless engine (the single-checkpoint headline config).
    Two throwaway adapter artifacts are trained-shaped (random factors,
    base-fingerprinted) and saved through ``train/lora.save_adapters`` so
    the load path exercised is the production one. Record keys:
    ``serve_multilora_tok_s`` / ``serve_multilora_base_tok_s`` / their
    ratio (the ≥0.8x acceptance gate reads it) and the per-adapter fairness
    ratio (min/max delivered tokens across base + adapters — 1.0 = perfectly
    even under the equal-demand mixed schedule).

    Scale note: run this at debug-128m (like the disagg section), not
    tiny-test — the gathered delta adds a fixed handful of small einsums
    per projection, and against a tiny model's near-zero matmuls that
    handful IS the runtime (the measured ratio would be an op-count
    artifact); at 128m the base matmuls are real work and the measured
    ratio reflects the architecture's actual multi-tenant cost."""
    import contextlib
    import dataclasses
    import tempfile

    import jax

    from prime_tpu.loadgen.backends import EngineTarget
    from prime_tpu.loadgen.report import scenario_row
    from prime_tpu.loadgen.runner import run_schedule
    from prime_tpu.loadgen.scenario import SCENARIOS, build_schedule
    from prime_tpu.serve.engine import ContinuousBatchingEngine
    from prime_tpu.train.lora import LoraConfig, init_lora_params, save_adapters

    schedule = build_schedule(
        SCENARIOS["mixed_tenants"](seed), vocab=config.vocab_size
    )
    base_schedule = [dataclasses.replace(r, adapter=None) for r in schedule]
    params = params_fn()
    lora = LoraConfig(r=8, alpha=16)
    stack = contextlib.ExitStack()
    tmp = stack.enter_context(
        tempfile.TemporaryDirectory(prefix="prime-multilora-")
    )
    paths: dict[str, str] = {}
    for i, name in enumerate(("adapter-a", "adapter-b")):
        factors = init_lora_params(jax.random.PRNGKey(10 + i), config, lora)
        # B is zero at init (a no-op adapter); give it small random values so
        # the gathered matmuls measure real distinct fine-tunes, not zeros
        factors["layers"] = {
            t: {
                "a": ab["a"],
                "b": (
                    jax.random.normal(jax.random.PRNGKey(20 + i), ab["b"].shape)
                    * 0.02
                ).astype(ab["b"].dtype),
            }
            for t, ab in factors["layers"].items()
        }
        path = os.path.join(tmp, name)
        save_adapters(path, factors, lora, config, base_params=params)
        paths[name] = path

    rows = []
    try:
        for label, adapters, sched in (
            ("multilora_base", None, base_schedule),
            ("multilora", paths, schedule),
        ):
            engine = ContinuousBatchingEngine(
                params, config, pad_id=0, max_slots=4, capacity=256, chunk=4,
                prefix_cache_mb=8, adapters=adapters, mesh_config=mesh or None,
            )
            try:
                # warm the shapes in play, then measure registry-windowed
                for _ in range(2):
                    warm = engine.submit(
                        list(sched[0].prompt_ids),
                        max_new_tokens=sched[0].max_new_tokens,
                    )
                    while not warm.done:
                        engine.tick()
                engine.tick()
                result = run_schedule(
                    sched, EngineTarget(engine), scenario=label, seed=seed,
                    time_scale=0.0,
                )
                rows.append(scenario_row(result))
            finally:
                engine.shutdown()
    finally:
        stack.close()  # the artifact dir is throwaway — never leak it
    base_row, mixed_row = rows
    record: dict[str, Any] = {
        "serve_multilora_base_tok_s": base_row["tok_s"],
        "serve_multilora_tok_s": mixed_row["tok_s"],
    }
    if base_row["tok_s"]:
        record["serve_multilora_ratio"] = round(
            mixed_row["tok_s"] / base_row["tok_s"], 3
        )
    split = mixed_row.get("adapters") or {}
    per_adapter = [entry["tokens"] for entry in split.values()]
    if per_adapter and max(per_adapter) > 0:
        record["serve_multilora_fairness"] = round(
            min(per_adapter) / max(per_adapter), 3
        )
    record["serve_multilora_adapters"] = {
        name: entry["tokens"] for name, entry in split.items()
    }
    log(
        f"# loadgen-smoke: multilora mixed {record['serve_multilora_tok_s']} "
        f"vs base-only {record['serve_multilora_base_tok_s']} tok/s "
        f"(ratio {record.get('serve_multilora_ratio')}, fairness "
        f"{record.get('serve_multilora_fairness')}, per-adapter "
        f"{record['serve_multilora_adapters']})"
    )
    return record, rows


def _elastic_section(
    config, params_fn, *, seed: int, log
) -> tuple[dict[str, Any], list]:
    """The live elastic leg (docs/architecture.md "Elastic fleet"): ONE
    tiny-engine replica behind a router whose autoscaler is armed with
    smoke-scale windows/cooldowns, driven by repeated ``rate_storm`` bursts
    over real HTTP. The storm must breach the tightened SLO policies, the
    actuator must spawn real engine replicas (in-process launcher — the
    same ``ReplicaLauncher`` seam the subprocess launcher plugs), and once
    the storm ends the fleet must drain back down: replica count 1→N→1
    with zero failed requests (429s are shed load, not failures) and every
    drain completing in-flight work. Record keys ``serve_elastic_*``."""
    import time

    from prime_tpu.loadgen.backends import HTTPTarget, NumericTokenizer
    from prime_tpu.loadgen.report import scenario_row
    from prime_tpu.loadgen.runner import run_schedule
    from prime_tpu.loadgen.scenario import SCENARIOS, build_schedule
    from prime_tpu.obs.slo import SloEvaluator, SloPolicy
    from prime_tpu.serve.engine import ContinuousBatchingEngine, EngineBackend
    from prime_tpu.serve.fleet import (
        AutoscalerConfig,
        FleetAutoscaler,
        ReplicaSupervisor,
        serve_fleet,
    )
    from prime_tpu.serve.server import InferenceServer

    class _ServerHandle:
        def __init__(self, srv) -> None:
            self.srv = srv
            self.url = srv.url
            self._alive = True

        def alive(self) -> bool:
            return self._alive

        def terminate(self) -> None:
            if self._alive:
                self._alive = False
                self.srv.stop()  # shuts the backing engine down too

    class _EngineLauncher:
        """In-process ReplicaLauncher: each spawn is a REAL engine behind a
        REAL InferenceServer on a fresh port — the launch seam exercised
        end to end without subprocess checkpoint loads."""

        def __init__(self) -> None:
            self.handles: list[_ServerHandle] = []

        def spawn(self) -> _ServerHandle:
            engine = ContinuousBatchingEngine(
                params_fn(), config, pad_id=0, max_slots=4, capacity=128,
                chunk=4, prefix_cache_mb=8, max_queue=16,
            )
            engine.start()
            srv = InferenceServer(
                "loadgen-smoke", EngineBackend(engine, NumericTokenizer()), port=0
            ).start()
            handle = _ServerHandle(srv)
            self.handles.append(handle)
            return handle

    # smaller bursts than the CI rate_storm default: each round must finish
    # in seconds on one tiny CPU engine so several rounds fit the smoke
    schedule = build_schedule(
        SCENARIOS["rate_storm"](seed, n=12, prompt_tokens=16, max_new_tokens=8),
        vocab=config.vocab_size,
    )
    launcher = _EngineLauncher()
    seed_handle = launcher.spawn()  # replica #1 (managed, so 1→N→1 can reap N-1)
    router = serve_fleet(
        [seed_handle.url], poll_interval=0.2, model_id="loadgen-smoke",
    )
    rows: list = []
    record: dict[str, Any] = {}
    try:
        # smoke-scale observatory: tight windows + thresholds a tiny-engine
        # storm actually breaches within seconds (the production defaults
        # would need minutes of sustained burn — right for a fleet, wrong
        # for a CI leg)
        router.slo = SloEvaluator(
            (
                SloPolicy(name="ttft_p95", kind="latency",
                          metric="serve_ttft_seconds", threshold=0.3),
                SloPolicy(name="queue_wait_p95", kind="latency",
                          metric="serve_queue_wait_seconds", threshold=0.2),
                SloPolicy(
                    name="reject_rate", kind="error_rate", source="router",
                    numerator=("fleet_admission_rejected_total",),
                    denominator=(
                        "fleet_admission_rejected_total", "fleet_requests_total",
                    ),
                    threshold=0.05,
                ),
                SloPolicy(name="utilization_floor", kind="utilization_floor",
                          metric="serve_active_slots", threshold=0.1),
            ),
            fast_s=1.5, slow_s=4.0,
        )
        supervisor = ReplicaSupervisor(launcher, membership=router.membership)
        router.attach_autoscaler(
            FleetAutoscaler(
                supervisor,
                AutoscalerConfig(
                    min_replicas=1, max_replicas=3,
                    up_cooldown_s=2.0, down_cooldown_s=3.0,
                ),
            )
        )
        # the seed replica is the one engine guaranteed alive all run
        # (LIFO retirement keeps the oldest), so the registry-windowed
        # tok_s scrapes it — spawned replicas' tokens are NOT counted
        # (they come and go mid-run; the replica-count trajectory, not
        # throughput, is this leg's headline)
        target = HTTPTarget(
            router.url,
            scrape_urls={"router": router.url, "replica0": seed_handle.url},
            timeout_s=120.0,
        )
        # warm the seed replica's shapes off the measured storm
        import httpx

        for n in sorted({len(r.prompt_ids) for r in schedule}):
            httpx.post(
                f"{seed_handle.url}/v1/chat/completions",
                json={"messages": [{"role": "user", "content": " ".join(["7"] * n)}],
                      "max_tokens": 4, "temperature": 0.0},
                timeout=120.0,
            ).raise_for_status()
        peak = 1
        failed = 0
        rounds = 6
        for i in range(rounds):
            result = run_schedule(
                schedule, target, scenario="elastic", seed=seed, time_scale=0.0,
            )
            failed += result.outcomes.get("error", 0) + result.outcomes.get(
                "timeout", 0
            )
            with router.membership._lock:
                peak = max(peak, len(router.membership.replicas))
            if i == rounds - 1:
                rows.append(scenario_row(result))
        # storm over: the idle fleet must shrink back to min (drains
        # complete in-flight work first; the poll loop keeps actuating)
        deadline = time.monotonic() + 60.0
        final = peak
        while time.monotonic() < deadline:
            with router.membership._lock:
                final = len(router.membership.replicas)
            if final <= 1 and not supervisor.pending():
                break
            time.sleep(0.25)
        # actuation counts come from the actions COUNTER, not the bounded
        # journal tail (a long run's early spawns scroll out of the tail)
        actions = {
            (s["labels"]["direction"], s["labels"]["outcome"]): int(s["value"])
            for s in router.registry.snapshot()["fleet_autoscale_actions_total"][
                "series"
            ]
        }
        ups = actions.get(("up", "spawned"), 0)
        downs = actions.get(("down", "retired"), 0)
        record = {
            "serve_elastic_tok_s": rows[0]["tok_s"] if rows else 0.0,
            "serve_elastic_peak_replicas": peak,
            "serve_elastic_final_replicas": final,
            "serve_elastic_scale_ups": ups,
            "serve_elastic_scale_downs": downs,
            "serve_elastic_failed_requests": failed,
        }
        if failed or peak < 2 or final > 1:
            record["serve_elastic_error"] = (
                f"elastic leg did not complete 1→N→1 cleanly: peak={peak} "
                f"final={final} failed={failed}"
            )
        log(
            f"# loadgen-smoke: elastic 1→{peak}→{final} "
            f"({ups} scale-ups, {downs} scale-downs, {failed} failed requests, "
            f"{record['serve_elastic_tok_s']} tok/s final round)"
        )
        return record, rows
    finally:
        router.stop()  # reaps the managed replicas through the supervisor
        for handle in launcher.handles:
            handle.terminate()


def _sentinel_section(config, params_fn, *, seed: int, log) -> dict[str, Any]:
    """The sentinel's false-positive AND true-positive gate in one leg
    (docs/observability.md "Sentinel & incidents"): a clean in-process
    server driven with steady traffic must raise ZERO incidents, then the
    same traffic against an engine with an env-injected dispatch delay
    (``PRIME_SENTINEL_INJECT_MS``, armed to activate only after the clean
    run's measured dispatch count — a genuine mid-run change-point) must
    raise EXACTLY ONE incident whose bundle carries flight timelines and
    registry deltas, fetchable over ``GET /admin/incidents``. Record keys
    ``serve_sentinel_*``."""
    import time

    import httpx

    from prime_tpu.loadgen.backends import NumericTokenizer
    from prime_tpu.obs.sentinel import Sentinel, SentinelRule
    from prime_tpu.serve.engine import ContinuousBatchingEngine, EngineBackend
    from prime_tpu.serve.server import InferenceServer

    # smoke-scale rule: tiny windows a seconds-long leg actually covers
    # (production defaults need minutes of history), fast p95 vs slow
    # MEDIAN so the slow window absorbing the regression's own samples
    # doesn't erase the change-point, and a 20 ms absolute deadband so
    # clean-run CPU timing jitter can't fire it (steps run ~1-5 ms/token;
    # the planted 120 ms/dispatch delay lands ~30 ms/token)
    rule = SentinelRule(
        name="step_clock_regression", kind="quantile_regression",
        metric="serve_decode_step_seconds", severity="critical",
        q=0.95, baseline_q=0.5, ratio=3.0, min_value=0.02,
    )
    prompt = " ".join(["7"] * 12)

    def _launch(inject: str | None):
        saved = os.environ.pop("PRIME_SENTINEL_INJECT_MS", None)
        if inject is not None:
            os.environ["PRIME_SENTINEL_INJECT_MS"] = inject
        try:
            engine = ContinuousBatchingEngine(
                params_fn(), config, pad_id=0, max_slots=4, capacity=128,
                chunk=4, prefix_cache_mb=8, max_queue=16,
            )
        finally:
            if saved is None:
                os.environ.pop("PRIME_SENTINEL_INJECT_MS", None)
            else:
                os.environ["PRIME_SENTINEL_INJECT_MS"] = saved
        engine.start()
        srv = InferenceServer(
            "loadgen-smoke", EngineBackend(engine, NumericTokenizer()), port=0
        ).start()
        srv.sentinel = Sentinel((rule,), fast_s=1.0, slow_s=3.2, min_samples=3)
        return engine, srv

    def _drive(srv, n: int, *, pause_s: float, stop_on_incident: bool) -> None:
        for _ in range(n):
            httpx.post(
                f"{srv.url}/v1/chat/completions",
                json={"messages": [{"role": "user", "content": prompt}],
                      "max_tokens": 8, "temperature": 0.0},
                timeout=120.0,
            ).raise_for_status()
            srv.observatory_sample()
            if stop_on_incident and len(srv.incidents):
                return
            if pause_s:
                time.sleep(pause_s)

    # ---- clean phase: steady traffic, zero incidents ----------------------
    engine, srv = _launch(None)
    try:
        _drive(srv, 14, pause_s=0.14, stop_on_incident=False)
        clean_incidents = len(srv.incidents)
        # the planted run replays this exact request sequence, so this
        # engine's dispatch count is where its delay should switch on
        clean_dispatches = int(getattr(engine, "_dispatch_count", 0))
    finally:
        srv.stop()

    # ---- planted phase: same traffic, delay arms mid-run ------------------
    engine, srv = _launch(f"120@{max(1, clean_dispatches)}")
    bundle: dict[str, Any] = {}
    listing: dict[str, Any] = {}
    try:
        _drive(srv, 14, pause_s=0.14, stop_on_incident=True)  # clean baseline
        deadline = time.monotonic() + 20.0
        while not len(srv.incidents) and time.monotonic() < deadline:
            _drive(srv, 4, pause_s=0.0, stop_on_incident=True)
        planted_incidents = len(srv.incidents)
        if planted_incidents:
            # the bundle must round-trip over the admin surface, not just
            # the in-process store
            listing = httpx.get(f"{srv.url}/admin/incidents", timeout=10).json()
            first = (listing.get("incidents") or [{}])[0]
            bundle = httpx.get(
                f"{srv.url}/admin/incidents/{first.get('id')}", timeout=10
            ).json()
    finally:
        srv.stop()

    bundle_ok = bool(bundle.get("flights")) and bool(bundle.get("metrics"))
    record: dict[str, Any] = {
        "serve_sentinel_clean_incidents": clean_incidents,
        "serve_sentinel_planted_incidents": planted_incidents,
        "serve_sentinel_bundle_flights": len(bundle.get("flights") or ()),
        "serve_sentinel_bundle_metrics": len(bundle.get("metrics") or ()),
    }
    if clean_incidents != 0 or planted_incidents != 1 or not bundle_ok:
        record["serve_sentinel_error"] = (
            f"sentinel leg off-contract: clean={clean_incidents} (want 0) "
            f"planted={planted_incidents} (want 1) bundle_ok={bundle_ok}"
        )
    log(
        f"# loadgen-smoke: sentinel clean={clean_incidents} incidents, "
        f"planted={planted_incidents} (rule={bundle.get('rule')}, "
        f"{record['serve_sentinel_bundle_flights']} flight timelines, "
        f"{record['serve_sentinel_bundle_metrics']} registry deltas)"
    )
    return record


def disagg_comparison(
    config,
    params_fn,
    *,
    seed: int,
    model_id: str = "disagg",
    max_slots: int = 8,
    capacity: int = 1024,
    chunk: int = 4,
    decode_chunk: int | None = None,
    prefix_cache_mb: float = 256,
    max_queue: int = 64,
    time_scale: float = 1.0,
    warmup: bool = False,
    mesh_roles: bool = False,
    log=print,
) -> tuple[dict[str, Any], list]:
    """Phase-split vs colocated, same device budget, same schedule.

    ``mesh_roles=True`` is the MULTICHIP variant: every replica becomes a
    SHARDED engine over a disjoint half of the available devices, laid out
    by its role preset (``role:prefill`` = tp-absorbing, ``role:decode`` =
    dp-absorbing, serve/mesh_config.ROLE_MESH_PRESETS; colocated ``any``
    replicas take the prefill-shaped tp layout so both cells span identical
    hardware). Run under ``XLA_FLAGS=--xla_force_host_platform_device_count=4``
    this measures the role-preset disaggregation on a forced CPU mesh — the
    open measurement from the PR 11 round.

    The long-prompt-heavy ``disagg`` scenario runs over real HTTP through a
    FleetRouter against (a) two colocated ``any``-role replicas on the
    balanced serving config, and (b) 1 prefill + 1 decode replica with KV
    migration over the prefix-cache wire format. The phase replicas run
    ROLE-TUNED engine policies — the point of disaggregation (PAPERS'
    per-topology Gemma serving tables): the prefill replica stores every
    batched-wave member's KV (``prefix_store_all``, so its exports cover
    batched admissions), and ``decode_chunk`` (None = same as ``chunk``)
    can deepen the decode replica's chunk to amortize per-dispatch
    overhead. When a deep chunk is asked for, a third cell — colocated on
    the SAME deep chunk — is also measured (``serve_disagg_colo_deep_*``):
    a both-phases replica pays for that setting in cold-admission latency
    and retirement waste (up to a whole chunk per retirement), and the
    cell shows the compromise is real rather than assumed. Returns the
    ``serve_disagg_*`` BENCH-record keys plus the SLO scenario rows.

    Honesty note: every migrated request makes its prefill replica emit ONE
    throwaway token (``max_tokens=1`` pins the KV store). The registry-
    derived row counts it; ``serve_disagg_tok_s`` subtracts those tokens so
    the committed headline counts only client-delivered tokens."""
    import concurrent.futures

    import httpx
    import jax

    from prime_tpu.loadgen.backends import HTTPTarget, NumericTokenizer
    from prime_tpu.loadgen.report import scenario_row
    from prime_tpu.loadgen.runner import run_schedule
    from prime_tpu.loadgen.scenario import SCENARIOS, build_schedule
    from prime_tpu.serve.engine import ContinuousBatchingEngine, EngineBackend
    from prime_tpu.serve.fleet import serve_fleet
    from prime_tpu.serve.server import InferenceServer

    schedule = build_schedule(SCENARIOS["disagg"](seed), vocab=config.vocab_size)
    prompt_len = len(schedule[0].prompt_ids)
    rows: dict[str, dict] = {}
    record: dict[str, Any] = {}
    decode_chunk = chunk if decode_chunk is None else decode_chunk
    cells: list[tuple[str, tuple[str, str], tuple[int, int]]] = [
        ("colocated", ("any", "any"), (chunk, chunk)),
        ("disagg", ("prefill", "decode"), (chunk, decode_chunk)),
    ]
    if decode_chunk != chunk:
        # the compromise cell: colocated on the decode role's deep chunk —
        # evidence that the role-tuned setting is NOT free for a replica
        # that must also admit cold interactive prefills
        cells.insert(1, ("colocated_deep", ("any", "any"), (decode_chunk, decode_chunk)))
    # ONE parameter set for every replica in every cell: a fleet serves one
    # checkpoint, and above all the migrated KV is only meaningful when the
    # decode replica resumes under the SAME weights that computed it —
    # per-replica params would silently benchmark an incoherent fleet
    params = params_fn(0)
    per_replica_devices = jax.device_count() // 2 if mesh_roles else 0
    if mesh_roles and per_replica_devices < 2:
        raise ValueError(
            f"mesh_roles needs >= 4 devices (2 per replica); have "
            f"{jax.device_count()} — force more with "
            "XLA_FLAGS=--xla_force_host_platform_device_count=4"
        )
    for mode, roles, chunks in cells:
        engines: list = []
        servers: list = []
        router = None
        try:
            for i, role in enumerate(roles):
                mesh_kw: dict = {"mesh_config": ""}
                engine_params = params
                if mesh_roles:
                    # role-preset layout over this replica's DISJOINT device
                    # slice (same disjointness contract as run_smoke --mesh:
                    # overlapping meshes would measure contention, not
                    # disaggregation). "any" replicas take the prefill
                    # (tp-absorbing) shape so the colocated cell spans the
                    # same hardware as the phase-split one.
                    from prime_tpu.parallel.sharding import (
                        serving_cache_spec,
                        shard_params,
                    )
                    from prime_tpu.serve.mesh_config import parse_mesh_spec

                    spec = "role:prefill" if role in ("any", "prefill") else "role:decode"
                    cfg = parse_mesh_spec(spec, per_replica_devices)
                    replica_mesh = cfg.build(
                        jax.devices()[
                            i * per_replica_devices : (i + 1) * per_replica_devices
                        ]
                    )
                    engine_params = shard_params(params, replica_mesh, config)
                    mesh_kw = {
                        "mesh": replica_mesh,
                        "cache_spec": serving_cache_spec(config, replica_mesh),
                    }
                engine = ContinuousBatchingEngine(
                    engine_params, config, pad_id=0, max_slots=max_slots,
                    capacity=capacity, chunk=chunks[i],
                    prefix_cache_mb=prefix_cache_mb, max_queue=max_queue,
                    warmup=warmup, **mesh_kw,
                    # role-tuned store policy: the prefill replica's batched
                    # waves must leave every member exportable
                    prefix_store_all=role == "prefill",
                )
                engine.start()
                engines.append(engine)
                servers.append(
                    InferenceServer(
                        model_id, EngineBackend(engine, NumericTokenizer()),
                        port=0, role=role,
                    ).start()
                )
            router = serve_fleet(
                [srv.url for srv in servers], poll_interval=0.2, model_id=model_id,
            )
            target = HTTPTarget(
                router.url,
                scrape_urls={
                    "router": router.url,
                    **{f"replica{i}": srv.url for i, srv in enumerate(servers)},
                },
                timeout_s=240.0,
            )
            # warm OFF the measured window. Direct per-replica warms compile
            # the cold prefill/decode shapes on both engines; router-path
            # warm bursts (4 concurrent, distinct non-schedule prefixes)
            # compile the batched admission widths AND — in disagg mode —
            # the migration-only shapes (the mid-length assemble_row and the
            # suffix chunk on the decode replica, the export/import path on
            # both). Warm prompts lead with reserved ids so they can never
            # prefix-hit a schedule prompt.
            def warm_ids(k: int) -> str:
                return " ".join(["2"] + [str(k)] * (prompt_len - 1))

            for srv in servers:
                httpx.post(
                    f"{srv.url}/v1/chat/completions",
                    json={
                        "messages": [{"role": "user", "content": warm_ids(0)}],
                        "max_tokens": 4, "temperature": 0.0,
                    },
                    timeout=240.0,
                ).raise_for_status()

            def warm_router(k: int) -> None:
                httpx.post(
                    f"{router.url}/v1/chat/completions",
                    json={
                        "messages": [{"role": "user", "content": warm_ids(k)}],
                        "max_tokens": 4, "temperature": 0.0,
                    },
                    timeout=240.0,
                ).raise_for_status()

            with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
                for _round in range(2):
                    list(
                        pool.map(warm_router, range(1 + _round * 4, 5 + _round * 4))
                    )

            result = run_schedule(
                schedule, target,
                scenario="disagg" if mode == "disagg" else f"disagg_{mode}",
                seed=seed, time_scale=time_scale, max_workers=8,
            )
            rows[mode] = scenario_row(result)
        finally:
            if router is not None:
                router.stop()
            for srv in servers:
                srv.stop()  # also shuts down the backing engine
            for engine in engines[len(servers):]:
                engine.shutdown()
    colo, split = rows["colocated"], rows["disagg"]
    fleet = split.get("fleet") or {}
    migrations = fleet.get("migrations") or {}
    # every migration whose prefill leg answered 200 emitted one throwaway
    # token — ok, cold, AND the decode-side failures; only prefill_failed
    # never got that far
    migrated = sum(
        int(v) for k, v in migrations.items() if k != "prefill_failed"
    )
    split_duration = split.get("duration_s") or 0.0
    # delivered-token throughput: drop the 1 throwaway prefill-replica
    # token per migrated request (docstring)
    split_tok_s = (
        round(max(0, split["tokens"] - migrated) / split_duration, 2)
        if split_duration
        else 0.0
    )
    record["serve_disagg_tok_s"] = split_tok_s
    record["serve_disagg_colo_tok_s"] = colo["tok_s"]
    if colo["tok_s"]:
        record["serve_disagg_speedup"] = round(split_tok_s / colo["tok_s"], 3)
    for key, row in (("serve_disagg", split), ("serve_disagg_colo", colo)):
        for q in ("p50", "p95"):
            value = (row.get("ttft_s") or {}).get(q)
            if isinstance(value, (int, float)):
                record[f"{key}_ttft_{q}_ms"] = round(value * 1e3, 3)
    deep = rows.get("colocated_deep")
    if deep is not None:
        record["serve_disagg_colo_deep_tok_s"] = deep["tok_s"]
        deep_p95 = (deep.get("ttft_s") or {}).get("p95")
        if isinstance(deep_p95, (int, float)):
            record["serve_disagg_colo_deep_ttft_p95_ms"] = round(deep_p95 * 1e3, 3)
    record["serve_disagg_migrations"] = {k: int(v) for k, v in migrations.items()}
    record["serve_disagg_migrate_bytes"] = int(fleet.get("migrate_bytes") or 0)
    record["serve_disagg_model"] = getattr(config, "name", "?")
    record["serve_disagg_chunks"] = {"colocated": chunk, "decode_role": decode_chunk}
    if mesh_roles:
        from prime_tpu.serve.mesh_config import ROLE_MESH_PRESETS

        record["serve_disagg_mesh_roles"] = dict(ROLE_MESH_PRESETS)
        record["serve_disagg_mesh_devices"] = per_replica_devices * 2
    if not int(migrations.get("ok", 0)):
        record["serve_disagg_error"] = (
            "no successful KV migration in the measured window — the "
            "phase split never engaged; both numbers are colocated"
        )
    log(
        f"# disagg: phase-split {record['serve_disagg_tok_s']} vs colocated "
        f"{record['serve_disagg_colo_tok_s']} tok/s "
        f"(migrations {record['serve_disagg_migrations']}, "
        f"{record['serve_disagg_migrate_bytes']} KV bytes shipped; TTFT p95 "
        f"{record.get('serve_disagg_ttft_p95_ms')} vs "
        f"{record.get('serve_disagg_colo_ttft_p95_ms')} ms)"
    )
    return record, [row for row in (colo, deep, split) if row is not None]


def run_smoke(
    output_dir: str,
    *,
    scenario: str = "smoke",
    seed: int | None = None,
    replicas: int = 2,
    mesh: str | None = None,
    time_scale: float = 1.0,
    log=print,
) -> dict[str, Any]:
    """Run the CPU fleet smoke end to end; returns ``{"ok", "report",
    "record", "lint"}`` and writes the artifacts into ``output_dir``.
    ``ok`` is False when the headline is zero or any exposition fails lint —
    the CI job exits nonzero on it.

    ``mesh`` (a ``"dp=1,fsdp=2,tp=2"``-style spec) builds each replica as a
    SHARDED engine spanning that mesh (serve/mesh_config.py) — under
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` this measures the
    multi-chip serving path on CPU and stamps the record with the mesh, the
    shape a committed ``MULTICHIP_*.json`` round wants (docs/benchmarking.md)."""
    # CPU pin before jax initializes: the smoke must never touch (or wait
    # for) an accelerator backend, exactly like bench.py's smoke mode
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    import jax.numpy as jnp

    from prime_tpu.analysis.obs_contract import load_metrics_catalog
    from prime_tpu.loadgen.backends import HTTPTarget, NumericTokenizer
    from prime_tpu.loadgen.report import build_report
    from prime_tpu.loadgen.runner import run_schedule
    from prime_tpu.loadgen.scenario import build_schedule
    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.obs.metrics import lint_prometheus_text
    from prime_tpu.serve.engine import ContinuousBatchingEngine, EngineBackend
    from prime_tpu.serve.fleet import serve_fleet
    from prime_tpu.serve.server import InferenceServer

    seed = loadgen_seed_default() if seed is None else seed
    os.makedirs(output_dir, exist_ok=True)
    config = get_config("tiny-test")
    scenario_obj = SCENARIOS[scenario](seed)
    schedule = build_schedule(scenario_obj, vocab=config.vocab_size)
    log(
        f"# loadgen-smoke: scenario {scenario!r} seed {seed} -> "
        f"{len(schedule)} requests, {replicas} replicas"
        + (f", mesh {mesh}" if mesh else "")
    )

    engines: list = []
    servers: list = []
    router = None
    try:
        # sharded replicas get DISJOINT device slices: N engines all built
        # over jax.devices()[:k] would measure device contention (double-
        # subscribed HBM on real chips) and stamp it into the committed
        # MULTICHIP trajectory as a clean multichip number
        mesh_cfg = None
        if mesh:
            from prime_tpu.serve.mesh_config import parse_mesh_spec

            mesh_cfg = parse_mesh_spec(mesh, jax.device_count())
        if mesh_cfg is not None and replicas * mesh_cfg.total_devices > jax.device_count():
            raise ValueError(
                f"mesh {mesh!r} x {replicas} replicas needs "
                f"{replicas * mesh_cfg.total_devices} devices; only "
                f"{jax.device_count()} are available (drop --replicas or "
                "force more with --xla_force_host_platform_device_count)"
            )
        for i in range(replicas):
            params = init_params(jax.random.PRNGKey(i), config, dtype=jnp.float32)
            kw: dict = {"mesh_config": mesh}
            if mesh_cfg is not None and replicas > 1:
                # explicit surface: replica i's mesh over its own device
                # slice, params/cache placed by the same one-owner specs the
                # declarative path uses
                from prime_tpu.parallel.sharding import serving_cache_spec, shard_params

                need = mesh_cfg.total_devices
                replica_mesh = mesh_cfg.build(jax.devices()[i * need : (i + 1) * need])
                params = shard_params(params, replica_mesh, config)
                kw = {
                    "mesh": replica_mesh,
                    "cache_spec": serving_cache_spec(config, replica_mesh),
                }
            engine = ContinuousBatchingEngine(
                params, config, pad_id=0, max_slots=4, capacity=128, chunk=4,
                prefix_cache_mb=8, max_queue=16, **kw,
            )
            if mesh_cfg is None and replicas > 1 and engine.mesh_devices > 1:
                # PRIME_SERVE_MESH reached the engines without --mesh: every
                # replica built over the SAME first-k devices — contention,
                # not multichip serving. The explicit flag places disjointly.
                engine.shutdown()
                raise ValueError(
                    "PRIME_SERVE_MESH sharded every replica over the same "
                    "devices; pass --mesh explicitly (or --replicas 1) so "
                    "replicas get disjoint device slices"
                )
            engine.start()
            engines.append(engine)
            servers.append(
                InferenceServer(
                    "loadgen-smoke", EngineBackend(engine, NumericTokenizer()), port=0
                ).start()
            )
        router = serve_fleet(
            [srv.url for srv in servers], poll_interval=0.2, model_id="loadgen-smoke",
        )
        target = HTTPTarget(
            router.url,
            scrape_urls={
                "router": router.url,
                **{f"replica{i}": srv.url for i, srv in enumerate(servers)},
            },
            timeout_s=120.0,
        )
        # warm every prompt-length bucket the schedule will hit, per
        # replica: first-compile time belongs to startup, not to the
        # measured window's TTFT histogram bracket — warming one token
        # count would leave the other buckets' compiles inside the window
        # and the percentiles would measure XLA, not serving
        import httpx

        warm_lens = sorted({len(r.prompt_ids) for r in schedule})
        for srv in servers:
            for n in warm_lens:
                httpx.post(
                    f"{srv.url}/v1/chat/completions",
                    json={
                        "messages": [{"role": "user",
                                      "content": " ".join(["7"] * n)}],
                        "max_tokens": 4, "temperature": 0.0,
                    },
                    timeout=120.0,
                ).raise_for_status()

        result = run_schedule(
            schedule, target, scenario=scenario_obj.name, seed=seed,
            time_scale=time_scale, max_workers=8,
        )
        # stamp from the engines' ACTUAL mesh state, not the `mesh` argument:
        # PRIME_SERVE_MESH can shard the engines with mesh=None here, and a
        # sharded run labeled as single-chip would land in the wrong
        # perf-delta trajectory row (the mc-prefix design exists to prevent
        # exactly that cross-backend contamination)
        mesh_axes = engines[0].mesh_axes if engines else {}
        mesh_devices = engines[0].mesh_devices if engines else 1
        sharded = mesh_devices > 1
        mesh_desc = ",".join(f"{k}={v}" for k, v in mesh_axes.items())
        report = build_report(
            [result],
            meta={
                "backend": jax.default_backend(),
                "mode": "cpu-mesh-smoke" if sharded else "cpu-smoke",
                **({"mesh": mesh_axes, "mesh_devices": mesh_devices} if sharded else {}),
            },
        )
        headline = report["headline"]
        log(
            f"# loadgen-smoke: {headline['tok_s']} tok/s over "
            f"{headline['requests']} requests "
            f"(outcomes {dict(result.outcomes)})"
        )

        # observatory leg (docs/observability.md "Observatory"), captured
        # RIGHT after the fleet run while the fast window still covers it:
        # the live view must be well-formed — a valid scale signal, one row
        # per replica — with a NONZERO windowed token rate for the run just
        # driven (the observatory and the SLO report window the same
        # counters; a zero here while the report is nonzero means the
        # sensor layer is blind). The view JSON lands in the artifacts
        # either way, so a CI failure uploads the evidence.
        observatory: dict[str, Any] = {}
        obs_ok = False
        try:
            router.membership.poll_all()  # trailing sample closes the run window
            observatory = httpx.get(
                f"{router.url}/admin/observatory", timeout=10
            ).json()
            fleet_fast = (observatory.get("fleet") or {}).get("fast") or {}
            obs_ok = (
                observatory.get("signal", {}).get("direction")
                in ("up", "down", "hold")
                and isinstance(observatory.get("replicas"), list)
                and len(observatory["replicas"]) == replicas
                and (fleet_fast.get("tok_s") or 0) > 0
            )
            if obs_ok:
                log(
                    f"# loadgen-smoke: observatory signal "
                    f"{observatory['signal']['direction']} — fast-window "
                    f"{fleet_fast.get('tok_s')} tok/s over "
                    f"{fleet_fast.get('span_s')} s"
                )
            else:
                log(
                    "# loadgen-smoke: observatory view malformed or blind: "
                    f"signal={observatory.get('signal')} fast={fleet_fast}"
                )
        except Exception as e:  # noqa: BLE001 — the artifact write below must run
            log(f"# loadgen-smoke: observatory leg failed: {e}")
        with open(os.path.join(output_dir, "observatory.json"), "w") as f:
            json.dump(observatory, f, indent=2)

        # speculative on/off section (spec_friendly scenario, in-process
        # engines — sharded when --mesh is set). Appended to the report's
        # scenario rows WITHOUT touching the headline: the headline gate
        # stays the fleet scenario's, exactly as before.
        spec_record: dict[str, Any] = {}
        try:
            spec_record, spec_rows = _spec_section(
                config,
                lambda: init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32),
                seed=seed, mesh=mesh, log=log,
            )
            report["scenarios"].extend(spec_rows)
        except Exception as e:  # noqa: BLE001 — the headline gate must survive
            spec_record = {"serve_spec_error": f"{type(e).__name__}: {e}"[:200]}
            log(f"# loadgen-smoke: spec section failed: {e}")

        # paged-vs-copy seeding section (longprefix scenario, in-process
        # tiny-test engines): record keys serve_longprefix_* — tok/s and
        # mean hit-seed ms per seeding path. Skipped under --mesh: paged
        # seeding is gated off on sharded engines (the comparison would be
        # copy vs copy).
        longprefix_record: dict[str, Any] = {}
        if not mesh:
            try:
                longprefix_record, longprefix_rows = _longprefix_section(
                    config,
                    lambda: init_params(
                        jax.random.PRNGKey(0), config, dtype=jnp.float32
                    ),
                    seed=seed, mesh=None, log=log,
                )
                report["scenarios"].extend(longprefix_rows)
            except Exception as e:  # noqa: BLE001 — the headline gate must survive
                longprefix_record = {
                    "serve_longprefix_error": f"{type(e).__name__}: {e}"[:200]
                }
                log(f"# loadgen-smoke: longprefix section failed: {e}")

        # autotune round-trip leg: dry-run sweep + artifact save/load
        # through the production resolution path (record keys autotune_*)
        autotune_record: dict[str, Any] = {}
        if not mesh:
            try:
                autotune_record = _autotune_section(log=log)
            except Exception as e:  # noqa: BLE001 — the headline gate must survive
                autotune_record = {
                    "autotune_error": f"{type(e).__name__}: {e}"[:200]
                }
                log(f"# loadgen-smoke: autotune section failed: {e}")

        # batched multi-LoRA section (mixed 3-adapter traffic through one
        # engine vs the same schedule base-only): record keys
        # serve_multilora_tok_s / _base_tok_s / _ratio / _fairness, rows
        # appended WITHOUT touching the headline gate — like the spec
        # section. Runs at debug-128m scale (see _multilora_section's scale
        # note: at tiny-test the gathered-delta op count, not the
        # architecture, is what a CPU ratio measures) and skips under --mesh
        # like the disagg section (its extra engines would contend for the
        # forced device set).
        multilora_record: dict[str, Any] = {}
        if not mesh:
            try:
                ml_config = get_config("debug-128m")
                multilora_record, multilora_rows = _multilora_section(
                    ml_config,
                    lambda: init_params(
                        jax.random.PRNGKey(0), ml_config, dtype=jnp.float32
                    ),
                    seed=seed, mesh=None, log=log,
                )
                report["scenarios"].extend(multilora_rows)
            except Exception as e:  # noqa: BLE001 — the headline gate must survive
                multilora_record = {
                    "serve_multilora_error": f"{type(e).__name__}: {e}"[:200]
                }
                log(f"# loadgen-smoke: multilora section failed: {e}")

        # elastic fleet section (the autoscaler's live 1→N→1 proof: real
        # engines spawned and drained by the actuator under a sustained
        # rate_storm; record keys serve_elastic_*). Runs at tiny-test scale
        # — the leg measures the control loop, not matmuls — and skips
        # under --mesh like the sections below (spawned replicas would
        # contend for the forced device set).
        elastic_record: dict[str, Any] = {}
        if not mesh:
            try:
                elastic_record, elastic_rows = _elastic_section(
                    config,
                    lambda: init_params(
                        jax.random.PRNGKey(3), config, dtype=jnp.float32
                    ),
                    seed=seed, log=log,
                )
                report["scenarios"].extend(elastic_rows)
            except Exception as e:  # noqa: BLE001 — the headline gate must survive
                elastic_record = {
                    "serve_elastic_error": f"{type(e).__name__}: {e}"[:200]
                }
                log(f"# loadgen-smoke: elastic section failed: {e}")

        # disaggregated prefill/decode section (phase-split vs colocated on
        # the long-prompt-heavy `disagg` scenario, real HTTP fleets both
        # ways). Runs at debug-128m scale, not tiny-test: the migration's
        # fixed per-request cost (three extra HTTP exchanges + the KV ship)
        # amortizes against real prefill compute — at tiny-test scale the
        # overhead is bigger than the prefill it offloads and the comparison
        # measures the harness, not the architecture. Rows append to the
        # report; the headline gate stays the fleet scenario's. Skipped
        # under --mesh: the section's four extra engines would contend for
        # the forced device set.
        disagg_record: dict[str, Any] = {}
        if not mesh:
            try:
                disagg_config = get_config("debug-128m")
                disagg_record, disagg_rows = disagg_comparison(
                    disagg_config,
                    lambda i: init_params(
                        jax.random.PRNGKey(i), disagg_config, dtype=jnp.float32
                    ),
                    seed=seed, model_id="loadgen-smoke", log=log,
                )
                report["scenarios"].extend(disagg_rows)
            except Exception as e:  # noqa: BLE001 — the headline gate must survive
                disagg_record = {
                    "serve_disagg_error": f"{type(e).__name__}: {e}"[:200]
                }
                log(f"# loadgen-smoke: disagg section failed: {e}")

        # sentinel section (clean run quiet / planted env-injected dispatch
        # delay raises exactly one incident with a complete bundle): record
        # keys serve_sentinel_*. Skipped under --mesh like the sections
        # above — its two extra engines would contend for the forced
        # device set.
        sentinel_record: dict[str, Any] = {}
        if not mesh:
            try:
                sentinel_record = _sentinel_section(
                    config,
                    lambda: init_params(
                        jax.random.PRNGKey(0), config, dtype=jnp.float32
                    ),
                    seed=seed, log=log,
                )
            except Exception as e:  # noqa: BLE001 — the headline gate must survive
                sentinel_record = {
                    "serve_sentinel_error": f"{type(e).__name__}: {e}"[:200]
                }
                log(f"# loadgen-smoke: sentinel section failed: {e}")

        # exposition lint, pinned to the documented catalog: every /metrics
        # surface the smoke stood up must be well-formed AND in-contract
        doc_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            "docs", "observability.md",
        )
        catalog = None
        if os.path.exists(doc_path):
            with open(doc_path) as f:
                catalog = load_metrics_catalog(f.read())
        lint: dict[str, list[str]] = {}
        for label, text in target.expositions().items():
            problems = lint_prometheus_text(text, catalog=catalog)
            if problems:
                lint[label] = problems
                log(f"# loadgen-smoke: exposition lint FAILED for {label}:")
                for p in problems:
                    log(f"#   {p}")

        metric = (
            f"serve_sharded_tok_s (tiny-test, {replicas}x sharded replica "
            f"over mesh {mesh_desc}, scenario {scenario_obj.name})"
            if sharded
            else f"loadgen_smoke_tok_s (tiny-test, {replicas}-replica fleet, "
                 f"scenario {scenario_obj.name})"
        )
        record = {
            "schema": 2,
            "metric": metric,
            "value": headline["tok_s"],
            "unit": "tokens/s",
            "vs_baseline": 0.0,
            "backend": jax.default_backend(),
            **({"mesh": mesh_axes, "mesh_devices": mesh_devices} if sharded else {}),
            **spec_record,
            **longprefix_record,
            **autotune_record,
            **multilora_record,
            **elastic_record,
            **disagg_record,
            **sentinel_record,
            "loadgen": report,
        }
        with open(os.path.join(output_dir, "slo_report.json"), "w") as f:
            json.dump(report, f, indent=2)
        with open(os.path.join(output_dir, "bench_record.json"), "w") as f:
            json.dump(record, f, indent=2)
        with open(os.path.join(output_dir, "flight.json"), "w") as f:
            json.dump(result.flight, f, indent=2)
        ok = headline["tok_s"] > 0 and not lint and obs_ok
        log(
            f"# loadgen-smoke: {'OK' if ok else 'FAILED'} — artifacts in "
            f"{output_dir}"
        )
        return {
            "ok": ok,
            "report": report,
            "record": record,
            "lint": lint,
            "observatory": observatory,
        }
    finally:
        if router is not None:
            router.stop()
        for srv in servers:
            srv.stop()  # also shuts down the backing engine
        for engine in engines[len(servers):]:
            engine.shutdown()


def run_disagg_mesh_round(
    output_dir: str,
    *,
    seed: int | None = None,
    log=print,
) -> dict[str, Any]:
    """The MULTICHIP disaggregation round (the open measurement from the
    disagg PR): :func:`disagg_comparison` with ``mesh_roles=True`` — every
    replica a sharded engine over a disjoint half of the forced CPU device
    set, laid out by its ``role:prefill`` / ``role:decode`` preset — at
    debug-128m scale. Writes ``bench_record.json`` in the MULTICHIP record
    shape (mesh-stamped schema 2, ``serve_disagg_*`` keys plus the SLO
    scenario rows under ``loadgen``) for committing as
    ``MULTICHIP_loadgen_cpu_rNN.json``. Run under
    ``XLA_FLAGS=--xla_force_host_platform_device_count=4``."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    import jax.numpy as jnp

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params

    seed = loadgen_seed_default() if seed is None else seed
    os.makedirs(output_dir, exist_ok=True)
    config = get_config("debug-128m")
    record, rows = disagg_comparison(
        config,
        lambda i: init_params(jax.random.PRNGKey(i), config, dtype=jnp.float32),
        seed=seed, model_id="disagg-mesh", mesh_roles=True, log=log,
    )
    total = sum(r.get("tokens", 0) for r in rows)
    duration = sum(r.get("duration_s") or 0.0 for r in rows)
    report = {
        "slo_schema": 1,
        "scenarios": rows,
        "headline": {
            "tok_s": round(total / duration, 2) if duration else 0.0,
            "tokens": int(total),
            "duration_s": round(duration, 6),
            "requests": sum(r.get("requests", 0) for r in rows),
            "rejected_429": sum(r.get("rejected_429", 0) for r in rows),
        },
    }
    out = {
        "schema": 2,
        "metric": (
            "serve_disagg_mesh_tok_s (debug-128m, role-preset meshes — "
            "prefill tp-absorbing / decode dp-absorbing — over "
            f"{record.get('serve_disagg_mesh_devices')} forced CPU devices)"
        ),
        "value": record.get("serve_disagg_tok_s", 0.0),
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "backend": jax.default_backend(),
        "mesh": record.get("serve_disagg_mesh_roles", {}),
        "mesh_devices": record.get("serve_disagg_mesh_devices", 0),
        **record,
        "loadgen": report,
    }
    with open(os.path.join(output_dir, "bench_record.json"), "w") as f:
        json.dump(out, f, indent=2)
    ok = bool(record.get("serve_disagg_tok_s", 0.0)) and not record.get(
        "serve_disagg_error"
    )
    log(f"# disagg-mesh round: {'OK' if ok else 'FAILED'} — record in {output_dir}")
    return {"ok": ok, "record": out}
