"""Backend adapters: one schedule, any serving surface.

The runner drives a schedule against a :class:`Target`:

- :class:`EngineTarget` — an in-process ``ContinuousBatchingEngine`` driven
  synchronously (the runner owns ``tick()``), the deterministic mode tests
  and bench sections use. Submissions, cancels, and queue-full rejections
  go through the exact engine API the server uses.
- :class:`HTTPTarget` — any OpenAI-compatible URL: a single
  ``InferenceServer``, a ``prime serve fleet`` router, or something else
  entirely. Requests ride real HTTP (SSE streams for cancellable requests,
  429s surfaced as rejections, never silently retried — loadgen measures
  the admission gate, it does not mask it), and observability is *scraped*:
  registry snapshots from ``/metrics?format=registry``, flight-recorder
  timelines from ``/debug/requests``, exposition text for linting from
  ``/metrics?format=prometheus``.

Both expose the same read surface — ``snapshots()`` (component name →
``Registry.snapshot()`` dict) and ``flight_summaries()`` — which is all the
report builder needs; tok/s, TTFT/TPOT percentiles, hit and overlap ratios
all come from snapshot deltas, not from anything the client timed.
"""

from __future__ import annotations

from typing import Any

from prime_tpu.loadgen.scenario import PlannedRequest

# Client-observed outcome labels (the report's `requests` section).
OUTCOME_COMPLETED = "completed"
OUTCOME_CANCELLED = "cancelled"
OUTCOME_REJECTED = "rejected_429"
OUTCOME_FAILED = "failed"


class NumericTokenizer:
    """Whitespace-number tokenizer: HTTP text round-trips to the same int
    ids loadgen feeds engines directly, so an HTTP-driven run and an
    in-process run exercise identical prompt blocks (non-numeric template
    words — role markers from the chat template — hash to stable small
    ids). Shared by bench.py's fleet section and the loadgen smoke."""

    def encode(self, text: str, add_special_tokens: bool = True) -> list[int]:
        return [
            int(tok) if tok.isdigit() else (sum(tok.encode()) % 97) + 3
            for tok in text.split()
        ]

    def decode(self, ids: list[int]) -> str:
        return " ".join(str(i) for i in ids)


def prompt_text(prompt_ids: tuple[int, ...]) -> str:
    """A prompt's on-the-wire form for :class:`NumericTokenizer` backends."""
    return " ".join(str(i) for i in prompt_ids)


class EngineTarget:
    """In-process engine, synchronously driven. The runner calls
    ``submit``/``tick``; this adapter owns nothing but the translation."""

    name = "engine"

    def __init__(self, engine: Any) -> None:
        self.engine = engine

    def submit(self, planned: PlannedRequest):
        """Submit one planned request; returns the live EngineRequest.
        Raises QueueFullError when the admission gate rejects (the runner
        records the rejection — deliberately no retry). A planned adapter
        rides through to the engine's multi-LoRA bank (``"base"``/None both
        mean the base model, matching the HTTP target's `model` field)."""
        kwargs = {}
        if planned.adapter and planned.adapter != "base":
            kwargs["adapter"] = planned.adapter
        return self.engine.submit(
            list(planned.prompt_ids), max_new_tokens=planned.max_new_tokens,
            **kwargs,
        )

    def tick(self) -> None:
        self.engine.tick()

    def snapshots(self) -> dict[str, dict]:
        self.engine.stats()  # refresh point-in-time gauges before the read
        return {"engine": self.engine.registry.snapshot()}

    def flight_summaries(self, limit: int = 1000) -> dict:
        return self.engine.flight.summaries(limit=limit)


class HTTPTarget:
    """An OpenAI-compatible chat endpoint plus the metrics surfaces to
    scrape. ``url`` takes the traffic; ``scrape_urls`` (default: just
    ``url``) are polled for registry snapshots and flight timelines —
    pass router + replica URLs to capture a whole fleet's view."""

    name = "http"

    def __init__(
        self,
        url: str,
        *,
        scrape_urls: dict[str, str] | None = None,
        model: str | None = None,
        timeout_s: float = 240.0,
        admin_token: str | None = None,
    ) -> None:
        import httpx

        self.url = url.rstrip("/")
        self.scrape_urls = {
            label: u.rstrip("/") for label, u in (scrape_urls or {"target": url}).items()
        }
        self.model = model
        self.timeout_s = timeout_s
        self._headers = (
            {"Authorization": f"Bearer {admin_token}"} if admin_token else {}
        )
        self._httpx = httpx

    # -- traffic ---------------------------------------------------------------

    def _body(self, planned: PlannedRequest, stream: bool) -> dict:
        body: dict = {
            "messages": [{"role": "user", "content": prompt_text(planned.prompt_ids)}],
            "max_tokens": planned.max_new_tokens,
            "temperature": 0.0,
        }
        model = planned.adapter or self.model
        if model:
            body["model"] = model
        if stream:
            body["stream"] = True
        return body

    def perform(self, planned: PlannedRequest, cancel_at_s: float | None) -> tuple[str, int]:
        """Blocking: run one request to completion, cancellation, or
        rejection. Returns ``(outcome, completion_tokens)``. ``cancel_at_s``
        is an absolute ``time.monotonic()`` deadline (already time-scaled by
        the runner); cancellable requests stream so closing the response
        mid-decode is a real client abandon, not a post-hoc label."""
        import time

        chat = f"{self.url}/v1/chat/completions"
        try:
            if cancel_at_s is None:
                response = self._httpx.post(
                    chat, json=self._body(planned, stream=False), timeout=self.timeout_s
                )
                if response.status_code == 429:
                    return OUTCOME_REJECTED, 0
                if response.status_code != 200:
                    return OUTCOME_FAILED, 0
                usage = response.json().get("usage", {})
                return OUTCOME_COMPLETED, int(usage.get("completion_tokens", 0))
            # cancel path: stream and abandon at the deadline
            deltas = 0
            with self._httpx.stream(
                "POST", chat, json=self._body(planned, stream=True),
                timeout=self.timeout_s,
            ) as response:
                if response.status_code == 429:
                    return OUTCOME_REJECTED, 0
                if response.status_code != 200:
                    return OUTCOME_FAILED, 0
                for line in response.iter_lines():
                    if time.monotonic() >= cancel_at_s:
                        response.close()
                        return OUTCOME_CANCELLED, deltas
                    if line.startswith("data: ") and '"content"' in line:
                        deltas += 1
            return OUTCOME_COMPLETED, deltas
        except self._httpx.HTTPError:
            return OUTCOME_FAILED, 0

    # -- observability scrape --------------------------------------------------

    def snapshots(self) -> dict[str, dict]:
        """Registry snapshots from every scrape URL, flattened to
        ``label.section`` keys (a server exposes ``server``+``engine``
        sections, a router exposes ``router``)."""
        out: dict[str, dict] = {}
        for label, base in self.scrape_urls.items():
            response = self._httpx.get(
                f"{base}/metrics", params={"format": "registry"}, timeout=10.0
            )
            response.raise_for_status()
            for section, snapshot in response.json().items():
                out[f"{label}.{section}"] = snapshot
        return out

    def flight_summaries(self, limit: int = 1000) -> dict:
        """The traffic URL's flight-recorder view (inflight + recent
        summaries) — the replay seed. Routers merge their hop with the
        serving replica's, so one scrape covers the fleet path."""
        response = self._httpx.get(
            f"{self.url}/debug/requests",
            params={"limit": limit},
            headers=self._headers,
            timeout=10.0,
        )
        response.raise_for_status()
        return response.json()

    def expositions(self) -> dict[str, str]:
        """Prometheus text from every scrape URL, for lint."""
        out = {}
        for label, base in self.scrape_urls.items():
            response = self._httpx.get(
                f"{base}/metrics", params={"format": "prometheus"}, timeout=10.0
            )
            response.raise_for_status()
            out[label] = response.text
        return out
