"""Drive a schedule against a target and capture the evidence.

The runner is deliberately thin: release each :class:`PlannedRequest` at its
(time-scaled) arrival offset, apply its cancel point, record the
client-observed outcome — and bracket the whole run with registry snapshots
and a flight-recorder scrape. Everything quantitative in the SLO report
comes from those brackets (:mod:`prime_tpu.loadgen.report`), not from
anything timed here; the only client-side numbers kept are outcome counts,
which no server-side registry can know (a rejected request never reaches
an engine histogram).

Two drive modes, chosen by the target:

- ``EngineTarget`` → single-threaded tick loop (the runner owns the engine
  clock), fully deterministic given a schedule — the mode tests and bench
  sections use.
- ``HTTPTarget`` → a worker pool issuing real HTTP at the scheduled
  arrival times; server-side interleaving varies run to run, which is
  precisely why the report reads the registry.
"""

from __future__ import annotations

import time
from collections import Counter
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from prime_tpu.loadgen.backends import (
    OUTCOME_CANCELLED,
    OUTCOME_COMPLETED,
    OUTCOME_FAILED,
    OUTCOME_REJECTED,
    EngineTarget,
    HTTPTarget,
)
from prime_tpu.loadgen.scenario import PlannedRequest, schedule_digest
from prime_tpu.serve.errors import DrainingError, QueueFullError


@dataclass
class RunResult:
    """One scenario run's raw evidence, handed to ``build_report``."""

    scenario: str
    seed: int
    digest: str
    requests: int
    outcomes: Counter = field(default_factory=Counter)
    client_tokens: int = 0
    before: dict[str, dict] = field(default_factory=dict)  # component -> snapshot
    after: dict[str, dict] = field(default_factory=dict)
    flight: dict = field(default_factory=dict)
    time_scale: float = 1.0
    # the engine drive hit deadline_s and abandoned work: the run's numbers
    # cover a TRUNCATED window (scenario_row surfaces this as a warning)
    timed_out: bool = False


def run_schedule(
    schedule: list[PlannedRequest],
    target,
    *,
    scenario: str = "adhoc",
    seed: int = 0,
    time_scale: float = 1.0,
    max_workers: int = 8,
    deadline_s: float = 600.0,
) -> RunResult:
    """Run ``schedule`` against ``target`` and return the bracketed
    evidence. ``time_scale`` multiplies every arrival/cancel offset (0 =
    fire everything immediately); outcomes are counted client-side, all
    latency/throughput evidence is the before/after snapshot pair.

    ``time_scale`` compresses the ARRIVAL axis only; a request's cancel
    DELAY (``cancel_after_s − arrival_s``, the client's patience) stays
    unscaled — otherwise ``time_scale=0`` would degrade every cancellable
    request to cancel-before-first-token and the run would measure an
    all-cancelled no-op workload.

    ``deadline_s`` is a whole-run safety net for the synchronous engine
    drive: past it, live work is cancelled, the remainder counts under the
    ``timeout`` outcome, and the result is flagged ``timed_out`` so the
    report marks its window as truncated instead of publishing a
    plausible-looking partial number. HTTP drives are bounded per-request
    by ``HTTPTarget.timeout_s`` instead — a worker pool blocked on a live
    upstream has no safe midpoint to abandon from."""
    result = RunResult(
        scenario=scenario,
        seed=seed,
        digest=schedule_digest(schedule),
        requests=len(schedule),
        time_scale=time_scale,
    )
    result.before = target.snapshots()
    if isinstance(target, EngineTarget):
        _drive_engine(schedule, target, result, time_scale, deadline_s)
    else:
        _drive_http(schedule, target, result, time_scale, max_workers)
    result.after = target.snapshots()
    try:
        result.flight = target.flight_summaries(limit=max(len(schedule), 50))
    except Exception as e:  # noqa: BLE001 — a missing debug surface must not void the run
        result.flight = {"error": f"{type(e).__name__}: {e}"[:200]}
    return result


def _cancel_offset(planned: PlannedRequest, time_scale: float) -> float:
    """Wall offset of a cancel point: scaled arrival + UNSCALED patience
    (see run_schedule docstring)."""
    return planned.arrival_s * time_scale + (
        planned.cancel_after_s - planned.arrival_s
    )


def _drive_engine(
    schedule: list[PlannedRequest],
    target: EngineTarget,
    result: RunResult,
    time_scale: float,
    deadline_s: float,
) -> None:
    pending = sorted(schedule, key=lambda r: (r.arrival_s, r.index))
    live: list[tuple[PlannedRequest, object]] = []
    t0 = time.monotonic()
    deadline = t0 + deadline_s
    while pending or live:
        now = time.monotonic() - t0
        while pending and pending[0].arrival_s * time_scale <= now:
            planned = pending.pop(0)
            try:
                live.append((planned, target.submit(planned)))
            except QueueFullError:
                result.outcomes[OUTCOME_REJECTED] += 1
            except (DrainingError, ValueError):
                result.outcomes[OUTCOME_FAILED] += 1
        for planned, req in live:
            if (
                planned.cancel_after_s is not None
                and not req.done
                and not req.cancelled
                and now >= _cancel_offset(planned, time_scale)
            ):
                req.cancel()
        target.tick()
        still_live = []
        for planned, req in live:
            if req.done:
                result.client_tokens += req.emitted
                if req.error:
                    result.outcomes[OUTCOME_FAILED] += 1
                elif req.cancelled:
                    result.outcomes[OUTCOME_CANCELLED] += 1
                else:
                    result.outcomes[OUTCOME_COMPLETED] += 1
            else:
                still_live.append((planned, req))
        live = still_live
        if not live and pending:
            # idle gap before the next arrival: sleep it off instead of
            # spinning ticks against an empty engine
            gap = pending[0].arrival_s * time_scale - (time.monotonic() - t0)
            if gap > 0:
                time.sleep(min(gap, 0.05))
        if time.monotonic() > deadline:
            for _planned, req in live:
                req.cancel()
            result.outcomes["timeout"] += len(live) + len(pending)
            result.timed_out = True
            break
    target.tick()  # drain the overlap pipeline's lookahead chunk


def _drive_http(
    schedule: list[PlannedRequest],
    target: HTTPTarget,
    result: RunResult,
    time_scale: float,
    max_workers: int,
) -> None:
    t0 = time.monotonic()

    def issue(planned: PlannedRequest) -> tuple[str, int]:
        cancel_at = (
            t0 + _cancel_offset(planned, time_scale)
            if planned.cancel_after_s is not None
            else None
        )
        return target.perform(planned, cancel_at)

    ordered = sorted(schedule, key=lambda r: (r.arrival_s, r.index))
    with ThreadPoolExecutor(max_workers=max_workers) as pool:
        futures = []
        for planned in ordered:
            delay = t0 + planned.arrival_s * time_scale - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            futures.append(pool.submit(issue, planned))
        for future in futures:
            outcome, tokens = future.result()
            result.outcomes[outcome] += 1
            result.client_tokens += tokens
