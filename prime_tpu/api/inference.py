"""Inference API client (reference: prime_cli/api/inference.py:31-165).

OpenAI-compatible surface against ``config.inference_url``: list/retrieve
models, chat completions with SSE streaming. Long read timeout (600 s) for
generation; team rides the X-Prime-Team-ID header.
"""

from __future__ import annotations

import json
from typing import Any, Iterator

import httpx

from prime_tpu.core.client import APIClient
from prime_tpu.core.config import Config

INFERENCE_TIMEOUT = httpx.Timeout(600.0, connect=10.0, write=60.0)


class InferenceClient:
    def __init__(
        self,
        config: Config | None = None,
        transport: httpx.BaseTransport | None = None,
        base_url: str | None = None,
        timeout: httpx.Timeout | None = None,
    ) -> None:
        config = config or Config()
        # inference_url already includes its path prefix (e.g. /api/v1);
        # base_url overrides it for endpoint-alias targets, timeout for
        # fast-fail preflight probes
        self.api = APIClient(
            config=config,
            base_url=base_url or config.inference_url,
            api_prefix="",
            timeout=timeout or INFERENCE_TIMEOUT,
            transport=transport,
        )

    def list_models(self) -> list[dict[str, Any]]:
        data = self.api.get("/models")
        return data.get("data", []) if isinstance(data, dict) else data

    def retrieve_model(self, model_id: str) -> dict[str, Any]:
        return self.api.get(f"/models/{model_id}")

    def chat_completion(
        self,
        model: str,
        messages: list[dict[str, str]],
        max_tokens: int | None = None,
        temperature: float | None = None,
        job_id: str | None = None,
    ) -> dict[str, Any]:
        payload: dict[str, Any] = {"model": model, "messages": messages}
        if max_tokens is not None:
            payload["max_tokens"] = max_tokens
        if temperature is not None:
            payload["temperature"] = temperature
        headers = {"X-PI-Job-Id": job_id} if job_id else None
        return self.api.post("/chat/completions", json=payload, headers=headers)

    def chat_completion_stream(
        self,
        model: str,
        messages: list[dict[str, str]],
        max_tokens: int | None = None,
        temperature: float | None = None,
    ) -> Iterator[dict[str, Any]]:
        """Yield SSE delta chunks (parsed JSON) until [DONE]."""
        payload: dict[str, Any] = {"model": model, "messages": messages, "stream": True}
        if max_tokens is not None:
            payload["max_tokens"] = max_tokens
        if temperature is not None:
            payload["temperature"] = temperature
        for line in self.api.stream_lines("POST", "/chat/completions", json=payload):
            line = line.strip()
            if not line.startswith("data:"):
                continue
            data = line[len("data:"):].strip()
            if data == "[DONE]":
                return
            yield json.loads(data)
