"""Inference API client (reference: prime_cli/api/inference.py:31-165).

OpenAI-compatible surface against ``config.inference_url``: list/retrieve
models, chat completions with SSE streaming. Long read timeout (600 s) for
generation; team rides the X-Prime-Team-ID header.

Backpressure-aware: a serving stack with admission control (the engine's
bounded queue, the fleet router's admission gate — docs/architecture.md
"Serve fleet") answers 429 with a Retry-After when saturated. Chat calls
honor that header with bounded retries (``max_429_retries``, sleep capped at
``RETRY_AFTER_CAP``), reusing the RateLimitError plumbing in core/client.py
— SDK callers ride out transient saturation instead of surfacing it.
Streaming retries only before the first delta; a stream that already yielded
tokens is never silently replayed.
"""

from __future__ import annotations

import itertools
import json
import time
from typing import Any, Iterator

import httpx

from prime_tpu.core.client import APIClient
from prime_tpu.core.config import Config
from prime_tpu.core.exceptions import RateLimitError
from prime_tpu.obs.trace import TRACEPARENT_HEADER, TRACER, new_traceparent

INFERENCE_TIMEOUT = httpx.Timeout(600.0, connect=10.0, write=60.0)
# Retry-After values above this are "come back much later", not "ride it
# out": sleeping minutes inside a library call would look like a hang
RETRY_AFTER_CAP = 30.0
DEFAULT_429_RETRIES = 3


class InferenceClient:
    def __init__(
        self,
        config: Config | None = None,
        transport: httpx.BaseTransport | None = None,
        base_url: str | None = None,
        timeout: httpx.Timeout | None = None,
        max_429_retries: int = DEFAULT_429_RETRIES,
    ) -> None:
        config = config or Config()
        # inference_url already includes its path prefix (e.g. /api/v1);
        # base_url overrides it for endpoint-alias targets, timeout for
        # fast-fail preflight probes
        self.api = APIClient(
            config=config,
            base_url=base_url or config.inference_url,
            api_prefix="",
            timeout=timeout or INFERENCE_TIMEOUT,
            transport=transport,
        )
        self.max_429_retries = max(0, max_429_retries)

    def _backoff_429(self, exc: RateLimitError, attempt: int) -> None:
        """Sleep out a 429: the server's Retry-After when it sent one
        (capped), else a small attempt-scaled fallback."""
        if exc.retry_after is not None:
            # clamp both ends: a hostile/buggy negative Retry-After must not
            # turn into a time.sleep ValueError
            delay = max(0.0, min(float(exc.retry_after), RETRY_AFTER_CAP))
        else:
            delay = min(0.5 * (2**attempt), RETRY_AFTER_CAP)
        time.sleep(delay)

    def list_models(self) -> list[dict[str, Any]]:
        data = self.api.get("/models")
        return data.get("data", []) if isinstance(data, dict) else data

    def retrieve_model(self, model_id: str) -> dict[str, Any]:
        return self.api.get(f"/models/{model_id}")

    def chat_completion(
        self,
        model: str,
        messages: list[dict[str, str]],
        max_tokens: int | None = None,
        temperature: float | None = None,
        job_id: str | None = None,
    ) -> dict[str, Any]:
        payload: dict[str, Any] = {"model": model, "messages": messages}
        if max_tokens is not None:
            payload["max_tokens"] = max_tokens
        if temperature is not None:
            payload["temperature"] = temperature
        headers = {"X-PI-Job-Id": job_id} if job_id else {}
        # ONE trace for the whole logical call: 429 retries are attempts
        # inside the same request story, so they must share the trace id the
        # server-side spans join (a fresh traceparent per attempt would
        # shatter the waterfall). The span is the outermost client hop.
        with TRACER.span("client.chat", model=model) as span:
            traceparent = span.traceparent()
            if traceparent:
                headers[TRACEPARENT_HEADER] = traceparent
            for attempt in range(self.max_429_retries + 1):
                try:
                    return self.api.post(
                        "/chat/completions", json=payload, headers=headers or None
                    )
                except RateLimitError as e:
                    if attempt == self.max_429_retries:
                        raise
                    self._backoff_429(e, attempt)

    def chat_completion_stream(
        self,
        model: str,
        messages: list[dict[str, str]],
        max_tokens: int | None = None,
        temperature: float | None = None,
    ) -> Iterator[dict[str, Any]]:
        """Yield SSE delta chunks (parsed JSON) until [DONE]. A 429 raised
        while opening the stream (before any delta) retries after its
        Retry-After, like the non-streaming path; once bytes flow, failures
        surface — replaying a half-delivered stream would duplicate output."""
        payload: dict[str, Any] = {"model": model, "messages": messages, "stream": True}
        if max_tokens is not None:
            payload["max_tokens"] = max_tokens
        if temperature is not None:
            payload["temperature"] = temperature
        # streams share one trace across open-stream retries too; no client
        # span wraps the body (it would stay open for the stream's lifetime)
        headers = (
            {TRACEPARENT_HEADER: new_traceparent()} if TRACER.enabled else None
        )
        for attempt in range(self.max_429_retries + 1):
            lines = self.api.stream_lines(
                "POST", "/chat/completions", json=payload, headers=headers
            )
            try:
                # stream_lines raises the mapped status error on first pull
                first = next(lines, None)
            except RateLimitError as e:
                if attempt == self.max_429_retries:
                    raise
                self._backoff_429(e, attempt)
                continue
            break
        if first is None:
            return
        for line in itertools.chain([first], lines):
            line = line.strip()
            if not line.startswith("data:"):
                continue
            data = line[len("data:"):].strip()
            if data == "[DONE]":
                return
            yield json.loads(data)
