"""TPU availability queries.

Capability parity with the reference availability client
(prime_cli/api/availability.py:53-204: paginated GPU/cluster/disk availability,
single- + multi-node merge) re-keyed on TPU slices: an offer is a
(slice, provider, region, pricing, stock) row, single- and multi-host slices
are one namespace (the slice spec itself says whether it spans hosts), and
multi-slice (DCN-pooled) capacity is a first-class field instead of the
reference's separate multi-node endpoint.
"""

from __future__ import annotations

from typing import Any

from pydantic import BaseModel, ConfigDict, Field

from prime_tpu.core.client import APIClient
from prime_tpu.parallel.topology import SliceSpec, parse_slice


class TpuOffer(BaseModel):
    """One rentable TPU slice configuration at one provider/region."""

    model_config = ConfigDict(populate_by_name=True)

    offer_id: str = Field(alias="offerId")
    slice_name: str = Field(alias="sliceName")          # e.g. "v5e-8"
    tpu_type: str = Field(alias="tpuType")              # e.g. "v5e"
    chips: int
    hosts: int
    ici_topology: str = Field(alias="iciTopology")      # e.g. "2x4"
    provider: str
    region: str
    zone: str | None = None
    price_hourly: float = Field(alias="priceHourly")    # USD per slice-hour
    spot: bool = False
    stock_status: str = Field(alias="stockStatus")      # available|low|unavailable
    dcn_pool: str | None = Field(default=None, alias="dcnPool")
    max_slices_in_pool: int = Field(default=1, alias="maxSlicesInPool")
    hbm_gib: int | None = Field(default=None, alias="hbmGib")
    bf16_tflops: float | None = Field(default=None, alias="bf16Tflops")

    @property
    def spec(self) -> SliceSpec:
        return parse_slice(self.slice_name)

    @property
    def price_per_chip_hour(self) -> float:
        return self.price_hourly / max(1, self.chips)


class DiskAvailability(BaseModel):
    model_config = ConfigDict(populate_by_name=True)

    provider: str
    region: str
    disk_type: str = Field(alias="diskType")
    min_size_gib: int = Field(alias="minSizeGib")
    max_size_gib: int = Field(alias="maxSizeGib")
    price_gib_month: float = Field(alias="priceGibMonth")


class AvailabilityClient:
    """Client for /availability/* endpoints."""

    def __init__(self, client: APIClient) -> None:
        self.client = client

    def _fetch_paginated(self, path: str, params: dict[str, Any]) -> list[dict[str, Any]]:
        """Walk offset/limit pages until the backend reports the end.

        Mirrors the reference's pagination walk (api/availability.py:115
        `_fetch_paginaged`).
        """
        rows: list[dict[str, Any]] = []
        offset = 0
        limit = int(params.pop("limit", 100))
        while True:
            page = self.client.get(path, params={**params, "offset": offset, "limit": limit})
            items = page.get("items", []) if isinstance(page, dict) else page
            rows.extend(items)
            total = page.get("total") if isinstance(page, dict) else None
            offset += len(items)
            if not items or (total is not None and offset >= total):
                return rows

    def list_tpus(
        self,
        tpu_type: str | None = None,
        min_chips: int | None = None,
        region: str | None = None,
        provider: str | None = None,
        spot: bool | None = None,
        multi_host: bool | None = None,
    ) -> list[TpuOffer]:
        params: dict[str, Any] = {}
        if tpu_type:
            params["tpu_type"] = tpu_type
        if min_chips:
            params["min_chips"] = min_chips
        if region:
            params["region"] = region
        if provider:
            params["provider"] = provider
        if spot is not None:
            params["spot"] = spot
        offers = [TpuOffer.model_validate(r) for r in self._fetch_paginated("/availability/tpus", params)]
        if multi_host is not None:
            offers = [o for o in offers if (o.hosts > 1) == multi_host]
        return sorted(offers, key=lambda o: (o.tpu_type, o.chips, o.price_hourly))

    def list_tpu_types(self) -> list[dict[str, Any]]:
        """Distinct generations with chip counts/pricing ranges, for the picker."""
        return self.client.get("/availability/tpu-types")

    def list_disks(self, region: str | None = None, provider: str | None = None) -> list[DiskAvailability]:
        params: dict[str, Any] = {}
        if region:
            params["region"] = region
        if provider:
            params["provider"] = provider
        return [
            DiskAvailability.model_validate(r)
            for r in self._fetch_paginated("/availability/disks", params)
        ]
