"""Resource API clients (L2): pydantic models + thin REST wrappers.

One module per backend resource, mirroring the reference's surface
(prime_cli/api/, SURVEY.md §2.2) with TPU slices replacing GPU types.
"""
