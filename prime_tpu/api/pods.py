"""TPU pod (slice VM) lifecycle client.

Capability parity with the reference pods client (prime_cli/api/pods.py:66-240:
CRUD + status + history, team auto-injection, ssh normalization) with the
TPU-native twist: a pod is a **TPU VM slice**. Multi-host slices expose one SSH
endpoint per worker host (`ssh_connections: list[str]` — the reference's
multi-node `ssh_connection: List[str]` pattern, api/pods.py:10
`clean_connection_fields`), and slice/ICI metadata rides on the pod.
"""

from __future__ import annotations

from typing import Any

from pydantic import BaseModel, ConfigDict, Field, field_validator

from prime_tpu.core.client import APIClient


class PodStatus(BaseModel):
    model_config = ConfigDict(populate_by_name=True)

    pod_id: str = Field(alias="podId")
    status: str                                     # PENDING|PROVISIONING|ACTIVE|ERROR|TERMINATED
    ssh_connections: list[str] | None = Field(default=None, alias="sshConnections")
    installation_status: str | None = Field(default=None, alias="installationStatus")
    installation_progress: int | None = Field(default=None, alias="installationProgress")
    installation_failure: str | None = Field(default=None, alias="installationFailure")

    @field_validator("ssh_connections", mode="before")
    @classmethod
    def clean_connections(cls, v: Any) -> Any:
        """Normalize backend quirks: [None]/[""] → None, str → [str]."""
        if v is None:
            return None
        if isinstance(v, str):
            v = [v]
        cleaned = [c for c in v if c]
        return cleaned or None


class Pod(BaseModel):
    model_config = ConfigDict(populate_by_name=True)

    pod_id: str = Field(alias="podId")
    name: str
    status: str
    slice_name: str = Field(alias="sliceName")
    tpu_type: str = Field(alias="tpuType")
    chips: int
    hosts: int
    ici_topology: str = Field(alias="iciTopology")
    provider: str
    region: str
    zone: str | None = None
    runtime_version: str | None = Field(default=None, alias="runtimeVersion")  # TPU VM image
    disk_size_gib: int | None = Field(default=None, alias="diskSizeGib")
    price_hourly: float | None = Field(default=None, alias="priceHourly")
    spot: bool = False
    team_id: str | None = Field(default=None, alias="teamId")
    created_at: str | None = Field(default=None, alias="createdAt")
    ssh_connections: list[str] | None = Field(default=None, alias="sshConnections")
    disk_ids: list[str] = Field(default_factory=list, alias="diskIds")
    dcn_pool: str | None = Field(default=None, alias="dcnPool")

    _clean = field_validator("ssh_connections", mode="before")(PodStatus.clean_connections.__func__)  # type: ignore[arg-type]


class CreatePodRequest(BaseModel):
    model_config = ConfigDict(populate_by_name=True)

    name: str
    offer_id: str | None = Field(default=None, alias="offerId")
    slice_name: str = Field(alias="sliceName")
    provider: str | None = None
    region: str | None = None
    runtime_version: str | None = Field(default=None, alias="runtimeVersion")
    disk_size_gib: int | None = Field(default=None, alias="diskSizeGib")
    spot: bool = False
    team_id: str | None = Field(default=None, alias="teamId")
    env_vars: dict[str, str] = Field(default_factory=dict, alias="envVars")


class PodsClient:
    """Client for /pods endpoints. Injects the configured team automatically."""

    def __init__(self, client: APIClient) -> None:
        self.client = client

    def create(self, request: CreatePodRequest) -> Pod:
        payload = request.model_dump(by_alias=True, exclude_none=True)
        if "teamId" not in payload and self.client.team_id:
            payload["teamId"] = self.client.team_id
        return Pod.model_validate(self.client.post("/pods", json=payload))

    def list(self, limit: int = 100, offset: int = 0) -> list[Pod]:
        data = self.client.get("/pods", params={"limit": limit, "offset": offset})
        items = data.get("items", []) if isinstance(data, dict) else data
        return [Pod.model_validate(p) for p in items]

    def get(self, pod_id: str) -> Pod:
        return Pod.model_validate(self.client.get(f"/pods/{pod_id}"))

    def get_status(self, pod_id: str) -> PodStatus:
        return PodStatus.model_validate(self.client.get(f"/pods/{pod_id}/status"))

    def terminate(self, pod_id: str) -> None:
        self.client.delete(f"/pods/{pod_id}")

    def history(self, limit: int = 100) -> list[Pod]:
        data = self.client.get("/pods/history", params={"limit": limit})
        items = data.get("items", []) if isinstance(data, dict) else data
        return [Pod.model_validate(p) for p in items]
