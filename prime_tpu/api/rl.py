"""Hosted RL/LoRA training client (reference: prime_cli/api/rl.py:11-618).

Surface: trainable models with tiered pricing, run CRUD/stop/restart,
checkpoints, multi-component log retrieval (component / worker_index / env
filters — the TPU equivalent of the reference's pod_index), metrics /
rollouts / progress / distributions. TPU-native: runs land on TPU slices
(``tpu_type`` + ``num_slices``) instead of GPU-type picks.
"""

from __future__ import annotations

from typing import Any

from pydantic import BaseModel, ConfigDict, Field

from prime_tpu.core.client import APIClient


class RLModelPrice(BaseModel):
    model_config = ConfigDict(populate_by_name=True)

    tier: str = "standard"
    train_per_hour: float = Field(default=0.0, alias="trainPerHour")
    inference_per_mtok: float = Field(default=0.0, alias="inferencePerMtok")


class RLModel(BaseModel):
    model_config = ConfigDict(populate_by_name=True)

    model_id: str = Field(alias="modelId")
    name: str
    params_b: float = Field(default=0.0, alias="paramsB")
    prices: list[RLModelPrice] = Field(default_factory=list)
    default_tpu: str | None = Field(default=None, alias="defaultTpu")

    def resolve_price(self, tier: str = "standard") -> RLModelPrice | None:
        for price in self.prices:
            if price.tier == tier:
                return price
        return self.prices[0] if self.prices else None


class RLRun(BaseModel):
    model_config = ConfigDict(populate_by_name=True)

    run_id: str = Field(alias="runId")
    name: str
    model: str
    env: str | None = None
    status: str = "PENDING"          # PENDING|RUNNING|COMPLETED|FAILED|STOPPED
    run_type: str = Field(default="lora", alias="runType")  # lora | full_finetune
    tpu_type: str | None = Field(default=None, alias="tpuType")
    num_slices: int = Field(default=1, alias="numSlices")
    created_at: str | None = Field(default=None, alias="createdAt")
    failure_analysis: str | None = Field(default=None, alias="failureAnalysis")
    progress: dict[str, Any] = Field(default_factory=dict)


class RLCheckpoint(BaseModel):
    model_config = ConfigDict(populate_by_name=True)

    checkpoint_id: str = Field(alias="checkpointId")
    run_id: str = Field(alias="runId")
    step: int = 0
    created_at: str | None = Field(default=None, alias="createdAt")


class RLClient:
    def __init__(self, client: APIClient) -> None:
        self.client = client

    # -- catalog -------------------------------------------------------------

    def list_models(self) -> list[RLModel]:
        data = self.client.get("/rft/models")
        items = data.get("items", []) if isinstance(data, dict) else data
        return [RLModel.model_validate(m) for m in items]

    def list_tpus(self) -> list[dict[str, Any]]:
        return self.client.get("/rft/tpus")

    # -- run lifecycle -------------------------------------------------------

    def create_run(self, payload: dict[str, Any]) -> RLRun:
        return RLRun.model_validate(self.client.post("/rft/runs", json=payload, idempotent_post=True))

    def list_runs(self, limit: int = 50) -> list[RLRun]:
        data = self.client.get("/rft/runs", params={"limit": limit})
        items = data.get("items", []) if isinstance(data, dict) else data
        return [RLRun.model_validate(r) for r in items]

    def get_run(self, run_id: str) -> RLRun:
        return RLRun.model_validate(self.client.get(f"/rft/runs/{run_id}"))

    def stop_run(self, run_id: str) -> RLRun:
        return RLRun.model_validate(self.client.post(f"/rft/runs/{run_id}/stop", idempotent_post=True))

    def restart_run(self, run_id: str) -> RLRun:
        """Restart from the latest checkpoint (reference api/rl.py:365)."""
        return RLRun.model_validate(self.client.post(f"/rft/runs/{run_id}/restart", idempotent_post=True))

    def delete_run(self, run_id: str) -> None:
        self.client.delete(f"/rft/runs/{run_id}")

    # -- observability -------------------------------------------------------

    def get_logs(
        self,
        run_id: str,
        component: str | None = None,
        worker_index: int | None = None,
        env_name: str | None = None,
        since: str | None = None,
        search: str | None = None,
        level: str | None = None,
        limit: int = 200,
    ) -> list[dict[str, Any]]:
        params: dict[str, Any] = {"limit": limit}
        for key, value in (
            ("component", component),
            ("worker_index", worker_index),
            ("env_name", env_name),
            ("since", since),
            ("search", search),
            ("level", level),
        ):
            if value is not None:
                params[key] = value
        data = self.client.get(f"/rft/runs/{run_id}/logs", params=params)
        return data.get("items", []) if isinstance(data, dict) else data

    def components(self, run_id: str) -> list[str]:
        data = self.client.get(f"/rft/runs/{run_id}/components")
        return data.get("items", []) if isinstance(data, dict) else data

    def metrics(self, run_id: str) -> dict[str, Any]:
        return self.client.get(f"/rft/runs/{run_id}/metrics")

    def rollouts(self, run_id: str, limit: int = 20) -> list[dict[str, Any]]:
        data = self.client.get(f"/rft/runs/{run_id}/rollouts", params={"limit": limit})
        return data.get("items", []) if isinstance(data, dict) else data

    def progress(self, run_id: str) -> dict[str, Any]:
        return self.client.get(f"/rft/runs/{run_id}/progress")

    def distributions(self, run_id: str) -> dict[str, Any]:
        return self.client.get(f"/rft/runs/{run_id}/distributions")

    # -- checkpoints ---------------------------------------------------------

    def list_checkpoints(self, run_id: str) -> list[RLCheckpoint]:
        data = self.client.get(f"/rft/runs/{run_id}/checkpoints")
        items = data.get("items", []) if isinstance(data, dict) else data
        return [RLCheckpoint.model_validate(c) for c in items]
