"""Dedicated full-finetune dispatch (reference: prime_cli/api/training.py:33-118).

Full-FT runs ship the WHOLE TOML as opaque config (the training stack owns
the schema); the backend mints a per-run token server-side. The client only
picks the TPU slice shape.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

from prime_tpu.core.client import APIClient
from prime_tpu.utils.compat import tomllib


def build_payload_from_toml(
    toml_path: str | Path,
    env_vars: dict[str, str] | None = None,
    tpu_type: str | None = None,
    num_slices: int | None = None,
) -> dict[str, Any]:
    raw = Path(toml_path).read_text()
    parsed = tomllib.loads(raw)  # validates syntax before shipping
    payload: dict[str, Any] = {
        "name": parsed.get("name") or Path(toml_path).stem,
        "config": raw,
        "envVars": env_vars or {},
    }
    infra = parsed.get("infrastructure", {})
    payload["tpuType"] = tpu_type or infra.get("tpu_type", "v5e-8")
    payload["numSlices"] = num_slices or infra.get("num_slices", 1)
    return payload


class HostedTrainingClient:
    def __init__(self, client: APIClient) -> None:
        self.client = client

    def create_run(self, payload: dict[str, Any]) -> dict[str, Any]:
        return self.client.post("/training/runs", json=payload, idempotent_post=True)

    def get_run(self, run_id: str) -> dict[str, Any]:
        return self.client.get(f"/training/runs/{run_id}")
