"""Persistent disk CRUD client (reference: prime_cli/api/disks.py:19-150)."""

from __future__ import annotations

from pydantic import BaseModel, ConfigDict, Field

from prime_tpu.core.client import APIClient


class Disk(BaseModel):
    model_config = ConfigDict(populate_by_name=True)

    disk_id: str = Field(alias="diskId")
    name: str
    size_gib: int = Field(alias="sizeGib")
    disk_type: str = Field(alias="diskType")
    provider: str
    region: str
    status: str                              # CREATING|READY|ATTACHED|DELETING
    attached_pod_id: str | None = Field(default=None, alias="attachedPodId")
    team_id: str | None = Field(default=None, alias="teamId")
    created_at: str | None = Field(default=None, alias="createdAt")


class CreateDiskRequest(BaseModel):
    model_config = ConfigDict(populate_by_name=True)

    name: str
    size_gib: int = Field(alias="sizeGib")
    disk_type: str = Field(default="hyperdisk-balanced", alias="diskType")
    provider: str | None = None
    region: str | None = None
    team_id: str | None = Field(default=None, alias="teamId")


class DisksClient:
    def __init__(self, client: APIClient) -> None:
        self.client = client

    def create(self, request: CreateDiskRequest) -> Disk:
        payload = request.model_dump(by_alias=True, exclude_none=True)
        if "teamId" not in payload and self.client.team_id:
            payload["teamId"] = self.client.team_id
        return Disk.model_validate(self.client.post("/disks", json=payload))

    def list(self) -> list[Disk]:
        data = self.client.get("/disks")
        items = data.get("items", []) if isinstance(data, dict) else data
        return [Disk.model_validate(d) for d in items]

    def get(self, disk_id: str) -> Disk:
        return Disk.model_validate(self.client.get(f"/disks/{disk_id}"))

    def delete(self, disk_id: str) -> None:
        self.client.delete(f"/disks/{disk_id}")
