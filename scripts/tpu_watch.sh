#!/bin/bash
# TPU reachability watcher: probe the axon backend every ~3 min, log results.
# When the tunnel is up, /tmp/tpu_watch.log shows "UP" lines.
#
# Opportunistic bench (round 5): on the FIRST successful probe, run
# `python bench.py` immediately and commit the captured record as
# BENCH_opportunistic_r05.json plus a BASELINE.md row — the tunnel was down
# for the entire previous builder windows, so a single UP window anywhere in
# the round must yield a durable number even if the end-of-round window is
# down again. Only a NONZERO headline is committed; a 0.0 abort (tunnel
# flapped between probe and bench) leaves no marker so a later UP window
# retries. After a successful capture the watcher keeps logging.
#
# Env overrides (for end-to-end testing of this script):
#   TPU_WATCH_REPO   repo to commit into        (default /root/repo)
#   TPU_WATCH_LOG    log path                   (default /tmp/tpu_watch.log)
#   TPU_WATCH_PROBE  probe command              (default: inline jax matmul)
#   TPU_WATCH_SLEEP  seconds between probes     (default 160)
#
# NOTE: rc must come from `timeout python`, NOT a pipeline tail (a piped rc
# is the last command's — it reported false UPs for a hung backend).
LOG=${TPU_WATCH_LOG:-/tmp/tpu_watch.log}
REPO=${TPU_WATCH_REPO:-/root/repo}
SLEEP=${TPU_WATCH_SLEEP:-160}
OPP="$REPO/BENCH_opportunistic_r05.json"
# startup reconciliation: a crash between writing the marker and the commit
# landing leaves an uncommitted marker that would block every future
# capture — if the marker isn't in the git index, drop it and re-capture
if [ -e "$OPP" ] && ! git -C "$REPO" ls-files --error-unmatch \
    BENCH_opportunistic_r05.json >/dev/null 2>&1; then
  rm -f "$OPP"
fi
probe() {
  if [ -n "$TPU_WATCH_PROBE" ]; then
    timeout 200 bash -c "$TPU_WATCH_PROBE" 2>&1
  else
    timeout 200 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256,256))
print('PROBE_OK', float(jnp.sum(x@x)), jax.devices())
" 2>&1
  fi
}
echo "$(date -u +%H:%M:%S) watcher start" >> "$LOG"
while true; do
  t0=$(date +%s)
  out=$(probe)
  rc=$?
  t1=$(date +%s)
  if [ $rc -eq 0 ] && echo "$out" | grep -q PROBE_OK; then
    echo "$(date -u +%H:%M:%S) UP ($((t1-t0))s): $(echo "$out" | grep PROBE_OK)" >> "$LOG"
    if [ ! -e "$OPP" ] && ! pgrep -f 'bench\.py$' >/dev/null; then
      # (pgrep guard: if the DRIVER's bench is already running, starting ours
      # would sweep-kill it mid-measurement — defer to the next UP probe. The
      # pattern matches any cmdline ENDING in bench.py, the same breadth as
      # bench.py's own sweep signature, so `python3 bench.py` or an absolute
      # path also defers)
      echo "$(date -u +%H:%M:%S) OPPORTUNISTIC BENCH starting" >> "$LOG"
      # PRIME_BENCH_NO_SWEEP: the probe just proved the tunnel UP, and a
      # sweep from here could SIGKILL a concurrently-starting DRIVER bench
      # (the authoritative record); the driver's own sweep may kill THIS
      # bench instead, which is fine — no JSON lands, so a later UP window
      # retries. 45 min cap covers all sections.
      (cd "$REPO" && PRIME_BENCH_NO_SWEEP=1 timeout 2700 python bench.py \
        > /tmp/bench_opp.out 2> /tmp/bench_opp.err)
      brc=$?
      # last JSON line wins (same contract as the driver); validate in a
      # TEMP file first — the marker only appears once a real number exists,
      # so a kill mid-capture can't strand a marker that blocks retries
      TMP=/tmp/bench_opp_record.json
      grep '^{' /tmp/bench_opp.out | tail -1 > "$TMP"
      val=$(python -c "import json;print(json.load(open('$TMP'))['value'])" 2>/dev/null)
      # commit only a real measurement: a 0.0 abort means the tunnel flapped
      # between the probe and the bench — retry on the next UP window
      if [ -n "$val" ] && python -c "exit(0 if float('$val') > 0 else 1)" 2>/dev/null; then
        cp "$TMP" "$OPP"
        {
          echo ""
          echo "### Opportunistic capture $(date -u +%Y-%m-%dT%H:%M:%SZ) (round 5 watcher)"
          echo ""
          echo "Tunnel-UP window caught by scripts/tpu_watch.sh; full record in"
          echo "\`BENCH_opportunistic_r05.json\` (headline decode: ${val} tok/s)."
        } >> "$REPO/BASELINE.md"
        # pathspec after `--` restricts the commit to these two files even
        # if the operator has unrelated changes staged in the index
        if (cd "$REPO" && git add BENCH_opportunistic_r05.json BASELINE.md \
          && git commit -q -m "Capture opportunistic TPU bench during UP window (headline ${val} tok/s)" \
               -- BENCH_opportunistic_r05.json BASELINE.md); then
          echo "$(date -u +%H:%M:%S) OPPORTUNISTIC BENCH done rc=$brc value=$val (committed)" >> "$LOG"
        else
          # commit failed (index.lock, hook, ...): drop the marker so the
          # next UP window re-captures; the duplicate BASELINE.md row a
          # retry appends is timestamped and harmless
          rm -f "$OPP"
          echo "$(date -u +%H:%M:%S) OPPORTUNISTIC BENCH value=$val but git commit FAILED (will retry)" >> "$LOG"
        fi
      else
        echo "$(date -u +%H:%M:%S) OPPORTUNISTIC BENCH no usable number rc=$brc value='$val' (will retry; see /tmp/bench_opp.err)" >> "$LOG"
      fi
    fi
  else
    echo "$(date -u +%H:%M:%S) DOWN rc=$rc ($((t1-t0))s)" >> "$LOG"
  fi
  sleep "$SLEEP"
done
