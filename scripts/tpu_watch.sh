#!/bin/bash
# TPU reachability watcher: probe the axon backend every ~3 min, log results.
# When the tunnel is up, /tmp/tpu_watch.log shows "UP" lines — bench then.
# NOTE: rc must come from `timeout python`, NOT a pipeline tail (a piped rc
# is the last command's — it reported false UPs for a hung backend).
LOG=/tmp/tpu_watch.log
echo "$(date -u +%H:%M:%S) watcher start" >> "$LOG"
while true; do
  t0=$(date +%s)
  out=$(timeout 200 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256,256))
print('PROBE_OK', float(jnp.sum(x@x)), jax.devices())
" 2>&1)
  rc=$?
  t1=$(date +%s)
  if [ $rc -eq 0 ] && echo "$out" | grep -q PROBE_OK; then
    echo "$(date -u +%H:%M:%S) UP ($((t1-t0))s): $(echo "$out" | grep PROBE_OK)" >> "$LOG"
  else
    echo "$(date -u +%H:%M:%S) DOWN rc=$rc ($((t1-t0))s)" >> "$LOG"
  fi
  sleep 160
done
