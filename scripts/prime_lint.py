#!/usr/bin/env python3
"""Thin launcher for the prime-lint invariant suite.

Equivalent to ``python -m prime_tpu.analysis``; exists so the repo's
scripts/ directory has one obvious entry point (and so the suite runs from
a checkout without an installed wheel: the repo root is prepended to
sys.path). See docs/analysis.md for the rule catalog.
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from prime_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
