"""Phase-level profile of the continuous-batching engine's bench scenario.

Answers ONE question: where does the serve bench's wall-clock go on the real
chip — admissions (prefill dispatches), decode chunks, or mid-run XLA
compiles? The serve roofline in bench.py says ~2% of HBM peak, which means
the engine is host/dispatch-bound there, not bandwidth-bound; this script
attributes the time so the fix targets the right layer.

Usage: python scripts/serve_profile.py  (single real chip; ~2 min)
"""

from __future__ import annotations

import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from prime_tpu.models import get_config
from prime_tpu.models.llama import init_params
from prime_tpu.serve.engine import ContinuousBatchingEngine

TIMES: dict[str, float] = defaultdict(float)
COUNTS: dict[str, int] = defaultdict(int)


def _wrap(obj, name: str) -> None:
    """Time a method into TIMES[name], EXCLUDING any XLA-compile seconds that
    fire inside it (they land in TIMES['xla_compile'] via the compiler spy) —
    the report's buckets must be disjoint or mid-run compiles get attributed
    to the phase they happened to fire in."""
    fn = getattr(obj, name)

    def timed(*a, **k):
        compile_before = TIMES["xla_compile"]
        t0 = time.perf_counter()
        out = fn(*a, **k)
        elapsed = time.perf_counter() - t0
        TIMES[name] += elapsed - (TIMES["xla_compile"] - compile_before)
        COUNTS[name] += 1
        return out

    setattr(obj, name, timed)


def main() -> None:
    # the scenario comes from bench.py so this profiles EXACTLY the workload
    # the bench's serve section measures
    import bench

    config = get_config("llama3.2-1b")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.bfloat16)
    req_new = bench.SERVE_NEW
    prompts = bench.serve_prompts_for(config)
    engine = ContinuousBatchingEngine(
        params, config, pad_id=0, max_slots=bench.SERVE_SLOTS,
        capacity=bench.SERVE_CAPACITY, chunk=bench.SERVE_CHUNK,
    )
    # count XLA compiles (remote compiles over the tunnel cost seconds each)
    import jax._src.compiler as _c

    cname = (
        "backend_compile_and_load"
        if hasattr(_c, "backend_compile_and_load")
        else "backend_compile"
    )
    real_compile = getattr(_c, cname)

    def spy(*a, **k):
        t0 = time.perf_counter()
        out = real_compile(*a, **k)
        TIMES["xla_compile"] += time.perf_counter() - t0
        COUNTS["xla_compile"] += 1
        return out

    setattr(_c, cname, spy)

    _wrap(engine, "_prefill")
    _wrap(engine, "_decode_chunk")
    for phase in ("warm1", "warm2", "measured"):
        TIMES.clear()
        COUNTS.clear()
        t0 = time.perf_counter()
        if phase.startswith("warm"):
            reqs = [engine.submit(prompts[0], max_new_tokens=req_new)]
        else:
            reqs = [engine.submit(ids, max_new_tokens=req_new) for ids in prompts]
        while not all(r.done for r in reqs):
            engine.tick()
        elapsed = time.perf_counter() - t0
        total = sum(len(r.all_tokens(timeout=1)) for r in reqs)
        print(f"--- {phase}: {total} tokens in {elapsed:.2f}s = {total/elapsed:.1f} tok/s")
        for k in sorted(TIMES):
            print(f"    {k}: {TIMES[k]:.2f}s over {COUNTS[k]} calls")
        other = elapsed - sum(
            TIMES[k] for k in ("_prefill", "_decode_chunk", "xla_compile")
        )
        print(f"    other (host glue): {other:.2f}s")


if __name__ == "__main__":
    main()
