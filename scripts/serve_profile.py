"""Phase-level profile of the continuous-batching engine's bench scenario.

Answers ONE question: where does the serve bench's wall-clock go on the real
chip — admissions (prefill dispatches), decode dispatch/sync, or mid-run XLA
compiles? The serve roofline in bench.py says ~2% of HBM peak, which means
the engine is host/dispatch-bound there, not bandwidth-bound; this script
attributes the time so the fix targets the right layer.

Two modes:

- ``python scripts/serve_profile.py``  (single real chip; ~2 min) — run the
  bench serve scenario with per-phase timers. Set ``PRIME_TRACE=trace.jsonl``
  first and the run also leaves a span log the second mode can analyze.
- ``python scripts/serve_profile.py --trace trace.jsonl`` — read a
  PRIME_TRACE JSONL (from any serve run) and print the per-chunk
  dispatch-vs-sync overlap report: for every decode chunk, how long the
  host spent enqueuing it (``serve.dispatch``), how long it later blocked
  fetching the tokens (``serve.sync``), and the host-stall fraction of the
  dispatch→sync window. A well-overlapped engine shows stall fractions near
  zero; ~1.0 means the loop is effectively synchronous. When the run touched
  the prefix cache's host spill tier, a tier report follows: spill (demotion)
  timing from the store path and host-re-upload vs pure-HBM assemble costs
  (``serve.assemble`` spans carry ``tier=device|host``). ``--trace`` repeats:
  pass each process's JSONL (client, router, replicas) and spans sharing a
  W3C trace id are merged into a per-request cross-process waterfall —
  router queue → replica queue → prefill → decode, with parent→child gaps
  called out (``--trace-id`` narrows to one request).
- ``python scripts/serve_profile.py --fleet http://router:8080`` — scrape a
  running `prime serve fleet` router and print the routing report: request
  distribution and outcomes per replica, affinity hit ratio (the fraction of
  keyed requests the consistent-hash scheduler landed on their prefix-warm
  replica), reroute reasons, breaker states, and admission-gate queue waits.
- ``--slo report.json`` (with ``--trace``) — merge a loadgen SLO report's
  per-scenario rows (docs/benchmarking.md) into the trace output: the
  scenario table prints first (which scenario regressed), the waterfalls
  below it say where inside a request the time went. Repeatable to compare
  two reports side by side.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TIMES: dict[str, float] = defaultdict(float)
COUNTS: dict[str, int] = defaultdict(int)


def _wrap(obj, name: str) -> None:
    """Time a method into TIMES[name], EXCLUDING any XLA-compile seconds that
    fire inside it (they land in TIMES['xla_compile'] via the compiler spy) —
    the report's buckets must be disjoint or mid-run compiles get attributed
    to the phase they happened to fire in."""
    fn = getattr(obj, name)

    def timed(*a, **k):
        compile_before = TIMES["xla_compile"]
        t0 = time.perf_counter()
        out = fn(*a, **k)
        elapsed = time.perf_counter() - t0
        TIMES[name] += elapsed - (TIMES["xla_compile"] - compile_before)
        COUNTS[name] += 1
        return out

    setattr(obj, name, timed)


def overlap_report(path: str, quiet: bool = False) -> None:
    """Pair serve.dispatch / serve.sync spans by chunk seq and print the
    per-chunk host-stall breakdown plus aggregates. One PRIME_TRACE file can
    hold several engines' spans back-to-back (bench.py builds a fresh engine
    per serve section, each restarting seq at 0): a dispatch whose seq was
    already seen starts a new run, so runs are reported separately instead
    of silently overwriting each other. Concurrent engines interleaving one
    sink are not disambiguated. ``quiet`` suppresses the no-engine-spans
    diagnostic — in multi-file waterfall mode, router/client files can never
    contain dispatch/sync pairs, and the hint would read as a serving
    misconfiguration that does not exist."""
    runs: list[tuple[dict[int, dict], dict[int, dict]]] = [({}, {})]
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            span = json.loads(line)
            seq = span.get("attrs", {}).get("seq")
            if seq is None:
                continue
            dispatch, sync = runs[-1]
            if span["name"] in ("serve.dispatch", "serve.spec_dispatch"):
                # spec chunks pipeline identically (one fused propose+verify
                # dispatch per seq) — same pairing, same stall math
                if seq in dispatch:  # seq restarted: a new engine's spans begin
                    dispatch, sync = {}, {}
                    runs.append((dispatch, sync))
                dispatch[seq] = span
            elif span["name"] == "serve.sync":
                sync[seq] = span
    runs = [(d, s) for d, s in runs if set(d) & set(s)]
    if not runs:
        if not quiet:
            print(f"no paired serve.dispatch/serve.sync spans in {path}")
            print(
                "(synchronous loop? PRIME_SERVE_OVERLAP=0 emits "
                "serve.decode_chunk only)"
            )
        return
    tot_stall = tot_window = 0.0
    for i, (dispatch, sync) in enumerate(runs):
        seqs = sorted(set(dispatch) & set(sync))
        label = f" (engine run {i + 1}/{len(runs)})" if len(runs) > 1 else ""
        print(f"--- overlap report: {len(seqs)} chunks from {path}{label}")
        print(
            f"{'chunk':>6} {'dispatch_ms':>12} {'stall_ms':>10} "
            f"{'window_ms':>10} {'stall_frac':>10}"
        )
        for seq in seqs:
            d, s = dispatch[seq], sync[seq]
            # window: dispatch start -> sync end, on the shared monotonic clock
            window = (s["start_s"] + s["duration_s"]) - d["start_s"]
            stall = s["duration_s"]
            tot_stall += stall
            tot_window += window
            print(
                f"{seq:>6} {d['duration_s'] * 1e3:>12.2f} {stall * 1e3:>10.2f} "
                f"{window * 1e3:>10.2f} {stall / window if window > 0 else 0.0:>10.3f}"
            )
    frac = tot_stall / tot_window if tot_window > 0 else 0.0
    print(
        f"--- total: stall {tot_stall:.3f}s of {tot_window:.3f}s window "
        f"({frac:.1%} stalled, {1 - frac:.1%} overlapped)"
    )


def tier_report(paths: list[str]) -> None:
    """Prefix-cache tier timing from the trace JSONL: what demotions to the
    host spill tier cost on the store path (``serve.spill`` synthetic spans —
    each is a forced device sync) and what a host-tier hit's re-upload added
    to its assemble (``serve.assemble`` spans carry ``tier`` + token counts).
    Silent when the run never touched the spill tier — single-tier traces
    should not grow a table of zeros."""
    spans = _load_spans(paths)
    spills = [s for s in spans if s.get("name") == "serve.spill"]
    assembles = [s for s in spans if s.get("name") == "serve.assemble"]
    by_tier: dict[str, list[dict]] = defaultdict(list)
    for span in assembles:
        by_tier[(span.get("attrs") or {}).get("tier", "device")].append(span)
    if not spills and not by_tier.get("host"):
        return
    print("--- prefix-cache tier report")
    print(f"{'path':>22} {'count':>6} {'total_ms':>9} {'mean_ms':>8}  detail")
    if spills:
        total = sum(s.get("duration_s") or 0.0 for s in spills)
        segments = sum((s.get("attrs") or {}).get("segments", 0) for s in spills)
        nbytes = sum((s.get("attrs") or {}).get("bytes", 0) for s in spills)
        print(
            f"{'spill (store path)':>22} {len(spills):>6} {total * 1e3:>9.2f} "
            f"{total / len(spills) * 1e3:>8.2f}  {segments} segments, "
            f"{nbytes / 1e6:.2f} MB demoted"
        )
    for tier in ("host", "device"):
        group = by_tier.get(tier)
        if not group:
            continue
        total = sum(s.get("duration_s") or 0.0 for s in group)
        tokens = sum((s.get("attrs") or {}).get("hit_tokens", 0) for s in group)
        label = "assemble (re-upload)" if tier == "host" else "assemble (HBM hit)"
        detail = f"{tokens} hit tokens"
        if tier == "host":
            host_tokens = sum(
                (s.get("attrs") or {}).get("host_tokens", 0) for s in group
            )
            detail += f", {host_tokens} re-uploaded"
        print(
            f"{label:>22} {len(group):>6} {total * 1e3:>9.2f} "
            f"{total / len(group) * 1e3:>8.2f}  {detail}"
        )


def _load_spans(paths: list[str]) -> list[dict]:
    """Every parseable span from every file, tagged with its source file —
    the waterfall marks parent→child edges that cross files as the
    cross-process hops they are."""
    spans: list[dict] = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    span = json.loads(line)
                except ValueError:
                    continue
                if isinstance(span, dict) and "name" in span:
                    span["_source"] = os.path.basename(path)
                    spans.append(span)
    return spans


def waterfall_report(paths: list[str], trace_id: str | None = None, limit: int = 5) -> None:
    """Merge spans from N processes' JSONL files by trace id and print a
    per-request waterfall: one indented tree per trace, offsets on the
    shared wall clock (start_unix_s — the cross-process axis; the monotonic
    start_s only orders within one process), durations, and the gap between
    each child's start and its parent's, flagged ``[cross-process]`` when
    the edge spans two files. Gaps are where a distributed request's time
    goes missing: router queue → replica queue → prefill → decode should
    tile the root span; a hole is a stall nobody's histogram attributes."""
    spans = _load_spans(paths)
    groups: dict[str, list[dict]] = defaultdict(list)
    for span in spans:
        tid = span.get("trace_id")
        if tid:
            groups[tid].append(span)
    if trace_id is not None:
        if trace_id not in groups:
            print(f"no spans with trace id {trace_id}")
            return
        selected = [(trace_id, groups[trace_id])]
    else:
        multi = {tid: g for tid, g in groups.items() if len(g) >= 2}
        if not multi:
            if trace_id is None and not any(s.get("trace_id") for s in spans):
                return  # single-process legacy file: the overlap report said it all
            print("no multi-span traces to stitch (single-span traces only)")
            return
        # newest requests first, bounded — a long serve run holds thousands
        newest = sorted(
            multi.items(),
            key=lambda item: max(s.get("start_unix_s", 0) for s in item[1]),
            reverse=True,
        )
        selected = newest[:limit]
        if len(newest) > limit:
            print(
                f"--- showing the {limit} newest of {len(newest)} stitched "
                "traces (use --trace-id to pick one)"
            )
    for tid, group in selected:
        sources = sorted({s["_source"] for s in group})
        print(f"--- trace {tid}: {len(group)} spans from {', '.join(sources)}")
        print(f"{'offset_ms':>10} {'dur_ms':>9}  span")
        by_id = {s["span_id"]: s for s in group if s.get("span_id")}
        children: dict[str, list[dict]] = defaultdict(list)
        roots: list[dict] = []
        for span in group:
            parent = by_id.get(span.get("parent_id"))
            if parent is not None and parent is not span:
                children[span["parent_id"]].append(span)
            else:
                roots.append(span)
        t0 = min(s.get("start_unix_s", 0.0) for s in group)

        def emit(span: dict, depth: int, parent: dict | None) -> None:
            start = span.get("start_unix_s", 0.0) - t0
            dur = span.get("duration_s") or 0.0
            notes = []
            if parent is not None:
                gap = span.get("start_unix_s", 0.0) - parent.get("start_unix_s", 0.0)
                hop = span["_source"] != parent["_source"]
                if hop or gap * 1e3 >= 1.0:
                    notes.append(
                        f"+{gap * 1e3:.2f} ms after parent"
                        + (" [cross-process]" if hop else "")
                    )
            attrs = span.get("attrs") or {}
            brief = ", ".join(
                f"{k}={attrs[k]}"
                for k in (
                    "replica", "request", "outcome", "slot", "prompt_len",
                    "tokens", "tier", "hit_tokens", "host_tokens",
                )
                if k in attrs
            )
            line = f"{start * 1e3:>10.2f} {dur * 1e3:>9.2f}  {'  ' * depth}{span['name']}"
            if brief:
                line += f" ({brief})"
            if notes:
                line += "  " + " ".join(notes)
            print(line)
            for child in sorted(
                children.get(span.get("span_id"), []),
                key=lambda s: s.get("start_unix_s", 0.0),
            ):
                emit(child, depth + 1, span)

        for root in sorted(roots, key=lambda s: s.get("start_unix_s", 0.0)):
            emit(root, 0, None)


def slo_report(paths: list[str]) -> None:
    """Per-scenario SLO rows from loadgen report file(s) — printed above
    the waterfalls so one invocation answers both 'which scenario
    regressed' and 'where in the request did the time go'. Multiple
    reports print in argument order (pass previous + current to eyeball
    the delta; `prime bench delta` renders the committed trajectory)."""

    def ms(quantiles: dict | None, key: str) -> str:
        value = (quantiles or {}).get(key)
        return f"{value * 1e3:.1f}" if isinstance(value, (int, float)) else "—"

    for path in paths:
        try:
            with open(path) as f:
                report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"--- SLO report {path}: unreadable ({e})")
            continue
        headline = report.get("headline", {})
        print(
            f"--- SLO report {path} (schema {report.get('slo_schema', '?')}): "
            f"aggregate {headline.get('tok_s', '?')} tok/s, "
            f"{headline.get('requests', '?')} requests, "
            f"{headline.get('rejected_429', 0)} rejected"
        )
        print(
            f"{'scenario':>18} {'tok/s':>8} {'ttft_p50':>9} {'ttft_p95':>9} "
            f"{'tpot_p50':>9} {'overlap':>8} {'hit':>6} {'429s':>5}  outcomes"
        )
        for row in report.get("scenarios", []):
            outcomes = ", ".join(
                f"{k}={v}" for k, v in sorted((row.get("outcomes") or {}).items())
            )
            fleet = row.get("fleet") or {}
            if fleet.get("affinity_ratio") is not None:
                outcomes += f" | affinity {fleet['affinity_ratio']}"
            print(
                f"{row.get('scenario', '?'):>18} {row.get('tok_s', 0):>8} "
                f"{ms(row.get('ttft_s'), 'p50'):>9} {ms(row.get('ttft_s'), 'p95'):>9} "
                f"{ms(row.get('tpot_s'), 'p50'):>9} "
                f"{row.get('overlap_ratio') if row.get('overlap_ratio') is not None else '—':>8} "
                f"{row.get('prefix_hit_ratio') if row.get('prefix_hit_ratio') is not None else '—':>6} "
                f"{row.get('rejected_429', 0):>5}  {outcomes}"
            )


def fleet_report(url: str) -> None:
    """Scrape a FleetRouter's /metrics and /admin/fleet and print where the
    traffic went and why — the first question when fleet throughput
    disappoints is 'did affinity routing actually concentrate the shared
    prefixes, or did saturation/reroutes scatter them?'."""
    import httpx

    base = url.rstrip("/")
    stats = httpx.get(f"{base}/metrics", timeout=10).json()
    print(f"--- fleet routing report: {base}")
    print(
        f"affinity: {stats['affinity_hits']}/{stats['affinity_requests']} keyed "
        f"requests hit their hash target (ratio {stats['affinity_hit_ratio']})"
    )
    rejected = stats.get("admission_rejected", 0)
    if rejected:
        print(f"admission gate rejected {rejected} requests (fleet saturated)")
    if stats.get("reroutes"):
        reasons = ", ".join(f"{k}={v}" for k, v in sorted(stats["reroutes"].items()))
        print(f"reroutes: {reasons}")
    print(f"{'replica':>24} {'state':>9} {'breaker':>9} {'queue':>6} {'slots':>8} requests")
    for rid, replica in sorted(stats.get("replicas", {}).items()):
        outcomes = stats.get("requests_by_replica", {}).get(rid, {})
        shown = ", ".join(f"{k}={v}" for k, v in sorted(outcomes.items())) or "-"
        slots = f"{replica['active_slots']}/{replica['max_slots'] or '?'}"
        print(
            f"{rid:>24} {replica['state']:>9} {replica['breaker']:>9} "
            f"{replica['queue_depth']:>6} {slots:>8} {shown}"
        )
    registry = httpx.get(f"{base}/metrics", params={"format": "registry"}, timeout=10).json()
    wait = next(
        (s for s in registry["router"]["fleet_queue_wait_seconds"]["series"] if s["count"]),
        None,
    )
    if wait:
        print(
            f"admission queue wait: {wait['count']} requests, "
            f"mean {wait['sum'] / wait['count'] * 1e3:.2f} ms"
        )


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--trace", metavar="JSONL", action="append", default=None,
        help="Print the dispatch-vs-sync overlap report from a PRIME_TRACE "
             "JSONL instead of running the profile. Repeatable: multiple "
             "files (router + replicas) are also stitched by trace id into "
             "per-request cross-process waterfalls.",
    )
    parser.add_argument(
        "--trace-id", metavar="HEX", default=None,
        help="With --trace: stitch only this W3C trace id's waterfall.",
    )
    parser.add_argument(
        "--fleet", metavar="ROUTER_URL", default=None,
        help="Print the routing report scraped from a running "
             "`prime serve fleet` router instead of running the profile.",
    )
    parser.add_argument(
        "--slo", metavar="REPORT_JSON", action="append", default=None,
        help="Merge a loadgen SLO report's per-scenario rows into the "
             "output (above the waterfalls). Repeatable.",
    )
    args = parser.parse_args()
    # --slo composes with every offline mode: scenario rows print first,
    # then whichever detail view (--trace waterfalls / --fleet routing)
    if args.slo:
        slo_report(args.slo)
    if args.trace:
        for path in args.trace:
            overlap_report(path, quiet=len(args.trace) > 1)
        tier_report(args.trace)
        waterfall_report(args.trace, trace_id=args.trace_id)
        return
    if args.fleet:
        fleet_report(args.fleet)
        return
    if args.slo:
        return

    import jax
    import jax.numpy as jnp

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.serve.engine import ContinuousBatchingEngine

    # the scenario comes from bench.py so this profiles EXACTLY the workload
    # the bench's serve section measures
    import bench

    config = get_config("llama3.2-1b")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.bfloat16)
    req_new = bench.SERVE_NEW
    prompts = bench.serve_prompts_for(config)
    engine = ContinuousBatchingEngine(
        params, config, pad_id=0, max_slots=bench.SERVE_SLOTS,
        capacity=bench.SERVE_CAPACITY, chunk=bench.SERVE_CHUNK,
    )
    # count XLA compiles (remote compiles over the tunnel cost seconds each)
    import jax._src.compiler as _c

    cname = (
        "backend_compile_and_load"
        if hasattr(_c, "backend_compile_and_load")
        else "backend_compile"
    )
    real_compile = getattr(_c, cname)

    def spy(*a, **k):
        t0 = time.perf_counter()
        out = real_compile(*a, **k)
        TIMES["xla_compile"] += time.perf_counter() - t0
        COUNTS["xla_compile"] += 1
        return out

    setattr(_c, cname, spy)

    _wrap(engine, "_prefill")
    _wrap(engine, "_prefill_batch")
    # prefix-cache attribution: seed covers the radix match + the single
    # assemble_row dispatch on hits, store the block split/insert path
    _wrap(engine, "_prefix_seed")
    _wrap(engine, "_store_prefix")
    if engine.overlap:
        # the pipelined loop: dispatch is host enqueue time, sync is the
        # blocked fetch — their gap is exactly what overlap bought
        _wrap(engine, "_dispatch_decode")
        _wrap(engine, "_sync_decode")
    else:
        _wrap(engine, "_decode_chunk")
    decode_keys = (
        ("_dispatch_decode", "_sync_decode") if engine.overlap else ("_decode_chunk",)
    )
    for phase in ("warm1", "warm2", "measured"):
        TIMES.clear()
        COUNTS.clear()
        t0 = time.perf_counter()
        if phase.startswith("warm"):
            reqs = [engine.submit(prompts[0], max_new_tokens=req_new)]
        else:
            reqs = [engine.submit(ids, max_new_tokens=req_new) for ids in prompts]
        while not all(r.done for r in reqs):
            engine.tick()
        elapsed = time.perf_counter() - t0
        # snapshot the phase timers BEFORE draining the lookahead chunk: the
        # drain's sync time is outside `elapsed` and must not skew the
        # attribution (nor leak into the next phase's timed window)
        times, counts = dict(TIMES), dict(COUNTS)
        engine.tick()
        total = sum(len(r.all_tokens(timeout=1)) for r in reqs)
        print(f"--- {phase}: {total} tokens in {elapsed:.2f}s = {total/elapsed:.1f} tok/s")
        for k in sorted(times):
            print(f"    {k}: {times[k]:.2f}s over {counts[k]} calls")
        other = elapsed - sum(
            times.get(k, 0.0)
            for k in ("_prefill", "_prefill_batch", "xla_compile", *decode_keys)
        )
        print(f"    other (host glue): {other:.2f}s")
    stats = engine.stats()
    print(
        f"--- engine: overlap_ratio {stats['overlap_ratio']}, host stall "
        f"{stats['host_stall_s']}s of {stats['chunk_window_s']}s window, "
        f"wasted decode tokens {stats['wasted_decode_tokens']}"
    )
    print(
        f"--- prefix cache: {stats['prefix_cache_bytes'] / 1e6:.1f} MB device "
        f"+ {stats['prefix_cache_host_bytes'] / 1e6:.1f} MB host in "
        f"{stats['prefix_cache_nodes']} nodes, {engine.prefix_hits} hits / "
        f"{stats['prefix_assembles']} assembles, "
        f"{stats['prefix_spills']} spills / {stats['prefix_reuploads']} "
        f"re-uploads, {stats['prefix_evictions']} evictions"
    )
    if os.environ.get("PRIME_TRACE"):
        print(f"--- spans at {os.environ['PRIME_TRACE']}: rerun with "
              f"--trace {os.environ['PRIME_TRACE']} for the per-chunk report")


if __name__ == "__main__":
    main()
