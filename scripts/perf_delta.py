"""Render the per-PR perf delta table from committed BENCH_*.json rounds.

Usage:
    python scripts/perf_delta.py [--root DIR] [--pattern GLOB] [--json]
    python scripts/perf_delta.py --min-rounds 1   # CI smoke: never fail on a
                                                  # fresh checkout with one round

Thin wrapper over prime_tpu.loadgen.perf_delta (stdlib-only — no jax, no
install) so the same table renders from CI, a laptop, and `prime bench
delta`. Schema-1 records (rounds before the loadgen era) are labeled and
parsed with headline fields only; schema-2 records additionally contribute
their loadgen SLO rows (per-scenario tok/s and TTFT percentiles).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from prime_tpu.loadgen.perf_delta import (  # noqa: E402
    delta_json,
    delta_table,
    load_all_rounds,
    load_rounds,
)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--root", default=".", help="Directory holding BENCH_*.json")
    parser.add_argument(
        "--pattern", default=None,
        help="Restrict to one glob (default: BENCH_*.json + MULTICHIP_*.json "
             "merged — multichip rounds render their own mc-prefixed rows).",
    )
    parser.add_argument("--json", action="store_true", help="Machine-readable output")
    parser.add_argument(
        "--min-rounds", type=int, default=2,
        help="Fail (exit 1) below this many parseable rounds.",
    )
    args = parser.parse_args()
    rounds = (
        load_rounds(args.root, args.pattern)
        if args.pattern
        else load_all_rounds(args.root)
    )
    if args.json:
        print(json.dumps(delta_json(rounds), indent=2))
    else:
        print(delta_table(rounds, min_rounds=args.min_rounds))
    return 0 if len(rounds) >= args.min_rounds else 1


if __name__ == "__main__":
    raise SystemExit(main())
