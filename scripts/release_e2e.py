"""Release E2E harness (shape of reference scripts/release_e2e.py).

Spins up the live-socket fake control plane, then exercises the release
candidate's CLI end-to-end as real subprocesses: identity, availability,
pods lifecycle, sandbox exec, env push/install, eval run+push, training
dispatch, inference chat. Exits non-zero on the first failure.

Run:  python scripts/release_e2e.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from prime_tpu.testing.live_server import LiveControlPlane  # noqa: E402

PASS = 0
FAIL: list[str] = []


def run_cli(*args: str, env: dict[str, str], check: bool = True, input_text: str | None = None):
    proc = subprocess.run(
        [sys.executable, "-m", "prime_tpu.commands.main", *args],
        capture_output=True,
        text=True,
        env=env,
        input=input_text,
        cwd=str(REPO),
        timeout=300,
    )
    if check and proc.returncode != 0:
        raise RuntimeError(f"prime {' '.join(args)} failed ({proc.returncode}):\n{proc.stderr[-1500:]}")
    return proc


def step(name: str):
    def deco(fn):
        def wrapper(env):
            global PASS
            try:
                fn(env)
                PASS += 1
                print(f"  ok   {name}")
            except Exception as e:
                FAIL.append(name)
                print(f"  FAIL {name}: {e}")

        return wrapper

    return deco


@step("whoami")
def check_whoami(env):
    out = run_cli("whoami", "--output", "json", env=env).stdout
    assert json.loads(out)["email"] == "dev@example.com"


@step("availability list")
def check_availability(env):
    out = run_cli("availability", "list", "--tpu-type", "v5e", "--output", "json", env=env).stdout
    rows = json.loads(out)
    assert any(r["sliceName"] == "v5e-8" for r in rows)


@step("pods create/status/terminate")
def check_pods(env):
    out = run_cli("pods", "create", "--slice", "v5e-16", "--yes", "--output", "json", env=env).stdout
    pod_id = json.loads(out)["podId"]
    run_cli("pods", "status", pod_id, env=env)
    out = run_cli("pods", "status", pod_id, "--output", "json", env=env).stdout
    assert json.loads(out)["status"] == "ACTIVE"
    run_cli("pods", "terminate", pod_id, "--yes", env=env)


@step("sandbox create/exec/delete")
def check_sandbox(env):
    out = run_cli("sandbox", "create", "--name", "e2e", "--output", "json", env=env).stdout
    sid = json.loads(out)["sandboxId"]
    out = run_cli("sandbox", "run", sid, "echo e2e-works", env=env).stdout
    assert "e2e-works" in out
    run_cli("sandbox", "delete", sid, "--yes", env=env)


@step("env push/install")
def check_env(env):
    with tempfile.TemporaryDirectory() as tmp:
        env_dir = Path(tmp) / "e2e-env"
        run_cli("env", "init", "e2e-env", "--dir", str(env_dir), env=env)
        run_cli("env", "push", "--dir", str(env_dir), env=env)
        run_cli("env", "install", "e2e-env", env=env)


@step("eval run (tiny model) + hub push")
def check_eval(env):
    with tempfile.TemporaryDirectory() as tmp:
        out = run_cli(
            "eval", "run", "e2e-arith", "-m", "tiny-test", "-n", "2", "-b", "2",
            "--max-new-tokens", "4", "--output-dir", tmp, "--output", "json",
            env=env,
        ).stdout
        payload = json.loads(out)
        assert payload["metrics"]["num_samples"] == 2.0
        assert payload["evalId"].startswith("eval_")


@step("eval endpoint alias + preflights")
def check_eval_endpoints(env):
    """Round-4 surface: an alias with a base_url runs inference-backed (the
    fake endpoint echoes prompts), an unknown hosted model 402/404s BEFORE
    submission, and local-only flags hard-fail with --hosted."""
    with tempfile.TemporaryDirectory() as tmp:
        table = Path(tmp) / "endpoints.toml"
        table.write_text(
            f'[smoke]\nmodel = "llama3-8b"\nbase_url = "{env["PRIME_INFERENCE_URL"]}"\n'
        )
        out = run_cli(
            "eval", "run", "e2e-arith", "-m", "smoke", "-n", "2", "-b", "2",
            "--no-push", "--endpoints-path", str(table),
            "--output-dir", tmp, "--output", "json",
            env=env,
        ).stdout
        payload = json.loads(out[out.index("{"):])
        assert payload["metrics"]["num_samples"] == 2.0
        rows = [
            json.loads(line)
            for line in open(Path(payload["runDir"]) / "results.jsonl")
            if line.strip()
        ]
        assert all(r["completion"].startswith("echo: ") for r in rows)
        proc = run_cli(
            "eval", "run", "e2e-arith", "-m", "not-a-model", "--hosted",
            env=env, check=False,
        )
        assert proc.returncode != 0 and "Invalid model" in proc.stderr
        proc = run_cli(
            "eval", "run", "e2e-arith", "-m", "llama3-8b", "--hosted", "--kv-quant",
            env=env, check=False,
        )
        assert proc.returncode != 0 and "--kv-quant" in proc.stderr


@step("train dispatch + logs")
def check_train(env):
    with tempfile.TemporaryDirectory() as tmp:
        toml = Path(tmp) / "e2e.toml"
        run_cli("train", "init", "e2e-run", "--out", str(toml), env=env)
        out = run_cli("train", "run", str(toml), "--yes", "--output", "json", env=env).stdout
        run_id = json.loads(out)["runId"]
        out = run_cli("train", "logs", run_id, "--plain", env=env).stdout
        assert "trainer" in out


@step("inference chat")
def check_inference(env):
    out = run_cli(
        "inference", "chat", "llama3-8b", "-m", "ship it", "--no-stream", "--output", "json", env=env
    ).stdout
    assert json.loads(out)["choices"][0]["message"]["content"] == "echo: ship it"


@step("env execution protocol (hub resolve -> load_environment -> run)")
def check_env_execution(env):
    with tempfile.TemporaryDirectory() as tmp:
        run_cli("env", "push", "--dir", str(REPO / "examples" / "verifiers_example"), env=env)
        out = run_cli(
            "eval", "run", "arith-rl", "-m", "tiny-test", "--no-push", "-n", "2", "-b", "2",
            "--output-dir", tmp, "--plain", env=env,
        ).stdout
        assert "Resolved env arith-rl" in out
        out = run_cli("env", "inspect", "arith-rl", "--output", "json", env=env).stdout
        inspected = json.loads(out)
        assert inspected["loadEnvironment"] == "ok" and inspected["hasScorer"]
        actions = json.loads(run_cli("env", "actions", "list", "arith-rl", "--output", "json", env=env).stdout)
        logs = run_cli("env", "actions", "logs", "arith-rl", actions[0]["id"], "--plain", env=env).stdout
        assert "build finished" in logs


@step("images suite (build-vm, hf-cache, bulk, visibility)")
def check_images(env):
    with tempfile.TemporaryDirectory() as tmp:
        out = run_cli(
            "images", "build-vm", "--name", "e2e-vm", "--base-image", "tpu-base",
            "--output", "json", env=env,
        ).stdout
        image_id = json.loads(out)["imageId"]
        run_cli("images", "hf-cache", "--name", "e2e-cache", "--model", "m/llama", env=env)
        manifest = Path(tmp) / "m.json"
        manifest.write_text(json.dumps([{"name": "e2e-bulk", "dockerfileText": "FROM a\n"}]))
        out = run_cli("images", "bulk-push", "--manifest", str(manifest), "--plain", env=env).stdout
        assert "1/1 succeeded" in out
        run_cli("images", "visibility", "public", image_id, env=env)
        detail = json.loads(run_cli("images", "get", image_id, "--output", "json", env=env).stdout)
        assert detail["visibility"] == "public" and detail["artifacts"]


@step("train local (native trainer) + lab charts data")
def check_train_local(env):
    with tempfile.TemporaryDirectory() as tmp:
        out = run_cli(
            "train", "local", "-m", "tiny-test", "--steps", "3", "-b", "2", "--seq-len", "16",
            "--name", "e2e-local", "--output-dir", str(Path(tmp) / "outputs" / "train"),
            "--output", "json", env=env,
        ).stdout
        payload = json.loads(out)
        assert payload["steps"] == 3 and payload["tokens_per_sec"] > 0
        metrics = (Path(tmp) / "outputs" / "train" / "e2e-local" / "metrics.jsonl").read_text()
        assert len(metrics.splitlines()) == 3


@step("GRPO local RL + LoRA adapter -> eval --adapter round trip")
def check_local_rl_lora(env):
    with tempfile.TemporaryDirectory() as tmp:
        out = run_cli(
            "train", "local-rl", "arith", "-m", "tiny-test", "--steps", "2",
            "-g", "2", "-p", "2", "--max-prompt-len", "16", "--max-new-tokens", "4",
            "--lora", "--lora-r", "4", "--name", "e2e-rl",
            "--output-dir", str(Path(tmp) / "rl"), "--output", "json", env=env,
        ).stdout
        payload = json.loads(out)
        assert payload["steps"] == 2 and "adapterDir" in payload
        ev = run_cli(
            "eval", "run", "arith", "-m", "tiny-test", "--adapter", payload["adapterDir"],
            "--no-push", "-n", "2", "-b", "2", "--max-new-tokens", "4",
            "--output-dir", str(Path(tmp) / "evals"), "--plain", env=env,
        )
        assert "accuracy=" in ev.stdout


@step("speculative decoding through eval run")
def check_speculative(env):
    with tempfile.TemporaryDirectory() as tmp:
        out = run_cli(
            "eval", "run", "arith", "-m", "tiny-test", "--speculative", "--draft-len", "4",
            "--no-push", "-n", "2", "-b", "2", "--max-new-tokens", "4",
            "--output-dir", str(Path(tmp) / "evals"), "--plain", env=env,
        )
        assert "accuracy=" in out.stdout
        # sampled speculation (rejection sampling) runs the same surface at
        # a real temperature instead of hard-erroring
        sampled = run_cli(
            "eval", "run", "arith", "-m", "tiny-test", "--speculative", "-t", "0.5",
            "--no-push", "-n", "2", "-b", "2", "--max-new-tokens", "4",
            "--output-dir", str(Path(tmp) / "e2"), "--plain", env=env,
        )
        assert "accuracy=" in sampled.stdout


def _serve_round_trip(env, serve_kwargs: str, sentinel: str) -> None:
    """One OpenAI-compatible round trip against serve_model(<kwargs>)."""
    code = (
        "import httpx\n"
        "from prime_tpu.serve import serve_model\n"
        f"server = serve_model('tiny-test', port=0{serve_kwargs})\n"
        "with server:\n"
        "    r = httpx.post(server.url + '/v1/chat/completions',\n"
        "                   json={'messages': [{'role': 'user', 'content': 'hi'}], 'max_tokens': 3},\n"
        "                   timeout=240)\n"
        "    assert r.status_code == 200, r.text\n"
        "    assert r.json()['usage']['total_tokens'] >= 1\n"
        f"print('{sentinel}')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, env=env,
        cwd=str(REPO), timeout=300,
    )
    if proc.returncode != 0:
        raise RuntimeError(proc.stderr[-800:])
    assert sentinel in proc.stdout


@step("serve round trip (OpenAI-compatible)")
def check_serve(env):
    _serve_round_trip(env, "", "serve-ok")


@step("continuous-batching serve with int8 KV cache")
def check_serve_continuous_int8(env):
    _serve_round_trip(
        env,
        ", continuous=True, kv_quant=True, max_slots=2, slot_capacity=256, chunk=4",
        "serve-int8-ok",
    )


def main() -> int:
    server = LiveControlPlane().start()
    with tempfile.TemporaryDirectory() as config_dir:
        env = {
            **os.environ,
            "PRIME_BASE_URL": server.url,
            "PRIME_INFERENCE_URL": f"{server.url}/v1",
            "PRIME_API_KEY": "test-key",
            "PRIME_CONFIG_DIR": config_dir,
            "PRIME_DISABLE_VERSION_CHECK": "1",
            "PYTHONPATH": str(REPO),
            # eval generation must not depend on TPU availability
            "PALLAS_AXON_POOL_IPS": "",
            "JAX_PLATFORMS": "cpu",
        }
        print(f"release E2E against {server.url}")
        for check in (
            check_whoami,
            check_availability,
            check_pods,
            check_sandbox,
            check_env,
            check_eval,
            check_eval_endpoints,
            check_train,
            check_inference,
            check_env_execution,
            check_images,
            check_train_local,
            check_local_rl_lora,
            check_speculative,
            check_serve,
            check_serve_continuous_int8,
        ):
            check(env)
    server.stop()
    print(f"\n{PASS} passed, {len(FAIL)} failed" + (f": {FAIL}" if FAIL else ""))
    return 1 if FAIL else 0


if __name__ == "__main__":
    raise SystemExit(main())
