#!/usr/bin/env python
"""Release-time check: FRPC_CHECKSUMS must match the published artifacts.

Downloads each pinned frp release tarball and compares its sha256 against the
pin in prime_tpu.tunnel.binary. Needs network egress — run at release time,
not in CI sandboxes. Exits non-zero on any mismatch.
"""

from __future__ import annotations

import hashlib
import sys

import httpx

from prime_tpu.tunnel.binary import FRPC_CHECKSUMS, FRPC_VERSION, RELEASE_URL


def main() -> int:
    failures = 0
    for plat, expected in FRPC_CHECKSUMS.items():
        url = RELEASE_URL.format(v=FRPC_VERSION, plat=plat)
        try:
            response = httpx.get(url, follow_redirects=True, timeout=300.0)
            response.raise_for_status()
        except httpx.HTTPError as e:
            print(f"FAIL {plat}: download error: {e}")
            failures += 1
            continue
        digest = hashlib.sha256(response.content).hexdigest()
        if digest == expected:
            print(f"ok   {plat}: {digest}")
        else:
            print(f"FAIL {plat}: pinned {expected} but artifact is {digest}")
            failures += 1
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
