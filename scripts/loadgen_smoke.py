"""CI entry for the loadgen CPU smoke (docs/benchmarking.md).

Runs the deterministic ``smoke`` scenario against an in-process 2-replica
fleet over real HTTP on ``JAX_PLATFORMS=cpu``, writes the SLO report +
BENCH-schema record + flight scrape into ``--output``, lints every
``/metrics`` exposition against the docs catalog, and exits nonzero unless
the headline tok/s is positive and every exposition is clean — the CI job
``loadgen-smoke`` gates on exactly that.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from prime_tpu.loadgen.smoke import run_smoke  # noqa: E402


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--output", default="loadgen-smoke", help="Artifact directory")
    parser.add_argument("--scenario", default="smoke")
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument(
        "--mesh", default=None,
        help="Sharded-replica mesh spec (e.g. 'dp=1,fsdp=2,tp=2'): every "
             "replica becomes ONE engine spanning that mesh. Run under "
             "XLA_FLAGS=--xla_force_host_platform_device_count=N to measure "
             "the multi-chip serving path on CPU (MULTICHIP_*.json rounds).",
    )
    parser.add_argument("--time-scale", type=float, default=1.0)
    parser.add_argument(
        "--disagg-mesh", action="store_true",
        help="Instead of the fleet smoke, run the MULTICHIP disaggregation "
             "round: phase-split vs colocated with role-preset meshes "
             "(role:prefill / role:decode) over disjoint halves of the "
             "forced device set — the shape a committed "
             "MULTICHIP_loadgen_cpu_rNN.json wants.",
    )
    args = parser.parse_args()
    if args.disagg_mesh:
        from prime_tpu.loadgen.smoke import run_disagg_mesh_round

        return 0 if run_disagg_mesh_round(args.output, seed=args.seed)["ok"] else 1
    outcome = run_smoke(
        args.output,
        scenario=args.scenario,
        seed=args.seed,
        replicas=args.replicas,
        mesh=args.mesh,
        time_scale=args.time_scale,
    )
    return 0 if outcome["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
