"""CI entry for the loadgen CPU smoke (docs/benchmarking.md).

Runs the deterministic ``smoke`` scenario against an in-process 2-replica
fleet over real HTTP on ``JAX_PLATFORMS=cpu``, writes the SLO report +
BENCH-schema record + flight scrape into ``--output``, lints every
``/metrics`` exposition against the docs catalog, and exits nonzero unless
the headline tok/s is positive and every exposition is clean — the CI job
``loadgen-smoke`` gates on exactly that.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from prime_tpu.loadgen.smoke import run_smoke  # noqa: E402


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--output", default="loadgen-smoke", help="Artifact directory")
    parser.add_argument("--scenario", default="smoke")
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--time-scale", type=float, default=1.0)
    args = parser.parse_args()
    outcome = run_smoke(
        args.output,
        scenario=args.scenario,
        seed=args.seed,
        replicas=args.replicas,
        time_scale=args.time_scale,
    )
    return 0 if outcome["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
