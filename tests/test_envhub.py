"""Environments Hub: packaging, hashing, push/pull/install round trips."""

import json

import pytest
from click.testing import CliRunner

import prime_tpu.commands._deps as deps
from prime_tpu.commands.main import cli
from prime_tpu.envhub.packaging import (
    build_archive,
    content_hash,
    extract_archive,
    iter_env_files,
    read_env_metadata,
    write_env_template,
)
from prime_tpu.testing import FakeControlPlane


@pytest.fixture
def fake(monkeypatch):
    fake = FakeControlPlane()
    monkeypatch.setattr(deps, "transport_override", fake.transport)
    monkeypatch.setenv("PRIME_API_KEY", "test-key")
    monkeypatch.setenv("PRIME_BASE_URL", "https://api.fake")
    return fake


@pytest.fixture
def runner():
    return CliRunner()


@pytest.fixture
def env_dir(tmp_path):
    d = tmp_path / "my-env"
    write_env_template(d, "my-env")
    (d / "data").mkdir()
    (d / "data" / "eval.jsonl").write_text('{"question": "1+1?", "answer": "#### 2"}\n')
    return d


def test_template_and_metadata(env_dir):
    metadata = read_env_metadata(env_dir)
    assert metadata["name"] == "my-env"
    assert metadata["tpu"]["tpu_type"] == "v5e"


def test_gitignore_filtering(env_dir):
    (env_dir / "__pycache__").mkdir()
    (env_dir / "__pycache__" / "junk.pyc").write_text("x")
    (env_dir / ".gitignore").write_text("scratch/\n*.log\n")
    (env_dir / "scratch").mkdir()
    (env_dir / "scratch" / "tmp.txt").write_text("x")
    (env_dir / "debug.log").write_text("x")
    files = [f.name for f in iter_env_files(env_dir)]
    assert "junk.pyc" not in files and "tmp.txt" not in files and "debug.log" not in files
    assert "env.toml" in files


def test_content_hash_is_deterministic_and_drift_sensitive(env_dir):
    h1 = content_hash(env_dir)
    assert h1 == content_hash(env_dir)
    (env_dir / "data" / "eval.jsonl").write_text('{"question": "2+2?", "answer": "#### 4"}\n')
    assert content_hash(env_dir) != h1


def test_archive_roundtrip_and_determinism(env_dir, tmp_path):
    a1 = build_archive(env_dir)
    a2 = build_archive(env_dir)
    assert a1 == a2  # byte-identical (zeroed mtimes)
    out = tmp_path / "extracted"
    extract_archive(a1, out)
    assert (out / "env.toml").read_text() == (env_dir / "env.toml").read_text()
    assert (out / "data" / "eval.jsonl").exists()


def test_push_pull_install_cli_roundtrip(runner, fake, env_dir, tmp_path, monkeypatch):
    result = runner.invoke(cli, ["env", "push", "--dir", str(env_dir)])
    assert result.exit_code == 0, result.output
    assert "Pushed my-env@0.1.0" in result.output

    # idempotent push: unchanged content is detected by hash
    result = runner.invoke(cli, ["env", "push", "--dir", str(env_dir)])
    assert "unchanged" in result.output

    result = runner.invoke(cli, ["env", "list", "--output", "json"])
    envs = json.loads(result.output)
    assert envs[0]["name"] == "my-env"

    pull_dir = tmp_path / "pulled"
    result = runner.invoke(cli, ["env", "pull", "my-env", "--dir", str(pull_dir)])
    assert result.exit_code == 0, result.output
    assert (pull_dir / "data" / "eval.jsonl").exists()

    result = runner.invoke(cli, ["env", "install", "my-env"])
    assert result.exit_code == 0, result.output
    result = runner.invoke(cli, ["env", "list", "--installed", "--plain"])
    assert "my-env" in result.output

    result = runner.invoke(cli, ["env", "uninstall", "my-env"])
    assert result.exit_code == 0
    result = runner.invoke(cli, ["env", "list", "--installed", "--plain"])
    assert "my-env" not in result.output


def test_version_bumpers():
    """Pure bump semantics (reference env.py:2010-2076)."""
    from prime_tpu.envhub.provenance import bump_patch, bump_post, bump_rc

    assert bump_patch("1.2.3") == "1.2.4"
    assert bump_patch("1.2.3rc1") == "1.2.4"  # pre-release suffix dropped
    assert bump_patch("1.2") == "1.2.1"
    assert bump_patch("7") == "7.0.1"
    assert bump_rc("1.2.3") == "1.2.3.rc0"
    assert bump_rc("1.2.3.rc0") == "1.2.3.rc1"
    assert bump_rc("1.2.3rc2") == "1.2.3.rc3"
    assert bump_post("1.2.3") == "1.2.3.post0"
    assert bump_post("1.2.3.post0") == "1.2.3.post1"
    assert bump_post("1.2.3+local") == "1.2.3.post0"


def test_push_auto_bump_roundtrips_versions(runner, fake, env_dir):
    """--auto-bump rewrites env.toml AND pyproject in place, and the hub
    records the bumped version; --rc/--post stack on top."""
    from prime_tpu.envhub.provenance import (
        read_env_toml_version,
        read_pyproject_version,
    )

    result = runner.invoke(cli, ["env", "push", "--dir", str(env_dir), "--auto-bump"])
    assert result.exit_code == 0, result.output
    assert "Auto-bumping version: 0.1.0 -> 0.1.1" in result.output
    assert "Pushed my-env@0.1.1" in result.output
    assert read_env_toml_version(env_dir) == "0.1.1"
    assert read_pyproject_version(env_dir) == "0.1.1"

    result = runner.invoke(cli, ["env", "push", "--dir", str(env_dir), "--rc"])
    assert result.exit_code == 0, result.output
    assert read_env_toml_version(env_dir) == "0.1.1.rc0"
    result = runner.invoke(cli, ["env", "versions", "my-env", "--output", "json"])
    versions = [v["version"] for v in json.loads(result.output)]
    assert "0.1.1" in versions and "0.1.1.rc0" in versions

    # mutually exclusive
    result = runner.invoke(
        cli, ["env", "push", "--dir", str(env_dir), "--auto-bump", "--post"]
    )
    assert result.exit_code == 2
    assert "mutually exclusive" in result.output


def test_bump_rewrites_only_the_right_table(env_dir):
    """A version key in an earlier unrelated table must never be touched."""
    from prime_tpu.envhub.provenance import bumped_version

    pyproject = env_dir / "pyproject.toml"
    pyproject.write_text(
        '[tool.something]\nversion = "9.9.9"\n\n' + pyproject.read_text()
    )
    old, new = bumped_version(env_dir, "patch")
    assert (old, new) == ("0.1.0", "0.1.1")
    content = pyproject.read_text()
    assert 'version = "9.9.9"' in content  # [tool.*] untouched
    assert content.count('version = "0.1.1"') == 1  # [project] bumped


def test_push_failure_rolls_back_bump(runner, fake, env_dir, monkeypatch):
    """A failed upload must not burn the bumped version number."""
    from prime_tpu.core.exceptions import APIError
    from prime_tpu.envhub.provenance import read_env_toml_version

    class FailingHub:
        def push(self, *a, **k):
            raise APIError("hub unreachable")

    monkeypatch.setattr(
        "prime_tpu.commands.env.build_hub_client", lambda: FailingHub()
    )
    result = runner.invoke(cli, ["env", "push", "--dir", str(env_dir), "--auto-bump"])
    assert result.exit_code != 0
    assert "rolled back" in result.output
    assert read_env_toml_version(env_dir) == "0.1.0"


def test_provenance_roundtrip_and_hash_exclusion(runner, fake, env_dir, tmp_path):
    """pull links the checkout to its upstream; push displays the link and
    refreshes it; inspect surfaces it in both output modes; the .prime/
    record never enters the content hash (else every pull would 'drift')."""
    from prime_tpu.envhub.provenance import read_provenance

    runner.invoke(cli, ["env", "push", "--dir", str(env_dir)])
    pull_dir = tmp_path / "checkout"
    result = runner.invoke(cli, ["env", "pull", "my-env", "--dir", str(pull_dir)])
    assert result.exit_code == 0, result.output

    record = read_provenance(pull_dir)
    assert record["name"] == "my-env" and record["source"] == "pull"
    # provenance is local state: hash matches the hub despite the new file
    assert content_hash(pull_dir) == content_hash(env_dir)

    # push from the linked checkout announces its upstream (bumped: the hub
    # rightly refuses same-version pushes with different content)
    (pull_dir / "data" / "eval.jsonl").write_text('{"question": "2+2?", "answer": "#### 4"}\n')
    result = runner.invoke(cli, ["env", "push", "--dir", str(pull_dir), "--auto-bump"])
    assert result.exit_code == 0, result.output
    assert "Using upstream environment my-env" in result.output
    assert read_provenance(pull_dir)["source"] == "push"

    # inspect renders the link in both modes
    result = runner.invoke(cli, ["env", "inspect", str(pull_dir), "--output", "json"])
    data = json.loads(result.output)
    assert data["upstream"].endswith("my-env")  # owner/name once the hub names an owner
    assert data["upstreamSource"] == "push"
    result = runner.invoke(cli, ["env", "inspect", str(pull_dir), "--plain"])
    assert "my-env" in result.output and "upstream" in result.output.lower()


def test_env_secrets_and_versions_cli(runner, fake, env_dir):
    runner.invoke(cli, ["env", "push", "--dir", str(env_dir)])
    assert runner.invoke(cli, ["env", "secrets", "set", "my-env", "HF_TOKEN", "tok"]).exit_code == 0
    result = runner.invoke(cli, ["env", "secrets", "list", "my-env", "--plain"])
    assert "HF_TOKEN" in result.output
    assert runner.invoke(cli, ["env", "secrets", "delete", "my-env", "HF_TOKEN"]).exit_code == 0

    result = runner.invoke(cli, ["env", "versions", "my-env", "--plain"])
    assert "0.1.0" in result.output
    result = runner.invoke(cli, ["env", "actions", "list", "my-env", "--plain"])
    assert "push" in result.output


def test_push_without_env_toml_fails_cleanly(runner, fake, tmp_path):
    result = runner.invoke(cli, ["env", "push", "--dir", str(tmp_path)])
    assert result.exit_code != 0
    assert "env.toml" in result.output


def test_env_init_cli(runner, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    result = runner.invoke(cli, ["env", "init", "fresh-env"])
    assert result.exit_code == 0
    assert (tmp_path / "fresh-env" / "env.toml").exists()
    assert (tmp_path / "fresh-env" / "fresh_env.py").exists()


def test_install_removes_stale_files(runner, fake, env_dir, tmp_path):
    runner.invoke(cli, ["env", "push", "--dir", str(env_dir)])
    runner.invoke(cli, ["env", "install", "my-env"])
    from prime_tpu.envhub.local import installs_dir

    stale = installs_dir() / "my-env" / "old_task.py"
    assert stale.parent.exists()
    # simulate a v2 that no longer contains a file present in v1's install
    (env_dir / "env.toml").write_text((env_dir / "env.toml").read_text().replace("0.1.0", "0.2.0"))
    runner.invoke(cli, ["env", "push", "--dir", str(env_dir)])
    stale.write_text("# leftover from v1")
    result = runner.invoke(cli, ["env", "install", "my-env"])
    assert result.exit_code == 0, result.output
    assert not stale.exists()


def test_pull_refuses_nonempty_dir(runner, fake, env_dir, tmp_path):
    runner.invoke(cli, ["env", "push", "--dir", str(env_dir)])
    target = tmp_path / "occupied"
    target.mkdir()
    (target / "keep.txt").write_text("mine")
    result = runner.invoke(cli, ["env", "pull", "my-env", "--dir", str(target)])
    assert result.exit_code != 0
    assert "not empty" in result.output
    assert (target / "keep.txt").read_text() == "mine"


def test_repush_identical_old_version_is_not_conflict(fake):
    """Per-version hashes: re-pushing identical v0.1.0 after v0.2.0 exists."""
    plane = fake.envhub_plane
    import base64, httpx

    def push(version, digest):
        return fake.handle(
            httpx.Request(
                "POST",
                "https://api.fake/api/v1/envhub/environments/push",
                headers={"Authorization": "Bearer test-key"},
                content=__import__("json").dumps(
                    {
                        "name": "e",
                        "version": version,
                        "contentHash": digest,
                        "archiveB64": base64.b64encode(b"x").decode(),
                    }
                ).encode(),
            )
        )

    assert push("0.1.0", "hashA").status_code == 200
    assert push("0.2.0", "hashB").status_code == 200
    assert push("0.1.0", "hashA").status_code == 200  # identical re-push ok
    assert push("0.1.0", "hashC").status_code == 409  # changed content conflicts


# -- environment execution protocol (reference verifiers_bridge.py:724-1088) --

EXAMPLE_ENV = "examples/verifiers_example"


def test_eval_run_executes_hub_env_end_to_end(runner, fake, tmp_path):
    """North-star protocol: push the example env, then `prime eval run
    arith-rl` resolves it from the hub, installs it, imports
    load_environment(), and its dataset drives the (oracle-free) generator."""
    import pathlib

    push = runner.invoke(cli, ["env", "push", "--dir", EXAMPLE_ENV])
    assert push.exit_code == 0, push.output
    out_dir = tmp_path / "outs"
    result = runner.invoke(
        cli,
        [
            "eval", "run", "arith-rl", "-m", "tiny-test", "--no-push",
            "-n", "4", "-b", "2", "--output-dir", str(out_dir), "--plain",
        ],
    )
    assert result.exit_code == 0, result.output
    assert "Resolved env arith-rl (hub@0.1.0, 4 examples)" in result.output.replace("  ", " ") or "Resolved env arith-rl" in result.output
    run_dirs = list(out_dir.glob("arith-rl--tiny-test/*/results.jsonl"))
    assert len(run_dirs) == 1
    lines = [json.loads(l) for l in run_dirs[0].read_text().splitlines() if l.strip()]
    assert len(lines) == 4
    # prompts came from the env's data/eval.jsonl, not the synthetic fallback
    records = [
        json.loads(l)
        for l in pathlib.Path(EXAMPLE_ENV, "data", "eval.jsonl").read_text().splitlines()
        if l.strip()
    ]
    assert any(r["question"] in lines[0]["prompt"] for r in records)
    # second run resolves from the installed store without re-downloading
    result2 = runner.invoke(
        cli,
        ["eval", "run", "arith-rl", "-m", "tiny-test", "--no-push", "-n", "2",
         "--output-dir", str(out_dir), "--plain"],
    )
    assert result2.exit_code == 0, result2.output
    assert "(installed" in result2.output


def test_eval_run_env_defaults_apply(runner, fake, tmp_path):
    """env.toml [eval] max_new_tokens=128 is used when the flag is defaulted."""
    runner.invoke(cli, ["env", "push", "--dir", EXAMPLE_ENV])
    out_dir = tmp_path / "outs"
    result = runner.invoke(
        cli,
        ["eval", "run", "arith-rl", "-m", "tiny-test", "--no-push", "-n", "2",
         "--output-dir", str(out_dir), "--output", "json"],
    )
    assert result.exit_code == 0, result.output
    meta = json.loads(next(out_dir.glob("arith-rl--tiny-test/*/metadata.json")).read_text())
    assert meta["spec"]["max_new_tokens"] == 128


def test_eval_run_drift_warning_for_stale_install(runner, fake, tmp_path):
    """Reinstall hint when the hub moves past the installed content hash."""
    src = tmp_path / "src-env"
    write_env_template(src, "drift-env")
    (src / "drift_env.py").write_text(
        "def load_environment():\n"
        "    return {'name': 'drift-env', 'examples': [{'prompt': 'p', 'answer': 'a'}]}\n"
    )
    assert runner.invoke(cli, ["env", "push", "--dir", str(src)]).exit_code == 0
    assert runner.invoke(cli, ["env", "install", "drift-env"]).exit_code == 0
    # hub moves on: bump version + content
    (src / "NEW.txt").write_text("new content")
    toml = (src / "env.toml").read_text().replace('version = "0.1.0"', 'version = "0.2.0"')
    (src / "env.toml").write_text(toml)
    assert runner.invoke(cli, ["env", "push", "--dir", str(src)]).exit_code == 0

    from prime_tpu.commands.env import build_hub_client
    from prime_tpu.envhub.execution import resolve_environment

    resolved = resolve_environment("drift-env", hub_client=build_hub_client())
    assert resolved.source == "installed"
    assert resolved.drift and "stale" in resolved.drift


def test_eval_run_local_dir_drift_warning(runner, fake, tmp_path):
    """A local env dir that diverged from its hub version warns (local wins)."""
    src = tmp_path / "local-env"
    write_env_template(src, "local-env")
    (src / "local_env.py").write_text(
        "def load_environment():\n"
        "    return {'name': 'local-env', 'examples': [{'prompt': 'p', 'answer': 'a'}]}\n"
    )
    assert runner.invoke(cli, ["env", "push", "--dir", str(src)]).exit_code == 0
    (src / "local_change.txt").write_text("diverged")

    from prime_tpu.commands.env import build_hub_client
    from prime_tpu.envhub.execution import resolve_environment

    resolved = resolve_environment(str(src), hub_client=build_hub_client())
    assert resolved.source == "local"
    assert resolved.drift and "LOCAL" in resolved.drift


def test_env_custom_scorer_drives_rewards(runner, fake, tmp_path):
    """An env-provided score() sets sample rewards instead of exact match."""
    src = tmp_path / "scored-env"
    write_env_template(src, "scored-env")
    (src / "scored_env.py").write_text(
        "def load_environment():\n"
        "    return {\n"
        "        'name': 'scored-env',\n"
        "        'examples': [{'prompt': 'say hi', 'answer': 'hi'}] * 2,\n"
        "        'score': lambda completion, answer: 0.75,\n"
        "    }\n"
    )
    from prime_tpu.envhub.execution import load_environment, resolve_environment
    from prime_tpu.evals.datasets import EvalExample
    from prime_tpu.evals.runner import EvalRunSpec, run_eval

    resolved = resolve_environment(str(src))
    loaded = load_environment(resolved)
    examples = [
        EvalExample(question=e["prompt"], answer=e["answer"], prompt=e["prompt"])
        for e in loaded.examples
    ]

    class Oracle:
        def generate(self, prompts, max_new_tokens, temperature):
            return ["whatever"] * len(prompts)

    result = run_eval(
        EvalRunSpec(env="scored-env", model="oracle", limit=2, output_dir=str(tmp_path / "o")),
        generator=Oracle(),
        examples=examples,
        scorer=loaded.scorer,
    )
    assert all(s.reward == 0.75 for s in result.samples)
    assert all(s.correct for s in result.samples)  # 0.75 >= 0.5


def test_env_inspect_cli(runner, fake):
    runner.invoke(cli, ["env", "push", "--dir", EXAMPLE_ENV])
    result = runner.invoke(cli, ["env", "inspect", EXAMPLE_ENV, "--output", "json"])
    assert result.exit_code == 0, result.output
    data = json.loads(result.output)
    assert data["name"] == "arith-rl"
    assert data["loadEnvironment"] == "ok"
    assert data["examples"] == 16
    assert data["source"] == "local"


def test_env_actions_logs_and_retry_cli(runner, fake, env_dir):
    runner.invoke(cli, ["env", "push", "--dir", str(env_dir)])
    listed = runner.invoke(cli, ["env", "actions", "list", "my-env", "--output", "json"])
    actions = json.loads(listed.output)
    assert actions and actions[0]["action"] == "push"
    action_id = actions[0]["id"]
    logs = runner.invoke(cli, ["env", "actions", "logs", "my-env", action_id, "--plain"])
    assert "build finished" in logs.output
    retry = runner.invoke(cli, ["env", "actions", "retry", "my-env", action_id, "--plain"])
    assert retry.exit_code == 0 and "Retried" in retry.output
    relisted = json.loads(runner.invoke(cli, ["env", "actions", "list", "my-env", "--output", "json"]).output)
    assert len(relisted) == 2


def test_install_pip_installs_into_env_site(runner, fake, env_dir):
    runner.invoke(cli, ["env", "push", "--dir", str(env_dir)])
    result = runner.invoke(cli, ["env", "install", "my-env", "--output", "json"])
    assert result.exit_code == 0, result.output
    data = json.loads(result.output)
    from prime_tpu.envhub.execution import env_site_dir

    if data["pipInstalled"]:
        assert (env_site_dir() / "my_env.py").exists() or list(env_site_dir().glob("my_env*"))
    else:
        assert "installNote" in data


def test_builtin_labels_never_resolve_as_envs(runner, fake, tmp_path):
    """A hub env named 'gsm8k' must not shadow the built-in dataset label."""
    src = tmp_path / "impostor"
    write_env_template(src, "gsm8k")
    (src / "gsm8k.py").write_text(
        "def load_environment():\n"
        "    return {'name': 'gsm8k', 'examples': [{'prompt': 'x', 'answer': 'y'}]}\n"
    )
    runner.invoke(cli, ["env", "push", "--dir", str(src)])
    out_dir = tmp_path / "outs"
    result = runner.invoke(
        cli,
        ["eval", "run", "gsm8k", "-m", "tiny-test", "--no-push", "-n", "2",
         "--output-dir", str(out_dir), "--plain"],
    )
    assert result.exit_code == 0, result.output
    assert "Resolved env" not in result.output  # synthetic/builtin path ran


def test_explicit_dataset_beats_env_resolution(runner, fake, tmp_path):
    """--dataset wins: the env's bundled data must not silently replace it."""
    runner.invoke(cli, ["env", "push", "--dir", EXAMPLE_ENV])
    custom = tmp_path / "custom.jsonl"
    custom.write_text('{"question": "7*3?", "answer": "#### 21"}\n' * 3)
    out_dir = tmp_path / "outs"
    result = runner.invoke(
        cli,
        ["eval", "run", "arith-rl", "-m", "tiny-test", "--no-push", "-n", "3",
         "--dataset", str(custom), "--output-dir", str(out_dir), "--plain"],
    )
    assert result.exit_code == 0, result.output
    assert "Resolved env" not in result.output
    lines = next(out_dir.glob("arith-rl--tiny-test/*/results.jsonl")).read_text()
    assert "7*3?" in lines


def test_inspect_uninstalled_hub_env(runner, fake, env_dir):
    runner.invoke(cli, ["env", "push", "--dir", str(env_dir)])
    result = runner.invoke(cli, ["env", "inspect", "my-env", "--plain"])
    assert result.exit_code == 0, result.output
    assert "hub (not installed)" in result.output


def test_train_local_rl_runs_env_protocol(runner, fake, tmp_path):
    """`prime train local-rl <env>` drives GRPO with the environment execution
    protocol: the hub env's dataset and scorer supply prompts and rewards."""
    push = runner.invoke(cli, ["env", "push", "--dir", EXAMPLE_ENV])
    assert push.exit_code == 0, push.output
    result = runner.invoke(
        cli,
        ["train", "local-rl", "arith-rl", "-m", "tiny-test", "--steps", "2",
         "-g", "2", "-p", "2", "--max-prompt-len", "24", "--max-new-tokens", "4",
         "--name", "rl-env-run", "--output-dir", str(tmp_path / "rl"), "--plain"],
    )
    assert result.exit_code == 0, result.output
    assert "Resolved env arith-rl" in result.output
    metrics = (tmp_path / "rl" / "rl-env-run" / "metrics.jsonl").read_text().splitlines()
    assert len(metrics) == 2
