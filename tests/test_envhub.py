"""Environments Hub: packaging, hashing, push/pull/install round trips."""

import json

import pytest
from click.testing import CliRunner

import prime_tpu.commands._deps as deps
from prime_tpu.commands.main import cli
from prime_tpu.envhub.packaging import (
    build_archive,
    content_hash,
    extract_archive,
    iter_env_files,
    read_env_metadata,
    write_env_template,
)
from prime_tpu.testing import FakeControlPlane


@pytest.fixture
def fake(monkeypatch):
    fake = FakeControlPlane()
    monkeypatch.setattr(deps, "transport_override", fake.transport)
    monkeypatch.setenv("PRIME_API_KEY", "test-key")
    monkeypatch.setenv("PRIME_BASE_URL", "https://api.fake")
    return fake


@pytest.fixture
def runner():
    return CliRunner()


@pytest.fixture
def env_dir(tmp_path):
    d = tmp_path / "my-env"
    write_env_template(d, "my-env")
    (d / "data").mkdir()
    (d / "data" / "eval.jsonl").write_text('{"question": "1+1?", "answer": "#### 2"}\n')
    return d


def test_template_and_metadata(env_dir):
    metadata = read_env_metadata(env_dir)
    assert metadata["name"] == "my-env"
    assert metadata["tpu"]["tpu_type"] == "v5e"


def test_gitignore_filtering(env_dir):
    (env_dir / "__pycache__").mkdir()
    (env_dir / "__pycache__" / "junk.pyc").write_text("x")
    (env_dir / ".gitignore").write_text("scratch/\n*.log\n")
    (env_dir / "scratch").mkdir()
    (env_dir / "scratch" / "tmp.txt").write_text("x")
    (env_dir / "debug.log").write_text("x")
    files = [f.name for f in iter_env_files(env_dir)]
    assert "junk.pyc" not in files and "tmp.txt" not in files and "debug.log" not in files
    assert "env.toml" in files


def test_content_hash_is_deterministic_and_drift_sensitive(env_dir):
    h1 = content_hash(env_dir)
    assert h1 == content_hash(env_dir)
    (env_dir / "data" / "eval.jsonl").write_text('{"question": "2+2?", "answer": "#### 4"}\n')
    assert content_hash(env_dir) != h1


def test_archive_roundtrip_and_determinism(env_dir, tmp_path):
    a1 = build_archive(env_dir)
    a2 = build_archive(env_dir)
    assert a1 == a2  # byte-identical (zeroed mtimes)
    out = tmp_path / "extracted"
    extract_archive(a1, out)
    assert (out / "env.toml").read_text() == (env_dir / "env.toml").read_text()
    assert (out / "data" / "eval.jsonl").exists()


def test_push_pull_install_cli_roundtrip(runner, fake, env_dir, tmp_path, monkeypatch):
    result = runner.invoke(cli, ["env", "push", "--dir", str(env_dir)])
    assert result.exit_code == 0, result.output
    assert "Pushed my-env@0.1.0" in result.output

    # idempotent push: unchanged content is detected by hash
    result = runner.invoke(cli, ["env", "push", "--dir", str(env_dir)])
    assert "unchanged" in result.output

    result = runner.invoke(cli, ["env", "list", "--output", "json"])
    envs = json.loads(result.output)
    assert envs[0]["name"] == "my-env"

    pull_dir = tmp_path / "pulled"
    result = runner.invoke(cli, ["env", "pull", "my-env", "--dir", str(pull_dir)])
    assert result.exit_code == 0, result.output
    assert (pull_dir / "data" / "eval.jsonl").exists()

    result = runner.invoke(cli, ["env", "install", "my-env"])
    assert result.exit_code == 0, result.output
    result = runner.invoke(cli, ["env", "list", "--installed", "--plain"])
    assert "my-env" in result.output

    result = runner.invoke(cli, ["env", "uninstall", "my-env"])
    assert result.exit_code == 0
    result = runner.invoke(cli, ["env", "list", "--installed", "--plain"])
    assert "my-env" not in result.output


def test_env_secrets_and_versions_cli(runner, fake, env_dir):
    runner.invoke(cli, ["env", "push", "--dir", str(env_dir)])
    assert runner.invoke(cli, ["env", "secrets", "set", "my-env", "HF_TOKEN", "tok"]).exit_code == 0
    result = runner.invoke(cli, ["env", "secrets", "list", "my-env", "--plain"])
    assert "HF_TOKEN" in result.output
    assert runner.invoke(cli, ["env", "secrets", "delete", "my-env", "HF_TOKEN"]).exit_code == 0

    result = runner.invoke(cli, ["env", "versions", "my-env", "--plain"])
    assert "0.1.0" in result.output
    result = runner.invoke(cli, ["env", "actions", "my-env", "--plain"])
    assert "push" in result.output


def test_push_without_env_toml_fails_cleanly(runner, fake, tmp_path):
    result = runner.invoke(cli, ["env", "push", "--dir", str(tmp_path)])
    assert result.exit_code != 0
    assert "env.toml" in result.output


def test_env_init_cli(runner, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    result = runner.invoke(cli, ["env", "init", "fresh-env"])
    assert result.exit_code == 0
    assert (tmp_path / "fresh-env" / "env.toml").exists()
    assert (tmp_path / "fresh-env" / "fresh_env.py").exists()


def test_install_removes_stale_files(runner, fake, env_dir, tmp_path):
    runner.invoke(cli, ["env", "push", "--dir", str(env_dir)])
    runner.invoke(cli, ["env", "install", "my-env"])
    from prime_tpu.commands.env import installs_dir

    stale = installs_dir() / "my-env" / "old_task.py"
    assert stale.parent.exists()
    # simulate a v2 that no longer contains a file present in v1's install
    (env_dir / "env.toml").write_text((env_dir / "env.toml").read_text().replace("0.1.0", "0.2.0"))
    runner.invoke(cli, ["env", "push", "--dir", str(env_dir)])
    stale.write_text("# leftover from v1")
    result = runner.invoke(cli, ["env", "install", "my-env"])
    assert result.exit_code == 0, result.output
    assert not stale.exists()


def test_pull_refuses_nonempty_dir(runner, fake, env_dir, tmp_path):
    runner.invoke(cli, ["env", "push", "--dir", str(env_dir)])
    target = tmp_path / "occupied"
    target.mkdir()
    (target / "keep.txt").write_text("mine")
    result = runner.invoke(cli, ["env", "pull", "my-env", "--dir", str(target)])
    assert result.exit_code != 0
    assert "not empty" in result.output
    assert (target / "keep.txt").read_text() == "mine"


def test_repush_identical_old_version_is_not_conflict(fake):
    """Per-version hashes: re-pushing identical v0.1.0 after v0.2.0 exists."""
    plane = fake.envhub_plane
    import base64, httpx

    def push(version, digest):
        return fake.handle(
            httpx.Request(
                "POST",
                "https://api.fake/api/v1/envhub/environments/push",
                headers={"Authorization": "Bearer test-key"},
                content=__import__("json").dumps(
                    {
                        "name": "e",
                        "version": version,
                        "contentHash": digest,
                        "archiveB64": base64.b64encode(b"x").decode(),
                    }
                ).encode(),
            )
        )

    assert push("0.1.0", "hashA").status_code == 200
    assert push("0.2.0", "hashB").status_code == 200
    assert push("0.1.0", "hashA").status_code == 200  # identical re-push ok
    assert push("0.1.0", "hashC").status_code == 409  # changed content conflicts
