"""Lab TUI shell: key handling, three-pane rendering, refresh, launch cards."""

import json

import pytest

from prime_tpu.core.client import APIClient
from prime_tpu.core.config import Config
from prime_tpu.lab.data import LabDataSource
from prime_tpu.lab.tui import PrimeLabApp, render_text
from prime_tpu.lab.tui.app import SECTIONS
from prime_tpu.lab.tui.keys import decode_key
from prime_tpu.testing import FakeControlPlane

from _markers import get_tomllib


@pytest.fixture
def fake():
    return FakeControlPlane()


@pytest.fixture
def api(fake):
    cfg = Config()
    cfg.api_key = "test-key"
    return APIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)


@pytest.fixture
def app(fake, api, tmp_path):
    source = LabDataSource(tmp_path, api_client=api)
    return PrimeLabApp(data_source=source, workspace=tmp_path, api_client=api)


def _local_run(tmp_path, env="gsm8k", model="m1", run="r1", accuracy=0.5, n_samples=None):
    """One local eval run dir; n_samples also writes a results.jsonl."""
    run_dir = tmp_path / "outputs" / "evals" / f"{env}--{model}" / run
    run_dir.mkdir(parents=True)
    (run_dir / "metadata.json").write_text(
        json.dumps({"metrics": {"accuracy": accuracy, "num_samples": n_samples or 4}})
    )
    if n_samples:
        with open(run_dir / "results.jsonl", "w") as f:
            for i in range(n_samples):
                f.write(
                    json.dumps(
                        {"prompt": f"p{i}", "completion": "c", "reward": float(accuracy), "correct": True}
                    )
                    + "\n"
                )
    return run_dir


# -- key decoding -------------------------------------------------------------


@pytest.mark.parametrize(
    "data,key",
    [
        (b"\r", "enter"),
        (b"\t", "tab"),
        (b"q", "q"),
        (b"\x1b[A", "up"),
        (b"\x1b[B", "down"),
        (b"\x1b", "escape"),
        (b"\x03", "ctrl+c"),
        (b"\x1b[Z", None),  # unbound sequence ignored
        (b"\x00", None),
    ],
)
def test_decode_key(data, key):
    assert decode_key(data) == key


# -- navigation ---------------------------------------------------------------


def test_nav_cycles_sections_and_digit_jump(app):
    assert app.section == SECTIONS[0]
    app.on_key("down")
    assert app.section == SECTIONS[1]
    app.on_key("up")
    app.on_key("up")
    assert app.section == SECTIONS[-1]  # wraps
    app.on_key("3")
    assert app.section == SECTIONS[2] and app.focus == "rows"


def test_cursor_clamps_to_rows(app, tmp_path):
    _local_run(tmp_path, run="r1")
    _local_run(tmp_path, run="r2")
    app.tick()
    app.focus = "rows"
    app.on_key("down")
    app.on_key("down")
    app.on_key("down")
    assert app.cursors["local-runs"] == 1  # clamped to 2 rows
    app.on_key("g")
    assert app.cursors["local-runs"] == 0
    app.on_key("G")
    assert app.cursors["local-runs"] == 1


def test_quit_key(app):
    app.on_key("q")
    assert app.quit


# -- rendering ----------------------------------------------------------------


def test_render_three_panes_headless(app, tmp_path):
    _local_run(tmp_path, env="arith", model="tiny", run="r9", accuracy=1.0)
    app.tick()
    text = render_text(app)
    assert "PRIME LAB" in text
    assert "sections" in text and "inspector" in text
    assert "Local eval runs" in text
    assert "arith" in text and "r9" in text
    # nav shows counts for every section
    assert "Launch cards (0)" in text


def test_render_empty_section(app):
    app.on_key("7")  # sandboxes (no fetch yet)
    text = render_text(app)
    assert "(empty)" in text


def test_inspector_shows_selected_row(app, tmp_path):
    _local_run(tmp_path, env="arith", model="tiny", run="zzz", accuracy=0.25)
    app.tick()
    app.focus = "rows"
    text = render_text(app)
    assert "zzz" in text
    assert "0.250" in text  # float formatting in inspector/table


# -- refresh ------------------------------------------------------------------


def test_refresh_all_hydrates_platform_sections(app, fake, api):
    # create a sandbox through the SDK so the platform has a row
    from prime_tpu.sandboxes import SandboxClient
    from prime_tpu.sandboxes.models import CreateSandboxRequest

    SandboxClient(client=api).create(CreateSandboxRequest())
    app.on_key("R")
    assert app.status == "refreshed"
    rows = app.snapshot.platform["sandboxes"]
    assert len(rows) == 1
    app.on_key("7")
    text = render_text(app)
    assert rows[0]["sandboxId"][:12] in text


def test_refresh_errors_reported_in_status(app, monkeypatch):
    def boom():
        raise RuntimeError("plane down")

    monkeypatch.setattr(app.data, "_fetch_pods", boom)
    app.on_key("6")  # pods
    app.on_key("r")
    assert "pods: plane down" in app.status


# -- launch cards -------------------------------------------------------------


def _write_card(tmp_path, name="card1", kind="eval"):
    launch = tmp_path / ".prime-lab" / "launch"
    launch.mkdir(parents=True, exist_ok=True)
    (launch / f"{name}.toml").write_text(
        f'[launch]\nkind = "{kind}"\nname = "{name}"\n\n'
        f"[{kind}]\n"
        + ('env = "arith"\nmodel = "tiny-test"\n' if kind == "eval" else 'model = "llama3-8b"\nenvId = "env_x"\n')
    )


def test_launch_section_lists_cards(app, tmp_path):
    _write_card(tmp_path, "nightly", "eval")
    app.on_key("8")  # launch section
    text = render_text(app)
    assert "nightly" in text and "eval" in text


def test_launch_requires_arm_then_submits(app, tmp_path, fake):
    _write_card(tmp_path, "nightly", "eval")
    app.on_key("8")
    app.focus = "rows"
    app.on_key("enter")
    assert "press enter again" in app.status
    assert not fake.evals_plane.hosted
    app.on_key("enter")
    assert "launched eval heval_" in app.status
    assert len(fake.evals_plane.hosted) == 1


def test_launch_disarms_on_move_or_escape(app, tmp_path, fake):
    _write_card(tmp_path, "a-card", "eval")
    _write_card(tmp_path, "b-card", "eval")
    app.on_key("8")
    app.focus = "rows"
    app.on_key("enter")
    app.on_key("down")  # moving disarms
    app.on_key("enter")
    assert "press enter again" in app.status
    app.on_key("escape")
    assert "disarmed" in app.status
    assert not fake.evals_plane.hosted


def test_malformed_card_ignored(app, tmp_path):
    launch = tmp_path / ".prime-lab" / "launch"
    launch.mkdir(parents=True)
    (launch / "broken.toml").write_text("not [ valid toml")
    (launch / "wrongkind.toml").write_text('[launch]\nkind = "dance"\n')
    app.on_key("8")
    assert app.rows() == []


# -- CLI entry ----------------------------------------------------------------


def test_lab_tui_requires_tty(fake, monkeypatch):
    from click.testing import CliRunner

    import prime_tpu.commands._deps as deps
    from prime_tpu.commands.main import cli

    monkeypatch.setattr(deps, "transport_override", fake.transport)
    monkeypatch.setenv("PRIME_API_KEY", "test-key")
    monkeypatch.setenv("PRIME_BASE_URL", "https://api.fake")
    result = CliRunner().invoke(cli, ["lab", "tui"])
    assert result.exit_code != 0
    assert "interactive terminal" in result.output


def test_decode_keys_batched():
    from prime_tpu.lab.tui.keys import decode_keys

    assert decode_keys(b"jjj") == ["j", "j", "j"]
    assert decode_keys(b"\x1b[A\x1b[A") == ["up", "up"]
    assert decode_keys(b"j\x1b[Bq") == ["j", "down", "q"]
    assert decode_keys(b"\x1b[Zjq") == ["j", "q"]  # unknown CSI swallowed
    assert decode_keys(b"\x1bq") == ["escape", "q"]


def test_view_explicit_bad_target_errors(fake, monkeypatch, tmp_path):
    from click.testing import CliRunner

    import prime_tpu.commands._deps as deps
    from prime_tpu.commands.main import cli

    monkeypatch.setattr(deps, "transport_override", fake.transport)
    monkeypatch.setenv("PRIME_API_KEY", "test-key")
    monkeypatch.setenv("PRIME_BASE_URL", "https://api.fake")
    result = CliRunner().invoke(cli, ["eval", "view", str(tmp_path / "nope-typo")])
    assert result.exit_code != 0
    assert "not a run directory" in result.output


# -- training charts (reference training_charts.py role) ----------------------


def _training_run(tmp_path, name="run1", steps=20):
    import math

    run = tmp_path / "outputs" / "train" / name
    run.mkdir(parents=True)
    with open(run / "metrics.jsonl", "w") as f:
        for step in range(steps):
            f.write(json.dumps({
                "step": step,
                "loss": 5.0 * math.exp(-step / 7) + 1.0,
                "grad_norm": 2.0,
                "tokens_per_sec": 1000.0 + step,
                "step_time_s": 0.1,
            }) + "\n")


def test_sparkline_shapes():
    from prime_tpu.lab.tui.charts import sparkline

    assert sparkline([]) == ""
    assert sparkline([1.0, 1.0, 1.0]) == "▁▁▁"
    line = sparkline([0, 1, 2, 3, 4, 5, 6, 7])
    assert line[0] == "▁" and line[-1] == "█" and len(line) == 8
    assert len(sparkline(list(range(1000)), width=40)) == 40


def test_local_training_section_and_chart(app, tmp_path):
    _training_run(tmp_path, "sweep-a")
    app.tick()
    app.on_key("2")  # local-training section
    rows = app.rows()
    assert rows and rows[0]["run"] == "sweep-a" and rows[0]["steps"] == 19
    text = render_text(app)
    assert "Local training" in text and "sweep-a" in text
    assert "loss" in text and "▁" in text  # sparkline rendered in inspector
    assert "tokens_per_sec" in text


def test_training_chart_lines_skip_missing_metrics():
    from prime_tpu.lab.tui.charts import training_chart_lines

    rows = [{"step": i, "loss": 3.0 - i * 0.1} for i in range(10)]
    lines = training_chart_lines(rows)
    assert len(lines) == 1 and lines[0].strip().startswith("loss")


def test_metrics_scan_survives_partial_tail_line(app, tmp_path):
    _training_run(tmp_path, "mid-write", steps=5)
    path = tmp_path / "outputs" / "train" / "mid-write" / "metrics.jsonl"
    with open(path, "a") as f:
        f.write('{"step": 5, "loss": 1.')  # torn append
    app.tick()
    app.on_key("2")
    rows = app.rows()
    assert rows and rows[0]["steps"] == 4  # parsed rows kept, tail skipped


def test_metrics_scan_caches_on_mtime(app, tmp_path, monkeypatch):
    _training_run(tmp_path, "cached", steps=5)
    app.tick()
    calls = {"n": 0}
    original = json.loads

    def counting_loads(*a, **k):
        calls["n"] += 1
        return original(*a, **k)

    monkeypatch.setattr(json, "loads", counting_loads)
    import prime_tpu.lab.data as data_mod

    monkeypatch.setattr(data_mod.json, "loads", counting_loads)
    app.tick()  # unchanged file: no re-parse
    assert calls["n"] == 0


def test_sparkline_last_bucket_includes_newest_sample():
    from prime_tpu.lab.tui.charts import sparkline

    # huge final spike must show in the last cell even with inexact buckets
    values = [0.0] * 999 + [100.0]
    assert sparkline(values, width=48)[-1] != "▁"


def test_block_chart_levels_and_shape():
    from prime_tpu.lab.tui.charts import block_chart

    rows = block_chart([0.0, 0.5, 1.0], width=10, height=4)
    assert len(rows) == 4 and all(len(r) == 3 for r in rows)
    # max column fills the top row; min column only the bottom's smallest block
    assert rows[0][2] == "█" and rows[0][0] == " "
    assert rows[3][0] == "▁"
    # constant series renders mid-height, not empty
    flat = block_chart([2.0, 2.0, 2.0], width=10, height=4)
    assert any(ch != " " for r in flat for ch in r)


def test_ema_and_adaptive_retention():
    from prime_tpu.lab.tui.charts import adaptive_retention, ema

    assert ema([], 0.9) == []
    assert ema([1.0, 1.0, 1.0], 0.9) == [1.0, 1.0, 1.0]
    smoothed = ema([0.0, 10.0], 0.5)
    assert smoothed == [0.0, 5.0]
    assert adaptive_retention(4) == 0.0          # short series stay raw
    assert 0.9 < adaptive_retention(1000) <= 0.98


def test_chart_panel_labels_window_and_smooth():
    from prime_tpu.lab.tui.charts import chart_panel

    rows = [{"step": i, "loss": 10.0 - i * 0.01} for i in range(600)]
    panel = chart_panel(rows, "loss", width=40, height=4)
    assert panel[0][0] == "bold" and "last=4.01" in panel[0][1]
    assert "step 0 → 599" in panel[-1][1]
    # window keeps only the tail
    windowed = chart_panel(rows, "loss", width=40, height=4, window=128)
    assert "step 472 → 599 (128 pts)" in windowed[-1][1]
    # smoothing tags the title but stats stay raw
    smooth = chart_panel(rows, "loss", width=40, height=4, smooth=True)
    assert "(ema)" in smooth[0][1] and "last=4.01" in smooth[0][1]
    # missing metric or too-few points -> empty
    assert chart_panel(rows, "absent") == []
    assert chart_panel(rows[:1], "loss") == []


def test_chart_panel_gutter_matches_bucketed_columns():
    from prime_tpu.lab.tui.charts import chart_panel

    # one 9.0 outlier in ~1.0 noise, 600 pts into 40 buckets: the outlier's
    # bucket mean is ~1.5, so the axis label must NOT claim the chart shows 9
    rows = [{"step": i, "reward": 9.0 if i == 300 else 1.0} for i in range(600)]
    panel = chart_panel(rows, "reward", width=40, height=4)
    top_label = panel[1][1].split()[0]
    assert float(top_label) < 2.0
    assert "max=9" in panel[0][1]  # the stats line still reports the raw max


def test_discover_metrics_order_and_exclusions():
    from prime_tpu.lab.tui.charts import discover_metrics

    rows = [
        {"step": 1, "ts": 123.0, "tokens_per_sec": 900.0, "loss": 2.0, "reward_mean": 0.5},
        {"step": 2, "flag": True, "note": "text", "grad_norm": 1.0},
    ]
    keys = discover_metrics(rows)
    assert keys[0] in ("loss", "reward_mean") and keys[1] in ("loss", "reward_mean")
    assert "step" not in keys and "ts" not in keys
    assert "flag" not in keys and "note" not in keys
    assert "tokens_per_sec" in keys and "grad_norm" in keys


def test_training_detail_block_chart_smooth_and_window(app, tmp_path):
    run_dir = tmp_path / "outputs" / "train" / "runZ"
    run_dir.mkdir(parents=True)
    with open(run_dir / "metrics.jsonl", "w") as f:
        for step in range(200):
            f.write(json.dumps({"step": step, "loss": 5.0 - step * 0.01}) + "\n")
    app.tick()
    app.on_key("2")
    app.on_key("enter")
    detail = app.screens[-1]
    text = render_text(app)
    assert "last=3.01" in text and "step 0 → 199" in text
    app.on_key("s")
    assert detail.smooth and "(ema)" in render_text(app)
    app.on_key("]")          # zoom in one step on the window ladder
    assert "last 512" in app.status or "last 128" in app.status
    app.on_key("[")
    assert detail.window_idx == 0
    app.on_key("escape")


def test_eval_tui_requires_tty(fake, monkeypatch):
    from click.testing import CliRunner

    import prime_tpu.commands._deps as deps
    from prime_tpu.commands.main import cli

    monkeypatch.setattr(deps, "transport_override", fake.transport)
    monkeypatch.setenv("PRIME_API_KEY", "test-key")
    monkeypatch.setenv("PRIME_BASE_URL", "https://api.fake")
    result = CliRunner().invoke(cli, ["eval", "tui"])
    assert result.exit_code != 0
    assert "interactive terminal" in result.output


# -- detail screens (VERDICT r2 #3: section -> row -> detail -> back) ---------


def _run_with_samples(tmp_path, n=4):
    run_dir = _local_run(tmp_path)
    with open(run_dir / "results.jsonl", "w") as f:
        for i in range(n):
            f.write(
                json.dumps(
                    {
                        "prompt": f"what is {i}+{i}?",
                        "completion": str(2 * i),
                        "answer": str(2 * i) if i % 2 == 0 else "nope",
                        "reward": 1.0 if i % 2 == 0 else 0.0,
                        "correct": i % 2 == 0,
                    }
                )
                + "\n"
            )
    return run_dir


def test_eval_detail_drilldown_and_back(app, tmp_path):
    _run_with_samples(tmp_path)
    app.tick()
    app.on_key("1")          # local-runs section, rows focus
    app.on_key("enter")      # drill into the run overview
    assert app.screens and "eval:" in app.screens[-1].title
    text = render_text(app)
    assert "pass rate" in text and "50.0%" in text and "reward dist" in text
    app.on_key("enter")      # overview -> sample browser
    text = render_text(app)
    assert "sample 1/4" in text and "what is 0+0?" in text
    app.on_key("n")          # next sample
    assert "sample 2/4" in render_text(app)
    app.on_key("escape")     # back to the overview
    assert app.screens and app.screens[-1].__class__.__name__ == "EvalRunOverview"
    app.on_key("escape")     # back to the shell
    assert not app.screens
    assert "Local eval runs" in render_text(app)


def test_eval_detail_filter_and_search(app, tmp_path):
    _run_with_samples(tmp_path)
    app.tick()
    app.on_key("1")
    app.on_key("enter")      # overview
    app.on_key("enter")      # sample browser
    browser = app.screens[-1]
    app.on_key("f")          # all -> correct
    assert browser.filter_mode == "correct" and len(browser.visible()) == 2
    app.on_key("f")          # correct -> incorrect
    assert browser.filter_mode == "incorrect"
    assert all(not browser.samples[i]["correct"] for i in browser.visible())
    app.on_key("f")          # back to all
    for ch in ("/", "3", "+", "3"):
        app.on_key(ch)
    app.on_key("enter")      # jump to the sample containing "3+3"
    assert browser.samples[browser.idx]["prompt"] == "what is 3+3?"
    # 'q' during search input types a literal q instead of quitting
    app.on_key("/")
    app.on_key("q")
    assert not app.quit and browser.search_input == "q"
    app.on_key("escape")     # cancel search input
    assert browser.search_input is None and app.screens


def test_eval_overview_reload_sees_appended_rows(app, tmp_path):
    run_dir = _run_with_samples(tmp_path)
    app.tick()
    app.on_key("1")
    app.on_key("enter")      # overview
    overview = app.screens[-1]
    assert overview.overview.n_samples == 4
    with open(run_dir / "results.jsonl", "a") as f:
        f.write(json.dumps({"prompt": "late", "completion": "x", "reward": 1.0, "correct": True}) + "\n")
    app.on_key("r")
    assert overview.overview.n_samples == 5
    assert "5 samples" in app.status


def test_sample_browser_markdown_toggle(app, tmp_path):
    run_dir = _local_run(tmp_path)
    with open(run_dir / "results.jsonl", "w") as f:
        f.write(
            json.dumps(
                {
                    "prompt": r"compute $\frac{1}{2}$ of **eight**",
                    "completion": "```python\nprint(4)\n```",
                    "answer": "4",
                    "reward": 1.0,
                    "correct": True,
                }
            )
            + "\n"
        )
    app.tick()
    app.on_key("1")
    app.on_key("enter")      # overview
    app.on_key("enter")      # browser
    text = render_text(app)
    assert r"$\frac{1}{2}$" in text          # raw by default
    app.on_key("m")
    text = render_text(app)
    assert "(1)/(2) of eight" in text         # math + bold rendered
    assert "print(4)" in text
    app.on_key("m")
    assert r"$\frac{1}{2}$" in render_text(app)


def test_sample_browser_chat_messages_sections(app, tmp_path):
    """Multi-turn rollouts (a `messages` list) render one section per role
    turn, including part-list content; search spans the turns."""
    run_dir = _local_run(tmp_path)
    with open(run_dir / "results.jsonl", "w") as f:
        f.write(
            json.dumps(
                {
                    "messages": [
                        {"role": "system", "content": "be terse"},
                        {"role": "user", "content": [{"type": "text", "text": "what is 2+2?"}]},
                        {"role": "assistant", "content": "4"},
                    ],
                    "answer": "4",
                    "reward": 1.0,
                    "correct": True,
                }
            )
            + "\n"
        )
        f.write(json.dumps({"prompt": "plain row", "completion": "x", "reward": 0.0, "correct": False}) + "\n")
    app.tick()
    app.on_key("1")
    app.on_key("enter")      # overview
    app.on_key("enter")      # browser
    text = render_text(app)
    assert "SYSTEM" in text and "USER" in text and "ASSISTANT" in text
    assert "what is 2+2?" in text            # part-list content flattened
    assert "PROMPT" not in text              # chat rows don't show the flat labels
    # search reaches message turns and jumps across row shapes
    for ch in "/plain":
        app.on_key(ch)
    app.on_key("enter")
    assert "match at sample 2/2" in app.status
    assert "plain row" in render_text(app) and "PROMPT" in render_text(app)


def test_training_detail_tabs_and_reload(app, tmp_path):
    run_dir = tmp_path / "outputs" / "train" / "run1"
    run_dir.mkdir(parents=True)
    with open(run_dir / "metrics.jsonl", "w") as f:
        for step in range(6):
            f.write(json.dumps({"step": step, "loss": 3.0 - step * 0.3, "tokens_per_sec": 900.0 + step}) + "\n")
    (run_dir / "config.json").write_text(json.dumps({"model": "tiny-test", "lr": 3e-4}))
    (run_dir / "train.log").write_text("line-a\nline-b\n")
    app.tick()
    app.on_key("2")          # local-training
    app.on_key("enter")
    assert app.screens and "training:" in app.screens[-1].title
    text = render_text(app)
    assert "loss" in text   # chart tab renders metric sparkline
    app.on_key("tab")        # -> config
    text = render_text(app)
    assert "tiny-test" in text and "lr" in text
    app.on_key("tab")        # -> logs
    text = render_text(app)
    assert "line-a" in text and "line-b" in text
    app.on_key("r")          # reload does not crash and keeps metrics
    assert app.screens[-1].metrics
    app.on_key("escape")
    assert not app.screens


def test_hub_eval_detail_fetches_samples(app, fake, api):
    from prime_tpu.evals import EvalsClient
    from prime_tpu.evals.models import CreateEvaluationRequest

    client = EvalsClient(api)
    ev = client.create_evaluation(CreateEvaluationRequest(env="gsm8k", model="m1"))
    client.push_samples(
        ev.eval_id,
        [
            {"sample_id": "s0", "prompt": "p0", "completion": "c0", "reward": 1.0, "correct": True},
            {"sample_id": "s1", "prompt": "p1", "completion": "c1", "reward": 0.0, "correct": False},
        ],
    )
    app.refresh_all()
    app.on_key("3")          # evals hub section
    app.on_key("enter")
    assert app.screens
    text = render_text(app)
    assert "sample 1/2" in text and "p0" in text


def test_env_detail_versions_and_actions(app, fake, api, tmp_path):
    fake.envhub_plane.environments["arith"] = {
        "name": "arith",
        "versions": ["0.1.0", "0.2.0"],
        "owner": "dev",
        "visibility": "private",
    }
    fake.envhub_plane.actions["arith"] = [
        {"id": "act_1", "kind": "build", "status": "completed", "logs": ["built ok"]}
    ]
    app.refresh_all()
    app.on_key("5")          # environments
    app.on_key("enter")
    assert app.screens and app.screens[-1].title == "env: arith"
    text = render_text(app)
    assert "0.2.0" in text and "act_1" in text
    app.on_key("enter")      # fetch logs for the selected action
    text = render_text(app)
    assert "built ok" in text
    app.on_key("escape")
    assert not app.screens


# -- config-card editor (reference config_screen.py role) ---------------------


def test_card_editor_edit_save_roundtrip(app, tmp_path):
    tomllib = get_tomllib()

    _write_card(tmp_path, "sweep", "eval")
    app.on_key("8")              # launch section
    app.on_key("e")              # open editor
    assert app.screens and app.screens[-1].title == "edit: sweep.toml"
    editor = app.screens[-1]
    # move to the "model" field and retype its value
    while editor.fields[editor.cursor][0] != "model":
        app.on_key("j")
    app.on_key("enter")          # edit mode, prefilled with current value
    for _ in range("tiny-test".__len__()):
        app.on_key("backspace")
    for ch in "llama3-8b":
        app.on_key(ch)
    app.on_key("enter")          # commit
    assert editor.dirty
    app.on_key("s")              # save
    assert not editor.dirty
    data = tomllib.loads((tmp_path / ".prime-lab" / "launch" / "sweep.toml").read_text())
    assert data["eval"]["model"] == "llama3-8b"
    assert data["launch"]["kind"] == "eval"
    app.on_key("escape")
    assert not app.screens
    # the shell's launch row reflects the rescan
    assert "sweep" in render_text(app)


def test_card_editor_add_delete_and_typing(app, tmp_path):
    tomllib = get_tomllib()

    _write_card(tmp_path, "card2", "eval")
    app.on_key("8")
    app.on_key("e")
    editor = app.screens[-1]
    app.on_key("a")              # add field
    for ch in "num_samples=64":
        app.on_key(ch)
    app.on_key("enter")
    app.on_key("a")
    for ch in "push=false":
        app.on_key(ch)
    app.on_key("enter")
    app.on_key("s")
    data = tomllib.loads(editor.card.path.read_text())
    assert data["eval"]["num_samples"] == 64          # typed int, not "64"
    assert data["eval"]["push"] is False              # typed bool
    # delete it again (cursor sits on the later-added "push"; num_samples is above)
    while editor.fields[editor.cursor][0] != "num_samples":
        app.on_key("k")
    app.on_key("d")
    app.on_key("s")
    data = tomllib.loads(editor.card.path.read_text())
    assert "num_samples" not in data["eval"]


def test_card_editor_new_card_and_launch(app, fake, tmp_path):
    app.on_key("8")
    app.on_key("n")              # new card template
    editor = app.screens[-1]
    assert editor.card.kind == "eval" and not editor.card.path.exists()
    app.on_key("L")                          # launch before save
    assert "unsaved" in app.status           # guard message surfaced via app
    assert "unsaved" in (editor.launch())    # and via the direct call
    editor.dirty = True
    app.on_key("s")
    assert editor.card.path.exists()
    app.on_key("L")              # launch through the fake plane
    assert "launched eval" in app.status or "launched eval" in editor.message


def test_card_editor_payload_name_key_survives(app, tmp_path):
    """A payload key literally named `name` must not collide with the
    [launch].name pseudo-field: zero-edit save keeps both intact."""
    tomllib = get_tomllib()

    base = tmp_path / ".prime-lab" / "launch"
    base.mkdir(parents=True, exist_ok=True)
    (base / "named.toml").write_text(
        '[launch]\nkind = "eval"\nname = "outer"\n\n[eval]\nname = "inner"\nmodel = "m"\n'
    )
    app.on_key("8")
    while app.selected_row() and app.selected_row()["name"] != "outer":
        app.on_key("j")
    app.on_key("e")
    editor = app.screens[-1]
    editor.dirty = True
    app.on_key("s")
    data = tomllib.loads((base / "named.toml").read_text())
    assert data["launch"]["name"] == "outer"
    assert data["eval"]["name"] == "inner"


def test_card_editor_rejects_dotted_keys(app, tmp_path):
    _write_card(tmp_path, "card4", "eval")
    app.on_key("8")
    app.on_key("e")
    editor = app.screens[-1]
    app.on_key("a")
    for ch in "lr.schedule=cosine":
        app.on_key(ch)
    app.on_key("enter")
    assert "must be bare" in editor.message
    assert all(k != "lr.schedule" for k, _ in editor.fields)


# -- run comparison (eval compare, in-shell) ----------------------------------


def _run_with_flips(tmp_path, run, rewards):
    """Run dir whose sample i is correct iff rewards[i]; prompts shared."""
    run_dir = tmp_path / "outputs" / "evals" / "gsm8k--m1" / run
    run_dir.mkdir(parents=True)
    accuracy = sum(rewards) / len(rewards)
    (run_dir / "metadata.json").write_text(
        json.dumps({"metrics": {"accuracy": accuracy, "num_samples": len(rewards)}})
    )
    with open(run_dir / "results.jsonl", "w") as f:
        for i, ok in enumerate(rewards):
            f.write(
                json.dumps(
                    {
                        "prompt": f"q{i}",
                        "completion": f"{run}-ans{i}",
                        "answer": str(i),
                        "reward": 1.0 if ok else 0.0,
                        "correct": bool(ok),
                    }
                )
                + "\n"
            )
    return run_dir


def test_compare_runs_flips_and_metric_deltas(tmp_path):
    from prime_tpu.lab.evalrecords import compare_runs

    dir_a = _run_with_flips(tmp_path, "run-a", [1, 1, 0, 0])
    dir_b = _run_with_flips(tmp_path, "run-b", [1, 0, 1, 0])
    comparison = compare_runs(dir_a, dir_b)
    assert comparison.shared == 4
    assert comparison.regressions == 1 and comparison.improvements == 1
    directions = {f.key: f.direction for f in comparison.flips}
    assert directions == {"q1": "regression", "q2": "improvement"}
    accuracy = next(m for m in comparison.metrics if m[0] == "accuracy")
    assert accuracy[3] == pytest.approx(0.0)   # 0.5 -> 0.5


def test_compare_runs_edge_cases(tmp_path):
    """sample_id 0 keys, rows without 'correct', and duplicate keys."""
    from prime_tpu.lab.evalrecords import compare_runs

    def write(run, rows):
        run_dir = tmp_path / "outputs" / "evals" / "e--m" / run
        run_dir.mkdir(parents=True)
        (run_dir / "metadata.json").write_text(json.dumps({"metrics": {}}))
        with open(run_dir / "results.jsonl", "w") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
        return run_dir

    dir_a = write(
        "a",
        [
            {"sample_id": 0, "completion": "x", "correct": True},   # falsy key kept
            {"prompt": "dup", "completion": "first", "correct": True},
            {"prompt": "dup", "completion": "second", "correct": False},  # ignored
            {"prompt": "reward-only", "completion": "r", "reward": 0.5},  # no correct
        ],
    )
    dir_b = write(
        "b",
        [
            {"sample_id": 0, "completion": "y", "correct": False},
            {"prompt": "dup", "completion": "other", "correct": True},
            {"prompt": "reward-only", "completion": "r2", "correct": True},
        ],
    )
    comparison = compare_runs(dir_a, dir_b)
    assert comparison.shared == 3
    assert comparison.duplicates == 1
    # sample 0 regressed; dup compares first occurrences (no flip);
    # reward-only is excluded from flip accounting, not counted as regression
    assert [(f.key, f.direction) for f in comparison.flips] == [("0", "regression")]


def test_compare_screen_via_x_marks(app, tmp_path):
    _run_with_flips(tmp_path, "run-a", [1, 0])
    _run_with_flips(tmp_path, "run-b", [0, 1])
    app.tick()
    app.on_key("1")
    app.on_key("x")                  # mark baseline (first row)
    assert "baseline" in app.status
    app.on_key("j")
    app.on_key("x")                  # compare with second row
    assert app.screens and app.screens[-1].title.startswith("compare:")
    text = render_text(app)
    assert "improvements" in text and "regressions" in text
    app.on_key("enter")              # expand the selected flip
    text = render_text(app)
    assert "ans0" in text            # both completions shown
    app.on_key("f")                  # filter cycles
    assert "filter:" in app.status
    app.on_key("escape")
    assert not app.screens


def test_help_overlay(app):
    app.on_key("?")
    text = render_text(app)
    assert "Sample browser" in text and "markdown" in text.lower()
    app.on_key("escape")
    assert not app.screens


# -- grouped eval tree (reference evaluation_browser.py role) -----------------


def test_eval_tree_groups_and_aggregates(app, tmp_path):
    _local_run(tmp_path, "gsm8k", "m1", "run-a", 0.5, n_samples=2)
    _local_run(tmp_path, "gsm8k", "m1", "run-b", 1.0, n_samples=2)
    _local_run(tmp_path, "gsm8k", "m2", "run-c", 0.25, n_samples=2)
    _local_run(tmp_path, "math", "m1", "run-d", 0.75, n_samples=2)
    app.tick()
    app.on_key("1")
    app.on_key("t")
    tree = app.screens[-1]
    assert tree.title.startswith("eval runs")
    text = render_text(app)
    # env aggregates over all its models: gsm8k mean = (0.5+1.0+0.25)/3
    assert "gsm8k" in text and "3 run(s)" in text and "58.3%" in text
    assert "math" in text and "75.0%" in text
    # newest-first run ordering within a model
    assert text.index("run-b") < text.index("run-a")
    # collapse the gsm8k env: its models/runs disappear
    app.on_key("g")
    app.on_key(" ")
    text = render_text(app)
    assert "run-a" not in text and "m2" not in text and "math" in text
    app.on_key("enter")      # expand again (enter toggles groups too)
    assert "run-a" in render_text(app)


def test_eval_tree_opens_run_overview(app, tmp_path):
    _local_run(tmp_path, "gsm8k", "m1", "run-a", 1.0, n_samples=2)
    app.tick()
    app.on_key("1")
    app.on_key("t")
    tree = app.screens[-1]
    # walk down to the run node and open it
    while tree.current()["level"] != 2:
        app.on_key("j")
    app.on_key("enter")
    assert app.screens[-1].__class__.__name__ == "EvalRunOverview"
    assert "pass rate" in render_text(app)
    app.on_key("escape")     # back to the tree
    assert app.screens[-1] is tree
    app.on_key("escape")
    assert not app.screens


# -- agent config editor (reference agent_cards.py role) ----------------------


def test_agent_editor_create_and_edit(app, tmp_path):
    from prime_tpu.lab.tui.app import SECTIONS

    app.section_idx = SECTIONS.index("agents")
    app.focus = "rows"
    app.on_key("n")          # new agent
    editor = app.screens[-1]
    app.on_key("enter")      # edit name
    for _ in range(len("new-agent")):
        app.on_key("backspace")
    for ch in "helper":
        app.on_key(ch)
    app.on_key("enter")
    app.on_key("j")          # dialect row
    app.on_key("enter")      # cycle to the next dialect in the runtime table
    assert editor.entry["dialect"] == "codex"  # sorted table: acp -> codex
    app.on_key("s")
    assert "command is required" in app.status
    app.on_key("j")          # command row
    app.on_key("enter")
    for ch in "python -u agent.py":
        app.on_key(ch)
    app.on_key("enter")
    app.on_key("s")
    assert "saved helper" in app.status
    app.on_key("escape")
    # the agents section now lists it (load_agents_config reads the file)
    rows = app.rows("agents")
    assert any(r["name"] == "helper" and r["dialect"] == "codex" for r in rows)
    # re-open for edit and delete
    app.on_key("e")
    editor = app.screens[-1]
    assert editor.entry["name"] == "helper"
    app.on_key("d")
    assert not app.screens   # delete closes the editor
    assert all(r["name"] != "helper" for r in app.rows("agents"))


def test_agent_editor_resolves_nameless_row(app, tmp_path):
    """A row without a 'name' key is listed under its synthesized agent-<i>
    label; editing it must resolve to the row, not append a duplicate."""
    import json as _json

    cfg = tmp_path / ".prime-lab"
    cfg.mkdir(parents=True, exist_ok=True)
    (cfg / "agents.json").write_text(
        _json.dumps({"agents": [{"command": "python -u a.py", "dialect": "simple"}]})
    )
    from prime_tpu.lab.tui.app import SECTIONS

    app.section_idx = SECTIONS.index("agents")
    app.focus = "rows"
    rows = app.rows("agents")
    assert rows and rows[0]["name"] == "agent-0"
    app.on_key("e")
    editor = app.screens[-1]
    assert editor.entry["command"] == "python -u a.py"
    assert editor.entry["dialect"] == "simple"
    assert len(editor.agents) == 1   # no duplicate appended


# -- workspace setup screen (reference setup_screens.py role) -----------------


def test_setup_screen_runs_setup_and_doctor(app, tmp_path):
    app.on_key("S")
    screen = app.screens[-1]
    assert screen.title == "lab setup"
    # uncheck codex, keep claude
    while screen.surfaces[screen.cursor] != "codex":
        app.on_key("j")
    app.on_key(" ")
    assert not screen.checked["codex"] and screen.checked["claude"]
    app.on_key("enter")          # run setup
    assert "setup ok" in app.status
    assert (tmp_path / ".prime-lab" / "lab.toml").exists()
    assert (tmp_path / "CLAUDE.md").exists()
    assert not (tmp_path / "AGENTS.md").exists()
    text = render_text(app)
    assert "created" in text
    app.on_key("d")              # doctor pass
    assert "doctor" in app.status
    app.on_key("escape")
    assert not app.screens


def test_setup_screen_no_surfaces_checked(app):
    app.on_key("S")
    screen = app.screens[-1]
    for name in screen.surfaces:
        screen.checked[name] = False
    app.on_key("enter")
    assert "no surfaces checked" in app.status
    assert screen.report is None


def test_card_editor_q_types_not_quits(app, tmp_path):
    _write_card(tmp_path, "card3", "eval")
    app.on_key("8")
    app.on_key("e")
    editor = app.screens[-1]
    app.on_key("enter")          # edit mode
    app.on_key("q")
    assert not app.quit and editor.input.endswith("q")
    app.on_key("escape")         # cancel edit
    assert editor.input is None and app.screens


def test_sample_browser_tool_calls_reasoning_usage_state(app, tmp_path):
    """Round-4 render breadth: tool-call turns, tool replies paired by id,
    reasoning content, token usage, and env state all render (reference
    eval_render.py tool_call_parts / stringify_message_reasoning /
    build_usage_text / build_state_text roles)."""
    run_dir = _local_run(tmp_path)
    with open(run_dir / "results.jsonl", "w") as f:
        f.write(
            json.dumps(
                {
                    "messages": [
                        {"role": "user", "content": "weather in SF?"},
                        {
                            "role": "assistant",
                            "content": "",
                            "reasoning": "user wants current weather",
                            "tool_calls": [
                                {
                                    "id": "call_1",
                                    "function": {
                                        "name": "get_weather",
                                        "arguments": {"city": "SF"},
                                    },
                                }
                            ],
                        },
                        {"role": "tool", "tool_call_id": "call_1", "content": "64F sunny"},
                        {"role": "assistant", "content": "64F and sunny."},
                    ],
                    "usage": {"prompt_tokens": 21, "completion_tokens": 9},
                    "state": {"turns": 2},
                    "reward": 1.0,
                    "correct": True,
                }
            )
            + "\n"
        )
    app.tick()
    app.on_key("1")
    app.on_key("enter")
    app.on_key("enter")
    text = render_text(app)
    assert 'get_weather({"city": "SF"}) -> call_1' in text
    assert "TOOL get_weather (call_1)" in text and "64F sunny" in text
    assert "[reasoning] user wants current weather" in text
    assert "USAGE" in text and "completion_tokens=9" in text
    assert "STATE" in text and '"turns": 2' in text


def test_sample_browser_tool_chains_media_and_error_turns(app, tmp_path):
    """Round-5 render breadth (VERDICT r4 #3): multi-turn tool chains pair
    each reply with its calling tool by name across turns, image/file parts
    render as placeholders instead of vanishing, refusal/error turns and the
    sample-level harness error render explicitly."""
    run_dir = _local_run(tmp_path)
    with open(run_dir / "results.jsonl", "w") as f:
        f.write(
            json.dumps(
                {
                    "messages": [
                        {
                            "role": "user",
                            "content": [
                                {"type": "text", "text": "what is in this picture?"},
                                {"type": "image_url", "image_url": {"url": "https://x/cat.png"}},
                                {"type": "input_file", "filename": "notes.pdf"},
                            ],
                        },
                        {
                            "role": "assistant",
                            "content": "",
                            "tool_calls": [
                                {"id": "c1", "function": {"name": "look", "arguments": {}}},
                                {"id": "c2", "function": {"name": "fetch", "arguments": {}}},
                            ],
                        },
                        {"role": "tool", "tool_call_id": "c2", "content": "fetched"},
                        {"role": "tool", "tool_call_id": "c1", "content": "a cat"},
                        {"role": "tool", "tool_call_id": "c9", "content": "orphan reply"},
                        {
                            "role": "assistant",
                            "content": "",
                            "refusal": "I can't help with that.",
                            "error": "rate limited",
                        },
                    ],
                    "error": "rollout aborted after turn 6",
                    "reward": 0.0,
                    "correct": False,
                }
            )
            + "\n"
        )
    app.tick()
    app.on_key("1")
    app.on_key("enter")
    app.on_key("enter")
    text = render_text(app)
    # out-of-order replies still name their tools; orphans say so
    assert "TOOL fetch (c2)" in text and "TOOL look (c1)" in text
    assert "TOOL c9 (unmatched)" in text
    # media placeholders
    assert "[image: https://x/cat.png]" in text
    assert "[file: notes.pdf]" in text
    # refusal + per-turn error + sample-level error (scroll to the tail —
    # the ERROR/USAGE sections sit below the first page)
    assert "[refusal] I can't help with that." in text
    assert "[error] rate limited" in text
    for _ in range(30):
        app.on_key("j")
    tail = render_text(app)
    assert "ERROR" in tail and "rollout aborted after turn 6" in tail


def test_empty_text_parts_render_nothing(app, tmp_path):
    """An empty 'text' part (streamed turns that only carry tool_calls) must
    not leave a '[text]' placeholder behind."""
    from prime_tpu.lab.tui.detail import _content_text

    assert _content_text([{"type": "text", "text": ""}]) == ""
    assert _content_text([{"type": "reasoning"}]) == ""
    assert _content_text([{"type": "mystery_kind"}]) == "[mystery_kind]"
