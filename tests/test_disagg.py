"""Disaggregated prefill/decode: KV wire format, roles, migration e2e.

The CI serve-smoke disaggregation leg. Load-bearing properties:

1. The prefix-cache wire format round-trips bytes/dtypes/shapes (int8
   scales included) across tiers, pins refcounts only for the duration of
   serialization, and rejects version/block/shape mismatches cleanly.
2. Roles parse tolerantly everywhere (/healthz junk never breaks polling),
   and the balancer's role-restricted pick honors them.
3. A 1-prefill + 1-decode fleet over REAL HTTP serves greedy outputs
   bit-identical to a colocated reference, with the KV migrated (prefix
   hit on the decode replica, zero prefix recompute) — and falls back to
   colocated serving when the decode replica dies.
"""

import time

import httpx
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from prime_tpu.loadgen.backends import NumericTokenizer  # noqa: E402
from prime_tpu.models import get_config  # noqa: E402
from prime_tpu.models.llama import init_params  # noqa: E402
from prime_tpu.serve.digest import parse_role  # noqa: E402
from prime_tpu.serve.engine import (  # noqa: E402
    ContinuousBatchingEngine,
    EngineBackend,
)
from prime_tpu.serve.fleet import serve_fleet  # noqa: E402
from prime_tpu.serve.fleet.balancer import PrefixAffinityBalancer  # noqa: E402
from prime_tpu.serve.fleet.membership import FleetMembership, Replica  # noqa: E402
from prime_tpu.serve.mesh_config import parse_mesh_spec  # noqa: E402
from prime_tpu.serve.prefix_cache import (  # noqa: E402
    KV_WIRE_VERSION,
    BlockPrefixCache,
)
from prime_tpu.serve.server import InferenceServer  # noqa: E402

CONFIG = get_config("tiny-test")
PARAMS = init_params(jax.random.PRNGKey(0), CONFIG, dtype=jnp.float32)


# ---- wire format units (numpy, identity converters) -------------------------


def _leaves(n: int) -> dict:
    rng = np.random.default_rng(7)
    return {
        "k": rng.standard_normal((2, 1, 2, 4, n)).astype(np.float32),
        "v": rng.standard_normal((2, 1, 2, 4, n)).astype(np.float32),
        "k_scale": rng.standard_normal((2, 1, 2, 1, n)).astype(np.float32),
        "q8": rng.integers(-128, 127, (2, 1, 2, 4, n)).astype(np.int8),
    }


def _seeded_cache(ids, full, **kw) -> BlockPrefixCache:
    cache = BlockPrefixCache(10**9, block=16, **kw)
    cache.insert(list(ids), lambda a, b: {k: v[..., a:b] for k, v in full.items()})
    return cache


def test_wire_roundtrip_preserves_bytes_dtypes_scales_refcounts():
    ids = list(range(100, 164))
    full = _leaves(64)
    src = _seeded_cache(ids, full)
    payload = src.export_segments(ids)
    assert payload is not None

    dst = BlockPrefixCache(10**9, block=16)
    added = dst.import_segments(payload)
    assert added == dst.bytes > 0
    match = dst.match(ids, limit=len(ids))
    assert match.length == 64
    got = {
        name: np.concatenate([np.asarray(s[name]) for s in match.segments()], axis=-1)
        for name in full
    }
    for name, want in full.items():
        assert got[name].dtype == want.dtype
        assert np.array_equal(got[name], want), name
    dst.release(match)
    # refcounts released on both sides: a follow-up export sees unpinned
    # nodes and produces the identical payload (byte-stable round trip)
    assert dst.export_segments(ids) == payload
    for node, _ in match.entries:
        assert node.refs == 0


def test_wire_export_is_tier_aware_and_byte_identical_across_tiers():
    ids = list(range(64))
    full = _leaves(64)
    # two-tier cache with identity converters: spill everything to the host
    # tier by shrinking the device budget, then export — the payload must be
    # byte-identical to the all-device export (shapes/dtypes round-trip)
    device = _seeded_cache(ids, full)
    want = device.export_segments(ids)
    spilled = _seeded_cache(ids, full, host_budget_bytes=10**9)
    spilled.budget_bytes = 1
    spilled.evict_to_budget()
    assert spilled.host_bytes > 0 and spilled.bytes == 0
    assert spilled.export_segments(ids) == want


def test_wire_partial_prefix_export_and_dedup_on_import():
    ids = list(range(64))
    full = _leaves(64)
    src = _seeded_cache(ids, full)
    # 40 requested -> 32 (block-aligned) exported
    partial = src.export_segments(ids[:40])
    dst = BlockPrefixCache(10**9, block=16)
    dst.import_segments(partial)
    assert dst.match_len(ids, limit=len(ids)) == 32
    # importing the full path afterwards dedups the shared 32 tokens: only
    # the tail's bytes are added
    added = dst.import_segments(src.export_segments(ids))
    assert 0 < added < dst.bytes
    assert dst.match_len(ids, limit=len(ids)) == 64


def test_wire_version_block_and_truncation_reject_cleanly():
    ids = list(range(32))
    src = _seeded_cache(ids, _leaves(32))
    payload = src.export_segments(ids)

    bad_version = payload.replace(b'"version":1', b'"version":99', 1)
    with pytest.raises(ValueError, match="version"):
        BlockPrefixCache(10**9, block=16).import_segments(bad_version)
    with pytest.raises(ValueError, match="block"):
        BlockPrefixCache(10**9, block=32).import_segments(payload)
    with pytest.raises(ValueError, match="truncated|header"):
        BlockPrefixCache(10**9, block=16).import_segments(payload[:-8])
    with pytest.raises(ValueError, match="header"):
        BlockPrefixCache(10**9, block=16).import_segments(b"junk")
    # a clean failure leaves the cache untouched
    fresh = BlockPrefixCache(10**9, block=16)
    with pytest.raises(ValueError):
        fresh.import_segments(bad_version)
    assert fresh.bytes == 0 and fresh.nodes == 0
    assert KV_WIRE_VERSION == 1  # bump = update this suite's tamper targets


def test_wire_export_returns_none_when_nothing_cached():
    cache = BlockPrefixCache(10**9, block=16)
    assert cache.export_segments(list(range(64))) is None
    seeded = _seeded_cache(list(range(64)), _leaves(64))
    # disjoint ids: no shared block
    assert seeded.export_segments(list(range(1000, 1064))) is None


# ---- roles: tolerant parse + role-aware pick --------------------------------


def test_parse_role_coerces_junk_to_any():
    assert parse_role("prefill") == "prefill"
    assert parse_role("decode") == "decode"
    assert parse_role("any") == "any"
    for junk in (None, "", "PREFILL", "gpu", 7, ["prefill"], {"role": "decode"}, True):
        assert parse_role(junk) == "any"


def test_balancer_role_restricted_pick():
    membership = FleetMembership()
    a = membership.add("http://127.0.0.1:1111")
    b = membership.add("http://127.0.0.1:2222")
    c = membership.add("http://127.0.0.1:3333")
    a.role, b.role, c.role = "prefill", "decode", "any"
    balancer = PrefixAffinityBalancer(membership)
    prompt = "a migratable prompt body " * 8
    for _ in range(4):
        assert balancer.pick(prompt, role="prefill").replica.id in (a.id, c.id)
        assert balancer.pick(prompt, role="decode").replica.id in (b.id, c.id)
    # exclusion + role can empty the pool -> None (router falls back)
    assert balancer.pick(prompt, {a.id, c.id}, role="prefill") is None


def test_role_mesh_presets_parse():
    prefill = parse_mesh_spec("role:prefill", 8)
    assert prefill.axes["tp"] == 8  # FLOPs-bound: the slice goes to tp
    decode = parse_mesh_spec("role:decode", 8)
    assert decode.axes["dp"] == 8  # capacity-bound: the slice goes to dp
    assert parse_mesh_spec("role:any", 8) is None
    with pytest.raises(ValueError, match="role preset"):
        parse_mesh_spec("role:gpu", 8)


# ---- engine-level export/import ---------------------------------------------


def make_engine(**kw) -> ContinuousBatchingEngine:
    kw.setdefault("max_slots", 2)
    kw.setdefault("capacity", 128)
    kw.setdefault("chunk", 4)
    kw.setdefault("prefix_cache_mb", 8)
    return ContinuousBatchingEngine(PARAMS, CONFIG, pad_id=0, **kw)


def _drain(engine, req):
    while not req.done:
        engine.tick()
    out = []
    while not req.events.empty():
        item = req.events.get_nowait()
        if item:
            out.extend(item)
    return out


PROMPT = [1] + [((7 * i) % (CONFIG.vocab_size - 3)) + 3 for i in range(63)]


def test_engine_migration_seeds_decode_engine_bit_identically():
    reference = make_engine()
    ref_tokens = _drain(reference, reference.submit(list(PROMPT), max_new_tokens=12))

    prefill_engine = make_engine()
    _drain(prefill_engine, prefill_engine.submit(list(PROMPT), max_new_tokens=1))
    payload = prefill_engine.export_kv(list(PROMPT))
    assert payload is not None
    assert prefill_engine.stats()["kv_exports"] == 1

    decode_engine = make_engine()
    added = decode_engine.import_kv(payload)
    assert added > 0
    tokens = _drain(decode_engine, decode_engine.submit(list(PROMPT), max_new_tokens=12))
    stats = decode_engine.stats()
    assert stats["kv_imports"] == 1
    assert stats["prefix_hits"] == 1  # assemble_row seeded the slot
    assert tokens == ref_tokens


def test_engine_kv_calls_marshal_onto_running_loop():
    prefill_engine = make_engine()
    _drain(prefill_engine, prefill_engine.submit(list(PROMPT), max_new_tokens=1))
    payload = prefill_engine.export_kv(list(PROMPT))

    engine = make_engine()
    engine.start()
    try:
        # cross-thread calls must round-trip through the engine loop's job
        # queue (the radix tree is engine-thread-owned)
        assert engine.import_kv(payload, timeout=30.0) > 0
        assert engine.export_kv(list(PROMPT), timeout=30.0) is not None
        with pytest.raises(ValueError):
            engine.import_kv(b"junk no header", timeout=30.0)
    finally:
        engine.shutdown()


def test_serialize_match_off_thread_survives_concurrent_split():
    """Off-loop export (ROADMAP item 4 follow-up, enabled by PR 12's
    pin-surviving snapshots): the tree owner pins the match, the expensive
    serialization runs on ANOTHER thread, and an insert that _splits the
    pinned path mid-flight must not change a byte — the pin-time snapshots
    keep the read consistent."""
    import threading

    ids = list(range(64))
    full = _leaves(64)
    cache = _seeded_cache(ids, full)
    want = cache.export_segments(ids)

    match = cache.match(ids, limit=len(ids))  # tree-owner side: pin
    # a diverging insert splits the pinned single-run node at token 32
    other = _leaves(64)
    cache.insert(
        ids[:32] + [999 + i for i in range(32)],
        lambda a, b: {k: v[..., a:b] for k, v in other.items()},
    )
    got: list = []
    worker = threading.Thread(
        target=lambda: got.append(cache.serialize_match(match))
    )
    worker.start()
    worker.join(timeout=30)
    cache.release(match)
    assert got and got[0] == want
    # the tree stayed coherent: a fresh export carries the SAME tokens and
    # KV content (its manifest now shows the split's two segments, so the
    # comparison is content-level through a round-trip import)
    from prime_tpu.serve.prefix_cache import decode_wire_payload

    tokens, leaves = decode_wire_payload(cache.export_segments(ids), 16)
    assert tokens == ids
    for name, want_arr in full.items():
        assert np.array_equal(leaves[name], want_arr), name


def test_engine_export_off_loop_bit_identical():
    """export_kv from a non-engine thread (the running-loop path) must
    produce the same bytes the direct synchronous path produces — the loop
    only services the tiny pin/release jobs, the serialization runs on the
    calling thread (the decode stall this kills on any-role exporters)."""
    engine = make_engine()
    _drain(engine, engine.submit(list(PROMPT), max_new_tokens=1))
    direct = engine.export_kv(list(PROMPT))  # loop not started: direct path
    assert direct is not None
    engine.start()
    try:
        off_loop = engine.export_kv(list(PROMPT), timeout=30.0)
    finally:
        engine.shutdown()
    assert off_loop == direct
    assert engine.stats()["kv_exports"] == 2
    # the pin was released: nothing on the exported path stays refcounted
    match = engine.prefix_cache.match(list(PROMPT))
    for node, _ in match.entries:
        assert node.refs == 1  # exactly this fresh match's pin
    engine.prefix_cache.release(match)


def test_engine_without_prefix_cache_refuses_kv():
    engine = make_engine(prefix_cache_mb=0)
    assert engine.export_kv(list(PROMPT)) is None
    with pytest.raises(ValueError, match="prefix cache"):
        engine.import_kv(b"whatever")


# ---- HTTP e2e: 1 prefill + 1 decode replica over a real router --------------


def _stack(role: str, key: int = 0, **engine_kw):
    params = init_params(jax.random.PRNGKey(key), CONFIG, dtype=jnp.float32)
    engine_kw.setdefault("max_slots", 2)
    engine_kw.setdefault("capacity", 128)
    engine_kw.setdefault("chunk", 4)
    engine_kw.setdefault("prefix_cache_mb", 8)
    engine = ContinuousBatchingEngine(params, CONFIG, pad_id=0, **engine_kw)
    engine.start()
    server = InferenceServer(
        "tiny-test", EngineBackend(engine, NumericTokenizer()), port=0, role=role
    ).start()
    return engine, server


def _wait_for_roles(router, expected: set[str], timeout_s: float = 30.0) -> None:
    """Block until the router's health poller has learned every expected
    replica role. The first routed chat races the initial poll cycle
    otherwise: a router that still sees role 'any' plans a colocated path
    and the migration-evidence asserts flake."""
    deadline = time.monotonic() + timeout_s
    seen: set[str] = set()
    while time.monotonic() < deadline:
        seen = {r.role for r in router.membership.routable_replicas()}
        if expected <= seen:
            return
        router.membership.poll_all()
        time.sleep(0.05)
    raise AssertionError(f"router never learned roles {expected}: saw {seen}")


def _wait_for_migrations(router, expected_ok: int, timeout_s: float = 30.0) -> dict:
    """Poll the router's migration-outcome counters until ``ok`` reaches the
    expected count. The counter increments AFTER the resume leg's last byte
    reaches the client, so a stats read right after the chat returns races
    it by design — the response is done, the bookkeeping is microseconds
    behind."""
    deadline = time.monotonic() + timeout_s
    stats = router.stats()
    while time.monotonic() < deadline:
        stats = router.stats()
        if stats["migrations"].get("ok", 0) >= expected_ok:
            return stats
        time.sleep(0.02)
    raise AssertionError(
        f"migrations never reached ok={expected_ok}: {stats['migrations']}"
    )


def _chat(url: str, ids, max_tokens: int = 12) -> httpx.Response:
    return httpx.post(
        f"{url}/v1/chat/completions",
        json={
            "messages": [{"role": "user", "content": " ".join(str(t) for t in ids)}],
            "max_tokens": max_tokens,
            "temperature": 0.0,
        },
        timeout=120.0,
    )


def test_http_disagg_bit_identity_and_migration_evidence():
    ref_engine, ref_server = _stack("any")
    prefill_engine, prefill_server = _stack("prefill")
    decode_engine, decode_server = _stack("decode")
    router = serve_fleet(
        [prefill_server.url, decode_server.url],
        poll_interval=0.2,
        model_id="tiny-test",
    )
    try:
        _wait_for_roles(router, {"prefill", "decode"})
        reference = _chat(ref_server.url, PROMPT).json()["choices"][0]["message"]
        routed = _chat(router.url, PROMPT).json()["choices"][0]["message"]
        assert routed["content"] == reference["content"]

        stats = _wait_for_migrations(router, 1)
        assert stats["migrations"].get("ok") == 1
        assert stats["migrate_bytes"] > 0
        roles = {r["role"] for r in stats["replicas"].values()}
        assert roles == {"prefill", "decode"}
        # the phase split actually split the phases: the prefill replica
        # admitted the clamped leg and exported; the decode replica imported,
        # prefix-hit, and owned the whole decode stream
        assert prefill_engine.stats()["kv_exports"] == 1
        assert prefill_engine.stats()["tokens_emitted"] == 1
        assert decode_engine.stats()["kv_imports"] == 1
        assert decode_engine.stats()["prefix_hits"] == 1
        assert decode_engine.stats()["tokens_emitted"] == 12

        # a second identical request dedups the KV ship (import plants 0 new
        # bytes) and stays bit-identical
        again = _chat(router.url, PROMPT).json()["choices"][0]["message"]
        assert again["content"] == reference["content"]
        assert _wait_for_migrations(router, 2)["migrations"].get("ok") == 2
    finally:
        router.stop()
        for server in (ref_server, prefill_server, decode_server):
            server.stop()


def test_http_disagg_streaming_and_short_prompt_colocated():
    prefill_engine, prefill_server = _stack("prefill")
    decode_engine, decode_server = _stack("decode")
    router = serve_fleet(
        [prefill_server.url, decode_server.url],
        poll_interval=0.2,
        model_id="tiny-test",
    )
    try:
        _wait_for_roles(router, {"prefill", "decode"})
        # streaming rides the migration path too (the decode leg streams)
        deltas = []
        with httpx.stream(
            "POST",
            f"{router.url}/v1/chat/completions",
            json={
                "messages": [
                    {"role": "user", "content": " ".join(str(t) for t in PROMPT)}
                ],
                "max_tokens": 8,
                "temperature": 0.0,
                "stream": True,
            },
            timeout=120.0,
        ) as response:
            assert response.status_code == 200
            for line in response.iter_lines():
                if line.startswith("data: ") and '"content"' in line:
                    deltas.append(line)
        assert deltas
        assert _wait_for_migrations(router, 1)["migrations"].get("ok") == 1
        # a sub-block prompt has no migratable KV: colocated path, no new
        # migration recorded
        assert _chat(router.url, [1, 5, 9], max_tokens=4).status_code == 200
        assert sum(router.stats()["migrations"].values()) == 1
    finally:
        router.stop()
        prefill_server.stop()
        decode_server.stop()


def test_http_disagg_fails_over_to_colocated_when_decode_dies():
    prefill_engine, prefill_server = _stack("prefill")
    decode_engine, decode_server = _stack("decode")
    router = serve_fleet(
        [prefill_server.url, decode_server.url],
        poll_interval=0.1,
        model_id="tiny-test",
        fail_threshold=1,
        cooldown=30.0,
    )
    try:
        decode_server.stop()
        deadline = time.monotonic() + 10.0
        # the poller needs a cycle to open the dead replica's breaker; until
        # then the migration path discovers the death itself and falls back
        response = _chat(router.url, PROMPT, max_tokens=6)
        assert response.status_code == 200
        assert response.json()["choices"][0]["message"]["content"]
        while time.monotonic() < deadline:
            routable = router.membership.routable_replicas()
            if all(r.role == "prefill" for r in routable):
                break
            time.sleep(0.05)
        # with no decode replica routable the plan is colocated from the
        # start: the prefill replica serves the whole request
        served = _chat(router.url, PROMPT, max_tokens=6)
        assert served.status_code == 200
        outcomes = router.stats()["migrations"]
        assert outcomes.get("ok", 0) == 0
    finally:
        router.stop()
        prefill_server.stop()


def test_admin_kv_endpoints_auth_and_validation():
    engine, server = _stack("prefill")
    gated_engine, gated_server = _stack("decode")
    gated_server.admin_token = "s3cret"
    try:
        # 400: neither ids nor prompt
        assert httpx.get(f"{server.url}/admin/kv", timeout=10).status_code == 400
        # 204: nothing cached for this prompt
        assert (
            httpx.get(
                f"{server.url}/admin/kv", params={"prompt": "9 9 9"}, timeout=10
            ).status_code
            == 204
        )
        # serve once, then export by prompt text and by exact ids. The
        # engine cached the RENDERED chat prompt's encoding — what the
        # router holds and ships in ?prompt= — so both forms must name it
        # the same way the chat path did.
        from prime_tpu.serve.server import render_chat_prompt

        _chat(server.url, PROMPT, max_tokens=1)
        rendered = render_chat_prompt(
            [{"role": "user", "content": " ".join(str(t) for t in PROMPT)}]
        )
        cached_ids = NumericTokenizer().encode(rendered)
        by_ids = httpx.get(
            f"{server.url}/admin/kv",
            params={"ids": ",".join(str(t) for t in cached_ids)},
            timeout=30,
        )
        assert by_ids.status_code == 200
        assert by_ids.headers["content-type"] == "application/octet-stream"
        by_prompt = httpx.get(
            f"{server.url}/admin/kv", params={"prompt": rendered}, timeout=30
        )
        assert by_prompt.status_code == 200
        assert by_prompt.content == by_ids.content  # same tokenization
        # PUT parity: token-gated server refuses without the bearer
        put_unauth = httpx.put(
            f"{gated_server.url}/admin/kv", content=by_ids.content, timeout=30
        )
        assert put_unauth.status_code == 403
        assert (
            httpx.get(f"{gated_server.url}/admin/kv", timeout=10).status_code == 403
        )
        put_ok = httpx.put(
            f"{gated_server.url}/admin/kv",
            content=by_ids.content,
            headers={"Authorization": "Bearer s3cret"},
            timeout=30,
        )
        assert put_ok.status_code == 200
        assert put_ok.json()["imported_bytes"] > 0
        # malformed payload answers 400, not 500
        bad = httpx.put(
            f"{gated_server.url}/admin/kv",
            content=b"not a payload",
            headers={"Authorization": "Bearer s3cret"},
            timeout=30,
        )
        assert bad.status_code == 400
    finally:
        server.stop()
        gated_server.stop()


class TemplatedNumericTokenizer(NumericTokenizer):
    """Numeric tokenizer with its own chat template — the HF-checkpoint
    shape where the replica's rendering differs from the router's."""

    def render_chat(self, messages) -> str:
        return "<t> " + " ".join(m.get("content", "") for m in messages) + " </t>"


def test_export_kv_messages_matches_templated_admission():
    """The migration export must tokenize like the ADMISSION did: on a
    templated backend the router's own rendering names a different id path
    (migrations would silently go cold), while the messages-body export
    reproduces template + special-token handling exactly."""
    from prime_tpu.serve.server import render_chat_prompt

    engine = make_engine()
    backend = EngineBackend(engine, TemplatedNumericTokenizer())
    messages = [{"role": "user", "content": " ".join(str(t) for t in PROMPT)}]
    req = backend.submit_text(
        backend.tokenizer.render_chat(messages),
        max_new_tokens=1, temperature=0.0, templated=True,
    )
    _drain(engine, req)
    # the router-rendered text path cannot find the templated admission
    assert backend.export_kv_text(render_chat_prompt(messages)) is None
    payload = backend.export_kv_messages(messages)
    assert payload is not None
    # and a decode twin seeded through the same messages path prefix-hits
    decode_engine = make_engine()
    decode_backend = EngineBackend(decode_engine, TemplatedNumericTokenizer())
    assert decode_backend.import_kv(payload) > 0
    req2 = decode_backend.submit_text(
        decode_backend.tokenizer.render_chat(messages),
        max_new_tokens=4, temperature=0.0, templated=True,
    )
    _drain(decode_engine, req2)
    assert decode_engine.stats()["prefix_hits"] == 1


def test_admin_kv_get_accepts_messages_body():
    """The router's export form: chat messages in the GET body (no URL-
    length cap) must produce the same payload as the equivalent ?prompt=
    export on an untemplated backend."""
    from prime_tpu.serve.server import render_chat_prompt

    engine, server = _stack("prefill")
    try:
        _chat(server.url, PROMPT, max_tokens=1)
        messages = [{"role": "user", "content": " ".join(str(t) for t in PROMPT)}]
        by_body = httpx.request(
            "GET", f"{server.url}/admin/kv",
            json={"messages": messages, "max_tokens": 1}, timeout=30,
        )
        assert by_body.status_code == 200
        by_prompt = httpx.get(
            f"{server.url}/admin/kv",
            params={"prompt": render_chat_prompt(messages)},
            timeout=30,
        )
        assert by_body.content == by_prompt.content
        bad = httpx.request(
            "GET", f"{server.url}/admin/kv", content=b"not json", timeout=30
        )
        assert bad.status_code == 400
    finally:
        server.stop()


def test_healthz_advertises_role_and_membership_retains_it():
    engine, server = _stack("decode")
    try:
        body = httpx.get(f"{server.url}/healthz", timeout=10).json()
        assert body["role"] == "decode"
        membership = FleetMembership()
        replica = Replica(server.url)
        membership.replicas[replica.id] = replica
        membership.apply_health(replica, body, 200)
        assert replica.role == "decode"
        assert membership.snapshot()[replica.id]["role"] == "decode"
    finally:
        server.stop()
