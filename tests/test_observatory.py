"""Fleet SLO observatory: rolling time-series, burn-rate evaluation, scale
signals (docs/observability.md "Observatory").

The load-bearing properties:

1. the snapshot ring's windowed rate/quantile queries reproduce the loadgen
   report's registry-delta arithmetic live, and NEVER emit a negative rate
   across a replica restart (counter-reset clamp + ring drop + reset count);
2. the burn-rate sim is deterministic: a rate_storm-shaped fixture replays
   to `up`, an idle fixture to `down`→`hold`, byte-identically across
   reruns — no sockets, no sleeps, no wall clock;
3. the fleet poller's registry capture shares the digest's tolerance
   contract (junk/absent/oversized never fails a poll);
4. `GET /admin/observatory` (router and server, admin-token parity) reports
   windowed tok/s agreeing with the loadgen SLO report for the same run.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import httpx
import pytest

from prime_tpu.obs.metrics import (
    DEFAULT_LATENCY_BUCKETS,
    Registry,
    counter_delta,
    hist_delta,
)
from prime_tpu.obs.slo import (
    FAST_WINDOW_S,
    SloEvaluator,
    default_policies,
    replay,
)
from prime_tpu.obs.timeseries import (
    SnapshotRing,
    merge_registry_payload,
    serving_window_view,
)
from prime_tpu.serve.fleet import FleetMembership

# ---- synthetic snapshot fixtures (pure dicts, hand-stamped clocks) ----------

BUCKETS = list(DEFAULT_LATENCY_BUCKETS)


def _hist(observations: list[float]) -> dict:
    counts = [0] * (len(BUCKETS) + 1)
    for value in observations:
        for i, bound in enumerate(BUCKETS):
            if value <= bound:
                counts[i] += 1
                break
        else:
            counts[-1] += 1
    return {
        "buckets": list(BUCKETS),
        "counts": counts,
        "sum": float(sum(observations)),
        "count": len(observations),
    }


def snap(
    t: float,
    counters: dict | None = None,
    hists: dict | None = None,
    gauges: dict | None = None,
) -> dict:
    """A synthetic Registry.snapshot() with an explicit capture instant —
    the sim's whole point is that no wall clock is involved."""
    out: dict = {
        "captured_at": {
            "type": "gauge",
            "help": "t",
            "series": [{"labels": {}, "value": float(t)}],
        }
    }
    for name, value in (counters or {}).items():
        out[name] = {
            "type": "counter",
            "help": name,
            "series": [{"labels": {}, "value": float(value)}],
        }
    for name, observations in (hists or {}).items():
        out[name] = {"type": "histogram", "help": name, "series": [
            {"labels": {}, **_hist(observations)}
        ]}
    for name, value in (gauges or {}).items():
        out[name] = {
            "type": "gauge",
            "help": name,
            "series": [{"labels": {}, "value": float(value)}],
        }
    return out


# ---- ring arithmetic --------------------------------------------------------


def test_ring_windowed_rate_and_quantile():
    """rate()/quantile() answer over the asked window's delta only — the
    pre-window history must not leak into the estimate."""
    ring = SnapshotRing(depth=16)
    # 60 s of history: slow tokens + slow TTFTs early, fast late
    ring.append(snap(0, counters={"serve_tokens_emitted_total": 0},
                     hists={"serve_ttft_seconds": []}))
    ring.append(snap(30, counters={"serve_tokens_emitted_total": 300},
                     hists={"serve_ttft_seconds": [8.0] * 10}))
    ring.append(snap(60, counters={"serve_tokens_emitted_total": 1500},
                     hists={"serve_ttft_seconds": [8.0] * 10 + [0.1] * 30}))
    # last-30s window: 1200 tokens over 30 s
    assert ring.rate("serve_tokens_emitted_total", 30) == pytest.approx(40.0)
    # whole history: 1500 over 60 s
    assert ring.rate("serve_tokens_emitted_total", 60) == pytest.approx(25.0)
    # the last 30 s saw ONLY the 0.1 s TTFTs: p95 must not see the 8 s ones
    q = ring.quantile("serve_ttft_seconds", 0.95, 30)
    assert q is not None and q < 0.5
    # over the full hour the 8 s observations surface again
    q_all = ring.quantile("serve_ttft_seconds", 0.95, 120)
    assert q_all is not None and q_all > 1.0
    # a single-sample ring has no window
    fresh = SnapshotRing(depth=4)
    fresh.append(snap(0, counters={"serve_tokens_emitted_total": 5}))
    assert fresh.rate("serve_tokens_emitted_total", 30) is None


def test_ring_counter_reset_clamps_and_counts():
    """Satellite: a replica restart (counters shrink) must clamp to the
    post-reset value, count the reset, drop pre-restart history, and never
    emit a negative rate."""
    assert counter_delta(100.0, 40.0) == (40.0, True)
    assert counter_delta(40.0, 100.0) == (60.0, False)
    shrunk = hist_delta(_hist([1.0] * 5), _hist([1.0] * 2))
    assert shrunk is not None and shrunk["count"] == 2  # post-reset series
    ring = SnapshotRing(depth=8)
    ring.append(snap(0, counters={"serve_tokens_emitted_total": 0}))
    ring.append(snap(10, counters={"serve_tokens_emitted_total": 500}))
    # restart: counter falls back toward zero
    reset = ring.append(snap(20, counters={"serve_tokens_emitted_total": 30}))
    assert reset and ring.resets == 1
    assert len(ring) == 1  # pre-restart history dropped
    ring.append(snap(30, counters={"serve_tokens_emitted_total": 90}))
    rate = ring.rate("serve_tokens_emitted_total", 60)
    assert rate is not None and rate == pytest.approx(6.0)  # 60 over 10 s
    assert rate >= 0.0


def test_merge_registry_payload_sections_and_junk():
    engine = snap(5, counters={"serve_tokens_emitted_total": 10})
    server = snap(5.001, counters={"http_requests_total": 3})
    merged = merge_registry_payload({"server": server, "engine": engine})
    assert merged is not None
    assert "serve_tokens_emitted_total" in merged and "http_requests_total" in merged
    assert merged["captured_at"]["series"][0]["value"] == pytest.approx(5.001)
    # junk shapes degrade to None, never raise
    for junk in (None, 7, "x", [], {"engine": "nope"}, {"engine": {}}):
        assert merge_registry_payload(junk) is None


def test_serving_window_view_shape():
    ring = SnapshotRing(depth=8)
    ring.append(snap(0, counters={"serve_tokens_emitted_total": 0,
                                  "serve_requests_admitted_total": 0}))
    ring.append(snap(10, counters={"serve_tokens_emitted_total": 120,
                                   "serve_requests_admitted_total": 4},
                     hists={"serve_ttft_seconds": [0.2] * 4}))
    view = serving_window_view([ring], 30)
    assert view["window_s"] == 30
    assert view["span_s"] == pytest.approx(10.0)
    assert view["tok_s"] == pytest.approx(12.0)
    assert view["admitted_per_s"] == pytest.approx(0.4)
    assert view["ttft_p95_s"] is not None
    # an empty ring answers None everywhere, not fake zeros
    empty = serving_window_view([SnapshotRing(depth=4)], 30)
    assert empty["span_s"] is None and empty["tok_s"] is None


# ---- burn-rate sim (the deterministic replay harness) -----------------------


def _storm_sequences(steps: int = 24):
    """A rate_storm-shaped fixture derived from the loadgen scenario: the
    schedule's oversubscription wave arrives faster than a replica can
    serve, TTFT observations blow past the objective, and the router sheds
    the overflow as 429s. Snapshots are synthesized per 1 s step — same
    registry families a real poll captures, no hardware."""
    from prime_tpu.loadgen.scenario import SCENARIOS, build_schedule

    schedule = build_schedule(SCENARIOS["rate_storm"](seed=7), vocab=101)
    # rate_storm is an INSTANTANEOUS oversubscription burst aimed at the 429
    # admission gate; under Retry-After its rejected clients come straight
    # back, so the fixture re-releases the seeded burst every few steps —
    # a sustained storm against a replica serving a fraction of it
    burst = len(schedule)
    serve_per_s = max(1, burst // 12)
    tokens = admitted = rejected = forwarded = 0
    backlog = 0.0
    ttfts: list[float] = []
    engine_seq, router_seq = [], []
    for t in range(1, steps + 1):
        arrived = burst if t % 3 == 1 else 0
        served = min(serve_per_s, arrived + int(backlog))
        overflow = max(0, int(backlog) + arrived - served - 8)  # queue cap 8
        backlog = max(0.0, backlog + arrived - served - overflow)
        rejected += overflow
        forwarded += served
        admitted += served
        tokens += served * 16
        # queueing delay grows with backlog: TTFTs land far over the 2 s
        # objective for the storm's whole tail
        ttfts.extend([0.5 + backlog] * served)
        engine_seq.append(
            snap(
                t,
                counters={
                    "serve_tokens_emitted_total": tokens,
                    "serve_requests_admitted_total": admitted,
                    "serve_requests_completed_total": admitted,
                },
                hists={"serve_ttft_seconds": list(ttfts)},
                gauges={"serve_active_slots": 8},
            )
        )
        router_seq.append(
            snap(
                t,
                counters={
                    "fleet_admission_rejected_total": rejected,
                    "fleet_requests_total": forwarded,
                },
            )
        )
    return engine_seq, router_seq


def _idle_sequences(steps: int = 24):
    """A post-storm idle fixture: counters flat, utilization on the floor."""
    engine_seq = [
        snap(
            t,
            counters={
                "serve_tokens_emitted_total": 1000,
                "serve_requests_admitted_total": 50,
                "serve_requests_completed_total": 50,
            },
            hists={"serve_ttft_seconds": [0.1] * 50},
            gauges={"serve_active_slots": 0},
        )
        for t in range(1, steps + 1)
    ]
    router_seq = [
        snap(t, counters={"fleet_admission_rejected_total": 0,
                          "fleet_requests_total": 50})
        for t in range(1, steps + 1)
    ]
    return engine_seq, router_seq


def _cancel_sequences(steps: int = 24):
    """A cancel_storm-shaped fixture: clients abandon mid-decode (cancelled
    counters climb) but latency stays on budget and the fleet is busy —
    churn alone must neither page nor shrink the fleet."""
    from prime_tpu.loadgen.scenario import SCENARIOS, build_schedule

    schedule = build_schedule(SCENARIOS["cancel_storm"](seed=7), vocab=101)
    cancelled_total = sum(1 for r in schedule if r.cancel_after_s is not None)
    engine_seq, router_seq = [], []
    for t in range(1, steps + 1):
        served = 4 * t
        engine_seq.append(
            snap(
                t,
                counters={
                    "serve_tokens_emitted_total": served * 8,
                    "serve_requests_admitted_total": served,
                    "serve_requests_completed_total": served // 2,
                    "serve_requests_cancelled_total": min(cancelled_total, served // 2),
                },
                hists={"serve_ttft_seconds": [0.2] * served},
                gauges={"serve_active_slots": 6},
            )
        )
        router_seq.append(
            snap(t, counters={"fleet_admission_rejected_total": 0,
                              "fleet_requests_total": served})
        )
    return engine_seq, router_seq


SIM_WINDOWS = {"fast_s": 5.0, "slow_s": 15.0}


def test_replay_rate_storm_scales_up_byte_identically():
    engine_seq, router_seq = _storm_sequences()
    runs = []
    for _ in range(2):
        signals = replay(
            {"replica0": engine_seq},
            router_sequence=router_seq,
            capacity=8,
            **SIM_WINDOWS,
        )
        runs.append(json.dumps([s.to_dict() for s in signals], sort_keys=True))
        assert signals[-1].direction == "up"
        # the multi-window AND demands genuine slow-window coverage: the
        # storm's first seconds must NOT page (on a young ring the slow
        # window would evaluate the same seconds as the fast one)
        assert all(s.direction == "hold" for s in signals[:4])
        # the reason names the worst burner with its burn evidence
        assert "burning" in signals[-1].reason
        assert signals[-1].evidence
    # acceptance: byte-identical signals across reruns
    assert runs[0] == runs[1]


def test_replay_idle_scales_down_once_then_holds():
    engine_seq, router_seq = _idle_sequences()
    signals = replay(
        {"replica0": engine_seq},
        router_sequence=router_seq,
        capacity=16,
        **SIM_WINDOWS,
    )
    directions = [s.direction for s in signals]
    assert "up" not in directions
    first_down = directions.index("down")
    # before the slow window has history the evaluator must hold, not guess
    assert all(d == "hold" for d in directions[:first_down])
    # one recommendation per idle episode: down once, hold after
    assert directions[first_down] == "down"
    assert all(d == "hold" for d in directions[first_down + 1:])
    again = replay(
        {"replica0": engine_seq},
        router_sequence=router_seq,
        capacity=16,
        **SIM_WINDOWS,
    )
    assert json.dumps([s.to_dict() for s in signals], sort_keys=True) == json.dumps(
        [s.to_dict() for s in again], sort_keys=True
    )


def test_replay_cancel_storm_holds():
    engine_seq, router_seq = _cancel_sequences()
    signals = replay(
        {"replica0": engine_seq},
        router_sequence=router_seq,
        capacity=8,
        **SIM_WINDOWS,
    )
    assert {s.direction for s in signals} == {"hold"}


def test_default_policies_env_overrides(monkeypatch):
    monkeypatch.setenv("PRIME_SLO_TTFT_P95_S", "0.25")
    monkeypatch.setenv("PRIME_SLO_REJECT_RATE", "0.5")
    by_name = {p.name: p for p in default_policies()}
    assert by_name["ttft_p95"].threshold == pytest.approx(0.25)
    assert by_name["reject_rate"].threshold == pytest.approx(0.5)
    assert by_name["tpot_p95"].threshold == pytest.approx(0.5)  # untouched default


def test_evaluator_reports_no_data_without_windows():
    evaluator = SloEvaluator()
    verdicts, signal = evaluator.evaluate([SnapshotRing(depth=4)], None, capacity=8)
    assert signal.direction == "hold"
    assert all(v.fast.burn is None and not v.breached for v in verdicts)


# ---- membership capture tolerance (satellite) -------------------------------


def test_membership_apply_metrics_tolerance():
    """The observatory-era registry payload parses with the digest's
    tolerance contract: junk shapes, junk sections, pre-observatory replies
    all degrade to 'not sampled' — never an exception."""
    m = FleetMembership(["http://127.0.0.1:1"])
    replica = next(iter(m.replicas.values()))
    for junk in (
        None, 7, "nope", [], {"engine": "nope"}, {"engine": {}},
        {"engine": {"captured_at": "junk"}},
        {"engine": {"captured_at": {"series": "x"}}},
        {"engine": {"serve_tokens_emitted_total": {"series": [{"value": "NaNope"}]}}},
    ):
        assert m.apply_metrics(replica, junk) is False
    assert len(replica.ring) <= 1 and replica.resets == 0
    # a well-formed payload samples; a shrunk re-poll counts a reset and
    # fires the hook the router counts fleet_replica_resets_total from
    events = []
    m._on_sample = lambda r, reset: events.append((r.id, reset))
    assert m.apply_metrics(replica, {"engine": snap(10, counters={"c_total": 5})}) is False
    assert m.apply_metrics(replica, {"engine": snap(20, counters={"c_total": 1})}) is True
    assert replica.resets == 1
    assert events == [(replica.id, False), (replica.id, True)]


class _JunkMetricsHandler(BaseHTTPRequestHandler):
    """A replica whose /healthz is fine but whose /metrics is hostile:
    junk JSON or an oversized body. The poll must still succeed."""

    payload = b"not json at all {{{"

    def log_message(self, *args):  # noqa: D102 — quiet
        pass

    def do_GET(self):
        if self.path.startswith("/healthz"):
            body = json.dumps({"state": "ready", "queue_depth": 1}).encode()
        else:
            body = self.payload
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def test_poll_survives_junk_and_oversized_metrics_payloads():
    from prime_tpu.obs.timeseries import MAX_SAMPLE_BYTES

    server = ThreadingHTTPServer(("127.0.0.1", 0), _JunkMetricsHandler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    url = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        m = FleetMembership([url])
        replica = next(iter(m.replicas.values()))
        m.poll_once(replica)
        assert replica.state == "ready" and replica.queue_depth == 1
        assert len(replica.ring) == 0  # junk skipped, poll intact
        # oversized payload: skipped before parsing, poll still healthy
        _JunkMetricsHandler.payload = b"[" + b"0," * (MAX_SAMPLE_BYTES // 2) + b"0]"
        m.poll_once(replica)
        assert replica.state == "ready"
        assert len(replica.ring) == 0
    finally:
        _JunkMetricsHandler.payload = b"not json at all {{{"
        server.shutdown()
        server.server_close()


# ---- live endpoints ---------------------------------------------------------


class _ScriptedBackend:
    concurrent = True

    def __init__(self):
        self.registry = Registry()
        self._tokens = self.registry.counter(
            "serve_tokens_emitted_total", "tokens")
        self._ttft = self.registry.histogram("serve_ttft_seconds", "ttft")
        self.registry.gauge("serve_active_slots", "slots").set(2)

    def stats(self):
        return {"queue_depth": 0, "active_slots": 2, "max_slots": 8}

    def generate(self, prompts, max_new_tokens, temperature, top_p=1.0, templated=False):
        self._tokens.inc(8)
        self._ttft.observe(0.05)
        return ["ok"] * len(prompts)


@pytest.fixture
def fleet():
    from prime_tpu.serve import InferenceServer
    from prime_tpu.serve.fleet import serve_fleet

    backends = [_ScriptedBackend(), _ScriptedBackend()]
    servers = [
        InferenceServer("tiny-test", b, port=0, admin_token="obs-secret").start()
        for b in backends
    ]
    router = serve_fleet(
        [srv.url for srv in servers],
        poll_interval=0.05,
        model_id="tiny-test",
        admin_token="obs-secret",
    )
    try:
        yield router, servers
    finally:
        router.stop()
        for srv in servers:
            srv.stop()


def test_gauge_mean_absent_family_is_none_not_zero():
    """'No data' must never read as zero utilization: a ring whose
    snapshots never carried the gauge answers None (a loading replica
    without serve_active_slots is not an idle one)."""
    ring = SnapshotRing(depth=4)
    ring.append(snap(0, counters={"c_total": 1}))
    ring.append(snap(10, counters={"c_total": 2}))
    assert ring.gauge_mean("serve_active_slots", 30) is None
    ring.append(snap(20, counters={"c_total": 3}, gauges={"serve_active_slots": 4}))
    assert ring.gauge_mean("serve_active_slots", 30) == pytest.approx(4.0)


def test_router_observatory_filters_stale_replica_rings(fleet):
    """A dead replica's frozen ring must not pin its last windows into
    every future evaluation: only freshly-polled replicas feed the merged
    fleet view (the table still lists everyone)."""
    router, _servers = fleet
    router.membership.poll_all()
    assert len(router._fresh_replicas()) == 2
    stale = next(iter(router.membership.replicas.values()))
    stale.last_poll_at -= 3600.0  # as if its last successful poll was an hour ago
    fresh = router._fresh_replicas()
    assert len(fresh) == 1 and fresh[0].id != stale.id
    view = router.observatory_view()
    assert len(view["replicas"]) == 2  # visibility is not freshness


def test_router_observatory_endpoint_shape_and_auth(fleet):
    router, servers = fleet
    # chat traffic so the rings have token counters to window
    for _ in range(3):
        response = httpx.post(
            f"{router.url}/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "hello observatory"}]},
            timeout=30,
        )
        assert response.status_code == 200
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        router.membership.poll_all()
        if all(len(r.ring) >= 2 for r in router.membership.replicas.values()):
            break
    # admin parity: no token -> 403, token -> the view
    assert (
        httpx.get(f"{router.url}/admin/observatory", timeout=5).status_code == 403
    )
    view = httpx.get(
        f"{router.url}/admin/observatory",
        headers={"Authorization": "Bearer obs-secret"},
        timeout=5,
    ).json()
    assert set(view) >= {"windows", "signal", "slo", "replicas", "fleet", "resets"}
    assert view["signal"]["direction"] in ("up", "down", "hold")
    assert len(view["replicas"]) == 2
    assert all(row["samples"] >= 2 for row in view["replicas"])
    fast = view["fleet"]["fast"]
    assert fast["span_s"] and fast["tok_s"] is not None and fast["tok_s"] > 0
    policies = {entry["policy"] for entry in view["slo"]}
    assert {"ttft_p95", "reject_rate", "utilization_floor"} <= policies
    # the observatory observes itself: gauge exposed + catalog-clean text
    from pathlib import Path

    from prime_tpu.analysis.obs_contract import load_metrics_catalog
    from prime_tpu.obs import lint_prometheus_text

    catalog = load_metrics_catalog(
        (Path(__file__).parent.parent / "docs" / "observability.md").read_text()
    )
    text = httpx.get(
        f"{router.url}/metrics", params={"format": "prometheus"}, timeout=5
    ).text
    assert "fleet_scale_signal" in text
    assert lint_prometheus_text(text, catalog=catalog) == []


def test_server_observatory_endpoint(fleet):
    _router, servers = fleet
    httpx.post(
        f"{servers[0].url}/v1/chat/completions",
        json={"messages": [{"role": "user", "content": "hi"}]},
        timeout=30,
    ).raise_for_status()
    servers[0].observatory_sample()
    view = httpx.get(
        f"{servers[0].url}/admin/observatory",
        headers={"Authorization": "Bearer obs-secret"},
        timeout=5,
    ).json()
    assert set(view) >= {"windows", "signal", "slo", "replica", "serving"}
    assert view["replica"]["samples"] >= 1
    assert view["serving"]["fast"]["window_s"] == FAST_WINDOW_S
    # admin parity holds on the server too
    assert (
        httpx.get(f"{servers[0].url}/admin/observatory", timeout=5).status_code
        == 403
    )


def test_serve_top_cli_once_and_json(fleet):
    from click.testing import CliRunner

    from prime_tpu.commands.serve import serve_cmd

    router, _servers = fleet
    router.membership.poll_all()
    result = CliRunner().invoke(
        serve_cmd,
        ["top", "--url", router.url, "--once", "--admin-token", "obs-secret"],
    )
    assert result.exit_code == 0, result.output
    assert "signal:" in result.output and "Replicas" in result.output
    as_json = CliRunner().invoke(
        serve_cmd,
        ["top", "--url", router.url, "--once", "--admin-token", "obs-secret",
         "--output", "json"],
    )
    assert as_json.exit_code == 0, as_json.output
    payload = json.loads(as_json.output)
    assert payload["signal"]["direction"] in ("up", "down", "hold")
    # a missing token is a clean error, not a stack trace
    denied = CliRunner().invoke(serve_cmd, ["top", "--url", router.url, "--once"])
    assert denied.exit_code != 0 and "admin token" in denied.output


def test_serve_metrics_watch_cli(fleet):
    from click.testing import CliRunner

    from prime_tpu.commands.serve import serve_cmd

    router, _servers = fleet
    httpx.post(
        f"{router.url}/v1/chat/completions",
        json={"messages": [{"role": "user", "content": "hi"}]},
        timeout=30,
    ).raise_for_status()
    result = CliRunner().invoke(
        serve_cmd,
        ["metrics", "--url", router.url, "--watch", "0.05", "--count", "2"],
    )
    assert result.exit_code == 0, result.output
    assert "per_s" in result.output
    # watch is a live table mode; machine formats must refuse loudly
    bad = CliRunner().invoke(
        serve_cmd,
        ["metrics", "--url", router.url, "--watch", "1", "--output", "json"],
    )
    assert bad.exit_code != 0


# ---- acceptance: observatory tok/s vs loadgen report ------------------------


@pytest.mark.slow
def test_observatory_tok_s_within_10pct_of_slo_report():
    """Acceptance pin: GET /admin/observatory on a smoke-style fleet reports
    windowed tok/s within 10% of the loadgen SLO report's registry-delta
    tok/s for the same run — the two systems window the SAME counters, one
    live, one post-hoc."""
    import jax
    import jax.numpy as jnp

    from prime_tpu.loadgen.backends import HTTPTarget, NumericTokenizer
    from prime_tpu.loadgen.report import scenario_row
    from prime_tpu.loadgen.runner import run_schedule
    from prime_tpu.loadgen.scenario import SCENARIOS, build_schedule
    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.serve import InferenceServer
    from prime_tpu.serve.engine import ContinuousBatchingEngine, EngineBackend
    from prime_tpu.serve.fleet import serve_fleet

    config = get_config("tiny-test")
    schedule = build_schedule(SCENARIOS["smoke"](seed=5), vocab=config.vocab_size)
    engine = ContinuousBatchingEngine(
        init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32),
        config, pad_id=0, max_slots=4, capacity=128, chunk=4, prefix_cache_mb=8,
    )
    engine.start()
    server = InferenceServer(
        "tiny-test", EngineBackend(engine, NumericTokenizer()), port=0
    ).start()
    router = None
    try:
        # warm every prompt-length bucket BEFORE the router exists, so the
        # replica ring's whole history is the measured run (the report's
        # bracket and the ring's window must cover the same tokens)
        for n in sorted({len(r.prompt_ids) for r in schedule}):
            httpx.post(
                f"{server.url}/v1/chat/completions",
                json={"messages": [{"role": "user", "content": " ".join(["7"] * n)}],
                      "max_tokens": 4, "temperature": 0.0},
                timeout=120.0,
            ).raise_for_status()
        router = serve_fleet([server.url], poll_interval=0.05, model_id="tiny-test")
        target = HTTPTarget(
            router.url,
            scrape_urls={"router": router.url, "replica0": server.url},
            timeout_s=120.0,
        )
        result = run_schedule(
            schedule, target, scenario="smoke", seed=5, time_scale=0.5,
        )
        row = scenario_row(result)
        assert row["tok_s"] > 0, row
        router.membership.poll_all()  # a fresh trailing sample closes the window
        view = router.observatory_view()
        # the slow window covers the ring's whole (run-only) history
        live = view["fleet"]["slow"]["tok_s"]
        assert live is not None and live > 0
        assert live == pytest.approx(row["tok_s"], rel=0.10), (live, row["tok_s"])
        # token DELTAS agree exactly (same counters, same clamp rules)
        span = view["fleet"]["slow"]["span_s"]
        assert round(live * span) == pytest.approx(row["tokens"], rel=0.02)
    finally:
        if router is not None:
            router.stop()
        server.stop()  # shuts the engine down through the backend
