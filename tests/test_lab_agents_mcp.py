"""Agent runtime (subprocess stdio chat) + MCP server protocol tests."""

import io
import json
import subprocess
import sys
import textwrap

import pytest

from prime_tpu.lab.agents import AgentError, AgentRuntime
from prime_tpu.lab.mcp import build_tools, handle_request

# -- scripted fake agents ------------------------------------------------------

SIMPLE_AGENT = textwrap.dedent(
    """
    import json, sys
    for line in sys.stdin:
        msg = json.loads(line)
        if msg.get("type") == "prompt":
            for word in msg["text"].split():
                print(json.dumps({"type": "chunk", "text": word.upper() + " "}), flush=True)
            print(json.dumps({"type": "done", "id": msg["id"]}), flush=True)
    """
)

ACP_AGENT = textwrap.dedent(
    """
    import json, sys
    def send(obj):
        print(json.dumps(obj), flush=True)
    for line in sys.stdin:
        msg = json.loads(line)
        method = msg.get("method")
        if method == "initialize":
            send({"jsonrpc": "2.0", "id": msg["id"], "result": {"protocolVersion": 1}})
        elif method == "session/new":
            send({"jsonrpc": "2.0", "id": msg["id"], "result": {"sessionId": "sess-1"}})
        elif method == "session/prompt":
            text = msg["params"]["prompt"][0]["text"]
            assert msg["params"]["sessionId"] == "sess-1"
            for chunk in (text[:3], text[3:]):
                send({"jsonrpc": "2.0", "method": "session/update",
                      "params": {"update": {"sessionUpdate": "agent_message_chunk",
                                             "content": {"type": "text", "text": chunk}}}})
            send({"jsonrpc": "2.0", "id": msg["id"], "result": {"stopReason": "end_turn"}})
    """
)

CRASHING_AGENT = "import sys; sys.exit(3)"


def _agent(script: str, dialect: str) -> AgentRuntime:
    return AgentRuntime([sys.executable, "-u", "-c", script], dialect=dialect)


def test_simple_dialect_chat():
    with _agent(SIMPLE_AGENT, "simple") as agent:
        assert agent.chat("hello tpu world", timeout_s=20) == "HELLO TPU WORLD "
        # second turn on the same process
        assert agent.chat("again", timeout_s=20) == "AGAIN "


def test_acp_dialect_handshake_and_chat():
    with _agent(ACP_AGENT, "acp") as agent:
        assert agent.dialect.session_id == "sess-1"
        assert agent.chat("ping-pong", timeout_s=20) == "ping-pong"


def test_agent_crash_is_detected():
    agent = _agent(CRASHING_AGENT, "simple")
    agent.start()
    with pytest.raises(AgentError, match="exited|closed"):
        agent.chat("anything", timeout_s=10)
    agent.close()


def test_unknown_dialect_rejected():
    with pytest.raises(AgentError, match="unknown dialect"):
        AgentRuntime(["true"], dialect="letta-v9")


def test_agent_turn_timeout():
    hang = "import sys\nfor line in sys.stdin: pass"
    agent = AgentRuntime([sys.executable, "-u", "-c", hang], dialect="simple")
    agent.start()
    with pytest.raises(AgentError, match="timed out"):
        agent.chat("no reply", timeout_s=1.0)
    agent.close()


# -- MCP server ---------------------------------------------------------------


def _rpc(method, params=None, request_id=1):
    msg = {"jsonrpc": "2.0", "id": request_id, "method": method}
    if params is not None:
        msg["params"] = params
    return msg


def test_mcp_initialize_and_tools_list(tmp_path):
    tools = build_tools(str(tmp_path))
    response = handle_request(_rpc("initialize"), tools)
    assert response["result"]["serverInfo"]["name"] == "prime-lab"
    listing = handle_request(_rpc("tools/list"), tools)
    names = {t["name"] for t in listing["result"]["tools"]}
    assert {"lab_snapshot", "lab_eval_runs", "lab_launch_cards", "lab_hygiene"} <= names


def test_mcp_tool_call_eval_runs(tmp_path):
    run_dir = tmp_path / "outputs" / "evals" / "arith--m" / "r1"
    run_dir.mkdir(parents=True)
    (run_dir / "metadata.json").write_text(json.dumps({"metrics": {"accuracy": 1.0}}))
    tools = build_tools(str(tmp_path))
    response = handle_request(
        _rpc("tools/call", {"name": "lab_eval_runs", "arguments": {}}), tools
    )
    rows = json.loads(response["result"]["content"][0]["text"])
    assert rows[0]["env"] == "arith" and rows[0]["accuracy"] == 1.0


def test_mcp_unknown_tool_and_method(tmp_path):
    tools = build_tools(str(tmp_path))
    bad_tool = handle_request(_rpc("tools/call", {"name": "nope"}), tools)
    assert bad_tool["error"]["code"] == -32602
    bad_method = handle_request(_rpc("frobnicate"), tools)
    assert bad_method["error"]["code"] == -32601
    assert handle_request({"jsonrpc": "2.0", "method": "notifications/initialized"}, tools) is None


def test_mcp_tool_error_is_in_band(tmp_path, monkeypatch):
    tools = build_tools(str(tmp_path / "missing-dir"))
    response = handle_request(
        _rpc("tools/call", {"name": "lab_hygiene", "arguments": {}}), tools
    )
    payload = response["result"]
    assert payload.get("isError") is True
    assert "error" in payload["content"][0]["text"]


def test_mcp_stdio_end_to_end(tmp_path):
    """Spawn the real `prime lab mcp` process and speak the protocol."""
    messages = "\n".join(
        json.dumps(m)
        for m in [
            _rpc("initialize", request_id=1),
            {"jsonrpc": "2.0", "method": "notifications/initialized"},
            _rpc("tools/list", request_id=2),
            _rpc("tools/call", {"name": "lab_launch_cards", "arguments": {}}, request_id=3),
        ]
    )
    proc = subprocess.run(
        [sys.executable, "-m", "prime_tpu.commands.main", "lab", "mcp", "--dir", str(tmp_path)],
        input=messages + "\n",
        capture_output=True,
        text=True,
        timeout=60,
        cwd="/root/repo",
    )
    responses = [json.loads(line) for line in proc.stdout.splitlines() if line.strip()]
    assert len(responses) == 3  # notification produced no response
    assert responses[0]["result"]["protocolVersion"]
    assert json.loads(responses[2]["result"]["content"][0]["text"]) == []


# -- CLI agent turn -----------------------------------------------------------


def test_lab_agent_cli_one_turn(tmp_path):
    from click.testing import CliRunner

    from prime_tpu.commands.main import cli

    script = tmp_path / "agent.py"
    script.write_text(SIMPLE_AGENT)
    result = CliRunner().invoke(
        cli,
        ["lab", "agent", "hello world", "--dialect", "simple",
         "--command", f"{sys.executable} -u {script}"],
    )
    assert result.exit_code == 0, result.output
    assert "HELLO WORLD" in result.output


def test_agent_nonobject_json_does_not_kill_reader():
    weird = textwrap.dedent(
        """
        import json, sys
        print("null", flush=True)
        print("[1,2,3]", flush=True)
        for line in sys.stdin:
            msg = json.loads(line)
            if msg.get("type") == "prompt":
                print(json.dumps({"type": "chunk", "text": "ok"}), flush=True)
                print(json.dumps({"type": "done"}), flush=True)
        """
    )
    with AgentRuntime([sys.executable, "-u", "-c", weird], dialect="simple") as agent:
        assert agent.chat("x", timeout_s=20) == "ok"


def test_stale_turn_events_are_drained():
    slow = textwrap.dedent(
        """
        import json, sys, time
        for line in sys.stdin:
            msg = json.loads(line)
            if msg.get("type") != "prompt":
                continue
            text = msg["text"]
            if text == "warmup":
                print(json.dumps({"type": "chunk", "text": "ok"}), flush=True)
            elif text == "turn1":
                time.sleep(2)  # answer turn 1 late
                print(json.dumps({"type": "chunk", "text": "STALE"}), flush=True)
            else:
                print(json.dumps({"type": "chunk", "text": "fresh"}), flush=True)
            print(json.dumps({"type": "done"}), flush=True)
        """
    )
    agent = AgentRuntime([sys.executable, "-u", "-c", slow], dialect="simple")
    agent.start()
    assert agent.chat("warmup", timeout_s=30) == "ok"  # agent fully up
    with pytest.raises(AgentError, match="timed out"):
        agent.chat("turn1", timeout_s=0.5)
    import time as _time

    _time.sleep(2.5)  # let the stale answer land in the queue
    assert agent.chat("turn2", timeout_s=20) == "fresh"
    agent.close()


def test_mcp_rejects_nonobject_requests(tmp_path):
    proc = subprocess.run(
        [sys.executable, "-m", "prime_tpu.commands.main", "lab", "mcp", "--dir", str(tmp_path)],
        input='[1,2]\n"str"\n' + json.dumps(_rpc("tools/list", request_id=9)) + "\n",
        capture_output=True,
        text=True,
        timeout=60,
        cwd="/root/repo",
    )
    responses = [json.loads(line) for line in proc.stdout.splitlines() if line.strip()]
    assert responses[0]["error"]["code"] == -32600
    assert responses[1]["error"]["code"] == -32600
    assert "tools" in responses[2]["result"]  # server survived bad input
