"""Agent runtime (subprocess stdio chat) + MCP server protocol tests."""

import io
import json
import subprocess
import sys
import textwrap

import pytest

from prime_tpu.lab.agents import AgentError, AgentRuntime
from prime_tpu.lab.mcp import build_tools, handle_request

from _markers import get_tomllib

# -- scripted fake agents ------------------------------------------------------

SIMPLE_AGENT = textwrap.dedent(
    """
    import json, sys
    for line in sys.stdin:
        msg = json.loads(line)
        if msg.get("type") == "prompt":
            for word in msg["text"].split():
                print(json.dumps({"type": "chunk", "text": word.upper() + " "}), flush=True)
            print(json.dumps({"type": "done", "id": msg["id"]}), flush=True)
    """
)

ACP_AGENT = textwrap.dedent(
    """
    import json, sys
    def send(obj):
        print(json.dumps(obj), flush=True)
    for line in sys.stdin:
        msg = json.loads(line)
        method = msg.get("method")
        if method == "initialize":
            send({"jsonrpc": "2.0", "id": msg["id"], "result": {"protocolVersion": 1}})
        elif method == "session/new":
            send({"jsonrpc": "2.0", "id": msg["id"], "result": {"sessionId": "sess-1"}})
        elif method == "session/prompt":
            text = msg["params"]["prompt"][0]["text"]
            assert msg["params"]["sessionId"] == "sess-1"
            for chunk in (text[:3], text[3:]):
                send({"jsonrpc": "2.0", "method": "session/update",
                      "params": {"update": {"sessionUpdate": "agent_message_chunk",
                                             "content": {"type": "text", "text": chunk}}}})
            send({"jsonrpc": "2.0", "id": msg["id"], "result": {"stopReason": "end_turn"}})
    """
)

CRASHING_AGENT = "import sys; sys.exit(3)"


def _agent(script: str, dialect: str) -> AgentRuntime:
    return AgentRuntime([sys.executable, "-u", "-c", script], dialect=dialect)


def test_simple_dialect_chat():
    with _agent(SIMPLE_AGENT, "simple") as agent:
        assert agent.chat("hello tpu world", timeout_s=20) == "HELLO TPU WORLD "
        # second turn on the same process
        assert agent.chat("again", timeout_s=20) == "AGAIN "


def test_acp_dialect_handshake_and_chat():
    with _agent(ACP_AGENT, "acp") as agent:
        assert agent.dialect.session_id == "sess-1"
        assert agent.chat("ping-pong", timeout_s=20) == "ping-pong"


def test_agent_crash_is_detected():
    agent = _agent(CRASHING_AGENT, "simple")
    agent.start()
    with pytest.raises(AgentError, match="exited|closed"):
        agent.chat("anything", timeout_s=10)
    agent.close()


def test_unknown_dialect_rejected():
    with pytest.raises(AgentError, match="unknown dialect"):
        AgentRuntime(["true"], dialect="letta-v9")


def test_agent_turn_timeout():
    hang = "import sys\nfor line in sys.stdin: pass"
    agent = AgentRuntime([sys.executable, "-u", "-c", hang], dialect="simple")
    agent.start()
    with pytest.raises(AgentError, match="timed out"):
        agent.chat("no reply", timeout_s=1.0)
    agent.close()


# -- MCP server ---------------------------------------------------------------


def _rpc(method, params=None, request_id=1):
    msg = {"jsonrpc": "2.0", "id": request_id, "method": method}
    if params is not None:
        msg["params"] = params
    return msg


def test_mcp_initialize_and_tools_list(tmp_path):
    tools = build_tools(str(tmp_path))
    response = handle_request(_rpc("initialize"), tools)
    assert response["result"]["serverInfo"]["name"] == "prime-lab"
    listing = handle_request(_rpc("tools/list"), tools)
    names = {t["name"] for t in listing["result"]["tools"]}
    assert {"lab_snapshot", "lab_eval_runs", "lab_launch_cards", "lab_hygiene"} <= names


def test_mcp_tool_call_eval_runs(tmp_path):
    run_dir = tmp_path / "outputs" / "evals" / "arith--m" / "r1"
    run_dir.mkdir(parents=True)
    (run_dir / "metadata.json").write_text(json.dumps({"metrics": {"accuracy": 1.0}}))
    tools = build_tools(str(tmp_path))
    response = handle_request(
        _rpc("tools/call", {"name": "lab_eval_runs", "arguments": {}}), tools
    )
    rows = json.loads(response["result"]["content"][0]["text"])
    assert rows[0]["env"] == "arith" and rows[0]["accuracy"] == 1.0


def test_mcp_unknown_tool_and_method(tmp_path):
    tools = build_tools(str(tmp_path))
    bad_tool = handle_request(_rpc("tools/call", {"name": "nope"}), tools)
    assert bad_tool["error"]["code"] == -32602
    bad_method = handle_request(_rpc("frobnicate"), tools)
    assert bad_method["error"]["code"] == -32601
    assert handle_request({"jsonrpc": "2.0", "method": "notifications/initialized"}, tools) is None


def test_mcp_tool_error_is_in_band(tmp_path, monkeypatch):
    tools = build_tools(str(tmp_path / "missing-dir"))
    response = handle_request(
        _rpc("tools/call", {"name": "lab_hygiene", "arguments": {}}), tools
    )
    payload = response["result"]
    assert payload.get("isError") is True
    assert "error" in payload["content"][0]["text"]


def test_mcp_stdio_end_to_end(tmp_path):
    """Spawn the real `prime lab mcp` process and speak the protocol."""
    messages = "\n".join(
        json.dumps(m)
        for m in [
            _rpc("initialize", request_id=1),
            {"jsonrpc": "2.0", "method": "notifications/initialized"},
            _rpc("tools/list", request_id=2),
            _rpc("tools/call", {"name": "lab_launch_cards", "arguments": {}}, request_id=3),
        ]
    )
    proc = subprocess.run(
        [sys.executable, "-m", "prime_tpu.commands.main", "lab", "mcp", "--dir", str(tmp_path)],
        input=messages + "\n",
        capture_output=True,
        text=True,
        timeout=60,
        cwd="/root/repo",
    )
    responses = [json.loads(line) for line in proc.stdout.splitlines() if line.strip()]
    assert len(responses) == 3  # notification produced no response
    assert responses[0]["result"]["protocolVersion"]
    assert json.loads(responses[2]["result"]["content"][0]["text"]) == []


# -- CLI agent turn -----------------------------------------------------------


def test_lab_agent_cli_one_turn(tmp_path):
    from click.testing import CliRunner

    from prime_tpu.commands.main import cli

    script = tmp_path / "agent.py"
    script.write_text(SIMPLE_AGENT)
    result = CliRunner().invoke(
        cli,
        ["lab", "agent", "hello world", "--dialect", "simple",
         "--command", f"{sys.executable} -u {script}"],
    )
    assert result.exit_code == 0, result.output
    assert "HELLO WORLD" in result.output


def test_agent_nonobject_json_does_not_kill_reader():
    weird = textwrap.dedent(
        """
        import json, sys
        print("null", flush=True)
        print("[1,2,3]", flush=True)
        for line in sys.stdin:
            msg = json.loads(line)
            if msg.get("type") == "prompt":
                print(json.dumps({"type": "chunk", "text": "ok"}), flush=True)
                print(json.dumps({"type": "done"}), flush=True)
        """
    )
    with AgentRuntime([sys.executable, "-u", "-c", weird], dialect="simple") as agent:
        assert agent.chat("x", timeout_s=20) == "ok"


def test_stale_turn_events_are_drained():
    slow = textwrap.dedent(
        """
        import json, sys, time
        for line in sys.stdin:
            msg = json.loads(line)
            if msg.get("type") != "prompt":
                continue
            text = msg["text"]
            if text == "warmup":
                print(json.dumps({"type": "chunk", "text": "ok"}), flush=True)
            elif text == "turn1":
                time.sleep(2)  # answer turn 1 late
                print(json.dumps({"type": "chunk", "text": "STALE"}), flush=True)
            else:
                print(json.dumps({"type": "chunk", "text": "fresh"}), flush=True)
            print(json.dumps({"type": "done"}), flush=True)
        """
    )
    agent = AgentRuntime([sys.executable, "-u", "-c", slow], dialect="simple")
    agent.start()
    assert agent.chat("warmup", timeout_s=30) == "ok"  # agent fully up
    with pytest.raises(AgentError, match="timed out"):
        agent.chat("turn1", timeout_s=0.5)
    import time as _time

    _time.sleep(2.5)  # let the stale answer land in the queue
    assert agent.chat("turn2", timeout_s=20) == "fresh"
    agent.close()


def test_mcp_rejects_nonobject_requests(tmp_path):
    proc = subprocess.run(
        [sys.executable, "-m", "prime_tpu.commands.main", "lab", "mcp", "--dir", str(tmp_path)],
        input='[1,2]\n"str"\n' + json.dumps(_rpc("tools/list", request_id=9)) + "\n",
        capture_output=True,
        text=True,
        timeout=60,
        cwd="/root/repo",
    )
    responses = [json.loads(line) for line in proc.stdout.splitlines() if line.strip()]
    assert responses[0]["error"]["code"] == -32600
    assert responses[1]["error"]["code"] == -32600
    assert "tools" in responses[2]["result"]  # server survived bad input


# -- codex app-server dialect (VERDICT r2 #4) ---------------------------------

CODEX_AGENT = textwrap.dedent(
    """
    import json, sys
    def send(obj):
        print(json.dumps(obj), flush=True)
    for line in sys.stdin:
        msg = json.loads(line)
        method = msg.get("method")
        if method == "initialize":
            send({"jsonrpc": "2.0", "id": msg["id"], "result": {}})
        elif method == "thread/start":
            assert isinstance(msg["params"].get("dynamicTools"), list)
            assert any(t["name"] == "choose" for t in msg["params"]["dynamicTools"])
            send({"jsonrpc": "2.0", "id": msg["id"], "result": {"thread": {"id": "th-1"}}})
        elif method == "turn/start":
            assert msg["params"]["threadId"] == "th-1"
            text = msg["params"]["input"][0]["text"]
            for piece in (text[:2], text[2:]):
                send({"jsonrpc": "2.0", "method": "item/agentMessage/delta",
                      "params": {"delta": piece, "turnId": "t1"}})
            send({"jsonrpc": "2.0", "method": "item/tool/call",
                  "params": {"name": "show_chart", "arguments": {"values": [1, 2, 3]}}})
            send({"jsonrpc": "2.0", "method": "turn/completed", "params": {"turn": {}}})
    """
)

LETTA_AGENT = textwrap.dedent(
    """
    import json, sys
    def send(obj):
        print(json.dumps(obj), flush=True)
    send({"type": "system", "session_id": "lt-1"})
    for line in sys.stdin:
        msg = json.loads(line)
        if msg.get("type") == "control_request":
            continue  # client-initiated init/register: no reply needed
        if msg.get("type") == "control_response":
            continue
        if msg.get("type") == "user":
            text = msg["message"]["content"]
            # ask permission first; the client must auto-allow
            send({"type": "control_request", "request_id": "r1",
                  "request": {"subtype": "can_use_tool", "tool_name": "choose"}})
            granted = json.loads(input())
            assert granted["response"]["response"]["behavior"] == "allow"
            send({"type": "control_request", "request_id": "r2",
                  "request": {"subtype": "execute_external_tool",
                               "tool_name": "choose",
                               "arguments": {"options": ["a", "b"]}}})
            ack = json.loads(input())
            assert ack["response"]["response"]["status"] == "rendered"
            send({"type": "assistant", "message": {"role": "assistant",
                  "content": [{"type": "text", "text": text[::-1]}]}})
            send({"type": "result"})
    """
)


def test_codex_dialect_chat_and_widget():
    with _agent(CODEX_AGENT, "codex") as agent:
        events = list(agent.prompt("hello", timeout_s=20))
    text = "".join(e.text for e in events if e.kind == "chunk")
    widgets = [e.widget for e in events if e.kind == "widget"]
    assert text == "hello"
    assert widgets == [{"name": "show_chart", "args": {"values": [1, 2, 3]}}]
    # handshake captured the thread id
    assert agent.dialect.session_id == "th-1"


def test_codex_turn_error_raises():
    script = textwrap.dedent(
        """
        import json, sys
        def send(obj):
            print(json.dumps(obj), flush=True)
        for line in sys.stdin:
            msg = json.loads(line)
            if msg.get("method") == "initialize":
                send({"jsonrpc": "2.0", "id": msg["id"], "result": {}})
            elif msg.get("method") == "thread/start":
                send({"jsonrpc": "2.0", "id": msg["id"], "result": {"thread": {"id": "t"}}})
            elif msg.get("method") == "turn/start":
                send({"jsonrpc": "2.0", "method": "turn/completed",
                      "params": {"turn": {"error": {"message": "model overloaded"}}}})
        """
    )
    with _agent(script, "codex") as agent:
        with pytest.raises(AgentError, match="model overloaded"):
            list(agent.prompt("hi", timeout_s=20))


def test_letta_dialect_auto_allows_tools_and_streams():
    with _agent(LETTA_AGENT, "letta") as agent:
        events = list(agent.prompt("abc", timeout_s=20))
    text = "".join(e.text for e in events if e.kind == "chunk")
    widgets = [e.widget for e in events if e.kind == "widget"]
    assert text == "cba"
    assert widgets == [{"name": "choose", "args": {"options": ["a", "b"]}}]
    assert agent.dialect.session_id == "lt-1"


# -- widget contract -----------------------------------------------------------


def test_widget_specs_cover_both_wire_shapes():
    from prime_tpu.lab.widgets import WIDGET_TOOLS, letta_external_tools, widget_tool_specs

    names = {t.name for t in WIDGET_TOOLS}
    assert {"choose", "show_table", "show_chart", "launch_run", "show_patch"} <= names
    codex = widget_tool_specs()
    letta = letta_external_tools()
    assert {t["name"] for t in codex} == names == {t["name"] for t in letta}
    assert all("parameters" in t for t in codex)
    assert all(t["label"].startswith("Lab ") for t in letta)


def test_widget_render_and_validation():
    from rich.console import Console

    from prime_tpu.lab.widgets import render_widget, validate_widget_call

    assert validate_widget_call("choose", {}) is not None          # missing options
    assert validate_widget_call("choose", {"options": ["x"]}) is None
    assert validate_widget_call("nope", {}) is not None
    console = Console(width=80, file=io.StringIO(), force_terminal=False)
    console.print(render_widget("show_table", {"rows": [{"a": 1, "b": 2}]}))
    console.print(render_widget("show_chart", {"values": [1.0, 5.0, 2.0]}))
    console.print(render_widget("choose", {"options": ["first", "second"]}))
    console.print(render_widget("bad_tool", {}))
    out = console.file.getvalue()
    assert "first" in out and "widget error" in out


# -- in-shell chat screen ------------------------------------------------------


class _ScriptedRuntime:
    """Deterministic in-process stand-in for AgentRuntime."""

    def __init__(self):
        self.started = False
        self.closed = False

    def start(self):
        self.started = True

    def close(self):
        self.closed = True

    def prompt(self, text, timeout_s=120.0):
        from prime_tpu.lab.agents import AgentEvent

        yield AgentEvent("chunk", text=f"echo:{text}")
        yield AgentEvent("widget", widget={"name": "choose", "args": {"options": ["x", "y"]}})


def test_chat_screen_turn_and_widget_render():
    from rich.console import Console

    from prime_tpu.lab.tui.chat import AgentChatScreen

    screen = AgentChatScreen("tester", _ScriptedRuntime)
    for ch in "hi!":
        screen.on_key(ch)
    assert screen.input_buffer == "hi!"
    screen.on_key("enter")
    assert screen.wait_idle(5)
    roles = [e["role"] for e in screen.transcript]
    assert roles == ["user", "assistant", "widget"]
    assert screen.transcript[1]["text"] == "echo:hi!"
    console = Console(width=90, file=io.StringIO(), force_terminal=False)
    console.print(screen.render())
    out = console.file.getvalue()
    assert "echo:hi!" in out and "choose" in out


def test_chat_screen_esc_clears_then_closes():
    from prime_tpu.lab.tui.chat import AgentChatScreen
    from prime_tpu.lab.tui.detail import CLOSE

    runtime = _ScriptedRuntime()
    screen = AgentChatScreen("tester", lambda: runtime)
    screen.on_key("x")
    assert screen.on_key("escape") is None and screen.input_buffer == ""
    screen.on_key("h")
    screen.on_key("enter")
    assert screen.wait_idle(5)
    assert screen.on_key("escape") == CLOSE
    assert runtime.closed


class _WidgetScriptRuntime:
    """Emits a launch proposal for 'launch', otherwise echo + a choose."""

    def start(self):
        pass

    def close(self):
        pass

    def prompt(self, text, timeout_s=120.0):
        from prime_tpu.lab.agents import AgentEvent

        if text == "launch":
            yield AgentEvent(
                "widget",
                widget={
                    "name": "launch_run",
                    "args": {
                        "kind": "eval",
                        "config": {"env": "gsm8k", "model": "m1", "nested": {"x": 1}},
                    },
                },
            )
        else:
            yield AgentEvent("chunk", text=f"echo:{text}")
            yield AgentEvent("widget", widget={"name": "choose", "args": {"options": ["x", "y"]}})


def test_chat_choice_selection_roundtrip():
    from rich.console import Console

    from prime_tpu.lab.tui.chat import AgentChatScreen

    screen = AgentChatScreen("tester", _WidgetScriptRuntime)
    screen.on_key("h")
    screen.on_key("enter")
    assert screen.wait_idle(5)
    assert screen.pending is not None and screen.pending["name"] == "choose"
    # pending cursor renders as a marker
    console = Console(width=90, file=io.StringIO(), force_terminal=False)
    console.print(screen.render())
    assert "▸" in console.file.getvalue()
    screen.on_key("down")          # cursor -> y
    screen.on_key("enter")         # select: answer goes back as a user turn
    assert screen.wait_idle(5)
    widget = next(e for e in screen.transcript if e["role"] == "widget")
    assert widget["args"]["selected"] == "y"
    texts = [e.get("text") for e in screen.transcript if e.get("role") == "user"]
    assert "y" in texts
    assert any(e.get("text") == "echo:y" for e in screen.transcript)


def test_chat_launch_proposal_writes_card(tmp_path):
    tomllib = get_tomllib()

    from prime_tpu.lab.tui.chat import AgentChatScreen

    screen = AgentChatScreen("tester", _WidgetScriptRuntime, workspace=str(tmp_path))
    for ch in "launch":
        screen.on_key(ch)
    screen.on_key("enter")
    assert screen.wait_idle(5)
    assert screen.pending is not None and screen.pending["name"] == "launch_run"
    status = screen.on_key("enter")    # act on the proposal
    assert "launch card written" in status
    assert screen.pending is None
    card_path = tmp_path / ".prime-lab" / "launch" / "tester-proposal.toml"
    data = tomllib.loads(card_path.read_text())
    assert data["launch"]["kind"] == "eval"
    assert data["eval"] == {"env": "gsm8k", "model": "m1"}   # nested value filtered
    widget = next(e for e in screen.transcript if e["role"] == "widget")
    assert widget["args"]["saved_card"] == "tester-proposal.toml"


def test_chat_launch_kind_normalized_and_bad_kind_rejected(tmp_path):
    tomllib = get_tomllib()

    from prime_tpu.lab.tui.chat import AgentChatScreen

    screen = AgentChatScreen("tester", _WidgetScriptRuntime, workspace=str(tmp_path))
    # kind='training' (widget enum) must become a 'train' card scan_cards accepts
    screen.transcript.append(
        {"role": "widget", "name": "launch_run",
         "args": {"kind": "training", "config": {"model": "m1", "steps": 5}}}
    )
    screen.pending = screen.transcript[-1]
    status = screen.on_key("enter")
    assert "launch card written" in status
    card = tmp_path / ".prime-lab" / "launch" / "tester-proposal.toml"
    data = tomllib.loads(card.read_text())
    assert data["launch"]["kind"] == "train" and data["train"]["steps"] == 5
    from prime_tpu.lab.tui.launch import scan_cards

    assert any(c.kind == "train" for c in scan_cards(tmp_path))
    # unsupported kind is refused, not silently lost
    screen.transcript.append(
        {"role": "widget", "name": "launch_run", "args": {"kind": "pod", "config": {"x": 1}}}
    )
    screen.pending = screen.transcript[-1]
    assert "eval' or 'training" in screen.on_key("enter")


def test_chat_launch_without_config_refused(tmp_path):
    from prime_tpu.lab.tui.chat import AgentChatScreen

    screen = AgentChatScreen("tester", _WidgetScriptRuntime, workspace=str(tmp_path))
    screen.transcript.append(
        {"role": "widget", "name": "launch_run", "args": {"kind": "eval"}}
    )
    screen.pending = screen.transcript[-1]
    status = screen.on_key("enter")
    assert "unusable proposal" in status
    # no template-default card was fabricated
    assert not (tmp_path / ".prime-lab" / "launch").exists()


def test_chat_whitespace_enter_acts_and_selection_matches_render():
    """Selection acts on the NORMALIZED options — the exact list the panel
    renders (a blank option is dropped by the widget model, so the cursor
    lands on the only real option and the agent receives its label, not a
    positional answer for an entry the UI never showed)."""
    from prime_tpu.lab.tui.chat import AgentChatScreen

    screen = AgentChatScreen("tester", _WidgetScriptRuntime)
    screen.transcript.append(
        {"role": "widget", "name": "choose", "args": {"options": ["", "retry"]}}
    )
    screen.pending = screen.transcript[-1]
    screen.on_key(" ")             # stray whitespace then enter still selects
    status = screen.on_key("enter")
    assert "selected" in status
    assert screen.wait_idle(5)
    user_turns = [e["text"] for e in screen.transcript if e.get("role") == "user"]
    assert user_turns == ["retry"]
    # all options unusable -> the widget refuses rather than misrendering
    screen.transcript.append(
        {"role": "widget", "name": "choose", "args": {"options": ["", "  "]}}
    )
    screen.pending = screen.transcript[-1]
    assert "no options" in screen.on_key("enter")


def test_chat_free_text_overrides_pending_choice():
    from prime_tpu.lab.tui.chat import AgentChatScreen

    screen = AgentChatScreen("tester", _WidgetScriptRuntime)
    screen.on_key("h")
    screen.on_key("enter")
    assert screen.wait_idle(5)
    first_widget = screen.pending
    for ch in "neither":
        screen.on_key(ch)
    screen.on_key("enter")         # typed reply, not a selection
    assert screen.wait_idle(5)
    assert "selected" not in first_widget["args"]
    assert any(e.get("text") == "neither" for e in screen.transcript)


def test_chat_section_lists_configured_agents(tmp_path):
    from prime_tpu.lab.tui.chat import load_agents_config

    cfg_dir = tmp_path / ".prime-lab"
    cfg_dir.mkdir()
    (cfg_dir / "agents.json").write_text(
        json.dumps({"agents": [
            {"name": "codex", "command": "codex app-server", "dialect": "codex"},
            {"name": "broken"},  # no command: skipped
        ]})
    )
    rows = load_agents_config(tmp_path)
    assert rows == [{"name": "codex", "dialect": "codex", "command": "codex app-server"}]
    assert load_agents_config(tmp_path / "nope") == []


# -- MCP widget + detail tools -------------------------------------------------


def test_mcp_widget_tools_journal(tmp_path):
    tools = build_tools(str(tmp_path))
    listed = handle_request({"jsonrpc": "2.0", "id": 1, "method": "tools/list"}, tools)
    names = {t["name"] for t in listed["result"]["tools"]}
    assert {"lab_widget_choose", "lab_widget_show_chart", "lab_training_runs",
            "lab_eval_samples"} <= names
    good = handle_request(
        {"jsonrpc": "2.0", "id": 2, "method": "tools/call",
         "params": {"name": "lab_widget_choose", "arguments": {"options": ["a"]}}},
        tools,
    )
    payload = json.loads(good["result"]["content"][0]["text"])
    assert payload["status"] == "rendered"
    journal = (tmp_path / ".prime-lab" / "widgets.jsonl").read_text().strip()
    assert json.loads(journal) == {"name": "choose", "args": {"options": ["a"]}}
    bad = handle_request(
        {"jsonrpc": "2.0", "id": 3, "method": "tools/call",
         "params": {"name": "lab_widget_choose", "arguments": {}}},
        tools,
    )
    assert json.loads(bad["result"]["content"][0]["text"])["status"] == "invalid"


def test_mcp_eval_samples_tool(tmp_path):
    run_dir = tmp_path / "outputs" / "evals" / "gsm8k--m1" / "r7"
    run_dir.mkdir(parents=True)
    (run_dir / "metadata.json").write_text(json.dumps({"metrics": {"accuracy": 1.0}}))
    (run_dir / "results.jsonl").write_text(
        json.dumps({"prompt": "p", "completion": "c", "reward": 1.0}) + "\n"
    )
    tools = build_tools(str(tmp_path))
    response = handle_request(
        {"jsonrpc": "2.0", "id": 4, "method": "tools/call",
         "params": {"name": "lab_eval_samples", "arguments": {"runId": "r7"}}},
        tools,
    )
    samples = json.loads(response["result"]["content"][0]["text"])
    assert samples[0]["prompt"] == "p"


def test_chat_form_edit_launch_roundtrip(tmp_path):
    """configure_run form: field edits stamp form_values, typed errors stay
    on the form, a valid enter writes the launch card (VERDICT r4 #3)."""
    tomllib = get_tomllib()

    from prime_tpu.lab.tui.chat import AgentChatScreen

    screen = AgentChatScreen("tester", lambda: None, workspace=str(tmp_path))
    screen.transcript.append(
        {"role": "widget", "name": "configure_run",
         "args": {"kind": "eval", "env": "gsm8k", "config": {"model": "tiny-test"}}}
    )
    screen.pending = screen.transcript[-1]

    # a field edit is intercepted (not sent to the agent) and stamped
    for ch in "limit=abc":
        screen.on_key(ch)
    status = screen.on_key("enter")
    assert status == "limit = abc"
    assert screen.pending["args"]["form_values"] == {"limit": "abc"}
    assert not any(e.get("role") == "user" for e in screen.transcript)

    # enter with a bad integer keeps the form pending, errors stamped
    status = screen.on_key("enter")
    assert "fix the form" in status
    assert screen.pending is not None
    assert screen.pending["args"]["form_errors"]

    # repair the field, launch: card written with typed values
    for ch in "limit=20":
        screen.on_key(ch)
    screen.on_key("enter")
    assert screen.pending["args"].get("form_errors") is None
    status = screen.on_key("enter")
    assert "launch card written" in status, status
    assert screen.pending is None
    card = tmp_path / ".prime-lab" / "launch" / "tester-form.toml"
    data = tomllib.loads(card.read_text())
    assert data["launch"]["kind"] == "eval"
    assert data["eval"]["limit"] == 20 and isinstance(data["eval"]["limit"], int)
    assert data["eval"]["env"] == "gsm8k"
    widget = next(e for e in screen.transcript if e["role"] == "widget")
    assert widget["args"]["saved_card"] == "tester-form.toml"


def test_chat_form_stop_dismisses(tmp_path):
    from prime_tpu.lab.tui.chat import AgentChatScreen
    from prime_tpu.lab.tui.launch import scan_cards

    screen = AgentChatScreen("tester", lambda: None, workspace=str(tmp_path))
    screen.transcript.append(
        {"role": "widget", "name": "configure_run", "args": {"kind": "rl"}}
    )
    screen.pending = screen.transcript[-1]
    for ch in "stop":
        screen.on_key(ch)
    assert screen.on_key("enter") == "form dismissed"
    assert screen.pending is None and scan_cards(tmp_path) == []


def test_chat_form_renders_with_workspace_options(tmp_path):
    import io

    from rich.console import Console

    from prime_tpu.envhub.packaging import write_env_template
    from prime_tpu.lab.tui.chat import AgentChatScreen

    write_env_template(tmp_path / "environments" / "wordle", "wordle")
    screen = AgentChatScreen("tester", lambda: None, workspace=str(tmp_path))
    screen.transcript.append(
        {"role": "widget", "name": "configure_run", "args": {"kind": "eval"}}
    )
    screen.pending = screen.transcript[-1]
    console = Console(width=100, file=io.StringIO(), force_terminal=False)
    console.print(screen.render())
    out = console.file.getvalue()
    assert "Evaluate wordle" in out       # env select seeded from the workspace
    assert "name=value" in out            # edit hint
