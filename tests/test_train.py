"""Hosted training: TOML schema, dispatch, monitoring."""

import json

import pytest
from click.testing import CliRunner

import prime_tpu.commands._deps as deps
from prime_tpu.commands.main import cli
from prime_tpu.testing import FakeControlPlane
from prime_tpu.train.config import RL_TOML_TEMPLATE, load_rl_config, strip_deprecated


@pytest.fixture
def fake(monkeypatch):
    fake = FakeControlPlane()
    monkeypatch.setattr(deps, "transport_override", fake.transport)
    monkeypatch.setenv("PRIME_API_KEY", "test-key")
    monkeypatch.setenv("PRIME_BASE_URL", "https://api.fake")
    return fake


@pytest.fixture
def runner():
    return CliRunner()


@pytest.fixture
def toml_file(tmp_path):
    path = tmp_path / "job.toml"
    path.write_text(RL_TOML_TEMPLATE.format(name="my-run"))
    return path


# -- schema ------------------------------------------------------------------


def test_template_parses(toml_file):
    config, warnings = load_rl_config(toml_file)
    assert config.name == "my-run" and config.type == "lora"
    assert config.infrastructure.tpu_type == "v5e-8"
    assert warnings == []


def test_unknown_key_is_an_error(tmp_path):
    path = tmp_path / "bad.toml"
    path.write_text('name = "x"\nmodel = "m"\nbogus_key = 1\n[env]\nid = "e"\n')
    import pydantic

    with pytest.raises(pydantic.ValidationError):
        load_rl_config(path)


def test_deprecated_gpu_keys_stripped_with_warning(tmp_path):
    raw = {"name": "x", "gpu_type": "H100", "env": {"id": "e", "nccl_timeout": 30}}
    cleaned, warnings = strip_deprecated(raw)
    assert "gpu_type" not in cleaned
    assert "nccl_timeout" not in cleaned["env"]
    assert any("tpu_type" in w for w in warnings)
    assert any("no TPU equivalent" in w for w in warnings)


def test_full_finetune_detection(tmp_path):
    path = tmp_path / "ft.toml"
    path.write_text('name = "ft"\nmodel = "llama3-8b"\ntype = "full_finetune"\n[env]\nid = "e"\n')
    config, _ = load_rl_config(path)
    assert config.is_full_finetune


# -- dispatch ----------------------------------------------------------------


def test_train_run_lora_dispatch(runner, fake, toml_file):
    result = runner.invoke(cli, ["train", "run", str(toml_file), "--yes", "--output", "json"])
    assert result.exit_code == 0, result.output
    run_id = json.loads(result.output)["runId"]
    payload = fake.training_plane.payloads[run_id]
    assert payload["tpuType"] == "v5e-8" and payload["adapter"]["r"] == 16


def test_train_default_group_toml_shorthand(runner, fake, toml_file):
    """`prime train foo.toml` ≡ `prime train run foo.toml`."""
    result = runner.invoke(cli, ["train", str(toml_file), "--yes"])
    assert result.exit_code == 0, result.output
    assert "dispatched" in result.output


def test_train_full_ft_ships_whole_toml(runner, fake, tmp_path):
    path = tmp_path / "ft.toml"
    path.write_text(
        'name = "ft"\nmodel = "llama3-70b"\ntype = "full_finetune"\n'
        '[env]\nid = "e"\n[infrastructure]\ntpu_type = "v5p-64"\nnum_slices = 2\n'
    )
    result = runner.invoke(cli, ["train", str(path), "--yes", "--output", "json"])
    assert result.exit_code == 0, result.output
    run_id = json.loads(result.output)["runId"]
    payload = fake.training_plane.payloads[run_id]
    assert "config" in payload and 'type = "full_finetune"' in payload["config"]
    assert payload["tpuType"] == "v5p-64" and payload["numSlices"] == 2
    assert fake.training_plane.runs[run_id]["runToken"].startswith("rtok_")


def test_invalid_config_fails_cleanly(runner, fake, tmp_path):
    path = tmp_path / "bad.toml"
    path.write_text('name = "x"\nmodel = "m"\nwrong = 1\n[env]\nid = "e"\n')
    result = runner.invoke(cli, ["train", str(path), "--yes"])
    assert result.exit_code != 0
    assert "Invalid config" in result.output and "wrong" in result.output


# -- monitoring --------------------------------------------------------------


def _dispatch(runner, toml_file) -> str:
    result = runner.invoke(cli, ["train", "run", str(toml_file), "--yes", "--output", "json"])
    return json.loads(result.output)["runId"]


def test_lifecycle_and_monitoring(runner, fake, toml_file):
    run_id = _dispatch(runner, toml_file)
    result = runner.invoke(cli, ["train", "list", "--plain"])
    assert "my-run" in result.output

    # status advances per poll
    runner.invoke(cli, ["train", "get", run_id])
    result = runner.invoke(cli, ["train", "get", run_id, "--output", "json"])
    assert json.loads(result.output)["status"] in ("RUNNING", "COMPLETED")

    result = runner.invoke(cli, ["train", "logs", run_id, "--component", "trainer", "--worker", "0", "--plain"])
    assert "trainer w0" in result.output and "inference" not in result.output

    result = runner.invoke(cli, ["train", "metrics", run_id, "--output", "json"])
    assert "loss" in json.loads(result.output)

    result = runner.invoke(cli, ["train", "progress", run_id, "--output", "json"])
    assert "pct" in json.loads(result.output)

    result = runner.invoke(cli, ["train", "rollouts", run_id, "--plain"])
    assert "rollout" in result.output

    result = runner.invoke(cli, ["train", "components", run_id, "--plain"])
    assert "trainer" in result.output

    # drive to completion, then checkpoints exist
    for _ in range(4):
        runner.invoke(cli, ["train", "get", run_id])
    result = runner.invoke(cli, ["train", "checkpoints", run_id, "--output", "json"])
    assert json.loads(result.output)


def test_stop_restart_delete(runner, fake, toml_file):
    run_id = _dispatch(runner, toml_file)
    result = runner.invoke(cli, ["train", "stop", run_id])
    assert "STOPPED" in result.output
    result = runner.invoke(cli, ["train", "restart", run_id])
    assert "PENDING" in result.output
    assert runner.invoke(cli, ["train", "delete", run_id, "--yes"]).exit_code == 0
    result = runner.invoke(cli, ["train", "list", "--output", "json"])
    assert json.loads(result.output) == []


def test_models_tpus_configs(runner, fake):
    result = runner.invoke(cli, ["train", "models", "--plain"])
    assert "llama3-8b" in result.output and "llama3-70b" in result.output
    result = runner.invoke(cli, ["train", "tpus", "--plain"])
    assert "v5p-64" in result.output
    result = runner.invoke(cli, ["train", "configs"])
    schema = json.loads(result.output)
    assert schema["properties"]["infrastructure"]


def test_train_init_writes_template(runner, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    result = runner.invoke(cli, ["train", "init", "exp1"])
    assert result.exit_code == 0
    assert (tmp_path / "exp1.toml").exists()
    config, _ = load_rl_config(tmp_path / "exp1.toml")
    assert config.name == "exp1"


# -- native trainer checkpoint/metrics ---------------------------------------


def test_checkpoint_save_restore_roundtrip(tmp_path):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.train import default_optimizer, init_train_state, make_train_step
    from prime_tpu.train.checkpoint import CheckpointManager

    cfg = get_config("tiny-test")
    optimizer = default_optimizer(learning_rate=1e-2)
    state = init_train_state(init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32), optimizer)
    step = make_train_step(cfg, optimizer)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones_like(tokens, dtype=jnp.float32)
    state, _ = step(state, tokens, targets, mask)

    manager = CheckpointManager(tmp_path / "ckpts", keep=2)
    saved_step = manager.save(state, metrics={"loss": 1.0})
    assert saved_step == 1
    assert manager.latest_step() == 1

    fresh = init_train_state(init_params(jax.random.PRNGKey(7), cfg, dtype=jnp.float32), optimizer)
    restored = manager.restore(fresh)
    np.testing.assert_array_equal(
        np.asarray(restored.params["embed"]), np.asarray(state.params["embed"])
    )
    assert int(restored.step) == 1
    # resumed training continues from the restored state
    resumed, metrics = step(restored, tokens, targets, mask)
    assert int(resumed.step) == 2 and np.isfinite(float(metrics["loss"]))
    manager.close()


def test_checkpoint_retention(tmp_path):
    import jax
    import jax.numpy as jnp

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.train import default_optimizer, init_train_state
    from prime_tpu.train.checkpoint import CheckpointManager
    from prime_tpu.train.trainer import TrainState

    cfg = get_config("tiny-test")
    optimizer = default_optimizer()
    state = init_train_state(init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32), optimizer)
    manager = CheckpointManager(tmp_path / "ckpts", keep=2)
    for i in range(1, 5):
        state = TrainState(state.params, state.opt_state, jnp.asarray(i))
        manager.save(state)
    assert manager.latest_step() == 4
    steps = sorted(int(p.name) for p in (tmp_path / "ckpts").iterdir() if p.name.isdigit())
    assert steps == [3, 4]  # retention pruned older checkpoints
    manager.close()


def test_metrics_logger(tmp_path):
    from prime_tpu.train.metrics import MetricsLogger

    logger = MetricsLogger(tmp_path)
    logger.log(1, loss=2.5, grad_norm=1.1)
    logger.log(2, loss=2.1, note="warmup done")
    rows = logger.read()
    assert [r["step"] for r in rows] == [1, 2]
    assert rows[0]["loss"] == 2.5
    assert logger.last()["note"] == "warmup done"
