"""Hosted training: TOML schema, dispatch, monitoring."""

import json

import pytest
from click.testing import CliRunner

import prime_tpu.commands._deps as deps
from prime_tpu.commands.main import cli
from prime_tpu.testing import FakeControlPlane
from prime_tpu.train.config import RL_TOML_TEMPLATE, load_rl_config, strip_deprecated

from _markers import requires_shard_map


@pytest.fixture
def fake(monkeypatch):
    fake = FakeControlPlane()
    monkeypatch.setattr(deps, "transport_override", fake.transport)
    monkeypatch.setenv("PRIME_API_KEY", "test-key")
    monkeypatch.setenv("PRIME_BASE_URL", "https://api.fake")
    return fake


@pytest.fixture
def runner():
    return CliRunner()


@pytest.fixture
def toml_file(tmp_path):
    path = tmp_path / "job.toml"
    path.write_text(RL_TOML_TEMPLATE.format(name="my-run"))
    return path


# -- schema ------------------------------------------------------------------


def test_template_parses(toml_file):
    config, warnings = load_rl_config(toml_file)
    assert config.name == "my-run" and config.type == "lora"
    assert config.infrastructure.tpu_type == "v5e-8"
    assert warnings == []


def test_unknown_key_is_an_error(tmp_path):
    path = tmp_path / "bad.toml"
    path.write_text('name = "x"\nmodel = "m"\nbogus_key = 1\n[env]\nid = "e"\n')
    import pydantic

    with pytest.raises(pydantic.ValidationError):
        load_rl_config(path)


def test_deprecated_gpu_keys_stripped_with_warning(tmp_path):
    raw = {"name": "x", "gpu_type": "H100", "env": {"id": "e", "nccl_timeout": 30}}
    cleaned, warnings = strip_deprecated(raw)
    assert "gpu_type" not in cleaned
    assert "nccl_timeout" not in cleaned["env"]
    assert any("tpu_type" in w for w in warnings)
    assert any("no TPU equivalent" in w for w in warnings)


def test_full_finetune_detection(tmp_path):
    path = tmp_path / "ft.toml"
    path.write_text('name = "ft"\nmodel = "llama3-8b"\ntype = "full_finetune"\n[env]\nid = "e"\n')
    config, _ = load_rl_config(path)
    assert config.is_full_finetune


# -- dispatch ----------------------------------------------------------------


def test_train_run_lora_dispatch(runner, fake, toml_file):
    result = runner.invoke(cli, ["train", "run", str(toml_file), "--yes", "--output", "json"])
    assert result.exit_code == 0, result.output
    run_id = json.loads(result.output)["runId"]
    payload = fake.training_plane.payloads[run_id]
    assert payload["tpuType"] == "v5e-8" and payload["adapter"]["r"] == 16


def test_train_default_group_toml_shorthand(runner, fake, toml_file):
    """`prime train foo.toml` ≡ `prime train run foo.toml`."""
    result = runner.invoke(cli, ["train", str(toml_file), "--yes"])
    assert result.exit_code == 0, result.output
    assert "dispatched" in result.output


def test_train_full_ft_ships_whole_toml(runner, fake, tmp_path):
    path = tmp_path / "ft.toml"
    path.write_text(
        'name = "ft"\nmodel = "llama3-70b"\ntype = "full_finetune"\n'
        '[env]\nid = "e"\n[infrastructure]\ntpu_type = "v5p-64"\nnum_slices = 2\n'
    )
    result = runner.invoke(cli, ["train", str(path), "--yes", "--output", "json"])
    assert result.exit_code == 0, result.output
    run_id = json.loads(result.output)["runId"]
    payload = fake.training_plane.payloads[run_id]
    assert "config" in payload and 'type = "full_finetune"' in payload["config"]
    assert payload["tpuType"] == "v5p-64" and payload["numSlices"] == 2
    assert fake.training_plane.runs[run_id]["runToken"].startswith("rtok_")


def test_invalid_config_fails_cleanly(runner, fake, tmp_path):
    path = tmp_path / "bad.toml"
    path.write_text('name = "x"\nmodel = "m"\nwrong = 1\n[env]\nid = "e"\n')
    result = runner.invoke(cli, ["train", str(path), "--yes"])
    assert result.exit_code != 0
    assert "Invalid config" in result.output and "wrong" in result.output


# -- monitoring --------------------------------------------------------------


def _dispatch(runner, toml_file) -> str:
    result = runner.invoke(cli, ["train", "run", str(toml_file), "--yes", "--output", "json"])
    return json.loads(result.output)["runId"]


def test_lifecycle_and_monitoring(runner, fake, toml_file):
    run_id = _dispatch(runner, toml_file)
    result = runner.invoke(cli, ["train", "list", "--plain"])
    assert "my-run" in result.output

    # status advances per poll
    runner.invoke(cli, ["train", "get", run_id])
    result = runner.invoke(cli, ["train", "get", run_id, "--output", "json"])
    assert json.loads(result.output)["status"] in ("RUNNING", "COMPLETED")

    result = runner.invoke(cli, ["train", "logs", run_id, "--component", "trainer", "--worker", "0", "--plain"])
    assert "trainer w0" in result.output and "inference" not in result.output

    result = runner.invoke(cli, ["train", "metrics", run_id, "--output", "json"])
    assert "loss" in json.loads(result.output)

    result = runner.invoke(cli, ["train", "progress", run_id, "--output", "json"])
    assert "pct" in json.loads(result.output)

    result = runner.invoke(cli, ["train", "rollouts", run_id, "--plain"])
    assert "rollout" in result.output

    result = runner.invoke(cli, ["train", "components", run_id, "--plain"])
    assert "trainer" in result.output

    # drive to completion, then checkpoints exist
    for _ in range(4):
        runner.invoke(cli, ["train", "get", run_id])
    result = runner.invoke(cli, ["train", "checkpoints", run_id, "--output", "json"])
    assert json.loads(result.output)


def test_stop_restart_delete(runner, fake, toml_file):
    run_id = _dispatch(runner, toml_file)
    result = runner.invoke(cli, ["train", "stop", run_id])
    assert "STOPPED" in result.output
    result = runner.invoke(cli, ["train", "restart", run_id])
    assert "PENDING" in result.output
    assert runner.invoke(cli, ["train", "delete", run_id, "--yes"]).exit_code == 0
    result = runner.invoke(cli, ["train", "list", "--output", "json"])
    assert json.loads(result.output) == []


def test_models_tpus_configs(runner, fake):
    result = runner.invoke(cli, ["train", "models", "--plain"])
    assert "llama3-8b" in result.output and "llama3-70b" in result.output
    result = runner.invoke(cli, ["train", "tpus", "--plain"])
    assert "v5p-64" in result.output
    result = runner.invoke(cli, ["train", "configs"])
    schema = json.loads(result.output)
    assert schema["properties"]["infrastructure"]


def test_train_init_writes_template(runner, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    result = runner.invoke(cli, ["train", "init", "exp1"])
    assert result.exit_code == 0
    assert (tmp_path / "exp1.toml").exists()
    config, _ = load_rl_config(tmp_path / "exp1.toml")
    assert config.name == "exp1"


# -- native trainer checkpoint/metrics ---------------------------------------


def test_checkpoint_save_restore_roundtrip(tmp_path):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.train import default_optimizer, init_train_state, make_train_step
    from prime_tpu.train.checkpoint import CheckpointManager

    cfg = get_config("tiny-test")
    optimizer = default_optimizer(learning_rate=1e-2)
    state = init_train_state(init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32), optimizer)
    step = make_train_step(cfg, optimizer)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones_like(tokens, dtype=jnp.float32)
    state, _ = step(state, tokens, targets, mask)

    manager = CheckpointManager(tmp_path / "ckpts", keep=2)
    saved_step = manager.save(state, metrics={"loss": 1.0})
    assert saved_step == 1
    assert manager.latest_step() == 1

    fresh = init_train_state(init_params(jax.random.PRNGKey(7), cfg, dtype=jnp.float32), optimizer)
    restored = manager.restore(fresh)
    np.testing.assert_array_equal(
        np.asarray(restored.params["embed"]), np.asarray(state.params["embed"])
    )
    assert int(restored.step) == 1
    # resumed training continues from the restored state
    resumed, metrics = step(restored, tokens, targets, mask)
    assert int(resumed.step) == 2 and np.isfinite(float(metrics["loss"]))
    manager.close()


def test_checkpoint_retention(tmp_path):
    import jax
    import jax.numpy as jnp

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.train import default_optimizer, init_train_state
    from prime_tpu.train.checkpoint import CheckpointManager
    from prime_tpu.train.trainer import TrainState

    cfg = get_config("tiny-test")
    optimizer = default_optimizer()
    state = init_train_state(init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32), optimizer)
    manager = CheckpointManager(tmp_path / "ckpts", keep=2)
    for i in range(1, 5):
        state = TrainState(state.params, state.opt_state, jnp.asarray(i))
        manager.save(state)
    assert manager.latest_step() == 4
    steps = sorted(int(p.name) for p in (tmp_path / "ckpts").iterdir() if p.name.isdigit())
    assert steps == [3, 4]  # retention pruned older checkpoints
    manager.close()


def test_metrics_logger(tmp_path):
    from prime_tpu.train.metrics import MetricsLogger

    logger = MetricsLogger(tmp_path)
    logger.log(1, loss=2.5, grad_norm=1.1)
    logger.log(2, loss=2.1, note="warmup done")
    rows = logger.read()
    assert [r["step"] for r in rows] == [1, 2]
    assert rows[0]["loss"] == 2.5
    assert logger.last()["note"] == "warmup done"


# -- round-2 trainer depth: accumulation, schedule, loop ----------------------


def test_grad_accumulation_matches_full_batch():
    """accum_steps=N on batch B must match one step on the same batch (same
    data, same update) up to fp32 accumulation noise."""
    import jax
    import jax.numpy as jnp

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.train import default_optimizer, init_train_state, make_train_step

    config = get_config("tiny-test")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    optimizer = default_optimizer(learning_rate=1e-3)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, config.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones_like(tokens, dtype=jnp.float32)

    # the jitted step donates its state: each run needs its own buffers
    params_b = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    state_a = init_train_state(params, optimizer)
    state_b = init_train_state(params_b, optimizer)
    step_full = make_train_step(config, optimizer)
    step_accum = make_train_step(config, optimizer, accum_steps=2)

    state_a, metrics_a = step_full(state_a, tokens, targets, mask)
    state_b, metrics_b = step_accum(state_b, tokens, targets, mask)

    import numpy as np

    np.testing.assert_allclose(
        float(metrics_a["loss"]), float(metrics_b["loss"]), rtol=1e-5, atol=1e-5
    )
    leaves_a = jax.tree.leaves(state_a.params)
    leaves_b = jax.tree.leaves(state_b.params)
    for a, b in zip(leaves_a, leaves_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


def test_grad_accumulation_rejects_indivisible_batch():
    import jax
    import jax.numpy as jnp
    import pytest

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.train import default_optimizer, init_train_state, make_train_step

    config = get_config("tiny-test")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    optimizer = default_optimizer()
    state = init_train_state(params, optimizer)
    step = make_train_step(config, optimizer, accum_steps=3)
    tokens = jnp.zeros((4, 8), jnp.int32)
    with pytest.raises(ValueError, match="not divisible"):
        step(state, tokens, tokens, jnp.ones((4, 8), jnp.float32))


def test_warmup_cosine_schedule_shape():
    from prime_tpu.train import warmup_cosine

    schedule = warmup_cosine(3e-4, total_steps=100, warmup_steps=10)
    assert float(schedule(0)) == 0.0
    assert abs(float(schedule(10)) - 3e-4) < 1e-9  # peak after warmup
    assert float(schedule(100)) < 3e-4 * 0.11  # decayed to the floor


def test_train_loop_times_and_logs(tmp_path):
    import jax
    import jax.numpy as jnp

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.train import (
        default_optimizer,
        init_train_state,
        make_train_step,
        train_loop,
    )
    from prime_tpu.train.metrics import MetricsLogger

    config = get_config("tiny-test")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    optimizer = default_optimizer(learning_rate=1e-3)
    state = init_train_state(params, optimizer)
    step = make_train_step(config, optimizer)

    def batches(n=4):
        for i in range(n):
            tokens = jax.random.randint(jax.random.PRNGKey(i), (2, 16), 0, config.vocab_size)
            yield tokens, jnp.roll(tokens, -1, axis=1), jnp.ones_like(tokens, jnp.float32)

    metrics = MetricsLogger(tmp_path)
    seen = []
    state, report = train_loop(
        state, step, batches(), metrics=metrics, on_step=lambda s, row: seen.append(s),
        profile_dir=str(tmp_path / "trace"), profile_window=(1, 3),
    )
    assert report.steps == 4 and seen == [0, 1, 2, 3]
    assert report.mean_step_time_s > 0 and report.tokens_per_sec > 0
    rows = metrics.read()
    assert len(rows) == 4 and rows[-1]["step_time_s"] > 0
    assert (tmp_path / "trace").exists()  # profiler trace captured


def test_accum_matches_full_batch_with_ragged_mask():
    """Token-weighted accumulation: ragged masks must give the same global
    objective as the full-batch step (mean-of-means would not)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.train import default_optimizer, init_train_state, make_train_step

    config = get_config("tiny-test")
    optimizer = default_optimizer(learning_rate=1e-3)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, config.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.zeros((4, 16), jnp.float32)
    mask = mask.at[0, :16].set(1.0).at[1, :2].set(1.0).at[2, :9].set(1.0).at[3, :1].set(1.0)

    state_a = init_train_state(init_params(jax.random.PRNGKey(0), config, jnp.float32), optimizer)
    state_b = init_train_state(init_params(jax.random.PRNGKey(0), config, jnp.float32), optimizer)
    state_a, ma = make_train_step(config, optimizer)(state_a, tokens, targets, mask)
    state_b, mb = make_train_step(config, optimizer, accum_steps=2)(state_b, tokens, targets, mask)
    np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(state_a.params), jax.tree.leaves(state_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


def test_bf16_params_get_fp32_adam_moments():
    import jax
    import jax.numpy as jnp

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.train import default_optimizer, init_train_state, make_train_step

    config = get_config("tiny-test")
    optimizer = default_optimizer(learning_rate=1e-3)
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.bfloat16)
    state = init_train_state(params, optimizer)
    moment_dtypes = {leaf.dtype for leaf in jax.tree.leaves(state.opt_state)}
    assert jnp.bfloat16 not in moment_dtypes  # both mu and nu fp32

    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, config.vocab_size)
    step = make_train_step(config, optimizer)
    state, metrics = step(state, tokens, jnp.roll(tokens, -1, 1), jnp.ones_like(tokens, jnp.float32))
    assert all(leaf.dtype == jnp.bfloat16 for leaf in jax.tree.leaves(state.params))
    assert jnp.bfloat16 not in {leaf.dtype for leaf in jax.tree.leaves(state.opt_state)}


def test_train_local_cli(tmp_path):
    from click.testing import CliRunner

    from prime_tpu.commands.main import cli

    result = CliRunner().invoke(
        cli,
        ["train", "local", "-m", "tiny-test", "--steps", "6", "-b", "4",
         "--seq-len", "32", "--accum", "2", "--lr", "1e-3",
         "--name", "cli-run", "--output-dir", str(tmp_path), "--output", "json"],
    )
    assert result.exit_code == 0, result.output
    import json as _json

    payload = _json.loads(result.output)
    assert payload["steps"] == 6 and payload["tokens_per_sec"] > 0
    metrics = (tmp_path / "cli-run" / "metrics.jsonl").read_text().splitlines()
    assert len(metrics) == 6


def test_train_local_cli_sharded_with_text_data(tmp_path):
    from click.testing import CliRunner

    from prime_tpu.commands.main import cli

    data = tmp_path / "corpus.txt"
    data.write_text("the quick brown fox jumps over the lazy dog. " * 200)
    result = CliRunner().invoke(
        cli,
        ["train", "local", "-m", "tiny-test", "--steps", "4", "-b", "4",
         "--seq-len", "32", "--slice", "v5e-8", "--data", str(data),
         "--name", "sharded-run", "--output-dir", str(tmp_path), "--plain"],
    )
    assert result.exit_code == 0, result.output
    assert "mesh" in result.output and "done:" in result.output


def test_text_batches_shapes_and_determinism(tmp_path):
    from prime_tpu.train.data import text_batches

    data = tmp_path / "c.txt"
    data.write_text("abcdefgh" * 100)
    a = list(text_batches(data, batch=2, seq=16, steps=3, seed=7))
    b = list(text_batches(data, batch=2, seq=16, steps=3, seed=7))
    assert len(a) == 3
    tokens, targets, mask = a[0]
    assert tokens.shape == (2, 16) == targets.shape == mask.shape
    import numpy as _np

    _np.testing.assert_array_equal(_np.asarray(a[1][0]), _np.asarray(b[1][0]))
    # next-token contract: targets are tokens shifted by one
    _np.testing.assert_array_equal(_np.asarray(a[0][0][:, 1:]), _np.asarray(a[0][1][:, :-1]))


@pytest.mark.parametrize("remat", ["full", "dots"])
def test_remat_train_step_matches_plain(remat):
    """jax.checkpoint is semantics-preserving: loss and the updated params
    must match the un-checkpointed step (fp32: exactly, modulo recompute
    ordering — pinned with a tight tolerance)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.train import default_optimizer, init_train_state, make_train_step

    cfg = get_config("tiny-test")
    optimizer = default_optimizer(learning_rate=1e-2)
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones_like(tokens, dtype=jnp.float32)

    from prime_tpu.models.llama import forward
    from prime_tpu.train.trainer import cross_entropy_loss

    # compare RAW gradients, not post-Adam params: a fresh Adam step
    # normalizes every gradient to ~±lr, so a single ULP-level sign flip at
    # a zero-gradient coordinate would look like a full-update difference
    def loss_with(remat_mode):
        def loss(p):
            logits, _ = forward(p, tokens, cfg, cache=None, remat=remat_mode)
            return cross_entropy_loss(logits, targets, mask)

        return jax.jit(jax.value_and_grad(loss))

    plain_loss, plain_grads = loss_with("none")(params)
    remat_loss, remat_grads = loss_with(remat)(params)
    np.testing.assert_allclose(float(plain_loss), float(remat_loss), rtol=1e-6)
    for plain_leaf, remat_leaf in zip(
        jax.tree.leaves(plain_grads), jax.tree.leaves(remat_grads)
    ):
        np.testing.assert_allclose(
            np.asarray(plain_leaf), np.asarray(remat_leaf), rtol=1e-4, atol=1e-6
        )

    # and the full donated train step compiles + runs under remat
    state, metrics = make_train_step(cfg, optimizer, remat=remat)(
        init_train_state(jax.tree.map(jnp.copy, params), optimizer), tokens, targets, mask
    )
    assert np.isfinite(float(metrics["loss"]))


def test_train_local_remat_cli(tmp_path):
    """--remat drives a real (tiny) local training run end to end."""
    runner = CliRunner()
    with runner.isolated_filesystem(temp_dir=tmp_path):
        result = runner.invoke(
            cli,
            ["train", "local", "-m", "tiny-test", "--steps", "2", "-b", "2",
             "--seq-len", "16", "--remat", "dots", "--name", "remat-run", "--plain"],
        )
        assert result.exit_code == 0, result.output
        assert "loss" in result.output


def test_train_local_lora_with_remat(tmp_path):
    """--remat composes with --lora: the adapter step checkpoints the merged
    forward the same way the full-FT step does."""
    runner = CliRunner()
    with runner.isolated_filesystem(temp_dir=tmp_path):
        result = runner.invoke(
            cli,
            ["train", "local", "-m", "tiny-test", "--steps", "2", "-b", "2",
             "--seq-len", "16", "--lora", "--remat", "full", "--plain",
             "--name", "lora-remat"],
        )
        assert result.exit_code == 0, result.output
        assert "loss" in result.output


def test_text_batches_rejects_tiny_corpus(tmp_path):
    import pytest as _pytest

    from prime_tpu.train.data import text_batches

    data = tmp_path / "tiny.txt"
    data.write_text("ab")
    with _pytest.raises(ValueError, match="need at least"):
        list(text_batches(data, batch=2, seq=128, steps=1))


def test_train_local_rejects_bad_accum_and_reused_name(tmp_path):
    from click.testing import CliRunner

    from prime_tpu.commands.main import cli

    runner = CliRunner()
    bad = runner.invoke(cli, ["train", "local", "--accum", "0", "--output-dir", str(tmp_path)])
    assert bad.exit_code != 0 and "--accum" in bad.output

    args = ["train", "local", "-m", "tiny-test", "--steps", "2", "-b", "2",
            "--seq-len", "16", "--name", "dup", "--output-dir", str(tmp_path), "--plain"]
    assert runner.invoke(cli, args).exit_code == 0
    rerun = runner.invoke(cli, args)
    assert rerun.exit_code != 0 and "already has metrics" in rerun.output


def test_text_batches_exact_window_corpus(tmp_path):
    """A corpus of exactly seq+1 tokens has one valid window and must work."""
    from prime_tpu.evals.tokenizer import ByteTokenizer
    from prime_tpu.train.data import text_batches

    seq = 7
    text = "x" * (seq + 1 - 1)  # byte tokenizer adds a BOS -> seq+1 tokens total
    data = tmp_path / "exact.txt"
    data.write_text(text)
    assert len(ByteTokenizer().encode(text)) == seq + 1
    batches = list(text_batches(data, batch=2, seq=seq, steps=2))
    assert batches[0][0].shape == (2, seq)


def test_train_local_resume_from_checkpoint(tmp_path):
    import json as _json

    from click.testing import CliRunner

    from prime_tpu.commands.main import cli

    runner = CliRunner()
    base = ["train", "local", "-m", "tiny-test", "-b", "2", "--seq-len", "16",
            "--name", "resumable", "--output-dir", str(tmp_path),
            "--checkpoint-every", "2", "--plain"]
    first = runner.invoke(cli, base + ["--steps", "4"])
    assert first.exit_code == 0, first.output

    resumed = runner.invoke(cli, base + ["--steps", "3", "--resume"])
    assert resumed.exit_code == 0, resumed.output
    assert "resumed resumable from step 4" in resumed.output

    rows = [_json.loads(l) for l in (tmp_path / "resumable" / "metrics.jsonl").read_text().splitlines()]
    steps = [r["step"] for r in rows]
    assert steps == [0, 1, 2, 3, 4, 5, 6]  # continuous numbering across resume


def test_train_local_resume_requires_name_and_checkpoints(tmp_path):
    from click.testing import CliRunner

    from prime_tpu.commands.main import cli

    runner = CliRunner()
    no_name = runner.invoke(cli, ["train", "local", "--resume", "--output-dir", str(tmp_path)])
    assert no_name.exit_code != 0 and "--name" in no_name.output
    no_ckpt = runner.invoke(
        cli, ["train", "local", "--resume", "--name", "x", "--output-dir", str(tmp_path)]
    )
    assert no_ckpt.exit_code != 0 and "--checkpoint-every" in no_ckpt.output


def test_train_local_rl_remat_cli(tmp_path):
    """GRPO with --remat: the checkpointed update forward trains end to end."""
    from click.testing import CliRunner

    from prime_tpu.commands.main import cli

    result = CliRunner().invoke(
        cli,
        ["train", "local-rl", "arith", "-m", "tiny-test", "--steps", "2",
         "-g", "2", "-p", "2", "--max-prompt-len", "16", "--max-new-tokens", "4",
         "--remat", "dots", "--name", "rl-remat", "--output-dir", str(tmp_path),
         "--plain"],
    )
    assert result.exit_code == 0, result.output
    assert (tmp_path / "rl-remat" / "metrics.jsonl").exists()


def test_train_local_rl_cli_arith(tmp_path):
    """`prime train local-rl arith`: native GRPO from the CLI — the built-in
    arith env drives rollouts, metrics.jsonl gets one row per step."""
    import json as _json

    from click.testing import CliRunner

    from prime_tpu.commands.main import cli

    result = CliRunner().invoke(
        cli,
        ["train", "local-rl", "arith", "-m", "tiny-test", "--steps", "3",
         "-g", "2", "-p", "2", "--max-prompt-len", "16", "--max-new-tokens", "4",
         "--lr", "1e-3", "--name", "rl-run", "--output-dir", str(tmp_path),
         "--output", "json"],
    )
    assert result.exit_code == 0, result.output
    payload = _json.loads(result.output)
    assert payload["steps"] == 3 and payload["env"] == "arith"
    rows = [
        _json.loads(l)
        for l in (tmp_path / "rl-run" / "metrics.jsonl").read_text().splitlines()
    ]
    assert len(rows) == 3
    assert all("reward_mean" in r and "kl" in r for r in rows)


def test_train_local_rl_rejects_bad_flags(tmp_path):
    from click.testing import CliRunner

    from prime_tpu.commands.main import cli

    runner = CliRunner()
    greedy = runner.invoke(
        cli, ["train", "local-rl", "arith", "--temperature", "0",
              "--output-dir", str(tmp_path)]
    )
    assert greedy.exit_code != 0 and "temperature" in greedy.output
    solo = runner.invoke(
        cli, ["train", "local-rl", "arith", "-g", "1", "--output-dir", str(tmp_path)]
    )
    assert solo.exit_code != 0 and "group_size" in solo.output


def test_train_local_rl_lora_cli(tmp_path):
    """`train local-rl --lora`: GRPO over frozen base, adapter artifact out."""
    import json as _json

    from click.testing import CliRunner

    from prime_tpu.commands.main import cli

    result = CliRunner().invoke(
        cli,
        ["train", "local-rl", "arith", "-m", "tiny-test", "--steps", "2",
         "-g", "2", "-p", "2", "--max-prompt-len", "16", "--max-new-tokens", "4",
         "--lora", "--lora-r", "4", "--name", "rl-lora", "--output-dir",
         str(tmp_path), "--output", "json"],
    )
    assert result.exit_code == 0, result.output
    payload = _json.loads(result.output)
    assert payload["steps"] == 2
    adapter_dir = payload["adapterDir"]
    assert (tmp_path / "rl-lora" / "adapters" / "adapters.npz").exists()
    meta = _json.loads(
        (tmp_path / "rl-lora" / "adapters" / "adapter_config.json").read_text()
    )
    assert meta["r"] == 4 and meta["base_model"] == "tiny-test"
    assert adapter_dir.endswith("adapters")


@requires_shard_map
def test_train_local_cli_context_parallel(tmp_path):
    """--sp shards the sequence over the ring (context parallelism) through
    the real CLI: mesh reported, loss finite, metrics written."""
    from click.testing import CliRunner

    from prime_tpu.commands.main import cli

    result = CliRunner().invoke(
        cli,
        ["train", "local", "-m", "tiny-test", "--steps", "3", "-b", "2",
         "--seq-len", "64", "--slice", "v5e-8", "--sp", "8",
         "--name", "cp-run", "--output-dir", str(tmp_path), "--plain"],
    )
    assert result.exit_code == 0, result.output
    assert "'sp': 8" in result.output and "context-parallel" in result.output
    assert "done:" in result.output
    assert (tmp_path / "cp-run" / "metrics.jsonl").exists()
    # guardrails: --sp without --slice, indivisible seq, per-layer schedules
    bad = CliRunner().invoke(
        cli, ["train", "local", "-m", "tiny-test", "--sp", "8", "--steps", "2"]
    )
    assert bad.exit_code != 0 and "--slice" in bad.output
    bad = CliRunner().invoke(
        cli,
        ["train", "local", "-m", "tiny-test", "--sp", "8", "--slice", "v5e-8",
         "--seq-len", "30", "--steps", "2"],
    )
    assert bad.exit_code != 0 and "divide" in bad.output
    bad = CliRunner().invoke(
        cli,
        ["train", "local", "-m", "tiny-gptoss", "--sp", "8", "--slice", "v5e-8",
         "--seq-len", "64", "--steps", "2"],
    )
    assert bad.exit_code != 0 and "uniform" in bad.output


def test_train_request_models(runner, fake):
    """`prime train request` submits a model request as product feedback
    (reference rl.py:1803)."""
    from prime_tpu.commands.main import cli

    result = runner.invoke(
        cli, ["train", "request", "-m", "meta/llama-4-behemoth", "--context", "RL"]
    )
    assert result.exit_code == 0, result.output
    assert "Thanks" in result.output
    assert any("llama-4-behemoth" in m["message"] for m in fake.misc_plane.feedback)
    # a blank models answer is rejected
    result = runner.invoke(cli, ["train", "request", "-m", "  "])
    assert result.exit_code != 0 and "required" in result.output
