"""Typed widget-payload model (VERDICT r3 missing #3): repair/reject agent
widget JSON, state round-trips, card lifecycle.

The contract under test: ANY payload fed through normalize_widget_call +
render_widget either renders (possibly repaired, with the repairs recorded)
or renders an explicit error panel — never a crash, never a silent
misrender. Reference role: prime_lab_app/agent_widget_model.py:1-1168,
agent_cards.py:1-536.
"""

import math

import pytest

from prime_tpu.lab.widget_model import (
    MAX_OPTIONS,
    MAX_PATCH_LINES,
    MAX_POINTS,
    MAX_ROWS,
    NormalizedWidget,
    WidgetValidationError,
    launch_card_payload,
    normalize_widget_call,
)
from prime_tpu.lab.widgets import render_widget

from _markers import get_tomllib


def _render_text(renderable) -> str:
    from rich.console import Console

    console = Console(width=100, record=True, file=None, force_terminal=False)
    console.print(renderable)
    return console.export_text()


# -- repair --------------------------------------------------------------------


def test_choose_repairs_scalars_nulls_dupes():
    widget = normalize_widget_call(
        "choose", {"options": ["a", None, 3, "a", "", "  b  "], "title": 7}
    )
    assert widget.args["options"] == ["a", "3", "b"]
    assert widget.args["title"] == "7"
    assert any("null" in r for r in widget.repairs)
    assert any("duplicate" in r for r in widget.repairs)
    text = _render_text(render_widget("choose", {"options": ["a", None, 3]}))
    assert "repaired" in text


def test_chart_coerces_numeric_strings_drops_junk():
    widget = normalize_widget_call(
        "show_chart", {"values": [1, "2.5", "x", None, float("nan"), float("inf"), True]}
    )
    assert widget.args["values"] == [1, 2.5]
    assert len(widget.repairs) == 6  # 5 drops + the '2.5' coercion note
    text = _render_text(render_widget("show_chart", {"values": ["1", "2", "3"]}))
    assert "repaired" in text


def test_table_drops_non_object_rows():
    widget = normalize_widget_call(
        "show_table", {"rows": [{"a": 1}, "junk", None, {"b": 2}]}
    )
    assert widget.args["rows"] == [{"a": 1}, {"b": 2}]
    assert len(widget.repairs) == 2


def test_launch_coerces_typed_config_fields():
    widget = normalize_widget_call(
        "launch_run",
        {
            "kind": "training",
            "config": {
                "model": "llama3-8b",
                "limit": "64",
                "temperature": "0.7",
                "batch_size": 8,
                "junk": {"nested": True},
                "hole": None,
            },
        },
    )
    config = widget.args["config"]
    assert config["limit"] == 64 and isinstance(config["limit"], int)
    assert config["temperature"] == 0.7 and isinstance(config["temperature"], float)
    assert config["batch_size"] == 8
    assert "junk" not in config and "hole" not in config


def test_patch_truncates_and_coerces():
    long_patch = "\n".join(f"+line {i}" for i in range(MAX_PATCH_LINES + 50))
    widget = normalize_widget_call("show_patch", {"patch": long_patch})
    assert len(widget.args["patch"].splitlines()) == MAX_PATCH_LINES
    assert any("truncated" in r for r in widget.repairs)


def test_size_caps():
    options = normalize_widget_call(
        "choose", {"options": [f"o{i}" for i in range(MAX_OPTIONS + 10)]}
    )
    assert len(options.args["options"]) == MAX_OPTIONS
    rows = normalize_widget_call(
        "show_table", {"rows": [{"i": i} for i in range(MAX_ROWS + 10)]}
    )
    assert len(rows.args["rows"]) == MAX_ROWS
    points = normalize_widget_call(
        "show_chart", {"values": list(range(MAX_POINTS * 3))}
    )
    assert len(points.args["values"]) == MAX_POINTS
    # downsampling keeps the series shape (monotone stays monotone)
    values = points.args["values"]
    assert values == sorted(values) and values[0] == 0


# -- reject --------------------------------------------------------------------


@pytest.mark.parametrize(
    "name,args,reason",
    [
        ("nope", {}, "unknown widget tool"),
        ("choose", "not-a-dict", "must be an object"),
        ("choose", {"options": 5}, "array"),
        ("choose", {"options": [None, "", "  "]}, "no usable options"),
        ("show_table", {"rows": ["a", 1]}, "no object rows"),
        ("show_chart", {"values": ["x", None]}, "no numeric values"),
        ("launch_run", {"kind": "deploy", "config": {"a": 1}}, "kind"),
        ("launch_run", {"kind": "eval", "config": {"a": None}}, "no usable config"),
        ("launch_run", {"kind": "eval", "config": "x"}, "must be an object"),
        ("show_patch", {"patch": "   "}, "empty"),
        ("show_patch", {}, "required"),
    ],
)
def test_rejections(name, args, reason):
    with pytest.raises(WidgetValidationError, match=reason):
        normalize_widget_call(name, args)
    # and the renderer turns the same payload into an error panel, not a crash
    text = _render_text(render_widget(name, args))
    assert "widget error" in text


def test_malformed_battery_never_crashes_render():
    """Adversarial battery: every payload must produce SOME panel."""
    battery = [
        ("choose", {"options": [{"nested": "obj"}] * 3}),
        ("choose", {"options": ["ok"], "title": ["list", "title"]}),
        ("show_table", {"rows": [{"k" * 500: "v" * 500}]}),
        ("show_table", {"rows": [{1: 2, None: 3}]}),
        ("show_chart", {"values": [1e308, -1e308, 0]}),
        ("show_chart", {"values": [True, False]}),
        ("launch_run", {"kind": "eval", "config": {"limit": math.inf}}),
        ("show_patch", {"patch": 12345}),
        ("launch_run", {"kind": None, "config": None}),
        ("show_chart", {"values": {}}),
    ]
    for name, args in battery:
        text = _render_text(render_widget(name, args))
        assert text.strip(), (name, args)


# -- state round-trip ----------------------------------------------------------


def test_interaction_stamps_survive_renormalization():
    """The chat screen stamps selected/saved_card into rendered args; a
    re-render of the transcript re-normalizes — stamps must survive."""
    args = {"options": ["a", None, "b"], "selected": "b"}
    first = normalize_widget_call("choose", args)
    assert first.args["selected"] == "b"
    second = normalize_widget_call("choose", first.args)
    assert second.args["selected"] == "b"
    assert second.args["options"] == ["a", "b"]
    text = _render_text(render_widget("choose", second.args))
    assert "✓" in text  # selection marker rendered

    launch = {"kind": "eval", "config": {"limit": "4"}, "saved_card": "card-1.toml"}
    normalized = normalize_widget_call("launch_run", launch)
    assert normalized.args["saved_card"] == "card-1.toml"
    text = _render_text(render_widget("launch_run", normalized.args))
    assert "card written" in text


def test_normalization_is_idempotent():
    """Repair then re-normalize: second pass makes no further repairs."""
    cases = [
        ("choose", {"options": ["a", 3, None]}),
        ("show_chart", {"values": ["1", 2, "junk"]}),
        ("launch_run", {"kind": "training", "config": {"limit": "8", "x": None}}),
        ("show_table", {"rows": [{"a": 1}, "junk"]}),
    ]
    for name, args in cases:
        first = normalize_widget_call(name, args)
        second = normalize_widget_call(name, first.args)
        assert second.repairs == (), (name, second.repairs)
        assert second.args == first.args


# -- card lifecycle ------------------------------------------------------------


def test_launch_card_payload_maps_kind_and_types():
    normalized = normalize_widget_call(
        "launch_run",
        {"kind": "training", "config": {"model": "m", "limit": "16", "learning_rate": "3e-4"}},
    )
    kind, payload = launch_card_payload(normalized)
    assert kind == "train"
    assert payload == {"model": "m", "limit": 16, "learning_rate": 3e-4}
    with pytest.raises(WidgetValidationError, match="not a launch proposal"):
        launch_card_payload(NormalizedWidget(name="choose", args={}))


def test_chat_proposal_writes_typed_card(tmp_path):
    """End-to-end card lifecycle: agent proposal -> typed card on disk ->
    scan_cards sees it -> TOML round-trips with real types."""
    tomllib = get_tomllib()

    from prime_tpu.lab.tui.chat import AgentChatScreen
    from prime_tpu.lab.tui.launch import scan_cards

    screen = AgentChatScreen("tester", lambda: None, workspace=str(tmp_path))
    screen.pending = {
        "name": "launch_run",
        "args": {
            "kind": "training",
            "config": {"model": "llama3-8b", "limit": "32", "temperature": "0.5", "bad": None},
        },
    }
    message = screen._act_on_pending()
    assert "launch card written" in message
    cards = scan_cards(tmp_path)
    assert len(cards) == 1
    card = cards[0]
    assert card.kind == "train"
    parsed = tomllib.loads(card.path.read_text())
    payload = parsed["train"]  # card TOML: [launch] header + [<kind>] payload
    assert payload["limit"] == 32 and isinstance(payload["limit"], int)
    assert payload["temperature"] == 0.5 and isinstance(payload["temperature"], float)


def test_chat_unusable_proposal_writes_nothing(tmp_path):
    from prime_tpu.lab.tui.chat import AgentChatScreen
    from prime_tpu.lab.tui.launch import scan_cards

    screen = AgentChatScreen("tester", lambda: None, workspace=str(tmp_path))
    screen.pending = {
        "name": "launch_run",
        "args": {"kind": "eval", "config": {"everything": None}},
    }
    message = screen._act_on_pending()
    assert "unusable proposal" in message
    assert scan_cards(tmp_path) == []


# -- configure_run form model (VERDICT r4 #3: reference field-spec layer) -----


def _form(args, workspace=None):
    from prime_tpu.lab.widget_model import build_form_model, normalize_widget_call

    return build_form_model(normalize_widget_call("configure_run", args), workspace)


def test_form_normalization_maps_kind_and_coerces_config():
    from prime_tpu.lab.widget_model import normalize_widget_call

    normalized = normalize_widget_call(
        "configure_run",
        {"kind": "training", "env": 7, "config": {"limit": "20", "junk": None}},
    )
    assert normalized.args["kind"] == "rl"
    assert normalized.args["env"] == "7"
    assert normalized.args["config"]["limit"] == 20
    assert "junk" not in normalized.args["config"]
    assert any("mapped" in r for r in normalized.repairs)
    with pytest.raises(WidgetValidationError, match="kind"):
        normalize_widget_call("configure_run", {"kind": "pods"})


def test_form_defaults_and_layering():
    form = _form({"kind": "eval", "env": "gsm8k"})
    by_name = {f.name: f for f in form.fields}
    assert by_name["limit"].value == "50"            # seeded default
    assert by_name["rollouts_per_example"].value == "3"
    assert by_name["max_concurrent"].value == "auto"
    assert by_name["env"].value == "gsm8k"
    assert form.title == "Evaluate gsm8k"
    assert [a.name for a in form.actions] == ["launch", "stop"]

    # agent config beats defaults; user edits beat agent config
    form = _form(
        {"kind": "eval", "config": {"limit": 10}, "form_values": {"limit": "99"}}
    )
    assert {f.name: f for f in form.fields}["limit"].value == "99"


def test_form_rl_schedule_and_disabled_field():
    form = _form({"kind": "rl", "env": "arith-rl"})
    names = [f.name for f in form.fields]
    assert "max_steps" in names and "batch_size" in names
    assert "seq_len" not in names  # disabled + no value -> omitted
    form = _form({"kind": "rl", "config": {"seq_len": 2048}})
    seq = {f.name: f for f in form.fields}["seq_len"]
    assert seq.disabled and seq.value == "2048"
    assert form.title.startswith("Train")


def test_form_model_select_options(tmp_path):
    (tmp_path / "configs").mkdir()
    (tmp_path / "configs" / "endpoints.toml").write_text(
        '[fast]\nmodel = "llama3.2-1b"\nbase_url = "https://x/v1"\n'
    )
    form = _form({"kind": "eval"}, workspace=tmp_path)
    model = {f.name: f for f in form.fields}["model"]
    assert model.widget == "select"
    values = [v for _, v in model.options]
    assert "llama3.2-1b" in values          # preset registry
    assert "fast" in values                 # endpoint alias
    assert model.value == values[0]         # seeded with the first option

    # rl forms restrict to trainable presets (no serving aliases)
    rl_model = {f.name: f for f in _form({"kind": "rl"}, workspace=tmp_path).fields}["model"]
    assert "fast" not in [v for _, v in rl_model.options]

    # an unknown agent-proposed model is kept, prepended to the options
    form = _form({"kind": "eval", "config": {"model": "my-finetune"}}, workspace=tmp_path)
    model = {f.name: f for f in form.fields}["model"]
    assert model.value == "my-finetune" and model.options[0] == ("my-finetune", "my-finetune")


def test_form_environment_select_from_workspace(tmp_path):
    from prime_tpu.envhub.packaging import write_env_template

    write_env_template(tmp_path / "environments" / "wordle", "wordle")
    write_env_template(tmp_path / "environments" / "maze", "maze")
    form = _form({"kind": "eval"}, workspace=tmp_path)
    env = {f.name: f for f in form.fields}["env"]
    assert env.widget == "select"
    assert [v for _, v in env.options] == ["maze", "wordle"]
    assert env.value == "maze"


def test_form_parse_and_launch_payload():
    from prime_tpu.lab.widget_model import form_launch_payload, parse_form_values

    form = _form({"kind": "eval", "env": "gsm8k", "form_values": {"limit": "abc"}})
    _config, errors = parse_form_values(form)
    assert errors and "Examples" in errors[0]
    with pytest.raises(WidgetValidationError, match="Examples"):
        form_launch_payload(form)

    form = _form({"kind": "rl", "env": "arith-rl", "config": {"model": "tiny-test"}})
    kind, payload = form_launch_payload(form)
    assert kind == "train"                       # rl maps onto the card taxonomy
    assert payload["max_steps"] == 100 and isinstance(payload["max_steps"], int)
    assert payload["env"] == "arith-rl"

    with pytest.raises(WidgetValidationError, match="Environment"):
        form_launch_payload(_form({"kind": "eval"}))
    with pytest.raises(WidgetValidationError, match="command line"):
        form_launch_payload(_form({"kind": "gepa", "env": "wordle"}))


def test_form_command_text():
    from prime_tpu.lab.widget_model import form_command_text

    assert (
        form_command_text(_form({"kind": "eval", "env": "gsm8k", "config": {"model": "m1"}}))
        == "prime eval run gsm8k -m m1 -n 50 --max-new-tokens 1024"
    )
    assert form_command_text(
        _form({"kind": "gepa", "env": "wordle", "config": {"model": "m1"}})
    ) == "prime gepa run wordle -m m1"
    assert "train request" in form_command_text(_form({"kind": "rl", "env": "e"}))


def test_form_state_round_trips():
    from prime_tpu.lab.widget_model import normalize_widget_call

    args = {
        "kind": "eval",
        "env": "gsm8k",
        "form_values": {"limit": "7"},
        "form_errors": ["Examples: 'x' is not an integer"],
        "saved_card": "chat-form.toml",
    }
    normalized = normalize_widget_call("configure_run", args)
    assert normalized.args["form_values"] == {"limit": "7"}
    assert normalized.args["form_errors"] == ["Examples: 'x' is not an integer"]
    assert normalized.args["saved_card"] == "chat-form.toml"
    # idempotent: re-normalizing the normalized args changes nothing
    again = normalize_widget_call("configure_run", normalized.args)
    assert again.args == normalized.args


def test_form_renders_headlessly(tmp_path):
    import io

    from rich.console import Console

    from prime_tpu.lab.widgets import render_widget

    console = Console(width=90, file=io.StringIO(), force_terminal=False)
    console.print(
        render_widget(
            "configure_run",
            {"kind": "eval", "env": "gsm8k", "form_errors": ["Examples: bad"]},
            workspace=tmp_path,
        )
    )
    out = console.file.getvalue()
    assert "Evaluate gsm8k" in out
    assert "Examples" in out and "50" in out
    assert "bad" in out
    # error payload renders as an explicit error panel, never a crash
    console = Console(width=90, file=io.StringIO(), force_terminal=False)
    console.print(render_widget("configure_run", {"kind": "nope"}))
    assert "widget error" in console.file.getvalue()


def test_form_extras_visible_and_carried_to_card():
    """Agent config outside the schedule (temperature, seed) must ride onto
    the launched card — a proposal can't behave differently between
    launch_run and configure_run — and render in the form."""
    import io

    from rich.console import Console

    from prime_tpu.lab.widget_model import form_launch_payload
    from prime_tpu.lab.widgets import render_widget

    args = {"kind": "eval", "env": "gsm8k", "config": {"temperature": 0.0, "seed": 42}}
    form = _form(args)
    assert dict(form.extras) == {"temperature": 0.0, "seed": 42}
    _kind, payload = form_launch_payload(form)
    assert payload["temperature"] == 0.0 and payload["seed"] == 42
    console = Console(width=90, file=io.StringIO(), force_terminal=False)
    console.print(render_widget("configure_run", args))
    out = console.file.getvalue()
    assert "temperature" in out and "seed" in out


def test_gepa_form_stamps_command_not_card(tmp_path):
    import io

    from rich.console import Console

    from prime_tpu.lab.tui.chat import AgentChatScreen
    from prime_tpu.lab.tui.launch import scan_cards
    from prime_tpu.lab.widgets import render_widget

    screen = AgentChatScreen("tester", lambda: None, workspace=str(tmp_path))
    entry = {"role": "widget", "name": "configure_run",
             "args": {"kind": "gepa", "env": "wordle", "config": {"model": "m1"}}}
    screen.transcript.append(entry)
    screen.pending = entry
    status = screen.on_key("enter")
    assert status == "prime gepa run wordle -m m1"
    assert "saved_card" not in entry["args"]
    assert entry["args"]["command"] == status
    assert scan_cards(tmp_path) == []  # truly no card on disk
    console = Console(width=100, file=io.StringIO(), force_terminal=False)
    console.print(render_widget("configure_run", entry["args"]))
    out = console.file.getvalue()
    assert "command sent" in out and "card written" not in out
