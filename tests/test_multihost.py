"""Real multi-process jax.distributed coverage (VERDICT r3 weak #3).

Everything else in the suite runs single-process on a virtual 8-device CPU
mesh — which exercises sharding and collectives but not the distributed
runtime itself (coordinator handshake, cross-process Gloo collectives,
process-spanning meshes). These tests spawn ACTUAL separate Python
processes, each with its own 4-device virtual CPU backend, and require
cross-process communication to pass: this is the code path a real v5e-16+
multi-host slice runs over DCN, minus only the transport.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import pytest

from _markers import requires_vma


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_workers(num_processes: int, devices_per_process: int = 4):
    """Launch the multihost smoke on `num_processes` real subprocesses."""
    port = _free_port()
    # children pick their own platform/device-count (main() sets the env
    # vars itself from --devices-per-process); scrub the pytest process's
    # virtual-mesh settings so they don't leak
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS")
    }
    return [
        subprocess.Popen(
            [
                sys.executable, "-m", "prime_tpu.parallel.multihost_smoke",
                "--coordinator", f"127.0.0.1:{port}",
                "--num-processes", str(num_processes),
                "--process-id", str(i),
                "--devices-per-process", str(devices_per_process),
            ],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )
        for i in range(num_processes)
    ]


@pytest.mark.slow
@requires_vma
def test_two_process_distributed_smoke():
    """initialize_multihost + psum + all_gather + sharded matmul across two
    REAL processes: every check requires data to cross the process boundary."""
    import json

    procs = _spawn_workers(2)
    records = []
    failures = []
    try:
        for i, proc in enumerate(procs):
            out, err = proc.communicate(timeout=300)
            if proc.returncode != 0:
                failures.append(f"proc {i} rc={proc.returncode}:\n{err[-1500:]}")
                continue
            ok_lines = [l for l in out.splitlines() if l.startswith("MULTIHOST_SMOKE_OK ")]
            assert ok_lines, f"proc {i} printed no OK line:\n{out[-500:]}"
            records.append(json.loads(ok_lines[-1].split(" ", 1)[1]))
    finally:
        # a failed/timed-out worker must not orphan its sibling (it would
        # spin against the dead coordinator until jax's init timeout)
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
    assert not failures, "\n".join(failures)
    assert [r["process_id"] for r in records] == [0, 1]
    for record in records:
        assert record["process_count"] == 2
        assert record["global_devices"] == 8
        assert record["local_devices"] == 4
        assert record["psum"] == 8.0
        assert record["procs_seen_in_gather"] == [0, 1]
        assert record["sharded_matmul_ok"] is True


@pytest.mark.slow
def test_worker_failure_is_detected_not_hung():
    """If one worker never arrives, the coordinator side must FAIL (timeout
    error), not hang forever — the failure-detection property a real slice
    needs when a VM dies at launch."""
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS")
    }
    # ask for 2 processes but launch only process 1 (non-coordinator, so it
    # waits on a coordinator that never comes up); bound the wait via JAX's
    # own init timeout rather than killing from outside
    env["JAX_COORDINATOR_TIMEOUT"] = "10"  # newer jax: seconds to wait
    proc = subprocess.Popen(
        [
            sys.executable, "-c",
            "import os\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "import jax\n"
            "from prime_tpu.parallel.distributed import initialize_multihost\n"
            f"initialize_multihost('127.0.0.1:{port}', 2, 1,"
            " initialization_timeout=10)\n",
        ],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
    )
    try:
        _, err = proc.communicate(timeout=120)
    except subprocess.TimeoutExpired:
        proc.kill()
        pytest.fail("lone worker hung instead of timing out")
    assert proc.returncode != 0
    assert "deadline" in err.lower() or "timeout" in err.lower() or "unavailable" in err.lower()
