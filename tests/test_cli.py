"""CLI tests: CliRunner driving the real `prime` app against the fake backend.

Mirrors the reference's tier-1 CLI testing approach (tests/test_pods_create.py:
CliRunner + isolated HOME + canned fixtures), with the in-process fake control
plane replacing monkeypatched client methods.
"""

import json

import pytest
from click.testing import CliRunner

import prime_tpu.commands._deps as deps
from prime_tpu.commands.main import cli
from prime_tpu.testing import FakeControlPlane


@pytest.fixture
def fake(monkeypatch, tmp_path):
    fake = FakeControlPlane(pod_ready_after_polls=2)
    monkeypatch.setattr(deps, "transport_override", fake.transport)
    monkeypatch.setenv("PRIME_API_KEY", "test-key")
    monkeypatch.setenv("PRIME_BASE_URL", "https://api.fake")
    return fake


@pytest.fixture
def runner():
    return CliRunner()


def test_help_lists_panels(runner):
    result = runner.invoke(cli, ["--help"])
    assert result.exit_code == 0
    for cmd in ("availability", "pods", "config", "whoami"):
        assert cmd in result.output


def test_availability_tpu_types_plain(runner, fake):
    result = runner.invoke(cli, ["availability", "tpu-types", "--plain"])
    assert result.exit_code == 0, result.output
    lines = result.output.strip().splitlines()
    assert lines[0].startswith("TPU TYPE")
    assert any(line.startswith("v5e") for line in lines)


def test_availability_list_json_filters(runner, fake):
    result = runner.invoke(
        cli, ["availability", "list", "--tpu-type", "v5e", "--min-chips", "8", "--output", "json"]
    )
    assert result.exit_code == 0, result.output
    rows = json.loads(result.output)
    assert rows and all(r["tpuType"] == "v5e" and r["chips"] >= 8 for r in rows)
    assert {"iciTopology", "hosts", "priceHourly"} <= set(rows[0])


def test_availability_disks_plain(runner, fake):
    result = runner.invoke(cli, ["availability", "disks", "--plain"])
    assert result.exit_code == 0
    assert "hyperdisk-balanced" in result.output


def test_pods_create_noninteractive_and_lifecycle(runner, fake):
    result = runner.invoke(
        cli, ["pods", "create", "--slice", "v5e-16", "--name", "trainer", "--yes", "--output", "json"]
    )
    assert result.exit_code == 0, result.output
    pod = json.loads(result.output)
    assert pod["sliceName"] == "v5e-16" and pod["hosts"] == 2 and pod["iciTopology"] == "4x4"
    pod_id = pod["podId"]

    # status polls advance the fake lifecycle
    runner.invoke(cli, ["pods", "status", pod_id, "--plain"])
    result = runner.invoke(cli, ["pods", "status", pod_id, "--output", "json"])
    status = json.loads(result.output)
    assert status["status"] == "ACTIVE"
    assert len(status["sshConnections"]) == 2

    result = runner.invoke(cli, ["pods", "list", "--plain"])
    assert "trainer" in result.output

    result = runner.invoke(cli, ["pods", "terminate", pod_id, "--yes"])
    assert result.exit_code == 0
    result = runner.invoke(cli, ["pods", "history", "--plain"])
    assert "trainer" in result.output


def test_pods_create_wizard_interactive(runner, fake):
    # generation 2 (v5e), slice 4 (v5e-8), offer 1, runtime 1, disk 100, confirm
    result = runner.invoke(
        cli,
        ["pods", "create"],
        input="2\n4\n1\n1\n100\ny\n",
    )
    assert result.exit_code == 0, result.output
    assert "v5e-8" in result.output
    assert len(fake.pods) == 1


def test_pods_create_bad_slice_fails_cleanly(runner, fake):
    result = runner.invoke(cli, ["pods", "create", "--slice", "v9z-8", "--yes"])
    assert result.exit_code != 0
    assert "Unknown TPU generation" in result.output


def test_pods_connect_waits_and_uses_ssh_key(runner, fake, monkeypatch):
    calls = []

    class R:
        returncode = 0

    monkeypatch.setattr("prime_tpu.commands.pods.ssh_runner", lambda args: calls.append(args) or R())
    monkeypatch.setattr("prime_tpu.commands.pods.POLL_INTERVAL_S", 0)
    monkeypatch.setenv("PRIME_SSH_KEY_PATH", "/tmp/key")

    result = runner.invoke(cli, ["pods", "create", "--slice", "v5e-1", "--yes", "--output", "json"])
    pod_id = json.loads(result.output)["podId"]
    result = runner.invoke(cli, ["pods", "connect", pod_id])
    assert result.exit_code == 0, result.output
    assert calls and calls[0][0] == "ssh" and "/tmp/key" in calls[0]


def test_pods_connect_multihost_fanout(runner, fake, monkeypatch):
    calls = []

    class R:
        returncode = 0

    monkeypatch.setattr("prime_tpu.commands.pods.ssh_runner", lambda args: calls.append(args) or R())
    monkeypatch.setattr("prime_tpu.commands.pods.POLL_INTERVAL_S", 0)

    result = runner.invoke(cli, ["pods", "create", "--slice", "v5e-32", "--yes", "--output", "json"])
    pod_id = json.loads(result.output)["podId"]
    fake.make_pod_active(pod_id)
    result = runner.invoke(
        cli, ["pods", "connect", pod_id, "--all-workers", "--command", "hostname"]
    )
    assert result.exit_code == 0, result.output
    assert len(calls) == 4  # v5e-32 = 4 hosts; same command on every worker
    assert all(args[-1] == "hostname" for args in calls)


def test_config_view_and_set(runner, fake, monkeypatch):
    monkeypatch.delenv("PRIME_API_KEY")
    result = runner.invoke(cli, ["config", "set-api-key", "pk-test-1234567890"])
    assert result.exit_code == 0
    result = runner.invoke(cli, ["config", "view", "--output", "json"])
    view = json.loads(result.output)
    assert "1234567890" not in view["api_key"]  # masked


def test_config_contexts_roundtrip(runner, fake):
    assert runner.invoke(cli, ["config", "envs", "save", "prod"]).exit_code == 0
    result = runner.invoke(cli, ["config", "envs", "list", "--output", "json"])
    assert json.loads(result.output) == ["prod"]
    assert runner.invoke(cli, ["config", "envs", "use", "prod"]).exit_code == 0
    assert runner.invoke(cli, ["config", "envs", "delete", "prod"]).exit_code == 0
    result = runner.invoke(cli, ["config", "envs", "use", "missing"])
    assert result.exit_code != 0


def test_whoami_and_teams(runner, fake):
    result = runner.invoke(cli, ["whoami", "--output", "json"])
    assert json.loads(result.output)["email"] == "dev@example.com"
    result = runner.invoke(cli, ["teams", "list", "--plain"])
    assert "research" in result.output
    assert runner.invoke(cli, ["teams", "switch", "team_1"]).exit_code == 0


def test_wallet(runner, fake):
    result = runner.invoke(cli, ["wallet", "--output", "json"])
    assert json.loads(result.output)["balanceUsd"] == 100.0


def test_disks_crud(runner, fake):
    result = runner.invoke(
        cli, ["disks", "create", "--name", "data", "--size-gib", "200", "--output", "json"]
    )
    assert result.exit_code == 0, result.output
    disk = json.loads(result.output)
    assert disk["sizeGib"] == 200
    result = runner.invoke(cli, ["disks", "list", "--plain"])
    assert "data" in result.output
    assert runner.invoke(cli, ["disks", "delete", disk["diskId"], "--yes"]).exit_code == 0


def test_unauthorized_is_actionable(runner, fake, monkeypatch):
    monkeypatch.setenv("PRIME_API_KEY", "wrong")
    result = runner.invoke(cli, ["pods", "list"])
    assert result.exit_code != 0


def test_cli_startup_does_not_import_heavyweights():
    """`prime --help` must not drag in jax/flax or the SDK stacks."""
    import subprocess
    import sys

    code = (
        # the environment may preload jax itself (TPU tunnel sitecustomize);
        # assert the CLI doesn't ADD heavyweights beyond that baseline
        "import sys\n"
        "preloaded = set(sys.modules)\n"
        "import prime_tpu.commands.main as m\n"
        "from click.testing import CliRunner\n"
        "r = CliRunner().invoke(m.cli, ['--help'])\n"
        "assert r.exit_code == 0\n"
        "heavy = ('jax', 'flax', 'optax', 'torch', 'transformers')\n"
        "bad = [mod for mod in heavy if mod in sys.modules and mod not in preloaded]\n"
        "assert not bad, f'heavyweights imported at startup: {bad}'\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True, text=True, cwd="/root/repo")
    assert proc.returncode == 0, proc.stderr


def test_api_errors_render_clean_not_traceback(runner, fake, monkeypatch):
    monkeypatch.setenv("PRIME_API_KEY", "wrong")
    result = runner.invoke(cli, ["pods", "list"])
    assert result.exit_code == 1
    assert result.exception is None or isinstance(result.exception, SystemExit)
    assert "Error:" in result.output and "Traceback" not in result.output


def test_pods_create_on_demand_never_picks_spot_offer(runner, fake):
    result = runner.invoke(
        cli, ["pods", "create", "--slice", "v5e-8", "--yes", "--output", "json"]
    )
    assert result.exit_code == 0, result.output
    pod = json.loads(result.output)
    # fake prices spot at 0.4x; on-demand create must not have matched it
    offer_ids = {o["offerId"]: o for o in fake.offers}
    assert pod["spot"] is False


def test_connect_all_workers_propagates_failures(runner, fake, monkeypatch):
    class R:
        def __init__(self, rc):
            self.returncode = rc

    rcs = iter([0, 1, 0, 0])
    monkeypatch.setattr("prime_tpu.commands.pods.ssh_runner", lambda args: R(next(rcs)))
    monkeypatch.setattr("prime_tpu.commands.pods.POLL_INTERVAL_S", 0)
    result = runner.invoke(cli, ["pods", "create", "--slice", "v5e-32", "--yes", "--output", "json"])
    pod_id = json.loads(result.output)["podId"]
    fake.make_pod_active(pod_id)
    result = runner.invoke(cli, ["pods", "connect", pod_id, "--all-workers", "--command", "x"])
    assert result.exit_code == 1


def test_eval_run_and_push_cli(runner, fake, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    result = runner.invoke(
        cli,
        [
            "eval", "run", "arith", "-m", "tiny-test",
            "-n", "4", "-b", "2", "--max-new-tokens", "8",
            "--output", "json",
        ],
    )
    assert result.exit_code == 0, result.output
    payload = json.loads(result.output)
    assert payload["metrics"]["num_samples"] == 4.0
    assert payload["evalId"].startswith("eval_")
    assert fake.evals_plane.evaluations[payload["evalId"]]["status"] == "FINALIZED"

    result = runner.invoke(cli, ["eval", "list", "--plain"])
    assert result.exit_code == 0 and "arith" not in result.output  # env shown as id
    result = runner.invoke(cli, ["eval", "samples", payload["evalId"], "--plain"])
    assert result.exit_code == 0

    # push again from the run dir on disk
    result = runner.invoke(cli, ["eval", "push", "--output", "json"])
    assert result.exit_code == 0, result.output


def test_prompt_pickers():
    """utils.prompt: single row short-circuits, assume_default skips I/O."""
    import pytest as _pytest

    from prime_tpu.utils.prompt import confirm, pick, pick_value, prompt_int

    assert pick("t", ["only"]) == "only"
    assert pick("t", ["a", "b", "c"], assume_default=True) == "a"
    assert pick("t", ["a", "b"], default=2, assume_default=True) == "b"
    assert pick_value("t", "given", ["a", "b"]) == "given"
    assert pick_value("t", None, ["a", "b"], assume_default=True) == "a"
    assert prompt_int("n", 7, assume_default=True) == 7
    assert confirm("ok?", assume_yes=True) is True
    with _pytest.raises(Exception, match="nothing to select"):
        pick("t", [])


def test_pods_create_wizard_runtime_and_disk_in_payload(runner, fake):
    result = runner.invoke(
        cli,
        ["pods", "create"],
        input="2\n4\n1\n2\n250\ny\n",  # runtime option 2, disk 250
    )
    assert result.exit_code == 0, result.output
    pod = next(iter(fake.pods.values()))
    assert pod["runtimeVersion"] == "v2-alpha-tpuv5-lite"
    assert pod["diskSizeGib"] == 250


def test_switch_by_slug_id_personal_and_miss(runner, fake):
    """Top-level `prime switch` (reference commands/switch.py): resolves a
    team by slug or id, 'personal' clears the team, unknown targets list
    what's available, and no argument prompts interactively."""
    assert runner.invoke(cli, ["switch", "research"]).exit_code == 0
    # the switch must actually persist: teams list marks team_1 active
    listed = runner.invoke(cli, ["teams", "list", "--plain"]).output
    assert "*" in listed
    assert "Switched to team 'research'" in runner.invoke(cli, ["switch", "team_1"]).output
    assert "personal" in runner.invoke(cli, ["switch", "personal"]).output
    missed = runner.invoke(cli, ["switch", "nope"])
    assert missed.exit_code != 0 and "research" in missed.output
    picked = runner.invoke(cli, ["switch"], input="1\n")
    assert picked.exit_code == 0 and "Switched to team 'research'" in picked.output
    picked = runner.invoke(cli, ["switch"], input="0\n")
    assert picked.exit_code == 0 and "personal" in picked.output
