"""Login, inference, secrets, deployments, usage, images/registry, tunnel,
feedback, upgrade, lab — against the fake planes."""

import json
import os
import stat

import pytest
from click.testing import CliRunner

import prime_tpu.commands._deps as deps
from prime_tpu.commands.main import cli
from prime_tpu.testing import FakeControlPlane

from _markers import requires_cryptography


@pytest.fixture
def fake(monkeypatch):
    fake = FakeControlPlane()
    monkeypatch.setattr(deps, "transport_override", fake.transport)
    monkeypatch.setenv("PRIME_API_KEY", "test-key")
    monkeypatch.setenv("PRIME_BASE_URL", "https://api.fake")
    monkeypatch.setenv("PRIME_INFERENCE_URL", "https://inference.fake/v1")
    return fake


@pytest.fixture
def runner():
    return CliRunner()


# -- login -------------------------------------------------------------------


@requires_cryptography
def test_login_challenge_flow_decrypts_key(runner, fake, monkeypatch):
    monkeypatch.delenv("PRIME_API_KEY")  # login must work without a key
    monkeypatch.setattr("prime_tpu.commands.login.browser_open", lambda url: True)
    monkeypatch.setattr("prime_tpu.commands.login.POLL_INTERVAL_S", 0)
    result = runner.invoke(cli, ["login"])
    assert result.exit_code == 0, result.output
    assert "Logged in as dev@example.com" in result.output
    # the OAEP-decrypted key now authenticates real calls
    assert deps.build_config().api_key == "test-key"
    result = runner.invoke(cli, ["whoami", "--output", "json"])
    assert json.loads(result.output)["email"] == "dev@example.com"


@requires_cryptography
def test_login_no_browser_prints_url(runner, fake, monkeypatch):
    monkeypatch.delenv("PRIME_API_KEY")
    monkeypatch.setattr("prime_tpu.commands.login.POLL_INTERVAL_S", 0)
    result = runner.invoke(cli, ["login", "--no-browser"])
    assert "https://app.fake/auth/" in result.output


def test_logout_clears_key(runner, fake, monkeypatch):
    monkeypatch.delenv("PRIME_API_KEY")
    cfg = deps.build_config()
    cfg.api_key = "something"
    cfg.save()
    assert runner.invoke(cli, ["logout"]).exit_code == 0
    assert deps.build_config().api_key == ""


# -- inference ---------------------------------------------------------------


def test_inference_models_and_chat(runner, fake):
    result = runner.invoke(cli, ["inference", "models", "--plain"])
    assert "llama3-8b" in result.output
    result = runner.invoke(
        cli, ["inference", "chat", "llama3-8b", "-m", "hello tpu", "--no-stream", "--output", "json"]
    )
    data = json.loads(result.output)
    assert data["choices"][0]["message"]["content"] == "echo: hello tpu"


def test_inference_chat_streaming(runner, fake):
    result = runner.invoke(cli, ["inference", "chat", "llama3-8b", "-m", "stream me please"])
    assert result.exit_code == 0, result.output
    assert "echo: stream me please" in result.output


# -- secrets / deployments / usage / feedback --------------------------------


def test_secrets_crud(runner, fake):
    assert runner.invoke(cli, ["secrets", "set", "WANDB_API_KEY", "w"]).exit_code == 0
    result = runner.invoke(cli, ["secrets", "list", "--plain"])
    assert "WANDB_API_KEY" in result.output
    assert runner.invoke(cli, ["secrets", "delete", "WANDB_API_KEY", "--yes"]).exit_code == 0
    result = runner.invoke(cli, ["secrets", "list", "--plain"])
    assert "WANDB_API_KEY" not in result.output


def test_deployments_flow(runner, fake):
    result = runner.invoke(cli, ["deployments", "deploy", "--checkpoint", "ckpt_123", "--output", "json"])
    adapter_id = json.loads(result.output)["adapterId"]
    result = runner.invoke(cli, ["deployments", "list", "--plain"])
    assert adapter_id in result.output
    result = runner.invoke(cli, ["deployments", "base-models", "--plain"])
    assert "llama3-8b" in result.output
    assert runner.invoke(cli, ["deployments", "unload", adapter_id]).exit_code == 0


def test_usage_and_watch(runner, fake):
    result = runner.invoke(cli, ["usage", "--output", "json"])
    rows = json.loads(result.output)
    assert rows[0]["runId"] == "run_demo1"
    result = runner.invoke(cli, ["usage", "--watch", "--interval", "0", "--iterations", "2", "--plain"])
    assert result.output.count("run_demo1") == 2


def test_feedback(runner, fake):
    assert runner.invoke(cli, ["feedback", "love the TPUs"]).exit_code == 0
    assert fake.misc_plane.feedback == [{"message": "love the TPUs"}]


# -- images / registry -------------------------------------------------------


def test_images_build_flow(runner, fake, tmp_path):
    dockerfile = tmp_path / "Dockerfile"
    dockerfile.write_text("FROM primetpu/jax-tpu:latest\n")
    result = runner.invoke(
        cli, ["images", "push", "--name", "my-image", "--dockerfile", str(dockerfile), "--output", "json"]
    )
    image_id = json.loads(result.output)["imageId"]
    result = runner.invoke(cli, ["images", "build-status", image_id, "--output", "json"])
    assert json.loads(result.output)["status"] == "READY"
    assert runner.invoke(cli, ["images", "publish", image_id]).exit_code == 0
    result = runner.invoke(cli, ["images", "list", "--plain"])
    assert "my-image" in result.output and "public" in result.output


def test_registry_commands(runner, fake):
    result = runner.invoke(cli, ["registry", "credentials", "--plain"])
    assert "docker.io" in result.output
    result = runner.invoke(cli, ["registry", "check-access", "python:3.12", "--plain"])
    assert "accessible" in result.output
    result = runner.invoke(cli, ["registry", "check-access", "private/img", "--plain"])
    assert "NOT accessible" in result.output


# -- tunnel ------------------------------------------------------------------


FAKE_FRPC = """\
#!/usr/bin/env python3
import sys, time
print("frpc starting with config", sys.argv[-1], flush=True)
print("[proxy] start proxy success", flush=True)
time.sleep(60)
"""


@pytest.fixture
def fake_frpc(tmp_path):
    script = tmp_path / "frpc"
    script.write_text(FAKE_FRPC)
    script.chmod(script.stat().st_mode | stat.S_IEXEC)
    return script


def test_tunnel_sdk_lifecycle(fake, fake_frpc, monkeypatch):
    from prime_tpu.core.client import APIClient
    from prime_tpu.core.config import Config
    from prime_tpu.tunnel import Tunnel

    cfg = Config()
    cfg.api_key = "test-key"
    api = APIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)
    tunnel = Tunnel(8080, client=api, frpc_path=fake_frpc)
    url = tunnel.start(timeout_s=15)
    assert url.startswith("https://") and "tunnels.fake" in url
    assert len(fake.misc_plane.tunnels) == 1
    status = tunnel.status()
    assert status["processAlive"] is True
    tunnel.stop()
    assert fake.misc_plane.tunnels == {}
    assert tunnel.process.poll() is not None


def test_tunnel_sdk_failure_log(fake, tmp_path):
    from prime_tpu.core.client import APIClient
    from prime_tpu.core.config import Config
    from prime_tpu.tunnel import Tunnel, TunnelError

    bad = tmp_path / "frpc"
    bad.write_text("#!/usr/bin/env python3\nprint('login to server failed: auth', flush=True)\n")
    bad.chmod(0o755)
    cfg = Config()
    cfg.api_key = "test-key"
    api = APIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)
    tunnel = Tunnel(8080, client=api, frpc_path=bad)
    with pytest.raises(TunnelError, match="login to server failed"):
        tunnel.start(timeout_s=10)


def test_tunnel_cli_list_stop(runner, fake, fake_frpc):
    # create a registration directly via the API (start would block on frpc)
    client_result = runner.invoke(cli, ["tunnel", "list", "--output", "json"])
    assert json.loads(client_result.output) == []
    from prime_tpu.core.client import APIClient
    from prime_tpu.core.config import Config

    cfg = Config()
    cfg.api_key = "test-key"
    api = APIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)
    created = api.post("/tunnels", json={"localPort": 9999})
    result = runner.invoke(cli, ["tunnel", "list", "--plain"])
    assert created["tunnelId"] in result.output
    assert runner.invoke(cli, ["tunnel", "stop", created["tunnelId"]]).exit_code == 0


# -- upgrade / lab -----------------------------------------------------------


def test_upgrade_reports_method(runner, fake):
    result = runner.invoke(cli, ["upgrade", "--output", "json"])
    data = json.loads(result.output)
    assert data["installMethod"] in ("pip", "pipx", "uv-tool", "source")
    assert data["command"]


def test_lab_setup_and_doctor(runner, fake, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    result = runner.invoke(cli, ["lab", "setup"])
    assert result.exit_code == 0
    assert (tmp_path / ".prime-lab" / "lab.toml").exists()
    assert "outputs/" in (tmp_path / ".gitignore").read_text()
    result = runner.invoke(cli, ["lab", "doctor", "--output", "json"])
    checks = json.loads(result.output)
    assert checks["workspace"] is True and checks["jax"] is True
    # one-shot dashboard renders from cache even without textual
    result = runner.invoke(cli, ["lab", "view", "--cached"])
    assert result.exit_code == 0, result.output


# -- parity gap-fill regressions ---------------------------------------------


def test_sandbox_ssh_session_vm_only(runner, fake, monkeypatch):
    calls = []

    class R:
        returncode = 0

    import prime_tpu.commands.sandbox as sb_cmd

    monkeypatch.setattr(sb_cmd, "ssh_runner", lambda args: calls.append(args) or R())
    result = runner.invoke(cli, ["sandbox", "create", "--vm", "--output", "json"])
    sid = json.loads(result.output)["sandboxId"]
    result = runner.invoke(cli, ["sandbox", "ssh", sid])
    assert result.exit_code == 0, result.output
    assert calls and calls[0][0] == "ssh" and f"root@{sid}.ssh.fake" in calls[0]

    result = runner.invoke(cli, ["sandbox", "create", "--output", "json"])
    container_sid = json.loads(result.output)["sandboxId"]
    result = runner.invoke(cli, ["sandbox", "ssh", container_sid])
    assert result.exit_code != 0
    assert "VM sandbox" in result.output


def test_hosted_eval_flow(runner, fake, monkeypatch):
    import prime_tpu.commands.evals as ev_cmd

    monkeypatch.setattr(ev_cmd, "POLL_INTERVAL_S", 0)
    result = runner.invoke(
        cli, ["eval", "run", "gsm8k", "-m", "llama3-8b", "--hosted", "--tpu", "v5e-16", "--output", "json"]
    )
    assert result.exit_code == 0, result.output
    run = json.loads(result.output)
    assert run["status"] == "COMPLETED" and run["metrics"]["accuracy"] == 0.62
    assert run["tpuType"] == "v5e-16"


def test_hosted_eval_stop(runner, fake, monkeypatch):
    import prime_tpu.commands.evals as ev_cmd

    monkeypatch.setattr(ev_cmd, "POLL_INTERVAL_S", 0)
    # create a hosted run directly and cancel it before polling
    import httpx, json as j

    resp = fake.handle(
        httpx.Request(
            "POST",
            "https://api.fake/api/v1/evals/hosted",
            headers={"Authorization": "Bearer test-key"},
            content=j.dumps({"env": "e", "model": "m"}).encode(),
        )
    )
    hid = resp.json()["hostedId"]
    result = runner.invoke(cli, ["eval", "stop", hid])
    assert "CANCELLED" in result.output
    result = runner.invoke(cli, ["eval", "stop", hid, "--output", "json"])
    assert json.loads(result.output)["status"] == "CANCELLED"


def test_fork_env(runner, fake, tmp_path):
    from prime_tpu.envhub.packaging import write_env_template

    env_dir = tmp_path / "orig"
    write_env_template(env_dir, "orig")
    runner.invoke(cli, ["env", "push", "--dir", str(env_dir)])
    result = runner.invoke(cli, ["fork", "orig", "my-copy"])
    assert result.exit_code == 0, result.output
    result = runner.invoke(cli, ["env", "info", "my-copy", "--output", "json"])
    data = json.loads(result.output)
    assert data["forkedFrom"] == "orig"


@pytest.fixture
def gepa_exec(monkeypatch):
    """Capture the exec step so injection/resolution are provable without
    the optional `gepa` package installed (VERDICT r4 #4)."""
    calls = []

    def fake_exec(run_target, args, env):
        calls.append((run_target, args, env))

    monkeypatch.setattr("prime_tpu.commands.gepa_fork._exec_gepa", fake_exec)
    return calls


def _local_env(tmp_path, name="wordle"):
    from prime_tpu.envhub.packaging import write_env_template

    env_dir = tmp_path / "environments" / name
    write_env_template(env_dir, name)
    return env_dir


def test_gepa_requires_package_at_exec(runner, fake, tmp_path, monkeypatch):
    """The package gate fires at exec time, AFTER injection/resolution."""
    import importlib.util

    monkeypatch.chdir(tmp_path)
    _local_env(tmp_path)
    real_find_spec = importlib.util.find_spec
    monkeypatch.setattr(
        importlib.util,
        "find_spec",
        lambda name, *a: None if name == "gepa" else real_find_spec(name, *a),
    )
    result = runner.invoke(cli, ["gepa", "run", "wordle"])
    assert result.exit_code != 0
    assert "not installed" in result.output


def test_gepa_injects_endpoint_and_key(runner, fake, tmp_path, monkeypatch, gepa_exec):
    """Default injection: -b <inference_url>, -k PRIME_API_KEY, key in env
    (reference verifiers_bridge.py:823)."""
    monkeypatch.chdir(tmp_path)
    _local_env(tmp_path)
    result = runner.invoke(cli, ["gepa", "run", "wordle", "--max-calls", "100"])
    assert result.exit_code == 0, result.output
    [(target, args, env)] = gepa_exec
    assert target == "wordle"  # local ./environments checkout resolved
    assert args[:2] == ["--max-calls", "100"]
    b_at = args.index("-b")
    assert args[b_at + 1] == "https://inference.fake/v1"
    k_at = args.index("-k")
    assert args[k_at + 1] == "PRIME_API_KEY"
    assert env["PRIME_API_KEY"] == "test-key"


def test_gepa_default_run_subcommand(runner, fake, tmp_path, monkeypatch, gepa_exec):
    """`prime gepa wordle ...` == `prime gepa run wordle ...`."""
    monkeypatch.chdir(tmp_path)
    _local_env(tmp_path)
    result = runner.invoke(cli, ["gepa", "wordle"])
    assert result.exit_code == 0, result.output
    assert gepa_exec[0][0] == "wordle"


def test_gepa_respects_explicit_base_and_keyvar(
    runner, fake, tmp_path, monkeypatch, gepa_exec
):
    """Caller's -b/-k win: nothing is injected, no PRIME_API_KEY override."""
    monkeypatch.chdir(tmp_path)
    _local_env(tmp_path)
    result = runner.invoke(
        cli,
        ["gepa", "run", "wordle", "-b", "https://my.llm/v1/", "-k", "MY_KEY"],
    )
    assert result.exit_code == 0, result.output
    [(_, args, env)] = gepa_exec
    assert args.count("-b") == 1 and args.count("-k") == 1
    assert "PRIME_API_KEY" not in args
    # caller named their own key var: the bridge must not export the prime key
    assert env.get("PRIME_API_KEY") == os.environ.get("PRIME_API_KEY")


def test_gepa_endpoint_alias_rides_through(
    runner, fake, tmp_path, monkeypatch, gepa_exec
):
    """A configs/endpoints.toml alias for the model suppresses -b/-k
    injection (the downstream CLI re-resolves the alias itself)."""
    monkeypatch.chdir(tmp_path)
    _local_env(tmp_path)
    (tmp_path / "configs").mkdir()
    (tmp_path / "configs" / "endpoints.toml").write_text(
        '[fast]\nmodel = "llama3.2-1b"\nbase_url = "https://alias.fake/v1"\n'
    )
    result = runner.invoke(cli, ["gepa", "run", "wordle", "-m", "fast"])
    assert result.exit_code == 0, result.output
    [(_, args, env)] = gepa_exec
    assert "-b" not in args and "-k" not in args
    assert env["PRIME_API_KEY"] == "test-key"  # key still exported


def test_gepa_config_target_preinstalls_env(
    runner, fake, tmp_path, monkeypatch, gepa_exec
):
    """A *.toml target passes through as-is; its [env] env_id is resolved
    (reference _collect_gepa_config_env)."""
    monkeypatch.chdir(tmp_path)
    _local_env(tmp_path, "maze")
    config = tmp_path / "gepa.toml"
    config.write_text('[env]\nenv_id = "maze"\n')
    result = runner.invoke(cli, ["gepa", "run", str(config)])
    assert result.exit_code == 0, result.output
    [(target, _, _)] = gepa_exec
    assert target == str(config)
    assert "maze" in result.output  # resolution announced


def test_gepa_run_help_without_package(runner, fake, monkeypatch):
    """--help renders the injected-defaults help with no optional package
    and no environment argument."""
    import importlib.util

    real_find_spec = importlib.util.find_spec
    monkeypatch.setattr(
        importlib.util,
        "find_spec",
        lambda name, *a: None if name == "gepa" else real_find_spec(name, *a),
    )
    result = runner.invoke(cli, ["gepa", "run", "--help"])
    assert result.exit_code == 0, result.output
    assert "prime gepa run" in result.output
    assert "PRIME_API_KEY" in result.output


def test_gepa_errors(runner, fake, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    # flag before environment
    result = runner.invoke(cli, ["gepa", "run", "--max-calls", "3"])
    assert result.exit_code == 2
    assert "first argument" in result.output
    # unresolvable environment
    result = runner.invoke(cli, ["gepa", "run", "no-such-env-anywhere"])
    assert result.exit_code != 0
    # no API key at all
    monkeypatch.delenv("PRIME_API_KEY")
    result = runner.invoke(cli, ["gepa", "run", "whatever"])
    assert result.exit_code != 0
    assert "No API key" in result.output


def test_env_vars_util(tmp_path, monkeypatch):
    from prime_tpu.utils.env_vars import FULL_FT_ALLOWED_KEYS, collect_env_vars, parse_dotenv

    dotenv = tmp_path / ".env"
    monkeypatch.setenv("BASE_URL", "https://x")
    dotenv.write_text('WANDB_API_KEY="wb-123"\nDERIVED=${BASE_URL}/path\n# comment\nHF_TOKEN=hf-1\nOTHER=x\n')
    parsed = parse_dotenv(dotenv)
    assert parsed["WANDB_API_KEY"] == "wb-123"
    assert parsed["DERIVED"] == "https://x/path"

    merged = collect_env_vars(dotenv_path=dotenv, allowed=FULL_FT_ALLOWED_KEYS)
    assert set(merged) == {"WANDB_API_KEY", "HF_TOKEN"}  # OTHER filtered out


def test_version_check_cache_and_offline(tmp_path, monkeypatch):
    from prime_tpu.utils import version_check

    monkeypatch.setenv("PRIME_CONFIG_DIR", str(tmp_path))
    # offline: returns None, never raises
    assert version_check.check_for_update("0.1.0", timeout_s=0.01) is None
    # warm cache: newer version reported without network
    import json as j, time

    (tmp_path / "version_check.json").write_text(
        j.dumps({"latest": "9.9.9", "checkedAt": time.time()})
    )
    assert version_check.check_for_update("0.1.0") == "9.9.9"
    assert version_check.check_for_update("9.9.9") is None


def test_multislice_mesh_axes():
    from prime_tpu.parallel.distributed import multislice_mesh_axes

    axes = multislice_mesh_axes("v5e-16", num_slices=4)
    assert axes == {"dp": 4, "fsdp": 2, "tp": 8}
    assert axes["fsdp"] * axes["tp"] == 16


def test_version_check_comparison_and_failure_cache(tmp_path, monkeypatch):
    from prime_tpu.utils import version_check
    import json as j, time

    monkeypatch.setenv("PRIME_CONFIG_DIR", str(tmp_path))
    # dev version newer than PyPI: no nag
    (tmp_path / "version_check.json").write_text(
        j.dumps({"latest": "0.1.0", "checkedAt": time.time()})
    )
    assert version_check.check_for_update("0.2.0.dev0") is None
    # failed lookups are cached so offline machines pay the timeout once
    (tmp_path / "version_check.json").unlink()
    assert version_check.check_for_update("0.1.0", timeout_s=0.01) is None
    cached = j.loads((tmp_path / "version_check.json").read_text())
    assert cached["latest"] is None and cached["checkedAt"] > 0


def test_hosted_eval_failure_exits_nonzero(runner, fake, monkeypatch):
    import prime_tpu.commands.evals as ev_cmd

    monkeypatch.setattr(ev_cmd, "POLL_INTERVAL_S", 0)
    fake.evals_plane.hosted_complete_after = 10**9  # never completes on its own

    orig_get = fake.evals_plane.hosted

    def fail_soon():
        for run in fake.evals_plane.hosted.values():
            run["status"] = "FAILED"

    import threading

    timer = threading.Timer(0.2, fail_soon)
    timer.start()
    # llama3-8b: a model the preflight validates (an unknown id now fails
    # fast BEFORE submission — tests/test_eval_endpoints.py covers that)
    result = runner.invoke(cli, ["eval", "run", "e", "-m", "llama3-8b", "--hosted"])
    timer.cancel()
    assert result.exit_code == 1
    assert "FAILED" in result.output


@pytest.mark.anyio
async def test_async_tunnel_lifecycle(fake, fake_frpc):
    from prime_tpu.core.client import AsyncAPIClient
    from prime_tpu.core.config import Config
    from prime_tpu.tunnel import AsyncTunnel

    cfg = Config()
    cfg.api_key = "test-key"
    api = AsyncAPIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)
    tunnel = AsyncTunnel(8080, client=api, frpc_path=fake_frpc)
    url = await tunnel.start(timeout_s=15)
    assert "tunnels.fake" in url
    status = await tunnel.status()
    assert status["processAlive"] is True
    await tunnel.stop()
    assert fake.misc_plane.tunnels == {}
    await api.close()


@pytest.mark.anyio
async def test_async_tunnel_failure(fake, tmp_path):
    from prime_tpu.core.client import AsyncAPIClient
    from prime_tpu.core.config import Config
    from prime_tpu.tunnel import AsyncTunnel, TunnelError

    bad = tmp_path / "frpc-bad"
    bad.write_text("#!/usr/bin/env python3\nprint('connect to server error: refused', flush=True)\nimport time; time.sleep(5)\n")
    bad.chmod(0o755)
    cfg = Config()
    cfg.api_key = "test-key"
    api = AsyncAPIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)
    tunnel = AsyncTunnel(8080, client=api, frpc_path=bad)
    with pytest.raises(TunnelError, match="connect to server error"):
        await tunnel.start(timeout_s=10)
    await api.close()


def test_tunnel_spawn_failure_cleans_registration(fake, tmp_path):
    from prime_tpu.core.client import APIClient
    from prime_tpu.core.config import Config
    from prime_tpu.tunnel import Tunnel

    cfg = Config()
    cfg.api_key = "test-key"
    api = APIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)
    tunnel = Tunnel(8080, client=api, frpc_path=tmp_path / "missing-frpc")
    with pytest.raises(OSError):
        tunnel.start(timeout_s=5)
    assert fake.misc_plane.tunnels == {}  # registration rolled back


def test_tunnel_config_failure_cleans_registration(fake, fake_frpc, monkeypatch):
    """Any failure after POST /tunnels — not just spawn — rolls back (ADVICE r1)."""
    from prime_tpu.core.client import APIClient
    from prime_tpu.core.config import Config
    from prime_tpu.tunnel import Tunnel
    from prime_tpu.tunnel.tunnel import _TunnelOps

    def boom(self, registration):
        self.registration = registration
        raise KeyError("hostname")

    monkeypatch.setattr(_TunnelOps, "write_config", boom)
    cfg = Config()
    cfg.api_key = "test-key"
    api = APIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)
    tunnel = Tunnel(8080, client=api, frpc_path=fake_frpc)
    with pytest.raises(KeyError):
        tunnel.start(timeout_s=5)
    assert fake.misc_plane.tunnels == {}


def test_tunnel_timeout_cleans_registration(fake, tmp_path):
    from prime_tpu.core.client import APIClient
    from prime_tpu.core.config import Config
    from prime_tpu.tunnel import Tunnel, TunnelError

    silent = tmp_path / "frpc-silent"
    silent.write_text("#!/usr/bin/env python3\nimport time; time.sleep(30)\n")
    silent.chmod(0o755)
    cfg = Config()
    cfg.api_key = "test-key"
    api = APIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)
    tunnel = Tunnel(8080, client=api, frpc_path=silent)
    with pytest.raises(TunnelError, match="did not connect"):
        tunnel.start(timeout_s=0.5)
    assert fake.misc_plane.tunnels == {}
    assert tunnel.process.poll() is not None  # frpc reaped


# -- eval view / logs (reference evals.py:1149,1357) --------------------------


def _make_local_run(tmp_path):
    run_dir = tmp_path / "outs" / "arith--tiny-test" / "20260101-000000-abcd1234"
    run_dir.mkdir(parents=True)
    (run_dir / "metadata.json").write_text(json.dumps({
        "env": "arith", "model": "tiny-test",
        "metrics": {"accuracy": 0.5, "samples_per_sec": 2.0},
    }))
    (run_dir / "results.jsonl").write_text(
        json.dumps({"sample_id": "s_0", "correct": True, "answer": "4", "completion": "4"}) + "\n"
        + json.dumps({"sample_id": "s_1", "correct": False, "answer": "9", "completion": "7"}) + "\n"
    )
    return run_dir


def test_eval_view_local_run(runner, fake, tmp_path):
    run_dir = _make_local_run(tmp_path)
    result = runner.invoke(
        cli, ["eval", "view", "--output-dir", str(tmp_path / "outs"), "--plain"]
    )
    assert result.exit_code == 0, result.output
    assert "1/2 correct" in result.output and "s_1" in result.output

    result = runner.invoke(cli, ["eval", "view", str(run_dir), "--output", "json"])
    data = json.loads(result.output)
    assert data["metadata"]["metrics"]["accuracy"] == 0.5
    assert len(data["samples"]) == 2


def test_eval_view_hub_eval(runner, fake, tmp_path):
    run_dir = _make_local_run(tmp_path)
    pushed = runner.invoke(
        cli, ["eval", "push", "--run-dir", str(run_dir), "--output", "json"]
    )
    eval_id = json.loads(pushed.output)["evalId"]
    result = runner.invoke(cli, ["eval", "view", eval_id, "--plain"])
    assert result.exit_code == 0, result.output
    assert "s_0" in result.output

    as_json = json.loads(runner.invoke(cli, ["eval", "view", eval_id, "--output", "json"]).output)
    assert as_json["evaluation"]["status"] == "FINALIZED"


def test_eval_view_and_logs_hosted(runner, fake, monkeypatch):
    import prime_tpu.commands.evals as ev_cmd

    monkeypatch.setattr(ev_cmd, "POLL_INTERVAL_S", 0)
    runner.invoke(cli, ["eval", "run", "gsm8k", "-m", "llama3-8b", "--hosted", "--plain"])
    import httpx

    listing = fake.evals_plane.hosted
    hid = next(iter(listing))
    result = runner.invoke(cli, ["eval", "view", hid, "--plain"])
    assert result.exit_code == 0, result.output
    assert "COMPLETED" in result.output

    logs = runner.invoke(cli, ["eval", "logs", hid, "--plain"])
    assert logs.exit_code == 0
    assert logs.output.strip()

    follow = runner.invoke(cli, ["eval", "logs", hid, "-f", "--plain"])
    assert "[COMPLETED]" in follow.output


def test_eval_compare(runner, fake, tmp_path):
    import json as _json

    def make_run(name, rows, accuracy):
        run_dir = tmp_path / name
        run_dir.mkdir()
        (run_dir / "metadata.json").write_text(_json.dumps({
            "env": "arith", "model": "m", "metrics": {"accuracy": accuracy},
        }))
        (run_dir / "results.jsonl").write_text(
            "\n".join(_json.dumps(r) for r in rows)
        )
        return run_dir

    a = make_run("a", [
        {"prompt": "1+1", "correct": True},
        {"prompt": "2+2", "correct": True},
        {"prompt": "3+3", "correct": False},
    ], 0.67)
    b = make_run("b", [
        {"prompt": "1+1", "correct": True},
        {"prompt": "2+2", "correct": False},   # regression
        {"prompt": "3+3", "correct": True},    # improvement
    ], 0.67)

    result = runner.invoke(cli, ["eval", "compare", str(a), str(b), "--output", "json"])
    assert result.exit_code == 0, result.output
    data = json.loads(result.output)
    assert data["regressions"] == 1 and data["improvements"] == 1
    assert data["regressedPrompts"] == ["2+2"]

    plain = runner.invoke(cli, ["eval", "compare", str(a), str(b), "--plain"])
    assert "1 improved, 1 regressed" in plain.output
    assert "regressed: 2+2" in plain.output

    bad = runner.invoke(cli, ["eval", "compare", str(tmp_path / "nope"), str(b)])
    assert bad.exit_code != 0


def test_gepa_config_target_errors_and_warnings(runner, fake, tmp_path, monkeypatch, gepa_exec):
    monkeypatch.chdir(tmp_path)
    # missing config file is a hard CLI error, not a silent passthrough
    result = runner.invoke(cli, ["gepa", "run", "nope.toml"])
    assert result.exit_code != 0
    assert "does not exist" in result.output
    # unparseable [env] warns and skips the pre-install, still execs
    config = tmp_path / "broken.toml"
    config.write_text("not [ valid toml")
    result = runner.invoke(cli, ["gepa", "run", str(config)])
    assert result.exit_code == 0, result.output
    assert "skipping environment pre-install" in result.output
    assert gepa_exec[-1][0] == str(config)
    # malformed workspace endpoints.toml fails as a CLI error, not a traceback
    (tmp_path / "configs").mkdir()
    (tmp_path / "configs" / "endpoints.toml").write_text("also not [ toml")
    _local_env(tmp_path)
    result = runner.invoke(cli, ["gepa", "run", "wordle"])
    assert result.exit_code != 0
    assert "Malformed endpoints file" in result.output
    assert not isinstance(result.exception, Exception) or result.exception.__class__ is SystemExit
