"""Config store: persistence, env precedence, named contexts, name sanitizing.

Mirrors reference behaviors at prime_cli/core/config.py:81-82 (env precedence),
:215-224 (path-traversal-safe context names), :244-389 (context CRUD).
"""

import json

import pytest

from prime_tpu.core.config import Config, InvalidContextName, sanitize_context_name


def test_defaults_and_roundtrip(tmp_path):
    cfg = Config(tmp_path / "prime")
    assert cfg.api_key == ""
    assert cfg.base_url.startswith("https://")
    cfg.api_key = "pk-123"
    cfg.team_id = "team-9"
    cfg.save()

    cfg2 = Config(tmp_path / "prime")
    assert cfg2.api_key == "pk-123"
    assert cfg2.team_id == "team-9"
    data = json.loads((tmp_path / "prime" / "config.json").read_text())
    assert data["api_key"] == "pk-123"


def test_env_var_precedence(tmp_path, monkeypatch):
    cfg = Config(tmp_path / "prime")
    cfg.api_key = "from-file"
    cfg.save()
    monkeypatch.setenv("PRIME_API_KEY", "from-env")
    assert Config(tmp_path / "prime").api_key == "from-env"
    monkeypatch.delenv("PRIME_API_KEY")
    assert Config(tmp_path / "prime").api_key == "from-file"


def test_view_masks_api_key(tmp_path):
    cfg = Config(tmp_path / "prime")
    cfg.api_key = "pk-aaaaaaaaaaaaaaaabbbb"
    view = cfg.view()
    assert "aaaaaaaa" not in view["api_key"]
    assert view["api_key"].startswith("pk-a")


def test_context_save_use_delete_list(tmp_path):
    cfg = Config(tmp_path / "prime")
    cfg.api_key = "key-prod"
    cfg.save()
    cfg.save_context("prod")
    cfg.api_key = "key-dev"
    cfg.save()
    cfg.save_context("dev")
    assert cfg.list_contexts() == ["dev", "prod"]

    cfg.use_context("prod")
    assert cfg.api_key == "key-prod"
    assert Config(tmp_path / "prime").api_key == "key-prod"

    assert cfg.delete_context("dev") is True
    assert cfg.delete_context("dev") is False
    assert cfg.list_contexts() == ["prod"]


def test_prime_context_env_switches_active(tmp_path, monkeypatch):
    cfg = Config(tmp_path / "prime")
    cfg.api_key = "default-key"
    cfg.save()
    cfg.api_key = "ctx-key"
    cfg.save_context("alt")
    cfg.api_key = "default-key"
    cfg.save()

    monkeypatch.setenv("PRIME_CONTEXT", "alt")
    assert Config(tmp_path / "prime").api_key == "ctx-key"
    # config.json untouched
    monkeypatch.delenv("PRIME_CONTEXT")
    assert Config(tmp_path / "prime").api_key == "default-key"


@pytest.mark.parametrize("bad", ["../evil", "a/b", ".hidden", "", "x" * 80, "a\\b"])
def test_context_name_sanitizer_rejects(bad):
    with pytest.raises(InvalidContextName):
        sanitize_context_name(bad)


def test_context_name_sanitizer_accepts():
    assert sanitize_context_name(" prod-2.x ") == "prod-2.x"


def test_corrupt_config_file_falls_back_to_defaults(tmp_path):
    d = tmp_path / "prime"
    d.mkdir()
    (d / "config.json").write_text("{not json")
    assert Config(d).api_key == ""


def test_config_cli_frontend_share_remove_reset(tmp_path, monkeypatch):
    """Round-4 parity: set-frontend-url / remove-team-id /
    set-share-resources-with-team / reset (reference commands/config.py)."""
    import json as _json

    from click.testing import CliRunner

    from prime_tpu.commands.main import cli

    monkeypatch.setenv("PRIME_CONFIG_DIR", str(tmp_path))
    monkeypatch.delenv("PRIME_API_KEY", raising=False)
    monkeypatch.delenv("PRIME_BASE_URL", raising=False)
    runner = CliRunner()
    assert runner.invoke(cli, ["config", "set-frontend-url", "https://f.example"]).exit_code == 0
    assert runner.invoke(cli, ["config", "set-team-id", "team_9"]).exit_code == 0
    assert runner.invoke(
        cli, ["config", "set-share-resources-with-team", "true"]
    ).exit_code == 0
    saved = _json.loads((tmp_path / "config.json").read_text())
    assert saved["frontend_url"] == "https://f.example"
    assert saved["share_resources_with_team"] is True
    assert runner.invoke(cli, ["config", "remove-team-id"]).exit_code == 0
    assert _json.loads((tmp_path / "config.json").read_text())["team_id"] == ""
    # invalid share value is rejected by the choice type
    assert runner.invoke(
        cli, ["config", "set-share-resources-with-team", "maybe"]
    ).exit_code != 0
    # reset restores defaults (confirmation skipped with -y)
    assert runner.invoke(cli, ["config", "reset", "-y"]).exit_code == 0
    saved = _json.loads((tmp_path / "config.json").read_text())
    assert saved["frontend_url"] != "https://f.example"
    assert saved["share_resources_with_team"] is False
