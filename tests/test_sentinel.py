"""Regression sentinel + incident forensics (obs/sentinel.py,
serve/fleet/incidents.py).

The load-bearing properties: (1) detection is a pure function of ring
contents — the same capture replayed twice yields BYTE-IDENTICAL detection
streams with content-hash ids; (2) coverage gates keep short rings and
reset windows quiet (a fresh replica is not a regression); (3) one
sustained breach latches to exactly one detection; (4) incident bundles
persist through a bounded on-disk ring and serve over /admin/incidents
with admin-token parity; (5) the trajectory gate passes the real committed
BENCH trajectory and fails a synthetic regressed round appended to it.
"""

import json

import httpx
import pytest

from prime_tpu.obs.metrics import DEFAULT_LATENCY_BUCKETS
from prime_tpu.obs.sentinel import (
    Detection,
    Sentinel,
    SentinelRule,
    default_rules,
    evaluate_rule,
    replay,
    replay_digest,
    smaller_is_better,
    trajectory_gate,
    trajectory_verdicts,
)
from prime_tpu.obs.timeseries import (
    SnapshotRing,
    fleet_rate,
    fleet_window_span,
    serving_window_view,
)
from prime_tpu.serve.fleet.incidents import (
    IncidentStore,
    build_bundle,
    bundle_summary,
    slowest_flights,
    snapshot_delta,
)

# ---- synthetic snapshot fixtures (pure dicts, hand-stamped clocks) ----------

BUCKETS = list(DEFAULT_LATENCY_BUCKETS)


def _hist(observations: list[float]) -> dict:
    counts = [0] * (len(BUCKETS) + 1)
    for value in observations:
        for i, bound in enumerate(BUCKETS):
            if value <= bound:
                counts[i] += 1
                break
        else:
            counts[-1] += 1
    return {
        "buckets": list(BUCKETS),
        "counts": counts,
        "sum": float(sum(observations)),
        "count": len(observations),
    }


def snap(
    t: float,
    counters: dict | None = None,
    hists: dict | None = None,
    gauges: dict | None = None,
) -> dict:
    """A synthetic Registry.snapshot() with an explicit capture instant —
    the decision core must never consult a wall clock."""
    out: dict = {
        "captured_at": {
            "type": "gauge",
            "help": "t",
            "series": [{"labels": {}, "value": float(t)}],
        }
    }
    for name, value in (counters or {}).items():
        out[name] = {
            "type": "counter",
            "help": name,
            "series": [{"labels": {}, "value": float(value)}],
        }
    for name, observations in (hists or {}).items():
        out[name] = {"type": "histogram", "help": name, "series": [
            {"labels": {}, **_hist(observations)}
        ]}
    for name, value in (gauges or {}).items():
        out[name] = {
            "type": "gauge",
            "help": name,
            "series": [{"labels": {}, "value": float(value)}],
        }
    return out


def _latency_timeline(clean_steps: int = 9, slow_steps: int = 2) -> list[dict]:
    """15 s sampling cadence: `clean_steps` captures of 50 ms TTFTs, then
    `slow_steps` captures where every new observation is 2 s. Histograms are
    cumulative, exactly like Registry.snapshot()."""
    seq = []
    observations: list[float] = []
    for i in range(clean_steps + slow_steps):
        observations = observations + [0.05 if i < clean_steps else 2.0] * 6
        seq.append(snap(i * 15.0, hists={"serve_ttft_seconds": list(observations)}))
    return seq


REPLAY_RULE = SentinelRule(
    name="ttft_regression", kind="quantile_regression",
    metric="serve_ttft_seconds", severity="warn",
    q=0.95, baseline_q=0.5, ratio=3.0,
)


# ---- rule evaluation units --------------------------------------------------


def test_quantile_regression_fires_on_change_point():
    ring = SnapshotRing(depth=32)
    for s in _latency_timeline():
        ring.append(s)
    det = evaluate_rule(
        ring, REPLAY_RULE, scope="r0",
        fast_s=30.0, slow_s=120.0, change_ratio=1.6, min_samples=4,
    )
    assert det is not None
    assert det.metric == "serve_ttft_seconds"
    assert det.value > det.baseline * 3.0
    assert det.windows["end_at"] == pytest.approx(150.0)


def test_quantile_regression_min_value_deadband():
    """An absolute floor on the triggering value: the same relative jump
    below the deadband stays quiet (CPU jitter on near-zero latencies)."""
    ring = SnapshotRing(depth=32)
    for s in _latency_timeline():
        ring.append(s)
    deadbanded = SentinelRule(
        name="ttft_regression", kind="quantile_regression",
        metric="serve_ttft_seconds", q=0.95, baseline_q=0.5, ratio=3.0,
        min_value=10.0,  # above the 2 s regression
    )
    assert evaluate_rule(
        ring, deadbanded, scope="r0",
        fast_s=30.0, slow_s=120.0, change_ratio=1.6, min_samples=4,
    ) is None


def test_rate_collapse_fires_and_idle_floor_does_not():
    ring = SnapshotRing(depth=32)
    # 100 tok/s for 120 s, then the stream stalls
    for i in range(9):
        ring.append(snap(i * 15.0, counters={"serve_tokens_emitted_total": i * 1500}))
    ring.append(snap(150.0, counters={"serve_tokens_emitted_total": 8 * 1500 + 10}))
    rule = next(r for r in default_rules() if r.name == "token_rate_collapse")
    det = evaluate_rule(
        ring, rule, scope="r0",
        fast_s=30.0, slow_s=120.0, change_ratio=1.6, min_samples=4,
    )
    assert det is not None and det.value < det.baseline
    # an idle replica (0 -> 0) must never read as a cliff: baseline floor
    idle = SnapshotRing(depth=32)
    for i in range(11):
        idle.append(snap(i * 15.0, counters={"serve_tokens_emitted_total": 0}))
    assert evaluate_rule(
        idle, rule, scope="r0",
        fast_s=30.0, slow_s=120.0, change_ratio=1.6, min_samples=4,
    ) is None


def test_gauge_shift_on_kernel_config_source():
    """The config-source gauge leaving its autotune-registry era (2 ->
    env-forced 0) is a detection; a steady gauge is not."""
    rule = next(r for r in default_rules() if r.name == "kernel_config_shift")
    ring = SnapshotRing(depth=32)
    for i in range(11):
        ring.append(snap(
            i * 15.0, gauges={"serve_kernel_config_source": 2.0 if i < 9 else 0.0}
        ))
    det = evaluate_rule(
        ring, rule, scope="r0",
        fast_s=30.0, slow_s=120.0, change_ratio=1.6, min_samples=4,
    )
    assert det is not None and det.value == 0.0 and det.baseline == 2.0
    steady = SnapshotRing(depth=32)
    for i in range(11):
        steady.append(snap(i * 15.0, gauges={"serve_kernel_config_source": 2.0}))
    assert evaluate_rule(
        steady, rule, scope="r0",
        fast_s=30.0, slow_s=120.0, change_ratio=1.6, min_samples=4,
    ) is None


def test_ratio_collapse_prefix_hit_rate():
    rule = next(r for r in default_rules() if r.name == "prefix_hit_collapse")
    ring = SnapshotRing(depth=32)
    # 90% hit rate for 120 s, then hits stop while admissions continue
    for i in range(9):
        ring.append(snap(i * 15.0, counters={
            "serve_requests_admitted_total": i * 20,
            "serve_prefix_hits_total": i * 18,
        }))
    for j, t in enumerate((135.0, 150.0), start=1):
        ring.append(snap(t, counters={
            "serve_requests_admitted_total": 8 * 20 + j * 20,
            "serve_prefix_hits_total": 8 * 18,
        }))
    det = evaluate_rule(
        ring, rule, scope="r0",
        fast_s=30.0, slow_s=120.0, change_ratio=1.6, min_samples=4,
    )
    assert det is not None
    assert det.value == pytest.approx(0.0)
    assert det.baseline > 0.5


# ---- coverage gates (the satellite's SnapshotRing edge cases) ---------------


def test_ring_shorter_than_detection_window_stays_quiet():
    """A ring spanning 10 s must not evaluate 30/300 s windows, however
    dramatic its contents — a fresh replica is not a regression."""
    ring = SnapshotRing(depth=32)
    obs: list[float] = []
    for i in range(6):
        obs = obs + ([0.05] * 6 if i < 4 else [5.0] * 6)
        ring.append(snap(i * 2.0, hists={"serve_ttft_seconds": list(obs)}))
    sentinel = Sentinel((REPLAY_RULE,))  # production 30/300 s windows
    assert sentinel.observe({"r0": ring}) == []
    # the same contents over a wide-enough span DO fire (the gate was the
    # only thing holding the detection back)
    assert evaluate_rule(
        ring, REPLAY_RULE, scope="r0",
        fast_s=2.0, slow_s=8.0, change_ratio=1.6, min_samples=4,
    ) is not None


def test_counter_reset_mid_window_clears_history_and_stays_quiet():
    """A replica restart (counters shrink) drops pre-reset history: the
    sentinel sees no covered window right after, and never a negative
    rate-collapse verdict."""
    ring = SnapshotRing(depth=32)
    for i in range(9):
        ring.append(snap(i * 15.0, counters={"serve_tokens_emitted_total": i * 1500}))
    reset = ring.append(snap(135.0, counters={"serve_tokens_emitted_total": 30}))
    assert reset and ring.resets == 1 and len(ring) == 1
    rule = next(r for r in default_rules() if r.name == "token_rate_collapse")
    assert evaluate_rule(
        ring, rule, scope="r0",
        fast_s=30.0, slow_s=120.0, change_ratio=1.6, min_samples=4,
    ) is None
    sentinel = Sentinel((rule,), fast_s=30.0, slow_s=120.0)
    assert sentinel.observe({"r0": ring}) == []


def test_fleet_merge_with_one_stale_replica():
    """Fleet-wide windows over [fresh, fresh, just-restarted]: the stale
    ring (single capture, no window) contributes nothing — no fabricated
    zeros dragging the fleet rate down, no crash."""
    fresh = []
    for base in (0, 1):
        ring = SnapshotRing(depth=8)
        for i in range(3):
            ring.append(snap(
                i * 10.0, counters={"serve_tokens_emitted_total": (base + 1) * i * 500}
            ))
        fresh.append(ring)
    stale = SnapshotRing(depth=8)
    stale.append(snap(25.0, counters={"serve_tokens_emitted_total": 7}))
    rings = [*fresh, stale]
    span = fleet_window_span(rings, 20.0)
    assert span == pytest.approx(20.0)
    # 1000 + 2000 tokens over the 20 s window; the stale ring adds nothing
    assert fleet_rate(rings, "serve_tokens_emitted_total", 20.0) == pytest.approx(150.0)
    view = serving_window_view(rings, 20.0)
    assert view["tok_s"] == pytest.approx(150.0)


# ---- latch + replay determinism ---------------------------------------------


def test_sustained_breach_latches_to_one_detection_then_rearms():
    ring = SnapshotRing(depth=32)
    sentinel = Sentinel((REPLAY_RULE,), fast_s=30.0, slow_s=120.0, min_samples=4)
    obs: list[float] = []
    fired = []
    for i in range(12):
        # 9 clean captures, then the regression holds for 3 more
        obs = obs + [0.05 if i < 9 else 2.0] * 6
        ring.append(snap(i * 15.0, hists={"serve_ttft_seconds": list(obs)}))
        new = sentinel.observe({"r0": ring})
        if new:
            # edge-trigger: the breach latches the instant it fires
            assert sentinel.active() == [("ttft_regression", "r0")]
        fired.extend(new)
    assert len(fired) == 1  # one sustained regression == one incident
    assert sentinel.detections_total == 1
    # the slow window absorbs the regression and the breach clears — the
    # latch re-arms instead of re-firing on every observe cycle
    for i in range(12, 22):
        obs = obs + [2.0] * 6
        ring.append(snap(i * 15.0, hists={"serve_ttft_seconds": list(obs)}))
        fired.extend(sentinel.observe({"r0": ring}))
    assert len(fired) == 1
    assert sentinel.active() == []


def test_replay_is_byte_identical_and_detects():
    """The tentpole pin: identical fixtures through the replay sim produce
    byte-identical detection streams — same dicts, same content-hash ids,
    same digest. A second scope staying clean must stay silent."""
    sequences = {
        "replica0": _latency_timeline(),
        "replica1": [
            snap(i * 15.0, hists={"serve_ttft_seconds": [0.05] * 6 * (i + 1)})
            for i in range(11)
        ],
    }
    kwargs = dict(
        rules=(REPLAY_RULE,), fast_s=30.0, slow_s=120.0,
        change_ratio=1.6, min_samples=4,
    )
    first = replay(sequences, **kwargs)
    second = replay(sequences, **kwargs)
    assert json.dumps(first, sort_keys=True) == json.dumps(second, sort_keys=True)
    assert replay_digest(first) == replay_digest(second)
    detections = [d for step in first for d in step]
    assert len(detections) == 1
    assert detections[0]["scope"] == "replica0"
    assert detections[0]["id"]  # content hash, minted identically both runs
    assert all(d["scope"] != "replica1" for d in detections)


def test_detection_id_is_a_content_hash():
    ring = SnapshotRing(depth=32)
    for s in _latency_timeline():
        ring.append(s)
    kwargs = dict(
        scope="r0", fast_s=30.0, slow_s=120.0, change_ratio=1.6, min_samples=4
    )
    a = evaluate_rule(ring, REPLAY_RULE, **kwargs)
    b = evaluate_rule(ring, REPLAY_RULE, **kwargs)
    assert isinstance(a, Detection) and isinstance(b, Detection)
    assert a.id == b.id  # same content, same id — no clock, no RNG
    other = evaluate_rule(ring, REPLAY_RULE, **{**kwargs, "scope": "r1"})
    assert other is not None and other.id != a.id


# ---- incident bundles + store -----------------------------------------------


def _detection_dict() -> dict:
    return {
        "id": "abc123def456",
        "rule": "ttft_regression",
        "severity": "warn",
        "scope": "r0",
        "metric": "serve_ttft_seconds",
        "value": 2.0,
        "baseline": 0.05,
        "ratio": 40.0,
        "windows": {"fast_s": 30.0, "slow_s": 120.0, "end_at": 150.0},
    }


def test_build_bundle_collects_evidence_and_never_raises():
    ring = SnapshotRing(depth=32)
    for i in range(11):
        ring.append(snap(i * 15.0, counters={"serve_tokens_emitted_total": i * 1500}))

    class Flight:
        def summaries(self, limit=50):
            return {
                "inflight": [],
                "recent": [
                    {"id": "req-1", "duration_s": 3.0},
                    {"id": "req-2", "duration_s": 0.5},
                ],
            }

        def get(self, key):
            return {"id": key, "events": [{"event": "admitted"}]}

    bundle = build_bundle(
        _detection_dict(),
        ring=ring,
        flight=Flight(),
        journal=[{"direction": "up"}] * 12,
        spans=lambda: [{"name": "fleet.observe"}] * 30,
    )
    assert bundle["metrics"]["serve_tokens_emitted_total"]["after"] == 15000.0
    assert [f["id"] for f in bundle["flights"]] == ["req-1", "req-2"]
    assert len(bundle["journal"]) == 8 and len(bundle["spans"]) == 20
    assert bundle["rule"] == "ttft_regression"
    summary = bundle_summary(bundle)
    assert summary["id"] == "abc123def456" and summary["flights"] == 2
    # every evidence source degrades, none raises
    hostile = build_bundle(
        _detection_dict(), ring=None, flight=object(), journal=7, spans=object()
    )
    assert hostile["metrics"] == {} and hostile["flights"] == []
    assert snapshot_delta(None, 60.0) == {}
    assert slowest_flights(None) == []


def test_incident_store_persists_prunes_and_reloads(tmp_path):
    store = IncidentStore(tmp_path, depth=2)
    ids = []
    for i in range(3):
        det = {**_detection_dict(), "id": f"{i:012x}"}
        ids.append(store.add(build_bundle(det)))
    assert len(store) == 2  # oldest pruned
    assert store.get(ids[0]) is None
    assert store.get(ids[2])["rule"] == "ttft_regression"
    files = sorted(p.name for p in tmp_path.glob("incident-*.json"))
    assert len(files) == 2  # disk mirrors the ring
    # a restarted replica reloads the surviving bundles AND keeps counting
    # sequence numbers from where the dead process stopped
    revived = IncidentStore(tmp_path, depth=2)
    assert len(revived) == 2
    assert revived.get(ids[1]) is not None
    revived.add({**_detection_dict(), "id": "f" * 12})
    assert revived.get(ids[1]) is None  # pruned as the ring advances
    # id hygiene: traversal-shaped ids never touch the filesystem
    assert revived.get("../../etc/passwd") is None
    assert revived.get("not-hex!") is None


# ---- /admin/incidents over HTTP (server + router parity) --------------------


class _ScriptedBackend:
    """Minimal generate-backend (the test_fleet pattern): enough for an
    InferenceServer to boot without an engine."""

    concurrent = True
    prefix_cache_enabled = True

    def __init__(self, name: str = "replica-a"):
        self.name = name

    def stats(self):
        return {"queue_depth": 0, "active_slots": 0, "max_slots": 8}

    def generate(self, prompts, max_new_tokens, temperature, top_p=1.0, templated=False):
        return [self.name] * len(prompts)


def test_admin_incidents_endpoint_auth_parity_and_detail():
    from prime_tpu.serve import InferenceServer

    srv = InferenceServer(
        "tiny-test", _ScriptedBackend(), port=0, admin_token="sekrit"
    ).start()
    try:
        bundle = build_bundle(_detection_dict())
        srv.incidents.add(bundle)
        url = f"{srv.url}/admin/incidents"
        assert httpx.get(url, timeout=10).status_code == 403  # token parity
        headers = {"Authorization": "Bearer sekrit"}
        listing = httpx.get(url, headers=headers, timeout=10).json()
        assert [i["id"] for i in listing["incidents"]] == ["abc123def456"]
        detail = httpx.get(
            f"{url}/abc123def456", headers=headers, timeout=10
        ).json()
        assert detail["rule"] == "ttft_regression" and "metrics" in detail
        assert httpx.get(
            f"{url}/000000000000", headers=headers, timeout=10
        ).status_code == 404
    finally:
        srv.stop()


def test_router_fleet_view_merges_replica_bundles():
    from prime_tpu.serve import InferenceServer
    from prime_tpu.serve.fleet import serve_fleet

    srv = InferenceServer("tiny-test", _ScriptedBackend(), port=0).start()
    router = serve_fleet([srv.url], poll_interval=0.1, model_id="tiny-test")
    try:
        srv.incidents.add(build_bundle(_detection_dict()))
        view = httpx.get(f"{router.url}/admin/incidents", timeout=10).json()
        assert view["router"] == []
        merged = [
            i["id"]
            for replica in view["replicas"].values()
            for i in replica.get("incidents", [])
        ]
        assert merged == ["abc123def456"]
        # detail fan-out: the router doesn't own the bundle, a replica does
        detail = httpx.get(
            f"{router.url}/admin/incidents/abc123def456", timeout=10
        ).json()
        assert detail["rule"] == "ttft_regression" and detail.get("replica")
    finally:
        router.stop()
        srv.stop()


# ---- injected-delay knob (the planted-regression lever) ---------------------


def test_parse_inject_spec_formats_and_junk():
    from prime_tpu.serve.engine import _parse_inject_spec

    assert _parse_inject_spec("120") == (0.12, 0)
    assert _parse_inject_spec("60@40") == (0.06, 40)
    assert _parse_inject_spec("  5@3  ") == (0.005, 3)
    for junk in ("", "abc", "10@x", "@", "@5"):
        assert _parse_inject_spec(junk) == (0.0, 0)
    # negatives clamp to inactive rather than going back in time
    assert _parse_inject_spec("-5") == (0.0, 0)


# ---- trajectory gate --------------------------------------------------------


def _round(label: str, metrics: dict) -> dict:
    return {"label": label, "metrics": metrics}


def test_trajectory_gate_passes_committed_history_fails_synthetic_regression():
    """The CI contract: the real committed trajectory gates clean, and the
    same history plus a synthetic collapsed round fails."""
    from pathlib import Path

    from prime_tpu.loadgen.perf_delta import Round, load_all_rounds

    root = Path(__file__).resolve().parent.parent
    rounds = load_all_rounds(str(root))
    assert len(rounds) >= 3, "committed BENCH trajectory went missing"
    gate = trajectory_gate(rounds)
    assert gate["ok"], f"committed trajectory must gate clean: {gate['latest']}"
    # synthetic regression: every gated metric of the last round collapses 10x
    last = rounds[-1]
    bad = Round(
        label="synthetic-regressed", path="<test>", order=(9999, "z"),
        schema=2, record={},
        metrics={name: value / 10.0 for name, value in last.metrics.items()},
    )
    gate_bad = trajectory_gate([*rounds, bad])
    assert not gate_bad["ok"]
    assert gate_bad["latest"]["verdict"] == "regressed"
    assert gate_bad["latest"]["regressions"]


def test_trajectory_verdicts_bands_directions_and_history():
    rounds = [
        _round("r1", {"loadgen tok/s": 100.0, "slo:smoke ttft p95 ms": 50.0}),
        _round("r2", {"loadgen tok/s": 105.0, "slo:smoke ttft p95 ms": 55.0}),
        _round("r3", {"loadgen tok/s": 95.0, "slo:smoke ttft p95 ms": 45.0}),
        _round("r4", {"loadgen tok/s": 20.0, "slo:smoke ttft p95 ms": 48.0}),
    ]
    verdicts = trajectory_verdicts(rounds, band_pct=50.0, min_history=3)
    assert [v["verdict"] for v in verdicts] == [
        "insufficient-history", "insufficient-history", "insufficient-history",
        "regressed",
    ]
    assert verdicts[-1]["regressions"][0]["metric"] == "loadgen tok/s"
    # latency rows are smaller-is-better and gate only when opted in
    assert smaller_is_better("slo:smoke ttft p95 ms")
    assert not smaller_is_better("loadgen tok/s")
    lat = [
        _round(f"r{i}", {"slo:smoke ttft p95 ms": 50.0}) for i in range(3)
    ] + [_round("r4", {"slo:smoke ttft p95 ms": 500.0})]
    assert trajectory_gate(lat)["ok"]  # curated default gate skips latency
    assert not trajectory_gate(lat, gate_metrics="all")["ok"]


def test_trajectory_gate_insufficient_history_passes():
    rounds = [_round("r1", {"loadgen tok/s": 100.0})]
    gate = trajectory_gate(rounds)
    assert gate["ok"] and gate["latest"]["verdict"] == "insufficient-history"
    assert trajectory_gate([])["ok"]


def test_perf_delta_renders_sentinel_verdict_row():
    """Satellite: the delta table and the CI gate share one implementation —
    the table's `sentinel verdict` row must reflect trajectory_verdicts."""
    from prime_tpu.loadgen.perf_delta import Round, delta_table

    rounds = [
        Round(
            label=f"r{i}", path="<test>", order=(i, ""), schema=2, record={},
            metrics={"loadgen tok/s": 100.0 if i < 4 else 10.0},
        )
        for i in range(5)
    ]
    table = delta_table(rounds)
    assert "sentinel verdict" in table
    assert "REGRESSED(1)" in table
    assert "no-history" in table
