"""Test harness config.

- Forces JAX onto a virtual 8-device CPU mesh (set BEFORE any jax import) so
  multi-chip sharding logic is testable without TPU hardware (SURVEY.md §4).
- Isolates HOME / PRIME_CONFIG_DIR per test so no test touches ~/.prime
  (mirrors the reference's HOME->tmp_path isolation, tests/test_pods_create.py).
- Provides the anyio backend fixture so async tests run under pytest without
  pytest-asyncio.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# The environment may preload jax and initialize a TPU backend at interpreter
# start (sitecustomize); env vars alone are then too late. jax.config still
# switches the active platform, and the CPU client initializes lazily with the
# XLA_FLAGS above — giving the 8 virtual devices regardless of preload.
import jax

jax.config.update("jax_platforms", "cpu")

import pytest

# Environment capability gates: the repo targets the jax_graft toolchain; an
# older JAX build in a test container lacks part of that surface (e.g.
# jax.set_mesh landed after 0.4.x). Test modules exercising such APIs define
# a `requires_set_mesh`-style skipif marker locally (NOT here — `import
# conftest` from a test module is ambiguous with tests/live/conftest.py), so
# a red tier-1 signal means a broken change, not a thin environment.


@pytest.fixture
def anyio_backend():
    return "asyncio"


@pytest.fixture(autouse=True)
def _isolate_config(tmp_path, monkeypatch):
    monkeypatch.setenv("PRIME_CONFIG_DIR", str(tmp_path / ".prime"))
    monkeypatch.setenv("PRIME_DISABLE_VERSION_CHECK", "1")  # no network nag in tests
    monkeypatch.delenv("PRIME_API_KEY", raising=False)
    monkeypatch.delenv("PRIME_TEAM_ID", raising=False)
    monkeypatch.delenv("PRIME_BASE_URL", raising=False)
    monkeypatch.delenv("PRIME_CONTEXT", raising=False)
    yield
