"""Sharded-replica serving tests (forced multi-device CPU mesh).

The load-bearing property mirrors test_engine.py's: whatever the topology,
a greedy request decoded by the engine produces exactly the tokens the
single-chip engine (and the one-shot sampler) produces. Everything here
runs on the virtual CPU mesh the conftest forces (8 devices; the CI
serve-smoke mesh leg re-runs it at ``host_platform_device_count=4``) — the
engine builds a 4-device ``(dp, fsdp, tp)`` mesh from the declarative spec
and shards params + paged KV itself (docs/architecture.md "Sharded
replica").
"""

import jax
import jax.numpy as jnp
import pytest

from prime_tpu.models import get_config
from prime_tpu.models.llama import init_params
from prime_tpu.models.sampler import generate
from prime_tpu.serve.engine import ContinuousBatchingEngine
from prime_tpu.serve.mesh_config import ServeMeshConfig, parse_mesh_spec

CONFIG = get_config("tiny-test")
PARAMS = init_params(jax.random.PRNGKey(0), CONFIG, dtype=jnp.float32)
MESH_SPEC = "dp=1,fsdp=2,tp=2"

requires_multichip = pytest.mark.skipif(
    jax.device_count() < 4,
    reason="needs >=4 devices (XLA_FLAGS=--xla_force_host_platform_device_count=4)",
)


@pytest.fixture(autouse=True)
def _default_serve_env(monkeypatch):
    """Pin the env-driven engine defaults (same rationale as test_engine)."""
    monkeypatch.delenv("PRIME_SERVE_OVERLAP", raising=False)
    monkeypatch.delenv("PRIME_SERVE_WARMUP", raising=False)
    monkeypatch.delenv("PRIME_SERVE_MESH", raising=False)
    monkeypatch.delenv("PRIME_SERVE_SPEC", raising=False)
    monkeypatch.delenv("PRIME_SERVE_DRAFT_LEN", raising=False)
    monkeypatch.delenv("PRIME_SERVE_PREFIX_CACHE_MB", raising=False)
    monkeypatch.delenv("PRIME_SERVE_PREFIX_CACHE_HOST_MB", raising=False)


def reference_tokens(prompt_ids: list[int], n: int) -> list[int]:
    result = generate(
        PARAMS, jnp.asarray([prompt_ids], dtype=jnp.int32),
        jnp.asarray([len(prompt_ids)], dtype=jnp.int32), CONFIG,
        jax.random.PRNGKey(7), max_new_tokens=n, temperature=0.0,
    )
    return result.tokens[0].tolist()


def make_engine(**kw) -> ContinuousBatchingEngine:
    kw.setdefault("max_slots", 4)
    kw.setdefault("capacity", 128)
    kw.setdefault("chunk", 4)
    kw.setdefault("prefix_cache_mb", 0)
    return ContinuousBatchingEngine(PARAMS, CONFIG, **kw)


def drain(engine, *requests, max_ticks=300):
    for _ in range(max_ticks):
        engine.tick()
        if all(r.done for r in requests):
            return
    raise AssertionError("requests did not finish")


# two waves of shared-prefix prompts: long enough (>= 2 blocks) that the
# store/hit path engages when the cache is on, divergent tails so the radix
# tree actually branches
_PREAMBLE = [(7 * i) % 50 + 3 for i in range(34)]
WAVE_PROMPTS = [
    _PREAMBLE + [61, 62, 63],
    _PREAMBLE + [64, 65],
    [9, 8, 7, 6, 5, 4, 3, 2],
]


# ---- declarative mesh config -------------------------------------------------


def test_parse_mesh_spec_explicit_and_absorbing():
    cfg = parse_mesh_spec("dp=1,fsdp=2,tp=2", 8)
    assert cfg.axes == {"dp": 1, "fsdp": 2, "tp": 2}
    assert cfg.total_devices == 4
    assert cfg.spec == "dp=1,fsdp=2,tp=2"
    # bare names: sizes default to 1 except the LAST unsized axis, which
    # absorbs every remaining device
    cfg = parse_mesh_spec("dp,fsdp,tp", 8)
    assert cfg.axes == {"dp": 1, "fsdp": 1, "tp": 8}
    cfg = parse_mesh_spec("fsdp=2,tp", 8)
    assert cfg.axes == {"fsdp": 2, "tp": 4}
    # fully sized specs may describe a SUB-slice of the host (build() takes
    # the first total_devices devices) — only an absorbing axis needs the
    # device count to factor cleanly
    assert parse_mesh_spec("dp=1,fsdp=2,tp=2", 6).total_devices == 4
    assert parse_mesh_spec("", 8) is None
    assert parse_mesh_spec("  ", 8) is None


def test_parse_mesh_spec_rejects_junk():
    with pytest.raises(ValueError, match="unknown mesh axis"):
        parse_mesh_spec("dp=1,warp=2", 8)
    with pytest.raises(ValueError, match="integer"):
        parse_mesh_spec("tp=two", 8)
    with pytest.raises(ValueError, match="positive"):
        parse_mesh_spec("tp=0", 8)
    with pytest.raises(ValueError, match="available"):
        parse_mesh_spec("tp=16", 8)  # fully sized but bigger than the host
    with pytest.raises(ValueError, match="divide"):
        parse_mesh_spec("dp,tp=3", 8)  # absorbing axis can't resolve 8/3
    with pytest.raises(ValueError, match="duplicate"):
        ServeMeshConfig(("tp", "tp"), (2, 2))
    with pytest.raises(ValueError, match="equal rank"):
        ServeMeshConfig(("dp", "tp"), (2,))


@requires_multichip
def test_mesh_config_build_uses_prefix_of_devices():
    mesh = parse_mesh_spec(MESH_SPEC, jax.device_count()).build()
    assert mesh.size == 4
    assert dict(mesh.shape) == {"dp": 1, "fsdp": 2, "tp": 2}


def test_mesh_config_build_rejects_oversize():
    cfg = ServeMeshConfig(("tp",), (jax.device_count() * 2,))
    with pytest.raises(ValueError, match="devices"):
        cfg.build()


# ---- greedy bit-identity matrix: sharded vs single-chip ----------------------


@requires_multichip
@pytest.mark.parametrize("overlap", [True, False], ids=["overlap", "sync"])
@pytest.mark.parametrize("cache_mb", [0, 1], ids=["nocache", "prefixcache"])
def test_sharded_bit_identity_matrix(overlap, cache_mb):
    """Greedy outputs on the 4-device mesh are bit-identical to the
    single-chip engine (itself pinned to the one-shot sampler) across the
    overlap x prefix-cache matrix — two waves, so the cached leg's second
    wave actually assembles from the sharded radix cache."""

    def run(**engine_kw):
        engine = make_engine(overlap=overlap, prefix_cache_mb=cache_mb, **engine_kw)
        out = []
        for _ in range(2):  # second wave prefix-hits when the cache is on
            reqs = [engine.submit(p, max_new_tokens=8) for p in WAVE_PROMPTS]
            drain(engine, *reqs)
            out.append([r.all_tokens(timeout=5) for r in reqs])
        return engine, out

    sharded, sharded_out = run(mesh_config=MESH_SPEC)
    assert sharded.mesh_devices == 4
    assert sharded.attn_impl == "sharded"
    single, single_out = run()
    assert single.mesh_devices == 1
    assert sharded_out == single_out
    for prompt, tokens in zip(WAVE_PROMPTS, sharded_out[0]):
        assert tokens == reference_tokens(prompt, 8)
    if cache_mb:
        # the sharded cache really served the second wave (no silent miss)
        assert sharded.prefix_hits >= 2
        assert sharded.prefix_hits == single.prefix_hits


@requires_multichip
def test_sharded_warmup_program_set_pin():
    """AOT warmup covers the sharded program set: the bounded program
    shapes are topology-independent, so the sharded engine must execute
    EXACTLY as many warmup programs as the single-chip engine — a drifting
    count means a program real traffic compiles mid-pipeline that warmup
    missed (or warmup compiling shapes traffic never runs). Warmup must
    also leave the sharded engine cold-state clean: the first real request
    after it still matches the reference."""
    sharded = make_engine(prefix_cache_mb=1, capacity=64, mesh_config=MESH_SPEC)
    single = make_engine(prefix_cache_mb=1, capacity=64)
    assert sharded.warmup() == single.warmup()
    assert int(sharded.registry.values()["serve_warmup_programs"]) > 0
    req = sharded.submit(WAVE_PROMPTS[2], max_new_tokens=6)
    drain(sharded, req)
    assert req.all_tokens(timeout=5) == reference_tokens(WAVE_PROMPTS[2], 6)


# ---- speculative decoding on the mesh ----------------------------------------


# periodic + aperiodic + the shared-prefix pair: drafts land on the first,
# miss on the second, and the pair's second wave exercises spec + cache hit
SPEC_PROMPTS = [
    list(range(1, 9)) * 2,
    [7, 100, 23, 451, 88, 3],
    _PREAMBLE + [61, 62],
    _PREAMBLE + [63],
]


@requires_multichip
@pytest.mark.parametrize("overlap", [True, False], ids=["overlap", "sync"])
def test_sharded_spec_bit_identity(overlap):
    """Speculative decoding spans the mesh: the fused propose+verify program
    (history ring + draft buffers placed with the (dp, fsdp, tp) layout)
    emits greedy tokens bit-identical to the single-chip spec engine, to the
    serial spec loop, and to non-spec decode — two waves with the prefix
    cache on, so the second wave assembles from the sharded radix cache
    while speculating."""

    def run(**engine_kw):
        engine = make_engine(
            overlap=overlap, prefix_cache_mb=1, min_prefix=16, **engine_kw
        )
        waves = []
        for _ in range(2):
            reqs = [engine.submit(list(p), max_new_tokens=8) for p in SPEC_PROMPTS]
            drain(engine, *reqs)
            engine.tick()  # drain any lookahead chunk
            waves.append([r.all_tokens(timeout=5) for r in reqs])
        return engine, waves

    sharded, sharded_out = run(speculative=True, mesh_config=MESH_SPEC)
    assert sharded.mesh_devices == 4 and sharded.speculative
    single, single_out = run(speculative=True)
    plain, plain_out = run()
    assert sharded_out == single_out == plain_out
    for prompt, tokens in zip(SPEC_PROMPTS, sharded_out[0]):
        assert tokens == reference_tokens(list(prompt), 8)
    # the sharded cache served the second wave while speculating
    assert sharded.prefix_hits >= 2
    assert sharded.prefix_hits == single.prefix_hits
    # acceptance evidence from the sharded verify windows
    assert sharded.stats()["spec_accept_ratio"] > 0


@requires_multichip
def test_sharded_spec_warmup_program_set_pin():
    """The spec program set is topology-independent too: a speculative
    sharded engine executes exactly the speculative single-chip engine's
    warmup program count (fused spec dispatch + hist-seed wave widths
    included)."""
    sharded = make_engine(
        prefix_cache_mb=1, capacity=64, speculative=True, mesh_config=MESH_SPEC
    )
    single = make_engine(prefix_cache_mb=1, capacity=64, speculative=True)
    assert sharded.warmup() == single.warmup()
    req = sharded.submit(SPEC_PROMPTS[0], max_new_tokens=6)
    drain(sharded, req)
    assert req.all_tokens(timeout=5) == reference_tokens(SPEC_PROMPTS[0], 6)


@requires_multichip
def test_sharded_spec_dispatch_spans_carry_mesh_devices(tmp_path):
    """The serve.spec_dispatch span (satellite obs) stamps mesh_devices on a
    sharded engine, read back from a real JSONL sink."""
    import json

    from prime_tpu.obs.trace import TRACER

    sink = tmp_path / "trace.jsonl"
    engine = make_engine(speculative=True, mesh_config=MESH_SPEC)
    prev = TRACER.reconfigure(enabled=True, sink_path=str(sink))
    try:
        req = engine.submit(SPEC_PROMPTS[0], max_new_tokens=4)
        drain(engine, req)
        engine.tick()
    finally:
        TRACER.reconfigure(**prev)
    spans = [
        json.loads(line)["attrs"]
        for line in sink.read_text().splitlines()
        if json.loads(line)["name"] == "serve.spec_dispatch"
    ]
    assert spans and all(a.get("mesh_devices") == 4 for a in spans)
    assert all(a.get("draft_len") == 4 for a in spans)


# ---- mesh observability ------------------------------------------------------


@requires_multichip
def test_sharded_stats_and_gauge_report_mesh():
    engine = make_engine(mesh_config=MESH_SPEC)
    stats = engine.stats()
    assert stats["mesh_devices"] == 4
    assert stats["mesh_axes"] == {"dp": 1, "fsdp": 2, "tp": 2}
    assert int(engine.registry.values()["serve_mesh_devices"]) == 4
    # single-chip engines report the same keys with the trivial values
    single = make_engine()
    stats = single.stats()
    assert stats["mesh_devices"] == 1
    assert stats["mesh_axes"] == {}
    assert int(single.registry.values()["serve_mesh_devices"]) == 1


@requires_multichip
def test_sharded_healthz_reports_mesh_shape():
    from prime_tpu.evals.tokenizer import ByteTokenizer
    from prime_tpu.serve.engine import EngineBackend
    from prime_tpu.serve.server import InferenceServer

    import httpx

    engine = make_engine(mesh_config=MESH_SPEC)
    with engine:
        backend = EngineBackend(engine, ByteTokenizer())
        with InferenceServer("tiny-test", backend, port=0) as srv:
            payload = httpx.get(f"{srv.url}/healthz").json()
            assert payload["mesh_devices"] == 4
            assert payload["mesh"] == {"dp": 1, "fsdp": 2, "tp": 2}


def test_single_chip_healthz_omits_mesh():
    from prime_tpu.evals.tokenizer import ByteTokenizer
    from prime_tpu.serve.engine import EngineBackend
    from prime_tpu.serve.server import InferenceServer

    import httpx

    engine = make_engine()
    with engine:
        backend = EngineBackend(engine, ByteTokenizer())
        with InferenceServer("tiny-test", backend, port=0) as srv:
            payload = httpx.get(f"{srv.url}/healthz").json()
            assert "mesh_devices" not in payload
            assert "mesh" not in payload


@requires_multichip
def test_sharded_dispatch_spans_carry_mesh_devices(tmp_path):
    import json

    from prime_tpu.obs.trace import TRACER

    sink = tmp_path / "trace.jsonl"
    engine = make_engine(mesh_config=MESH_SPEC)
    prev = TRACER.reconfigure(enabled=True, sink_path=str(sink))
    try:
        req = engine.submit(WAVE_PROMPTS[2], max_new_tokens=4)
        drain(engine, req)
    finally:
        TRACER.reconfigure(**prev)
    by_name: dict[str, list[dict]] = {}
    for line in sink.read_text().splitlines():
        span = json.loads(line)
        by_name.setdefault(span["name"], []).append(span["attrs"])
    assert by_name["serve.prefill"] and all(
        a.get("mesh_devices") == 4 for a in by_name["serve.prefill"]
    )
    device_spans = by_name.get("serve.dispatch", []) + by_name.get(
        "serve.decode_chunk", []
    )
    assert device_spans and all(a.get("mesh_devices") == 4 for a in device_spans)


# ---- env knob + host-tier gate ----------------------------------------------


@requires_multichip
def test_prime_serve_mesh_env_wiring(monkeypatch):
    monkeypatch.setenv("PRIME_SERVE_MESH", MESH_SPEC)
    engine = make_engine()
    assert engine.mesh_devices == 4
    assert engine.mesh_axes == {"dp": 1, "fsdp": 2, "tp": 2}
    # explicit kwarg beats env; empty env means single-chip
    monkeypatch.setenv("PRIME_SERVE_MESH", "")
    assert make_engine().mesh_devices == 1
    monkeypatch.delenv("PRIME_SERVE_MESH")
    assert make_engine(mesh_config="fsdp=2,tp=2").mesh_devices == 4


@requires_multichip
def test_host_tier_gate_is_explicit_in_stats_and_gauge():
    """Satellite: configuring a prefix-cache host tier on a multi-device
    mesh must surface as the serve_prefix_host_tier_disabled gauge and the
    prefix_host_tier_disabled stats key — not only a log warning."""
    with pytest.warns(UserWarning, match="host spill tier"):
        gated = make_engine(
            prefix_cache_mb=1, prefix_cache_host_mb=2, mesh_config=MESH_SPEC
        )
    assert gated.prefix_cache_host_mb == 0.0
    assert int(gated.registry.values()["serve_prefix_host_tier_disabled"]) == 1
    assert gated.stats()["prefix_host_tier_disabled"] == 1
    # single-chip engines keep the tier and report 0
    kept = make_engine(prefix_cache_mb=1, prefix_cache_host_mb=2)
    assert kept.prefix_cache_host_mb == 2
    assert int(kept.registry.values()["serve_prefix_host_tier_disabled"]) == 0
    assert kept.stats()["prefix_host_tier_disabled"] == 0


def test_serve_model_mesh_validation():
    from prime_tpu.serve import serve_model

    with pytest.raises(ValueError, match="--continuous"):
        serve_model("tiny-test", port=0, mesh="tp=2")
    with pytest.raises(ValueError, match="one"):
        serve_model("tiny-test", port=0, continuous=True, mesh="tp=2", slice_name="v5e-8")


# ---- perf delta: MULTICHIP rounds render as their own rows -------------------


def test_perf_delta_multichip_rounds_own_rows(tmp_path):
    import json

    from prime_tpu.loadgen.perf_delta import delta_table, load_all_rounds

    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"value": 100.0, "metric": "decode_tokens_per_sec", "serve_tok_s": 50.0}
    ))
    # legacy dryrun wrapper (rounds 1-5's shape)
    (tmp_path / "MULTICHIP_r01.json").write_text(json.dumps(
        {"n_devices": 8, "rc": 0, "ok": True, "skipped": False, "tail": "..."}
    ))
    # schema-2 sharded loadgen record (this PR's shape)
    (tmp_path / "MULTICHIP_loadgen_cpu_r02.json").write_text(json.dumps(
        {
            "schema": 2, "metric": "serve_sharded_tok_s (...)", "value": 34.9,
            "unit": "tokens/s", "backend": "cpu",
            "mesh": {"dp": 1, "fsdp": 2, "tp": 2}, "mesh_devices": 4,
            "loadgen": {"headline": {"tok_s": 34.9}, "scenarios": [
                {"scenario": "smoke", "tok_s": 34.9, "ttft_s": {"p50": 0.75}},
            ]},
        }
    ))
    # a full bench.py record committed as a MULTICHIP round: the sharded
    # headline is serve_sharded_tok_s — "value" is the single-chip decode
    # headline and must NOT render as the multichip number
    (tmp_path / "MULTICHIP_r03.json").write_text(json.dumps(
        {
            "schema": 2, "value": 1800.0, "metric": "decode_tokens_per_sec",
            "serve_sharded_tok_s": 210.5, "serve_mesh": "dp=1,fsdp=2,tp=4",
            "serve_mesh_devices": 8,
        }
    ))
    # a bench record whose sharded section FAILED: no serve_sharded_tok_s,
    # no mesh stamp — the single-chip decode headline must not masquerade
    # as the multichip number
    (tmp_path / "MULTICHIP_r04.json").write_text(json.dumps(
        {
            "schema": 2, "value": 1800.0, "metric": "decode_tokens_per_sec",
            "serve_sharded_error": "RuntimeError: boom",
        }
    ))
    # a sharded smoke record committed under a BENCH name: its own stamps
    # (mesh_devices + serve_sharded_tok_s metric) route it to the mc rows —
    # its headline must never land in the single-chip 'cpu-smoke tok/s' row
    (tmp_path / "BENCH_loadgen_cpu_r05.json").write_text(json.dumps(
        {
            "schema": 2, "metric": "serve_sharded_tok_s (...)", "value": 33.0,
            "backend": "cpu", "mesh": {"tp": 4}, "mesh_devices": 4,
        }
    ))
    rounds = load_all_rounds(str(tmp_path))
    assert [r.label for r in rounds] == [
        "r01", "mc01", "mc02-loadgen_cpu", "mc03", "mc04", "mc05-loadgen_cpu",
    ]
    mc05 = rounds[5]
    assert mc05.metrics["mc sharded tok/s"] == 33.0
    assert "cpu-smoke tok/s" not in mc05.metrics
    mc01, mc02, mc03, mc04 = rounds[1], rounds[2], rounds[3], rounds[4]
    assert mc01.metrics == {"mc dryrun ok": 1.0}
    assert mc02.metrics["mc sharded tok/s"] == 34.9
    assert mc02.metrics["mc mesh devices"] == 4.0
    assert mc02.metrics["mc-slo:smoke tok/s"] == 34.9
    assert mc03.metrics["mc sharded tok/s"] == 210.5
    assert mc03.metrics["mc mesh devices"] == 8.0
    assert "mc sharded tok/s" not in mc04.metrics
    # multichip metric names are disjoint from every BENCH row: the delta
    # math can therefore never produce a cross-backend delta
    bench_names = set(rounds[0].metrics)
    assert not (bench_names & set(mc01.metrics) | bench_names & set(mc02.metrics))
    table = delta_table(rounds)
    assert "mc sharded tok/s" in table and "mc01" in table
    # the committed repo rounds must keep parsing too (r01..r06 + mc01..mc06)
    import os

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    committed = load_all_rounds(repo_root)
    assert any(r.label.startswith("mc06") for r in committed)
    assert any(r.metrics.get("mc sharded tok/s", 0) > 0 for r in committed)


# ---- multi-LoRA on the mesh --------------------------------------------------


@requires_multichip
def test_sharded_multilora_bit_identity(tmp_path):
    """The multi-LoRA × mesh cell of the acceptance matrix: an adapter
    request through a SHARDED engine's gathered path emits the same greedy
    tokens as (a) the single-chip banked engine and (b) a merged-adapter
    engine — and base traffic on the sharded banked engine stays
    bit-identical to the plain sharded engine's."""
    from prime_tpu.train.lora import (
        LoraConfig,
        init_lora_params,
        merge_lora,
        save_adapters,
    )

    lora = LoraConfig(r=4, alpha=8)
    factors = init_lora_params(jax.random.PRNGKey(11), CONFIG, lora)
    factors["layers"] = {
        name: {
            "a": ab["a"],
            "b": (
                jax.random.normal(jax.random.PRNGKey(12), ab["b"].shape) * 0.05
            ).astype(ab["b"].dtype),
        }
        for name, ab in factors["layers"].items()
    }
    path = tmp_path / "tenant-a"
    save_adapters(path, factors, lora, CONFIG, base_params=PARAMS)
    prompt = WAVE_PROMPTS[0]

    def run(engine, adapter=None):
        req = engine.submit(prompt, max_new_tokens=10, adapter=adapter)
        drain(engine, req)
        toks = req.all_tokens(timeout=2)
        engine.shutdown()
        return toks

    single = run(
        make_engine(adapters={"tenant-a": str(path)}), adapter="tenant-a"
    )
    merged = run(
        ContinuousBatchingEngine(
            merge_lora(PARAMS, factors, lora), CONFIG,
            max_slots=4, capacity=128, chunk=4, prefix_cache_mb=0,
        )
    )
    assert single == merged
    sharded = run(
        make_engine(adapters={"tenant-a": str(path)}, mesh_config=MESH_SPEC),
        adapter="tenant-a",
    )
    assert sharded == single
    # base traffic: banked sharded == plain sharded (slot 0 is exact zero)
    plain = run(make_engine(mesh_config=MESH_SPEC))
    base_on_banked = run(
        make_engine(adapters={"tenant-a": str(path)}, mesh_config=MESH_SPEC)
    )
    assert base_on_banked == plain


@requires_multichip
def test_sharded_bank_placement_follows_projection_axes(tmp_path):
    """The bank shards consistently with the wrapped projections: A on the
    base weight's input (fsdp) axis, B on its output (tp) axis."""
    from prime_tpu.train.lora import LoraConfig, init_lora_params, save_adapters

    lora = LoraConfig(r=4, alpha=8)
    factors = init_lora_params(jax.random.PRNGKey(11), CONFIG, lora)
    path = tmp_path / "tenant-a"
    save_adapters(path, factors, lora, CONFIG, base_params=PARAMS)
    engine = make_engine(
        adapters={"tenant-a": str(path)}, mesh_config=MESH_SPEC
    )
    try:
        stacks = engine.adapter_bank.stacks["layers"]
        a_spec = stacks["wq"]["a"].sharding.spec
        b_spec = stacks["wq"]["b"].sharding.spec
        # (L, A, d_in, r): d_in on fsdp; (L, A, r, d_out): d_out on tp
        assert tuple(a_spec) == (None, None, "fsdp", None)
        assert tuple(b_spec) == (None, None, None, "tp")
    finally:
        engine.shutdown()
