"""Images suite: SDK client (sync+async), CLI commands, bulk operations."""

import json

import pytest
from click.testing import CliRunner

import prime_tpu.commands._deps as deps
from prime_tpu.commands.main import cli
from prime_tpu.core.client import APIClient, AsyncAPIClient
from prime_tpu.core.config import Config
from prime_tpu.sandboxes.images import AsyncImageClient, ImageClient
from prime_tpu.testing import FakeControlPlane


@pytest.fixture
def fake(monkeypatch):
    fake = FakeControlPlane()
    monkeypatch.setattr(deps, "transport_override", fake.transport)
    monkeypatch.setenv("PRIME_API_KEY", "test-key")
    monkeypatch.setenv("PRIME_BASE_URL", "https://api.fake")
    return fake


@pytest.fixture
def client(fake):
    cfg = Config()
    cfg.api_key = "test-key"
    return ImageClient(APIClient(config=cfg, base_url="https://api.fake", transport=fake.transport))


@pytest.fixture
def runner():
    return CliRunner()


# -- SDK ----------------------------------------------------------------------


def test_sdk_build_and_lifecycle(client):
    image = client.build("jax-base", dockerfile_text="FROM python:3.12\n")
    assert image["status"] == "BUILDING" and image["kind"] == "container"
    assert client.build_status(image["imageId"])["status"] == "READY"
    assert client.publish(image["imageId"])["visibility"] == "public"
    assert client.unpublish(image["imageId"])["visibility"] == "private"
    assert client.get(image["imageId"])["name"] == "jax-base"
    assert len(client.list()) == 1


def test_sdk_duplicate_name_conflict(client):
    client.build("dup", dockerfile_text="FROM a\n")
    from prime_tpu.core.exceptions import APIError

    with pytest.raises(APIError):
        client.build("dup", dockerfile_text="FROM b\n")


def test_sdk_build_vm_requires_base(client):
    from prime_tpu.core.exceptions import ValidationError

    vm = client.build_vm("vm-img", base_image="tpu-ubuntu2204", boot_disk_gb=100)
    assert vm["kind"] == "vm" and vm["bootDiskGb"] == 100
    with pytest.raises(ValidationError):
        client.api.post("/images/build-vm", json={"name": "x"}, idempotent_post=True)


def test_sdk_hf_cache_image(client):
    image = client.build_hf_cache("llama-cache", ["meta-llama/Llama-3.2-1B"])
    assert image["kind"] == "hf-cache"
    cache = next(a for a in image["artifacts"] if a["partition"] == "cache")
    assert cache["status"] == "READY" and cache["sizeMb"] == 1024
    with pytest.raises(ValueError, match="at least one model"):
        client.build_hf_cache("empty", [])


def test_sdk_transfer_derives_name(client):
    image = client.transfer("docker.io/library/python:3.12-slim")
    assert image["name"] == "python-3.12-slim"
    assert image["status"] == "TRANSFERRING"


def test_sdk_visibility_bulk_mixed(client):
    a = client.build("a", dockerfile_text="FROM a\n")
    results = client.set_visibility_bulk([a["imageId"], "img_missing"], "public")
    by_id = {r["imageId"]: r for r in results}
    assert by_id[a["imageId"]]["ok"] and not by_id["img_missing"]["ok"]
    assert client.get(a["imageId"])["visibility"] == "public"


def test_sdk_update_bulk(client):
    a = client.build("old-name", dockerfile_text="FROM a\n")
    results = client.update_bulk([{"imageId": a["imageId"], "name": "new-name"}])
    assert results[0]["ok"]
    assert client.get(a["imageId"])["name"] == "new-name"


@pytest.mark.anyio
async def test_sdk_async_mirror(fake):
    cfg = Config()
    cfg.api_key = "test-key"
    api = AsyncAPIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)
    client = AsyncImageClient(api)
    image = await client.build("async-img", dockerfile_text="FROM a\n")
    assert (await client.build_status(image["imageId"]))["status"] == "READY"
    assert (await client.set_visibility_bulk([image["imageId"]], "public"))[0]["ok"]
    assert len(await client.list()) == 1
    await api.close()


# -- CLI ----------------------------------------------------------------------


def test_cli_get_renders_artifacts(runner, fake, client):
    image = client.build("arty", dockerfile_text="FROM a\n")
    result = runner.invoke(cli, ["images", "get", image["imageId"], "--plain"])
    assert result.exit_code == 0, result.output
    assert "rootfs" in result.output and "PARTITION" in result.output
    as_json = json.loads(
        runner.invoke(cli, ["images", "get", image["imageId"], "--output", "json"]).output
    )
    assert as_json["artifacts"][0]["partition"] == "rootfs"


def test_cli_build_vm_and_unpublish(runner, fake):
    result = runner.invoke(
        cli,
        ["images", "build-vm", "--name", "vm1", "--base-image", "tpu-vm-base", "--output", "json"],
    )
    assert result.exit_code == 0, result.output
    image_id = json.loads(result.output)["imageId"]
    runner.invoke(cli, ["images", "publish", image_id])
    result = runner.invoke(cli, ["images", "unpublish", image_id, "--plain"])
    assert "private" in result.output


def test_cli_hf_cache(runner, fake):
    result = runner.invoke(
        cli,
        ["images", "hf-cache", "--name", "caches", "--model",
         "meta-llama/Llama-3.2-1B", "--model", "Qwen/Qwen2-0.5B", "--output", "json"],
    )
    assert result.exit_code == 0, result.output
    data = json.loads(result.output)
    assert data["kind"] == "hf-cache" and len(data["models"]) == 2


def test_cli_visibility_bulk(runner, fake, client):
    a = client.build("va", dockerfile_text="FROM a\n")
    b = client.build("vb", dockerfile_text="FROM b\n")
    result = runner.invoke(
        cli, ["images", "visibility", "public", a["imageId"], b["imageId"], "--plain"]
    )
    assert result.exit_code == 0, result.output
    assert "2/2 succeeded" in result.output


def test_cli_bulk_push_manifest(runner, fake, tmp_path):
    manifest = tmp_path / "m.json"
    manifest.write_text(json.dumps([
        {"name": "bulk-a", "dockerfileText": "FROM a\n"},
        {"name": "bulk-b", "dockerfileText": "FROM b\n"},
        {"name": "bulk-c", "dockerfileText": "FROM c\n"},
    ]))
    result = runner.invoke(cli, ["images", "bulk-push", "--manifest", str(manifest), "--plain"])
    assert result.exit_code == 0, result.output
    assert "3/3 succeeded" in result.output
    assert len(fake.misc_plane.images) == 3


def test_cli_bulk_push_retries_429(runner, fake, tmp_path, monkeypatch):
    import prime_tpu.commands.images as images_cmd

    monkeypatch.setattr(images_cmd, "_bulk_sleep", lambda s: None)
    fake.misc_plane.image_build_429s = 1  # first build attempt rate-limited
    manifest = tmp_path / "m.json"
    manifest.write_text(json.dumps([{"name": "retry-a", "dockerfileText": "FROM a\n"}]))
    result = runner.invoke(cli, ["images", "bulk-push", "--manifest", str(manifest), "--plain"])
    assert result.exit_code == 0, result.output
    assert "1/1 succeeded" in result.output


def test_cli_bulk_push_partial_failure_exits_nonzero(runner, fake, tmp_path):
    manifest = tmp_path / "m.json"
    manifest.write_text(json.dumps([
        {"name": "dup-x", "dockerfileText": "FROM a\n"},
        {"name": "dup-x", "dockerfileText": "FROM b\n"},  # 409 duplicate
    ]))
    result = runner.invoke(cli, ["images", "bulk-push", "--manifest", str(manifest), "--plain"])
    assert result.exit_code == 1
    assert "1/2 succeeded" in result.output and "ERR" in result.output


def test_cli_bulk_transfer_and_update(runner, fake, tmp_path, client):
    transfers = tmp_path / "t.json"
    transfers.write_text(json.dumps([
        {"source": "docker.io/library/redis:7"},
        {"source": "gcr.io/foo/bar:latest", "name": "bar"},
    ]))
    result = runner.invoke(cli, ["images", "bulk-transfer", "--manifest", str(transfers), "--plain"])
    assert result.exit_code == 0, result.output
    assert "2/2 succeeded" in result.output

    ids = list(fake.misc_plane.images)
    updates = tmp_path / "u.json"
    updates.write_text(json.dumps([
        {"imageId": ids[0], "visibility": "public"},
        {"imageId": "img_nope", "name": "x"},
    ]))
    result = runner.invoke(cli, ["images", "bulk-update", "--manifest", str(updates), "--plain"])
    assert result.exit_code == 1  # one entry failed
    assert "1/2 succeeded" in result.output
    assert fake.misc_plane.images[ids[0]]["visibility"] == "public"


def test_cli_bulk_push_bad_manifest(runner, fake, tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    result = runner.invoke(cli, ["images", "bulk-push", "--manifest", str(bad)])
    assert result.exit_code != 0
    assert "JSON list" in result.output


def test_cli_bulk_push_bad_entry_does_not_abort_batch(runner, fake, tmp_path):
    manifest = tmp_path / "m.json"
    manifest.write_text(json.dumps([
        {"name": "no-dockerfile"},                      # client-side ValueError
        {"name": "fine", "dockerfileText": "FROM a\n"},
    ]))
    result = runner.invoke(cli, ["images", "bulk-push", "--manifest", str(manifest), "--plain"])
    assert result.exit_code == 1
    assert "1/2 succeeded" in result.output
    assert "no-dockerfile" in result.output  # failed entry still labeled


def test_images_update_and_delete(runner, fake, client):
    """Single-image update (shares the bulk contract) and delete with
    confirmation (reference images.py update/delete)."""
    from prime_tpu.commands.main import cli

    image_id = client.build("upd-img", dockerfile_text="FROM x\n")["imageId"]
    result = runner.invoke(
        cli, ["images", "update", image_id, "--name", "renamed", "--visibility", "public"]
    )
    assert result.exit_code == 0, result.output
    assert fake.misc_plane.images[image_id]["name"] == "renamed"
    assert fake.misc_plane.images[image_id]["visibility"] == "public"
    # nothing-to-update and unknown image both error loudly
    assert runner.invoke(cli, ["images", "update", image_id]).exit_code != 0
    assert runner.invoke(
        cli, ["images", "update", "img_nope", "--name", "x"]
    ).exit_code != 0
    # delete: refused without confirmation, removed with -y
    refused = runner.invoke(cli, ["images", "delete", image_id], input="n\n")
    assert refused.exit_code == 0 and image_id in fake.misc_plane.images
    assert runner.invoke(cli, ["images", "delete", image_id, "-y"]).exit_code == 0
    assert image_id not in fake.misc_plane.images
    assert runner.invoke(cli, ["images", "delete", image_id, "-y"]).exit_code != 0
