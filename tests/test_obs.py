"""Observability layer: registry semantics, Prometheus exposition, span
tracing, and the serve wiring (JSON /metrics backward compatibility +
histograms populating through a real streamed completion).

The fast server-scrape tests double as the tier-1 smoke for exposition
regressions: they import prime_tpu.obs, stand up a live in-process
InferenceServer, and parse the actual Prometheus text a scraper would see.
"""

import json
import math

import httpx
import pytest

from prime_tpu.obs import (
    FlightRecorder,
    Registry,
    TraceContext,
    Tracer,
    lint_prometheus_text,
    new_traceparent,
    parse_traceparent,
    quantile_from_snapshot,
)

# ---- histogram semantics ----------------------------------------------------


def test_histogram_bucket_boundaries():
    """``le`` semantics: a value ON a bound lands in that bucket; past the
    last bound only +Inf counts it."""
    r = Registry()
    h = r.histogram("h_seconds", "x", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.100001, 1.0, 9.9, 10.0, 11.0):
        h.observe(v)
    snap = h.series_snapshot()
    assert snap["counts"] == [2, 2, 2, 1]  # per-bucket (non-cumulative) + Inf
    assert snap["count"] == 7
    assert snap["sum"] == pytest.approx(sum((0.05, 0.1, 0.100001, 1.0, 9.9, 10.0, 11.0)))


def test_histogram_quantiles():
    r = Registry()
    h = r.histogram("h", "x", buckets=(1.0, 2.0, 4.0))
    for _ in range(50):
        h.observe(0.5)
    for _ in range(50):
        h.observe(3.0)
    # 50 obs in (0,1], 50 in (2,4]: the median sits exactly at bucket 1's
    # upper bound, p99 interpolates inside the (2,4] bucket
    assert h.quantile(0.5) == pytest.approx(1.0)
    assert 2.0 <= h.quantile(0.99) <= 4.0
    assert math.isnan(r.histogram("empty", "x", buckets=(1.0,)).quantile(0.5))
    with pytest.raises(ValueError):
        h.quantile(1.5)
    # the snapshot-based estimator is the same math
    snap = h.series_snapshot()
    assert quantile_from_snapshot(snap["buckets"], snap["counts"], 0.5) == pytest.approx(
        h.quantile(0.5)
    )


def test_histogram_bucket_validation():
    r = Registry()
    with pytest.raises(ValueError):
        r.histogram("bad", "x", buckets=())
    with pytest.raises(ValueError):
        r.histogram("bad2", "x", buckets=(2.0, 1.0))


# ---- registry semantics -----------------------------------------------------


def test_counter_and_gauge():
    r = Registry()
    c = r.counter("c_total", "x")
    c.inc()
    c.inc(2)
    assert c.value() == 3
    with pytest.raises(ValueError):
        c.inc(-1)
    g = r.gauge("g", "x")
    g.set(5)
    g.dec(2)
    assert g.value() == 3


def test_registry_get_or_create_and_conflicts():
    r = Registry()
    assert r.counter("c_total") is r.counter("c_total")
    with pytest.raises(ValueError):
        r.gauge("c_total")  # same name, different kind
    with pytest.raises(ValueError):
        r.counter("c_total", labelnames=("x",))  # same kind, different labels
    with pytest.raises(ValueError):
        r.counter("bad name")
    with pytest.raises(ValueError):
        r.counter("ok_total", labelnames=("bad-label",))


def test_labeled_series_and_values():
    r = Registry()
    c = r.counter("req_total", "x", labelnames=("method",))
    c.inc(method="GET")
    c.inc(3, method="POST")
    assert c.value(method="POST") == 3
    assert c.value(method="DELETE") == 0  # never observed
    with pytest.raises(ValueError):
        c.inc(verb="GET")  # wrong label name
    # values() is the unlabeled-only consistent read (engine stats source)
    plain = r.counter("plain_total")
    plain.inc(7)
    assert r.values() == {"plain_total": 7.0}


# ---- Prometheus exposition --------------------------------------------------


def test_prometheus_rendering_and_escaping():
    r = Registry()
    c = r.counter("reqs_total", 'help with \\ and\nnewline', labelnames=("path",))
    c.inc(2, path='a"b\\c\nd')
    text = r.render_prometheus()
    assert '# HELP reqs_total help with \\\\ and\\nnewline' in text
    assert "# TYPE reqs_total counter" in text
    assert 'reqs_total{path="a\\"b\\\\c\\nd"} 2' in text


def test_prometheus_histogram_rendering():
    r = Registry()
    h = r.histogram("lat_seconds", "latency", buckets=(0.5, 1.0))
    h.observe(0.2)
    h.observe(0.7)
    h.observe(5.0)
    lines = r.render_prometheus().splitlines()
    assert 'lat_seconds_bucket{le="0.5"} 1' in lines
    assert 'lat_seconds_bucket{le="1"} 2' in lines  # cumulative
    assert 'lat_seconds_bucket{le="+Inf"} 3' in lines
    assert "lat_seconds_sum 5.9" in lines
    assert "lat_seconds_count 3" in lines


def test_prometheus_unobserved_and_nonfinite_are_well_formed():
    """Satellite: a registered-but-never-observed label-less histogram must
    emit zero-count bucket series (not a bare HELP/TYPE header), and NaN/Inf
    gauges must use the text-format spellings — checked by the lint."""
    r = Registry()
    r.histogram("cold_seconds", "never observed", buckets=(0.5, 1.0))
    r.counter("cold_total", "never incremented")
    g = r.gauge("weird")
    g.set(float("nan"))
    g2 = r.gauge("hot")
    g2.set(float("inf"))
    text = r.render_prometheus()
    assert 'cold_seconds_bucket{le="+Inf"} 0' in text
    assert "cold_seconds_count 0" in text and "cold_seconds_sum 0" in text
    assert "cold_total 0" in text
    assert "weird NaN" in text and "nan" not in text
    assert "hot +Inf" in text
    assert lint_prometheus_text(text) == []


def test_exposition_lint_catches_violations():
    ok = (
        "# TYPE h_seconds histogram\n"
        'h_seconds_bucket{le="1"} 1\n'
        'h_seconds_bucket{le="+Inf"} 2\n'
        "h_seconds_sum 1.5\n"
        "h_seconds_count 2\n"
    )
    assert lint_prometheus_text(ok) == []
    # non-cumulative buckets
    assert lint_prometheus_text(
        '# TYPE h histogram\nh_bucket{le="1"} 5\nh_bucket{le="+Inf"} 2\nh_count 2\n'
    )
    # missing +Inf bucket
    assert lint_prometheus_text('# TYPE h histogram\nh_bucket{le="1"} 1\nh_count 1\n')
    # _count disagrees with the +Inf bucket
    assert lint_prometheus_text(
        '# TYPE h histogram\nh_bucket{le="+Inf"} 2\nh_count 3\n'
    )
    # duplicate series, bad value spelling, unparseable line
    assert lint_prometheus_text("# TYPE c counter\nc 1\nc 2\n")
    assert lint_prometheus_text("# TYPE g gauge\ng nan\n")
    assert lint_prometheus_text("just not exposition\n")
    # legal label values containing '}', ',' and escapes must NOT trip it
    assert lint_prometheus_text(
        '# TYPE c counter\nc{a="x,y",b="cl}osed",d="q\\"uo"} 1\n'
    ) == []
    assert lint_prometheus_text('# TYPE c counter\nc{a="trailing",} 1\n') == []
    assert lint_prometheus_text('# TYPE c counter\nc{a=unquoted} 1\n')


def test_snapshot_roundtrips_through_json():
    r = Registry()
    r.counter("c_total").inc()
    r.histogram("h_seconds", buckets=(1.0,)).observe(2.0)
    snap = json.loads(json.dumps(r.snapshot()))
    assert snap["c_total"]["type"] == "counter"
    assert snap["h_seconds"]["series"][0]["counts"] == [0, 1]


# ---- tracing ----------------------------------------------------------------


def test_span_nesting_and_jsonl_roundtrip(tmp_path):
    tracer = Tracer()
    with tracer.span("outer", kind="request") as outer:
        with tracer.span("inner") as inner:
            inner.set_attr("tokens", 3)
        assert inner.parent_id == outer.span_id
        assert inner.trace_id == outer.trace_id
    path = tmp_path / "trace.jsonl"
    assert tracer.export_jsonl(path) == 2
    rows = [json.loads(line) for line in path.read_text().splitlines()]
    by_name = {row["name"]: row for row in rows}
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
    assert by_name["outer"]["parent_id"] is None
    assert by_name["inner"]["attrs"] == {"tokens": 3}
    assert all(row["duration_s"] >= 0 for row in rows)
    # inner is fully contained in outer on the monotonic clock
    assert by_name["inner"]["start_s"] >= by_name["outer"]["start_s"]
    assert tracer.drain() == []  # export drained the buffer


def test_span_records_exceptions_and_sink(tmp_path):
    sink = tmp_path / "sink.jsonl"
    tracer = Tracer(sink_path=sink)
    with pytest.raises(RuntimeError):
        with tracer.span("boom"):
            raise RuntimeError("kaput")
    row = json.loads(sink.read_text().splitlines()[0])
    assert "kaput" in row["attrs"]["error"]
    tracer.close()


def test_disabled_tracer_is_noop():
    tracer = Tracer(enabled=False)
    with tracer.span("x", a=1) as s:
        s.set_attr("b", 2)  # must not raise
    assert s.traceparent() is None  # callers skip header injection
    assert tracer.drain() == []


# ---- trace context propagation ----------------------------------------------


def test_traceparent_roundtrip_valid():
    header = new_traceparent()
    ctx = parse_traceparent(header)
    assert ctx is not None
    assert ctx.to_header() == header
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    # whitespace/case tolerated (header values travel through proxies)
    assert parse_traceparent(f"  {header.upper()}  ") == ctx
    # future versions may carry extra fields — parse the known prefix
    assert parse_traceparent("cf-" + "a" * 32 + "-" + "b" * 16 + "-01-extra") is not None


@pytest.mark.parametrize(
    "header",
    [
        None,
        "",
        "garbage",
        "00-" + "a" * 31 + "-" + "b" * 16 + "-01",  # short trace id
        "00-" + "a" * 32 + "-" + "b" * 15 + "-01",  # short span id
        "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",  # invalid version
        "00-" + "0" * 32 + "-" + "b" * 16 + "-01",  # all-zero trace id
        "00-" + "a" * 32 + "-" + "0" * 16 + "-01",  # all-zero span id
        "00-" + "a" * 32 + "-" + "b" * 16 + "-01-extra",  # v00 has no extras
        "00-" + "A" * 32 + "-" + "b" * 16,  # missing flags
    ],
)
def test_traceparent_malformed_or_absent(header):
    assert parse_traceparent(header) is None


def test_span_joins_inbound_context():
    tracer = Tracer()
    ctx = parse_traceparent(new_traceparent())
    with tracer.span("server.hop", context=ctx) as root:
        with tracer.span("inner") as inner:
            pass
    assert root.trace_id == ctx.trace_id
    assert root.parent_id == ctx.span_id
    assert inner.trace_id == ctx.trace_id and inner.parent_id == root.span_id
    # the span's own traceparent parses back to (trace_id, span_id)
    fwd = parse_traceparent(root.traceparent())
    assert fwd.trace_id == ctx.trace_id and fwd.span_id == root.span_id
    # explicit context beats the thread-local stack
    other = TraceContext.generate()
    with tracer.span("outer"):
        with tracer.span("rebased", context=other) as rebased:
            pass
    assert rebased.trace_id == other.trace_id


def test_tracer_emit_synthetic_span():
    tracer = Tracer()
    ctx = TraceContext.generate()
    tracer.emit("serve.queue_wait", 0.25, context=ctx, request=7)
    tracer.emit("rootless", 0.1)
    spans = tracer.drain()
    wait = next(s for s in spans if s["name"] == "serve.queue_wait")
    assert wait["trace_id"] == ctx.trace_id and wait["parent_id"] == ctx.span_id
    assert wait["duration_s"] == pytest.approx(0.25)
    assert wait["attrs"] == {"request": 7}
    # disabled tracer: emit is free
    off = Tracer(enabled=False)
    off.emit("x", 1.0)
    assert off.drain() == []


def test_tracer_reconfigure_roundtrip(tmp_path):
    tracer = Tracer(enabled=False)
    sink = tmp_path / "sink.jsonl"
    prev = tracer.reconfigure(enabled=True, sink_path=str(sink))
    with tracer.span("x"):
        pass
    tracer.reconfigure(**prev)
    assert not tracer.enabled
    assert len(sink.read_text().splitlines()) == 1


# ---- serve wiring -----------------------------------------------------------


class EchoGenerator:
    def generate(self, prompts, max_new_tokens, temperature, top_p=1.0):
        return [p.splitlines()[-2].split(":", 1)[1].strip().upper() for p in prompts]


@pytest.fixture
def server():
    from prime_tpu.serve import InferenceServer

    with InferenceServer("tiny-test", EchoGenerator(), port=0) as srv:
        yield srv


def test_metrics_json_shape_unchanged(server):
    """The default JSON /metrics response keeps the pre-obs shape for
    existing keys (wire compatibility for whatever already scrapes it)."""
    data = httpx.get(f"{server.url}/metrics").json()
    assert data["model"] == "tiny-test"
    assert data["loaded"] is True
    assert "engine" not in data  # EchoGenerator has no stats()


def test_healthz(server):
    data = httpx.get(f"{server.url}/healthz").json()
    assert data["status"] == "ok"
    assert data["loaded"] is True
    assert data["uptime_s"] >= 0

    from prime_tpu.serve import InferenceServer

    with InferenceServer("tiny-test", port=0) as unloaded:
        data = httpx.get(f"{unloaded.url}/healthz").json()
        assert data["status"] == "ok" and data["loaded"] is False


def test_prometheus_scrape_live_server(server):
    """Fast exposition smoke: a live in-process server must serve parseable
    Prometheus text with the http metrics populated."""
    httpx.post(
        f"{server.url}/v1/chat/completions",
        json={"messages": [{"role": "user", "content": "hi"}]},
        timeout=30,
    )
    response = httpx.get(f"{server.url}/metrics", params={"format": "prometheus"})
    assert response.status_code == 200
    assert response.headers["content-type"].startswith("text/plain")
    text = response.text
    assert "# TYPE http_requests_total counter" in text
    assert 'http_requests_total{route="/v1/chat/completions",method="POST",status="200"} 1' in text
    assert "# TYPE http_request_seconds histogram" in text
    # every non-comment line is `name{labels} value`
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        name_part, _, value = line.rpartition(" ")
        assert name_part and float(value) >= 0


def test_registry_json_view(server):
    payload = httpx.get(f"{server.url}/metrics", params={"format": "registry"}).json()
    assert "server" in payload
    assert payload["server"]["http_requests_total"]["type"] == "counter"


def test_engine_histograms_populate_through_streamed_completion():
    """Acceptance: one streamed chat completion through InferenceServer over
    the continuous-batching engine leaves serve_ttft_seconds and
    serve_queue_wait_seconds with non-zero counts in the Prometheus text,
    while the JSON /metrics engine keys stay the legacy shape."""
    import jax
    import jax.numpy as jnp

    from prime_tpu.evals.tokenizer import ByteTokenizer
    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.serve import InferenceServer
    from prime_tpu.serve.engine import ContinuousBatchingEngine, EngineBackend

    config = get_config("tiny-test")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    engine = ContinuousBatchingEngine(
        params, config, max_slots=2, capacity=128, chunk=4, prefix_cache_mb=0
    )
    with engine:
        backend = EngineBackend(engine, ByteTokenizer())
        with InferenceServer("tiny-test", backend, port=0) as srv:
            with httpx.stream(
                "POST",
                f"{srv.url}/v1/chat/completions",
                json={
                    "messages": [{"role": "user", "content": "ab"}],
                    "max_tokens": 6,
                    "stream": True,
                },
                timeout=120,
            ) as response:
                assert response.status_code == 200
                body = "".join(response.iter_lines())
                assert "[DONE]" in body

            # legacy JSON: the pre-registry counter keys, plus the decode
            # pipeline fields (PR 2), the radix prefix-cache fields (PR 3),
            # the fleet admission/drain fields (PR 4), the host spill
            # tier fields (PR 6), the sharded-replica mesh fields, the
            # speculative-decoding fields, the disaggregated-serving KV
            # export/import counters, and the paged-seeding counter —
            # additive only
            engine_stats = httpx.get(f"{srv.url}/metrics").json()["engine"]
            assert set(engine_stats) == {
                "requests_admitted", "requests_completed", "requests_cancelled",
                "requests_failed", "tokens_emitted", "prefix_hits",
                "batched_admission_waves", "active_slots", "queue_depth",
                "max_slots", "max_queue", "mesh_devices", "mesh_axes",
                "adapters_loaded", "adapters", "adapter_weights", "state",
                "overlap", "speculative", "draft_len", "spec_accept_ratio",
                "inflight_depth", "host_stall_s", "chunk_window_s",
                "overlap_ratio", "wasted_decode_tokens", "warmup_programs",
                "prefix_cache_bytes", "prefix_cache_host_bytes",
                "prefix_host_tier_disabled",
                "prefix_cache_nodes", "prefix_evictions", "prefix_spills",
                "prefix_reuploads", "prefix_assembles", "prefix_paged_seeds",
                "kv_exports", "kv_imports", "uptime_s",
            }
            assert engine_stats["requests_admitted"] == 1
            assert engine_stats["requests_completed"] == 1

            text = httpx.get(
                f"{srv.url}/metrics", params={"format": "prometheus"}
            ).text
    assert "serve_ttft_seconds_count 1" in text
    assert "serve_queue_wait_seconds_count 1" in text
    assert "serve_prefill_seconds_count 1" in text
    assert "serve_tokens_emitted_total 6" in text
    # decode ran at least one chunk past the prefill's first token
    assert "serve_decode_step_seconds_count 0" not in text
    # TTFT must be a real measurement, not a zero-fill
    for line in text.splitlines():
        if line.startswith("serve_ttft_seconds_sum"):
            assert float(line.split()[-1]) > 0


def test_engine_tpot_and_batch_size_histograms():
    """Direct engine drive: TPOT records per completed multi-token request,
    admission batch size records the wave width."""
    import jax
    import jax.numpy as jnp

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.serve.engine import ContinuousBatchingEngine

    config = get_config("tiny-test")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    engine = ContinuousBatchingEngine(
        params, config, max_slots=4, capacity=128, chunk=4, prefix_cache_mb=0
    )
    reqs = [engine.submit([3, 1, 4, 1], max_new_tokens=5) for _ in range(2)]
    for _ in range(50):
        engine.tick()
        if all(r.done for r in reqs):
            break
    assert all(r.done for r in reqs)
    tpot = engine.registry.get("serve_tpot_seconds").series_snapshot()
    assert tpot["count"] == 2
    batch = engine.registry.get("serve_admission_batch_size").series_snapshot()
    assert batch["count"] >= 1 and batch["sum"] == 2  # one 2-wide wave


def test_client_http_metrics():
    """Every APIClient request records latency/status/retries into the
    process-wide registry, sync and async alike."""
    from prime_tpu.core.client import (
        _HTTP_LATENCY,
        _HTTP_REQUESTS,
        _HTTP_RETRIES,
        APIClient,
    )
    from prime_tpu.core.config import Config

    before_ok = _HTTP_REQUESTS.value(method="GET", status="200")
    before_404 = _HTTP_REQUESTS.value(method="GET", status="404")
    before_retry = _HTTP_RETRIES.value(method="GET")
    lat_before = _HTTP_LATENCY.series_snapshot(method="GET")
    lat_before_count = lat_before["count"] if lat_before else 0

    calls = {"n": 0}

    def handler(request):
        calls["n"] += 1
        if calls["n"] == 1:
            return httpx.Response(503, json={})  # retried (idempotent GET)
        if calls["n"] == 2:
            return httpx.Response(200, json={"ok": True})
        return httpx.Response(404, json={"detail": "nope"})

    cfg = Config()
    cfg.api_key = "k"
    client = APIClient(
        config=cfg, base_url="https://api.test",
        transport=httpx.MockTransport(handler),
    )
    import prime_tpu.core.client as client_mod

    # no real sleeps in tests: the 503→200 retry backoff would add seconds
    orig = client_mod._backoff
    client_mod._backoff = lambda attempt: 0.0
    try:
        assert client.get("/thing") == {"ok": True}
        with pytest.raises(Exception):
            client.get("/missing")
    finally:
        client_mod._backoff = orig
    assert _HTTP_REQUESTS.value(method="GET", status="200") == before_ok + 1
    assert _HTTP_REQUESTS.value(method="GET", status="404") == before_404 + 1
    assert _HTTP_RETRIES.value(method="GET") == before_retry + 1  # the 503 retry
    assert _HTTP_LATENCY.series_snapshot(method="GET")["count"] == lat_before_count + 2


def test_eval_runner_latency_metrics(tmp_path):
    from prime_tpu.evals.runner import EvalRunSpec, run_eval

    class Oracle:
        def generate(self, prompts, max_new_tokens, temperature, top_p=1.0):
            return ["42"] * len(prompts)

    spec = EvalRunSpec(limit=6, batch_size=2, output_dir=str(tmp_path))
    result = run_eval(spec, generator=Oracle())
    for key in (
        "sample_latency_mean_s", "sample_latency_p50_s",
        "sample_latency_p95_s", "sample_latency_max_s",
    ):
        assert key in result.metrics
        assert result.metrics[key] >= 0
    meta = json.loads((result.run_dir / "metadata.json").read_text())
    obs = meta["obs"]
    assert obs["eval_samples_total"]["series"][0]["value"] == 6
    assert obs["eval_batch_seconds"]["series"][0]["count"] == 3


def test_serve_metrics_cli(server):
    from click.testing import CliRunner

    from prime_tpu.commands.serve import serve_cmd

    httpx.get(f"{server.url}/v1/models")  # populate an http counter
    runner = CliRunner()
    out = runner.invoke(
        serve_cmd, ["metrics", "--url", server.url, "--plain"]
    )
    assert out.exit_code == 0, out.output
    assert "http_requests_total" in out.output
    as_json = runner.invoke(
        serve_cmd, ["metrics", "--url", server.url, "--output", "json"]
    )
    assert as_json.exit_code == 0
    assert json.loads(as_json.output)["server"]["http_requests_total"]["type"] == "counter"
    prom = runner.invoke(serve_cmd, ["metrics", "--url", server.url, "--prometheus"])
    assert prom.exit_code == 0
    assert "# TYPE http_requests_total counter" in prom.output
    dead = runner.invoke(serve_cmd, ["metrics", "--url", "http://127.0.0.1:9"])
    assert dead.exit_code != 0
    assert "could not scrape" in dead.output


def test_serve_cli_still_requires_model():
    """The group conversion must not silently accept a bare `prime serve`."""
    from click.testing import CliRunner

    from prime_tpu.commands.serve import serve_cmd

    result = CliRunner().invoke(serve_cmd, [])
    assert result.exit_code != 0
    assert "--model" in result.output


def test_int4_pallas_gate_under_mesh():
    """ADVICE r5: the fused int4 kernel must be ineligible under a
    multi-device mesh context, and the XLA fallback must match the
    ungated reference numerics."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from prime_tpu.models import quantize as qz

    w = jax.random.normal(jax.random.PRNGKey(0), (256, 128), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 256), jnp.float32)
    qw = qz.quantize_weight_int4(w)
    assert qw[0].ndim == 2 and qw[0].dtype == jnp.uint8
    # outside any mesh: interpret mode keeps the kernel eligible (CPU tests)
    assert qz._int4_pallas_eligible(x, qw[0], True)
    ref = qz.matmul(x, qw)

    mesh = jax.make_mesh((2,), ("tp",), devices=jax.devices()[:2])
    with mesh:
        assert qz._mesh_context_active()
        assert not qz._int4_pallas_eligible(x, qw[0], True)
        out = qz.matmul(x, qw)
    assert not qz._mesh_context_active()
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


# ---- flight recorder --------------------------------------------------------


def test_flight_recorder_ring_bounded_under_churn():
    """Acceptance: the recorder's memory is strictly bounded no matter how
    many requests/events churn through it, and truncation is counted."""
    fr = FlightRecorder(capacity=8, max_events=4, max_inflight=3, slow_ms=0)
    for i in range(100):
        fr.begin(i, trace_id=f"{i:032x}", prompt_tokens=i)
        for j in range(10):
            fr.event(i, "chunk", seq=j)
        if i % 2 == 0:
            fr.end(i, "completed", tokens=3)
    s = fr.summaries()
    assert len(s["recent"]) <= 8
    assert len(s["inflight"]) <= 3
    full = fr.get(f"{98:032x}")  # lookup by trace id
    assert full is not None and full["id"] == "98"
    assert len(full["events"]) <= 4
    assert full["events_dropped"] > 0
    # evicted-over-inflight-bound timelines are retired, not leaked
    assert any(t["outcome"] == "evicted" for t in s["recent"])
    # unknown keys never raise (late events after retirement)
    fr.event("nope", "chunk")
    fr.end("nope", "completed")


def test_flight_recorder_summary_and_timeline_shape():
    fr = FlightRecorder(slow_ms=0)
    fr.begin("r1", trace_id="t" * 32, prompt_tokens=5)
    fr.event("r1", "admitted", slot=2)
    fr.annotate("r1", replica="10.0.0.1:8000")
    fr.end("r1", "completed", tokens=6)
    summary = fr.summaries()["recent"][0]
    assert summary["state"] == "done" and summary["outcome"] == "completed"
    assert summary["replica"] == "10.0.0.1:8000"
    timeline = fr.get("r1")
    events = [e["event"] for e in timeline["events"]]
    assert events == ["admitted", "completed"]
    assert timeline["events"][0]["slot"] == 2
    json.dumps(timeline)  # wire-able


def test_flight_recorder_slow_capture_persists_to_tracer(monkeypatch):
    from prime_tpu.obs import TRACER

    prev = TRACER.reconfigure(enabled=True, sink_path=None)
    try:
        fr = FlightRecorder(slow_ms=0.0001)
        fr.begin("slow", trace_id="a" * 32)
        fr.event("slow", "chunk")
        fr.end("slow", "completed")
        spans = [s for s in TRACER.drain() if s["name"] == "flight.slow_request"]
        assert spans and spans[-1]["trace_id"] == "a" * 32
        assert spans[-1]["attrs"]["timeline"][0]["event"] == "chunk"
    finally:
        TRACER.reconfigure(**prev)


def test_server_debug_requests_and_auth_parity():
    """/debug/requests on a plain (non-engine) server records the HTTP hop
    and honors the same admin-token gate as /admin/drain."""
    from prime_tpu.serve import InferenceServer

    with InferenceServer(
        "tiny-test", EchoGenerator(), port=0, admin_token="sekrit"
    ) as srv:
        tp = new_traceparent()
        ctx = parse_traceparent(tp)
        httpx.post(
            f"{srv.url}/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "hi"}]},
            headers={"traceparent": tp},
            timeout=30,
        )
        assert httpx.get(f"{srv.url}/debug/requests").status_code == 403
        auth = {"Authorization": "Bearer sekrit"}
        listing = httpx.get(f"{srv.url}/debug/requests", headers=auth).json()
        assert listing["recent"][0]["trace_id"] == ctx.trace_id
        timeline = httpx.get(
            f"{srv.url}/debug/requests/{ctx.trace_id}", headers=auth
        ).json()
        assert timeline["outcome"] == "http_200"
        assert (
            httpx.get(f"{srv.url}/debug/requests/zzz", headers=auth).status_code
            == 404
        )
    # no admin token -> open, like the admin surface
    with InferenceServer("tiny-test", EchoGenerator(), port=0, admin_token="") as srv:
        assert httpx.get(f"{srv.url}/debug/requests").status_code == 200


def test_engine_trace_continuity_and_flight_timeline(tmp_path):
    """Tentpole acceptance (replica half): a traced streamed request through
    the engine leaves serve.queue_wait/serve.prefill/serve.request spans
    sharing the INBOUND trace id, and /debug/requests/{trace_id} returns the
    engine's per-chunk timeline."""
    import jax
    import jax.numpy as jnp

    from prime_tpu.evals.tokenizer import ByteTokenizer
    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.obs import TRACER
    from prime_tpu.serve import InferenceServer
    from prime_tpu.serve.engine import ContinuousBatchingEngine, EngineBackend

    config = get_config("tiny-test")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    engine = ContinuousBatchingEngine(
        params, config, max_slots=2, capacity=128, chunk=4, prefix_cache_mb=0
    )
    sink = tmp_path / "trace.jsonl"
    prev = TRACER.reconfigure(enabled=True, sink_path=str(sink))
    tp = new_traceparent()
    ctx = parse_traceparent(tp)
    try:
        with engine:
            backend = EngineBackend(engine, ByteTokenizer())
            with InferenceServer("tiny-test", backend, port=0) as srv:
                with httpx.stream(
                    "POST",
                    f"{srv.url}/v1/chat/completions",
                    json={
                        "messages": [{"role": "user", "content": "ab"}],
                        "max_tokens": 6,
                        "stream": True,
                    },
                    headers={"traceparent": tp},
                    timeout=120,
                ) as response:
                    assert response.status_code == 200
                    "".join(response.iter_lines())
                timeline = httpx.get(
                    f"{srv.url}/debug/requests/{ctx.trace_id}", timeout=5
                ).json()
    finally:
        TRACER.reconfigure(**prev)
    events = [e["event"] for e in timeline["events"]]
    assert events[0] == "admitted"
    for expected in ("prefill_done", "first_token", "chunk"):
        assert expected in events, events
    assert timeline["outcome"] == "completed"
    spans = [json.loads(line) for line in sink.read_text().splitlines()]
    mine = {s["name"] for s in spans if s["trace_id"] == ctx.trace_id}
    assert {"serve.queue_wait", "serve.prefill", "serve.request"} <= mine
    # batched device spans stay process-local (they cover many requests)
    dispatch = next(s for s in spans if s["name"] == "serve.dispatch")
    assert dispatch["trace_id"] != ctx.trace_id


def test_serve_profile_waterfall_stitches_cross_process(tmp_path):
    """serve_profile --trace A --trace B: spans sharing a W3C trace id merge
    into one per-request waterfall with cross-process gaps called out."""
    import pathlib
    import subprocess
    import sys

    trace_id = "ab" * 16
    router_spans = [
        {"name": "fleet.route", "trace_id": trace_id, "span_id": "r" * 16,
         "parent_id": None, "start_unix_s": 100.0, "start_s": 0.0,
         "duration_s": 0.5, "attrs": {}},
        {"name": "fleet.attempt", "trace_id": trace_id, "span_id": "a" * 16,
         "parent_id": "r" * 16, "start_unix_s": 100.01, "start_s": 0.01,
         "duration_s": 0.48, "attrs": {"replica": "rep-1"}},
    ]
    replica_spans = [
        {"name": "serve.chat", "trace_id": trace_id, "span_id": "c" * 16,
         "parent_id": "a" * 16, "start_unix_s": 100.06, "start_s": 7.0,
         "duration_s": 0.4, "attrs": {}},
        # an unrelated single-span trace: not stitched
        {"name": "serve.request", "trace_id": "cd" * 16, "span_id": "d" * 16,
         "parent_id": "e" * 16, "start_unix_s": 50.0, "start_s": 1.0,
         "duration_s": 0.1, "attrs": {}},
    ]
    a = tmp_path / "router.jsonl"
    b = tmp_path / "replica.jsonl"
    a.write_text("".join(json.dumps(s) + "\n" for s in router_spans))
    b.write_text("".join(json.dumps(s) + "\n" for s in replica_spans))
    script = str(
        pathlib.Path(__file__).resolve().parents[1] / "scripts" / "serve_profile.py"
    )
    out = subprocess.run(
        [sys.executable, script, "--trace", str(a), "--trace", str(b)],
        capture_output=True, text=True, timeout=60, check=True,
    ).stdout
    assert f"trace {trace_id}: 3 spans" in out
    assert "router.jsonl" in out and "replica.jsonl" in out
    # indentation encodes the parent chain; the replica hop calls out its gap
    assert "fleet.route" in out and "fleet.attempt" in out and "serve.chat" in out
    assert "[cross-process]" in out
    assert "+50.00 ms after parent" in out
    # --trace-id narrows to one request
    picked = subprocess.run(
        [sys.executable, script, "--trace", str(a), "--trace", str(b),
         "--trace-id", "cd" * 16],
        capture_output=True, text=True, timeout=60, check=True,
    ).stdout
    assert "serve.request" in picked and "fleet.route" not in picked


def test_serve_profile_overlap_report(tmp_path):
    """scripts/serve_profile.py --trace: pairs serve.dispatch/serve.sync
    spans by chunk seq from a PRIME_TRACE JSONL and reports the per-chunk
    host-stall fraction (the offline twin of serve_overlap_ratio)."""
    import json
    import pathlib
    import subprocess
    import sys

    spans = [
        # chunk 0: dispatched at t=0.00 (1ms enqueue), synced over [0.10, 0.101]
        {"name": "serve.dispatch", "start_s": 0.0, "duration_s": 0.001,
         "attrs": {"seq": 0, "steps": 8}},
        {"name": "serve.sync", "start_s": 0.10, "duration_s": 0.001,
         "attrs": {"seq": 0}},
        # chunk 1: fully stalled (sync spans the whole window)
        {"name": "serve.dispatch", "start_s": 0.2, "duration_s": 0.001,
         "attrs": {"seq": 1, "steps": 8}},
        {"name": "serve.sync", "start_s": 0.201, "duration_s": 0.099,
         "attrs": {"seq": 1}},
        # unrelated span: must be ignored
        {"name": "serve.prefill", "start_s": 0.0, "duration_s": 0.5, "attrs": {}},
        # a second engine's spans (seq restarts at 0): a new run, not an
        # overwrite of the first engine's chunk 0
        {"name": "serve.dispatch", "start_s": 1.0, "duration_s": 0.001,
         "attrs": {"seq": 0, "steps": 8}},
        {"name": "serve.sync", "start_s": 1.05, "duration_s": 0.002,
         "attrs": {"seq": 0}},
    ]
    trace = tmp_path / "trace.jsonl"
    trace.write_text("".join(json.dumps(s) + "\n" for s in spans))
    script = str(
        pathlib.Path(__file__).resolve().parents[1] / "scripts" / "serve_profile.py"
    )
    out = subprocess.run(
        [sys.executable, script, "--trace", str(trace)],
        capture_output=True, text=True, timeout=60, check=True,
    ).stdout
    assert "overlap report: 2 chunks" in out and "engine run 1/2" in out
    assert "overlap report: 1 chunks" in out and "engine run 2/2" in out
    assert "stall_frac" in out
    lines = [l for l in out.splitlines() if l.strip().startswith(("0 ", "1 "))]
    assert len(lines) == 3  # chunks 0+1 of run 1, chunk 0 of run 2
    # run 1: chunk 0 barely stalled, chunk 1 fully stalled
    assert float(lines[0].split()[-1]) < 0.05
    assert float(lines[1].split()[-1]) > 0.9
    assert "overlapped)" in out

    # an empty / span-free file degrades with a pointer, not a crash
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    out2 = subprocess.run(
        [sys.executable, script, "--trace", str(empty)],
        capture_output=True, text=True, timeout=60, check=True,
    ).stdout
    assert "no paired" in out2
