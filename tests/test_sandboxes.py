"""Sandbox SDK tests against the fake two-plane backend.

The gateway fake really executes commands (bash subprocess per sandbox root),
so exec, background jobs (nohup + exit files), and windowed file reads are
pinned against real shell behavior. Retry/auth state-machine tests mirror the
reference's transport-fake approach (prime-sandboxes/tests/test_client_retry.py,
test_gateway_error_mapping.py, test_command_transport_selection.py).
"""

import pytest

from prime_tpu.core.client import APIClient, AsyncAPIClient
from prime_tpu.core.config import Config
from prime_tpu.core.exceptions import APIError
from prime_tpu.sandboxes import (
    AsyncSandboxClient,
    CreateSandboxRequest,
    EgressPolicy,
    SandboxClient,
    SandboxNotFoundError,
    SandboxOOMError,
)
from prime_tpu.sandboxes.auth import AsyncSandboxAuthCache, SandboxAuthCache
from prime_tpu.testing import FakeControlPlane


@pytest.fixture
def fake():
    fake = FakeControlPlane()
    fake.sandbox_plane.ready_after_polls = 1
    return fake


@pytest.fixture
def client(fake, tmp_path):
    cfg = Config()
    cfg.api_key = "test-key"
    api = APIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)
    c = SandboxClient(
        client=api,
        auth_cache=SandboxAuthCache(tmp_path / "auth.json"),
        gateway_transport=fake.transport,
    )
    yield c
    c.close()


def make_async_client(fake, tmp_path) -> AsyncSandboxClient:
    cfg = Config()
    cfg.api_key = "test-key"
    api = AsyncAPIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)
    return AsyncSandboxClient(
        client=api,
        auth_cache=AsyncSandboxAuthCache(tmp_path / "auth-async.json"),
        gateway_transport=fake.transport,
    )


def create_running(client, fake, **kw) -> str:
    sb = client.create(CreateSandboxRequest(**kw))
    fake.sandbox_plane.make_running(sb.sandbox_id)
    return sb.sandbox_id


# -- lifecycle ---------------------------------------------------------------


def test_create_defaults_to_tpu_image(client):
    sb = client.create(CreateSandboxRequest())
    assert sb.docker_image == "primetpu/jax-tpu:latest"
    assert sb.status == "PENDING"


def test_create_is_idempotent_with_key(client):
    a = client.create(CreateSandboxRequest(name="one"), idempotency_key="k1")
    b = client.create(CreateSandboxRequest(name="one"), idempotency_key="k1")
    assert a.sandbox_id == b.sandbox_id


def test_tpu_type_must_be_single_host():
    with pytest.raises(ValueError, match="single-host"):
        CreateSandboxRequest(tpu_type="v5e-16")
    assert CreateSandboxRequest(tpu_type="v5e-8").tpu_type == "v5e-8"


def test_wait_for_creation_polls_then_reachability(client, fake):
    fake.sandbox_plane.ready_after_polls = 3
    sb = client.create(CreateSandboxRequest())
    ready = client.wait_for_creation(sb.sandbox_id, poll_interval_s=0)
    assert ready.status == "RUNNING"


def test_wait_for_creation_oom_is_typed(client, fake):
    sb = client.create(CreateSandboxRequest())
    fake.sandbox_plane.fail_sandbox(sb.sandbox_id, reason="oom", detail="container OOM-killed")
    with pytest.raises(SandboxOOMError, match="OOM-killed"):
        client.wait_for_creation(sb.sandbox_id, poll_interval_s=0)


def test_bulk_wait_uses_list_endpoint(client, fake):
    ids = [client.create(CreateSandboxRequest()).sandbox_id for _ in range(3)]
    fake.requests.clear()
    ready = client.bulk_wait_for_creation(ids, poll_interval_s=0)
    assert [s.sandbox_id for s in ready] == ids
    gets = [p for m, p in fake.requests if m == "GET" and p == "/api/v1/sandbox"]
    per_id_gets = [p for m, p in fake.requests if m == "GET" and p.startswith("/api/v1/sandbox/")]
    assert gets and not per_id_gets  # one list call per poll, no per-id polling


def test_delete_and_bulk_delete(client, fake):
    sid = create_running(client, fake)
    client.delete(sid)
    assert fake.sandbox_plane.sandboxes[sid]["status"] == "TERMINATED"
    client.delete(sid)  # idempotent — no raise on already-deleted

    ids = [client.create(CreateSandboxRequest()).sandbox_id for _ in range(2)]
    result = client.bulk_delete(ids + ["sbx_missing"])
    assert set(result["deleted"]) == set(ids)
    assert result["missing"] == ["sbx_missing"]


def test_logs(client, fake):
    sid = create_running(client, fake)
    assert "started" in client.logs(sid)


# -- exec + transports -------------------------------------------------------


def test_execute_command_real_shell(client, fake):
    sid = create_running(client, fake)
    result = client.execute_command(sid, "echo hello-tpu; echo oops >&2; exit 3")
    assert result.stdout.strip() == "hello-tpu"
    assert result.stderr.strip() == "oops"
    assert result.exit_code == 3 and not result.ok


def test_vm_sandbox_uses_streaming_transport(client, fake):
    sid = create_running(client, fake, is_vm=True)
    result = client.execute_command(sid, "echo streamed")
    assert result.stdout.strip() == "streamed"
    assert result.ok


def test_exec_after_terminal_is_not_found(client, fake):
    sid = create_running(client, fake)
    client.execute_command(sid, "true")  # prime the auth cache
    fake.sandbox_plane.sandboxes[sid]["status"] = "TERMINATED"
    with pytest.raises(SandboxNotFoundError):
        client.execute_command(sid, "echo nope")


# -- gateway retry/auth state machine ----------------------------------------


def test_gateway_401_reauths_exactly_once(client, fake):
    sid = create_running(client, fake)
    client.execute_command(sid, "true")
    mints_before = fake.sandbox_plane.auth_mints
    fake.sandbox_plane.expire_tokens()
    result = client.execute_command(sid, "echo again")
    assert result.ok
    assert fake.sandbox_plane.auth_mints == mints_before + 1


def test_gateway_409_busy_retries(client, fake, monkeypatch):
    monkeypatch.setattr("prime_tpu.sandboxes.client.CONFLICT_BACKOFF_S", 0)
    sid = create_running(client, fake)
    fake.sandbox_plane.busy_conflicts[sid] = 2
    assert client.execute_command(sid, "echo ok").ok


def test_gateway_5xx_retries_idempotent_reads(client, fake, monkeypatch):
    monkeypatch.setattr("prime_tpu.core.client._backoff", lambda a: 0)
    sid = create_running(client, fake)
    client.write_file(sid, "/data.txt", b"abc")
    fake.sandbox_plane.gateway_faults = [503, 524]
    assert client.read_file(sid, "/data.txt") == "abc"


def test_gateway_5xx_does_not_retry_exec(client, fake, monkeypatch):
    monkeypatch.setattr("prime_tpu.core.client._backoff", lambda a: 0)
    sid = create_running(client, fake)
    fake.sandbox_plane.gateway_faults = [500]
    with pytest.raises(APIError):
        client.execute_command(sid, "echo x")
    assert fake.sandbox_plane.gateway_faults == []  # consumed exactly one fault


def test_auth_cache_reuses_token_across_clients(fake, tmp_path):
    cfg = Config()
    cfg.api_key = "test-key"
    path = tmp_path / "shared-auth.json"

    def build():
        api = APIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)
        return SandboxClient(client=api, auth_cache=SandboxAuthCache(path), gateway_transport=fake.transport)

    c1 = build()
    sb = c1.create(CreateSandboxRequest())
    fake.sandbox_plane.make_running(sb.sandbox_id)
    c1.execute_command(sb.sandbox_id, "true")
    mints = fake.sandbox_plane.auth_mints
    c2 = build()  # fresh client, same disk cache
    c2.execute_command(sb.sandbox_id, "true")
    assert fake.sandbox_plane.auth_mints == mints  # token came from disk


# -- background jobs ---------------------------------------------------------


def test_background_job_lifecycle(client, fake):
    sid = create_running(client, fake)
    job = client.start_background_job(sid, "train", "echo step1; sleep 0.2; echo done")
    assert job.running and job.pid

    finished = client.wait_for_background_job(sid, "train", timeout_s=10, poll_interval_s=0.1)
    assert not finished.running
    assert finished.exit_code == 0
    assert "done" in finished.stdout_tail


def test_background_job_failure_exit_code(client, fake):
    sid = create_running(client, fake)
    client.start_background_job(sid, "bad", "echo starting; exit 7")
    job = client.wait_for_background_job(sid, "bad", timeout_s=10, poll_interval_s=0.1)
    assert job.exit_code == 7


# -- files -------------------------------------------------------------------


def test_file_roundtrip_and_windowed_read(client, fake, tmp_path):
    sid = create_running(client, fake)
    src = tmp_path / "input.bin"
    src.write_bytes(b"0123456789")
    client.upload_file(sid, src, "/work/input.bin")

    assert client.read_file_bytes(sid, "/work/input.bin") == b"0123456789"
    assert client.read_file_bytes(sid, "/work/input.bin", offset=3, length=4) == b"3456"

    dst = tmp_path / "out.bin"
    client.download_file(sid, "/work/input.bin", dst)
    assert dst.read_bytes() == b"0123456789"

    files = client.list_files(sid, "/work")
    assert [f.path for f in files] == ["/work/input.bin"]


def test_file_upload_visible_to_exec(client, fake):
    sid = create_running(client, fake)
    client.write_file(sid, "/script.py", b"print(2 + 3)")
    result = client.execute_command(sid, "python3 script.py || python script.py")
    assert result.stdout.strip() == "5"


def test_path_traversal_blocked(client, fake):
    sid = create_running(client, fake)
    with pytest.raises(APIError):
        client.write_file(sid, "../../etc/passwd", b"x")


# -- egress + ports ----------------------------------------------------------


def test_egress_roundtrip(client, fake):
    sid = create_running(client, fake)
    policy = EgressPolicy(default_action="deny", allow_hosts=["*.googleapis.com", "pypi.org:443"])
    saved = client.set_egress(sid, policy)
    assert saved.default_action == "deny"
    assert client.get_egress(sid).allow_hosts == ["*.googleapis.com", "pypi.org:443"]


def test_egress_validator_rejects_bad_hosts():
    with pytest.raises(ValueError, match="Invalid host pattern"):
        EgressPolicy(allow_hosts=["not a host!"])


def test_ports_expose_unexpose(client, fake):
    sid = create_running(client, fake)
    port = client.expose(sid, 8888, auth_required=False)
    assert port.url.endswith(".ports.fake") and not port.auth_required
    assert [p.port for p in client.list_ports(sid)] == [8888]
    client.unexpose(sid, 8888)
    assert client.list_ports(sid) == []


# -- async mirror ------------------------------------------------------------


@pytest.mark.anyio
async def test_async_full_lifecycle(fake, tmp_path):
    client = make_async_client(fake, tmp_path)
    sb = await client.create(CreateSandboxRequest(name="async-sb"))
    fake.sandbox_plane.make_running(sb.sandbox_id)
    ready = await client.wait_for_creation(sb.sandbox_id, poll_interval_s=0)
    assert ready.status == "RUNNING"

    result = await client.execute_command(sb.sandbox_id, "echo async-hello")
    assert result.stdout.strip() == "async-hello"

    await client.write_file(sb.sandbox_id, "/a.txt", b"abc")
    assert await client.read_file(sb.sandbox_id, "/a.txt") == "abc"

    job = await client.start_background_job(sb.sandbox_id, "j1", "echo bg-done")
    assert job.running
    import anyio

    for _ in range(50):
        job = await client.get_background_job(sb.sandbox_id, "j1")
        if not job.running:
            break
        await anyio.sleep(0.1)
    assert job.exit_code == 0 and "bg-done" in job.stdout_tail

    await client.delete(sb.sandbox_id)
    await client.close()


@pytest.mark.anyio
async def test_async_vm_streaming_and_reauth(fake, tmp_path):
    client = make_async_client(fake, tmp_path)
    sb = await client.create(CreateSandboxRequest(is_vm=True))
    fake.sandbox_plane.make_running(sb.sandbox_id)
    result = await client.execute_command(sb.sandbox_id, "echo vm-stream")
    assert result.stdout.strip() == "vm-stream"

    mints = fake.sandbox_plane.auth_mints
    fake.sandbox_plane.expire_tokens()
    # VM streaming path re-auths via the shared _auth too: token refresh happens
    # on the next non-stream gateway call; for stream we expect a clean 401 error
    await client.write_file(sb.sandbox_id, "/x", b"1")
    assert fake.sandbox_plane.auth_mints == mints + 1
    await client.close()


@pytest.mark.anyio
async def test_async_auth_coalescing(fake, tmp_path):
    """N concurrent commands on a fresh sandbox mint exactly one token."""
    import anyio

    client = make_async_client(fake, tmp_path)
    sb = await client.create(CreateSandboxRequest())
    fake.sandbox_plane.make_running(sb.sandbox_id)
    mints_before = fake.sandbox_plane.auth_mints

    async with anyio.create_task_group() as tg:
        for i in range(8):
            tg.start_soon(client.execute_command, sb.sandbox_id, f"echo {i}")
    assert fake.sandbox_plane.auth_mints == mints_before + 1
    await client.close()


# -- review-finding regressions ----------------------------------------------


def test_vm_streaming_reauths_once_on_401(client, fake):
    sid = create_running(client, fake, is_vm=True)
    client.execute_command(sid, "true")
    mints = fake.sandbox_plane.auth_mints
    fake.sandbox_plane.expire_tokens()
    assert client.execute_command(sid, "echo back").stdout.strip() == "back"
    assert fake.sandbox_plane.auth_mints == mints + 1


def test_vm_streaming_409_retries(client, fake, monkeypatch):
    monkeypatch.setattr("prime_tpu.sandboxes.client.CONFLICT_BACKOFF_S", 0)
    sid = create_running(client, fake, is_vm=True)
    client.execute_command(sid, "true")
    fake.sandbox_plane.busy_conflicts[sid] = 2
    assert client.execute_command(sid, "echo ok").ok


def test_kill_background_job_reaps_process_tree(client, fake):
    sid = create_running(client, fake)
    client.start_background_job(sid, "lived", "sleep 30; echo never")
    import time as _time

    # poll-until-deadline instead of fixed sleeps: under a loaded machine the
    # job spawn / group kill can take well over the former 0.2 s (flaked in
    # the round-4 full-suite run while passing in isolation)
    deadline = _time.monotonic() + 10.0
    while _time.monotonic() < deadline:
        probe = client.execute_command(sid, "pgrep -f 'sleep [3]0' || echo absent")
        if "absent" not in probe.stdout:
            break  # the sleep is alive: the job tree has spawned
        _time.sleep(0.05)
    else:
        pytest.fail("background job never spawned its process tree")
    client.kill_background_job(sid, "lived")
    # the group kill must reap the sleep: pgrep finds nothing
    # ([3]0 so the probe's own cmdline doesn't match itself). The kill is
    # idempotent, so RE-ISSUE it each poll: under heavy machine load (three
    # concurrent suites) a single kill+10s wait still flaked — each probe's
    # round trip through the fake plane can take seconds by itself.
    deadline = _time.monotonic() + 30.0
    while _time.monotonic() < deadline:
        result = client.execute_command(sid, "pgrep -f 'sleep [3]0' || echo gone")
        if "gone" in result.stdout:
            break
        client.kill_background_job(sid, "lived")
        _time.sleep(0.05)
    else:
        pytest.fail("killed background job's process tree still alive after 30s")


def test_get_unknown_background_job_raises(client, fake):
    from prime_tpu.sandboxes.exceptions import SandboxError

    sid = create_running(client, fake)
    with pytest.raises(SandboxError, match="not found"):
        client.get_background_job(sid, "never-started")


def test_bulk_wait_walks_pages(client, fake):
    ids = [client.create(CreateSandboxRequest()).sandbox_id for _ in range(7)]
    # force tiny pages so the walk must paginate
    ready = [s.sandbox_id for s in client.list_all(page_size=3)]
    assert set(ids) <= set(ready)


@pytest.mark.anyio
async def test_async_wait_for_background_job(fake, tmp_path):
    client = make_async_client(fake, tmp_path)
    sb = await client.create(CreateSandboxRequest())
    fake.sandbox_plane.make_running(sb.sandbox_id)
    await client.start_background_job(sb.sandbox_id, "aw", "echo finished")
    job = await client.wait_for_background_job(sb.sandbox_id, "aw", timeout_s=10, poll_interval_s=0.1)
    assert job.exit_code == 0 and "finished" in job.stdout_tail
    await client.close()


@pytest.mark.parametrize("bad", ["a b", "x;rm -rf /", "../escape", "", "a" * 65, "$(id)", ".", ".."])
def test_background_job_name_validation_rejects(client, bad):
    with pytest.raises(ValueError, match="Invalid background job name"):
        client.start_background_job("sbx-any", bad, "true")


def test_background_job_name_validation_accepts_safe_charset():
    from prime_tpu.sandboxes.client import _SandboxOps

    assert _SandboxOps.validate_job_name("train-run_1.log") == "train-run_1.log"
