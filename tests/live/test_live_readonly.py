"""Tier-2 read-only live smokes: list endpoints against the real platform.

These assert SHAPE, not content — live inventory changes constantly, so a
passing run means auth, transport, pagination, and response models agree
with the deployed backend (the one thing tier 1's fakes cannot prove).
"""

from __future__ import annotations


def test_availability_lists_tpu_offers(live_client):
    from prime_tpu.api.availability import AvailabilityClient

    offers = AvailabilityClient(live_client).list_tpus()
    assert isinstance(offers, list)
    for offer in offers[:5]:
        assert offer.tpu_type
        assert offer.chips >= 1


def test_pods_list_paginates(live_client):
    from prime_tpu.api.pods import PodsClient

    pods = PodsClient(live_client).list(limit=5)
    assert isinstance(pods, list)
    for pod in pods:
        assert pod.id


def test_evals_list(live_client):
    from prime_tpu.evals import EvalsClient

    evaluations = EvalsClient(live_client).list_evaluations(limit=5)
    assert isinstance(evaluations, list)


def test_sandboxes_list(live_client):
    from prime_tpu.sandboxes.client import SandboxClient

    sandboxes = SandboxClient(live_client).list(limit=5)
    assert isinstance(sandboxes, list)
