"""Tier-2 write-path live test: full sandbox lifecycle on the real platform.

Creates a billable resource — gated behind PRIME_LIVE_WRITE=1 on top of the
tier's own opt-in. Cleanup runs in ``finally`` so a mid-test failure cannot
leak a running sandbox.
"""

from __future__ import annotations

import os

import pytest

# defined here rather than imported from conftest: conftest is not an
# importable module unless the repo root happens to be on sys.path
requires_write = pytest.mark.skipif(
    os.environ.get("PRIME_LIVE_WRITE") != "1",
    reason="write-path live test: set PRIME_LIVE_WRITE=1 to create real resources",
)


@requires_write
def test_sandbox_create_exec_delete(live_client, unique_name):
    from prime_tpu.sandboxes.client import SandboxClient
    from prime_tpu.sandboxes.models import CreateSandboxRequest

    client = SandboxClient(live_client)
    sandbox = client.create(
        CreateSandboxRequest(name=unique_name, timeout_minutes=10, labels={"tier": "live-test"})
    )
    try:
        running = client.wait_for_creation(sandbox.id)
        assert running.status.value.upper() == "RUNNING"
        result = client.execute_command(sandbox.id, "echo live-ok && uname -s")
        assert result.exit_code == 0
        assert "live-ok" in result.stdout
    finally:
        client.delete(sandbox.id)


@requires_write
def test_sandbox_background_job(live_client, unique_name):
    from prime_tpu.sandboxes.client import SandboxClient
    from prime_tpu.sandboxes.models import CreateSandboxRequest

    client = SandboxClient(live_client)
    sandbox = client.create(CreateSandboxRequest(name=unique_name, timeout_minutes=10))
    try:
        client.wait_for_creation(sandbox.id)
        client.start_background_job(sandbox.id, "smoke", "sleep 1 && echo done")
        finished = client.wait_for_background_job(sandbox.id, "smoke", timeout_s=120)
        assert not finished.running
        assert "done" in (finished.stdout_tail or "")
    finally:
        client.delete(sandbox.id)
