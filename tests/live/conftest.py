"""Tier-2 live-platform tests (SURVEY.md §4 tier 2; reference
prime-sandboxes/tests/conftest.py:13-23 role).

Everything under ``tests/live/`` talks to the REAL platform with real
credentials — no fakes, no fixtures plane. The tier is opt-in and skipped by
default so the hermetic tier-1 suite stays runnable offline:

    PRIME_LIVE_TESTS=1 PRIME_API_KEY=... python -m pytest tests/live/ -q

Write-path tests (anything that creates billable resources) additionally
require ``PRIME_LIVE_WRITE=1`` so a credentialed read-only smoke run can
never spin up pods or sandboxes by accident.

Config isolation: the client reads ``PRIME_CONFIG_DIR`` pointed at a temp
dir, so a developer's real ``~/.prime`` is never mutated by a test run.
"""

from __future__ import annotations

import os

import pytest


def _live_enabled() -> bool:
    return os.environ.get("PRIME_LIVE_TESTS") == "1" and bool(
        os.environ.get("PRIME_API_KEY")
    )


@pytest.fixture(autouse=True)
def _require_live_opt_in():
    if not _live_enabled():
        pytest.skip("tier-2 live tests: set PRIME_LIVE_TESTS=1 and PRIME_API_KEY")


@pytest.fixture()
def live_client(tmp_path, monkeypatch):
    """APIClient against the real platform, config isolated to a temp dir."""
    monkeypatch.setenv("PRIME_CONFIG_DIR", str(tmp_path / "config"))
    from prime_tpu.core.client import APIClient
    from prime_tpu.core.config import Config

    config = Config()  # PRIME_API_KEY env var wins over the (empty) temp file
    return APIClient(config)


@pytest.fixture()
def unique_name():
    import uuid

    return f"tpu-live-{uuid.uuid4().hex[:8]}"
