"""Property-based tests (hypothesis) for the pure-math invariants.

These pin the algebraic contracts that example-based tests sample only
pointwise: slice topology arithmetic, deterministic packaging, key decoding,
MoE routing conservation laws, sparkline bounds.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("hypothesis", reason="hypothesis not installed (env gap)")
from hypothesis import given, settings
from hypothesis import strategies as st

from prime_tpu.parallel.topology import list_slice_names, parse_slice

settings.register_profile("ci", max_examples=25, deadline=None)
settings.load_profile("ci")


# -- slice topology -----------------------------------------------------------


@given(st.sampled_from(["v4", "v5e", "v5p", "v6e"]))
def test_every_listed_slice_parses_consistently(generation):
    for name in list_slice_names(generation):
        spec = parse_slice(name)
        dims = [int(d) for d in spec.topology.split("x")]
        assert np.prod(dims) == spec.chips
        assert spec.chips % spec.hosts == 0
        assert spec.hosts >= 1
        assert parse_slice(spec.name).chips == spec.chips  # roundtrip


# -- packaging determinism ----------------------------------------------------


@given(
    st.dictionaries(
        st.text(alphabet="abcdefgh", min_size=1, max_size=8).map(lambda s: s + ".txt"),
        st.binary(max_size=64),
        min_size=1,
        max_size=5,
    )
)
def test_content_hash_is_order_independent_and_exclusion_stable(tmp_path_factory, files):
    from prime_tpu.envhub.packaging import build_archive, content_hash

    base = tmp_path_factory.mktemp("env")
    for name, data in files.items():
        (base / name).write_bytes(data)
    digest_one = content_hash(base)
    # excluded junk must not affect the hash or the archive
    (base / "__pycache__").mkdir(exist_ok=True)
    (base / "__pycache__" / "x.pyc").write_bytes(b"junk")
    (base / "ignored.pyc").write_bytes(b"junk")
    assert content_hash(base) == digest_one
    assert build_archive(base) == build_archive(base)  # byte-identical archives


def test_build_archive_is_time_independent(tmp_path, monkeypatch):
    """Regression: tarfile's w:gz stamps time.time() into the gzip header, so
    builds straddling a second boundary differed byte-for-byte. The archive
    must be identical no matter when it is built."""
    import time

    from prime_tpu.envhub.packaging import build_archive

    (tmp_path / "a.txt").write_bytes(b"stable")
    real_time = time.time
    first = build_archive(tmp_path)
    monkeypatch.setattr(time, "time", lambda: real_time() + 3600.0)
    second = build_archive(tmp_path)
    assert first == second


# -- TUI key decoding ---------------------------------------------------------


@given(st.lists(st.sampled_from(["j", "k", "q", "\r", "\t", "\x1b[A", "\x1b[B"]), max_size=12))
def test_decode_keys_concatenation_is_associative(parts):
    from prime_tpu.lab.tui.keys import decode_keys

    joined = decode_keys("".join(parts).encode())
    split = [key for part in parts for key in decode_keys(part.encode())]
    assert joined == split


# -- MoE routing conservation laws --------------------------------------------


@given(st.integers(0, 2**31 - 1), st.sampled_from([1, 2]))
def test_routing_conservation(seed, k):
    from prime_tpu.ops.moe import top_k_routing

    logits = jax.random.normal(jax.random.PRNGKey(seed), (24, 4), dtype=jnp.float32)
    capacity = 8
    dispatch, combine, aux = top_k_routing(logits, k=k, capacity=capacity)
    d = np.asarray(dispatch)
    c = np.asarray(combine)
    # each token dispatched to at most k (expert, slot) pairs
    assert (d.sum(axis=(1, 2)) <= k + 1e-6).all()
    # each (expert, slot) pair serves at most one token
    assert (d.sum(axis=0) <= 1 + 1e-6).all()
    # combine weight only where dispatched, total mass <= 1 per token
    assert (c[d == 0] == 0).all()
    assert (c.sum(axis=(1, 2)) <= 1 + 1e-5).all()
    assert np.isfinite(float(aux))


# -- sparkline ----------------------------------------------------------------


@given(
    st.lists(st.floats(-1e6, 1e6, allow_nan=False), min_size=0, max_size=300),
    st.integers(1, 64),
)
def test_sparkline_bounds(values, width):
    from prime_tpu.lab.tui.charts import BLOCKS, sparkline

    line = sparkline(values, width=width)
    assert len(line) <= max(width, len(values) if len(values) <= width else width)
    assert all(ch in BLOCKS for ch in line)


# -- gitignore escaping -------------------------------------------------------


@given(st.text(alphabet="ab*?[]!#x.", min_size=1, max_size=12))
def test_escaped_gitignore_patterns_match_literally(name):
    import fnmatch

    from prime_tpu.lab.hygiene import _escape_gitignore

    escaped = _escape_gitignore(name)
    # the escaped pattern, with escapes stripped the way git reads them,
    # must match exactly the literal name via fnmatch-style semantics
    assert escaped.replace("\\\\", "\0").replace("\\", "").replace("\0", "\\") == name


@settings(max_examples=40, deadline=None)
@given(
    seed=st.integers(0, 2**31 - 1),
    heads=st.sampled_from([1, 2, 4]),
    keys=st.integers(1, 9),
)
def test_sink_softmax_equals_concat_softmax(seed, heads, keys):
    """_sink_softmax(scores, sink) must equal softmax over [scores, sink]
    with the sink column dropped (the HF GPT-OSS formulation) for any
    scores, including extreme magnitudes."""
    from prime_tpu.ops.attention import _sink_softmax

    rng = np.random.default_rng(seed)
    scores = jnp.asarray(
        rng.normal(scale=rng.choice([1.0, 30.0, 300.0]), size=(1, heads, 2, keys)),
        dtype=jnp.float32,
    )
    sinks = jnp.asarray(rng.normal(size=(heads,)), dtype=jnp.float32)
    got = _sink_softmax(scores, sinks.reshape(1, heads, 1, 1))
    padded = jnp.concatenate(
        [scores, jnp.broadcast_to(sinks.reshape(1, heads, 1, 1), (1, heads, 2, 1))],
        axis=-1,
    )
    want = jax.nn.softmax(padded, axis=-1)[..., :-1]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)


@settings(max_examples=100, deadline=None)
@given(
    window=st.integers(1, 10_000),
    s_local=st.sampled_from([1, 8, 64, 256, 1024]),
    axis_size=st.sampled_from([2, 4, 8, 16]),
)
def test_ring_hops_is_sufficient_and_tight(window, s_local, axis_size):
    """The hop cap must be SUFFICIENT (every position a query can see lies
    within `hops` shards upstream) and TIGHT (one fewer hop would miss a
    visible position, unless capped at the full rotation)."""
    from prime_tpu.parallel.ring_attention import ring_hops

    hops = ring_hops(window, s_local, axis_size)
    assert 0 <= hops <= axis_size - 1
    # sufficiency: the earliest query on any shard (local offset 0) sees
    # back window-1 positions; those must fit within hops upstream shards
    if hops < axis_size - 1:
        assert window - 1 <= hops * s_local
        # tightness: hops-1 shards would NOT cover the band
        if hops > 0:
            assert window - 1 > (hops - 1) * s_local


@given(st.integers(min_value=1, max_value=256))
def test_power_batches_decomposition(n):
    """_power_batches covers n exactly with descending powers of two — the
    invariant that bounds the engine's batched-admission compile set."""
    from prime_tpu.serve.engine import _power_batches

    parts = _power_batches(n)
    assert sum(parts) == n
    assert all(p & (p - 1) == 0 for p in parts)  # powers of two
    assert parts == sorted(parts, reverse=True)
    assert len(set(parts)) == len(parts)  # binary decomposition: no repeats


@given(
    st.integers(min_value=1, max_value=600),
    st.integers(min_value=1, max_value=600),
)
@settings(deadline=None)
def test_cold_chunk_plans_equal_iff_groupable(len_a, len_b):
    """Two cold prompts batch together exactly when their (row capacity,
    plan) keys match — and matching plans guarantee both prompts' last
    token lands inside the final chunk (what the batched rels gather
    assumes)."""
    from prime_tpu.serve.engine import chunk_plan, row_capacity_for

    capacity, max_chunk = 1024, 128
    rows = [row_capacity_for(n, max_chunk, capacity) for n in (len_a, len_b)]
    plans = [
        chunk_plan(0, n, max_chunk, r) for n, r in zip((len_a, len_b), rows)
    ]
    if (rows[0], plans[0]) == (rows[1], plans[1]):
        off, size = plans[0][-1]
        for n in (len_a, len_b):
            assert off < n <= off + size
