"""Lazy eval-record access (lab/evalrecords.py) and transcript markdown
rendering (lab/tui/markdown.py)."""

import json

from prime_tpu.lab.evalrecords import IndexedJsonl, run_overview
from prime_tpu.lab.tui.markdown import latex_to_text, markdown_lines, replace_math


def _write_results(path, n=20):
    with open(path, "w") as f:
        for i in range(n):
            f.write(
                json.dumps(
                    {
                        "prompt": f"q{i}",
                        "completion": f"a{i}",
                        "reward": i / max(n - 1, 1),
                        "correct": i % 2 == 0,
                        "format_reward": 0.5,
                        "turns": i % 3,
                    }
                )
                + "\n"
            )
    return path


# -- IndexedJsonl --------------------------------------------------------------


def test_indexed_jsonl_random_access(tmp_path):
    path = _write_results(tmp_path / "results.jsonl", n=50)
    records = IndexedJsonl(path, cache_rows=4)
    assert records[17]["prompt"] == "q17"
    assert records[0]["prompt"] == "q0"
    assert records[49]["completion"] == "a49"
    assert len(records) == 50
    # out of range is empty, not an exception
    assert records[99] == {}
    assert records[-1] == {}


def test_indexed_jsonl_cache_is_bounded(tmp_path):
    path = _write_results(tmp_path / "results.jsonl", n=30)
    records = IndexedJsonl(path, cache_rows=8)
    for i in range(30):
        records.get(i)
    assert len(records._cache) == 8
    # evicted rows re-parse correctly
    assert records[0]["prompt"] == "q0"


def test_indexed_jsonl_malformed_line_is_empty_dict(tmp_path):
    path = tmp_path / "results.jsonl"
    path.write_text('{"ok": 1}\nNOT JSON\n{"ok": 3}\n')
    records = IndexedJsonl(path)
    assert len(records) == 3
    assert records[1] == {}
    assert records[2]["ok"] == 3


def test_indexed_jsonl_torn_tail_and_refresh(tmp_path):
    path = tmp_path / "results.jsonl"
    path.write_text('{"i": 0}\n{"i": 1')  # torn mid-append
    records = IndexedJsonl(path)
    assert len(records) == 1
    # writer finishes the line and appends another
    with open(path, "a") as f:
        f.write('}\n{"i": 2}\n')
    records.refresh()
    assert len(records) == 3
    assert records[1]["i"] == 1 and records[2]["i"] == 2


def test_indexed_jsonl_iter_agrees_with_len_after_append(tmp_path):
    """Appended rows are invisible to BOTH iteration and get() until
    refresh() — a filtered view must never see rows get() refuses to serve."""
    path = _write_results(tmp_path / "results.jsonl", n=4)
    records = IndexedJsonl(path)
    assert len(records) == 4  # freezes the index at EOF
    with open(path, "a") as f:
        f.write(json.dumps({"prompt": "late", "correct": True}) + "\n")
    assert len(list(records)) == 4
    assert records[4] == {}
    records.refresh()
    assert len(records) == 5
    assert len(list(records)) == 5 and records[4]["prompt"] == "late"


def test_indexed_jsonl_missing_file(tmp_path):
    records = IndexedJsonl(tmp_path / "absent.jsonl")
    assert len(records) == 0
    assert records[0] == {}
    assert list(records) == []


# -- run_overview --------------------------------------------------------------


def test_run_overview_aggregates(tmp_path):
    path = _write_results(tmp_path / "results.jsonl", n=20)
    ov = run_overview(path)
    assert ov.n_samples == 20
    assert ov.pass_rate == 0.5
    assert abs(ov.mean_reward - 0.5) < 1e-9
    by_name = {m.name: m for m in ov.metrics}
    # custom numeric fields become metrics; bookkeeping fields do not
    assert by_name["format_reward"].mean == 0.5
    assert by_name["turns"].maximum == 2
    assert "prompt" not in by_name and "reward" not in by_name
    hist = ov.reward_histogram(bins=10)
    assert sum(hist) == 20 and len(hist) == 10


def test_run_overview_empty(tmp_path):
    path = tmp_path / "results.jsonl"
    path.write_text("")
    ov = run_overview(path)
    assert ov.n_samples == 0
    assert ov.pass_rate is None and ov.mean_reward is None
    assert ov.reward_histogram() == []


def test_run_overview_constant_rewards_single_bin(tmp_path):
    path = tmp_path / "results.jsonl"
    with open(path, "w") as f:
        for _ in range(5):
            f.write(json.dumps({"reward": 1.0}) + "\n")
    ov = run_overview(path)
    hist = ov.reward_histogram(bins=4)
    assert hist == [5, 0, 0, 0]


# -- latex / markdown ----------------------------------------------------------


def test_latex_fraction_sqrt_and_symbols():
    assert latex_to_text(r"\frac{1}{2}") == "(1)/(2)"
    assert latex_to_text(r"\sqrt{x+1}") == "√(x+1)"
    assert latex_to_text(r"a \times b \le c") == "a × b ≤ c"
    assert latex_to_text(r"\frac{\sqrt{2}}{2}") == "(√(2))/(2)"


def test_latex_super_subscripts():
    assert latex_to_text("x^2") == "x²"
    assert latex_to_text("x^{10}") == "x¹⁰"
    assert latex_to_text("a_1") == "a₁"
    # non-translatable exponent degrades to ^(...) form
    assert latex_to_text("x^{y+z}") == "x^(y+z)"


def test_latex_text_and_boxed_and_unknown():
    assert latex_to_text(r"\text{speed} = 5") == "speed = 5"
    assert latex_to_text(r"\boxed{42}") == "[42]"
    # unknown command degrades to its name, never an error
    assert latex_to_text(r"\weirdcmd{x}") == "weirdcmd{x}".replace("{", "").replace("}", "")


def test_replace_math_spans():
    out = replace_math(r"the answer is $\frac{3}{4}$ of the total")
    assert out == "the answer is (3)/(4) of the total"
    out = replace_math("total: \\[ x^2 + 1 \\]")
    assert "x² + 1" in out
    # dollars inside distinct lines don't pair across lines
    assert replace_math("costs $5 now") == "costs $5 now"


def test_markdown_lines_structure():
    text = "# Title\n\nsome **bold** and `code`\n- item one\n```python\nx = 1\n```\n> quoted"
    lines = markdown_lines(text)
    styles = dict(lines)
    assert ("bold magenta", "Title") in lines
    assert ("", "some bold and code") in lines
    assert ("", "• item one") in lines
    assert ("cyan", "│ x = 1") in lines
    assert ("dim italic", "quoted") in lines
    assert styles  # noqa: the dict form just proves uniqueness isn't required


def test_markdown_lines_math_inside_prose():
    lines = markdown_lines(r"Compute $\frac{a}{b}$ here")
    assert ("", "Compute (a)/(b) here") in lines
