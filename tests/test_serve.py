"""Local OpenAI-compatible serving: wire contract + InferenceClient interop."""

import json

import httpx
import pytest

from prime_tpu.serve import InferenceServer
from prime_tpu.serve.server import render_chat_prompt


class EchoGenerator:
    """Deterministic fake: replies with the last user message, uppercased."""

    def __init__(self, fail: bool = False):
        self.fail = fail
        self.calls: list[tuple] = []

    def generate(self, prompts, max_new_tokens, temperature, top_p=1.0):
        if self.fail:
            raise RuntimeError("chip on fire")
        self.calls.append((prompts, max_new_tokens, temperature, top_p))
        return [p.splitlines()[-2].split(":", 1)[1].strip().upper() for p in prompts]


@pytest.fixture
def server():
    with InferenceServer("tiny-test", EchoGenerator(), port=0) as srv:
        yield srv


def test_metrics_endpoint(server):
    data = httpx.get(f"{server.url}/metrics").json()
    assert data["model"] == "tiny-test"
    assert data["loaded"] is True
    assert "engine" not in data  # EchoGenerator has no stats()


def test_metrics_forwards_engine_stats():
    class StatsGenerator(EchoGenerator):
        def stats(self):
            return {"tokens_emitted": 42, "requests_completed": 3}

    with InferenceServer("tiny-test", StatsGenerator(), port=0) as srv:
        data = httpx.get(f"{srv.url}/metrics").json()
    assert data["engine"] == {"tokens_emitted": 42, "requests_completed": 3}


def test_models_endpoints(server):
    data = httpx.get(f"{server.url}/v1/models").json()
    assert data["data"][0]["id"] == "tiny-test"
    one = httpx.get(f"{server.url}/v1/models/tiny-test").json()
    assert one["id"] == "tiny-test"
    assert httpx.get(f"{server.url}/nope").status_code == 404


def test_chat_completion(server):
    response = httpx.post(
        f"{server.url}/v1/chat/completions",
        json={
            "model": "tiny-test",
            "messages": [{"role": "user", "content": "hello tpu"}],
            "max_tokens": 32,
            "temperature": 0.5,
        },
        timeout=30,
    )
    assert response.status_code == 200
    body = response.json()
    assert body["choices"][0]["message"]["content"] == "HELLO TPU"
    assert body["object"] == "chat.completion"
    assert body["usage"]["completion_tokens"] >= 1


def test_chat_streaming(server):
    with httpx.stream(
        "POST",
        f"{server.url}/v1/chat/completions",
        json={"messages": [{"role": "user", "content": "stream me please"}], "stream": True},
        timeout=30,
    ) as response:
        assert response.status_code == 200
        chunks, done = [], False
        for line in response.iter_lines():
            if not line.startswith("data:"):
                continue
            data = line[5:].strip()
            if data == "[DONE]":
                done = True
                break
            chunks.append(json.loads(data))
    text = "".join(c["choices"][0]["delta"].get("content", "") for c in chunks)
    assert text == "STREAM ME PLEASE" and done


def test_chat_errors(server):
    bad = httpx.post(f"{server.url}/v1/chat/completions", content=b"not json")
    assert bad.status_code == 400
    empty = httpx.post(f"{server.url}/v1/chat/completions", json={"messages": []})
    assert empty.status_code == 400
    wrong_model = httpx.post(
        f"{server.url}/v1/chat/completions",
        json={"model": "other", "messages": [{"role": "user", "content": "x"}]},
    )
    assert wrong_model.status_code == 404


def test_generation_failure_is_500_and_server_survives():
    with InferenceServer("tiny-test", EchoGenerator(fail=True), port=0) as srv:
        response = httpx.post(
            f"{srv.url}/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "x"}]},
        )
        assert response.status_code == 500
        assert "chip on fire" in response.json()["error"]["message"]
        # still serving
        assert httpx.get(f"{srv.url}/v1/models").status_code == 200


def test_inference_client_interop(server, monkeypatch, tmp_path):
    """The framework's own InferenceClient drives the local server unchanged."""
    monkeypatch.setenv("PRIME_CONFIG_DIR", str(tmp_path))
    monkeypatch.setenv("PRIME_API_KEY", "local")
    monkeypatch.setenv("PRIME_INFERENCE_URL", f"{server.url}/v1")

    from prime_tpu.api.inference import InferenceClient
    from prime_tpu.core.config import Config

    client = InferenceClient(config=Config())
    assert client.list_models()[0]["id"] == "tiny-test"
    reply = client.chat_completion(
        "tiny-test", [{"role": "user", "content": "round trip"}], max_tokens=16
    )
    assert reply["choices"][0]["message"]["content"] == "ROUND TRIP"
    chunks = list(
        client.chat_completion_stream("tiny-test", [{"role": "user", "content": "sse too"}])
    )
    text = "".join(c["choices"][0]["delta"].get("content", "") for c in chunks)
    assert text == "SSE TOO"


def test_serve_real_tiny_model_end_to_end(tmp_path, monkeypatch):
    """Full path: serve_model('tiny-test') -> HTTP chat -> decoded text."""
    from prime_tpu.serve import serve_model

    server = serve_model("tiny-test", port=0)
    with server:
        response = httpx.post(
            f"{server.url}/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "2+2="}], "max_tokens": 4},
            timeout=120,
        )
        assert response.status_code == 200
        body = response.json()
        assert isinstance(body["choices"][0]["message"]["content"], str)


def test_render_chat_prompt():
    prompt = render_chat_prompt(
        [{"role": "system", "content": "be brief"}, {"role": "user", "content": "hi"}]
    )
    assert prompt == "system: be brief\nuser: hi\nassistant:"


def test_malformed_requests_get_responses_not_resets(server):
    list_body = httpx.post(f"{server.url}/v1/chat/completions", json=[1, 2])
    assert list_body.status_code == 400
    bad_temp = httpx.post(
        f"{server.url}/v1/chat/completions",
        json={"messages": [{"role": "user", "content": "x"}], "temperature": "hot"},
    )
    assert bad_temp.status_code == 400
    bad_message = httpx.post(
        f"{server.url}/v1/chat/completions", json={"messages": ["just a string"]}
    )
    assert bad_message.status_code == 400


def test_usage_has_total_tokens(server):
    body = httpx.post(
        f"{server.url}/v1/chat/completions",
        json={"messages": [{"role": "user", "content": "count me"}]},
        timeout=30,
    ).json()
    usage = body["usage"]
    assert usage["total_tokens"] == usage["prompt_tokens"] + usage["completion_tokens"]


def test_unloaded_server_returns_503():
    with InferenceServer("tiny-test", port=0) as srv:  # no generator yet
        response = httpx.post(
            f"{srv.url}/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "x"}]},
        )
        assert response.status_code == 503


def test_max_tokens_validation(server):
    zero = httpx.post(
        f"{server.url}/v1/chat/completions",
        json={"messages": [{"role": "user", "content": "x"}], "max_tokens": 0},
    )
    assert zero.status_code == 400
    negative = httpx.post(
        f"{server.url}/v1/chat/completions",
        json={"messages": [{"role": "user", "content": "x"}], "max_tokens": -5},
    )
    assert negative.status_code == 400


def test_serve_model_closes_socket_on_load_failure():
    from prime_tpu.serve import serve_model

    with pytest.raises(ValueError):
        serve_model("definitely-not-a-model", port=8991)
    # the port must be reusable immediately in this same process
    with InferenceServer("tiny-test", EchoGenerator(), port=8991) as srv:
        assert httpx.get(f"{srv.url}/v1/models").status_code == 200


def test_top_p_validation_and_passthrough(server):
    bad = httpx.post(
        f"{server.url}/v1/chat/completions",
        json={"messages": [{"role": "user", "content": "x"}], "top_p": 1.5},
    )
    assert bad.status_code == 400
    ok = httpx.post(
        f"{server.url}/v1/chat/completions",
        json={"messages": [{"role": "user", "content": "hello there"}],
              "temperature": 0.7, "top_p": 0.9},
        timeout=30,
    )
    assert ok.status_code == 200
    assert server.generator.calls[-1][3] == 0.9  # top_p reached the generator


def test_chat_template_preferred_over_generic():
    """A generator exposing a tokenizer with render_chat gets model-faithful
    formatting; returning None falls back to the generic template."""
    prompts_seen = []

    class TemplatedTokenizer:
        def render_chat(self, messages):
            return "<|chat|>" + messages[-1]["content"] + "<|assistant|>"

    templated_flags = []

    class Gen:
        tokenizer = TemplatedTokenizer()

        def generate(self, prompts, max_new_tokens, temperature, top_p=1.0, templated=False):
            prompts_seen.extend(prompts)
            templated_flags.append(templated)
            return ["ok"] * len(prompts)

    with InferenceServer("tiny-test", Gen(), port=0) as srv:
        r = httpx.post(
            f"{srv.url}/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "hi"}]},
            timeout=30,
        )
        assert r.status_code == 200
    assert prompts_seen == ["<|chat|>hi<|assistant|>"]
    # templated prompts carry their own BOS/headers: the generator must be
    # told not to add special tokens again (the double-BOS regression)
    assert templated_flags == [True]

    class OldSignatureGen:
        """A provider written before the templated kwarg existed."""

        tokenizer = TemplatedTokenizer()

        def generate(self, prompts, max_new_tokens, temperature, top_p=1.0):
            prompts_seen.extend(prompts)
            return ["ok"] * len(prompts)

    prompts_seen.clear()
    with InferenceServer("tiny-test", OldSignatureGen(), port=0) as srv:
        r = httpx.post(
            f"{srv.url}/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "hi"}]},
            timeout=30,
        )
        assert r.status_code == 200  # no TypeError 500: kwarg withheld
    assert prompts_seen == ["<|chat|>hi<|assistant|>"]

    class NoneTokenizer:
        def render_chat(self, messages):
            return None

    class Gen2(Gen):
        tokenizer = NoneTokenizer()

    prompts_seen.clear()
    with InferenceServer("tiny-test", Gen2(), port=0) as srv:
        httpx.post(
            f"{srv.url}/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "hi"}]},
            timeout=30,
        )
    assert prompts_seen == ["user: hi\nassistant:"]


def test_healthz_states_gate_traffic():
    """/healthz answers 503 while loading or draining and 200 when ready,
    with the queue/slot fields routers balance on (fleet satellite)."""
    with InferenceServer("tiny-test", port=0) as loading:  # no generator yet
        response = httpx.get(f"{loading.url}/healthz")
        assert response.status_code == 503
        assert response.json()["state"] == "loading"
        # liveness stays 200 through unready states (k8s livenessProbe moved
        # to /livez when /healthz became a readiness gate)
        assert httpx.get(f"{loading.url}/livez").status_code == 200

    class StatsGenerator(EchoGenerator):
        def stats(self):
            return {"queue_depth": 3, "active_slots": 2, "max_slots": 8}

    with InferenceServer("tiny-test", StatsGenerator(), port=0) as srv:
        response = httpx.get(f"{srv.url}/healthz")
        assert response.status_code == 200
        body = response.json()
        assert body["state"] == "ready"
        assert (body["queue_depth"], body["active_slots"], body["max_slots"]) == (3, 2, 8)
        # a backend with no prefix cache must not advertise one: the field
        # is absent so the fleet balancer never cache-routes toward a
        # replica that would serve every "hit" with a full recompute
        assert "prefix_digest" not in body

        # POST /admin/drain flips the state; in-flight finish, new work 503s
        drained = httpx.post(f"{srv.url}/admin/drain")
        assert drained.status_code == 200
        assert drained.json()["state"] == "draining"
        assert httpx.get(f"{srv.url}/healthz").status_code == 503
        assert httpx.get(f"{srv.url}/livez").status_code == 200
        # this backend reports queued work (queue_depth 3): not drained yet
        assert httpx.get(f"{srv.url}/healthz").json()["drained"] is False

    with InferenceServer("tiny-test", EchoGenerator(), port=0) as idle:
        httpx.post(f"{idle.url}/admin/drain")
        # no stats, no in-flight chats: the server's own counter says done
        assert httpx.get(f"{idle.url}/healthz").json()["drained"] is True
        refused = httpx.post(
            f"{idle.url}/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "x"}]},
        )
        assert refused.status_code == 503
        assert refused.json()["error"]["type"] == "draining"


def test_admin_drain_token_gate():
    """Drain is irreversible: with an admin token configured, an anonymous
    POST /admin/drain must be refused."""
    with InferenceServer("tiny-test", EchoGenerator(), port=0, admin_token="t0k") as srv:
        assert httpx.post(f"{srv.url}/admin/drain").status_code == 403
        assert httpx.get(f"{srv.url}/healthz").status_code == 200  # NOT drained
        ok = httpx.post(
            f"{srv.url}/admin/drain", headers={"Authorization": "Bearer t0k"}
        )
        assert ok.status_code == 200
        assert httpx.get(f"{srv.url}/healthz").status_code == 503


def test_drain_during_loading_reaches_late_generator():
    """A drain landing in the checkpoint-loading window must forward to the
    generator assigned afterwards, or `drained` could never flip true."""
    drain_calls = []

    class DrainableGen(EchoGenerator):
        drained = True

        def drain(self):
            drain_calls.append(True)

    srv = InferenceServer("tiny-test", port=0).start()  # still "loading"
    try:
        assert httpx.post(f"{srv.url}/admin/drain").status_code == 200
        srv.generator = DrainableGen()  # serve_model's late assignment
        assert drain_calls  # the pending drain was forwarded
        body = httpx.get(f"{srv.url}/healthz").json()
        assert body["state"] == "draining" and body["drained"] is True
    finally:
        srv.stop()


def test_queue_full_maps_to_429_with_retry_after():
    """A backend raising the typed QueueFullError surfaces as 429 with a
    Retry-After header (the admission-control contract clients and the
    fleet router both build on)."""
    from prime_tpu.serve.errors import QueueFullError

    class FullGenerator(EchoGenerator):
        def generate(self, prompts, max_new_tokens, temperature, top_p=1.0):
            raise QueueFullError("pending queue is full (4/4)", retry_after=1.5)

    with InferenceServer("tiny-test", FullGenerator(), port=0) as srv:
        response = httpx.post(
            f"{srv.url}/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "x"}]},
        )
        assert response.status_code == 429
        # header is RFC 9110 integer delta-seconds (ceil); body keeps the float
        assert response.headers["Retry-After"] == "2"
        body = response.json()["error"]
        assert body["type"] == "overloaded" and body["retry_after"] == 1.5
        # still serving
        assert httpx.get(f"{srv.url}/v1/models").status_code == 200


def test_serve_with_lora_adapter(tmp_path):
    """serve_model --adapter really merges: a nonzero-B adapter must change
    the greedy completion vs the unadapted base server."""
    import httpx
    import jax
    import jax.numpy as jnp

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.serve import serve_model
    from prime_tpu.train.lora import LoraConfig, init_lora_params, save_adapters

    cfg = get_config("tiny-test")
    lora = LoraConfig(r=4, alpha=64)
    adapters = init_lora_params(jax.random.PRNGKey(1), cfg, lora)
    # zero-effect init would make this test pass even with the plumbing cut
    adapters["layers"]["wq"]["b"] = jax.random.normal(
        jax.random.PRNGKey(2), adapters["layers"]["wq"]["b"].shape, jnp.float32
    )
    base = init_params(jax.random.PRNGKey(0), cfg)  # serve's own init seed/dtype
    path = save_adapters(tmp_path / "art", adapters, lora, cfg, base_params=base)

    body = {
        "messages": [{"role": "user", "content": "hello there"}],
        "max_tokens": 8,
        "temperature": 0.0,
    }

    def completion(**kw):
        server = serve_model("tiny-test", port=0, **kw)
        with server:
            r = httpx.post(server.url + "/v1/chat/completions", json=body, timeout=240)
            assert r.status_code == 200, r.text
            return r.json()["choices"][0]["message"]["content"]

    assert completion(adapter=str(path)) != completion()
