"""Loadgen determinism, SLO report sourcing, replay, and perf-delta tests.

The load-bearing property (ISSUE 9 / ROADMAP Open item 5): the same seed
must produce a byte-identical request schedule — prompts, tenants, arrival
offsets, cancel points — and the SLO report must derive every number from
registry snapshots / flight-recorder data, never from client stopwatches.
"""

import json
import os
import sys

import pytest

from prime_tpu.loadgen import (
    SCENARIOS,
    EngineTarget,
    Phase,
    Scenario,
    build_report,
    build_schedule,
    run_schedule,
    scenario_row,
    schedule_digest,
    schedule_from_flight,
    schedule_from_prompts,
    schedule_from_trace,
)
from prime_tpu.loadgen.perf_delta import delta_table, load_rounds
from prime_tpu.obs.metrics import Registry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# ---- schedule determinism ----------------------------------------------------


@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_same_seed_byte_identical_schedule(name):
    a = build_schedule(SCENARIOS[name](seed=42))
    b = build_schedule(SCENARIOS[name](seed=42))
    assert a == b  # full dataclass equality: prompts, tenants, arrivals, cancels
    assert schedule_digest(a) == schedule_digest(b)


@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_different_seed_different_schedule(name):
    a = build_schedule(SCENARIOS[name](seed=1))
    b = build_schedule(SCENARIOS[name](seed=2))
    assert schedule_digest(a) != schedule_digest(b)


def test_schedule_sorted_and_indexed():
    schedule = build_schedule(SCENARIOS["smoke"](seed=0))
    arrivals = [r.arrival_s for r in schedule]
    assert arrivals == sorted(arrivals)
    assert sorted(r.index for r in schedule) == list(range(len(schedule)))


def test_shared_prefix_shared_within_tenant_only():
    scenario = Scenario(
        "t", 7,
        (Phase(kind="chat_burst", n=6, tenants=2, shared_prefix=16,
               prompt_tokens=24, max_new_tokens=4),),
    )
    schedule = build_schedule(scenario)
    by_tenant = {}
    for r in schedule:
        by_tenant.setdefault(r.tenant, []).append(r.prompt_ids[:16])
    assert len(by_tenant) == 2
    for prefixes in by_tenant.values():
        assert len({p for p in prefixes}) == 1  # identical within a tenant
    (p1,), (p2,) = ({p for p in v} for v in by_tenant.values())
    assert p1 != p2  # distinct across tenants


def test_spec_friendly_prompts_tile_a_cycle():
    """The spec_friendly scenario's tails tile one short token cycle per
    request (the repetitive shape n-gram drafts accept), deterministically
    from the seed; cycle_tokens=0 keeps the historical i.i.d. draw for
    every other kind."""
    schedule = build_schedule(SCENARIOS["spec_friendly"](seed=3))
    assert schedule, "spec_friendly produced no requests"
    phase = SCENARIOS["spec_friendly"](seed=3).phases[0]
    assert phase.cycle_tokens > 0
    for r in schedule:
        tail = r.prompt_ids[1:]  # [0] is the BOS stand-in
        cycle = tail[: phase.cycle_tokens]
        for i, tok in enumerate(tail):
            assert tok == cycle[i % len(cycle)]
    with pytest.raises(ValueError, match="cycle_tokens"):
        Phase(kind="spec_friendly", n=1, prompt_tokens=8, cycle_tokens=8)


def test_cancel_storm_pins_cancel_points():
    schedule = build_schedule(SCENARIOS["cancel_storm"](seed=5))
    cancels = [r for r in schedule if r.cancel_after_s is not None]
    assert cancels, "cancel storm produced no cancel points"
    for r in cancels:
        assert r.cancel_after_s > r.arrival_s


def test_mixed_tenants_pin_adapters():
    schedule = build_schedule(SCENARIOS["mixed_tenants"](seed=3))
    assert {r.adapter for r in schedule} == {"base", "adapter-a", "adapter-b"}


def test_vocab_is_part_of_the_determinism_key():
    scenario = SCENARIOS["chat_burst"](seed=9)
    assert schedule_digest(build_schedule(scenario, vocab=500)) != schedule_digest(
        build_schedule(scenario, vocab=600)
    )


def test_phase_validation():
    with pytest.raises(ValueError):
        Phase(kind="nope", n=1)
    with pytest.raises(ValueError):
        Phase(kind="chat_burst", n=0)
    with pytest.raises(ValueError):
        Phase(kind="chat_burst", n=1, shared_prefix=8, prompt_tokens=8)


def test_schedule_from_prompts_preserves_order_and_ids():
    prompts = [[1, 5, 9], [1, 7, 7, 7]]
    schedule = schedule_from_prompts("bench", prompts, 8)
    assert [list(r.prompt_ids) for r in schedule] == prompts
    assert all(r.arrival_s == 0.0 for r in schedule)
    assert [r.max_new_tokens for r in schedule] == [8, 8]


# ---- captured_at + report sourcing ------------------------------------------


def test_registry_snapshot_embeds_monotonic_captured_at():
    r = Registry()
    first = r.snapshot()
    second = r.snapshot()
    t1 = first["captured_at"]["series"][0]["value"]
    t2 = second["captured_at"]["series"][0]["value"]
    assert t2 >= t1
    # family-shaped: JSON-round-trips and walks like any other family
    snap = json.loads(json.dumps(first))
    assert "series" in snap["captured_at"]


def test_captured_at_name_is_reserved():
    r = Registry()
    with pytest.raises(ValueError):
        r.counter("captured_at")
    with pytest.raises(ValueError):
        r.gauge("captured_at")


def _snap(captured_at, tokens, admitted=4, hits=1, ttft_counts=None,
          stall=0.0, window=0.0, spec_accepted=0.0, spec_windows=0,
          spec_drafts=0.0):
    """Hand-built registry snapshot: the report consumes plain dicts, so the
    arithmetic is testable without clocks."""
    ttft_counts = ttft_counts or [0, 0, 0]
    snap = {
        "serve_spec_accepted_tokens": {"type": "histogram", "help": "", "series": [{
            "labels": {}, "buckets": [1.0, 4.0], "counts": [0, 0, 0],
            "sum": float(spec_accepted), "count": int(spec_windows)}]},
        "serve_spec_draft_tokens_total": {"type": "counter", "help": "", "series": [
            {"labels": {}, "value": float(spec_drafts)}]},
        "captured_at": {"type": "gauge", "help": "", "series": [
            {"labels": {}, "value": captured_at}]},
        "serve_tokens_emitted_total": {"type": "counter", "help": "", "series": [
            {"labels": {}, "value": float(tokens)}]},
        "serve_requests_admitted_total": {"type": "counter", "help": "", "series": [
            {"labels": {}, "value": float(admitted)}]},
        "serve_requests_completed_total": {"type": "counter", "help": "", "series": [
            {"labels": {}, "value": float(admitted)}]},
        "serve_requests_cancelled_total": {"type": "counter", "help": "", "series": []},
        "serve_requests_failed_total": {"type": "counter", "help": "", "series": []},
        "serve_prefix_hits_total": {"type": "counter", "help": "", "series": [
            {"labels": {}, "value": float(hits)}]},
        "serve_host_stall_seconds_total": {"type": "counter", "help": "", "series": [
            {"labels": {}, "value": stall}]},
        "serve_chunk_window_seconds_total": {"type": "counter", "help": "", "series": [
            {"labels": {}, "value": window}]},
        "serve_ttft_seconds": {"type": "histogram", "help": "", "series": [{
            "labels": {}, "buckets": [0.1, 1.0], "counts": list(ttft_counts),
            "sum": 1.0, "count": sum(ttft_counts)}]},
    }
    return snap


class _FakeResult:
    """Duck-typed RunResult for pure-arithmetic report tests."""

    def __init__(self, before, after):
        from collections import Counter

        self.scenario = "fake"
        self.seed = 0
        self.digest = "d" * 64
        self.requests = 4
        self.outcomes = Counter({"completed": 4})
        self.client_tokens = 0
        self.before = before
        self.after = after
        self.flight = {}
        self.time_scale = 1.0


def test_report_numbers_come_from_snapshot_deltas_not_client_timers():
    before = {"engine": _snap(100.0, tokens=40, admitted=0, hits=0)}
    after = {"engine": _snap(102.0, tokens=140, admitted=4, hits=2,
                             ttft_counts=[3, 1, 0], stall=0.5, window=2.0)}
    row = scenario_row(_FakeResult(before, after))
    assert row["duration_s"] == pytest.approx(2.0)
    assert row["tok_s"] == pytest.approx(50.0)  # (140-40) / (102-100)
    assert row["admitted"] == 4
    assert row["prefix_hit_ratio"] == pytest.approx(0.5)
    assert row["overlap_ratio"] == pytest.approx(0.75)  # 1 - 0.5/2.0
    # p50 of [3 <= 0.1s, 1 <= 1.0s]: rank 2 of 4 inside the first bucket
    assert row["ttft_s"]["p50"] == pytest.approx(0.1 * 2 / 3, rel=1e-4)
    assert row["ttft_s"]["p95"] > row["ttft_s"]["p50"]


def test_report_merges_multiple_engine_components():
    before = {
        "replica0.engine": _snap(10.0, tokens=0),
        "replica1.engine": _snap(20.0, tokens=10),
    }
    after = {
        "replica0.engine": _snap(12.0, tokens=60),
        "replica1.engine": _snap(22.0, tokens=50),
    }
    row = scenario_row(_FakeResult(before, after))
    # 60 + 40 tokens over the (equal) 2 s windows
    assert row["tokens"] == 100
    assert row["tok_s"] == pytest.approx(50.0)


def test_report_spec_fields_are_registry_windowed():
    """spec_accepted_tokens / spec_accept_ratio come from the accepted-
    tokens histogram's sum delta over the proposed-draft counter delta —
    windowed like every other field, None when no verify window ran."""
    before = {"engine": _snap(10.0, tokens=0, spec_accepted=12.0,
                              spec_windows=6, spec_drafts=40.0)}
    after = {"engine": _snap(12.0, tokens=80, spec_accepted=42.0,
                             spec_windows=16, spec_drafts=80.0)}
    row = scenario_row(_FakeResult(before, after))
    assert row["spec_accepted_tokens"] == 30  # 42 - 12
    assert row["spec_accept_ratio"] == pytest.approx(30.0 / 40.0)
    # spec off: the counter never moves -> ratio is None, not 0.0
    quiet = scenario_row(_FakeResult(
        {"engine": _snap(1.0, tokens=0)}, {"engine": _snap(2.0, tokens=8)}
    ))
    assert quiet["spec_accept_ratio"] is None
    assert quiet["spec_accepted_tokens"] == 0


def test_report_field_set_is_stable():
    before = {"engine": _snap(1.0, tokens=0)}
    after = {"engine": _snap(2.0, tokens=8)}
    row_a = scenario_row(_FakeResult(before, after))
    row_b = scenario_row(_FakeResult(before, after))
    assert row_a == row_b
    expected = {
        "scenario", "seed", "schedule_digest", "requests", "outcomes",
        "client_tokens", "duration_s", "tokens", "tok_s", "admitted",
        "completed", "cancelled", "failed", "overlap_ratio",
        "prefix_hit_ratio", "prefix_hit_tokens", "prefix_spills",
        "prefix_reuploads", "wasted_decode_tokens", "ttft_s", "tpot_s",
        "queue_wait_s", "rejected_429",
    }
    assert expected <= set(row_a)
    report = build_report([_FakeResult(before, after)])
    assert report["slo_schema"] == 1
    assert report["headline"]["tok_s"] == pytest.approx(8.0)


# ---- end-to-end against a tiny in-process engine ----------------------------


@pytest.fixture(scope="module")
def tiny_engine_factory():
    import jax
    import jax.numpy as jnp

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.serve.engine import ContinuousBatchingEngine

    config = get_config("tiny-test")
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)

    def make(**kw):
        kw.setdefault("max_slots", 4)
        kw.setdefault("capacity", 128)
        kw.setdefault("chunk", 4)
        kw.setdefault("prefix_cache_mb", 1)
        return config, ContinuousBatchingEngine(params, config, pad_id=0, **kw)

    return make


def test_engine_run_produces_registry_sourced_report(tiny_engine_factory):
    config, engine = tiny_engine_factory()
    scenario = SCENARIOS["smoke"](seed=11)
    schedule = build_schedule(scenario, vocab=config.vocab_size)
    result = run_schedule(
        schedule, EngineTarget(engine), scenario="smoke", seed=11, time_scale=0.2,
    )
    assert result.digest == schedule_digest(schedule)
    report = build_report([result])
    row = report["scenarios"][0]
    assert report["headline"]["tok_s"] > 0
    assert row["tokens"] > 0
    assert row["admitted"] > 0
    assert row["duration_s"] and row["duration_s"] > 0
    assert sum(result.outcomes.values()) == len(schedule)
    # flight scrape captured the run (replay seed)
    recent = result.flight["recent"]
    assert len(recent) >= row["admitted"]


def test_engine_runs_same_seed_matching_field_sets(tiny_engine_factory):
    rows = []
    for _ in range(2):
        config, engine = tiny_engine_factory()
        schedule = build_schedule(SCENARIOS["chat_burst"](seed=21), vocab=config.vocab_size)
        result = run_schedule(
            schedule, EngineTarget(engine), scenario="chat_burst", seed=21,
            time_scale=0.0,
        )
        rows.append(scenario_row(result))
    assert set(rows[0]) == set(rows[1])
    assert rows[0]["schedule_digest"] == rows[1]["schedule_digest"]
    assert rows[0]["requests"] == rows[1]["requests"]


def test_queue_full_counts_as_rejected(tiny_engine_factory):
    config, engine = tiny_engine_factory(max_queue=1, max_slots=2)
    schedule = build_schedule(
        Scenario("storm", 1, (Phase(kind="rate_storm", n=12, prompt_tokens=16,
                                    max_new_tokens=4),)),
        vocab=config.vocab_size,
    )
    result = run_schedule(schedule, EngineTarget(engine), scenario="storm",
                          time_scale=0.0)
    # every request is accounted for exactly once; the oversubscribed wave
    # must trip the bounded queue at least once
    assert sum(result.outcomes.values()) == len(schedule)
    assert result.outcomes["rejected_429"] > 0
    assert scenario_row(result)["rejected_429"] == result.outcomes["rejected_429"]


# ---- replay ------------------------------------------------------------------


def test_replay_from_flight_fixture_reproduces_count_and_order():
    payload = {
        "inflight": [],
        "recent": [
            {"id": "3", "trace_id": None, "state": "done", "outcome": "completed",
             "start_unix_s": 1000.5, "duration_s": 0.4, "events": 3,
             "last_event": "completed", "prompt_tokens": 24, "max_new_tokens": 8},
            {"id": "1", "trace_id": "a" * 32, "state": "done", "outcome": "cancelled",
             "start_unix_s": 1000.0, "duration_s": 0.2, "events": 2,
             "last_event": "cancelled", "prompt_tokens": 16, "max_new_tokens": 32},
            {"id": "2", "trace_id": None, "state": "done", "outcome": "completed",
             "start_unix_s": 1000.25, "duration_s": 0.3, "events": 3,
             "last_event": "completed", "prompt_tokens": 48, "max_new_tokens": 8},
        ],
    }
    schedule = schedule_from_flight(payload, seed=0, vocab=500)
    assert len(schedule) == 3
    # ordering and offsets follow recorded submit times, not list order
    assert [r.arrival_s for r in schedule] == [0.0, 0.25, 0.5]
    assert [len(r.prompt_ids) for r in schedule] == [16, 48, 24]
    assert [r.max_new_tokens for r in schedule] == [32, 8, 8]
    # the cancelled timeline cancels at its recorded duration
    assert schedule[0].cancel_after_s == pytest.approx(0.2)
    assert schedule[1].cancel_after_s is None
    # replay is itself deterministic
    assert schedule_digest(schedule) == schedule_digest(
        schedule_from_flight(payload, seed=0, vocab=500)
    )
    assert schedule_digest(schedule) != schedule_digest(
        schedule_from_flight(payload, seed=1, vocab=500)
    )


def test_replay_from_engine_flight_roundtrip(tiny_engine_factory):
    config, engine = tiny_engine_factory()
    schedule = build_schedule(SCENARIOS["chat_burst"](seed=31), vocab=config.vocab_size)
    result = run_schedule(schedule, EngineTarget(engine), scenario="chat_burst",
                          time_scale=0.0)
    replayed = schedule_from_flight(result.flight, vocab=config.vocab_size)
    served = result.outcomes["completed"] + result.outcomes["cancelled"]
    assert len(replayed) == served
    # prompt sizes survive the roundtrip (admission meta), arrival order is
    # the engine's recorded submit order
    assert sorted(len(r.prompt_ids) for r in replayed) == sorted(
        len(r.prompt_ids) for r in schedule
    )[: len(replayed)]
    arrivals = [r.arrival_s for r in replayed]
    assert arrivals == sorted(arrivals)
    # a replayed schedule drives the engine again, end to end
    config, engine2 = tiny_engine_factory()
    result2 = run_schedule(replayed, EngineTarget(engine2), scenario="replay",
                           time_scale=0.0)
    assert sum(result2.outcomes.values()) == len(replayed)


def test_replay_from_trace_jsonl(tmp_path):
    path = tmp_path / "trace.jsonl"
    spans = [
        {"name": "serve.request", "trace_id": "t1", "span_id": "s1",
         "parent_id": None, "start_unix_s": 50.0, "start_s": 1.0,
         "duration_s": 0.5, "attrs": {"request": 1, "outcome": "completed",
                                      "tokens": 6}},
        {"name": "serve.prefill", "trace_id": "t1", "span_id": "s2",
         "parent_id": None, "start_unix_s": 50.01, "start_s": 1.01,
         "duration_s": 0.1, "attrs": {"request": 1, "prompt_len": 20}},
        {"name": "serve.request", "trace_id": "t2", "span_id": "s3",
         "parent_id": None, "start_unix_s": 50.2, "start_s": 1.2,
         "duration_s": 0.3, "attrs": {"request": 2, "outcome": "cancelled",
                                      "tokens": 2}},
        {"name": "unrelated.span", "trace_id": "t3", "span_id": "s4",
         "parent_id": None, "start_unix_s": 49.0, "start_s": 0.5,
         "duration_s": 0.1, "attrs": {}},
    ]
    path.write_text("\n".join(json.dumps(s) for s in spans) + "\n")
    schedule = schedule_from_trace(str(path), vocab=500)
    assert len(schedule) == 2
    assert [r.arrival_s for r in schedule] == [0.0, pytest.approx(0.2)]
    assert len(schedule[0].prompt_ids) == 20
    assert schedule[0].max_new_tokens == 6
    assert schedule[1].cancel_after_s == pytest.approx(0.5)


# ---- perf delta --------------------------------------------------------------


def test_perf_delta_parses_all_committed_rounds_including_schema1():
    rounds = load_rounds(REPO_ROOT)
    assert len(rounds) >= 2
    schemas = {r.schema for r in rounds}
    assert 1 in schemas  # the five historical rounds parse as labeled legacy
    table = delta_table(rounds)
    assert "r01" in table and "(s1)" in table
    # the dead rounds are part of the trajectory, not skipped
    assert "headline tok/s" in table


def test_perf_delta_unwraps_driver_records_and_labels_schemas(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({
        "n": 1, "cmd": "python bench.py", "rc": 1, "tail": "...",
        "parsed": {"metric": "decode_tokens_per_sec", "value": 0.0,
                   "unit": "tokens/s", "error": "backend unresponsive"},
    }))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({
        "n": 2, "cmd": "python bench.py", "rc": 124, "tail": "...",
        "parsed": None,
    }))
    (tmp_path / "BENCH_r03.json").write_text(json.dumps({
        "schema": 2, "metric": "decode_tokens_per_sec (x)", "value": 123.4,
        "unit": "tokens/s",
        "loadgen": {"slo_schema": 1,
                    "headline": {"tok_s": 99.0},
                    "scenarios": [{"scenario": "serve", "tok_s": 99.0,
                                   "ttft_s": {"p50": 0.01, "p95": 0.02}}]},
    }))
    rounds = load_rounds(str(tmp_path))
    assert [r.schema for r in rounds] == [1, 1, 2]
    assert rounds[1].error and "rc=124" in rounds[1].error
    table = delta_table(rounds)
    assert "123" in table
    assert "slo:serve ttft p50 ms" in table
    assert "(∅→live)" in table  # 0.0 → measured renders as revival, not +inf%


def test_perf_delta_min_rounds_message(tmp_path):
    assert "need at least 2" in delta_table(load_rounds(str(tmp_path)))


def test_perf_delta_unnumbered_files_sort_last(tmp_path):
    # a BENCH_*.json without an r<N> must never become r01's delta baseline
    (tmp_path / "BENCH_baseline.json").write_text(json.dumps(
        {"value": 99.0, "metric": "decode_tokens_per_sec"}))
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"value": 0.0, "metric": "decode_tokens_per_sec"}))
    assert [r.label for r in load_rounds(str(tmp_path))] == [
        "r01", "BENCH_baseline"
    ]


def test_router_only_scrape_and_truncation_warn_instead_of_zero():
    result = _FakeResult({"target.router": _snap(1.0, tokens=0)},
                         {"target.router": _snap(2.0, tokens=0)})
    result.timed_out = True
    row = scenario_row(result)
    assert "no engine registries" in row["warning"]
    assert "truncated" in row["warning"]
    assert row["tok_s"] == 0.0 and row["duration_s"] is None


def test_bench_schema_version_and_opportunistic_labeling(tmp_path, monkeypatch):
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.remove(REPO_ROOT)
    assert bench.SCHEMA_VERSION == 2
    (tmp_path / "BENCH_opportunistic_r05.json").write_text(json.dumps({
        "metric": "decode_tokens_per_sec", "value": 1000.0, "unit": "tokens/s",
    }))
    monkeypatch.chdir(tmp_path)
    found = bench._latest_opportunistic_record()
    assert found is not None
    path, record = found
    assert record["schema"] == 1  # legacy records are labeled, not guessed
