"""Shared environment-capability gates for test modules.

The repo targets the jax_graft toolchain; an older JAX build in a test
container lacks part of that surface (jax.set_mesh landed after 0.4.x).
Tests exercising such APIs skip with a visible reason instead of failing, so
a red tier-1 signal means a broken change — not a thin environment.

This lives in its own module (not conftest.py) because ``import conftest``
from a test module is ambiguous with tests/live/conftest.py.
"""

import jax
import pytest

from prime_tpu.utils.compat import TOMLLIB_AVAILABLE
from prime_tpu.utils.compat import tomllib as _tomllib

requires_set_mesh = pytest.mark.skipif(
    not hasattr(jax, "set_mesh"),
    reason="jax.set_mesh unavailable in this jax build (toolchain env gap)",
)

# top-level jax.shard_map graduated from jax.experimental after 0.4.x; the
# shard_map-wrapped serving/eval paths (parallel/decode_sharded.py and
# friends) need it
requires_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="jax.shard_map unavailable in this jax build (toolchain env gap)",
)

# the varying-axis (vma) shard_map type system (jax.lax.pcast et al.) landed
# with the top-level shard_map; pre-vma builds reject or mis-propagate the
# sharding patterns written against it (pipeline aux scalars, fsdp/tp
# forward, multi-process device_put). CI runs these on the real toolchain.
requires_vma = pytest.mark.skipif(
    not hasattr(jax.lax, "pcast"),
    reason="pre-vma jax build mishandles this sharding pattern (toolchain env gap)",
)

# stdlib tomllib landed in Python 3.11; on 3.10 containers the tomli
# backport (same API) fills in when present — prime_tpu.utils.compat is the
# one owner of that resolution (product modules import through it too, so
# importing them never breaks collection). Test modules call get_tomllib()
# in the body (or decorate with requires_tomllib) so a thin environment
# skips visibly instead of failing.
tomllib = _tomllib if TOMLLIB_AVAILABLE else None

requires_tomllib = pytest.mark.skipif(
    not TOMLLIB_AVAILABLE,
    reason="no tomllib (py>=3.11) or tomli backport in this environment",
)


def _has_module(name: str) -> bool:
    import importlib.util

    return importlib.util.find_spec(name) is not None


# the login handshake (commands/login.py) encrypts the API key exchange with
# an RSA keypair; containers without the cryptography wheel can't run it
requires_cryptography = pytest.mark.skipif(
    not _has_module("cryptography"),
    reason="cryptography not installed in this environment (env gap)",
)


def get_tomllib():
    """In-test-body twin of ``requires_tomllib``: returns the tomllib (or
    tomli) module, skipping the calling test when neither exists — a drop-in
    for the bare ``import tomllib`` that broke collection on Python 3.10."""
    if tomllib is None:
        pytest.skip("no tomllib (py>=3.11) or tomli backport in this environment")
    return tomllib
