"""Shared environment-capability gates for test modules.

The repo targets the jax_graft toolchain; an older JAX build in a test
container lacks part of that surface (jax.set_mesh landed after 0.4.x).
Tests exercising such APIs skip with a visible reason instead of failing, so
a red tier-1 signal means a broken change — not a thin environment.

This lives in its own module (not conftest.py) because ``import conftest``
from a test module is ambiguous with tests/live/conftest.py.
"""

import jax
import pytest

requires_set_mesh = pytest.mark.skipif(
    not hasattr(jax, "set_mesh"),
    reason="jax.set_mesh unavailable in this jax build (toolchain env gap)",
)
