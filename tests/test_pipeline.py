"""Pipeline parallelism: GPipe schedule correctness + pipelined training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from prime_tpu.models import get_config
from prime_tpu.models.llama import forward, init_params
from prime_tpu.parallel.mesh import make_mesh
from prime_tpu.parallel.pipeline import (
    make_pipeline_train_step,
    pipeline_forward,
    shard_pipeline_params,
)

from _markers import requires_vma

CFG = get_config("tiny-test").scaled(n_layers=4)  # 4 layers over 2 or 4 stages


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)


def test_pipeline_forward_matches_dense(params):
    """Pipelined logits == the plain scan forward, for 2 and 4 stages."""
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, CFG.vocab_size)
    ref, _ = forward(params, tokens, CFG, attn_impl="xla")
    for stages, microbatches in ((2, 4), (4, 2), (2, 8)):
        mesh = make_mesh({"pp": stages}, devices=jax.devices()[:stages])
        staged = shard_pipeline_params(params, mesh, CFG)
        out = pipeline_forward(staged, tokens, CFG, mesh, n_microbatches=microbatches)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3,
            err_msg=f"pp={stages} M={microbatches}",
        )


def test_pipeline_single_stage_degenerates(params):
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 8), 0, CFG.vocab_size)
    mesh = make_mesh({"pp": 1}, devices=jax.devices()[:1])
    staged = shard_pipeline_params(params, mesh, CFG)
    out = pipeline_forward(staged, tokens, CFG, mesh, n_microbatches=2)
    ref, _ = forward(params, tokens, CFG, attn_impl="xla")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_pipeline_train_step_reduces_loss(params):
    from prime_tpu.train import default_optimizer, init_train_state

    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    optimizer = default_optimizer(learning_rate=1e-3)
    # fresh params: the jitted step donates its state, and device_put may
    # alias the module fixture's buffers when the placement already matches
    own_params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    state = init_train_state(shard_pipeline_params(own_params, mesh, CFG), optimizer)
    step = make_pipeline_train_step(CFG, optimizer, mesh, n_microbatches=2)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0, CFG.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones_like(tokens, jnp.float32)
    losses = []
    for _ in range(4):
        state, metrics = step(state, tokens, targets, mask)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_pipeline_grads_match_dense(params):
    """Backprop through ppermute: pipelined grads == dense grads."""
    from prime_tpu.train.trainer import cross_entropy_loss

    tokens = jax.random.randint(jax.random.PRNGKey(4), (4, 8), 0, CFG.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones_like(tokens, jnp.float32)

    def dense_loss(p):
        logits, _ = forward(p, tokens, CFG, attn_impl="xla")
        return cross_entropy_loss(logits, targets, mask)

    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])

    def pp_loss(p):
        return cross_entropy_loss(
            pipeline_forward(p, tokens, CFG, mesh, n_microbatches=2), targets, mask
        )

    dense_grads = jax.grad(dense_loss)(params)
    staged = shard_pipeline_params(params, mesh, CFG)
    pp_grads = jax.grad(pp_loss)(staged)
    for a, b in zip(jax.tree.leaves(dense_grads), jax.tree.leaves(pp_grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4)


@requires_vma
def test_pipeline_moe_matches_dense():
    """Sparse-MoE layers pipeline too: with capacity high enough that no
    token drops, the staged logits equal the plain scan's (per-microbatch
    routing groups see the same tokens), and the train step carries the
    bubble-masked load-balance aux."""
    cfg = get_config("tiny-moe").scaled(n_layers=4, capacity_factor=8.0)
    mparams = init_params(jax.random.PRNGKey(7), cfg, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(8), (4, 16), 0, cfg.vocab_size)
    ref, _, ref_aux = forward(mparams, tokens, cfg, attn_impl="xla", return_aux=True)
    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    staged = shard_pipeline_params(mparams, mesh, cfg)
    out, aux = pipeline_forward(staged, tokens, cfg, mesh, n_microbatches=2, return_aux=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)
    # aux is a mean over (different) routing groups — same scale, not equal
    assert 0.1 * float(ref_aux) < float(aux) < 10 * float(ref_aux)

    from prime_tpu.train import default_optimizer, init_train_state

    opt = default_optimizer(learning_rate=1e-3)
    state = init_train_state(staged, opt)
    step = make_pipeline_train_step(cfg, opt, mesh, n_microbatches=2)
    state, metrics = step(state, tokens, jnp.roll(tokens, -1, 1), jnp.ones_like(tokens, jnp.float32))
    assert np.isfinite(float(metrics["loss"]))


def test_pipeline_validates_divisibility(params):
    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    tokens = jnp.zeros((6, 8), jnp.int32)
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_forward(params, tokens, CFG, mesh, n_microbatches=4)
    bad_cfg = CFG.scaled(n_layers=3)
    with pytest.raises(ValueError, match="divide into"):
        pipeline_forward(params, jnp.zeros((4, 8), jnp.int32), bad_cfg, mesh, 2)


def test_pipeline_forward_matches_dense_gemma_style():
    """Gemma knobs (GeGLU, (1+w) norms, post-norms, scaled embed, softcaps,
    and the even sliding/global alternation whose per-layer flags must stay
    GLOBALLY indexed across stage boundaries) must produce identical logits
    through the pipeline schedule."""
    cfg = CFG.scaled(
        name="tiny-gemma-pp", act="gelu_tanh", norm_plus_one=True, post_norms=True,
        scale_embed=True, attn_softcap=50.0, final_softcap=30.0, query_scale=24,
        sliding_window=4,  # seq 16 > window 4: sliding layers genuinely differ
    )
    gparams = init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (4, 16), 0, cfg.vocab_size)
    ref, _ = forward(gparams, tokens, cfg, attn_impl="xla")
    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    staged = shard_pipeline_params(gparams, mesh, cfg)
    out = pipeline_forward(staged, tokens, cfg, mesh, n_microbatches=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_pipeline_forward_matches_dense_gemma3_style():
    """Gemma3's 5:1 schedule + dual-frequency rope (local theta selected by
    the traced flag) through the pipeline: with 4 layers and pattern '3:1',
    the global layer sits at index 3 — in the SECOND stage, so a local
    (stage-relative) flag indexing would compute it wrong."""
    cfg = CFG.scaled(
        name="tiny-g3-pp", act="gelu_tanh", norm_plus_one=True, post_norms=True,
        scale_embed=True, qk_norm=True, query_scale=24,
        sliding_window=4, sliding_pattern="3:1",
        rope_theta=1000000.0, rope_local_theta=10000.0, rope_scale=8.0,
    )
    gparams = init_params(jax.random.PRNGKey(5), cfg, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(6), (4, 16), 0, cfg.vocab_size)
    ref, _ = forward(gparams, tokens, cfg, attn_impl="xla")
    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    staged = shard_pipeline_params(gparams, mesh, cfg)
    out = pipeline_forward(staged, tokens, cfg, mesh, n_microbatches=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_pipeline_gptoss_matches_dense():
    """GPT-OSS config (sinks + biased clamped-GLU MoE + even-alternating
    sliding window + non-truncated yarn) under pipeline parallelism: the
    sinks/bias leaves shard over pp with the layer stack and the staged
    logits match the plain scan."""
    cfg = get_config("tiny-gptoss").scaled(n_layers=4, capacity_factor=8.0)
    mparams = init_params(jax.random.PRNGKey(11), cfg, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(12), (4, 16), 1, cfg.vocab_size)
    ref, _ = forward(mparams, tokens, cfg, attn_impl="xla")
    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    staged = shard_pipeline_params(mparams, mesh, cfg)
    out = pipeline_forward(staged, tokens, cfg, mesh, n_microbatches=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)
