"""Slice-topology math: the TPU-native core of the availability/pods model."""

import pytest

from prime_tpu.parallel.topology import SliceSpec, TpuGeneration, list_slice_names, parse_slice


@pytest.mark.parametrize(
    "name,chips,cores,hosts,topology",
    [
        ("v5e-1", 1, 1, 1, "1x1"),
        ("v5e-4", 4, 4, 1, "2x2"),
        ("v5e-8", 8, 8, 1, "2x4"),
        ("v5e-16", 16, 16, 2, "4x4"),
        ("v5e-64", 64, 64, 8, "8x8"),
        ("v5e-256", 256, 256, 32, "16x16"),
        ("v5p-8", 4, 8, 1, "1x2x2"),
        ("v5p-16", 8, 16, 2, "2x2x2"),
        ("v5p-128", 64, 128, 16, "4x4x4"),
        ("v4-8", 4, 8, 1, "1x2x2"),
        ("v6e-8", 8, 8, 1, "2x4"),
    ],
)
def test_slice_math(name, chips, cores, hosts, topology):
    s = parse_slice(name)
    assert (s.chips, s.cores, s.hosts, s.topology) == (chips, cores, hosts, topology)
    assert s.multi_host == (hosts > 1)


def test_derived_capacity():
    s = parse_slice("v5e-8")
    assert s.hbm_gib == 8 * 16
    assert s.bf16_tflops == pytest.approx(8 * 197.0)
    assert parse_slice("v5p-8").hbm_gib == 4 * 95


def test_ici_links_2d():
    # 2x4 unwrapped mesh: rows 2*(4-1) + cols 4*(2-1) = 6 + 4 = 10
    assert parse_slice("v5e-8").ici_link_count == 10


def test_case_and_whitespace_tolerant():
    assert parse_slice(" V5E-8 ").name == "v5e-8"


@pytest.mark.parametrize(
    "bad,fragment",
    [
        ("v5e-3", "power of two"),
        ("h100-8", "Unknown TPU generation"),
        ("v5e", "Malformed"),
        ("v5e-x", "not a number"),
        ("v5e-512", "exceeds"),
        ("v5p-2", "count cores"),
    ],
)
def test_parse_errors_are_actionable(bad, fragment):
    with pytest.raises(ValueError, match=fragment):
        parse_slice(bad)


def test_catalog_roundtrips():
    for name in list_slice_names():
        spec = parse_slice(name)
        assert isinstance(spec, SliceSpec)
        assert spec.name == name
        assert spec.to_metadata()["ici_topology"] == spec.topology


def test_generation_properties():
    assert TpuGeneration.V5E.chips_per_host == 8
    assert TpuGeneration.V5P.cores_per_chip == 2
    assert TpuGeneration.V5P.suffix_counts_cores
    assert not TpuGeneration.V6E.suffix_counts_cores
