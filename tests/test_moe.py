"""Mixture-of-experts: routing math, model integration, Mixtral parity, ep sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from prime_tpu.models import get_config
from prime_tpu.models.llama import forward, init_params
from prime_tpu.ops.moe import expert_capacity, moe_mlp, top_k_routing

from _markers import requires_set_mesh, requires_vma

CFG = get_config("tiny-moe")


# -- routing ------------------------------------------------------------------


def test_topk_routing_shapes_and_mass():
    rng = jax.random.PRNGKey(0)
    logits = jax.random.normal(rng, (32, 4), dtype=jnp.float32)
    capacity = expert_capacity(32, 4, k=2, capacity_factor=2.0)
    dispatch, combine, aux = top_k_routing(logits, k=2, capacity=capacity)
    assert dispatch.shape == (32, 4, capacity) == combine.shape
    # with generous capacity every token is dispatched to exactly k experts
    np.testing.assert_allclose(np.asarray(jnp.sum(dispatch, axis=(1, 2))), 2.0)
    # combine weights sum to 1 per token (renormalized top-k gates)
    np.testing.assert_allclose(np.asarray(jnp.sum(combine, axis=(1, 2))), 1.0, rtol=1e-5)
    # each expert slot holds at most one token
    assert float(jnp.max(jnp.sum(dispatch, axis=0))) <= 1.0 + 1e-6
    assert float(aux) > 0


def test_capacity_drops_tokens():
    # all tokens prefer expert 0; capacity forces drops, residual path holds
    logits = jnp.zeros((32, 4)).at[:, 0].set(10.0)
    dispatch, combine, _ = top_k_routing(logits, k=1, capacity=8)
    per_token = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
    assert per_token.sum() == 8  # only capacity-many served
    assert set(per_token.tolist()) == {0.0, 1.0}


def test_moe_mlp_matches_dense_expert_when_one_expert():
    """n_experts=1, k=1: MoE must reduce to the plain FFN (no routing freedom)."""
    rng = jax.random.PRNGKey(1)
    d, f = 32, 64
    x = jax.random.normal(rng, (2, 8, d), dtype=jnp.float32)
    w_gate = jax.random.normal(jax.random.PRNGKey(2), (1, d, f), jnp.float32) * 0.1
    w_up = jax.random.normal(jax.random.PRNGKey(3), (1, d, f), jnp.float32) * 0.1
    w_down = jax.random.normal(jax.random.PRNGKey(4), (1, f, d), jnp.float32) * 0.1
    router = jnp.zeros((d, 1), jnp.float32)
    y, _ = moe_mlp(x, router, w_gate, w_up, w_down, k=1, capacity_factor=4.0)
    dense = (jax.nn.silu(x @ w_gate[0]) * (x @ w_up[0])) @ w_down[0]
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense), rtol=1e-4, atol=1e-5)


# -- model integration --------------------------------------------------------


def test_moe_forward_and_decode_consistency():
    """Prefill+decode through the MoE stack == full forward (same tokens)."""
    from prime_tpu.models.llama import init_cache

    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    seq = 8
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, seq), 0, CFG.vocab_size)
    full_logits, _ = forward(params, tokens, CFG)

    prefix = 5
    cache = init_cache(CFG, 2, seq + 2, dtype=jnp.float32)
    logits, cache = forward(params, tokens[:, :prefix], CFG, cache=cache)
    np.testing.assert_allclose(
        np.asarray(full_logits[:, :prefix]), np.asarray(logits), rtol=2e-3, atol=2e-3
    )
    for i in range(prefix, seq):
        step_logits, cache = forward(
            params, tokens[:, i : i + 1], CFG,
            positions=cache.lengths[:, None], cache=cache, decode=True,
        )
        np.testing.assert_allclose(
            np.asarray(full_logits[:, i]), np.asarray(step_logits[:, 0]), rtol=2e-3, atol=2e-3
        )


def test_moe_train_step_includes_aux_and_learns():
    from prime_tpu.train import default_optimizer, init_train_state, make_train_step

    optimizer = default_optimizer(learning_rate=1e-3)
    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    state = init_train_state(params, optimizer)
    step = make_train_step(CFG, optimizer)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, CFG.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones_like(tokens, jnp.float32)
    losses = []
    for _ in range(5):
        state, metrics = step(state, tokens, targets, mask)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_moe_generate_end_to_end():
    from prime_tpu.models.sampler import generate

    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 1, CFG.vocab_size)
    lengths = jnp.asarray([6, 4], jnp.int32)
    result = generate(params, tokens, lengths, CFG, jax.random.PRNGKey(2), max_new_tokens=4)
    assert result.tokens.shape == (2, 4)


# -- Mixtral checkpoint parity ------------------------------------------------


@pytest.fixture(scope="module")
def mixtral_model():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    cfg = transformers.MixtralConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_local_experts=4,
        num_experts_per_tok=2,
        max_position_embeddings=128,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.MixtralForCausalLM(cfg)
    model.eval()
    return model


def test_mixtral_logits_match_transformers(mixtral_model):
    torch = pytest.importorskip("torch")

    from prime_tpu.models.hf_loader import config_from_hf, params_from_state_dict

    state = {k: v.float().numpy() for k, v in mixtral_model.state_dict().items()}
    config = config_from_hf(mixtral_model.config, name="tiny-mixtral")
    assert config.is_moe and config.n_experts == 4
    # generous capacity: parity requires no token drops
    config = config.scaled(capacity_factor=8.0)
    params = params_from_state_dict(state, config, dtype=jnp.float32)

    tokens = np.array([[1, 7, 42, 5, 99, 3], [2, 11, 250, 77, 8, 4]], dtype=np.int32)
    with torch.no_grad():
        ref = mixtral_model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    ours, _ = forward(params, jnp.asarray(tokens), config)
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-3, atol=2e-3)


# -- expert parallelism -------------------------------------------------------


def test_moe_sharded_train_step_with_ep_axis():
    from prime_tpu.parallel.mesh import make_mesh
    from prime_tpu.parallel.sharding import shard_batch, shard_params
    from prime_tpu.train import (
        default_optimizer,
        init_train_state,
        make_train_step,
        shard_train_state,
    )

    mesh = make_mesh({"dp": 1, "fsdp": 2, "ep": 2, "tp": 2})
    optimizer = default_optimizer(learning_rate=1e-3)
    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    state = shard_train_state(init_train_state(params, optimizer), mesh, CFG)
    step = make_train_step(CFG, optimizer)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, CFG.vocab_size)
    tokens, targets, mask = (
        shard_batch(x, mesh)
        for x in (tokens, jnp.roll(tokens, -1, 1), jnp.ones_like(tokens, jnp.float32))
    )
    state, metrics = step(state, tokens, targets, mask)
    assert np.isfinite(float(metrics["loss"]))
    # expert weights really are sharded over ep
    sharding = state.params["layers"]["w_gate"].sharding
    assert "ep" in str(sharding.spec)


@requires_set_mesh
def test_moe_sharded_generate_via_slice():
    """JaxGenerator serves an MoE model over a slice mesh, auto-carving ep."""
    from prime_tpu.evals.runner import JaxGenerator

    gen = JaxGenerator("tiny-moe", slice_name="v5e-8", tensor_parallel=2)
    assert gen.mesh.shape.get("ep", 1) == 4  # 8 devices / tp2 -> all 4 experts sharded
    outs = gen.generate(["a", "bb"], max_new_tokens=4, temperature=0.0)
    assert len(outs) == 2


def test_prune_spec_drops_missing_axes():
    from jax.sharding import PartitionSpec as P

    from prime_tpu.parallel.mesh import make_mesh
    from prime_tpu.parallel.sharding import prune_spec

    mesh = make_mesh({"dp": 1, "fsdp": 4, "tp": 2})
    assert prune_spec(P(None, "ep", "fsdp", "tp"), mesh) == P(None, None, "fsdp", "tp")
    assert prune_spec(P(("dp", "fsdp"), None), mesh) == P(("dp", "fsdp"), None)
    assert prune_spec(P(("dp", "ep"), None), mesh) == P("dp", None)


def test_moe_serving_raises_capacity_to_no_drop():
    """JaxGenerator must never drop tokens at inference (capacity >= E/k)."""
    from prime_tpu.evals.runner import JaxGenerator

    gen = JaxGenerator("tiny-moe")  # preset capacity_factor is 2.0, E/k = 2.0
    assert gen.config.capacity_factor >= gen.config.n_experts / gen.config.experts_per_token

    tight = get_config("tiny-moe").scaled(capacity_factor=0.5)
    import prime_tpu.models as models_pkg

    # simulate a preset with a tight training capacity
    from prime_tpu.models.config import MODEL_PRESETS

    MODEL_PRESETS["tiny-moe-tight"] = tight.scaled(name="tiny-moe-tight")
    try:
        gen = JaxGenerator("tiny-moe-tight")
        assert gen.config.capacity_factor == 2.0  # raised to E/k
    finally:
        MODEL_PRESETS.pop("tiny-moe-tight")


def test_mesh_for_slice_rejects_impossible_fsdp_ep():
    from prime_tpu.parallel.mesh import mesh_for_slice

    devices = jax.devices()[:8]
    with pytest.raises(ValueError, match="must divide"):
        mesh_for_slice("v5e-8", tensor_parallel=2, fsdp=2, expert_parallel=4, devices=devices)
    with pytest.raises(ValueError, match="must divide"):
        mesh_for_slice("v5e-8", tensor_parallel=2, fsdp=3, devices=devices)


def test_grouped_routing_matches_single_group():
    """Grouped dispatch must not change results when capacity is generous."""
    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    lp = jax.tree.map(lambda p: p[0], params["layers"])  # layer 0 weights
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 8, CFG.d_model), jnp.float32)
    one_group, _ = moe_mlp(
        x, lp["router"], lp["w_gate"], lp["w_up"], lp["w_down"],
        k=2, capacity_factor=4.0, group_size=4096,
    )
    grouped, _ = moe_mlp(
        x, lp["router"], lp["w_gate"], lp["w_up"], lp["w_down"],
        k=2, capacity_factor=4.0, group_size=4,  # 4 groups of 4 tokens
    )
    np.testing.assert_allclose(np.asarray(grouped), np.asarray(one_group), rtol=1e-4, atol=1e-5)


def test_grouped_routing_pads_ragged_token_count():
    """Token count not divisible by the group: padding is masked from routing."""
    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    lp = jax.tree.map(lambda p: p[0], params["layers"])
    x = jax.random.normal(jax.random.PRNGKey(6), (1, 6, CFG.d_model), jnp.float32)
    y, aux = moe_mlp(
        x, lp["router"], lp["w_gate"], lp["w_up"], lp["w_down"],
        k=2, capacity_factor=4.0, group_size=4,  # 6 tokens -> groups of 4 + pad 2
    )
    ref, _ = moe_mlp(
        x, lp["router"], lp["w_gate"], lp["w_up"], lp["w_down"],
        k=2, capacity_factor=4.0, group_size=4096,
    )
    # group boundaries change per-group capacity contention; generous capacity
    # makes them equivalent
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-4, atol=1e-5)
    assert np.isfinite(float(aux))


def test_moe_dispatch_memory_is_linear_in_tokens():
    """The routing tensors must scale O(T·g), not O(T^2)."""
    from prime_tpu.ops.moe import MOE_GROUP_SIZE, expert_capacity

    seq, e, k, cf = 32768, 8, 2, 1.25
    capacity = expert_capacity(min(MOE_GROUP_SIZE, seq), e, k, cf)
    n_groups = -(-seq // MOE_GROUP_SIZE)
    dispatch_elems = n_groups * MOE_GROUP_SIZE * e * capacity
    # 32k-token Mixtral batch: routing tensors stay under ~100M elements
    assert dispatch_elems < 1.1e8, dispatch_elems


# -- DeepSeekMoE: sigmoid scores, selection bias, shared experts --------------


def test_sigmoid_routing_bias_shifts_selection_not_gates():
    """DeepSeek-V3 routing: sigmoid scores each expert independently; the
    aux-free balance bias changes WHICH experts win but the gate values come
    from the unbiased scores; routed_scaling multiplies the combine."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from prime_tpu.ops.moe import top_k_routing

    logits = jnp.asarray(
        [[2.0, 1.0, 0.0, -1.0], [0.0, 0.5, 1.5, -0.5]], jnp.float32
    )
    dispatch, combine, _aux = top_k_routing(
        logits, k=2, capacity=2, score_func="sigmoid", norm_topk=True
    )
    probs = np.asarray(jax.nn.sigmoid(logits))
    # token 0 picks experts 0,1; gates = normalized sigmoid scores
    g0 = probs[0, [0, 1]] / probs[0, [0, 1]].sum()
    np.testing.assert_allclose(np.asarray(combine[0]).sum(-1)[[0, 1]], g0, rtol=1e-5)

    # a huge bias on expert 3 forces it into every selection...
    bias = jnp.asarray([0.0, 0.0, 0.0, 100.0], jnp.float32)
    d_b, c_b, _ = top_k_routing(
        logits, k=2, capacity=2, score_func="sigmoid", select_bias=bias, norm_topk=True
    )
    assert np.asarray(d_b).sum(-1)[:, 3].all()  # expert 3 selected for all tokens
    # ...but its gate is still the UNBIASED sigmoid score (normalized)
    tok0 = probs[0, [0, 3]] / probs[0, [0, 3]].sum()
    np.testing.assert_allclose(np.asarray(c_b[0]).sum(-1)[[0, 3]], tok0, rtol=1e-5)

    # routed scaling multiplies the combine weights
    _d, c_s, _ = top_k_routing(
        logits, k=2, capacity=2, score_func="sigmoid", norm_topk=True, routed_scale=2.5
    )
    np.testing.assert_allclose(np.asarray(c_s), np.asarray(combine) * 2.5, rtol=1e-5)


def test_tiny_deepseek_forward_shared_expert_and_generate():
    """The V3-shaped preset (MLA + sigmoid MoE + shared experts) runs end to
    end; the shared expert really contributes; the balance bias reroutes."""
    import jax
    import jax.numpy as jnp

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import forward, init_params
    from prime_tpu.models.sampler import generate

    cfg = get_config("tiny-deepseek")
    params = init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    assert sum(x.size for x in jax.tree_util.tree_leaves(params)) == cfg.param_count
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 1, cfg.vocab_size)
    logits, _ = forward(params, tokens, cfg)
    assert bool(jnp.all(jnp.isfinite(logits)))

    zeroed = dict(params)
    layers = dict(zeroed["layers"])
    layers["w_shared_down"] = jnp.zeros_like(layers["w_shared_down"])
    zeroed["layers"] = layers
    logits0, _ = forward(zeroed, tokens, cfg)
    assert float(jnp.max(jnp.abs(logits - logits0))) > 1e-3

    biased = dict(params)
    layers = dict(biased["layers"])
    layers["score_bias"] = layers["score_bias"].at[:, 0].add(100.0)
    biased["layers"] = layers
    logits_b, _ = forward(biased, tokens, cfg)
    assert float(jnp.max(jnp.abs(logits_b - logits))) > 1e-4

    out = generate(
        params, tokens, jnp.full((2,), 10, jnp.int32), cfg,
        jax.random.PRNGKey(2), max_new_tokens=4, temperature=0.0,
    )
    assert out.tokens.shape == (2, 4)


def test_tiny_deepseek_ep_sharded_train_step():
    """MLA + DeepSeekMoE over a dp/fsdp/ep/tp mesh: one train step, finite
    loss and grads (experts on ep, shared expert megatron-dense)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from jax.sharding import NamedSharding, PartitionSpec

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.parallel.mesh import make_mesh
    from prime_tpu.train import (
        default_optimizer,
        init_train_state,
        make_train_step,
        shard_train_state,
    )

    cfg = get_config("tiny-deepseek")
    mesh = make_mesh(
        {"dp": 1, "fsdp": 2, "ep": 2, "tp": 2}, devices=jax.devices()[:8]
    )
    opt = default_optimizer()
    state = shard_train_state(
        init_train_state(init_params(jax.random.PRNGKey(0), cfg, jnp.float32), opt),
        mesh, cfg,
    )
    step = make_train_step(cfg, opt)
    t = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)
    sharding = NamedSharding(mesh, PartitionSpec(("dp", "fsdp"), None))
    batch = tuple(
        jax.device_put(x, sharding)
        for x in (t, jnp.roll(t, -1, 1), jnp.ones_like(t, jnp.float32))
    )
    _state, metrics = step(state, *batch)
    assert np.isfinite(float(metrics["loss"]))


def test_negative_selection_bias_never_double_picks():
    """Regression: a balance bias driving every non-chosen score negative
    must not let the zeroed winner be argmax'd twice (exclusion is -inf)."""
    import jax.numpy as jnp
    import numpy as np

    from prime_tpu.ops.moe import top_k_routing

    logits = jnp.asarray([[1.0, -2.0, -2.5, -3.0]], jnp.float32)
    bias = jnp.asarray([0.0, -0.5, -0.6, -0.7], jnp.float32)
    dispatch, _c, _a = top_k_routing(
        logits, k=2, capacity=2, score_func="sigmoid", select_bias=bias
    )
    per_expert = np.asarray(dispatch).sum(-1)[0]  # how often each expert chosen
    assert per_expert.max() <= 1.0, per_expert    # no expert picked twice
    assert per_expert.sum() == 2.0                # two DISTINCT experts


def test_score_bias_survives_training_steps():
    """The selection-only bias has zero gradient; unmasked AdamW decay would
    erase it. The optimizer's decay mask must leave it untouched."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.train import default_optimizer, init_train_state, make_train_step

    cfg = get_config("tiny-deepseek")
    params = init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    params["layers"]["score_bias"] = params["layers"]["score_bias"] + 0.25
    opt = default_optimizer()
    state = init_train_state(params, opt)
    step = make_train_step(cfg, opt)
    t = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    for _ in range(3):
        state, _metrics = step(state, t, jnp.roll(t, -1, 1), jnp.ones_like(t, jnp.float32))
    np.testing.assert_allclose(
        np.asarray(state.params["layers"]["score_bias"]), 0.25, rtol=1e-6
    )


@requires_vma
def test_tiny_deepseek_pipeline_train_step():
    """MLA + DeepSeekMoE staged over pp: specs cover the new keys and the
    stage forward routes through the MLA block."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.parallel.mesh import make_mesh
    from prime_tpu.parallel.pipeline import (
        make_pipeline_train_step,
        shard_pipeline_params,
    )
    from prime_tpu.train import default_optimizer, init_train_state

    cfg = get_config("tiny-deepseek")  # 2 layers -> 2 stages
    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    opt = default_optimizer()
    params = shard_pipeline_params(init_params(jax.random.PRNGKey(0), cfg, jnp.float32), mesh, cfg)
    state = init_train_state(params, opt)
    step = make_pipeline_train_step(cfg, opt, mesh, n_microbatches=2)
    t = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)
    _state, metrics = step(state, t, jnp.roll(t, -1, 1), jnp.ones_like(t, jnp.float32))
    assert np.isfinite(float(metrics["loss"]))
