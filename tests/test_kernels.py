"""Kernel-campaign tests (CPU; pallas kernels run in interpret mode).

The pins, per docs/kernels.md "Kernel campaign & autotune":

- **Config registry**: resolution order env > tuned artifact > default; a
  tuned artifact round-trips through ``save_artifact``/``load_tuned`` and
  flips ``source()``; malformed/mismatched artifacts degrade to defaults.
- **Paged gather**: the pallas kernel is BIT-IDENTICAL to the XLA take
  reference (it moves bytes, computes nothing), and the pool's
  store/gather/split/free lifecycle round-trips exactly.
- **Fused gathered-LoRA**: the one-pass kernel is BIT-IDENTICAL to the
  base + gather + einsum chain it replaces (rounding contract in
  ops/pallas_lora.py), across mixed adapter ids.
- **int4 KV decode**: the kernel matches the widen-in-graph XLA reference
  to fp32 accumulation-order noise (~3e-7 observed; 5e-6 pinned), and the
  int4 quantization itself sits within the documented rounding tolerance
  of the fp cache (~0.09 observed on unit-normal KV; 0.2 pinned — 4-bit
  symmetric rounding error, NOT a kernel property).
- **Autotune**: a dry-run sweep persists an artifact the resolution path
  demonstrably loads.
"""

import json
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from prime_tpu.ops import kernel_configs


@pytest.fixture(autouse=True)
def _clean_registry(monkeypatch, tmp_path):
    """Isolate every test from ambient env overrides and any committed
    artifact for this host's device kind; clear the jitted kernels whose
    traces baked in a prior test's resolution."""
    for knob in ("PRIME_TPU_BLOCK_Q", "PRIME_TPU_BLOCK_K", "PRIME_TPU_BLOCK_C"):
        monkeypatch.delenv(knob, raising=False)
    monkeypatch.setenv("PRIME_TPU_KERNEL_CONFIG_DIR", str(tmp_path / "cfg"))
    kernel_configs.invalidate_cache()
    yield
    kernel_configs.invalidate_cache()
    from prime_tpu.ops.pallas_lora import fused_lora_matmul
    from prime_tpu.ops.pallas_paged import paged_gather

    paged_gather.clear_cache()
    fused_lora_matmul.clear_cache()


# ---- config registry ---------------------------------------------------------


def test_resolve_order_default_tuned_env(monkeypatch, tmp_path):
    assert kernel_configs.resolve("flash_prefill", "block_q") == 128
    assert kernel_configs.source() == "default"

    out = tmp_path / "cfg"
    path = kernel_configs.save_artifact(
        {"flash_prefill": {"block_q": 256, "us": 12.5}}, directory=str(out)
    )
    assert json.loads(open(path).read())["schema"] == kernel_configs.SCHEMA_VERSION
    assert kernel_configs.resolve("flash_prefill", "block_q") == 256
    # params the artifact doesn't cover keep their defaults
    assert kernel_configs.resolve("flash_prefill", "block_k") == 128
    assert kernel_configs.source() == "tuned"

    monkeypatch.setenv("PRIME_TPU_BLOCK_Q", "64")
    assert kernel_configs.resolve("flash_prefill", "block_q") == 64
    assert kernel_configs.source() == "env"


def test_resolve_unknown_pair_raises():
    with pytest.raises(KeyError):
        kernel_configs.resolve("flash_prefill", "nope")
    with pytest.raises(KeyError):
        kernel_configs.resolve("not_a_kernel", "block_q")


def test_malformed_artifact_degrades_to_defaults(tmp_path):
    out = tmp_path / "cfg"
    out.mkdir()
    kind = kernel_configs.device_kind()
    (out / f"{kind}.json").write_text('{"schema": 999, "kernels": {}}')
    kernel_configs.invalidate_cache()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert kernel_configs.load_tuned() is None
    assert any("ignoring kernel config artifact" in str(w.message) for w in caught)
    assert kernel_configs.resolve("flash_decode", "block_c") == 128
    assert kernel_configs.source() == "default"


def test_wrong_device_kind_artifact_ignored(tmp_path):
    path = kernel_configs.save_artifact(
        {"flash_decode": {"block_c": 512}}, kind="tpu-v999"
    )
    assert path.endswith("tpu-v999.json")
    # this host's kind is not tpu-v999: the artifact must not feed it
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert kernel_configs.load_tuned() is None
    assert kernel_configs.resolve("flash_decode", "block_c") == 128


# ---- paged gather ------------------------------------------------------------


def _pool_and_table(seed=0, num_pages=8, r_dim=48, page_tokens=16, max_pages=6):
    rng = np.random.default_rng(seed)
    pool = jnp.asarray(
        rng.normal(size=(num_pages, r_dim, page_tokens)).astype(np.float32)
    )
    table = np.full(max_pages, -1, dtype=np.int32)
    used = rng.permutation(num_pages)[: max_pages - 2]  # leave a -1 tail
    table[: len(used)] = used
    return pool, jnp.asarray(table)


def test_paged_gather_kernel_bit_identical_to_xla():
    from prime_tpu.ops.pallas_paged import paged_gather, paged_gather_xla

    pool, table = _pool_and_table()
    out = paged_gather(pool, table, interpret=True)
    ref = paged_gather_xla(pool, table)
    assert out.shape == ref.shape == (48, 6 * 16)
    assert np.array_equal(np.asarray(out), np.asarray(ref))
    # empty slots are zeros (the copy path's init_cache contract)
    assert np.all(np.asarray(out)[:, 4 * 16 :] == 0)


def test_paged_gather_block_r_clamps_to_divisor():
    from prime_tpu.ops.pallas_paged import paged_gather, paged_gather_xla

    pool, table = _pool_and_table(r_dim=40)
    # 7 divides nothing relevant: the wrapper walks down to a divisor of 40
    out = paged_gather(pool, table, block_r=7, interpret=True)
    assert np.array_equal(np.asarray(out), np.asarray(paged_gather_xla(pool, table)))


def test_kv_pool_store_gather_split_free():
    from prime_tpu.serve.kv_pool import PagedKVPool, PagedSegment

    rng = np.random.default_rng(1)
    leaves = lambda t: {
        "k": jnp.asarray(rng.normal(size=(2, 1, 3, 8, t)).astype(np.float32)),
        "v": jnp.asarray(rng.normal(size=(2, 1, 3, 8, t)).astype(np.float32)),
    }
    page_nbytes = 2 * (2 * 3 * 8 * 16 * 4)
    pool = PagedKVPool(budget_bytes=page_nbytes * 4, page_tokens=16)

    seg = leaves(48)
    pages = pool.store(seg)
    assert pages is not None and len(pages) == 3 and pool.free_pages == 1

    # materialize round-trips the exact bytes
    back = pool.materialize(pages, 48)
    for name in seg:
        assert np.array_equal(np.asarray(back[name]), np.asarray(seg[name]))

    # over-budget store falls back (returns None, frees nothing)
    assert pool.store(leaves(32)) is None and pool.free_pages == 1

    # unaligned store falls back
    assert pool.store(leaves(10)) is None

    # gather_row lays pages contiguously, zeros past the table
    table = np.full(4, -1, dtype=np.int32)
    table[:3] = pages
    row = pool.gather_row(table)
    got = np.asarray(row["k"])
    assert got.shape == (2, 1, 3, 8, 64)
    assert np.array_equal(got[..., :48], np.asarray(seg["k"]))
    assert np.all(got[..., 48:] == 0)

    # split is a zero-copy page repartition; close frees exactly once
    ps = PagedSegment(pool, pages, 48)
    upper, lower = ps.split(16)
    assert upper.pages == pages[:1] and lower.pages == pages[1:]
    assert upper.nbytes + lower.nbytes == len(pages) * pool.page_nbytes
    upper.close()
    lower.close()
    lower.close()  # double close is a no-op
    assert pool.free_pages == 4
    with pytest.raises(ValueError):
        PagedSegment(pool, [0, 1], 32).split(8)  # not page-aligned


def test_kv_pool_budget_too_small_disables():
    from prime_tpu.serve.kv_pool import PagedKVPool

    pool = PagedKVPool(budget_bytes=16, page_tokens=16)
    seg = {"k": jnp.ones((2, 1, 3, 8, 16), dtype=jnp.float32)}
    assert pool.store(seg) is None
    assert pool.store(seg) is None  # stays disabled, no crash


# ---- fused gathered-LoRA -----------------------------------------------------


def _lora_reference(x, w, a, b, ids):
    """The einsum chain from models/llama._lora_mm, verbatim rounding."""
    y = x @ w
    a_rows = a[ids].astype(jnp.float32)
    b_rows = b[ids].astype(jnp.float32)
    h = jnp.einsum("bsd,bdr->bsr", x.astype(jnp.float32), a_rows)
    delta = jnp.einsum("bsr,bro->bso", h, b_rows)
    return y + delta.astype(y.dtype)


@pytest.mark.parametrize("seq", [1, 6])
def test_fused_lora_bit_identical_to_einsum_chain(seq):
    from prime_tpu.ops.pallas_lora import fused_lora_matmul

    rng = np.random.default_rng(2)
    batch, d_in, rank, d_out, bank = 4, 24, 4, 40, 3
    x = jnp.asarray(rng.normal(size=(batch, seq, d_in)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(d_in, d_out)).astype(np.float32))
    a = jnp.asarray(rng.normal(size=(bank, d_in, rank)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(bank, rank, d_out)).astype(np.float32))
    ids = jnp.asarray([0, 2, 1, 2], dtype=jnp.int32)  # mixed wave, incl. base
    out = fused_lora_matmul(x, w, a, b, ids, interpret=True)
    ref = _lora_reference(x, w, a, b, ids)
    assert np.array_equal(np.asarray(out), np.asarray(ref))


def test_lora_mm_dispatches_kernel_under_interpret(monkeypatch):
    """models/llama._lora_mm routes through the fused kernel when interpret
    mode marks it eligible, and the result still matches the chain."""
    from prime_tpu.models.llama import _lora_kernel_eligible, _lora_mm

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(2, 3, 16)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(16, 24)).astype(np.float32))
    a = jnp.asarray(rng.normal(size=(2, 16, 4)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(2, 4, 24)).astype(np.float32))
    ids = jnp.asarray([1, 0], dtype=jnp.int32)
    lp = {"wq": w, "lora:wq:a": a, "lora:wq:b": b}

    monkeypatch.delenv("PRIME_TPU_PALLAS_INTERPRET", raising=False)
    assert not _lora_kernel_eligible(w, x, b)  # CPU, no interpret: einsum path
    ref = _lora_mm(x, lp, "wq", ids)

    monkeypatch.setenv("PRIME_TPU_PALLAS_INTERPRET", "1")
    assert _lora_kernel_eligible(w, x, b)
    out = _lora_mm(x, lp, "wq", ids)
    assert np.array_equal(np.asarray(out), np.asarray(ref))
    # quantized base weights keep the chain (the kernel only fuses plain 2-D)
    assert not _lora_kernel_eligible((w, jnp.ones((1, 24))), x, b)


# ---- int4 KV decode ----------------------------------------------------------


def _int4_cache(seed=4, batch=2, kv_heads=1, dim=16, capacity=64):
    from prime_tpu.models.quantize import quantize_kv_int4

    rng = np.random.default_rng(seed)
    k = jnp.asarray(rng.normal(size=(batch, kv_heads, dim, capacity)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(batch, kv_heads, dim, capacity)).astype(np.float32))
    kq, ks = quantize_kv_int4(k)
    vq, vs = quantize_kv_int4(v)
    return k, v, kq, ks, vq, vs


def test_quantize_kv_int4_round_trip():
    from prime_tpu.models.quantize import quantize_kv_int4, unpack_kv_int4

    k, _, kq, ks, _, _ = _int4_cache()
    assert kq.dtype == jnp.uint8 and kq.shape == (2, 1, 8, 64)  # packed halves
    assert ks.shape == (2, 1, 1, 64)
    recon = np.asarray(unpack_kv_int4(kq) * ks)
    # 4-bit symmetric: |err| <= scale/2 per element
    assert np.all(np.abs(recon - np.asarray(k)) <= np.asarray(ks) / 2 + 1e-7)
    with pytest.raises(ValueError):
        quantize_kv_int4(jnp.ones((1, 1, 3, 16)))  # odd feature dim


def test_int4_decode_kernel_matches_xla_reference(monkeypatch):
    """flash_decode's int4 variant (interpret) vs the widen-in-graph XLA
    path: accumulation-order noise only."""
    from prime_tpu.ops.attention import decode_attention

    k, v, kq, ks, vq, vs = _int4_cache()
    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.normal(size=(2, 2, 1, 16)).astype(np.float32))
    lengths = jnp.asarray([64, 37], dtype=jnp.int32)
    sm_scale = 16 ** -0.5

    ref = decode_attention(
        q, kq, vq, lengths, sm_scale, impl="xla", k_scale=ks, v_scale=vs
    )
    monkeypatch.setenv("PRIME_TPU_PALLAS_INTERPRET", "1")
    out = decode_attention(
        q, kq, vq, lengths, sm_scale, impl="pallas", k_scale=ks, v_scale=vs
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=5e-6)

    # documented int4 rounding tolerance vs the fp cache (unit-normal KV:
    # ~0.09 observed; this is the quantizer's error, not the kernel's)
    fp = decode_attention(q, k, v, lengths, sm_scale, impl="xla")
    assert float(np.max(np.abs(np.asarray(out) - np.asarray(fp)))) < 0.2


def test_int4_decode_dispatch_detects_uint8():
    """decode_attention intercepts uint8 caches before the impl switch —
    auto on CPU (no interpret) must take the XLA widen path, not crash."""
    from prime_tpu.ops.attention import decode_attention

    _, _, kq, ks, vq, vs = _int4_cache()
    q = jnp.ones((2, 2, 1, 16), dtype=jnp.float32)
    lengths = jnp.asarray([64, 64], dtype=jnp.int32)
    out = decode_attention(
        q, kq, vq, lengths, 0.25, impl="auto", k_scale=ks, v_scale=vs
    )
    assert out.shape == (2, 2, 1, 16) and out.dtype == jnp.float32


# ---- autotune ----------------------------------------------------------------


def test_autotune_dry_run_round_trips_artifact(tmp_path):
    from prime_tpu.ops.autotune import run_autotune

    winners = run_autotune(
        kernels=["paged_gather", "lora_mm"], dry_run=True
    )
    assert set(winners) == {"paged_gather", "lora_mm"}
    assert winners["paged_gather"]["block_r"] > 0
    assert "us" in winners["lora_mm"]

    out = tmp_path / "tuned"
    kernel_configs.save_artifact(winners, directory=str(out))
    # resolution must read the persisted winners (us key ignored)
    import os

    os.environ["PRIME_TPU_KERNEL_CONFIG_DIR"] = str(out)
    kernel_configs.invalidate_cache()
    try:
        assert kernel_configs.source() == "tuned"
        assert (
            kernel_configs.resolve("paged_gather", "block_r")
            == winners["paged_gather"]["block_r"]
        )
        assert (
            kernel_configs.resolve("lora_mm", "block_out")
            == winners["lora_mm"]["block_out"]
        )
        # kernels not in the artifact keep defaults
        assert kernel_configs.resolve("flash_prefill", "block_q") == 128
    finally:
        kernel_configs.invalidate_cache()


def test_autotune_unknown_kernel_raises():
    from prime_tpu.ops.autotune import run_autotune

    with pytest.raises(ValueError, match="unknown kernel"):
        run_autotune(kernels=["nope"], dry_run=True)
