"""Model stack correctness on the virtual CPU mesh.

The load-bearing invariants:
- decode-with-cache must reproduce full-sequence forward logits exactly
  (the KV cache is an optimization, not an approximation);
- pallas flash attention (interpret mode on CPU) must match the XLA
  reference path;
- ring attention over the sp axis must match dense causal attention;
- the sharded train step must run and reduce loss on a (dp, fsdp, tp) mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from prime_tpu.models import get_config
from prime_tpu.models.llama import forward, init_cache, init_params
from prime_tpu.models.sampler import generate

CFG = get_config("tiny-test")


@pytest.fixture(scope="module")
def params():
    # float32 on CPU: bf16 matmul emulation is slow and loses test precision
    return init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)


def test_forward_shapes_and_determinism(params):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, CFG.vocab_size)
    logits, cache = forward(params, tokens, CFG)
    assert logits.shape == (2, 16, CFG.vocab_size)
    assert logits.dtype == jnp.float32
    assert cache is None
    logits2, _ = forward(params, tokens, CFG)
    np.testing.assert_array_equal(np.asarray(logits), np.asarray(logits2))


def test_last_positions_matches_full_logits(params):
    """forward(last_positions=...) == gathering those rows from full logits.

    The gathered-before-unembedding prefill path (models/llama.py forward)
    must be numerically identical to slicing the full (B, S, V) logits —
    it exists so long-context prefill never materializes that buffer."""
    tokens = jax.random.randint(jax.random.PRNGKey(11), (3, 16), 0, CFG.vocab_size)
    lengths = jnp.asarray([16, 9, 4], dtype=jnp.int32)
    full_logits, _ = forward(params, tokens, CFG)
    last_logits, _ = forward(params, tokens, CFG, last_positions=lengths - 1)
    assert last_logits.shape == (3, 1, CFG.vocab_size)
    expect = np.take_along_axis(
        np.asarray(full_logits), np.asarray(lengths - 1)[:, None, None], axis=1
    )
    np.testing.assert_allclose(np.asarray(last_logits), expect, rtol=1e-5, atol=1e-5)


def test_causality(params):
    """Changing a future token must not change past logits."""
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 12), 0, CFG.vocab_size)
    logits_a, _ = forward(params, tokens, CFG)
    tampered = tokens.at[0, 8].set((tokens[0, 8] + 7) % CFG.vocab_size)
    logits_b, _ = forward(params, tampered, CFG)
    np.testing.assert_allclose(
        np.asarray(logits_a[0, :8]), np.asarray(logits_b[0, :8]), rtol=1e-5, atol=1e-5
    )
    assert not np.allclose(np.asarray(logits_a[0, 8:]), np.asarray(logits_b[0, 8:]))


def test_decode_matches_full_forward(params):
    """Prefill + step-by-step decode == one full forward over the sequence."""
    seq = 10
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, seq), 0, CFG.vocab_size)
    full_logits, _ = forward(params, tokens, CFG)

    prefix = 6
    cache = init_cache(CFG, 2, seq + 4, dtype=jnp.float32)
    prefill_logits, cache = forward(params, tokens[:, :prefix], CFG, cache=cache)
    np.testing.assert_allclose(
        np.asarray(full_logits[:, :prefix]), np.asarray(prefill_logits), rtol=2e-4, atol=2e-4
    )
    for i in range(prefix, seq):
        step_logits, cache = forward(
            params,
            tokens[:, i : i + 1],
            CFG,
            positions=cache.lengths[:, None],
            cache=cache,
            decode=True,
        )
        np.testing.assert_allclose(
            np.asarray(full_logits[:, i]), np.asarray(step_logits[:, 0]), rtol=2e-4, atol=2e-4
        )


def test_chunked_prefill_quantized_matches_one_shot(params):
    """int8 cache + shared-offset chunked prefill: per-slot scales make each
    chunk's quantization independent, so the staged cache (values AND scales)
    must equal the one-shot quantized prefill's exactly. Logits only match
    approximately BY DESIGN: chunked prefill attends over the int8 cache
    (like decode does) while one-shot prefill attends on raw activations."""
    seq, capacity = 24, 32
    tokens = jax.random.randint(jax.random.PRNGKey(17), (2, seq), 0, CFG.vocab_size)
    ref_cache = init_cache(CFG, 2, capacity, dtype=jnp.float32, quantized=True)
    ref_logits, ref_cache = forward(params, tokens, CFG, cache=ref_cache)

    cache = init_cache(CFG, 2, capacity, dtype=jnp.float32, quantized=True)
    offset = 0
    chunk_logits = []
    for size in (8, 16):
        chunk = tokens[:, offset : offset + size]
        logits, cache = forward(
            params, chunk, CFG, cache=cache,
            prefill_offset=jnp.asarray(offset, dtype=jnp.int32),
        )
        chunk_logits.append(logits)
        offset += size
    got = jnp.concatenate(chunk_logits, axis=1)
    # int8 attention noise bound, and the same continuation choice
    np.testing.assert_allclose(np.asarray(ref_logits), np.asarray(got), rtol=0.1, atol=0.1)
    np.testing.assert_array_equal(
        np.asarray(jnp.argmax(ref_logits[:, -1, :], axis=-1)),
        np.asarray(jnp.argmax(got[:, -1, :], axis=-1)),
    )
    # layer 0 sees identical inputs either way -> bit-identical int8 payloads
    # and per-slot scales (deeper layers legitimately drift: their inputs
    # already differ by the int8 attention noise above)
    np.testing.assert_array_equal(
        np.asarray(ref_cache.k[0, :, :, :, :seq]), np.asarray(cache.k[0, :, :, :, :seq])
    )
    np.testing.assert_array_equal(
        np.asarray(ref_cache.k_scale[0, :, :, :, :seq]),
        np.asarray(cache.k_scale[0, :, :, :, :seq]),
    )
    np.testing.assert_array_equal(
        np.asarray(ref_cache.v_scale[0, :, :, :, :seq]),
        np.asarray(cache.v_scale[0, :, :, :, :seq]),
    )
    # deeper layers: dequantized caches stay within the int8 noise bound
    dequant = lambda c, s: np.asarray(c[:, :, :, :, :seq]).astype(np.float32) * np.asarray(  # noqa: E731
        s[:, :, :, :, :seq]
    )
    np.testing.assert_allclose(
        dequant(ref_cache.k, ref_cache.k_scale), dequant(cache.k, cache.k_scale),
        rtol=0.2, atol=0.1,
    )


def test_chunked_prefill_matches_one_shot(params):
    """Feeding a prompt in chunks (write-at-offset + attend-over-cache) must
    reproduce the one-shot prefill logits and leave an equivalent cache."""
    seq, capacity = 24, 32
    tokens = jax.random.randint(jax.random.PRNGKey(9), (2, seq), 0, CFG.vocab_size)
    ref_cache = init_cache(CFG, 2, capacity, dtype=jnp.float32)
    ref_logits, ref_cache = forward(params, tokens, CFG, cache=ref_cache)

    cache = init_cache(CFG, 2, capacity, dtype=jnp.float32)
    offset = 0
    chunk_logits = []
    for size in (8, 16):  # uneven chunks on purpose
        chunk = tokens[:, offset : offset + size]
        logits, cache = forward(
            params, chunk, CFG, cache=cache,
            prefill_offset=jnp.asarray(offset, dtype=jnp.int32),
        )
        chunk_logits.append(logits)
        offset += size
    got = jnp.concatenate(chunk_logits, axis=1)
    np.testing.assert_allclose(np.asarray(ref_logits), np.asarray(got), rtol=2e-4, atol=2e-4)
    assert int(cache.lengths[0]) == seq
    np.testing.assert_allclose(
        np.asarray(ref_cache.k[:, :, :, :, :seq]), np.asarray(cache.k[:, :, :, :, :seq]),
        rtol=2e-4, atol=2e-4,
    )

    # decode continues identically from the chunked cache
    nxt = jnp.argmax(got[:, -1, :], axis=-1)[:, None]
    step_ref, _ = forward(
        params, nxt, CFG, positions=ref_cache.lengths[:, None], cache=ref_cache, decode=True
    )
    step_chunked, _ = forward(
        params, nxt, CFG, positions=cache.lengths[:, None], cache=cache, decode=True
    )
    np.testing.assert_allclose(
        np.asarray(step_ref), np.asarray(step_chunked), rtol=2e-4, atol=2e-4
    )


def test_gqa_heads_differ(params):
    """Sanity: GQA config uses fewer kv heads than q heads."""
    assert CFG.n_kv_heads < CFG.n_heads
    assert params["layers"]["wk"].shape[-1] == CFG.n_kv_heads * CFG.head_dim


def test_generate_greedy_deterministic(params):
    prompts = jax.random.randint(jax.random.PRNGKey(4), (2, 8), 1, CFG.vocab_size)
    lengths = jnp.array([8, 5], dtype=jnp.int32)
    result = generate(
        params, prompts, lengths, CFG, jax.random.PRNGKey(0), max_new_tokens=6, temperature=0.0
    )
    assert result.tokens.shape == (2, 6)
    result2 = generate(
        params, prompts, lengths, CFG, jax.random.PRNGKey(9), max_new_tokens=6, temperature=0.0
    )
    np.testing.assert_array_equal(np.asarray(result.tokens), np.asarray(result2.tokens))
    assert jnp.all(result.logprobs <= 0)


def test_generate_respects_prompt_lengths(params):
    """A shorter (right-padded) prompt must generate from its own last token,
    not from the pad region."""
    prompt = jax.random.randint(jax.random.PRNGKey(5), (1, 5), 1, CFG.vocab_size)
    padded = jnp.concatenate([prompt, jnp.zeros((1, 3), dtype=prompt.dtype)], axis=1)
    r_exact = generate(
        params, prompt, jnp.array([5]), CFG, jax.random.PRNGKey(0), max_new_tokens=4
    )
    r_padded = generate(
        params, padded, jnp.array([5]), CFG, jax.random.PRNGKey(0), max_new_tokens=4
    )
    np.testing.assert_array_equal(np.asarray(r_exact.tokens), np.asarray(r_padded.tokens))


def test_param_count_llama8b():
    assert get_config("llama3-8b").param_count == pytest.approx(8.03e9, rel=0.01)
    assert get_config("llama3.2-1b").param_count == pytest.approx(1.24e9, rel=0.02)


def test_top_p_sampling_restricts_to_nucleus():
    """With a peaked distribution and small top_p, sampling == argmax; with
    top_p=1.0 the tail stays reachable."""
    import jax

    from prime_tpu.models.sampler import _sample

    logits = jnp.log(jnp.asarray([[0.6, 0.25, 0.1, 0.05]]))
    top = []
    for seed in range(64):
        token = int(_sample(logits, temperature=1.0, rng=jax.random.PRNGKey(seed), top_p=0.5, nucleus=True)[0])
        top.append(token)
    assert set(top) == {0}  # 0.6 >= 0.5: nucleus is exactly the top token

    mid = {
        int(_sample(logits, temperature=1.0, rng=jax.random.PRNGKey(seed), top_p=0.9, nucleus=True)[0])
        for seed in range(128)
    }
    assert mid <= {0, 1, 2} and {0, 1} <= mid  # 0.6+0.25+0.1 >= 0.9, token 3 cut

    full = {
        int(_sample(logits, temperature=1.0, rng=jax.random.PRNGKey(seed), top_p=1.0)[0])
        for seed in range(256)
    }
    assert 3 in full  # untruncated sampling still reaches the tail


def test_generate_with_top_p_runs():
    import jax

    from prime_tpu.models.sampler import generate

    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 1, CFG.vocab_size)
    lengths = jnp.asarray([6, 4], jnp.int32)
    result = generate(
        params, tokens, lengths, CFG, jax.random.PRNGKey(2),
        max_new_tokens=4, temperature=0.8, top_p=0.9, nucleus=True,
    )
    assert result.tokens.shape == (2, 4)


def test_int8_kv_cache_decode_matches_fp(params):
    """Prefill + decode with the int8 cache stays close to the fp cache path
    (only int8 rounding separates them), and the cache really is int8."""
    import jax

    from prime_tpu.models.llama import forward, init_cache

    seq = 10
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, seq), 0, CFG.vocab_size)
    full_logits, _ = forward(params, tokens, CFG)

    prefix = 6
    cache = init_cache(CFG, 2, seq + 4, dtype=jnp.float32, quantized=True)
    assert cache.k.dtype == jnp.int8 and cache.quantized
    logits, cache = forward(params, tokens[:, :prefix], CFG, cache=cache)
    np.testing.assert_allclose(
        np.asarray(full_logits[:, :prefix]), np.asarray(logits), rtol=2e-4, atol=2e-4
    )  # prefill logits don't read the cache: exact
    for i in range(prefix, seq):
        step_logits, cache = forward(
            params, tokens[:, i : i + 1], CFG,
            positions=cache.lengths[:, None], cache=cache, decode=True,
        )
        # int8 rounding error only: tight but not exact
        np.testing.assert_allclose(
            np.asarray(full_logits[:, i]), np.asarray(step_logits[:, 0]), rtol=0.06, atol=0.06
        )
    assert cache.k.dtype == jnp.int8  # stays quantized through the scan


def test_int8_kv_generate_greedy_matches_fp(params):
    """Greedy generation with the int8 cache picks the same tokens as fp on a
    tiny model (rounding noise must not flip confident argmaxes)."""
    import jax

    from prime_tpu.models.sampler import generate

    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 8), 1, CFG.vocab_size)
    lengths = jnp.asarray([8, 5], jnp.int32)
    fp = generate(params, tokens, lengths, CFG, jax.random.PRNGKey(5), max_new_tokens=6)
    q8 = generate(
        params, tokens, lengths, CFG, jax.random.PRNGKey(5), max_new_tokens=6, kv_quant=True
    )
    match = (np.asarray(fp.tokens) == np.asarray(q8.tokens)).mean()
    assert match >= 0.75, f"int8 cache flipped too many greedy tokens ({match:.0%} match)"


def test_int8_cache_halves_bytes():
    from prime_tpu.models.llama import init_cache

    fp = init_cache(CFG, 2, 256, dtype=jnp.bfloat16)
    q8 = init_cache(CFG, 2, 256, quantized=True)
    fp_bytes = fp.k.nbytes + fp.v.nbytes
    q8_bytes = q8.k.nbytes + q8.v.nbytes + q8.k_scale.nbytes + q8.v_scale.nbytes
    assert q8_bytes < 0.6 * fp_bytes  # int8 + small fp32 scale rows


def test_pallas_decode_int8_cache_matches_xla():
    """Round 4: the flash kernel handles int8 caches (half the HBM bytes,
    widened to fp32 in VMEM, per-slot scales folded into the epilogues) —
    parity vs the XLA quantized decode with ragged lengths, alone and with
    sinks/window."""
    from prime_tpu.models.llama import quantize_kv
    from prime_tpu.ops.attention import decode_attention
    from prime_tpu.ops.pallas_attention import flash_decode

    b, h, kh, d, c = 3, 8, 2, 64, 256
    k_raw = jax.random.normal(jax.random.PRNGKey(1), (b, kh, d, c), dtype=jnp.float32)
    v_raw = jax.random.normal(jax.random.PRNGKey(2), (b, kh, d, c), dtype=jnp.float32)
    kq, k_scale = quantize_kv(k_raw)
    vq, v_scale = quantize_kv(v_raw)
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, 1, d), dtype=jnp.float32)
    lengths = jnp.asarray([256, 77, 130], dtype=jnp.int32)
    sinks = jax.random.normal(jax.random.PRNGKey(3), (h,), dtype=jnp.float32)

    for kw in ({}, dict(sinks=sinks), dict(window=64, sliding=jnp.asarray(True))):
        ref = decode_attention(
            q, kq, vq, lengths, d**-0.5, impl="xla",
            k_scale=k_scale, v_scale=v_scale, **kw,
        )
        out = flash_decode(
            q, kq, vq, lengths, sm_scale=d**-0.5,
            k_scale=k_scale, v_scale=v_scale, interpret=True, **kw,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3,
            err_msg=f"variant {sorted(kw)}",
        )


def test_int8_weights_logits_close_and_bytes_halved(params):
    from prime_tpu.models.quantize import is_quantized, quantize_params_int8

    qparams = quantize_params_int8(params)
    assert is_quantized(qparams)
    tokens = jax.random.randint(jax.random.PRNGKey(7), (2, 10), 0, CFG.vocab_size)
    fp_logits, _ = forward(params, tokens, CFG)
    q_logits, _ = forward(qparams, tokens, CFG)
    # int8 per-channel rounding: close in probability space
    fp_probs = np.asarray(jax.nn.softmax(fp_logits, axis=-1))
    q_probs = np.asarray(jax.nn.softmax(q_logits, axis=-1))
    assert np.abs(fp_probs - q_probs).max() < 0.05
    # argmax agreement stays high
    agreement = (np.asarray(fp_logits).argmax(-1) == np.asarray(q_logits).argmax(-1)).mean()
    assert agreement >= 0.9

    big_fp = sum(w.nbytes for w in [params["layers"][k] for k in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")])
    big_q8 = sum(
        qparams["layers"][k][0].nbytes + qparams["layers"][k][1].nbytes
        for k in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")
    )
    # fixture params are fp32: int8 + scales must be well under half
    assert big_q8 < 0.3 * big_fp


def test_int8_weights_generate_end_to_end(params):
    from prime_tpu.models.quantize import quantize_params_int8
    from prime_tpu.models.sampler import generate

    qparams = quantize_params_int8(params)
    tokens = jax.random.randint(jax.random.PRNGKey(8), (2, 6), 1, CFG.vocab_size)
    lengths = jnp.asarray([6, 4], jnp.int32)
    result = generate(qparams, tokens, lengths, CFG, jax.random.PRNGKey(9), max_new_tokens=4)
    assert result.tokens.shape == (2, 4)


def test_int8_weights_moe_forward():
    from prime_tpu.models import get_config
    from prime_tpu.models.quantize import quantize_params_int8

    moe_cfg = get_config("tiny-moe")
    moe_params = init_params(jax.random.PRNGKey(0), moe_cfg, dtype=jnp.float32)
    qparams = quantize_params_int8(moe_params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, moe_cfg.vocab_size)
    fp_logits, _ = forward(moe_params, tokens, moe_cfg)
    q_logits, _ = forward(qparams, tokens, moe_cfg)
    fp_probs = np.asarray(jax.nn.softmax(fp_logits, axis=-1))
    q_probs = np.asarray(jax.nn.softmax(q_logits, axis=-1))
    assert np.abs(fp_probs - q_probs).max() < 0.08  # routing can amplify rounding


def test_int4_weights_matmul_exact_and_bytes_quartered(params):
    """W4A16 group-wise: the grouped matmul must equal x @ dequant(q, s)
    (same math, different order), logits stay usable, bytes ~quarter fp32."""
    from prime_tpu.models.quantize import (
        matmul,
        quantize_params_int4,
        quantize_weight_int4,
    )

    w = jax.random.normal(jax.random.PRNGKey(0), (256, 96)) * 0.02
    q, s = quantize_weight_int4(w)
    # nibble-packed uint8 carrier: half the rows, two weights per byte
    assert str(q.dtype) == "uint8" and q.shape == (128, 96)
    assert s.shape == (2, 1, 96)  # groups of 128
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 256))
    from prime_tpu.models.quantize import _unpack_nibbles

    lo, hi = _unpack_nibbles(q.reshape(2, 64, 96))
    unpacked = jnp.concatenate([lo, hi], axis=-2)  # (2, 128, 96) int8
    dequant = (unpacked.astype(jnp.float32) * s).reshape(256, 96)
    assert np.abs(np.asarray(matmul(x, (q, s)) - x @ dequant)).max() < 1e-4
    # 4-bit quantization noise is bounded for well-scaled weights
    rel = float(jnp.linalg.norm(matmul(x, (q, s)) - x @ w) / jnp.linalg.norm(x @ w))
    assert rel < 0.25

    q4params = quantize_params_int4(params)
    tokens = jax.random.randint(jax.random.PRNGKey(7), (2, 10), 0, CFG.vocab_size)
    logits, _ = forward(q4params, tokens, CFG)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_int4_weights_generate_and_compose_with_int8(params):
    """int4 dense + int8 leftovers compose in one tree; generate runs; a
    second int8 pass never re-quantizes an existing tuple."""
    from prime_tpu.models.quantize import quantize_params_int4, quantize_params_int8
    from prime_tpu.models.sampler import generate

    q4 = quantize_params_int8(quantize_params_int4(params))
    assert str(q4["layers"]["wq"][0].dtype) == "uint8"  # int8 pass left it alone
    tokens = jax.random.randint(jax.random.PRNGKey(8), (2, 6), 1, CFG.vocab_size)
    lengths = jnp.asarray([6, 4], jnp.int32)
    result = generate(q4, tokens, lengths, CFG, jax.random.PRNGKey(9), max_new_tokens=4)
    assert result.tokens.shape == (2, 4)
    # kv_quant composes too (int4 weights + int8 cache)
    result = generate(
        q4, tokens, lengths, CFG, jax.random.PRNGKey(9), max_new_tokens=4, kv_quant=True
    )
    assert result.tokens.shape == (2, 4)


def test_int4_pallas_kernel_matches_xla_path(params, monkeypatch):
    """The fused pallas int4 matmul (ops/pallas_quant.py) must match the XLA
    grouped-partial path bit-for-bit up to fp accumulation order, across the
    gemv shapes the decode regime dispatches (tall, wide, single-row), and
    the dispatch itself must hold end-to-end through generate() when
    interpret mode marks the kernel eligible off-TPU."""
    from prime_tpu.models.quantize import _matmul_int4, quantize_weight_int4
    from prime_tpu.models.sampler import generate
    from prime_tpu.ops.pallas_quant import int4_matmul

    # the references below must come from the XLA path: if interpret mode
    # leaked in from the environment the kernel would be compared to itself
    monkeypatch.delenv("PRIME_TPU_PALLAS_INTERPRET", raising=False)
    # 896 regression: a multiple of 128 but not of the 512 preferred block —
    # the kernel must pick a dividing block, not floor-drop tail columns
    for i, (din, dout, rows) in enumerate(
        [(256, 128, 8), (512, 256, 1), (256, 384, 3), (256, 896, 4)]
    ):
        w = jax.random.normal(jax.random.PRNGKey(i), (din, dout)) * 0.02
        q, s = quantize_weight_int4(w)
        x = jax.random.normal(jax.random.PRNGKey(10 + i), (rows, din))
        ref = _matmul_int4(x, q, s)  # XLA path (kernel ineligible off-TPU)
        out = int4_matmul(x, q, s[..., 0, :].astype(jnp.float32), interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)

    # end-to-end: interpret mode flips eligibility on (checked at trace
    # time), so the second generate uses a DIFFERENT max_new_tokens to force
    # a retrace — greedy tokens over the common prefix must agree exactly
    from prime_tpu.models.quantize import quantize_params_int4

    q4 = quantize_params_int4(params)
    tokens = jax.random.randint(jax.random.PRNGKey(20), (2, 6), 1, CFG.vocab_size)
    lengths = jnp.asarray([6, 5], jnp.int32)
    plain = generate(
        q4, tokens, lengths, CFG, jax.random.PRNGKey(0),
        max_new_tokens=4, temperature=0.0,
    )
    monkeypatch.setenv("PRIME_TPU_PALLAS_INTERPRET", "1")
    kernel = generate(
        q4, tokens, lengths, CFG, jax.random.PRNGKey(0),
        max_new_tokens=5, temperature=0.0,
    )
    np.testing.assert_array_equal(
        np.asarray(plain.tokens), np.asarray(kernel.tokens[:, :4])
    )


def test_int4_generator_weight_bits(tmp_path):
    from prime_tpu.evals.runner import JaxGenerator

    gen = JaxGenerator("tiny-test", weight_quant="int4")
    assert str(gen.params["layers"]["wq"][0].dtype) == "uint8"
    [out] = gen.generate(["2+2="], max_new_tokens=4, temperature=0.0)
    assert isinstance(out, str)


def test_weight_quant_rejected_on_multi_device_mesh():
    from prime_tpu.evals.runner import JaxGenerator

    with pytest.raises(ValueError, match="single-device"):
        JaxGenerator("tiny-test", slice_name="v5e-8", weight_quant=True)


def test_decode_attention_routes_quantized_cache_to_flash(monkeypatch):
    """The dispatch wiring itself (not just the kernel): impl='pallas' with
    an int8 cache must reach flash_decode with the scales intact. CPU can't
    execute the kernel natively, so flash_decode is wrapped to force
    interpret mode and record what arrived."""
    import prime_tpu.ops.pallas_attention as pa
    from prime_tpu.models.llama import quantize_kv
    from prime_tpu.ops.attention import decode_attention

    b, h, kh, d, c = 2, 4, 2, 64, 256
    k_raw = jax.random.normal(jax.random.PRNGKey(1), (b, kh, d, c), dtype=jnp.float32)
    v_raw = jax.random.normal(jax.random.PRNGKey(2), (b, kh, d, c), dtype=jnp.float32)
    kq, k_scale = quantize_kv(k_raw)
    vq, v_scale = quantize_kv(v_raw)
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, 1, d), dtype=jnp.float32)
    lengths = jnp.asarray([256, 77], dtype=jnp.int32)

    seen = {}
    real_flash = pa.flash_decode

    def recording_flash(*args, **kw):
        seen.update(kw)
        kw["interpret"] = True
        return real_flash(*args, **kw)

    monkeypatch.setattr(pa, "flash_decode", recording_flash)
    out = decode_attention(
        q, kq, vq, lengths, d**-0.5, impl="pallas", k_scale=k_scale, v_scale=v_scale,
    )
    assert seen["k_scale"] is k_scale and seen["v_scale"] is v_scale
    ref = decode_attention(
        q, kq, vq, lengths, d**-0.5, impl="xla", k_scale=k_scale, v_scale=v_scale,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_int8_weights_gptoss_tree_quantizes_cleanly():
    """The GPT-OSS param tree (sinks, router/expert biases, fused-expert
    layout) must survive W8A16 quantization: biases and sinks stay exact,
    expert matrices quantize, and greedy decode still tracks fp32 at tiny
    scale."""
    from prime_tpu.models.quantize import is_quantized, quantize_params_int8

    cfg = get_config("tiny-gptoss").scaled(capacity_factor=8.0)
    gp = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    qp = quantize_params_int8(gp)
    assert is_quantized(qp)
    # sinks and biases are not matmul weights — they must pass through exact
    np.testing.assert_array_equal(
        np.asarray(gp["layers"]["sinks"]), np.asarray(qp["layers"]["sinks"])
    )
    np.testing.assert_array_equal(
        np.asarray(gp["layers"]["router_bias"]), np.asarray(qp["layers"]["router_bias"])
    )
    prompts = jnp.asarray([[5, 42, 100, 7, 61]])
    lengths = jnp.asarray([5], jnp.int32)
    ref = generate(gp, prompts, lengths, cfg, jax.random.PRNGKey(0),
                   max_new_tokens=6, temperature=0.0)
    out = generate(qp, prompts, lengths, cfg, jax.random.PRNGKey(0),
                   max_new_tokens=6, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(ref.tokens), np.asarray(out.tokens))


def test_longrope_long_factor_branch_decodes():
    """A LongRoPE config whose cache capacity crosses the pretrained range
    selects the LONG factor set (static per run) and still decodes
    deterministically — the branch no short-context parity test reaches."""
    from prime_tpu.ops.rope import rope_frequencies

    cfg = CFG.scaled(
        rope_longrope=((1.0,) * (CFG.head_dim // 2), (4.0,) * (CFG.head_dim // 2), 32.0, 1.2),
        max_seq_len=128,
    )
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    prompts = jax.random.randint(jax.random.PRNGKey(1), (2, 40), 1, cfg.vocab_size)
    lengths = jnp.asarray([40, 33], jnp.int32)
    # capacity 40+8 = 48 > original_max 32 -> long factors
    out1 = generate(params, prompts, lengths, cfg, jax.random.PRNGKey(2),
                    max_new_tokens=8, temperature=0.0)
    out2 = generate(params, prompts, lengths, cfg, jax.random.PRNGKey(2),
                    max_new_tokens=8, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(out1.tokens), np.asarray(out2.tokens))
    # and the table builder demonstrably switches sets at the boundary
    cos_short, _ = rope_frequencies(
        CFG.head_dim, 16, 10000.0, longrope=cfg.rope_longrope, longrope_select=16
    )
    cos_long, _ = rope_frequencies(
        CFG.head_dim, 16, 10000.0, longrope=cfg.rope_longrope, longrope_select=64
    )
    assert not np.allclose(np.asarray(cos_short), np.asarray(cos_long))
