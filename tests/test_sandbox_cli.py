"""`prime sandbox` CLI against the fake two-plane backend."""

import json

import pytest
from click.testing import CliRunner

import prime_tpu.commands._deps as deps
from prime_tpu.commands.main import cli
from prime_tpu.testing import FakeControlPlane


@pytest.fixture
def fake(monkeypatch):
    fake = FakeControlPlane()
    fake.sandbox_plane.ready_after_polls = 1
    monkeypatch.setattr(deps, "transport_override", fake.transport)
    monkeypatch.setenv("PRIME_API_KEY", "test-key")
    monkeypatch.setenv("PRIME_BASE_URL", "https://api.fake")
    return fake


@pytest.fixture
def runner():
    return CliRunner()


def _create(runner, *args) -> str:
    result = runner.invoke(cli, ["sandbox", "create", "--output", "json", *args])
    assert result.exit_code == 0, result.output
    return json.loads(result.output)["sandboxId"]


def test_create_wait_run_roundtrip(runner, fake):
    sid = _create(runner, "--name", "demo")
    result = runner.invoke(cli, ["sandbox", "run", sid, "echo from-cli"])
    assert result.exit_code == 0, result.output
    assert "from-cli" in result.output


def test_run_propagates_exit_code(runner, fake):
    sid = _create(runner)
    result = runner.invoke(cli, ["sandbox", "run", sid, "exit 9"])
    assert result.exit_code == 9


def test_create_with_tpu_and_list(runner, fake):
    _create(runner, "--tpu", "v5e-1", "--label", "proj=demo")
    result = runner.invoke(cli, ["sandbox", "list", "--label", "proj=demo", "--output", "json"])
    rows = json.loads(result.output)
    assert len(rows) == 1 and rows[0]["tpuType"] == "v5e-1"


def test_create_multihost_tpu_rejected(runner, fake):
    result = runner.invoke(cli, ["sandbox", "create", "--tpu", "v5e-16"])
    assert result.exit_code != 0
    assert "single-host" in result.output


def test_upload_download(runner, fake, tmp_path):
    sid = _create(runner)
    src = tmp_path / "f.txt"
    src.write_text("payload")
    assert runner.invoke(cli, ["sandbox", "upload", sid, str(src), "/f.txt"]).exit_code == 0
    dst = tmp_path / "out.txt"
    assert runner.invoke(cli, ["sandbox", "download", sid, "/f.txt", str(dst)]).exit_code == 0
    assert dst.read_text() == "payload"


def test_bulk_delete_previews_and_confirms(runner, fake):
    ids = [_create(runner) for _ in range(2)]
    result = runner.invoke(cli, ["sandbox", "delete", *ids], input="n\n")
    assert "Aborted" in result.output
    result = runner.invoke(cli, ["sandbox", "delete", *ids], input="y\n")
    assert result.exit_code == 0
    assert "Deleted 2 sandboxes" in result.output


def test_network_and_ports(runner, fake):
    sid = _create(runner)
    result = runner.invoke(
        cli,
        ["sandbox", "network", sid, "--default-action", "deny", "--allow", "pypi.org", "--output", "json"],
    )
    assert json.loads(result.output)["defaultAction"] == "deny"

    result = runner.invoke(cli, ["sandbox", "expose", sid, "8080", "--output", "json"])
    assert json.loads(result.output)["port"] == 8080
    result = runner.invoke(cli, ["sandbox", "list-ports", sid, "--plain"])
    assert "8080" in result.output
    assert runner.invoke(cli, ["sandbox", "unexpose", sid, "8080"]).exit_code == 0
