"""Parallelism tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from prime_tpu.models import get_config
from prime_tpu.models.llama import forward, init_params
from prime_tpu.ops.attention import xla_attention_causal
from prime_tpu.ops.pallas_attention import flash_attention_causal
from prime_tpu.parallel.mesh import make_mesh, mesh_for_slice
from prime_tpu.parallel.ring_attention import ring_self_attention
from prime_tpu.parallel.sharding import shard_batch, shard_params
from prime_tpu.train import (
    default_optimizer,
    init_train_state,
    make_train_step,
    shard_train_state,
)

from _markers import requires_set_mesh, requires_vma

CFG = get_config("tiny-test")


def test_eight_virtual_devices():
    assert jax.device_count() == 8


def test_make_mesh_shapes():
    mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    assert mesh.shape == {"dp": 2, "fsdp": 2, "tp": 2}
    with pytest.raises(ValueError, match="multiply to"):
        make_mesh({"dp": 3, "tp": 2})


def test_mesh_for_slice_v5e8():
    mesh = mesh_for_slice("v5e-8")
    assert jax.device_count() == 8
    sizes = mesh.shape
    assert sizes["dp"] * sizes["fsdp"] * sizes["tp"] == 8
    assert sizes["tp"] >= 2  # tensor parallelism rides the minor ICI dim


def test_flash_attention_matches_xla_reference():
    """Pallas kernel (interpret mode on CPU) vs fp32 XLA reference, GQA."""
    rng = jax.random.PRNGKey(0)
    b, h, kh, s, d = 2, 4, 2, 256, 128
    q = jax.random.normal(rng, (b, h, s, d), dtype=jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, kh, s, d), dtype=jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, kh, s, d), dtype=jnp.float32)
    ref = xla_attention_causal(q, k, v, d**-0.5)
    out = flash_attention_causal(q, k, v, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_ring_attention_matches_dense():
    mesh = make_mesh({"sp": 8})
    b, h, kh, s, d = 1, 4, 2, 64, 32  # S=64 over 8 devices -> 8 per device
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, s, d), dtype=jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, kh, s, d), dtype=jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, kh, s, d), dtype=jnp.float32)
    ref = xla_attention_causal(q, k, v, d**-0.5)
    out = ring_self_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


@requires_vma
def test_sharded_forward_matches_single_device():
    mesh = make_mesh({"dp": 1, "fsdp": 2, "tp": 4})
    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, CFG.vocab_size)

    ref_logits, _ = forward(params, tokens, CFG)

    sharded_params = shard_params(params, mesh, CFG)
    sharded_tokens = shard_batch(tokens, mesh)
    out_logits, _ = jax.jit(lambda p, t: forward(p, t, CFG))(sharded_params, sharded_tokens)
    np.testing.assert_allclose(
        np.asarray(ref_logits), np.asarray(out_logits), rtol=5e-4, atol=5e-4
    )


def test_sharded_train_step_reduces_loss():
    mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    optimizer = default_optimizer(learning_rate=1e-2)
    state = shard_train_state(init_train_state(params, optimizer), mesh, CFG)
    step = make_train_step(CFG, optimizer)

    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, CFG.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones_like(tokens, dtype=jnp.float32)
    tokens, targets, mask = (shard_batch(x, mesh) for x in (tokens, targets, mask))

    losses = []
    for _ in range(5):
        state, metrics = step(state, tokens, targets, mask)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"
    assert all(np.isfinite(losses))
    # params remained sharded across the step
    embed_sharding = state.params["embed"].sharding
    assert embed_sharding.spec == jax.sharding.PartitionSpec("tp", "fsdp")


def test_opt_state_sharding_matches_params_by_position():
    """wo's Adam moments must get wo's spec, not wq's (identical shapes,
    transposed specs whenever n_heads*head_dim == d_model)."""
    mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    optimizer = default_optimizer()
    state = shard_train_state(init_train_state(params, optimizer), mesh, CFG)
    adam_state = state.opt_state[1][0]  # chain: (clip, (adamw scale, wd, lr...))
    mu = adam_state.mu
    assert mu["layers"]["wo"].sharding.spec == jax.sharding.PartitionSpec(None, "tp", "fsdp")
    assert mu["layers"]["wq"].sharding.spec == jax.sharding.PartitionSpec(None, "fsdp", "tp")


@requires_set_mesh
def test_sharded_generate_matches_single_device():
    """The eval/serve path: JaxGenerator over a mesh must produce the same
    tokens as the unsharded sampler (fp32 weights for determinism)."""
    from prime_tpu.models.sampler import generate as sample_generate
    from prime_tpu.parallel.sharding import batch_spec, cache_spec, lengths_spec

    from jax.sharding import NamedSharding

    mesh = make_mesh({"dp": 1, "fsdp": 4, "tp": 2})
    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 12), 0, CFG.vocab_size)
    lengths = jnp.asarray([12, 7, 9, 12], dtype=jnp.int32)

    ref = sample_generate(
        params, tokens, lengths, CFG, jax.random.PRNGKey(2),
        max_new_tokens=8, temperature=0.0, eos_id=-1, pad_id=0,
    )

    sharded_params = shard_params(params, mesh, CFG)
    tokens_s = jax.device_put(tokens, NamedSharding(mesh, batch_spec()))
    lengths_s = jax.device_put(lengths, NamedSharding(mesh, lengths_spec()))
    with jax.set_mesh(mesh):
        out = sample_generate(
            sharded_params, tokens_s, lengths_s, CFG, jax.random.PRNGKey(2),
            max_new_tokens=8, temperature=0.0, eos_id=-1, pad_id=0,
            cache_spec=cache_spec(),
        )
    np.testing.assert_array_equal(np.asarray(out.tokens), np.asarray(ref.tokens))
    np.testing.assert_array_equal(np.asarray(out.lengths), np.asarray(ref.lengths))


@requires_set_mesh
def test_jax_generator_mesh_pads_ragged_batch():
    from prime_tpu.evals.runner import JaxGenerator

    mesh = make_mesh({"dp": 1, "fsdp": 4, "tp": 2})
    gen = JaxGenerator("tiny-test", mesh=mesh)
    outs = gen.generate(["a", "bb", "ccc"], max_new_tokens=4, temperature=0.0)
    assert len(outs) == 3  # batch of 3 padded to 4 internally, extras dropped


def test_jax_generator_rejects_tp_not_dividing_kv_heads():
    from prime_tpu.evals.runner import JaxGenerator

    mesh = make_mesh({"dp": 1, "fsdp": 2, "tp": 4})  # tiny-test has 2 kv heads
    with pytest.raises(ValueError, match="tp=4"):
        JaxGenerator("tiny-test", mesh=mesh)


def test_flash_decode_matches_xla_decode():
    """Pallas flash-decode (interpret mode) vs the XLA grouped-einsum decode
    path, over the feature-major cache with ragged lengths."""
    from prime_tpu.ops.attention import decode_attention
    from prime_tpu.ops.pallas_attention import flash_decode

    b, h, kh, d, c = 4, 8, 2, 64, 256
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, 1, d), dtype=jnp.float32)
    k_cache = jax.random.normal(jax.random.PRNGKey(1), (b, kh, d, c), dtype=jnp.float32)
    v_cache = jax.random.normal(jax.random.PRNGKey(2), (b, kh, d, c), dtype=jnp.float32)
    lengths = jnp.asarray([256, 1, 130, 77], dtype=jnp.int32)

    ref = decode_attention(q, k_cache, v_cache, lengths, d**-0.5, impl="xla")
    out = flash_decode(q, k_cache, v_cache, lengths, sm_scale=d**-0.5, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_flash_decode_sharded_matches_xla():
    """The shard_map-wrapped pallas decode (interpret mode) over a
    (dp,fsdp,tp) mesh == the XLA grouped decode on the full arrays."""
    from prime_tpu.ops.attention import decode_attention
    from prime_tpu.parallel.decode_sharded import flash_decode_sharded

    mesh = make_mesh({"dp": 1, "fsdp": 4, "tp": 2})
    b, h, kh, d, c = 4, 8, 2, 64, 256
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, 1, d), dtype=jnp.float32)
    k_cache = jax.random.normal(jax.random.PRNGKey(1), (b, kh, d, c), dtype=jnp.float32)
    v_cache = jax.random.normal(jax.random.PRNGKey(2), (b, kh, d, c), dtype=jnp.float32)
    lengths = jnp.asarray([256, 1, 130, 77], dtype=jnp.int32)

    ref = decode_attention(q, k_cache, v_cache, lengths, d**-0.5, impl="xla")
    out = flash_decode_sharded(q, k_cache, v_cache, lengths, mesh, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_sp_decode_attention_matches_xla():
    """Sequence-sharded decode (cache slots over sp, two-phase softmax
    combine) == the single-device XLA decode."""
    from prime_tpu.ops.attention import decode_attention
    from prime_tpu.parallel.long_context import sp_decode_attention

    mesh = make_mesh({"sp": 8})
    b, h, kh, d, c = 2, 8, 2, 64, 512
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, 1, d), dtype=jnp.float32)
    k_cache = jax.random.normal(jax.random.PRNGKey(1), (b, kh, d, c), dtype=jnp.float32)
    v_cache = jax.random.normal(jax.random.PRNGKey(2), (b, kh, d, c), dtype=jnp.float32)
    lengths = jnp.asarray([512, 130], dtype=jnp.int32)  # one full, one short

    ref = decode_attention(q, k_cache, v_cache, lengths, d**-0.5, impl="xla")
    out = sp_decode_attention(q, k_cache, v_cache, lengths, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_sp_decode_rejects_indivisible_capacity():
    from prime_tpu.parallel.long_context import sp_decode_attention

    mesh = make_mesh({"sp": 8})
    with pytest.raises(ValueError, match="divide over sp"):
        sp_decode_attention(
            jnp.zeros((1, 4, 1, 32)), jnp.zeros((1, 2, 32, 100)),
            jnp.zeros((1, 2, 32, 100)), jnp.zeros((1,), jnp.int32), mesh,
        )


@requires_set_mesh
def test_sharded_generate_qwen_style_bias_and_decoupled_head_dim():
    """attn_bias + head_dim_override must shard and decode like the plain
    config: tp splits the bias vectors on the projection output dim."""
    from jax.sharding import NamedSharding

    from prime_tpu.models.sampler import generate as sample_generate
    from prime_tpu.parallel.sharding import batch_spec, cache_spec, lengths_spec

    cfg = CFG.scaled(name="tiny-qwen", attn_bias=True, head_dim_override=64, qk_norm=True)
    mesh = make_mesh({"dp": 1, "fsdp": 4, "tp": 2})
    params = init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    assert params["layers"]["bq"].shape == (cfg.n_layers, cfg.n_heads * 64)
    assert params["layers"]["q_norm"].shape == (cfg.n_layers, 64)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (4, 10), 0, cfg.vocab_size)
    lengths = jnp.asarray([10, 6, 8, 10], dtype=jnp.int32)

    ref = sample_generate(
        params, tokens, lengths, cfg, jax.random.PRNGKey(5),
        max_new_tokens=6, temperature=0.0, eos_id=-1, pad_id=0,
    )
    sharded_params = shard_params(params, mesh, cfg)
    tokens_s = jax.device_put(tokens, NamedSharding(mesh, batch_spec()))
    lengths_s = jax.device_put(lengths, NamedSharding(mesh, lengths_spec()))
    with jax.set_mesh(mesh):
        out = sample_generate(
            sharded_params, tokens_s, lengths_s, cfg, jax.random.PRNGKey(5),
            max_new_tokens=6, temperature=0.0, eos_id=-1, pad_id=0,
            cache_spec=cache_spec(),
        )
    np.testing.assert_array_equal(np.asarray(out.tokens), np.asarray(ref.tokens))


@requires_set_mesh
def test_sharded_generate_gemma_style_matches_single_device():
    """Softcap + sliding-window + post-norms must survive sharding: the
    Gemma2 masking paths are pure XLA and partition like the plain model."""
    from jax.sharding import NamedSharding

    from prime_tpu.models.sampler import generate as sample_generate
    from prime_tpu.parallel.sharding import batch_spec, cache_spec, lengths_spec

    cfg = CFG.scaled(
        name="tiny-gemma", act="gelu_tanh", norm_plus_one=True, post_norms=True,
        scale_embed=True, attn_softcap=50.0, final_softcap=30.0,
        query_scale=24, sliding_window=4,
    )
    mesh = make_mesh({"dp": 1, "fsdp": 4, "tp": 2})
    params = init_params(jax.random.PRNGKey(6), cfg, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(7), (4, 10), 0, cfg.vocab_size)
    lengths = jnp.asarray([10, 6, 8, 10], dtype=jnp.int32)

    ref = sample_generate(
        params, tokens, lengths, cfg, jax.random.PRNGKey(8),
        max_new_tokens=6, temperature=0.0, eos_id=-1, pad_id=0,
    )
    sharded_params = shard_params(params, mesh, cfg)
    tokens_s = jax.device_put(tokens, NamedSharding(mesh, batch_spec()))
    lengths_s = jax.device_put(lengths, NamedSharding(mesh, lengths_spec()))
    with jax.set_mesh(mesh):
        out = sample_generate(
            sharded_params, tokens_s, lengths_s, cfg, jax.random.PRNGKey(8),
            max_new_tokens=6, temperature=0.0, eos_id=-1, pad_id=0,
            cache_spec=cache_spec(),
        )
    np.testing.assert_array_equal(np.asarray(out.tokens), np.asarray(ref.tokens))


@pytest.mark.slow
def test_ring_attention_parity_at_scale():
    """VERDICT r3 weak #5: ring-vs-dense parity where the ring actually
    works — seq 2048 over sp=8 (256 tokens/device), so all 7 ppermute
    rotations carry substantial KV blocks and every device folds all 8
    blocks through its online-softmax accumulator, GQA layout.

    Tolerance rationale: both sides accumulate in fp32, but the ring folds
    blocks in ring order while dense softmax normalizes once — rounding
    differs by O(eps * n_blocks); 2e-3 rel/abs holds with margin."""
    mesh = make_mesh({"sp": 8})
    b, h, kh, s, d = 1, 8, 2, 2048, 64
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, s, d), dtype=jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, kh, s, d), dtype=jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, kh, s, d), dtype=jnp.float32)
    ref = xla_attention_causal(q, k, v, d**-0.5)
    out = ring_self_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_ring_attention_parity_at_scale_bf16():
    """Same scale, bf16 inputs (the serving dtype). The reference sees the
    SAME bf16-quantized q/k/v, so the comparison isolates the ring schedule
    itself; bf16 has ~3 decimal digits, and the fold order compounds it —
    5e-2 abs on O(1)-scale outputs (~1.5% of the value range) documents the
    expected bf16 drift without masking a schedule bug (a causality or
    source-index error shifts outputs by O(1))."""
    mesh = make_mesh({"sp": 8})
    b, h, kh, s, d = 1, 8, 2, 2048, 64
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, s, d)).astype(jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, kh, s, d)).astype(jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, kh, s, d)).astype(jnp.bfloat16)
    ref = xla_attention_causal(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32), d**-0.5
    )
    out = ring_self_attention(q, k, v, mesh)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.asarray(ref), atol=5e-2
    )


@pytest.mark.slow
def test_sp_decode_parity_long_cache():
    """Two-phase combine parity at a long-context cache (C=8192 over sp=8,
    1024 slots/shard) with ragged lengths straddling shard boundaries —
    including one that ends exactly ON a boundary and one inside shard 0."""
    from prime_tpu.ops.attention import decode_attention
    from prime_tpu.parallel.long_context import sp_decode_attention

    mesh = make_mesh({"sp": 8})
    b, h, kh, d, c = 4, 8, 2, 64, 8192
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, 1, d), dtype=jnp.float32)
    k_cache = jax.random.normal(jax.random.PRNGKey(1), (b, kh, d, c), dtype=jnp.float32)
    v_cache = jax.random.normal(jax.random.PRNGKey(2), (b, kh, d, c), dtype=jnp.float32)
    lengths = jnp.asarray([8192, 1024, 517, 5000], dtype=jnp.int32)

    ref = decode_attention(q, k_cache, v_cache, lengths, d**-0.5, impl="xla")
    out = sp_decode_attention(q, k_cache, v_cache, lengths, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_flash_decode_gemma_gptoss_variants_match_xla():
    """The round-4 kernel variants (softcap, sliding window with front-block
    skip, attention sinks — alone and combined) vs the XLA decode path, over
    ragged lengths that straddle block boundaries."""
    from prime_tpu.ops.attention import decode_attention
    from prime_tpu.ops.pallas_attention import flash_decode

    b, h, kh, d, c = 4, 8, 2, 64, 512
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, 1, d), dtype=jnp.float32)
    k_cache = jax.random.normal(jax.random.PRNGKey(1), (b, kh, d, c), dtype=jnp.float32)
    v_cache = jax.random.normal(jax.random.PRNGKey(2), (b, kh, d, c), dtype=jnp.float32)
    lengths = jnp.asarray([512, 1, 130, 300], dtype=jnp.int32)
    sinks = jax.random.normal(jax.random.PRNGKey(3), (h,), dtype=jnp.float32)

    cases = [
        dict(softcap=30.0),
        dict(window=64),                               # window < every block span
        dict(window=64, sliding=jnp.asarray(True)),
        dict(window=64, sliding=jnp.asarray(False)),   # traced OFF -> global
        dict(window=200),                              # window crosses block boundaries
        dict(sinks=sinks),
        dict(softcap=30.0, window=64, sliding=jnp.asarray(True)),
        dict(window=128, sinks=sinks),
    ]
    for kw in cases:
        ref = decode_attention(q, k_cache, v_cache, lengths, d**-0.5, impl="xla", **kw)
        out = flash_decode(
            q, k_cache, v_cache, lengths, sm_scale=d**-0.5, interpret=True, **kw
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3,
            err_msg=f"variant {sorted(kw)}",
        )


def test_flash_decode_multi_block_grid_parity():
    """The cache-block GRID path for real: capacity 1536 forces block_c=512
    and a 3-step block axis, so the scratch carry (init/accumulate/finalize
    across grid steps), the index-map live-block clip, and the window front
    skip across block boundaries all execute — the other decode tests'
    capacities collapse to a single block, which would hide a regression in
    exactly the machinery the grid rewrite introduced."""
    from prime_tpu.ops.attention import decode_attention
    from prime_tpu.ops.pallas_attention import flash_decode

    b, h, kh, d, c = 4, 8, 2, 64, 1536
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, 1, d), dtype=jnp.float32)
    k_cache = jax.random.normal(jax.random.PRNGKey(1), (b, kh, d, c), dtype=jnp.float32)
    v_cache = jax.random.normal(jax.random.PRNGKey(2), (b, kh, d, c), dtype=jnp.float32)
    # lengths hit: full capacity, inside block 0, just over a block edge,
    # and mid block 2
    lengths = jnp.asarray([1536, 100, 513, 1100], dtype=jnp.int32)
    sinks = jax.random.normal(jax.random.PRNGKey(3), (h,), dtype=jnp.float32)

    cases = [
        dict(),
        dict(window=600, sliding=jnp.asarray(True)),   # band crosses blocks
        dict(window=600, sliding=jnp.asarray(False)),  # traced OFF -> global
        dict(softcap=30.0, sinks=sinks),
        dict(window=512, sliding=jnp.asarray(True), sinks=sinks),
    ]
    for kw in cases:
        ref = decode_attention(q, k_cache, v_cache, lengths, d**-0.5, impl="xla", **kw)
        out = flash_decode(
            q, k_cache, v_cache, lengths, sm_scale=d**-0.5, interpret=True, **kw
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3,
            err_msg=f"variant {sorted(kw)}",
        )

    # Gemma-class head_dim (256, > one 128 lane) + softcap through the
    # multi-block grid: the scratch accumulator and q/k/v blocks carry a
    # two-lane-tile head axis
    d_big = 256
    qb_ = jax.random.normal(jax.random.PRNGKey(11), (2, 4, 1, d_big), dtype=jnp.float32)
    kb_ = jax.random.normal(jax.random.PRNGKey(12), (2, 2, d_big, c), dtype=jnp.float32)
    vb_ = jax.random.normal(jax.random.PRNGKey(13), (2, 2, d_big, c), dtype=jnp.float32)
    lens_b = jnp.asarray([1536, 700], dtype=jnp.int32)
    ref = decode_attention(qb_, kb_, vb_, lens_b, d_big**-0.5, impl="xla", softcap=50.0)
    out = flash_decode(
        qb_, kb_, vb_, lens_b, sm_scale=d_big**-0.5, interpret=True, softcap=50.0
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)

    # int8 cache variant through the same multi-block grid
    k_q = jnp.clip(jnp.round(k_cache / 0.05), -127, 127).astype(jnp.int8)
    v_q = jnp.clip(jnp.round(v_cache / 0.05), -127, 127).astype(jnp.int8)
    scales = jnp.full((b, kh, 1, c), 0.05, dtype=jnp.float32)
    ref = decode_attention(
        q, k_q, v_q, lengths, d**-0.5, impl="xla", k_scale=scales, v_scale=scales,
        window=600, sliding=jnp.asarray(True),
    )
    out = flash_decode(
        q, k_q, v_q, lengths, sm_scale=d**-0.5, interpret=True,
        k_scale=scales, v_scale=scales, window=600, sliding=jnp.asarray(True),
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_flash_decode_sharded_gptoss_variants():
    """The shard_map wrapper carries the variant args: sinks split over tp
    with their heads, window/softcap are elementwise-safe."""
    from prime_tpu.ops.attention import decode_attention
    from prime_tpu.parallel.decode_sharded import flash_decode_sharded

    mesh = make_mesh({"dp": 1, "fsdp": 4, "tp": 2})
    b, h, kh, d, c = 4, 8, 2, 64, 256
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, 1, d), dtype=jnp.float32)
    k_cache = jax.random.normal(jax.random.PRNGKey(1), (b, kh, d, c), dtype=jnp.float32)
    v_cache = jax.random.normal(jax.random.PRNGKey(2), (b, kh, d, c), dtype=jnp.float32)
    lengths = jnp.asarray([256, 1, 130, 77], dtype=jnp.int32)
    sinks = jax.random.normal(jax.random.PRNGKey(3), (h,), dtype=jnp.float32)

    cases = (
        dict(sinks=sinks),
        dict(window=64, softcap=20.0),
        # traced sliding flag: crosses the shard_map boundary via closure
        # capture (the production layer scan passes exactly this)
        dict(window=64, sliding=jnp.asarray(True)),
    )
    for kw in cases:
        ref = decode_attention(q, k_cache, v_cache, lengths, d**-0.5, impl="xla", **kw)
        out = flash_decode_sharded(
            q, k_cache, v_cache, lengths, mesh, interpret=True, **kw
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3,
            err_msg=f"variant {sorted(kw)}",
        )


def test_flash_prefill_gemma_gptoss_variants_match_xla():
    """Prefill flash kernel round-4 variants (softcap, sliding window with
    band block-skip, sinks — alone and combined) vs the XLA reference, at a
    seq spanning multiple query AND key blocks."""
    from prime_tpu.ops.pallas_attention import flash_attention_causal

    b, h, kh, s, d = 2, 4, 2, 384, 128
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, s, d), dtype=jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, kh, s, d), dtype=jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, kh, s, d), dtype=jnp.float32)
    sinks = jax.random.normal(jax.random.PRNGKey(3), (h,), dtype=jnp.float32)

    cases = [
        dict(softcap=30.0),
        dict(window=64),                              # band inside one block
        dict(window=200),                             # band crosses blocks
        dict(window=64, sliding=jnp.asarray(True)),
        dict(window=64, sliding=jnp.asarray(False)),  # traced OFF -> global
        dict(sinks=sinks),
        dict(softcap=30.0, window=200, sinks=sinks),
    ]
    for kw in cases:
        ref = xla_attention_causal(q, k, v, d**-0.5, **kw)
        out = flash_attention_causal(q, k, v, sm_scale=d**-0.5, interpret=True, **kw)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3,
            err_msg=f"variant {sorted(kw)}",
        )


def test_sharded_train_step_gptoss_updates_sinks_and_biases():
    """Training a GPT-OSS config over a (dp, fsdp, ep, tp) mesh: loss is
    finite and decreasing, and the round-4 leaves (attention sinks, router
    bias, expert biases) actually receive gradient updates."""
    cfg = get_config("tiny-gptoss").scaled(capacity_factor=8.0)
    mesh = make_mesh({"dp": 1, "fsdp": 1, "ep": 4, "tp": 2})
    params = init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    optimizer = default_optimizer(learning_rate=1e-2)
    state = shard_train_state(init_train_state(params, optimizer), mesh, cfg)
    step = make_train_step(cfg, optimizer)

    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 1, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones_like(tokens, dtype=jnp.float32)
    tokens, targets, mask = (shard_batch(x, mesh) for x in (tokens, targets, mask))

    before = {
        "sinks": np.asarray(state.params["layers"]["sinks"]),
        "router_bias": np.asarray(state.params["layers"]["router_bias"]),
        "b_down": np.asarray(state.params["layers"]["b_down"]),
    }
    losses = []
    for _ in range(4):
        state, metrics = step(state, tokens, targets, mask)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses)) and losses[-1] < losses[0], losses
    for name, old in before.items():
        new = np.asarray(state.params["layers"][name])
        assert not np.allclose(old, new), f"{name} never updated"


@pytest.mark.slow
def test_ring_attention_sliding_window_matches_dense():
    """Windowed ring attention (round 4): the mask adds the window band and
    the ring stops after ceil((window-1)/S_local) hops — parity vs dense
    windowed attention at window sizes inside one shard, straddling two,
    and spanning several (seq 2048 over sp=8, 256 tokens/device)."""
    mesh = make_mesh({"sp": 8})
    b, h, kh, s, d = 1, 4, 2, 2048, 32
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, s, d), dtype=jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, kh, s, d), dtype=jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, kh, s, d), dtype=jnp.float32)
    for window in (128, 300, 900):
        ref = xla_attention_causal(q, k, v, d**-0.5, window=window)
        out = ring_self_attention(q, k, v, mesh, window=window)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3,
            err_msg=f"window {window}",
        )


def test_ring_hops_formula():
    """The hop cap itself (parity can't see it: extra hops fold to zero).
    s_local=256, sp=8: window within one shard span = 1 hop, straddling =
    2, spanning several = ceil((w-1)/256), global/full = 7."""
    from prime_tpu.parallel.ring_attention import ring_hops

    assert ring_hops(0, 256, 8) == 7       # global layer: full rotation
    assert ring_hops(1, 256, 8) == 0       # self-attention only
    assert ring_hops(128, 256, 8) == 1
    assert ring_hops(256, 256, 8) == 1     # w-1 = 255 still within one span
    assert ring_hops(257, 256, 8) == 1
    assert ring_hops(258, 256, 8) == 2     # first query needs 257 back
    assert ring_hops(300, 256, 8) == 2
    assert ring_hops(900, 256, 8) == 4
    assert ring_hops(10**6, 256, 8) == 7   # capped at P-1


def test_sp_decode_int8_cache_matches_xla():
    """Sequence-sharded decode over an int8 cache: the per-slot scales live
    with their slots on each sp shard and fold into the local einsums —
    parity vs the single-device XLA quantized decode."""
    from prime_tpu.models.llama import quantize_kv
    from prime_tpu.ops.attention import decode_attention
    from prime_tpu.parallel.long_context import sp_decode_attention

    mesh = make_mesh({"sp": 8})
    b, h, kh, d, c = 2, 8, 2, 64, 512
    k_raw = jax.random.normal(jax.random.PRNGKey(1), (b, kh, d, c), dtype=jnp.float32)
    v_raw = jax.random.normal(jax.random.PRNGKey(2), (b, kh, d, c), dtype=jnp.float32)
    kq, k_scale = quantize_kv(k_raw)
    vq, v_scale = quantize_kv(v_raw)
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, 1, d), dtype=jnp.float32)
    lengths = jnp.asarray([512, 130], dtype=jnp.int32)

    ref = decode_attention(
        q, kq, vq, lengths, d**-0.5, impl="xla", k_scale=k_scale, v_scale=v_scale
    )
    out = sp_decode_attention(
        q, kq, vq, lengths, mesh, k_scale=k_scale, v_scale=v_scale
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)
    # and the fp path still matches (the dummy-scales signature must be inert)
    ref_fp = decode_attention(q, k_raw, v_raw, lengths, d**-0.5, impl="xla")
    out_fp = sp_decode_attention(q, k_raw, v_raw, lengths, mesh)
    np.testing.assert_allclose(np.asarray(out_fp), np.asarray(ref_fp), rtol=2e-3, atol=2e-3)


@requires_set_mesh
def test_generate_with_sp_sharded_cache_matches_plain():
    """Long-context serving building block: generate with the KV cache's
    SLOT axis sharded over sp (a cache bigger than one chip's HBM spreads
    across the slice) — token-exact vs the unsharded sampler."""
    from prime_tpu.models.sampler import generate as sample_generate
    from prime_tpu.parallel.sharding import prune_spec, sp_cache_spec

    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    prompts = jax.random.randint(jax.random.PRNGKey(1), (2, 24), 1, CFG.vocab_size)
    lengths = jnp.asarray([24, 17], jnp.int32)
    ref = sample_generate(
        params, prompts, lengths, CFG, jax.random.PRNGKey(2),
        max_new_tokens=8, temperature=0.0,
    )
    mesh = make_mesh({"sp": 8})
    with jax.set_mesh(mesh):
        out = sample_generate(
            params, prompts, lengths, CFG, jax.random.PRNGKey(2),
            max_new_tokens=8, temperature=0.0, attn_impl="xla",
            cache_spec=prune_spec(sp_cache_spec(), mesh),
        )
    np.testing.assert_array_equal(np.asarray(ref.tokens), np.asarray(out.tokens))
