"""HF checkpoint conversion: logits parity with transformers LlamaForCausalLM.

The strongest possible correctness pin for the native model: convert a tiny
random HF Llama checkpoint and require (near-)identical logits.
"""

import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from prime_tpu.models.hf_loader import config_from_hf, params_from_state_dict
from prime_tpu.models.llama import forward


@pytest.fixture(scope="module")
def hf_model():
    cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    return model


def test_logits_match_transformers(hf_model):
    state = {k: v.float().numpy() for k, v in hf_model.state_dict().items()}
    config = config_from_hf(hf_model.config, name="tiny-hf")
    params = params_from_state_dict(state, config, dtype=jnp.float32)

    tokens = np.array([[3, 17, 200, 45, 9, 88, 121, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    our_logits, _ = forward(params, jnp.asarray(tokens), config)
    np.testing.assert_allclose(np.asarray(our_logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_decode_matches_transformers_generation(hf_model):
    """Greedy continuation must agree token-for-token with HF generate."""
    from prime_tpu.models.sampler import generate

    state = {k: v.float().numpy() for k, v in hf_model.state_dict().items()}
    config = config_from_hf(hf_model.config, name="tiny-hf")
    params = params_from_state_dict(state, config, dtype=jnp.float32)

    prompt = np.array([[5, 42, 100, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_out = hf_model.generate(
            torch.tensor(prompt, dtype=torch.long),
            max_new_tokens=8,
            do_sample=False,
            eos_token_id=None,
            pad_token_id=0,
        ).numpy()[0, 4:]

    import jax

    result = generate(
        params,
        jnp.asarray(prompt),
        jnp.array([4]),
        config,
        jax.random.PRNGKey(0),
        max_new_tokens=8,
        temperature=0.0,
    )
    np.testing.assert_array_equal(np.asarray(result.tokens[0]), hf_out)


def test_config_from_hf_decoupled_head_dim_carried():
    from prime_tpu.models.hf_loader import config_from_hf

    class Cfg:
        vocab_size = 128
        hidden_size = 64
        num_hidden_layers = 2
        num_attention_heads = 4
        head_dim = 32  # != 64 // 4: decoupled (Qwen3/Gemma-style)
        intermediate_size = 256

    config = config_from_hf(Cfg())
    assert config.head_dim == 32 and config.head_dim_override == 32


def test_config_from_hf_matching_head_dim_not_marked_override():
    from prime_tpu.models.hf_loader import config_from_hf

    class Cfg:
        vocab_size = 128
        hidden_size = 64
        num_hidden_layers = 2
        num_attention_heads = 4
        head_dim = 16
        intermediate_size = 256

    config = config_from_hf(Cfg())
    assert config.d_model == 64 and config.head_dim_override is None


# -- Qwen2 family (q/k/v biases) ---------------------------------------------


@pytest.fixture(scope="module")
def qwen_model():
    cfg = transformers.Qwen2Config(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rope_theta=10000.0,
        rms_norm_eps=1e-6,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(1)
    model = transformers.Qwen2ForCausalLM(cfg)
    model.eval()
    return model


def test_qwen2_logits_match_transformers(qwen_model):
    state = {k: v.float().numpy() for k, v in qwen_model.state_dict().items()}
    config = config_from_hf(qwen_model.config, name="tiny-qwen")
    assert config.attn_bias  # qwen2 always carries q/k/v biases
    params = params_from_state_dict(state, config, dtype=jnp.float32)
    assert "bq" in params["layers"]

    tokens = np.array([[3, 17, 200, 45, 9, 88, 121, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_logits = qwen_model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    our_logits, _ = forward(params, jnp.asarray(tokens), config)
    np.testing.assert_allclose(np.asarray(our_logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_qwen2_decode_matches_transformers_generation(qwen_model):
    import jax

    from prime_tpu.models.sampler import generate

    state = {k: v.float().numpy() for k, v in qwen_model.state_dict().items()}
    config = config_from_hf(qwen_model.config, name="tiny-qwen")
    params = params_from_state_dict(state, config, dtype=jnp.float32)

    prompt = np.array([[5, 42, 100, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_out = qwen_model.generate(
            torch.tensor(prompt, dtype=torch.long),
            max_new_tokens=8,
            do_sample=False,
            eos_token_id=None,
            pad_token_id=0,
        ).numpy()[0, 4:]
    result = generate(
        params, jnp.asarray(prompt), jnp.array([4]), config,
        jax.random.PRNGKey(0), max_new_tokens=8, temperature=0.0,
    )
    np.testing.assert_array_equal(np.asarray(result.tokens[0]), hf_out)


# -- decoupled head_dim (Qwen3/Gemma-style layouts) --------------------------


def test_decoupled_head_dim_logits_match_transformers():
    cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=96,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=32,  # decoupled: 4 heads x 32 != hidden 64
        max_position_embeddings=128,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(2)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    state = {k: v.float().numpy() for k, v in model.state_dict().items()}
    config = config_from_hf(model.config, name="tiny-decoupled")
    assert config.head_dim == 32
    params = params_from_state_dict(state, config, dtype=jnp.float32)
    assert params["layers"]["wq"].shape == (2, 64, 128)  # (L, D, H*hd)

    tokens = np.array([[3, 17, 99, 45, 9, 88, 121, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_logits = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    our_logits, _ = forward(params, jnp.asarray(tokens), config)
    np.testing.assert_allclose(np.asarray(our_logits), hf_logits, rtol=2e-4, atol=2e-4)


# -- Qwen3 family (qk-norm + decoupled head_dim) -----------------------------


@pytest.fixture(scope="module")
def qwen3_model():
    cfg = transformers.Qwen3Config(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=32,  # decoupled: 4 x 32 != 64
        max_position_embeddings=128,
        rope_theta=10000.0,
        rms_norm_eps=1e-6,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(4)
    model = transformers.Qwen3ForCausalLM(cfg)
    model.eval()
    return model


def test_qwen3_logits_match_transformers(qwen3_model):
    state = {k: v.float().numpy() for k, v in qwen3_model.state_dict().items()}
    config = config_from_hf(qwen3_model.config, name="tiny-qwen3")
    assert config.qk_norm and config.head_dim == 32 and not config.attn_bias
    params = params_from_state_dict(state, config, dtype=jnp.float32)
    assert params["layers"]["q_norm"].shape == (2, 32)

    tokens = np.array([[3, 17, 200, 45, 9, 88, 121, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_logits = qwen3_model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    our_logits, _ = forward(params, jnp.asarray(tokens), config)
    np.testing.assert_allclose(np.asarray(our_logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_qwen3_decode_matches_transformers_generation(qwen3_model):
    import jax

    from prime_tpu.models.sampler import generate

    state = {k: v.float().numpy() for k, v in qwen3_model.state_dict().items()}
    config = config_from_hf(qwen3_model.config, name="tiny-qwen3")
    params = params_from_state_dict(state, config, dtype=jnp.float32)

    prompt = np.array([[5, 42, 100, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_out = qwen3_model.generate(
            torch.tensor(prompt, dtype=torch.long),
            max_new_tokens=8,
            do_sample=False,
            eos_token_id=None,
            pad_token_id=0,
        ).numpy()[0, 4:]
    result = generate(
        params, jnp.asarray(prompt), jnp.array([4]), config,
        jax.random.PRNGKey(0), max_new_tokens=8, temperature=0.0,
    )
    np.testing.assert_array_equal(np.asarray(result.tokens[0]), hf_out)


def test_llama_attention_bias_includes_o_proj_bias():
    """Llama-arch attention_bias=True biases o_proj as well as q/k/v —
    logits must still match transformers exactly."""
    cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=96,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        attention_bias=True,
        max_position_embeddings=128,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(5)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    # random biases: zero-init biases would mask a dropped-bias bug
    with torch.no_grad():
        for layer in model.model.layers:
            for proj in (layer.self_attn.q_proj, layer.self_attn.k_proj,
                         layer.self_attn.v_proj, layer.self_attn.o_proj):
                proj.bias.normal_(0.0, 0.5)
    state = {k: v.float().numpy() for k, v in model.state_dict().items()}
    config = config_from_hf(model.config, name="tiny-obias")
    assert config.attn_bias and config.attn_out_bias
    params = params_from_state_dict(state, config, dtype=jnp.float32)
    assert "bo" in params["layers"]

    tokens = np.array([[3, 17, 99, 45, 9, 88, 121, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_logits = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    our_logits, _ = forward(params, jnp.asarray(tokens), config)
    np.testing.assert_allclose(np.asarray(our_logits), hf_logits, rtol=2e-4, atol=2e-4)


# -- Gemma 2 family ----------------------------------------------------------


@pytest.fixture(scope="module")
def gemma2_model():
    cfg = transformers.Gemma2Config(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=4,   # two sliding + two global layers
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=32,
        max_position_embeddings=128,
        rope_theta=10000.0,
        rms_norm_eps=1e-6,
        tie_word_embeddings=True,
        query_pre_attn_scalar=24,    # decoupled from head_dim like gemma2-9b
        attn_logit_softcapping=50.0,
        final_logit_softcapping=30.0,
        sliding_window=4,            # tiny: the window genuinely bites at seq 8
        attn_implementation="eager",
    )
    torch.manual_seed(7)
    model = transformers.Gemma2ForCausalLM(cfg)
    model.eval()
    return model


def test_gemma2_logits_match_transformers(gemma2_model):
    state = {k: v.float().numpy() for k, v in gemma2_model.state_dict().items()}
    config = config_from_hf(gemma2_model.config, name="tiny-gemma2")
    assert config.post_norms and config.norm_plus_one and config.scale_embed
    assert config.attn_softcap == 50.0 and config.final_softcap == 30.0
    assert config.sliding_window == 4 and config.query_scale == 24
    params = params_from_state_dict(state, config, dtype=jnp.float32)
    assert "attn_post_norm" in params["layers"]

    # seq 8 > window 4: sliding layers and global layers genuinely differ
    tokens = np.array([[3, 17, 200, 45, 9, 88, 121, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_logits = gemma2_model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    our_logits, _ = forward(params, jnp.asarray(tokens), config)
    np.testing.assert_allclose(np.asarray(our_logits), hf_logits, rtol=5e-4, atol=5e-4)


def test_gemma2_decode_matches_transformers_generation(gemma2_model):
    """Greedy decode past the sliding window: cache masking must match HF."""
    import jax

    from prime_tpu.models.sampler import generate

    state = {k: v.float().numpy() for k, v in gemma2_model.state_dict().items()}
    config = config_from_hf(gemma2_model.config, name="tiny-gemma2")
    params = params_from_state_dict(state, config, dtype=jnp.float32)

    prompt = np.array([[5, 42, 100, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_out = gemma2_model.generate(
            torch.tensor(prompt, dtype=torch.long),
            max_new_tokens=8,    # decode positions 4..11 cross window 4
            do_sample=False,
            eos_token_id=None,
            pad_token_id=0,
        ).numpy()[0, 4:]
    result = generate(
        params, jnp.asarray(prompt), jnp.array([4]), config,
        jax.random.PRNGKey(0), max_new_tokens=8, temperature=0.0,
    )
    np.testing.assert_array_equal(np.asarray(result.tokens[0]), hf_out)


def test_gemma2_checkpoint_dir_roundtrip(tmp_path):
    """load_hf_checkpoint on a saved Gemma2 dir: config.json omits
    tie_word_embeddings (True is Gemma's default) — the loader must not go
    looking for an lm_head that tied checkpoints don't have."""
    from prime_tpu.models.hf_loader import load_hf_checkpoint

    cfg = transformers.Gemma2Config(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=2,
        num_key_value_heads=1,
        head_dim=16,
        query_pre_attn_scalar=16,
        sliding_window=8,
        tie_word_embeddings=True,
    )
    torch.manual_seed(9)
    transformers.Gemma2ForCausalLM(cfg).save_pretrained(tmp_path / "ckpt")
    params, config = load_hf_checkpoint(tmp_path / "ckpt", dtype=jnp.float32)
    assert config.tie_embeddings and "lm_head" not in params
    assert config.post_norms and config.sliding_window == 8


# -- Gemma 3 family ----------------------------------------------------------


@pytest.fixture(scope="module")
def gemma3_model():
    cfg = transformers.Gemma3TextConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=7,   # 5:1 schedule: layers 5 and 11... here 5 is global
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        max_position_embeddings=128,
        rope_theta=1000000.0,
        rope_local_base_freq=10000.0,
        rope_scaling={"rope_type": "linear", "factor": 8.0},
        rms_norm_eps=1e-6,
        tie_word_embeddings=True,
        query_pre_attn_scalar=24,
        sliding_window=4,      # tiny: the window genuinely bites at seq 8
        attn_implementation="eager",
    )
    torch.manual_seed(11)
    model = transformers.Gemma3ForCausalLM(cfg)
    model.eval()
    return model


def test_gemma3_config_mapping(gemma3_model):
    config = config_from_hf(gemma3_model.config, name="tiny-gemma3")
    assert config.qk_norm and config.norm_plus_one and config.post_norms
    assert config.attn_softcap == 0.0 and config.final_softcap == 0.0
    assert config.sliding_pattern == "5:1" and config.sliding_window == 4
    assert config.rope_local_theta == 10000.0 and config.rope_scale == 8.0
    assert config.query_scale == 24


def test_gemma3_logits_match_transformers(gemma3_model):
    """Exercises every Gemma3 delta at once: 5:1 sliding schedule, dual
    rope frequencies (+ linear scaling on the global table), per-head
    qk-norm with (1+w) weights, no softcaps."""
    state = {k: v.float().numpy() for k, v in gemma3_model.state_dict().items()}
    config = config_from_hf(gemma3_model.config, name="tiny-gemma3")
    params = params_from_state_dict(state, config, dtype=jnp.float32)
    assert "q_norm" in params["layers"] and "attn_post_norm" in params["layers"]

    tokens = np.array([[3, 17, 200, 45, 9, 88, 121, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_logits = gemma3_model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    our_logits, _ = forward(params, jnp.asarray(tokens), config)
    np.testing.assert_allclose(np.asarray(our_logits), hf_logits, rtol=5e-4, atol=5e-4)


def test_gemma3_decode_matches_transformers_generation(gemma3_model):
    import jax

    from prime_tpu.models.sampler import generate

    state = {k: v.float().numpy() for k, v in gemma3_model.state_dict().items()}
    config = config_from_hf(gemma3_model.config, name="tiny-gemma3")
    params = params_from_state_dict(state, config, dtype=jnp.float32)

    prompt = np.array([[5, 42, 100, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_out = gemma3_model.generate(
            torch.tensor(prompt, dtype=torch.long),
            max_new_tokens=8,    # decode positions 4..11 cross window 4
            do_sample=False,
            eos_token_id=None,
            pad_token_id=0,
        ).numpy()[0, 4:]
    result = generate(
        params, jnp.asarray(prompt), jnp.array([4]), config,
        jax.random.PRNGKey(0), max_new_tokens=8, temperature=0.0,
    )
    np.testing.assert_array_equal(np.asarray(result.tokens[0]), hf_out)


def test_gemma3_multimodal_config_unwraps_text_tower():
    from prime_tpu.models.hf_loader import config_from_hf

    class Wrapper:
        model_type = "gemma3"
        text_config = {
            "model_type": "gemma3_text",
            "vocab_size": 128,
            "hidden_size": 64,
            "num_hidden_layers": 6,
            "num_attention_heads": 4,
            "num_key_value_heads": 2,
            "head_dim": 16,
            "intermediate_size": 128,
            "sliding_window": 512,
            "rope_local_base_freq": 10000.0,
        }

    config = config_from_hf(Wrapper(), name="g3-mm")
    assert config.sliding_pattern == "5:1" and config.qk_norm
    assert config.rope_local_theta == 10000.0

    class Bare:
        model_type = "gemma3"

    with pytest.raises(ValueError, match="text_config"):
        config_from_hf(Bare())


def test_gemma3_irregular_layer_types_rejected():
    from prime_tpu.models.hf_loader import config_from_hf

    class Cfg:
        model_type = "gemma3_text"
        vocab_size = 128
        hidden_size = 64
        num_hidden_layers = 4
        num_attention_heads = 4
        num_key_value_heads = 2
        intermediate_size = 128
        sliding_window = 256
        layer_types = [
            "full_attention",
            "sliding_attention",
            "sliding_attention",
            "full_attention",
        ]  # aperiodic: full first

    with pytest.raises(ValueError, match="periodic"):
        config_from_hf(Cfg())


# -- Qwen3-MoE family ---------------------------------------------------------


@pytest.fixture(scope="module")
def qwen3moe_model():
    cfg = transformers.Qwen3MoeConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,     # unused: every layer is sparse
        moe_intermediate_size=48,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        num_experts=4,
        num_experts_per_tok=2,
        norm_topk_prob=True,       # the released 30B-A3B setting
        max_position_embeddings=128,
        rope_theta=1000000.0,
        rms_norm_eps=1e-6,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(13)
    model = transformers.Qwen3MoeForCausalLM(cfg)
    model.eval()
    return model


def test_qwen3moe_config_mapping(qwen3moe_model):
    config = config_from_hf(qwen3moe_model.config, name="tiny-qwen3moe")
    assert config.qk_norm and config.is_moe
    assert config.n_experts == 4 and config.experts_per_token == 2
    assert config.d_ff == 48       # moe_intermediate_size, not intermediate_size
    assert config.norm_topk is True


def test_qwen3moe_logits_match_transformers(qwen3moe_model):
    """Qwen expert layout (mlp.gate + experts.M.{gate,up,down}_proj) through
    the same grouped-dispatch MoE math as Mixtral, plus qk-norm attention."""
    state = {k: v.float().numpy() for k, v in qwen3moe_model.state_dict().items()}
    config = config_from_hf(qwen3moe_model.config, name="tiny-qwen3moe")
    config = config.scaled(capacity_factor=8.0)  # no capacity drops vs HF's exact routing
    params = params_from_state_dict(state, config, dtype=jnp.float32)
    assert "router" in params["layers"] and "q_norm" in params["layers"]

    tokens = np.array([[3, 17, 200, 45, 9, 88, 121, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_logits = qwen3moe_model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    our_logits, _ = forward(params, jnp.asarray(tokens), config)
    np.testing.assert_allclose(np.asarray(our_logits), hf_logits, rtol=5e-4, atol=5e-4)


def test_qwen3moe_norm_topk_false_changes_gates():
    """norm_topk=False keeps raw softmax mass on the chosen experts —
    the combine weights must NOT sum to 1 per token."""
    import jax

    from prime_tpu.ops.moe import expert_capacity, top_k_routing

    logits = jax.random.normal(jax.random.PRNGKey(0), (16, 4), dtype=jnp.float32)
    capacity = expert_capacity(16, 4, k=2, capacity_factor=8.0)
    _, combine_norm, _ = top_k_routing(logits, k=2, capacity=capacity, norm_topk=True)
    _, combine_raw, _ = top_k_routing(logits, k=2, capacity=capacity, norm_topk=False)
    sums_norm = np.asarray(jnp.sum(combine_norm, axis=(1, 2)))
    sums_raw = np.asarray(jnp.sum(combine_raw, axis=(1, 2)))
    np.testing.assert_allclose(sums_norm, 1.0, atol=1e-5)
    assert (sums_raw < 1.0 - 1e-4).all()   # softmax mass of k of 4 experts < 1


def test_qwen3moe_mixed_dense_layers_rejected():
    from prime_tpu.models.hf_loader import config_from_hf

    class Cfg:
        model_type = "qwen3_moe"
        vocab_size = 128
        hidden_size = 64
        num_hidden_layers = 4
        num_attention_heads = 4
        num_key_value_heads = 2
        intermediate_size = 128
        moe_intermediate_size = 48
        num_experts = 4
        num_experts_per_tok = 2
        mlp_only_layers = [0]

    with pytest.raises(ValueError, match="mlp_only_layers"):
        config_from_hf(Cfg())
    Cfg.mlp_only_layers = []
    Cfg.decoder_sparse_step = 2
    with pytest.raises(ValueError, match="decoder_sparse_step"):
        config_from_hf(Cfg())


def test_qwen3moe_pared_config_tracks_hf_defaults():
    """A config.json omitting norm_topk_prob / num_experts_per_tok must load
    with transformers' qwen3_moe defaults (False / 8), not this loader's
    Mixtral-shaped preferences."""
    from prime_tpu.models.hf_loader import config_from_hf

    class Cfg:
        model_type = "qwen3_moe"
        vocab_size = 128
        hidden_size = 64
        num_hidden_layers = 2
        num_attention_heads = 4
        num_key_value_heads = 2
        intermediate_size = 128
        moe_intermediate_size = 48
        num_experts = 16

    config = config_from_hf(Cfg())
    assert config.norm_topk is False
    assert config.experts_per_token == 8
    # Mixtral keeps its own defaults (renormalized gates, top-2)
    hf_mixtral = transformers.MixtralConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=128,
        num_local_experts=8,
    )
    mixtral_cfg = config_from_hf(hf_mixtral)
    assert mixtral_cfg.norm_topk is True and mixtral_cfg.experts_per_token == 2


# -- OLMo-2 family -------------------------------------------------------------


@pytest.fixture(scope="module")
def olmo2_model():
    cfg = transformers.Olmo2Config(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rope_theta=500000.0,
        rms_norm_eps=1e-6,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(29)
    model = transformers.Olmo2ForCausalLM(cfg)
    model.eval()
    return model


def test_olmo2_logits_match_transformers(olmo2_model):
    """OLMo-2's two deltas at once: post-norm-only blocks (no input norms —
    the raw residual feeds the projections, outputs normed before the add)
    and FULL-WIDTH q/k RMSNorm whose rms statistic spans all heads."""
    state = {k: v.float().numpy() for k, v in olmo2_model.state_dict().items()}
    config = config_from_hf(olmo2_model.config, name="tiny-olmo2")
    assert not config.pre_norms and config.post_norms and config.qk_norm_full
    params = params_from_state_dict(state, config, dtype=jnp.float32)
    assert "attn_norm" not in params["layers"] and "mlp_norm" not in params["layers"]
    assert params["layers"]["q_norm_full"].shape[-1] == config.n_heads * config.head_dim
    assert params["layers"]["k_norm_full"].shape[-1] == config.n_kv_heads * config.head_dim

    tokens = np.array([[3, 17, 200, 45, 9, 88, 121, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_logits = olmo2_model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    our_logits, _ = forward(params, jnp.asarray(tokens), config)
    np.testing.assert_allclose(np.asarray(our_logits), hf_logits, rtol=5e-4, atol=5e-4)


def test_olmo2_decode_matches_transformers_generation(olmo2_model):
    import jax

    from prime_tpu.models.sampler import generate

    state = {k: v.float().numpy() for k, v in olmo2_model.state_dict().items()}
    config = config_from_hf(olmo2_model.config, name="tiny-olmo2")
    params = params_from_state_dict(state, config, dtype=jnp.float32)

    prompt = np.array([[5, 42, 100, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_out = olmo2_model.generate(
            torch.tensor(prompt, dtype=torch.long),
            max_new_tokens=8, do_sample=False, eos_token_id=None, pad_token_id=0,
        ).numpy()[0, 4:]
    result = generate(
        params, jnp.asarray(prompt), jnp.array([4]), config,
        jax.random.PRNGKey(0), max_new_tokens=8, temperature=0.0,
    )
    np.testing.assert_array_equal(np.asarray(result.tokens[0]), hf_out)


# -- Phi-3 family --------------------------------------------------------------


@pytest.fixture(scope="module")
def phi3_model():
    cfg = transformers.Phi3Config(
        vocab_size=32064,          # Phi3Config pins padding_idx at 32000
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(23)
    model = transformers.Phi3ForCausalLM(cfg)
    model.eval()
    return model


def test_phi3_fused_projections_logits_match_transformers(phi3_model):
    """Phi3 fuses q/k/v into qkv_proj and gate/up into gate_up_proj — the
    loader's row-slice split must reproduce transformers logits exactly."""
    state = {k: v.float().numpy() for k, v in phi3_model.state_dict().items()}
    config = config_from_hf(phi3_model.config, name="tiny-phi3")
    params = params_from_state_dict(state, config, dtype=jnp.float32)
    assert params["layers"]["wq"].shape[-1] == config.n_heads * config.head_dim
    assert params["layers"]["wk"].shape[-1] == config.n_kv_heads * config.head_dim

    tokens = np.array([[3, 17, 200, 45, 9, 88, 121, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_logits = phi3_model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    our_logits, _ = forward(params, jnp.asarray(tokens), config)
    np.testing.assert_allclose(np.asarray(our_logits), hf_logits, rtol=5e-4, atol=5e-4)


def test_phi3_decode_matches_transformers_generation(phi3_model):
    import jax

    from prime_tpu.models.sampler import generate

    state = {k: v.float().numpy() for k, v in phi3_model.state_dict().items()}
    config = config_from_hf(phi3_model.config, name="tiny-phi3")
    params = params_from_state_dict(state, config, dtype=jnp.float32)

    prompt = np.array([[5, 42, 100, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_out = phi3_model.generate(
            torch.tensor(prompt, dtype=torch.long),
            max_new_tokens=8, do_sample=False, eos_token_id=None, pad_token_id=0,
        ).numpy()[0, 4:]
    result = generate(
        params, jnp.asarray(prompt), jnp.array([4]), config,
        jax.random.PRNGKey(0), max_new_tokens=8, temperature=0.0,
    )
    np.testing.assert_array_equal(np.asarray(result.tokens[0]), hf_out)


def test_phi3_partial_rotary_carried():
    """partial_rotary_factor is supported (round 4): the config carries it
    and only head_dim*factor features rotate (parity pinned by
    test_phi3_longrope_and_partial_rotary_match_transformers)."""
    from prime_tpu.models.hf_loader import config_from_hf

    class Cfg:
        model_type = "phi3"
        vocab_size = 128
        hidden_size = 64
        num_hidden_layers = 2
        num_attention_heads = 4
        num_key_value_heads = 2
        intermediate_size = 128
        partial_rotary_factor = 0.75

    assert config_from_hf(Cfg()).partial_rotary == 0.75


def test_llama3_rope_scaling_logits_match_transformers():
    """Llama 3.1/3.2 checkpoints carry rope_scaling {"rope_type": "llama3"}
    (frequency-dependent smoothing, NOT linear) — the loader must reproduce
    transformers' scaled frequencies exactly, at positions long enough that
    the low/medium/high frequency bands all genuinely differ."""
    cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        rope_theta=10000.0,
        rope_scaling={
            "rope_type": "llama3",
            "factor": 8.0,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 64,
        },
        attn_implementation="eager",
    )
    torch.manual_seed(19)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    config = config_from_hf(model.config, name="tiny-llama31")
    assert config.rope_llama3 == (8.0, 1.0, 4.0, 64.0)
    assert config.rope_scale == 1.0
    state = {k: v.float().numpy() for k, v in model.state_dict().items()}
    params = params_from_state_dict(state, config, dtype=jnp.float32)

    # 100 tokens > original_max_position 64: the scaled bands are exercised
    tokens = np.arange(3, 103, dtype=np.int32)[None, :] % 256
    with torch.no_grad():
        hf_logits = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    our_logits, _ = forward(params, jnp.asarray(tokens), config)
    np.testing.assert_allclose(np.asarray(our_logits), hf_logits, rtol=5e-4, atol=5e-4)


def test_yarn_rope_scaling_logits_match_transformers():
    """YaRN (NTK-by-parts + attention temperature) checkpoints — Qwen
    long-context releases, GPT-OSS-style configs — must reproduce
    transformers' frequencies AND the cos/sin attention factor exactly,
    past the original pretraining window."""
    cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        rope_theta=10000.0,
        rope_scaling={
            "rope_type": "yarn",
            "factor": 4.0,
            "original_max_position_embeddings": 64,
        },
        attn_implementation="eager",
    )
    torch.manual_seed(31)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    config = config_from_hf(model.config, name="tiny-yarn")
    factor, beta_fast, beta_slow, orig, att = config.rope_yarn
    assert (factor, beta_fast, beta_slow, orig) == (4.0, 32.0, 1.0, 64.0)
    assert att == pytest.approx(0.1 * np.log(4.0) + 1.0)
    state = {k: v.float().numpy() for k, v in model.state_dict().items()}
    params = params_from_state_dict(state, config, dtype=jnp.float32)

    # 100 tokens > original window 64: interpolated dims genuinely bite
    tokens = np.arange(5, 105, dtype=np.int32)[None, :] % 256
    with torch.no_grad():
        hf_logits = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    our_logits, _ = forward(params, jnp.asarray(tokens), config)
    np.testing.assert_allclose(np.asarray(our_logits), hf_logits, rtol=5e-4, atol=5e-4)


def test_yarn_truncate_false_carried():
    """Non-truncated yarn is supported (round 4, GPT-OSS ships it): the
    fractional correction bounds ride the config instead of being rejected."""
    from prime_tpu.models.hf_loader import config_from_hf

    class Cfg:
        model_type = "llama"
        vocab_size = 128
        hidden_size = 64
        num_hidden_layers = 2
        num_attention_heads = 4
        intermediate_size = 128
        rope_scaling = {"rope_type": "yarn", "factor": 4.0, "truncate": False}

    config = config_from_hf(Cfg())
    assert config.rope_yarn is not None and config.rope_yarn_truncate is False
    # truncate defaults True when absent
    Cfg.rope_scaling = {"rope_type": "yarn", "factor": 4.0}
    assert config_from_hf(Cfg()).rope_yarn_truncate is True


def test_rope_scaling_default_accepted_and_long_context_capped():
    """HF's rope_scaling {"rope_type": "default"} means unscaled — it must
    load; non-linear types must not. max_position_embeddings is capped at 32k
    (the no-cache forward materializes rope tables at max_seq_len)."""
    from prime_tpu.models.hf_loader import config_from_hf

    class Cfg:
        model_type = "llama"
        vocab_size = 128
        hidden_size = 64
        num_hidden_layers = 2
        num_attention_heads = 4
        intermediate_size = 128
        max_position_embeddings = 131072

    Cfg.rope_scaling = {"rope_type": "default"}
    config = config_from_hf(Cfg())
    assert config.rope_scale == 1.0
    assert config.max_seq_len == 32768

    Cfg.rope_scaling = {"rope_type": "linear", "factor": 4.0}
    assert config_from_hf(Cfg()).rope_scale == 4.0

    Cfg.rope_scaling = {"rope_type": "yarn", "factor": 4.0}
    assert config_from_hf(Cfg()).rope_yarn is not None  # yarn now supported

    Cfg.rope_scaling = {"rope_type": "longrope", "factor": 4.0}
    with pytest.raises(ValueError, match="rope_scaling"):
        config_from_hf(Cfg())


def test_config_from_hf_rejects_unsupported_model_type():
    """ADVICE r2 (medium): families sharing Llama state-dict keys but needing
    different math (gemma v1, gemma3, phi3) must fail loudly, not load and
    silently produce garbage logits."""
    import pytest

    from prime_tpu.models.hf_loader import config_from_hf

    class Cfg:
        vocab_size = 128
        hidden_size = 64
        num_hidden_layers = 2
        num_attention_heads = 4
        intermediate_size = 256

    for bad in ("gemma", "falcon", "deepseek_v2"):  # v3 loads now (test_mla)
        Cfg.model_type = bad
        with pytest.raises(ValueError, match="Unsupported model_type"):
            config_from_hf(Cfg())
    for ok in ("llama", "mistral", "qwen2", "qwen3", "gemma2", "gemma3_text", "phi3", ""):
        Cfg.model_type = ok
        config_from_hf(Cfg())  # must not raise


def test_config_from_hf_mistral_uniform_sliding():
    """Mistral v0.1-style configs slide EVERY layer — they must not inherit
    the Gemma2 even-layer alternation (ADVICE r2, llama.py:364)."""
    from prime_tpu.models.hf_loader import config_from_hf

    class Cfg:
        model_type = "mistral"
        vocab_size = 128
        hidden_size = 64
        num_hidden_layers = 2
        num_attention_heads = 4
        intermediate_size = 256
        sliding_window = 4096

    config = config_from_hf(Cfg())
    assert config.sliding_window == 4096 and config.sliding_pattern == "uniform"


def test_unknown_sliding_pattern_raises():
    import jax
    import jax.numpy as jnp
    import pytest

    from prime_tpu.models.config import ModelConfig
    from prime_tpu.models.llama import forward, init_params

    config = ModelConfig(
        name="t", vocab_size=64, d_model=32, n_layers=2, n_heads=2, n_kv_heads=2,
        d_ff=64, max_seq_len=64, sliding_window=8, sliding_pattern="every-third",
    )
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.float32)
    tokens = jnp.ones((1, 4), dtype=jnp.int32)
    with pytest.raises(ValueError, match="sliding_pattern"):
        forward(params, tokens, config, cache=None)


def test_moe_configs_get_dropless_headroom_capacity():
    """HF MoE checkpoints route dropless; the capacity-routing stack needs
    capacity_factor headroom (2.0, matching the hand-written qwen3-30b-a3b
    preset) or imbalance silently zeroes dropped tokens' expert output.
    Dense models keep the ModelConfig default."""
    from prime_tpu.models.hf_loader import config_from_hf

    class Cfg:
        model_type = "qwen3_moe"
        vocab_size = 128
        hidden_size = 64
        num_hidden_layers = 2
        num_attention_heads = 4
        num_key_value_heads = 2
        intermediate_size = 128
        moe_intermediate_size = 48
        num_experts = 16

    assert config_from_hf(Cfg()).capacity_factor == 2.0
    hf_mixtral = transformers.MixtralConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=128,
        num_local_experts=8,
    )
    assert config_from_hf(hf_mixtral).capacity_factor == 2.0
    hf_dense = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=128,
    )
    assert config_from_hf(hf_dense).capacity_factor == 1.25


# -- GPT-OSS family ------------------------------------------------------------
# attention sinks + biased clamped-GLU MoE + even-alternating sliding window +
# non-truncated yarn (reference for WHAT to support: the HF gpt_oss family;
# math mirrored from transformers modeling_gpt_oss eager paths)


@pytest.fixture(scope="module")
def gptoss_model():
    cfg = transformers.GptOssConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=48,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        num_local_experts=4,
        num_experts_per_tok=2,
        sliding_window=8,
        max_position_embeddings=128,
        rope_theta=150000.0,
        rope_scaling={
            "rope_type": "yarn",
            "factor": 32.0,
            "beta_fast": 32.0,
            "beta_slow": 1.0,
            "truncate": False,
            "original_max_position_embeddings": 64,
        },
        layer_types=["sliding_attention", "full_attention"],
        attention_bias=True,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.GptOssForCausalLM(cfg)
    model.eval()
    return model


def test_gptoss_config_mapping(gptoss_model):
    config = config_from_hf(gptoss_model.config, name="tiny-gptoss-hf")
    assert config.attn_sinks and config.moe_bias and config.moe_glu_clamp == 7.0
    assert config.sliding_window == 8 and config.sliding_pattern == "even"
    assert config.rope_yarn is not None and config.rope_yarn_truncate is False
    assert config.n_experts == 4 and config.experts_per_token == 2
    assert config.attn_bias and config.attn_out_bias
    assert config.head_dim == 16


def test_gptoss_logits_match_transformers(gptoss_model):
    state = {k: v.float().numpy() for k, v in gptoss_model.state_dict().items()}
    config = config_from_hf(gptoss_model.config, name="tiny-gptoss-hf")
    # HF routes dropless on CPU; crank capacity so no token can drop and the
    # comparison isolates the sink/clamped-GLU/bias math itself
    config = config.scaled(capacity_factor=float(config.n_experts))
    params = params_from_state_dict(state, config, dtype=jnp.float32)

    tokens = np.array([[3, 17, 200, 45, 9, 88, 121, 7, 54, 33, 2, 99]], dtype=np.int32)
    with torch.no_grad():
        hf_logits = gptoss_model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    our_logits, _ = forward(params, jnp.asarray(tokens), config)
    np.testing.assert_allclose(np.asarray(our_logits), hf_logits, rtol=5e-4, atol=5e-4)


def test_gptoss_greedy_decode_matches_transformers(gptoss_model):
    from prime_tpu.models.sampler import generate

    state = {k: v.float().numpy() for k, v in gptoss_model.state_dict().items()}
    config = config_from_hf(gptoss_model.config, name="tiny-gptoss-hf")
    config = config.scaled(capacity_factor=float(config.n_experts))
    params = params_from_state_dict(state, config, dtype=jnp.float32)

    prompt = np.array([[5, 42, 100, 7, 61]], dtype=np.int32)
    with torch.no_grad():
        hf_out = gptoss_model.generate(
            torch.tensor(prompt, dtype=torch.long), max_new_tokens=8, do_sample=False
        ).numpy()[:, prompt.shape[1]:]
    import jax

    result = generate(
        params, jnp.asarray(prompt), jnp.asarray([prompt.shape[1]], dtype=jnp.int32),
        config, jax.random.PRNGKey(0), max_new_tokens=8, temperature=0.0,
    )
    assert np.asarray(result.tokens)[0].tolist() == hf_out[0].tolist()


def test_gptoss_rejects_non_alternating_layer_types(gptoss_model):
    import copy

    cfg = copy.deepcopy(gptoss_model.config)
    cfg.layer_types = ["full_attention", "sliding_attention"]
    with pytest.raises(ValueError, match="even-alternating"):
        config_from_hf(cfg)


# -- Phi-3.5: longrope + partial rotary ---------------------------------------


def test_phi3_longrope_and_partial_rotary_match_transformers():
    """Phi3 with longrope scaling AND a partial rotary factor: logits parity
    proves the per-dim frequency rescale, the attention temperature, and the
    rotate-first-dims-only application all match HF."""
    cfg = transformers.Phi3Config(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=96,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        partial_rotary_factor=0.5,
        max_position_embeddings=256,
        original_max_position_embeddings=64,
        rope_theta=10000.0,
        rope_scaling={
            "type": "longrope",
            "short_factor": [1.0 + 0.1 * i for i in range(4)],
            "long_factor": [2.0 + 0.5 * i for i in range(4)],
        },
        sliding_window=None,
        tie_word_embeddings=False,
        attn_implementation="eager",
        pad_token_id=0,  # default 32000 would index past the tiny vocab
        bos_token_id=1,
        eos_token_id=2,
    )
    torch.manual_seed(1)
    model = transformers.Phi3ForCausalLM(cfg)
    model.eval()
    state = {k: v.float().numpy() for k, v in model.state_dict().items()}
    config = config_from_hf(cfg, name="tiny-phi35")
    assert config.partial_rotary == 0.5
    assert config.rope_longrope is not None
    params = params_from_state_dict(state, config, dtype=jnp.float32)

    tokens = np.array([[3, 17, 200, 45, 9, 88, 121, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_logits = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    our_logits, _ = forward(params, jnp.asarray(tokens), config)
    np.testing.assert_allclose(np.asarray(our_logits), hf_logits, rtol=5e-4, atol=5e-4)


def test_longrope_factor_semantics_match_hf():
    """HF's _compute_longrope_parameters reads original_max_position_embeddings
    ONLY from the config top level (a rope_scaling-nested copy is ignored) and
    falls back to the rope_scaling 'factor' key for the attention temperature.
    The loader must mirror that exactly or logits silently diverge."""
    import math

    from prime_tpu.models.hf_loader import config_from_hf

    class Cfg:
        model_type = "llama"
        vocab_size = 128
        hidden_size = 64
        num_hidden_layers = 2
        num_attention_heads = 4
        intermediate_size = 128
        max_position_embeddings = 4096
        rope_scaling = {
            "rope_type": "longrope",
            "short_factor": [1.0] * 8,
            "long_factor": [2.0] * 8,
            "factor": 4.0,
            # HF IGNORES this nested key — so must we
            "original_max_position_embeddings": 64,
        }

    config = config_from_hf(Cfg())
    short, long, original_max, attention_factor = config.rope_longrope
    assert original_max == 4096.0  # NOT the nested 64
    assert attention_factor == pytest.approx(
        math.sqrt(1.0 + math.log(4.0) / math.log(4096.0))
    )

    # with a top-level original_max, the temperature derives from the ratio
    # and the factor key is ignored (Phi3 behavior)
    Cfg2 = type("Cfg2", (), dict(vars(Cfg)))
    Cfg2.original_max_position_embeddings = 1024
    _, _, original_max2, attention_factor2 = config_from_hf(Cfg2()).rope_longrope
    assert original_max2 == 1024.0
    assert attention_factor2 == pytest.approx(
        math.sqrt(1.0 + math.log(4096.0 / 1024.0) / math.log(1024.0))
    )
