"""Evals SDK + native JAX runner tests against the fake hub."""

import json

import pytest

from prime_tpu.core.client import APIClient, AsyncAPIClient
from prime_tpu.core.config import Config
from prime_tpu.evals import AsyncEvalsClient, CreateEvaluationRequest, EvalsClient
from prime_tpu.evals.client import build_batches
from prime_tpu.evals.datasets import (
    extract_gold_answer,
    normalize_number,
    score_completion,
    synthetic_arithmetic,
)
from prime_tpu.evals.runner import EvalRunSpec, find_latest_run, push_eval_results, run_eval
from prime_tpu.testing import FakeControlPlane

from _markers import requires_shard_map


@pytest.fixture
def fake():
    return FakeControlPlane()


@pytest.fixture
def client(fake):
    cfg = Config()
    cfg.api_key = "test-key"
    return EvalsClient(APIClient(config=cfg, base_url="https://api.fake", transport=fake.transport))


# -- scoring -----------------------------------------------------------------


@pytest.mark.parametrize(
    "text,expected",
    [
        ("The answer is 42.", "42"),
        ("costs $1,234 total", "1234"),
        ("= 3.5 exactly", "3.5"),
        ("first 12 then 99", "99"),
        ("no numbers here", None),
    ],
)
def test_normalize_number(text, expected):
    assert normalize_number(text) == expected


def test_gold_answer_extraction():
    assert extract_gold_answer("Step 1... #### 1,234") == "1234"
    assert score_completion("so the total is 72", "72")
    assert not score_completion("so the total is 71", "72")


# -- SDK ---------------------------------------------------------------------


def test_env_get_or_create_and_resolution(client, fake):
    env1 = client.resolve_environment("gsm8k")
    env2 = client.resolve_environment("gsm8k")
    assert env1.env_id == env2.env_id  # second call found, not re-created
    by_id = client.resolve_environment(env1.env_id)
    assert by_id.name == "gsm8k"
    by_slug = client.resolve_environment("user_1/gsm8k")
    assert by_slug.env_id == env1.env_id


def test_eval_lifecycle_and_push(client, fake):
    evaluation = client.create_evaluation(CreateEvaluationRequest(env="gsm8k", model="llama3-8b"))
    assert evaluation.status == "RUNNING"
    n = client.push_samples(
        evaluation.eval_id,
        [{"sampleId": f"s{i}", "completion": f"c{i}", "correct": i % 2 == 0} for i in range(10)],
    )
    assert n == 10
    final = client.finalize_evaluation(evaluation.eval_id, {"accuracy": 0.5})
    assert final.status == "FINALIZED" and final.metrics["accuracy"] == 0.5
    assert len(client.get_samples(evaluation.eval_id)) == 10


def test_build_batches_respects_size_cap():
    samples = [{"completion": "x" * 1000} for _ in range(100)]
    batches = build_batches(samples, max_bytes=10_500)
    assert len(batches) > 1
    assert sum(len(b) for b in batches) == 100
    for batch in batches:
        assert len(json.dumps(batch)) <= 10_500 + 1100  # one-sample slack


def test_push_samples_retries_429(client, fake):
    evaluation = client.create_evaluation(CreateEvaluationRequest(env="e", model="m"))
    fake.evals_plane.rate_limit_next = 2
    n = client.push_samples(evaluation.eval_id, [{"sampleId": "a"}])
    assert n == 1
    assert fake.evals_plane.upload_posts >= 3  # 2 rate-limited + 1 success
    assert len(fake.evals_plane.samples[evaluation.eval_id]) == 1


def test_push_samples_parallel_batches(client, fake):
    evaluation = client.create_evaluation(CreateEvaluationRequest(env="e", model="m"))
    samples = [{"sampleId": f"s{i}", "completion": "y" * 100} for i in range(50)]
    posts_before = fake.evals_plane.upload_posts
    progress_calls = []
    n = client.push_samples(
        evaluation.eval_id,
        samples,
        max_batch_bytes=2000,
        progress=lambda done, total: progress_calls.append((done, total)),
    )
    assert n == 50
    batches_sent = fake.evals_plane.upload_posts - posts_before
    assert batches_sent > 3  # the cap really split the upload
    assert progress_calls[-1] == (batches_sent, batches_sent)
    assert len(fake.evals_plane.samples[evaluation.eval_id]) == 50


@pytest.mark.anyio
async def test_async_client_mirror(fake):
    cfg = Config()
    cfg.api_key = "test-key"
    client = AsyncEvalsClient(
        AsyncAPIClient(config=cfg, base_url="https://api.fake", transport=fake.transport)
    )
    evaluation = await client.create_evaluation(CreateEvaluationRequest(env="async-env", model="m"))
    fake.evals_plane.rate_limit_next = 1
    n = await client.push_samples(evaluation.eval_id, [{"sampleId": f"s{i}"} for i in range(5)])
    assert n == 5
    final = await client.finalize_evaluation(evaluation.eval_id, {"accuracy": 1.0})
    assert final.status == "FINALIZED"
    await client.api.close()


# -- runner ------------------------------------------------------------------


class OracleGenerator:
    """Always answers correctly — pins the scoring/writing plumbing."""

    def __init__(self, examples):
        self.answers = {e.prompt: e.answer for e in examples}

    def generate(self, prompts, max_new_tokens, temperature):
        return [f"The answer is {self.answers[p]}." for p in prompts]


def test_run_eval_oracle_end_to_end(tmp_path, client, fake):
    examples = synthetic_arithmetic(10)
    spec = EvalRunSpec(env="arith", model="oracle", limit=10, batch_size=4, output_dir=str(tmp_path))
    result = run_eval(spec, generator=OracleGenerator(examples))
    assert result.metrics["accuracy"] == 1.0
    assert result.metrics["num_samples"] == 10

    # results contract: metadata.json + results.jsonl
    metadata = json.loads((result.run_dir / "metadata.json").read_text())
    assert metadata["env"] == "arith" and metadata["metrics"]["accuracy"] == 1.0
    lines = (result.run_dir / "results.jsonl").read_text().strip().splitlines()
    assert len(lines) == 10
    assert json.loads(lines[0])["correct"] is True

    # discovery + hub push
    latest = find_latest_run(tmp_path)
    assert latest == result.run_dir
    eval_id, metrics = push_eval_results(latest, client)
    assert metrics["accuracy"] == 1.0
    assert fake.evals_plane.evaluations[eval_id]["status"] == "FINALIZED"
    assert len(fake.evals_plane.samples[eval_id]) == 10


def test_run_eval_with_jax_generator(tmp_path):
    """Full native path: tiny model + byte tokenizer (random weights — the
    pipeline is what's under test, accuracy will be ~0)."""
    spec = EvalRunSpec(
        env="arith",
        model="tiny-test",
        limit=4,
        batch_size=2,
        max_new_tokens=8,
        output_dir=str(tmp_path),
    )
    result = run_eval(spec)
    assert result.metrics["num_samples"] == 4
    assert result.metrics["samples_per_sec"] > 0
    assert (result.run_dir / "results.jsonl").exists()
    completions = [s.completion for s in result.samples]
    assert all(isinstance(c, str) for c in completions)


def test_missing_checkpoint_raises(tmp_path):
    from prime_tpu.evals.runner import JaxGenerator

    with pytest.raises(ValueError, match="does not exist"):
        JaxGenerator("llama3-8b", checkpoint=str(tmp_path / "nope"))


def test_bad_tokenizer_name_raises():
    from prime_tpu.evals.tokenizer import load_tokenizer

    with pytest.raises(ValueError, match="Could not load tokenizer"):
        load_tokenizer("meta-lama/definitely-not-a-tokenizer")


def test_max_new_tokens_bound(tmp_path):
    from prime_tpu.evals.runner import JaxGenerator

    gen = JaxGenerator("tiny-test")
    with pytest.raises(ValueError, match="max_new_tokens"):
        gen.generate(["hi"], max_new_tokens=600, temperature=0.0)


@requires_shard_map
def test_run_eval_sharded_slice(tmp_path):
    """North-star shape: eval run with --slice shards the generator over the
    (virtual) v5e-8 mesh and still writes the results contract."""
    spec = EvalRunSpec(
        env="arith",
        model="tiny-test",
        limit=4,
        batch_size=3,  # deliberately not divisible by the data axes
        max_new_tokens=8,
        output_dir=str(tmp_path),
        slice_name="v5e-8",
    )
    result = run_eval(spec)
    assert result.metrics["num_samples"] == 4
    assert (result.run_dir / "results.jsonl").exists()


def test_checkpoint_without_tokenizer_errors_not_byte_fallback(tmp_path):
    """A real checkpoint whose tokenizer can't load must be an error — a
    silent byte fallback would score garbage as results (VERDICT r1 weak #4)."""
    import json as _json

    from prime_tpu.evals.runner import JaxGenerator

    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    (ckpt / "config.json").write_text(_json.dumps({
        "vocab_size": 64, "hidden_size": 32, "num_hidden_layers": 1,
        "num_attention_heads": 4, "num_key_value_heads": 2,
        "intermediate_size": 64, "rms_norm_eps": 1e-5,
    }))
    # no tokenizer files and no weights: tokenizer failure must surface first
    with pytest.raises(ValueError, match="Could not load tokenizer"):
        JaxGenerator("some-model", checkpoint=str(ckpt))


def test_run_eval_with_kv_quant(tmp_path):
    spec = EvalRunSpec(
        env="arith",
        model="tiny-test",
        limit=2,
        batch_size=2,
        max_new_tokens=6,
        output_dir=str(tmp_path),
        kv_quant=True,
    )
    result = run_eval(spec)
    assert result.metrics["num_samples"] == 2


def test_run_eval_with_weight_quant(tmp_path):
    spec = EvalRunSpec(
        env="arith",
        model="tiny-test",
        limit=2,
        batch_size=2,
        max_new_tokens=6,
        output_dir=str(tmp_path),
        weight_quant=True,
        kv_quant=True,
    )
    result = run_eval(spec)
    assert result.metrics["num_samples"] == 2


@requires_shard_map
def test_run_eval_sequence_parallel_slot_sharded_cache(tmp_path):
    """eval run --slice --sp: the KV cache's slot axis shards over sp and
    the whole eval pipeline still produces results (long-context serving
    building block through the real runner)."""
    from prime_tpu.evals.runner import EvalRunSpec, run_eval

    spec = EvalRunSpec(
        env="synthetic-arith", model="tiny-test", limit=4, batch_size=4,
        max_new_tokens=8, output_dir=str(tmp_path),
        slice_name="v5e-8", tensor_parallel=1, sequence_parallel=4,
    )
    result = run_eval(spec)
    assert result.metrics["num_samples"] == 4
    assert (result.run_dir / "results.jsonl").exists()


def test_sequence_parallel_without_slice_rejected():
    """--sp must not be silently dropped: without a slice (or with an
    explicit mesh) the generator refuses instead of serving unsharded."""
    import pytest as _pytest

    from prime_tpu.evals.runner import JaxGenerator

    with _pytest.raises(ValueError, match="sequence_parallel needs slice_name"):
        JaxGenerator("tiny-test", sequence_parallel=4)
