"""LoRA adapters: zero-effect init, frozen base, artifacts, sharding, CLI."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from prime_tpu.models import get_config
from prime_tpu.models.llama import forward, init_params
from prime_tpu.train.lora import (
    LoraConfig,
    init_lora_params,
    init_lora_state,
    load_adapters,
    lora_param_specs,
    make_lora_train_step,
    merge_lora,
    save_adapters,
    shard_lora_state,
)
from prime_tpu.train.trainer import default_optimizer

CFG = get_config("tiny-test")


@pytest.fixture()
def params():
    return init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)


def test_config_validation():
    with pytest.raises(ValueError, match="rank"):
        LoraConfig(r=0)
    with pytest.raises(ValueError, match="targets"):
        LoraConfig(targets=("wq", "nope"))
    assert LoraConfig(r=8, alpha=16).scale == 2.0


def test_zero_init_merge_is_identity(params):
    lora = LoraConfig(r=4)
    adapters = init_lora_params(jax.random.PRNGKey(1), CFG, lora)
    merged = merge_lora(params, adapters, lora)
    tokens = jnp.asarray([[3, 7, 11, 2]], dtype=jnp.int32)
    ref, _ = forward(params, tokens, CFG)
    out, _ = forward(merged, tokens, CFG)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6, atol=1e-6)


def test_lora_step_trains_adapters_and_freezes_base(params):
    lora = LoraConfig(r=4, alpha=8)
    optimizer = default_optimizer(1e-2, weight_decay=0.0)
    adapters = init_lora_params(jax.random.PRNGKey(1), CFG, lora)
    state = init_lora_state(adapters, optimizer)
    step = make_lora_train_step(CFG, lora, optimizer)
    base_before = jax.tree.map(jnp.copy, params)

    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0, CFG.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones_like(tokens, jnp.float32)
    losses = []
    for _ in range(6):
        state, metrics = step(state, params, tokens, targets, mask)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], f"LoRA loss did not decrease: {losses}"
    # base weights untouched (only adapters are in the optimizer state)
    for a, b in zip(jax.tree.leaves(base_before), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # B factors moved off zero
    assert float(jnp.abs(state.params["layers"]["wq"]["b"]).max()) > 0


def test_adapter_artifact_roundtrip(tmp_path, params):
    lora = LoraConfig(r=4, alpha=8, targets=("wq", "wo"))
    adapters = init_lora_params(jax.random.PRNGKey(3), CFG, lora)
    # randomize B so the roundtrip carries real content
    adapters["layers"]["wq"]["b"] = jax.random.normal(
        jax.random.PRNGKey(4), adapters["layers"]["wq"]["b"].shape
    )
    path = save_adapters(tmp_path / "art", adapters, lora, CFG, base_params=params)
    loaded, lora2, meta = load_adapters(path)
    assert meta["base_model"] == CFG.name and lora2 == lora
    assert len(meta["base_fingerprint"]) == 6  # embed + wq + w_down moments
    for a, b in zip(jax.tree.leaves(adapters), jax.tree.leaves(loaded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    meta = json.loads((path / "adapter_config.json").read_text())
    assert meta["targets"] == ["wq", "wo"]


def test_lora_specs_mirror_base_axes():
    from jax.sharding import PartitionSpec as P

    lora = LoraConfig(targets=("wq", "wo", "w_down"))
    specs = lora_param_specs(CFG, lora)["layers"]
    assert specs["wq"] == {"a": P(None, "fsdp", None), "b": P(None, None, "tp")}
    assert specs["wo"] == {"a": P(None, "tp", None), "b": P(None, None, "fsdp")}
    assert specs["w_down"] == {"a": P(None, "tp", None), "b": P(None, None, "fsdp")}


def test_sharded_lora_step(params):
    from prime_tpu.parallel.mesh import make_mesh
    from prime_tpu.parallel.sharding import shard_batch, shard_params

    lora = LoraConfig(r=4)
    mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    optimizer = default_optimizer(1e-2, weight_decay=0.0)
    base = shard_params(params, mesh, CFG)
    adapters = init_lora_params(jax.random.PRNGKey(5), CFG, lora)
    state = shard_lora_state(init_lora_state(adapters, optimizer), mesh, CFG, lora)
    step = make_lora_train_step(CFG, lora, optimizer)

    tokens = jax.random.randint(jax.random.PRNGKey(6), (8, 16), 0, CFG.vocab_size)
    batch = tuple(
        shard_batch(x, mesh)
        for x in (tokens, jnp.roll(tokens, -1, 1), jnp.ones_like(tokens, jnp.float32))
    )
    state, metrics = step(state, base, *batch)
    assert np.isfinite(float(metrics["loss"]))


def test_train_local_lora_cli_and_eval_adapter(tmp_path):
    """train local --lora writes an adapter artifact that eval run --adapter
    merges (wrong-base adapters are refused)."""
    from click.testing import CliRunner

    from prime_tpu.commands.main import cli

    runner = CliRunner()
    result = runner.invoke(
        cli,
        ["train", "local", "-m", "tiny-test", "--steps", "4", "-b", "4",
         "--seq-len", "16", "--lora", "--lora-r", "4", "--lr", "1e-2",
         "--name", "lora-run", "--output-dir", str(tmp_path), "--output", "json"],
    )
    assert result.exit_code == 0, result.output
    payload = json.loads(result.output)
    adapter_dir = payload["adapterDir"]
    assert (tmp_path / "lora-run" / "adapters" / "adapters.npz").exists()

    ev = runner.invoke(
        cli,
        ["eval", "run", "arith", "-m", "tiny-test", "--adapter", adapter_dir,
         "--no-push", "-n", "2", "-b", "2", "--max-new-tokens", "4",
         "--output-dir", str(tmp_path / "evals"), "--plain"],
    )
    assert ev.exit_code == 0, ev.output

    wrong = runner.invoke(
        cli,
        ["eval", "run", "arith", "-m", "tiny-moe", "--adapter", adapter_dir,
         "--no-push", "-n", "2", "--output-dir", str(tmp_path / "evals2"), "--plain"],
    )
    assert wrong.exit_code != 0 and "trained on" in wrong.output


def test_adapter_fingerprint_rejects_different_base(tmp_path, params):
    """Same config name, different base weights (the random-init-vs-checkpoint
    trap): the merge must refuse based on the recorded fingerprint."""
    import jax.numpy as jnp

    from prime_tpu.evals.runner import JaxGenerator
    from prime_tpu.train.lora import base_fingerprint, fingerprints_match

    other = init_params(jax.random.PRNGKey(99), CFG, dtype=jnp.float32)
    assert not fingerprints_match(base_fingerprint(params), base_fingerprint(other))

    lora = LoraConfig(r=4)
    adapters = init_lora_params(jax.random.PRNGKey(1), CFG, lora)
    path = save_adapters(tmp_path / "art", adapters, lora, CFG, base_params=other)
    # JaxGenerator("tiny-test") random-inits with PRNGKey(0) -> mismatch
    with pytest.raises(ValueError, match="DIFFERENT base weights"):
        JaxGenerator("tiny-test", adapter=str(path))


def test_adapter_fingerprint_tolerates_dtype(params):
    """bf16 and fp32 loads of the same weights must fingerprint-match."""
    import jax.numpy as jnp

    from prime_tpu.train.lora import base_fingerprint, fingerprints_match

    bf16 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
    assert fingerprints_match(base_fingerprint(params), base_fingerprint(bf16))


def test_fingerprint_covers_non_embedding_drift(params):
    """ADVICE r2: two checkpoints differing ONLY outside the embedding (e.g.
    an SFT variant with frozen embeddings) must fingerprint-mismatch."""
    import jax.numpy as jnp

    from prime_tpu.train.lora import base_fingerprint, fingerprints_match

    drifted = jax.tree.map(jnp.copy, params)
    drifted["layers"]["w_down"] = drifted["layers"]["w_down"] + 0.5
    assert not fingerprints_match(base_fingerprint(params), base_fingerprint(drifted))


def test_fingerprint_length_mismatch_fails():
    """Unknown-scheme length mismatches must fail (zip truncation must not
    silently weaken the check) — EXCEPT the legacy 2-moment scheme, which
    compares against the embed moments (first 2 elements) of the current
    fingerprint so pre-existing adapter artifacts stay loadable."""
    from prime_tpu.train.lora import fingerprints_match

    assert not fingerprints_match([1.0, 2.0, 3.0, 4.0], [1.0, 2.0, 3.0, 4.0, 5.0, 6.0])
    # legacy 2-element artifact vs current 6-element: embed moments decide
    assert fingerprints_match([1.0, 2.0], [1.0, 2.0, 9.0, 9.0, 9.0, 9.0])
    assert not fingerprints_match([5.0, 2.0], [1.0, 2.0, 9.0, 9.0, 9.0, 9.0])


def test_lora_on_moe_attention_trains_and_merges():
    """MoE configs adapt attention projections: zero-init merge is identity,
    a step moves only the adapters, and the loss carries the balance aux."""
    cfg = get_config("tiny-moe")
    moe_params = init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    lora = LoraConfig(r=4, alpha=8)
    adapters = init_lora_params(jax.random.PRNGKey(4), cfg, lora)
    tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 12), 0, cfg.vocab_size)

    base_logits, _, _ = forward(moe_params, tokens, cfg, return_aux=True)
    merged_logits, _, _ = forward(
        merge_lora(moe_params, adapters, lora), tokens, cfg, return_aux=True
    )
    np.testing.assert_allclose(
        np.asarray(base_logits), np.asarray(merged_logits), rtol=1e-5, atol=1e-5
    )

    opt = default_optimizer(1e-2)
    state = init_lora_state(adapters, opt)
    step = make_lora_train_step(cfg, lora, opt)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones(tokens.shape, jnp.float32)
    state, metrics = step(state, moe_params, tokens, targets, mask)
    assert jnp.isfinite(metrics["loss"])
    # adapters moved; the frozen base rode along untouched by construction
    assert float(jnp.abs(state.params["layers"]["wq"]["b"]).max()) > 0


def test_lora_rejects_moe_mlp_targets_and_mla():
    cfg = get_config("tiny-moe")
    with pytest.raises(NotImplementedError, match="expert MLPs"):
        init_lora_params(
            jax.random.PRNGKey(0), cfg,
            LoraConfig(r=4, targets=("wq", "w_down")),
        )
    mla_cfg = get_config("tiny-mla")
    with pytest.raises(NotImplementedError, match="MLA"):
        init_lora_params(jax.random.PRNGKey(0), mla_cfg, LoraConfig(r=4))
