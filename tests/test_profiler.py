"""Device-time observatory tests (CPU, tiny model).

The load-bearing property: with profiling off, the profiler adds ZERO
``jax.block_until_ready`` calls to the dispatch path — the engine's
one-chunk-deep overlap pipeline must be bit-identical to the pre-profiler
engine. Everything else (sampled step clock, compile spy, capture window,
Chrome-trace export, trace-sink rotation, CLI/endpoint surfaces) is the
observatory built on top of that guarantee.
"""

import json
import sys
import threading
import time

import httpx
import jax
import jax.numpy as jnp
import pytest

from prime_tpu.models import get_config
from prime_tpu.models.llama import init_params
from prime_tpu.obs import DeviceProfiler, Registry, chrome_trace
from prime_tpu.obs import profiler as profiler_mod
from prime_tpu.obs.metrics import lint_prometheus_text
from prime_tpu.obs.trace import Tracer
from prime_tpu.serve.engine import ContinuousBatchingEngine

CONFIG = get_config("tiny-test")
PARAMS = init_params(jax.random.PRNGKey(0), CONFIG, dtype=jnp.float32)


@pytest.fixture(autouse=True)
def _default_profiler_env(monkeypatch):
    """Pin the env-driven defaults: ambient profiling/rotation knobs must not
    flip these tests onto another code path."""
    for knob in (
        "PRIME_SERVE_OVERLAP", "PRIME_SERVE_WARMUP", "PRIME_SERVE_MESH",
        "PRIME_SERVE_SPEC", "PRIME_SERVE_PROFILE", "PRIME_SERVE_PROFILE_SAMPLE",
        "PRIME_TRACE_MAX_MB", "PRIME_TRACE_KEEP", "PRIME_FLEET_ADMIN_TOKEN",
    ):
        monkeypatch.delenv(knob, raising=False)


def make_engine(**kw) -> ContinuousBatchingEngine:
    kw.setdefault("max_slots", 2)
    kw.setdefault("capacity", 64)
    kw.setdefault("chunk", 4)
    kw.setdefault("prefix_cache_mb", 0)
    return ContinuousBatchingEngine(PARAMS, CONFIG, **kw)


def drain(engine, *requests, max_ticks=200):
    for _ in range(max_ticks):
        engine.tick()
        if all(r.done for r in requests):
            return
    raise AssertionError("requests did not finish")


def _counting_block_until_ready(monkeypatch):
    """Wrap jax.block_until_ready, splitting calls by origin: the profiler's
    fences (frames inside obs/profiler.py) vs everyone else's."""
    counts = {"profiler": 0, "other": 0}
    real = jax.block_until_ready

    def spy(x):
        caller = sys._getframe(1).f_code.co_filename
        key = "profiler" if caller.endswith("profiler.py") else "other"
        counts[key] += 1
        return real(x)

    monkeypatch.setattr(jax, "block_until_ready", spy)
    return counts


# ---- the overhead guard ------------------------------------------------------


def test_profiling_off_adds_zero_syncs(monkeypatch):
    """Profiling off: every dispatch site gets the shared allocation-free
    no-op handle and the profiler contributes ZERO block_until_ready calls
    to a full request lifecycle (prefill + decode + finish)."""
    engine = make_engine()
    assert engine.profiler.active is False
    assert engine.profiler.step("decode") is profiler_mod._NULL_STEP
    assert engine.profiler.mark("warmup") is profiler_mod._NULL_STEP

    counts = _counting_block_until_ready(monkeypatch)
    req = engine.submit([5, 9, 301, 42], max_new_tokens=8)
    drain(engine, req)
    assert req.done
    assert counts["profiler"] == 0
    # sanity that the spy itself works: a sampled step from an armed
    # profiler is attributed to profiler.py
    prof = DeviceProfiler(Registry(), enabled=True, sample_every=1)
    with prof.step("decode", pre=jnp.zeros(())) as handle:
        handle.fence(jnp.zeros(()))
    prof.close()
    assert counts["profiler"] > 0


def test_profiling_on_fences_sampled_dispatches(monkeypatch):
    """PRIME_SERVE_PROFILE_SAMPLE=1 + profile=True: every dispatch is fenced
    by the profiler and the step clock fills per-phase."""
    monkeypatch.setenv("PRIME_SERVE_PROFILE_SAMPLE", "1")
    engine = make_engine(profile=True)
    assert engine.profile_enabled and engine.profiler.active
    counts = _counting_block_until_ready(monkeypatch)
    req = engine.submit([5, 9, 301, 42], max_new_tokens=8)
    drain(engine, req)
    assert counts["profiler"] > 0

    summary = engine.profiler.summary()
    assert summary["sample_every"] == 1
    phases = summary["phases"]
    assert phases["decode"]["samples"] > 0
    assert phases["decode"]["total_s"] > 0
    assert phases["prefill"]["samples"] >= 1
    # CPU backend: no roofline, so no MFU claims
    assert summary["peak_tflops"] is None
    assert "mfu" not in phases["decode"]
    # the compile spy attributed this engine's jit cache misses to phases
    assert summary["compiles"]["total"] > 0
    assert summary["compiles"]["seconds"] > 0

    # the metric families made it into clean Prometheus exposition
    text = engine.registry.render_prometheus()
    assert 'serve_device_step_seconds_count{phase="decode"' in text
    assert "serve_compiles_total" in text
    assert lint_prometheus_text(text) == []


def test_sampling_rate_limits_fences():
    """N-of-M: with sample_every=4 only every 4th dispatch of a phase is
    fenced; the rest get phase markers (no fence, no record)."""
    prof = DeviceProfiler(Registry(), enabled=True, sample_every=4)
    kinds = []
    for _ in range(8):
        handle = prof.step("decode")
        kinds.append(type(handle).__name__)
        with handle:
            handle.fence(jnp.zeros(()))
    assert kinds.count("_SampledStep") == 2
    assert kinds.count("_PhaseStep") == 6
    assert prof.summary()["phases"]["decode"]["samples"] == 2
    prof.close()


# ---- capture window + Chrome trace ------------------------------------------


def test_capture_window_fences_everything_and_exports_trace():
    """A capture window arms even a disabled profiler: every dispatch in the
    window is fenced and the stop payload carries a Perfetto-loadable
    Chrome trace merging device samples, compiles, and host spans."""
    engine = make_engine()
    assert engine.profiler.enabled is False
    assert engine.profiler.start_capture()
    assert not engine.profiler.start_capture()  # already open
    req = engine.submit([3, 1, 4, 1, 5], max_new_tokens=6)
    drain(engine, req)
    capture = engine.profiler.stop_capture()
    assert engine.profiler.stop_capture() is None  # window closed

    assert capture["samples"] > 0
    assert capture["duration_s"] > 0
    assert capture["summary"]["phases"]["decode"]["samples"] > 0
    _validate_chrome_trace(capture["trace"])
    # the engine's own serve.* spans from the window rode along on pid 1
    names = {e["name"] for e in capture["trace"]["traceEvents"]}
    assert any(n.startswith("device.") for n in names)
    # once the window closes, dispatches return to the free no-op path
    assert engine.profiler.step("decode") is profiler_mod._NULL_STEP


def _validate_chrome_trace(trace: dict) -> None:
    """Chrome-trace schema: X/M events only, int pid/tid, non-negative
    ts/dur microseconds, and per-(pid, tid) monotonic timestamps."""
    events = trace["traceEvents"]
    assert isinstance(events, list) and events
    last_ts: dict[tuple, float] = {}
    for event in events:
        assert event["ph"] in ("X", "M"), event
        assert isinstance(event["pid"], int)
        assert isinstance(event["tid"], int)
        assert isinstance(event["name"], str) and event["name"]
        if event["ph"] == "M":
            continue
        assert event["ts"] >= 0
        assert event["dur"] >= 0
        key = (event["pid"], event["tid"])
        assert event["ts"] >= last_ts.get(key, 0.0), "track not monotonic"
        last_ts[key] = event["ts"]
    assert trace["displayTimeUnit"] == "ms"


def test_chrome_trace_merges_three_sources():
    device = [
        {"phase": "decode", "start_s": 10.0, "duration_s": 0.002, "batch": 2, "steps": 1},
        {"phase": "decode", "start_s": 10.01, "duration_s": 0.001, "batch": 2, "steps": 1},
        {"phase": "prefill", "start_s": 10.005, "duration_s": 0.004, "batch": 1, "steps": 1},
    ]
    compiles = [{"phase": "decode", "start_s": 9.5, "duration_s": 0.4}]
    host = [
        {"name": "serve.request", "start_s": 9.9, "duration_s": 0.15,
         "attrs": {"tokens": 6}},
    ]
    trace = chrome_trace(device, compiles, host, base_s=9.0, base_unix_s=1234.5)
    _validate_chrome_trace(trace)
    events = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in events} == {1, 2}
    by_name = {}
    for e in events:
        by_name.setdefault(e["name"], []).append(e)
    assert len(by_name["device.decode"]) == 2
    # both decode samples share one track; prefill and the compile get their own
    assert len({e["tid"] for e in by_name["device.decode"]}) == 1
    assert by_name["device.prefill"][0]["tid"] != by_name["device.decode"][0]["tid"]
    assert by_name["xla.compile"][0]["tid"] not in {
        by_name["device.prefill"][0]["tid"], by_name["device.decode"][0]["tid"],
    }
    # µs from base_s: serve.request starts 0.9s after the base
    assert by_name["serve.request"][0]["ts"] == pytest.approx(0.9e6)
    assert by_name["serve.request"][0]["dur"] == pytest.approx(0.15e6)
    assert trace["metadata"]["capture_start_unix_s"] == 1234.5
    # track-naming metadata exists for every device phase
    meta_names = {
        e["args"]["name"] for e in trace["traceEvents"] if e["ph"] == "M"
    }
    assert {"decode", "prefill", "xla compile"} <= meta_names


# ---- warmup breakdown --------------------------------------------------------


def test_warmup_program_family_breakdown():
    """warmup() splits its wall time into serve_warmup_program_seconds
    {program=...} — one observation per family block — alongside the
    existing end-to-end gauges."""
    engine = make_engine(warmup=True)
    programs = engine.warmup()
    assert programs > 0
    hist = engine._m_warmup_program_s
    decode = hist.series_snapshot(program="decode")
    chunk = hist.series_snapshot(program="chunk_prefill")
    finalize = hist.series_snapshot(program="finalize")
    assert decode["count"] >= 1
    assert chunk["count"] >= 1 and finalize["count"] >= 1
    # the family splits sum to (roughly, <= because gaps exist) the gauge;
    # families this config never runs (spec off, prefix cache off) have no
    # series at all
    snaps = [
        hist.series_snapshot(program=p)
        for p in ("decode", "spec", "hist_seed", "chunk_prefill", "finalize", "assemble")
    ]
    total = sum(s["sum"] for s in snaps if s is not None)
    assert 0 < total <= engine._m_warmup_s.value() * 1.05 + 0.05


# ---- trace-sink rotation -----------------------------------------------------


def test_trace_sink_rotation_caps_live_file(tmp_path):
    sink = tmp_path / "trace.jsonl"
    cap_bytes = 4096
    tracer = Tracer(sink_path=sink, max_mb=cap_bytes / (1024 * 1024), keep=2)
    for i in range(200):
        with tracer.span("serve.request", idx=i, pad="x" * 64):
            pass
    tracer.close()
    assert sink.exists()
    rotated = tmp_path / "trace.jsonl.1"
    assert rotated.exists(), "sink never rotated under a 4KiB cap"
    assert not (tmp_path / "trace.jsonl.3").exists()  # keep=2
    # the live file respects the cap (one line of slack for the overflow write)
    assert sink.stat().st_size <= cap_bytes + 512
    # every surviving file is intact JSONL
    for path in (sink, rotated):
        for line in path.read_text().splitlines():
            assert json.loads(line)["name"] == "serve.request"


def test_trace_sink_unlimited_by_default(tmp_path):
    sink = tmp_path / "trace.jsonl"
    tracer = Tracer(sink_path=sink)  # max_mb -> env default 0 = unlimited
    for _ in range(50):
        with tracer.span("s"):
            pass
    tracer.close()
    assert not (tmp_path / "trace.jsonl.1").exists()
    assert len(sink.read_text().splitlines()) == 50


def test_tracer_tail_is_non_destructive():
    tracer = Tracer()
    with tracer.span("a"):
        pass
    assert [s["name"] for s in tracer.tail()] == ["a"]
    assert [s["name"] for s in tracer.tail()] == ["a"]  # still there
    assert [s["name"] for s in tracer.drain()] == ["a"]  # drain still clears
    assert tracer.tail() == []


# ---- /admin/profile endpoint -------------------------------------------------


def _chat(url: str, text: str = "ab", tokens: int = 6) -> None:
    response = httpx.post(
        f"{url}/v1/chat/completions",
        json={"messages": [{"role": "user", "content": text}],
              "max_tokens": tokens},
        timeout=120,
    )
    assert response.status_code == 200


def _serving_engine():
    from prime_tpu.evals.tokenizer import ByteTokenizer
    from prime_tpu.serve.engine import EngineBackend

    engine = make_engine()
    return engine, EngineBackend(engine, ByteTokenizer())


def test_admin_profile_endpoint_capture_roundtrip(monkeypatch):
    from prime_tpu.obs.trace import TRACER
    from prime_tpu.serve import InferenceServer

    # ring-only tracing (no sink): the capture merges host spans from here
    monkeypatch.setattr(TRACER, "enabled", True)
    engine, backend = _serving_engine()
    with engine:
        with InferenceServer("tiny-test", backend, port=0) as srv:
            status = httpx.get(f"{srv.url}/admin/profile").json()
            assert status["enabled"] is False and status["capturing"] is False
            assert status["sample_every"] >= 1 and "summary" in status

            # stop without start -> 409
            response = httpx.post(
                f"{srv.url}/admin/profile", json={"action": "stop"}
            )
            assert response.status_code == 409
            # bad action -> 400
            response = httpx.post(
                f"{srv.url}/admin/profile", json={"action": "dance"}
            )
            assert response.status_code == 400

            started = httpx.post(
                f"{srv.url}/admin/profile", json={"action": "start"}
            ).json()
            assert started == {"capturing": True, "started": True}
            assert httpx.get(f"{srv.url}/admin/profile").json()["capturing"]

            _chat(srv.url)
            capture = httpx.post(
                f"{srv.url}/admin/profile", json={"action": "stop"}
            ).json()
            assert capture["samples"] > 0
            assert capture["summary"]["phases"]["decode"]["samples"] > 0
            _validate_chrome_trace(capture["trace"])
            # the HTTP hop's own host span landed in the merged timeline
            assert capture["host_spans"] > 0

            # new metric families expose cleanly after real traffic
            text = httpx.get(
                f"{srv.url}/metrics", params={"format": "prometheus"}
            ).text
            assert "serve_device_step_seconds" in text
            assert lint_prometheus_text(text) == []


def test_admin_profile_honors_admin_token():
    from prime_tpu.serve import InferenceServer

    engine, backend = _serving_engine()
    with engine:
        with InferenceServer(
            "tiny-test", backend, port=0, admin_token="sekrit"
        ) as srv:
            assert httpx.get(f"{srv.url}/admin/profile").status_code == 403
            assert (
                httpx.post(
                    f"{srv.url}/admin/profile", json={"action": "start"}
                ).status_code
                == 403
            )
            auth = {"Authorization": "Bearer sekrit"}
            assert (
                httpx.get(f"{srv.url}/admin/profile", headers=auth).status_code
                == 200
            )


def test_admin_profile_404_without_engine_profiler():
    """A non-engine generator has no profiler: the endpoint 404s instead of
    pretending a capture could work."""
    from prime_tpu.serve import InferenceServer

    class EchoGenerator:
        def generate(self, prompts, max_new_tokens, temperature, top_p=1.0):
            return ["ok"] * len(prompts)

    with InferenceServer("tiny-test", EchoGenerator(), port=0) as srv:
        assert httpx.get(f"{srv.url}/admin/profile").status_code == 404
        assert (
            httpx.post(
                f"{srv.url}/admin/profile", json={"action": "start"}
            ).status_code
            == 404
        )


# ---- prime serve profile (CLI) ----------------------------------------------


def test_serve_profile_cli_renders_breakdown_and_writes_trace(tmp_path):
    from click.testing import CliRunner

    from prime_tpu.commands.main import cli
    from prime_tpu.serve import InferenceServer

    engine, backend = _serving_engine()
    with engine:
        with InferenceServer("tiny-test", backend, port=0) as srv:
            # compile every program BEFORE the window: a cold tiny-test chat
            # spends ~1s in XLA compiles, which would swallow the whole
            # capture (the one in-flight sampled step then exits after stop)
            _chat(srv.url, tokens=4)
            _chat(srv.url, tokens=4)
            stop = threading.Event()

            def traffic():
                while not stop.is_set():
                    _chat(srv.url, tokens=4)
                    time.sleep(0.02)

            thread = threading.Thread(target=traffic, daemon=True)
            thread.start()
            try:
                trace_out = tmp_path / "trace.json"
                result = CliRunner().invoke(
                    cli,
                    [
                        "serve", "profile", "--url", srv.url,
                        "--seconds", "0.8", "--trace-out", str(trace_out),
                    ],
                )
            finally:
                stop.set()
                thread.join(timeout=30)
    assert result.exit_code == 0, result.output
    assert "Device time @" in result.output
    assert "decode" in result.output
    assert "no roofline for this backend" in result.output  # CPU: no MFU claim
    assert "Perfetto" in result.output
    trace = json.loads(trace_out.read_text())
    _validate_chrome_trace(trace)
    assert any(
        e["name"].startswith("device.") for e in trace["traceEvents"]
    )


def test_serve_profile_cli_unreachable_target_fails_cleanly():
    from click.testing import CliRunner

    from prime_tpu.commands.main import cli

    result = CliRunner().invoke(
        cli,
        ["serve", "profile", "--url", "http://127.0.0.1:9", "--seconds", "0.1"],
    )
    assert result.exit_code != 0
    assert "could not reach" in result.output


# ---- perf_delta integration --------------------------------------------------


def test_perf_delta_flattens_device_profile():
    from prime_tpu.loadgen.perf_delta import _device_profile_metrics

    profile = {
        "phases": {
            "decode": {"samples": 12, "total_s": 0.24, "mean_s": 0.02,
                       "mfu": 0.31, "achieved_tflops": 142.0,
                       "achieved_gbps": 88.5},
            "prefill": {"samples": 3, "total_s": 0.09, "mean_s": 0.03},
        },
        "compiles": {"total": 7, "seconds": 12.5},
    }
    metrics = _device_profile_metrics(profile)
    assert metrics["dp:decode step ms"] == 20.0
    assert metrics["dp:decode mfu"] == 0.31
    assert metrics["dp:decode tflops"] == 142.0
    assert metrics["dp:decode gb/s"] == 88.5
    assert metrics["dp:prefill step ms"] == 30.0
    assert metrics["dp:compiles"] == 7.0
    assert metrics["dp:compile s"] == 12.5
    # malformed sections flatten to nothing, never raise
    assert _device_profile_metrics({}) == {}
    assert _device_profile_metrics({"phases": {"x": "oops"}, "compiles": 3}) == {}


def test_perf_delta_tolerates_absent_device_profile():
    """A profiler-era round next to a pre-profiler baseline: the dp: rows
    render an em-dash for the baseline column, not an error."""
    from prime_tpu.loadgen.perf_delta import _round_from_record, delta_table

    old = _round_from_record(
        "BENCH_r01.json",
        {"schema": 2, "value": 10.0, "metric": "decode_tokens_per_sec"},
    )
    new = _round_from_record(
        "BENCH_r02.json",
        {
            "schema": 2, "value": 11.0, "metric": "decode_tokens_per_sec",
            "device_profile": {
                "phases": {"decode": {"samples": 5, "total_s": 0.1,
                                      "mean_s": 0.02}},
                "compiles": {"total": 3, "seconds": 4.0},
            },
        },
    )
    table = delta_table([old, new])
    dp_row = next(
        line for line in table.splitlines()
        if line.startswith("dp:decode step ms")
    )
    assert "—" in dp_row  # r01 never measured it
    assert "20" in dp_row  # r02 did
