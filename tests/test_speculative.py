"""Prompt-lookup speculative decoding: exact parity with plain greedy decode.

The whole point of greedy speculation is that it changes WHEN tokens are
computed, never WHICH tokens — so every test pins spec_generate's buffer,
lengths, and padding against sampler.generate token-for-token.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from prime_tpu.models import get_config
from prime_tpu.models.llama import init_params
from prime_tpu.models.sampler import generate
from prime_tpu.models.speculative import propose_ngram_drafts, spec_generate

from _markers import requires_set_mesh

CFG = get_config("tiny-test")


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)


def ref_and_spec(params, tokens, lengths, max_new, eos_id=-1, draft_len=4):
    ref = generate(
        params, tokens, lengths, CFG, jax.random.PRNGKey(1),
        max_new_tokens=max_new, temperature=0.0, eos_id=eos_id, pad_id=0,
        attn_impl="xla",
    )
    out = spec_generate(
        params, tokens, lengths, CFG,
        max_new_tokens=max_new, draft_len=draft_len, eos_id=eos_id, pad_id=0,
        attn_impl="xla",
    )
    return ref, out


def test_spec_matches_greedy_random_prompts(params):
    """Arbitrary prompts (drafts mostly rejected): emitted tokens identical."""
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 12), 1, CFG.vocab_size)
    lengths = jnp.asarray([12, 7, 9, 12], dtype=jnp.int32)
    ref, out = ref_and_spec(params, tokens, lengths, max_new=16)
    np.testing.assert_array_equal(np.asarray(out.tokens), np.asarray(ref.tokens))
    np.testing.assert_array_equal(np.asarray(out.lengths), np.asarray(ref.lengths))


def test_spec_matches_greedy_repetitive_prompts(params):
    """Highly periodic prompts (drafts mostly ACCEPTED): still identical."""
    period = jnp.asarray([5, 9, 13, 17], dtype=jnp.int32)
    tokens = jnp.tile(period, (2, 6))  # (2, 24) period-4 repetition
    lengths = jnp.asarray([24, 21], dtype=jnp.int32)
    ref, out = ref_and_spec(params, tokens, lengths, max_new=24, draft_len=6)
    np.testing.assert_array_equal(np.asarray(out.tokens), np.asarray(ref.tokens))
    np.testing.assert_array_equal(np.asarray(out.lengths), np.asarray(ref.lengths))


def test_spec_matches_greedy_with_eos(params):
    """EOS placement, post-EOS padding, and lengths all match generate.
    Every vocab id is tried as EOS until one actually fires mid-stream."""
    tokens = jax.random.randint(jax.random.PRNGKey(3), (4, 10), 1, CFG.vocab_size)
    lengths = jnp.asarray([10, 10, 6, 8], dtype=jnp.int32)
    ref_free = generate(
        params, tokens, lengths, CFG, jax.random.PRNGKey(1),
        max_new_tokens=12, temperature=0.0, eos_id=-1, pad_id=0, attn_impl="xla",
    )
    # pick an EOS id that genuinely appears in the free-running output
    flat = np.asarray(ref_free.tokens).ravel()
    eos_id = int(flat[len(flat) // 2])
    ref, out = ref_and_spec(params, tokens, lengths, max_new=12, eos_id=eos_id)
    np.testing.assert_array_equal(np.asarray(out.tokens), np.asarray(ref.tokens))
    np.testing.assert_array_equal(np.asarray(out.lengths), np.asarray(ref.lengths))


def test_spec_draft_len_invariance(params):
    """The draft budget is a performance knob, never a correctness knob."""
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 8), 1, CFG.vocab_size)
    lengths = jnp.asarray([8, 5], dtype=jnp.int32)
    outs = [
        np.asarray(
            spec_generate(
                params, tokens, lengths, CFG, max_new_tokens=10,
                draft_len=d, eos_id=-1, pad_id=0, attn_impl="xla",
            ).tokens
        )
        for d in (1, 3, 8)
    ]
    np.testing.assert_array_equal(outs[0], outs[1])
    np.testing.assert_array_equal(outs[1], outs[2])


def test_propose_ngram_drafts_copies_after_bigram():
    history = jnp.asarray([[7, 8, 9, 3, 4, 7, 8, 0, 0, 0]], dtype=jnp.int32)
    lengths = jnp.asarray([7], dtype=jnp.int32)  # tail bigram (7, 8)
    drafts = propose_ngram_drafts(history, lengths, draft_len=3)
    # bigram (7,8) last occurred at 0..1 -> draft copies 9, 3, 4
    assert drafts.tolist() == [[9, 3, 4]]


def test_propose_ngram_drafts_fallback_repeats_last():
    history = jnp.asarray([[1, 2, 3, 4, 0, 0]], dtype=jnp.int32)
    lengths = jnp.asarray([4], dtype=jnp.int32)  # bigram (3,4) never seen before
    drafts = propose_ngram_drafts(history, lengths, draft_len=2)
    assert drafts.tolist() == [[4, 4]]


@requires_set_mesh
def test_spec_generate_sharded_matches_single_device(params):
    """spec_generate under a (fsdp, tp) mesh: per-row verify windows and
    cache scatters must partition like the plain decode path."""
    from jax.sharding import NamedSharding

    from prime_tpu.parallel.mesh import make_mesh
    from prime_tpu.parallel.sharding import batch_spec, cache_spec, lengths_spec, shard_params

    tokens = jnp.tile(jnp.asarray([5, 9, 13, 17], dtype=jnp.int32), (4, 4))  # periodic
    lengths = jnp.asarray([16, 13, 16, 11], dtype=jnp.int32)
    ref = spec_generate(
        params, tokens, lengths, CFG, max_new_tokens=12, draft_len=4,
        eos_id=-1, pad_id=0, attn_impl="xla",
    )
    mesh = make_mesh({"dp": 1, "fsdp": 4, "tp": 2})
    sharded = shard_params(params, mesh, CFG)
    tokens_s = jax.device_put(tokens, NamedSharding(mesh, batch_spec()))
    lengths_s = jax.device_put(lengths, NamedSharding(mesh, lengths_spec()))
    with jax.set_mesh(mesh):
        out = spec_generate(
            sharded, tokens_s, lengths_s, CFG, max_new_tokens=12, draft_len=4,
            eos_id=-1, pad_id=0, attn_impl="xla", cache_spec=cache_spec(),
        )
    np.testing.assert_array_equal(np.asarray(out.tokens), np.asarray(ref.tokens))


# -- sampled speculation: exact in DISTRIBUTION -------------------------------


SAMP_CFG = CFG.scaled(name="tiny-samp", vocab_size=16, d_model=32, n_layers=1, n_heads=2, n_kv_heads=1, d_ff=64)


@pytest.fixture(scope="module")
def samp_params():
    return init_params(jax.random.PRNGKey(3), SAMP_CFG, dtype=jnp.float32)


def test_spec_sampled_matches_plain_distribution(samp_params):
    """Rejection sampling against the n-gram proposal must reproduce the
    autoregressive sampling distribution exactly — compare per-position
    marginals over many seeds (TV distance below statistical noise)."""
    n_runs = 2048
    max_new = 3
    prompt = jnp.array([[3, 7, 3, 7, 3]], dtype=jnp.int32)
    lengths = jnp.array([5], dtype=jnp.int32)
    keys = jax.random.split(jax.random.PRNGKey(42), n_runs)

    def run_spec(key):
        return spec_generate(
            samp_params, prompt, lengths, SAMP_CFG, max_new_tokens=max_new,
            draft_len=3, pad_id=0, attn_impl="xla", temperature=0.7, rng=key,
        ).tokens[0]

    def run_plain(key):
        return generate(
            samp_params, prompt, lengths, SAMP_CFG, key, max_new_tokens=max_new,
            temperature=0.7, pad_id=0, attn_impl="xla",
        ).tokens[0]

    spec_tokens = np.asarray(jax.vmap(run_spec)(keys))      # (n, max_new)
    plain_tokens = np.asarray(jax.vmap(run_plain)(keys))
    for position in range(max_new):
        spec_hist = np.bincount(spec_tokens[:, position], minlength=16) / n_runs
        plain_hist = np.bincount(plain_tokens[:, position], minlength=16) / n_runs
        tv = 0.5 * np.abs(spec_hist - plain_hist).sum()
        assert tv < 0.09, f"position {position}: TV {tv:.3f}"


def test_spec_sampled_top_p_collapses_to_greedy(samp_params):
    """nucleus with a vanishing top_p keeps only the argmax token — sampled
    speculation must then emit exactly the greedy sequence."""
    prompt = jnp.array([[3, 7, 3, 7, 3, 9, 2, 11]], dtype=jnp.int32)
    lengths = jnp.array([8], dtype=jnp.int32)
    greedy = spec_generate(
        samp_params, prompt, lengths, SAMP_CFG, max_new_tokens=8,
        draft_len=3, pad_id=0, attn_impl="xla",
    )
    nucleus = spec_generate(
        samp_params, prompt, lengths, SAMP_CFG, max_new_tokens=8,
        draft_len=3, pad_id=0, attn_impl="xla",
        temperature=1.0, top_p=1e-6, nucleus=True, rng=jax.random.PRNGKey(9),
    )
    np.testing.assert_array_equal(np.asarray(greedy.tokens), np.asarray(nucleus.tokens))
    np.testing.assert_array_equal(np.asarray(greedy.lengths), np.asarray(nucleus.lengths))


def test_spec_sampled_requires_rng(samp_params):
    prompt = jnp.array([[1, 2, 3]], dtype=jnp.int32)
    with pytest.raises(ValueError, match="rng"):
        spec_generate(
            samp_params, prompt, jnp.array([3], dtype=jnp.int32), SAMP_CFG,
            max_new_tokens=2, temperature=0.5,
        )


def test_jax_generator_speculative_matches_plain():
    from prime_tpu.evals.runner import JaxGenerator

    plain = JaxGenerator("tiny-test")
    spec = JaxGenerator("tiny-test", speculative=True, draft_len=4)
    prompts = ["12+34=46 12+34=", "hello hello hello "]
    a = plain.generate(prompts, max_new_tokens=12, temperature=0.0)
    b = spec.generate(prompts, max_new_tokens=12, temperature=0.0)
    assert a == b


def test_spec_kv_quant_matches_plain_kv_quant(params):
    """int8-cache speculation: the verify window quantizes its slots with the
    same per-slot scheme plain decode uses, so the stored cache is identical
    — greedy spec+kvq must emit exactly what plain kvq decode does (fp32
    weights: no matmul-rounding ties)."""
    tokens = jnp.tile(jnp.arange(1, 9, dtype=jnp.int32), (2, 2))  # periodic (2, 16)
    lengths = jnp.array([16, 12], dtype=jnp.int32)
    ref = generate(
        params, tokens, lengths, CFG, jax.random.PRNGKey(1),
        max_new_tokens=12, temperature=0.0, pad_id=0, attn_impl="xla",
        kv_quant=True,
    )
    out = spec_generate(
        params, tokens, lengths, CFG,
        max_new_tokens=12, draft_len=4, pad_id=0, attn_impl="xla",
        kv_quant=True,
    )
    np.testing.assert_array_equal(np.asarray(ref.tokens), np.asarray(out.tokens))
    np.testing.assert_array_equal(np.asarray(ref.lengths), np.asarray(out.lengths))


def test_jax_generator_speculative_with_kv_quant():
    """The former hard incompatibility is now a working combination."""
    from prime_tpu.evals.runner import JaxGenerator

    gen = JaxGenerator("tiny-test", speculative=True, kv_quant=True)
    out = gen.generate(["12+34=46 12+34="], max_new_tokens=8, temperature=0.0)
    assert len(out) == 1 and isinstance(out[0], str)
