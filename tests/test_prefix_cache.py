"""Unit tests for the block-granular radix prefix cache (serve/prefix_cache).

These drive the trie with plain numpy segments — no jax dispatch, no model —
so the structural invariants (path compression, block alignment, split byte
conservation, dedup, LRU eviction, refcount pins) are pinned independently
of the engine. Engine-level integration (assemble, bit-identity, metrics)
lives in tests/test_engine.py.
"""

import numpy as np
import pytest

from prime_tpu.serve.prefix_cache import BlockPrefixCache, segment_nbytes

BLOCK = 16
# one fake capacity-axis leaf: 4 bytes per slot keeps the byte math legible
SLOT_BYTES = 4


def seg_for(row: np.ndarray, start: int, stop: int) -> dict:
    return {"k": row[..., start:stop]}


def make_row(tokens: list[int]) -> np.ndarray:
    # a 1 x len row whose values encode the token ids, so segment contents
    # can be checked after splits/partial takes
    return np.asarray([tokens], dtype=np.float32)


def insert(cache: BlockPrefixCache, tokens: list[int]) -> int:
    row = make_row(tokens)
    return cache.insert(tokens, lambda a, b: seg_for(row, a, b))


def test_insert_match_roundtrip_and_alignment():
    cache = BlockPrefixCache(budget_bytes=1 << 20, block=BLOCK)
    tokens = list(range(100, 148))  # 48 tokens = 3 blocks
    insert(cache, tokens)
    assert cache.nodes == 1 and cache.bytes == 48 * SLOT_BYTES
    # full-path match, capped at len-1 by the caller's limit
    m = cache.match(tokens + [7], limit=48)
    assert m is not None and m.length == 48
    cache.release(m)
    # mid-edge partial: a 40-token limit aligns down to 32
    m = cache.match(tokens, limit=40)
    assert m is not None and m.length == 32
    assert [t for t in m.takes()] == [32]
    np.testing.assert_array_equal(
        m.segments()[0]["k"][..., :32], make_row(tokens)[..., :32]
    )
    cache.release(m)
    # diverging after one block matches exactly that block
    assert cache.match_len(tokens[:16] + [1] * 32, limit=48) == 16
    # nothing under one block
    assert cache.match(tokens, limit=BLOCK - 1) is None
    with pytest.raises(ValueError, match="not aligned"):
        insert(cache, tokens[:20])


def test_shared_prefix_dedup_and_split_conserves_bytes():
    cache = BlockPrefixCache(budget_bytes=1 << 20, block=BLOCK)
    pre = list(range(32))
    a = pre + [500 + i for i in range(16)]
    b = pre + [900 + i for i in range(16)]
    insert(cache, a)
    assert cache.bytes == 48 * SLOT_BYTES and cache.nodes == 1
    insert(cache, b)
    # the 32-token preamble is stored once: a's edge split into 32 + 16 and
    # b added only its 16-token tail
    assert cache.bytes == 64 * SLOT_BYTES
    assert cache.nodes == 3
    assert cache.dedup_tokens == 32
    # both full paths still match, with the right segment contents
    for tokens in (a, b):
        m = cache.match(tokens, limit=48)
        assert m is not None and m.length == 48
        got = np.concatenate(
            [seg["k"][..., :take] for seg, take in zip(m.segments(), m.takes())],
            axis=-1,
        )
        np.testing.assert_array_equal(got, make_row(tokens))
        cache.release(m)
    # re-inserting an already-covered prompt adds nothing
    before = cache.bytes
    assert insert(cache, a) == 0
    assert cache.bytes == before


def test_byte_budget_evicts_lru_leaves_first():
    cache = BlockPrefixCache(budget_bytes=3 * 16 * SLOT_BYTES, block=BLOCK)
    p1, p2, p3 = [[k] * 16 for k in (1, 2, 3)]
    insert(cache, p1)
    insert(cache, p2)
    cache.release(cache.match(p1 + [9], limit=16))  # touch p1: p2 is now LRU
    insert(cache, p3)  # fits: 3 entries == budget
    assert cache.evictions == 0
    insert(cache, [4] * 16)  # over budget: evict exactly the LRU leaf (p2)
    assert cache.evictions == 1
    assert cache.match_len(p2, limit=16) == 0
    for p in (p1, p3, [4] * 16):
        assert cache.match_len(p, limit=16) == 16
    assert cache.bytes <= cache.budget_bytes


def test_eviction_cascades_to_emptied_interior_nodes():
    cache = BlockPrefixCache(budget_bytes=1 << 20, block=BLOCK)
    pre = list(range(32))
    insert(cache, pre + [500 + i for i in range(16)])
    insert(cache, pre + [900 + i for i in range(16)])
    assert cache.nodes == 3
    cache.budget_bytes = 1
    assert cache.evict_to_budget() == 3  # two tails, then the bared preamble
    assert cache.bytes == 0 and cache.nodes == 0


def test_refcount_protects_pinned_path_from_eviction():
    cache = BlockPrefixCache(budget_bytes=1 << 20, block=BLOCK)
    pre = list(range(32))
    insert(cache, pre + [500 + i for i in range(16)])
    insert(cache, pre + [900 + i for i in range(16)])
    pinned = cache.match(pre + [500 + i for i in range(16)], limit=48)
    assert pinned is not None and len(pinned.entries) == 2
    cache.budget_bytes = 1
    # the unpinned sibling tail goes; the pinned preamble+tail survive
    assert cache.evict_to_budget() == 1
    assert cache.bytes == 48 * SLOT_BYTES
    cache.release(pinned)
    assert cache.evict_to_budget() == 2
    assert cache.bytes == 0


def test_segment_nbytes_counts_every_leaf():
    seg = {
        "k": np.zeros((2, 3, 16), dtype=np.float32),
        "k_scale": np.zeros((2, 1, 16), dtype=np.int8),
    }
    assert segment_nbytes(seg) == 2 * 3 * 16 * 4 + 2 * 1 * 16
