"""Unit tests for the block-granular radix prefix cache (serve/prefix_cache).

These drive the trie with plain numpy segments — no jax dispatch, no model —
so the structural invariants (path compression, block alignment, split byte
conservation, dedup, LRU eviction, refcount pins) are pinned independently
of the engine. Engine-level integration (assemble, bit-identity, metrics)
lives in tests/test_engine.py.
"""

import numpy as np
import pytest

from prime_tpu.serve.prefix_cache import BlockPrefixCache, segment_nbytes

BLOCK = 16
# one fake capacity-axis leaf: 4 bytes per slot keeps the byte math legible
SLOT_BYTES = 4


def seg_for(row: np.ndarray, start: int, stop: int) -> dict:
    return {"k": row[..., start:stop]}


def make_row(tokens: list[int]) -> np.ndarray:
    # a 1 x len row whose values encode the token ids, so segment contents
    # can be checked after splits/partial takes
    return np.asarray([tokens], dtype=np.float32)


def insert(cache: BlockPrefixCache, tokens: list[int]) -> int:
    row = make_row(tokens)
    return cache.insert(tokens, lambda a, b: seg_for(row, a, b))


def test_insert_match_roundtrip_and_alignment():
    cache = BlockPrefixCache(budget_bytes=1 << 20, block=BLOCK)
    tokens = list(range(100, 148))  # 48 tokens = 3 blocks
    insert(cache, tokens)
    assert cache.nodes == 1 and cache.bytes == 48 * SLOT_BYTES
    # full-path match, capped at len-1 by the caller's limit
    m = cache.match(tokens + [7], limit=48)
    assert m is not None and m.length == 48
    cache.release(m)
    # mid-edge partial: a 40-token limit aligns down to 32
    m = cache.match(tokens, limit=40)
    assert m is not None and m.length == 32
    assert [t for t in m.takes()] == [32]
    np.testing.assert_array_equal(
        m.segments()[0]["k"][..., :32], make_row(tokens)[..., :32]
    )
    cache.release(m)
    # diverging after one block matches exactly that block
    assert cache.match_len(tokens[:16] + [1] * 32, limit=48) == 16
    # nothing under one block
    assert cache.match(tokens, limit=BLOCK - 1) is None
    with pytest.raises(ValueError, match="not aligned"):
        insert(cache, tokens[:20])


def test_shared_prefix_dedup_and_split_conserves_bytes():
    cache = BlockPrefixCache(budget_bytes=1 << 20, block=BLOCK)
    pre = list(range(32))
    a = pre + [500 + i for i in range(16)]
    b = pre + [900 + i for i in range(16)]
    insert(cache, a)
    assert cache.bytes == 48 * SLOT_BYTES and cache.nodes == 1
    insert(cache, b)
    # the 32-token preamble is stored once: a's edge split into 32 + 16 and
    # b added only its 16-token tail
    assert cache.bytes == 64 * SLOT_BYTES
    assert cache.nodes == 3
    assert cache.dedup_tokens == 32
    # both full paths still match, with the right segment contents
    for tokens in (a, b):
        m = cache.match(tokens, limit=48)
        assert m is not None and m.length == 48
        got = np.concatenate(
            [seg["k"][..., :take] for seg, take in zip(m.segments(), m.takes())],
            axis=-1,
        )
        np.testing.assert_array_equal(got, make_row(tokens))
        cache.release(m)
    # re-inserting an already-covered prompt adds nothing
    before = cache.bytes
    assert insert(cache, a) == 0
    assert cache.bytes == before


def test_byte_budget_evicts_lru_leaves_first():
    cache = BlockPrefixCache(budget_bytes=3 * 16 * SLOT_BYTES, block=BLOCK)
    p1, p2, p3 = [[k] * 16 for k in (1, 2, 3)]
    insert(cache, p1)
    insert(cache, p2)
    cache.release(cache.match(p1 + [9], limit=16))  # touch p1: p2 is now LRU
    insert(cache, p3)  # fits: 3 entries == budget
    assert cache.evictions == 0
    insert(cache, [4] * 16)  # over budget: evict exactly the LRU leaf (p2)
    assert cache.evictions == 1
    assert cache.match_len(p2, limit=16) == 0
    for p in (p1, p3, [4] * 16):
        assert cache.match_len(p, limit=16) == 16
    assert cache.bytes <= cache.budget_bytes


def test_eviction_cascades_to_emptied_interior_nodes():
    cache = BlockPrefixCache(budget_bytes=1 << 20, block=BLOCK)
    pre = list(range(32))
    insert(cache, pre + [500 + i for i in range(16)])
    insert(cache, pre + [900 + i for i in range(16)])
    assert cache.nodes == 3
    cache.budget_bytes = 1
    assert cache.evict_to_budget() == 3  # two tails, then the bared preamble
    assert cache.bytes == 0 and cache.nodes == 0


def test_refcount_protects_pinned_path_from_eviction():
    cache = BlockPrefixCache(budget_bytes=1 << 20, block=BLOCK)
    pre = list(range(32))
    insert(cache, pre + [500 + i for i in range(16)])
    insert(cache, pre + [900 + i for i in range(16)])
    pinned = cache.match(pre + [500 + i for i in range(16)], limit=48)
    assert pinned is not None and len(pinned.entries) == 2
    cache.budget_bytes = 1
    # the unpinned sibling tail goes; the pinned preamble+tail survive
    assert cache.evict_to_budget() == 1
    assert cache.bytes == 48 * SLOT_BYTES
    cache.release(pinned)
    assert cache.evict_to_budget() == 2
    assert cache.bytes == 0


def test_segment_nbytes_counts_every_leaf():
    seg = {
        "k": np.zeros((2, 3, 16), dtype=np.float32),
        "k_scale": np.zeros((2, 1, 16), dtype=np.int8),
    }
    assert segment_nbytes(seg) == 2 * 3 * 16 * 4 + 2 * 1 * 16


# ---- host spill tier --------------------------------------------------------


def tiered_cache(device_blocks: int, host_blocks: int) -> tuple[BlockPrefixCache, list]:
    """A two-tier cache whose converters copy (like device_get / re-upload
    do for real) and log every crossing, so tests can assert which segments
    moved, that the roundtrip is byte-identical, and that the copies stay
    tree-compatible (a host-resident edge can still be split/cut)."""
    log: list[tuple[str, int]] = []

    def to_host(seg):
        log.append(("spill", segment_nbytes(seg)))
        return {k: v.copy() for k, v in seg.items()}

    def to_device(seg):
        log.append(("upload", segment_nbytes(seg)))
        return {k: v.copy() for k, v in seg.items()}

    cache = BlockPrefixCache(
        budget_bytes=device_blocks * 16 * SLOT_BYTES,
        block=BLOCK,
        host_budget_bytes=host_blocks * 16 * SLOT_BYTES,
        to_host=to_host,
        to_device=to_device,
    )
    return cache, log


def test_device_pressure_spills_lru_to_host_instead_of_deleting():
    cache, log = tiered_cache(device_blocks=2, host_blocks=8)
    p1, p2, p3 = [[k] * 16 for k in (1, 2, 3)]
    insert(cache, p1)
    insert(cache, p2)
    cache.release(cache.match(p1 + [9], limit=16))  # p2 is now LRU
    insert(cache, p3)  # over device budget: p2 demotes, nothing is deleted
    assert cache.spills == 1 and cache.evictions == 0
    assert log == [("spill", 16 * SLOT_BYTES)]
    assert cache.nodes == 3 and cache.host_nodes == 1
    assert cache.bytes == 2 * 16 * SLOT_BYTES
    assert cache.host_bytes == 16 * SLOT_BYTES
    # the spilled prefix is still matchable — flagged host-resident
    m = cache.match(p2 + [9], limit=16)
    assert m is not None and m.length == 16 and m.host_tokens == 16
    cache.release(m)


def test_spill_reupload_roundtrip_preserves_bytes_and_refcounts():
    cache, log = tiered_cache(device_blocks=2, host_blocks=8)
    p1, p2, p3 = [[k] * 16 for k in (1, 2, 3)]
    for p in (p1, p2, p3):
        insert(cache, p)  # p1 demoted on the third insert
    assert cache.spills == 1 and cache.host_nodes == 1
    m = cache.match(p1 + [9], limit=16)
    assert m is not None and m.host_tokens == 16 and m.device_tokens == 0
    node = m.entries[0][0]
    assert node.refs == 1
    promoted, promoted_bytes = cache.promote(m)
    assert (promoted, promoted_bytes) == (1, 16 * SLOT_BYTES)
    assert cache.reuploads == 1 and cache.reupload_bytes == 16 * SLOT_BYTES
    # headroom is made BEFORE the re-upload (spill precedes upload in the
    # converter log), so the device tier never transiently overshoots its
    # budget on the hot-prefix path
    assert log[-2:] == [("spill", 16 * SLOT_BYTES), ("upload", 16 * SLOT_BYTES)]
    # the roundtrip is byte-identical and the pin survived the promote —
    # including the rebalance it triggered (device was full, so promoting
    # p1 demoted the coldest UNPINNED segment, never the pinned path)
    np.testing.assert_array_equal(m.segments()[0]["k"], make_row(p1))
    assert node.refs == 1 and node.tier == "device"
    assert cache.spills == 2  # p2 (now coldest) paid for p1's return
    assert cache.bytes <= cache.budget_bytes
    cache.release(m)
    assert node.refs == 0
    # accounting stayed conserved across the shuffle: 3 prefixes, 1 on host
    assert cache.nodes == 3 and cache.host_nodes == 1
    assert cache.bytes + cache.host_bytes == 3 * 16 * SLOT_BYTES


def test_lru_order_and_byte_accounting_across_tiers():
    cache, _ = tiered_cache(device_blocks=2, host_blocks=2)
    prefixes = [[k] * 16 for k in (1, 2, 3, 4)]
    for p in prefixes:
        insert(cache, p)
    # 4 inserts into 2+2 budgets: the two oldest (p1, p2) live on the host,
    # the two newest (p3, p4) on the device; nothing deleted yet
    assert cache.evictions == 0 and cache.spills == 2
    assert cache.host_nodes == 2
    assert cache.bytes == cache.host_bytes == 2 * 16 * SLOT_BYTES
    insert(cache, [5] * 16)  # p3 spills; host over budget drops its LRU (p1)
    assert cache.spills == 3 and cache.evictions == 1
    assert cache.match_len(prefixes[0], limit=16) == 0  # p1 is gone
    for p in prefixes[1:]:
        assert cache.match_len(p, limit=16) == 16
    assert cache.bytes <= cache.budget_bytes
    assert cache.host_bytes <= cache.host_budget_bytes


def test_host_budget_zero_keeps_single_tier_delete_behavior():
    cache, log = tiered_cache(device_blocks=2, host_blocks=0)
    for k in (1, 2, 3):
        insert(cache, [k] * 16)
    assert cache.spills == 0 and cache.evictions == 1 and log == []
    assert cache.host_bytes == 0 and cache.host_nodes == 0


def test_split_preserves_tier_and_host_accounting():
    cache, _ = tiered_cache(device_blocks=1, host_blocks=8)
    pre = list(range(32))
    insert(cache, pre)  # 2 blocks > 1-block device budget: demoted whole
    assert cache.host_nodes == 1 and cache.bytes == 0
    # a sibling insert splits the host-resident edge: both halves stay on
    # the host and host bytes are conserved (the new 1-block tail fills the
    # device budget exactly and stays resident)
    insert(cache, pre[:16] + [900 + i for i in range(16)])
    assert cache.nodes == 3
    assert cache.bytes + cache.host_bytes == 48 * SLOT_BYTES
    m = cache.match(pre + [7], limit=32)
    assert m is not None and m.length == 32 and m.host_tokens == 32
    cache.promote(m)
    got = np.concatenate(
        [seg["k"][..., :take] for seg, take in zip(m.segments(), m.takes())], axis=-1
    )
    np.testing.assert_array_equal(got, make_row(pre))
    cache.release(m)


def test_split_of_host_node_copies_instead_of_viewing():
    """Splitting a host-resident edge must materialize both halves: host
    arrays (device_get numpy) slice to VIEWS, and a view would pin the whole
    base buffer after the other half is evicted — the host byte budget would
    stop bounding actual RSS."""
    cache, _ = tiered_cache(device_blocks=1, host_blocks=8)
    pre = list(range(32))
    insert(cache, pre)  # demoted whole to host
    node = next(iter(cache._root.children.values()))
    base = node.segment["k"]
    assert node.tier == "host"
    insert(cache, pre[:16] + [900 + i for i in range(16)])  # splits the edge
    upper = cache._root.children[tuple(pre[:BLOCK])]
    lower = upper.children[tuple(pre[BLOCK : 2 * BLOCK])]
    assert upper.tier == lower.tier == "host"
    for half in (upper, lower):
        assert not np.shares_memory(half.segment["k"], base)
        assert half.segment["k"].base is None  # owns its buffer outright


def test_host_budget_enforced_when_only_interiors_hold_host_bytes():
    """insert() can plant a fresh DEVICE tail under a spilled (host) parent;
    leaf eviction can never delete that parent, so without the subtree
    fallback the host byte budget would be pinned open by HBM-resident
    children — an unbounded RAM footprint behind a bounding knob."""
    cache, _ = tiered_cache(device_blocks=8, host_blocks=1)
    pre = list(range(32))
    insert(cache, pre)
    insert(cache, pre + [900 + i for i in range(16)])  # device tail child
    parent = cache._root.children[tuple(pre[:BLOCK])]
    assert parent.children and parent.tier == "device"
    cache._spill(parent)  # as a past device-pressure demotion would
    assert cache.host_bytes == 2 * 16 * SLOT_BYTES > cache.host_budget_bytes
    evicted = cache.evict_to_budget()
    # no host LEAF existed; the whole host-rooted subtree (device tail
    # included) went, and both tiers' accounting drained with it
    assert evicted == 2
    assert cache.host_bytes <= cache.host_budget_bytes
    assert cache.host_bytes == 0 and cache.host_nodes == 0
    assert cache.bytes == 0 and cache.nodes == 0
    assert cache.match(pre + [7], limit=16) is None
    # a pinned path is never deleted, even by the subtree fallback
    insert(cache, pre)
    insert(cache, pre + [900 + i for i in range(16)])
    parent = cache._root.children[tuple(pre[:BLOCK])]
    cache._spill(parent)
    m = cache.match(pre + [7], limit=16)  # pins the host-resident parent
    assert m is not None and m.host_tokens == 16
    assert cache.evict_to_budget() == 0  # over budget but pinned: skipped
    assert cache.host_bytes > cache.host_budget_bytes
    cache.release(m)
    assert cache.evict_to_budget() == 2  # released: enforcement resumes


def test_spill_seconds_accumulates_converter_time_only():
    cache, _ = tiered_cache(device_blocks=1, host_blocks=8)
    assert cache.spill_seconds == 0.0
    insert(cache, [1] * 16)
    insert(cache, [2] * 16)  # first insert's segment demotes
    assert cache.spills == 1 and cache.spill_seconds >= 0.0


def test_iter_prefixes_is_root_first_and_bounded():
    cache, _ = tiered_cache(device_blocks=8, host_blocks=8)
    pre = list(range(32))
    a = pre + [500 + i for i in range(16)]
    b = pre + [900 + i for i in range(16)]
    insert(cache, a)
    insert(cache, b)
    paths = list(cache.iter_prefixes(limit=10))
    # BFS: the shared preamble precedes both full paths; every path is a
    # root-anchored token run
    assert paths[0] == tuple(pre)
    assert set(paths[1:]) == {tuple(a), tuple(b)}
    assert list(cache.iter_prefixes(limit=1)) == [tuple(pre)]


# -- pin-aware splits (off-loop export enabler) --------------------------------


def test_split_of_pinned_node_preserves_match_view_and_pins():
    """A store-path insert may split a node a live match has pinned (the
    concurrent-insert case an off-loop KV export creates): the match's
    snapshot must keep serving the FULL pre-split segment and token run, the
    lower split half must inherit the pin (so the LRU cannot evict the tail
    of a pinned path), and release() must return every refcount to zero."""
    cache = BlockPrefixCache(budget_bytes=1 << 20, block=BLOCK)
    tokens = list(range(100, 148))  # one 48-token edge
    insert(cache, tokens)
    m = cache.match(tokens + [7], limit=48)
    assert m is not None and m.length == 48
    pre_segments = m.segments()
    # concurrent insert diverging after 16 tokens: splits the pinned edge
    insert(cache, tokens[:16] + [900 + i for i in range(16)])
    assert cache.nodes == 3  # upper (16) + lower (32) + new tail
    # the match still reads the full uncut run (snapshot, not live nodes)
    assert m.tokens() == tokens
    got = np.concatenate(
        [seg["k"][..., :take] for seg, take in zip(m.segments(), m.takes())],
        axis=-1,
    )
    np.testing.assert_array_equal(got, make_row(tokens))
    assert m.segments()[0]["k"].shape == pre_segments[0]["k"].shape
    # both halves of the split are pinned: budget pressure cannot evict them
    cache.budget_bytes = 1  # force pressure
    cache.evict_to_budget()
    assert cache.match_len(tokens, limit=48) == 48  # path intact
    cache.budget_bytes = 1 << 20
    # release returns every node to refs == 0
    cache.release(m)

    def walk(node):
        yield node
        for child in node.children.values():
            yield from walk(child)

    assert all(n.refs == 0 for n in walk(cache._root))


def test_split_of_pinned_node_export_serializes_presplit_path():
    """export_segments pins for the whole serialization; a split landing
    mid-read (simulated by splitting between match and the byte walk) must
    not change the exported tokens or bytes — the wire payload equals the
    one serialized with no concurrent insert."""
    from prime_tpu.serve.prefix_cache import decode_wire_payload

    tokens = list(range(200, 248))
    quiet = BlockPrefixCache(budget_bytes=1 << 20, block=BLOCK)
    insert(quiet, tokens)
    reference = quiet.export_segments(tokens)

    cache = BlockPrefixCache(budget_bytes=1 << 20, block=BLOCK)
    insert(cache, tokens)
    m = cache.match(tokens, limit=48)  # pin like an in-flight export
    insert(cache, tokens[:16] + [1] * 16)  # splits the pinned edge
    cache.release(m)
    payload = cache.export_segments(tokens)
    assert payload is not None and reference is not None
    ref_tokens, ref_leaves = decode_wire_payload(reference, BLOCK)
    got_tokens, got_leaves = decode_wire_payload(payload, BLOCK)
    assert got_tokens == ref_tokens == tokens
    for name in ref_leaves:
        np.testing.assert_array_equal(got_leaves[name], ref_leaves[name])


def test_pinned_split_on_host_tier_keeps_byte_accounting():
    """Splitting a pinned HOST-resident node conserves per-tier bytes and
    the transferred pin blocks host-budget eviction of the lower half."""
    cache = BlockPrefixCache(
        budget_bytes=16 * SLOT_BYTES, block=BLOCK,
        host_budget_bytes=1 << 20,
    )
    tokens = list(range(300, 332))  # 32 tokens, one edge
    insert(cache, tokens)
    # drive the edge to the host tier
    insert(cache, [7000 + i for i in range(16)])
    node = cache._root.children[tuple(tokens[:BLOCK])]
    assert node.tier == "host"
    before_total = cache.bytes + cache.host_bytes
    m = cache.match(tokens, limit=32)
    assert m is not None and m.host_tokens == 32
    insert(cache, tokens[:16] + [8000 + i for i in range(16)])  # pinned split
    assert cache.bytes + cache.host_bytes >= before_total  # conserved + new tail
    # the lower (host) half is pinned: host-budget pressure skips it
    cache.host_budget_bytes = 1
    cache.evict_to_budget()
    assert m.tokens() == tokens
    got = np.concatenate(
        [seg["k"][..., :take] for seg, take in zip(m.segments(), m.takes())],
        axis=-1,
    )
    np.testing.assert_array_equal(got, make_row(tokens))
    cache.release(m)


def test_second_level_split_of_pin_inherited_lower_half():
    """A lower half created by splitting a pinned node is itself pinned via
    extra_pins; a SECOND insert splitting THAT half must transfer the pin
    again — the whole original pinned run stays unevictable until release."""
    cache = BlockPrefixCache(budget_bytes=1 << 20, block=BLOCK)
    tokens = list(range(400, 464))  # one 64-token edge
    insert(cache, tokens)
    m = cache.match(tokens, limit=64)
    assert m is not None and m.length == 64
    # first split at 16 (pins transfer to the 48-token lower half)...
    insert(cache, tokens[:16] + [900 + i for i in range(16)])
    # ...second split at 32 overall: splits the PIN-INHERITED lower half
    insert(cache, tokens[:32] + [800 + i for i in range(16)])
    # every piece of the original 64-token run must be pinned: budget
    # pressure cannot evict any of it while the match is live
    cache.budget_bytes = 1
    cache.evict_to_budget()
    assert cache.match_len(tokens, limit=64) == 64
    # the match's snapshot view is still the full pre-split run
    got = np.concatenate(
        [seg["k"][..., :take] for seg, take in zip(m.segments(), m.takes())],
        axis=-1,
    )
    np.testing.assert_array_equal(got, make_row(tokens))
    cache.budget_bytes = 1 << 20
    cache.release(m)

    def walk(node):
        yield node
        for child in node.children.values():
            yield from walk(child)

    assert all(n.refs == 0 for n in walk(cache._root))
