"""Context-parallel training: the sequence axis sharded over sp with ring
attention inside the model forward (SURVEY §5 long-context first-class;
the training-side complement of long_context.py's sp decode).

The invariants: cp logits == plain logits, cp train-step loss AND gradients
== the plain step's, window/softcap/sink configs ride the ring, and invalid
modes (cache, per-layer schedules, missing sp axis) reject loudly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding

from prime_tpu.models import get_config
from prime_tpu.models.llama import forward, init_cache, init_params
from prime_tpu.parallel.mesh import make_mesh
from prime_tpu.parallel.sharding import cp_batch_spec
from prime_tpu.train import (
    default_optimizer,
    init_train_state,
    make_train_step,
)

from _markers import requires_shard_map

CFG = get_config("tiny-test")


def _cp_put(x, mesh):
    from prime_tpu.parallel.sharding import prune_spec

    return jax.device_put(x, NamedSharding(mesh, prune_spec(cp_batch_spec(), mesh)))


@requires_shard_map
def test_cp_forward_matches_plain():
    mesh = make_mesh({"dp": 1, "fsdp": 1, "sp": 8})
    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, CFG.vocab_size)
    ref, _ = forward(params, tokens, CFG, attn_impl="xla")
    out, _ = jax.jit(
        lambda p, t: forward(p, t, CFG, attn_impl="ring", mesh=mesh)
    )(params, _cp_put(tokens, mesh))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


@requires_shard_map
def test_cp_forward_uniform_window_and_sinks():
    """Mistral-style uniform window and GPT-OSS sinks both ride the ring."""
    mesh = make_mesh({"dp": 1, "fsdp": 1, "sp": 8})
    windowed = CFG.scaled(sliding_window=24, sliding_pattern="uniform")
    params = init_params(jax.random.PRNGKey(2), windowed, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 128), 0, CFG.vocab_size)
    ref, _ = forward(params, tokens, windowed, attn_impl="xla")
    out, _ = jax.jit(
        lambda p, t: forward(p, t, windowed, attn_impl="ring", mesh=mesh)
    )(params, _cp_put(tokens, mesh))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)

    sinky = get_config("tiny-gptoss").scaled(
        sliding_window=0, capacity_factor=8.0
    )
    sp = init_params(jax.random.PRNGKey(4), sinky, dtype=jnp.float32)
    toks = jax.random.randint(jax.random.PRNGKey(5), (2, 128), 1, sinky.vocab_size)
    ref, _ = forward(sp, toks, sinky, attn_impl="xla")
    out, _ = jax.jit(
        lambda p, t: forward(p, t, sinky, attn_impl="ring", mesh=mesh)
    )(sp, _cp_put(toks, mesh))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


@requires_shard_map
def test_cp_forward_softcap():
    """Gemma2-style score softcapping rides the ring fold (the canonical
    _apply_softcap, cap-before-mask)."""
    mesh = make_mesh({"dp": 1, "fsdp": 1, "sp": 8})
    capped = CFG.scaled(attn_softcap=20.0)
    params = init_params(jax.random.PRNGKey(6), capped, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(7), (2, 128), 0, capped.vocab_size)
    ref, _ = forward(params, tokens, capped, attn_impl="xla")
    out, _ = jax.jit(
        lambda p, t: forward(p, t, capped, attn_impl="ring", mesh=mesh)
    )(params, _cp_put(tokens, mesh))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


@requires_shard_map
def test_cp_composes_with_tp_and_fsdp():
    """Context parallelism on a (fsdp, tp, sp) mesh: heads shard over tp
    (megatron layout — no silent per-device replication of every head's
    attention), batch over fsdp, sequence over sp."""
    from prime_tpu.parallel.sharding import ring_qkv_axes, shard_params

    mesh = make_mesh({"fsdp": 2, "tp": 2, "sp": 2})
    assert ring_qkv_axes(mesh, CFG.n_kv_heads) == (("fsdp",), "tp")
    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, CFG.vocab_size)
    ref, _ = forward(params, tokens, CFG, attn_impl="xla")
    sharded = shard_params(params, mesh, CFG)
    out, _ = jax.jit(
        lambda p, t: forward(p, t, CFG, attn_impl="ring", mesh=mesh)
    )(sharded, _cp_put(tokens, mesh))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)
    # a tp degree the kv heads can't divide is an error, not replication
    with pytest.raises(ValueError, match="divide n_kv_heads"):
        ring_qkv_axes(make_mesh({"tp": 8}), CFG.n_kv_heads)


@requires_shard_map
def test_cp_train_step_matches_plain():
    """One optimizer step under context parallelism == the plain step:
    same loss, same updated parameters (the ring is exactly differentiable
    — ppermute's transpose is the reverse rotation)."""
    mesh = make_mesh({"dp": 1, "fsdp": 1, "sp": 8})
    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    optimizer = default_optimizer(learning_rate=1e-2)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, CFG.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones_like(tokens, dtype=jnp.float32)

    # the step donates its state: each run gets its own copy of the params
    plain_step = make_train_step(CFG, optimizer, attn_impl="xla")
    plain_state, plain_metrics = plain_step(
        init_train_state(jax.tree.map(jnp.copy, params), optimizer), tokens, targets, mask
    )

    cp_step = make_train_step(CFG, optimizer, attn_impl="ring", ring_mesh=mesh)
    cp_state, cp_metrics = cp_step(
        init_train_state(jax.tree.map(jnp.copy, params), optimizer),
        _cp_put(tokens, mesh), _cp_put(targets, mesh), _cp_put(mask, mesh),
    )
    assert float(cp_metrics["loss"]) == pytest.approx(float(plain_metrics["loss"]), rel=1e-5)
    # the ring folds KV blocks in a different order than dense softmax, so
    # near-zero gradient elements see fp reassociation that Adam's
    # normalization amplifies — atol covers that, not a math divergence
    for a, b in zip(jax.tree.leaves(plain_state.params), jax.tree.leaves(cp_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=5e-4)


def test_cp_rejects_invalid_modes():
    mesh = make_mesh({"dp": 1, "fsdp": 1, "sp": 8})
    params = init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
    tokens = jnp.zeros((2, 128), jnp.int32)
    with pytest.raises(ValueError, match="no-cache"):
        forward(
            params, tokens, CFG, attn_impl="ring", mesh=mesh,
            cache=init_cache(CFG, 2, 256, dtype=jnp.float32),
        )
    with pytest.raises(ValueError, match="'sp' axis"):
        forward(params, tokens, CFG, attn_impl="ring", mesh=make_mesh({"dp": 8}))
    with pytest.raises(ValueError, match="uniform"):
        forward(
            params, tokens, CFG.scaled(sliding_window=16, sliding_pattern="even"),
            attn_impl="ring", mesh=mesh,
        )
