"""Elastic fleet actuator: autoscaler decisions, replica lifecycle, 1→N→1.

The load-bearing properties (docs/architecture.md "Elastic fleet"):

1. the decision core is a pure function walking the interlock ladder —
   bounds, pending ops, breaker storms, per-direction cooldowns, the
   inflight guard — deterministically;
2. the closed-loop replay (real evaluator + autoscaler + supervisor over a
   SimLauncher against loadgen-derived fixtures) is byte-identical across
   runs: rate_storm scales 1→4→1 with a pinned actuation sequence,
   cancel_storm rides out with ZERO actions;
3. the supervisor always drains before killing, and restarts crashed
   replicas with capped exponential backoff;
4. membership churn under actuation over REAL HTTP loses zero requests and
   never double-counts `fleet_replicas{state}`;
5. a live storm against a 1-replica fleet actually spawns replicas, serves
   every request, and shrinks back to 1 once idle.
"""

import json
import threading
import time

import httpx
import pytest

from prime_tpu.obs.metrics import Registry
from prime_tpu.obs.slo import ScaleSignal, SloEvaluator, SloPolicy
from prime_tpu.serve import InferenceServer
from prime_tpu.serve.fleet import (
    AutoscalerConfig,
    FleetAutoscaler,
    FleetState,
    ReplicaSupervisor,
    SimLauncher,
    closed_loop_replay,
    serve_fleet,
)
from prime_tpu.serve.fleet.autoscaler import (
    SimWorkload,
    cancel_storm_arrivals,
    decide,
    storm_arrivals,
)
from prime_tpu.serve.fleet.supervisor import LocalProcessLauncher

UP = ScaleSignal("up", "storm")
DOWN = ScaleSignal("down", "idle")
HOLD = ScaleSignal("hold", "on budget")


def state(**kw) -> FleetState:
    base = dict(
        replicas=2, retirable=1, demand_slots=0, capacity_slots=16,
        retire_slots=8, breakers_open=0, breakers_total=2, pending=0,
    )
    base.update(kw)
    return FleetState(**base)


CFG = AutoscalerConfig(
    min_replicas=1, max_replicas=4, up_cooldown_s=10.0, down_cooldown_s=30.0
)


# ---- decision core ----------------------------------------------------------


def test_decide_hold_passthrough():
    d = decide(HOLD, state(), CFG, now=100.0)
    assert (d.direction, d.outcome) == ("hold", "hold")


def test_decide_up_happy_path_and_bounds():
    d = decide(UP, state(replicas=2), CFG, now=100.0)
    assert (d.direction, d.outcome, d.count) == ("up", "spawned", 1)
    assert decide(UP, state(replicas=4), CFG, now=100.0).outcome == "at_max"
    # step sizing clamps to the ceiling
    wide = AutoscalerConfig(min_replicas=1, max_replicas=4, step=3)
    assert decide(UP, state(replicas=3), wide, now=100.0).count == 1
    assert decide(UP, state(replicas=1), wide, now=100.0).count == 3


def test_decide_down_happy_path_and_bounds():
    d = decide(DOWN, state(), CFG, now=100.0)
    assert (d.direction, d.outcome, d.count) == ("down", "retired", 1)
    assert decide(DOWN, state(replicas=1), CFG, now=100.0).outcome == "at_min"
    assert (
        decide(DOWN, state(retirable=0), CFG, now=100.0).outcome == "no_retirable"
    )


def test_decide_cooldowns_are_per_direction():
    # a recent scale-UP must not block a scale-down, and vice versa
    assert decide(UP, state(), CFG, now=100.0, last_up_at=95.0).outcome == "cooldown"
    assert decide(UP, state(), CFG, now=100.0, last_down_at=95.0).outcome == "spawned"
    assert (
        decide(DOWN, state(), CFG, now=100.0, last_down_at=80.0).outcome == "cooldown"
    )
    assert (
        decide(DOWN, state(), CFG, now=100.0, last_up_at=99.0).outcome == "retired"
    )


def test_decide_interlocks():
    # pending lifecycle op: one thing at a time, both directions
    assert decide(UP, state(pending=1), CFG, now=0.0).outcome == "pending"
    assert decide(DOWN, state(pending=1), CFG, now=0.0).outcome == "pending"
    # breaker storm pauses actuation both ways
    stormy = state(breakers_open=1, breakers_total=2)
    assert decide(UP, stormy, CFG, now=0.0).outcome == "breaker_storm"
    assert decide(DOWN, stormy, CFG, now=0.0).outcome == "breaker_storm"
    # one open breaker in a big fleet is NOT a storm
    assert decide(UP, state(breakers_open=1, breakers_total=4), CFG, now=0.0).outcome == "spawned"
    # inflight guard: never retire below live demand
    busy = state(demand_slots=10, capacity_slots=16, retire_slots=8)
    assert decide(DOWN, busy, CFG, now=0.0).outcome == "inflight_guard"
    ok = state(demand_slots=7, capacity_slots=16, retire_slots=8)
    assert decide(DOWN, ok, CFG, now=0.0).outcome == "retired"
    # paused wins over everything
    assert decide(UP, state(), CFG, now=0.0, paused=True).outcome == "paused"


def test_decide_bootstraps_below_min_floor():
    """An empty (or crashed-below-min) fleet has no rings to argue `up`
    from: the floor rule spawns the deficit on a hold signal, skipping the
    up-cooldown (repair, not scale) but honoring pause/pending/storm."""
    empty = state(replicas=0, retirable=0, capacity_slots=0, breakers_total=0)
    d = decide(HOLD, empty, CFG, now=0.0, last_up_at=-0.5)
    assert (d.direction, d.outcome, d.count) == ("up", "spawned", 1)
    two_floor = AutoscalerConfig(min_replicas=2, max_replicas=4)
    assert decide(HOLD, state(replicas=0, breakers_total=0), two_floor, now=0.0).count == 2
    assert decide(HOLD, empty, CFG, now=0.0, paused=True).outcome == "paused"
    assert decide(HOLD, state(replicas=0, pending=1), CFG, now=0.0).outcome == "pending"
    assert (
        decide(HOLD, state(replicas=1, breakers_open=1, breakers_total=2),
               two_floor, now=0.0).outcome
        == "breaker_storm"
    )
    # at or above the floor the rule is inert: hold passes through
    assert decide(HOLD, state(replicas=1), CFG, now=0.0).outcome == "hold"


def test_config_validation_and_env(monkeypatch):
    with pytest.raises(ValueError):
        AutoscalerConfig(min_replicas=5, max_replicas=2)
    with pytest.raises(ValueError):
        AutoscalerConfig(step=0)
    monkeypatch.setenv("PRIME_FLEET_AUTOSCALE_MIN", "2")
    monkeypatch.setenv("PRIME_FLEET_AUTOSCALE_MAX", "7")
    monkeypatch.setenv("PRIME_FLEET_AUTOSCALE_COOLDOWN_S", "3.5")
    cfg = AutoscalerConfig.from_env(max_replicas=9)
    assert (cfg.min_replicas, cfg.max_replicas) == (2, 9)  # override beats env
    assert cfg.up_cooldown_s == pytest.approx(3.5)
    assert cfg.down_cooldown_s == pytest.approx(30.0)


# ---- closed-loop replay (the deterministic sim) -----------------------------

SIM_CFG = AutoscalerConfig(
    min_replicas=1, max_replicas=4, up_cooldown_s=4.0, down_cooldown_s=6.0
)


def test_closed_loop_rate_storm_scales_1_to_4_to_1_byte_identically():
    """Acceptance: the replayed rate_storm fixture produces a deterministic
    scale-up→scale-down action sequence — pinned, and byte-identical
    across reruns."""
    arrivals = storm_arrivals(steps=60, quiet_tail=36)
    runs = [
        closed_loop_replay(SimWorkload(arrivals=arrivals), config=SIM_CFG)
        for _ in range(2)
    ]
    assert json.dumps(runs[0], sort_keys=True) == json.dumps(runs[1], sort_keys=True)
    out = runs[0]
    # the actuation sequence: three spawns up to max, three retires back
    actuations = [
        (d["direction"], d["outcome"], d["count"])
        for d in out["actions"]
        if d["outcome"] in ("spawned", "retired")
    ]
    assert actuations == [
        ("up", "spawned", 1), ("up", "spawned", 1), ("up", "spawned", 1),
        ("down", "retired", 1), ("down", "retired", 1), ("down", "retired", 1),
    ]
    # 1→4→1, monotone up then monotone down, bounds respected
    assert out["replicas"][0] == 1 and max(out["replicas"]) == 4
    assert out["replicas"][-1] == 1
    peak_at = out["replicas"].index(4)
    assert out["replicas"][:peak_at + 1] == sorted(out["replicas"][:peak_at + 1])
    assert out["replicas"][peak_at:] == sorted(out["replicas"][peak_at:], reverse=True)
    # the young-ring guard held: no action before the slow window covered
    assert all(d == "hold" for d in out["signals"][:4])


def test_closed_loop_cancel_storm_holds_with_zero_actions():
    out = closed_loop_replay(
        SimWorkload(arrivals=cancel_storm_arrivals()), config=SIM_CFG
    )
    assert out["actions"] == []
    assert set(out["replicas"]) == {1}


def test_closed_loop_respects_max_replicas_bound():
    tight = AutoscalerConfig(
        min_replicas=1, max_replicas=2, up_cooldown_s=2.0, down_cooldown_s=4.0
    )
    out = closed_loop_replay(
        SimWorkload(arrivals=storm_arrivals(steps=40, quiet_tail=16)), config=tight
    )
    assert max(out["replicas"]) == 2


# ---- supervisor: crash restart backoff, drain-before-kill -------------------


class _Clock:
    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


def test_supervisor_crash_restart_capped_exponential_backoff():
    clock = _Clock()
    launcher = SimLauncher()
    sup = ReplicaSupervisor(
        launcher, membership=None, restart_backoff_s=1.0,
        restart_backoff_cap_s=4.0, backoff_reset_s=100.0, clock=clock,
    )
    (url,) = sup.scale_up(1)
    handle = launcher.spawned[0]
    for round_idx, expected_wait in enumerate([1.0, 2.0, 4.0, 4.0]):  # capped at 4
        handle = launcher.spawned[-1]
        handle.crash()
        crash_at = clock.t
        sup.check()
        assert sup.counts() == {"restart_wait": 1}
        # one tick before the backoff lapses: still waiting
        clock.t = crash_at + expected_wait - 0.01
        sup.check()
        assert sup.counts() == {"restart_wait": 1}
        clock.t = crash_at + expected_wait
        sup.check()
        assert sup.counts() == {"ready": 1}
        assert sup.restarts_total == round_idx + 1
    # healthy long enough: the ladder resets to the bottom rung
    clock.t += 200.0
    launcher.spawned[-1].crash()
    crash_at = clock.t
    sup.check()
    clock.t = crash_at + 1.0
    sup.check()
    assert sup.counts() == {"ready": 1}


def test_supervisor_spawn_failure_counts_and_retries():
    clock = _Clock()
    launcher = SimLauncher()
    sup = ReplicaSupervisor(launcher, membership=None, restart_backoff_s=1.0, clock=clock)
    launcher.fail_next = 1
    assert sup.scale_up(1) == []
    assert sup.spawn_errors == 1
    # a crashed replica whose respawn ALSO fails climbs the ladder
    (url,) = sup.scale_up(1)
    launcher.spawned[-1].crash()
    sup.check()
    launcher.fail_next = 1
    clock.t = 1.0
    sup.check()  # respawn attempt fails -> back to waiting, errors counted
    assert sup.spawn_errors == 2
    assert sup.counts() == {"restart_wait": 1}
    clock.t = 10.0
    sup.check()
    assert sup.counts() == {"ready": 1}


class _SlowBackend:
    """Scripted backend whose generate() takes real wall time — the
    in-flight work a drain must finish."""

    concurrent = True

    def __init__(self, delay: float = 0.0) -> None:
        self.delay = delay
        self.registry = Registry()
        self._tokens = self.registry.counter("serve_tokens_emitted_total", "t")
        self._ttft = self.registry.histogram("serve_ttft_seconds", "t")
        self._slots = self.registry.gauge("serve_active_slots", "s")
        self.shared = {"ttft": 0.01, "slots": 0}
        self.inflight = 0
        self._lock = threading.Lock()

    def stats(self):
        with self._lock:
            inflight = self.inflight
        self._slots.set(max(inflight, self.shared["slots"]))
        return {"queue_depth": 0, "active_slots": inflight, "max_slots": 4}

    def generate(self, prompts, max_new_tokens, temperature, top_p=1.0, templated=False):
        with self._lock:
            self.inflight += 1
        try:
            if self.delay:
                time.sleep(self.delay)
            self._tokens.inc(4)
            self._ttft.observe(self.shared["ttft"])
            return ["ok"] * len(prompts)
        finally:
            with self._lock:
                self.inflight -= 1


class _ServerLauncher:
    """ReplicaLauncher spawning REAL InferenceServers over scripted
    backends — live HTTP without engine compiles. ``shared`` steers every
    replica's advertised TTFT/utilization so tests can stage storm→idle."""

    def __init__(self, shared: dict, delay: float = 0.0) -> None:
        self.shared = shared
        self.delay = delay
        self.servers: list = []

    def spawn(self):
        backend = _SlowBackend(self.delay)
        backend.shared = self.shared
        srv = InferenceServer("tiny-test", backend, port=0).start()
        self.servers.append(srv)

        class Handle:
            url = srv.url

            @staticmethod
            def alive() -> bool:
                return getattr(srv, "_serving", False)

            @staticmethod
            def terminate() -> None:
                if getattr(srv, "_serving", False):
                    srv.stop()

        return Handle()


def test_supervisor_drains_before_kill_over_real_http():
    """Drain-before-kill: a retirement marks the replica draining (routing
    excluded) while a live in-flight request FINISHES; the process is only
    reaped once the replica reports drained."""
    from prime_tpu.serve.fleet import FleetMembership

    shared = {"ttft": 0.01, "slots": 0}
    launcher = _ServerLauncher(shared, delay=0.8)
    membership = FleetMembership(poll_interval=0.05)
    sup = ReplicaSupervisor(launcher, membership=membership, drain_timeout_s=30.0)
    try:
        (url,) = sup.scale_up(1)
        replica_id = sup.snapshot()[0]["replica_id"]
        assert membership.get(replica_id) is not None
        results = []
        worker = threading.Thread(
            target=lambda: results.append(
                httpx.post(
                    f"{url}/v1/chat/completions",
                    json={"messages": [{"role": "user", "content": "slow one"}]},
                    timeout=30,
                )
            )
        )
        worker.start()
        time.sleep(0.2)  # the request is mid-generate
        assert sup.retire_one() == replica_id
        assert membership.get(replica_id).state == "draining"
        sup.check()
        # NOT reaped while the in-flight chat runs (healthz drained=false)
        membership.poll_once(membership.get(replica_id))
        sup.check()
        assert sup.counts().get("draining") == 1
        worker.join(timeout=30)
        assert results and results[0].status_code == 200
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            replica = membership.get(replica_id)
            if replica is not None:
                membership.poll_once(replica)
            sup.check()
            if not sup.counts():
                break
            time.sleep(0.05)
        assert sup.counts() == {}  # reaped after the drain completed
        assert membership.get(replica_id) is None
    finally:
        membership.stop()
        for srv in launcher.servers:
            try:
                srv.stop()
            except Exception:  # noqa: BLE001 — reaped servers are already down
                pass


def test_local_process_launcher_command_template_and_readiness():
    """Unit-level LocalProcessLauncher: template substitution, readiness
    polling, and the exited-during-launch error — with injected popen/probe
    (no real subprocess)."""
    spawned = {}

    class FakeProc:
        def __init__(self, argv):
            spawned["argv"] = argv
            self.returncode = None

        def poll(self):
            return self.returncode

        def terminate(self):
            self.returncode = -15

        def wait(self, timeout=None):
            return self.returncode

    probes = {"n": 0}

    def probe(url):
        probes["n"] += 1
        return probes["n"] >= 2  # ready on the second poll

    launcher = LocalProcessLauncher(
        "prime serve -m tiny --port {port} --replica-of {router}",
        router_url="http://127.0.0.1:9999",
        ready_timeout_s=5.0, probe_interval_s=0.01,
        popen_fn=lambda argv: FakeProc(argv), probe_fn=probe,
    )
    handle = launcher.spawn()
    argv = spawned["argv"]
    assert argv[:4] == ["prime", "serve", "-m", "tiny"]
    assert argv[argv.index("--replica-of") + 1] == "http://127.0.0.1:9999"
    port = int(argv[argv.index("--port") + 1])
    assert handle.url == f"http://127.0.0.1:{port}" and handle.alive()
    # a process that dies mid-launch surfaces, not hangs
    class DeadProc(FakeProc):
        def poll(self):
            return 1

    launcher_dead = LocalProcessLauncher(
        ["x", "--port", "{port}"], ready_timeout_s=1.0, probe_interval_s=0.01,
        popen_fn=lambda argv: DeadProc(argv), probe_fn=lambda url: False,
    )
    with pytest.raises(RuntimeError, match="exited during launch"):
        launcher_dead.spawn()


# ---- live fleet: churn, gauge accounting, endpoints, 1→N→1 ------------------


def _tight_slo() -> SloEvaluator:
    return SloEvaluator(
        (
            SloPolicy(name="ttft_p95", kind="latency",
                      metric="serve_ttft_seconds", threshold=0.3),
            SloPolicy(name="utilization_floor", kind="utilization_floor",
                      metric="serve_active_slots", threshold=0.1),
        ),
        fast_s=0.6, slow_s=1.6,
    )


def _replica_gauge(router) -> dict[str, float]:
    snap = router.registry.snapshot()["fleet_replicas"]["series"]
    return {s["labels"]["state"]: s["value"] for s in snap}


def test_fleet_replicas_gauge_never_double_counts():
    """Join/drain/re-join churn: the fleet_replicas{state} series always
    sum to the membership's replica count — a replica moving states must
    leave its old state's count, not linger in both."""
    backends = [_SlowBackend() for _ in range(2)]
    servers = [InferenceServer("tiny-test", b, port=0).start() for b in backends]
    extra = InferenceServer("tiny-test", _SlowBackend(), port=0).start()
    router = serve_fleet(
        [srv.url for srv in servers], poll_interval=0.05, model_id="tiny-test"
    )
    try:
        router.membership.poll_all()
        gauge = _replica_gauge(router)
        assert sum(gauge.values()) == 2 and gauge["ready"] == 2
        # join (twice — the second add must dedup, not double-count)
        for _ in range(2):
            r = httpx.post(
                f"{router.url}/admin/join", json={"url": extra.url}, timeout=5
            )
            assert r.status_code == 200
        router.membership.poll_all()
        router.observe_once()
        gauge = _replica_gauge(router)
        assert sum(gauge.values()) == 3 and gauge["ready"] == 3
        # drain one: it moves ready -> draining, total stays 3
        target = next(iter(router.membership.replicas))
        httpx.post(
            f"{router.url}/admin/drain", json={"replica": target}, timeout=5
        ).raise_for_status()
        router.membership.poll_all()
        router.observe_once()
        gauge = _replica_gauge(router)
        assert sum(gauge.values()) == 3
        assert gauge["draining"] == 1 and gauge["ready"] == 2
    finally:
        router.stop()
        for srv in [*servers, extra]:
            srv.stop()


def test_join_drain_mid_burst_loses_zero_requests():
    """Membership churn under load: a replica joining AND another draining
    mid-burst over real HTTP — every request completes 200, nothing lost,
    the drained replica finishes its in-flight work."""
    backends = [_SlowBackend(delay=0.05) for _ in range(2)]
    servers = [InferenceServer("tiny-test", b, port=0).start() for b in backends]
    joiner = InferenceServer("tiny-test", _SlowBackend(delay=0.05), port=0).start()
    router = serve_fleet(
        [srv.url for srv in servers], poll_interval=0.05, model_id="tiny-test"
    )
    results: list[int] = []
    lock = threading.Lock()

    def fire(i: int) -> None:
        r = httpx.post(
            f"{router.url}/v1/chat/completions",
            json={"messages": [{"role": "user", "content": f"burst {i} " * 8}]},
            timeout=30,
        )
        with lock:
            results.append(r.status_code)

    try:
        threads = [threading.Thread(target=fire, args=(i,)) for i in range(24)]
        for t in threads[:12]:
            t.start()
        # mid-burst churn: join a third replica, drain an original
        httpx.post(
            f"{router.url}/admin/join", json={"url": joiner.url}, timeout=5
        ).raise_for_status()
        target = next(iter(router.membership.replicas))
        httpx.post(
            f"{router.url}/admin/drain", json={"replica": target}, timeout=5
        ).raise_for_status()
        for t in threads[12:]:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert len(results) == 24
        assert all(code == 200 for code in results), results
        router.membership.poll_all()
        router.observe_once()
        gauge = _replica_gauge(router)
        assert sum(gauge.values()) == 3  # 2 original (1 draining) + joiner
    finally:
        router.stop()
        for srv in [*servers, joiner]:
            srv.stop()


@pytest.fixture
def elastic_fleet():
    """1 managed replica behind a router with a tight-window autoscaler —
    the live 1→N→1 rig (scripted backends: the leg tests the control
    loop, not matmuls)."""
    shared = {"ttft": 1.0, "slots": 4}
    launcher = _ServerLauncher(shared)
    router = serve_fleet([], poll_interval=0.05, model_id="tiny-test",
                         admin_token="elastic-secret")
    router.slo = _tight_slo()
    supervisor = ReplicaSupervisor(launcher, membership=router.membership)
    autoscaler = FleetAutoscaler(
        supervisor,
        AutoscalerConfig(
            min_replicas=1, max_replicas=3, up_cooldown_s=0.3, down_cooldown_s=0.5
        ),
    )
    router.attach_autoscaler(autoscaler)
    supervisor.scale_up(1)  # the seed replica is managed, so N-1 can retire
    try:
        yield router, shared, launcher
    finally:
        router.stop()
        for srv in launcher.servers:
            try:
                srv.stop()
            except Exception:  # noqa: BLE001 — retired replicas already stopped
                pass


def _chat_ok(url: str) -> bool:
    try:
        return (
            httpx.post(
                f"{url}/v1/chat/completions",
                json={"messages": [{"role": "user", "content": "elastic"}]},
                timeout=10,
            ).status_code
            == 200
        )
    except httpx.HTTPError:
        return False


@pytest.mark.slow
def test_live_storm_scales_1_to_n_to_1_with_zero_lost_requests(elastic_fleet):
    """Acceptance: live rate-storm-shaped load on a 1-replica fleet spawns
    replicas (1→N), every request serves 200, and the idle fleet drains
    back to 1 with all drains completing in-flight work."""
    router, shared, launcher = elastic_fleet
    ok = []
    stop = threading.Event()

    def storm():
        while not stop.is_set():
            ok.append(_chat_ok(router.url))

    workers = [threading.Thread(target=storm) for _ in range(4)]
    for w in workers:
        w.start()
    # storm phase: scripted TTFT far over the 0.3s objective
    deadline = time.monotonic() + 15
    peak = 1
    while time.monotonic() < deadline:
        with router.membership._lock:
            peak = max(peak, len(router.membership.replicas))
        if peak >= 2:
            break
        time.sleep(0.1)
    stop.set()
    for w in workers:
        w.join(timeout=30)
    assert peak >= 2, router.autoscaler_status()
    assert ok and all(ok), f"{ok.count(False)} lost of {len(ok)}"
    # idle phase: TTFT tiny, utilization zero -> drain back to 1
    shared["ttft"] = 0.01
    shared["slots"] = 0
    deadline = time.monotonic() + 30
    final = peak
    while time.monotonic() < deadline:
        with router.membership._lock:
            final = len(router.membership.replicas)
        if final == 1 and not router.autoscaler.supervisor.pending():
            break
        time.sleep(0.1)
    assert final == 1, router.autoscaler_status()
    status = router.autoscaler_status()
    ups = sum(e["count"] for e in status["journal"] if e["outcome"] == "spawned")
    downs = sum(e["count"] for e in status["journal"] if e["outcome"] == "retired")
    assert ups >= 1 and downs == ups
    # the actions metric counted the actuations
    snap = router.registry.snapshot()["fleet_autoscale_actions_total"]["series"]
    by_label = {
        (s["labels"]["direction"], s["labels"]["outcome"]): s["value"] for s in snap
    }
    assert by_label.get(("up", "spawned"), 0) >= 1
    assert by_label.get(("down", "retired"), 0) >= 1


def test_admin_autoscaler_endpoint_auth_and_pause(elastic_fleet):
    router, _shared, _launcher = elastic_fleet
    # auth parity on GET and POST
    assert httpx.get(f"{router.url}/admin/autoscaler", timeout=5).status_code == 403
    headers = {"Authorization": "Bearer elastic-secret"}
    status = httpx.get(
        f"{router.url}/admin/autoscaler", headers=headers, timeout=5
    ).json()
    assert status["enabled"] and status["state"] == "active"
    assert status["config"]["max_replicas"] == 3
    # pause -> decisions refuse -> resume
    r = httpx.post(
        f"{router.url}/admin/autoscaler", json={"action": "pause"},
        headers=headers, timeout=5,
    )
    assert r.status_code == 200 and r.json()["state"] == "paused"
    d = router.autoscaler.step(UP, state(replicas=1, retirable=1))
    assert d.outcome == "paused"
    r = httpx.post(
        f"{router.url}/admin/autoscaler", json={"action": "resume"},
        headers=headers, timeout=5,
    )
    assert r.status_code == 200 and r.json()["state"] == "active"
    bad = httpx.post(
        f"{router.url}/admin/autoscaler", json={"action": "explode"},
        headers=headers, timeout=5,
    )
    assert bad.status_code == 400
    # the observatory view carries the autoscaler section + managed states
    view = httpx.get(
        f"{router.url}/admin/observatory", headers=headers, timeout=5
    ).json()
    assert view["autoscaler"]["enabled"]
    assert all("managed" in row for row in view["replicas"])


def test_autoscaler_post_without_autoscaler_404s():
    backends = [_SlowBackend()]
    servers = [InferenceServer("tiny-test", b, port=0).start() for b in backends]
    router = serve_fleet([servers[0].url], poll_interval=5, model_id="tiny-test")
    try:
        assert (
            httpx.get(f"{router.url}/admin/autoscaler", timeout=5).json()["enabled"]
            is False
        )
        assert (
            httpx.post(
                f"{router.url}/admin/autoscaler", json={"action": "pause"}, timeout=5
            ).status_code
            == 404
        )
    finally:
        router.stop()
        servers[0].stop()


def test_serve_top_renders_role_managed_and_autoscaler(elastic_fleet):
    from click.testing import CliRunner

    from prime_tpu.commands.serve import serve_cmd

    router, _shared, _launcher = elastic_fleet
    router.membership.poll_all()
    result = CliRunner().invoke(
        serve_cmd,
        ["top", "--url", router.url, "--once", "--admin-token", "elastic-secret"],
    )
    assert result.exit_code == 0, result.output
    assert "autoscaler:" in result.output and "last action" in result.output
    # the text table may clip header names at narrow widths; the JSON view
    # below is the machine-checked column contract
    as_json = CliRunner().invoke(
        serve_cmd,
        ["top", "--url", router.url, "--once", "--admin-token", "elastic-secret",
         "--output", "json"],
    )
    assert as_json.exit_code == 0, as_json.output
    payload = json.loads(as_json.output)
    assert payload["autoscaler"]["enabled"] is True
    assert all("managed" in row and "role" in row for row in payload["replicas"])


def test_serve_fleet_cli_autoscale_requires_launch():
    from click.testing import CliRunner

    from prime_tpu.commands.serve import serve_cmd

    result = CliRunner().invoke(serve_cmd, ["fleet", "--autoscale", "--port", "0"])
    assert result.exit_code != 0
    assert "--launch" in result.output
