"""Batched multi-LoRA serving tests (CPU, tiny model).

The load-bearing properties (docs/architecture.md "Multi-LoRA serving"):

- **Bit-identity vs merged serving.** A request selecting adapter X through
  the batched gathered path emits exactly the greedy tokens a dedicated
  engine serving ``merge_lora(base, X)`` emits — across the overlap ×
  speculative × mesh matrix.
- **Mixed-wave isolation.** A tenant's adapter must never perturb another
  tenant's base-model tokens: bank slot 0 is the all-zeros base adapter and
  the per-row gather makes every row's math independent, so base requests
  in a mixed wave are bit-identical to a bankless engine's.
- **Prefix-cache isolation.** Cached KV is only valid under the adapter
  that computed it: adapter paths live in a salted key space, so a base
  request can never assemble an adapter's KV (or vice versa).
- **Fair admission.** Per-adapter round-robin pop with an optional
  ``adapter_max_inflight`` cap — one tenant's burst cannot starve others.
"""

import json

import jax
import jax.numpy as jnp
import pytest

from prime_tpu.models import get_config
from prime_tpu.models.llama import init_params
from prime_tpu.serve.adapters import load_adapter_bank, parse_adapter_spec
from prime_tpu.serve.engine import ContinuousBatchingEngine
from prime_tpu.train.lora import (
    LoraConfig,
    init_lora_params,
    merge_lora,
    save_adapters,
)

CONFIG = get_config("tiny-test")
PARAMS = init_params(jax.random.PRNGKey(0), CONFIG, dtype=jnp.float32)


@pytest.fixture(autouse=True)
def _default_env(monkeypatch):
    for knob in (
        "PRIME_SERVE_OVERLAP", "PRIME_SERVE_WARMUP", "PRIME_SERVE_MESH",
        "PRIME_SERVE_SPEC", "PRIME_SERVE_DRAFT_LEN", "PRIME_SERVE_ADAPTERS",
        "PRIME_SERVE_ADAPTER_MAX_INFLIGHT", "PRIME_SERVE_ADAPTER_WEIGHTS",
        "PRIME_SERVE_PREFIX_CACHE_MB",
    ):
        monkeypatch.delenv(knob, raising=False)


def make_factors(seed: int, lora: LoraConfig, scale: float = 0.05):
    """Trained-shaped random adapter factors: nonzero B so the adapter
    actually changes outputs (zero-init B is a no-op)."""
    factors = init_lora_params(jax.random.PRNGKey(seed), CONFIG, lora)
    factors["layers"] = {
        name: {
            "a": ab["a"],
            "b": (
                jax.random.normal(jax.random.PRNGKey(seed + 100), ab["b"].shape)
                * scale
            ).astype(ab["b"].dtype),
        }
        for name, ab in factors["layers"].items()
    }
    return factors


@pytest.fixture(scope="module")
def artifacts(tmp_path_factory):
    """Two saved adapter artifacts (different ranks — the bank must pad)
    plus their factor pytrees for merged references."""
    root = tmp_path_factory.mktemp("adapters")
    out = {}
    for name, seed, lora in (
        ("tenant-a", 1, LoraConfig(r=4, alpha=8)),
        ("tenant-b", 2, LoraConfig(r=2, alpha=4)),
    ):
        factors = make_factors(seed, lora)
        path = root / name
        save_adapters(path, factors, lora, CONFIG, base_params=PARAMS)
        out[name] = (str(path), factors, lora)
    return out


def make_engine(params=PARAMS, **kw) -> ContinuousBatchingEngine:
    kw.setdefault("max_slots", 4)
    kw.setdefault("capacity", 128)
    kw.setdefault("chunk", 4)
    kw.setdefault("prefix_cache_mb", 0)
    return ContinuousBatchingEngine(params, CONFIG, **kw)


def drain(engine, *requests, max_ticks=400):
    for _ in range(max_ticks):
        engine.tick()
        if all(r.done for r in requests):
            return
    raise AssertionError("requests did not finish")


def run_one(engine, prompt, n=10, adapter=None):
    req = engine.submit(prompt, max_new_tokens=n, adapter=adapter)
    drain(engine, req)
    return req.all_tokens(timeout=2)


PROMPT = list(range(5, 41))  # 36 tokens: spans two radix blocks


# ---- bank construction -------------------------------------------------------


def test_parse_adapter_spec():
    assert parse_adapter_spec("a=/x,b=/y") == {"a": "/x", "b": "/y"}
    assert parse_adapter_spec("") == {}
    assert parse_adapter_spec(" a = /x , ") == {"a": "/x"}
    with pytest.raises(ValueError, match="name=path"):
        parse_adapter_spec("justaname")
    with pytest.raises(ValueError, match="reserved"):
        parse_adapter_spec("base=/x")
    with pytest.raises(ValueError, match="duplicate"):
        parse_adapter_spec("a=/x,a=/y")


def test_bank_load_pads_ranks_and_reserves_base(artifacts):
    bank = load_adapter_bank(
        {name: path for name, (path, _, _) in artifacts.items()},
        PARAMS, CONFIG,
    )
    assert bank.names[0] == "base"
    assert bank.adapter_names == ("tenant-a", "tenant-b")
    assert bank.rank == 4  # max over (4, 2): tenant-b pads
    assert bank.index_of(None) == 0 and bank.index_of("base") == 0
    assert bank.index_of("tenant-a") == 1
    with pytest.raises(KeyError):
        bank.index_of("nope")
    # slot 0 is exactly zero: base rides the gathered matmul as a no-op
    for ab in bank.stacks["layers"].values():
        assert float(jnp.abs(ab["a"][:, 0]).max()) == 0.0
        assert float(jnp.abs(ab["b"][:, 0]).max()) == 0.0


def test_bank_rejects_wrong_base_fingerprint(tmp_path, artifacts):
    lora = LoraConfig(r=4, alpha=8)
    factors = make_factors(7, lora)
    other_base = init_params(jax.random.PRNGKey(99), CONFIG, dtype=jnp.float32)
    save_adapters(tmp_path / "bad", factors, lora, CONFIG, base_params=other_base)
    with pytest.raises(ValueError, match="DIFFERENT base weights"):
        load_adapter_bank({"bad": tmp_path / "bad"}, PARAMS, CONFIG)


def test_bank_rejects_wrong_base_model(tmp_path):
    other = get_config("debug-128m")
    other_params = init_params(jax.random.PRNGKey(0), other, dtype=jnp.float32)
    lora = LoraConfig(r=2, alpha=4)
    factors = init_lora_params(jax.random.PRNGKey(0), other, lora)
    save_adapters(tmp_path / "other", factors, lora, other, base_params=other_params)
    with pytest.raises(ValueError, match="trained on"):
        load_adapter_bank({"other": tmp_path / "other"}, PARAMS, CONFIG)


# ---- bit-identity vs merged serving ------------------------------------------


@pytest.mark.parametrize("overlap", [False, True])
@pytest.mark.parametrize("speculative", [False, True])
def test_adapter_bit_identity_vs_merged(artifacts, overlap, speculative):
    """The acceptance matrix: an adapter request through the batched
    gathered path emits the SAME greedy tokens as a dedicated engine
    serving merge_lora(base, adapter) — overlap × speculative."""
    path, factors, lora = artifacts["tenant-a"]
    merged_engine = make_engine(merge_lora(PARAMS, factors, lora))
    reference = run_one(merged_engine, PROMPT, n=12)
    merged_engine.shutdown()

    engine = make_engine(
        adapters={"tenant-a": path}, overlap=overlap, speculative=speculative,
    )
    got = run_one(engine, PROMPT, n=12, adapter="tenant-a")
    engine.shutdown()
    assert got == reference


def test_second_adapter_matches_its_own_merge(artifacts):
    """Adapter selection gathers the RIGHT slot: tenant-b's tokens match
    merge_lora(base, tenant-b), not tenant-a's."""
    engine = make_engine(
        adapters={name: p for name, (p, _, _) in artifacts.items()},
    )
    got_a = run_one(engine, PROMPT, n=10, adapter="tenant-a")
    got_b = run_one(engine, PROMPT, n=10, adapter="tenant-b")
    engine.shutdown()
    for name, got in (("tenant-a", got_a), ("tenant-b", got_b)):
        _, factors, lora = artifacts[name]
        ref_engine = make_engine(merge_lora(PARAMS, factors, lora))
        assert got == run_one(ref_engine, PROMPT, n=10), name
        ref_engine.shutdown()
    assert got_a != got_b  # the two fine-tunes genuinely diverge


def test_mixed_wave_isolation(artifacts):
    """Tenant A's adapter never perturbs tenant B's base tokens: a mixed
    concurrent wave's base members are bit-identical to a bankless engine,
    and its adapter members to their merged references."""
    bankless = make_engine()
    base_ref = run_one(bankless, PROMPT, n=10)
    base_ref2 = run_one(bankless, [7, 8, 9, 10, 11], n=10)
    bankless.shutdown()

    engine = make_engine(
        adapters={name: p for name, (p, _, _) in artifacts.items()},
    )
    reqs = [
        engine.submit(PROMPT, max_new_tokens=10, adapter="tenant-a"),
        engine.submit(PROMPT, max_new_tokens=10),
        engine.submit([7, 8, 9, 10, 11], max_new_tokens=10),
        engine.submit(PROMPT, max_new_tokens=10, adapter="tenant-b"),
    ]
    drain(engine, *reqs)
    assert reqs[1].all_tokens(timeout=2) == base_ref
    assert reqs[2].all_tokens(timeout=2) == base_ref2
    _, factors_a, lora_a = artifacts["tenant-a"]
    ref = make_engine(merge_lora(PARAMS, factors_a, lora_a))
    assert reqs[0].all_tokens(timeout=2) == run_one(ref, PROMPT, n=10)
    ref.shutdown()
    engine.shutdown()


def test_base_traffic_on_banked_engine_matches_bankless(artifacts):
    """Bank slot 0 is an exact zero: loading a bank changes NOTHING for
    base traffic (greedy tokens identical to a bankless engine)."""
    bankless = make_engine(prefix_cache_mb=8)
    ref = run_one(bankless, PROMPT, n=12)
    bankless.shutdown()
    banked = make_engine(
        prefix_cache_mb=8,
        adapters={"tenant-a": artifacts["tenant-a"][0]},
    )
    assert run_one(banked, PROMPT, n=12) == ref
    banked.shutdown()


def test_lora_kernel_engine_bit_identity_vs_einsum(artifacts, monkeypatch):
    """The fused gathered-LoRA pallas kernel vs the einsum reference chain
    at ENGINE level: the same mixed-adapter wave with the kernel forced in
    (interpret mode, CPU) emits bit-identical greedy tokens to the einsum
    path. Only the LoRA projection is flipped — attention and everything
    else stay on the exact same code — so any token drift is the kernel's
    rounding contract breaking."""
    from prime_tpu.models import llama
    from prime_tpu.ops import pallas_lora

    adapters = {name: p for name, (p, _, _) in artifacts.items()}
    wave = [
        (PROMPT, "tenant-a"),
        (PROMPT, None),
        (PROMPT, "tenant-b"),
        ([7, 8, 9, 10, 11], "tenant-a"),
    ]

    def run():
        engine = make_engine(adapters=adapters)
        reqs = [
            engine.submit(list(p), max_new_tokens=10, adapter=ad)
            for p, ad in wave
        ]
        drain(engine, *reqs)
        out = [r.all_tokens(timeout=2) for r in reqs]
        engine.shutdown()
        return out

    einsum_out = run()

    calls = []
    real = pallas_lora.fused_lora_matmul

    def forced(*args, **kw):
        calls.append(1)
        kw["interpret"] = True
        return real(*args, **kw)

    monkeypatch.setattr(llama, "_lora_kernel_eligible", lambda w, x, b: True)
    monkeypatch.setattr(pallas_lora, "fused_lora_matmul", forced)
    jax.clear_caches()  # the gate is trace-time: force a re-trace
    try:
        kernel_out = run()
    finally:
        jax.clear_caches()  # don't leak kernel-path traces past the patch
    assert calls, "the fused kernel never dispatched"
    assert kernel_out == einsum_out


# ---- prefix-cache isolation --------------------------------------------------


def test_prefix_cache_never_crosses_adapters(artifacts):
    """The salted key space: serving a prompt under tenant-a caches its KV,
    but the SAME prompt under base (or tenant-b) must not prefix-hit it —
    and same-adapter repeats must."""
    engine = make_engine(
        prefix_cache_mb=32,
        adapters={name: p for name, (p, _, _) in artifacts.items()},
    )
    run_one(engine, PROMPT + [50], n=4, adapter="tenant-a")
    hits0 = engine.prefix_hits
    # same prompt, other tenants: no hit (a cross hit would serve KV
    # computed under the wrong weights)
    run_one(engine, PROMPT + [51], n=4)
    run_one(engine, PROMPT + [52], n=4, adapter="tenant-b")
    assert engine.prefix_hits == hits0
    # same adapter again: hit
    run_one(engine, PROMPT + [53], n=4, adapter="tenant-a")
    assert engine.prefix_hits == hits0 + 1
    # and the hit-seeded tokens are still bit-identical to merged serving
    _, factors, lora = artifacts["tenant-a"]
    ref = make_engine(merge_lora(PARAMS, factors, lora))
    reference = run_one(ref, PROMPT + [53], n=4)
    ref.shutdown()
    hit = run_one(engine, PROMPT + [53], n=4, adapter="tenant-a")
    assert hit == reference
    engine.shutdown()


# ---- fair admission ----------------------------------------------------------


def test_fair_pop_round_robins_across_adapters(artifacts):
    """A burst of one tenant queued ahead of another must not starve it:
    with 2 slots and 4 queued requests of tenant-a followed by 2 of base,
    the round-robin pop interleaves tenants instead of FIFO-draining a."""
    engine = make_engine(
        max_slots=2,
        adapters={"tenant-a": artifacts["tenant-a"][0]},
    )
    a_reqs = [
        engine.submit(PROMPT, max_new_tokens=4, adapter="tenant-a")
        for _ in range(4)
    ]
    b_reqs = [engine.submit([9, 9, 9], max_new_tokens=4) for _ in range(2)]
    engine._admit()  # one wave: 2 slots
    admitted = {r.adapter_idx for r in engine._requests.values()}
    # one slot per tenant, not two tenant-a slots
    assert admitted == {0, 1}
    drain(engine, *a_reqs, *b_reqs)
    engine.shutdown()


def test_adapter_max_inflight_caps_one_tenant(artifacts):
    """adapter_max_inflight=1: no tenant (base included — base is tenant 0)
    ever holds more than one admitted slot even with free capacity, one
    admission wave cannot blow past the cap, and the capped backlog stays
    counted (queue_depth/drained) and still completes."""
    engine = make_engine(
        max_slots=4,
        adapters={"tenant-a": artifacts["tenant-a"][0]},
        adapter_max_inflight=1,
    )
    a_reqs = [
        engine.submit(PROMPT, max_new_tokens=4, adapter="tenant-a")
        for _ in range(3)
    ]
    base_reqs = [engine.submit([9, 9, 9], max_new_tokens=4) for _ in range(2)]
    engine._admit()
    by_adapter: dict[int, int] = {}
    for r in engine._requests.values():
        by_adapter[r.adapter_idx] = by_adapter.get(r.adapter_idx, 0) + 1
    assert by_adapter == {0: 1, 1: 1}  # one slot per tenant, cap respected
    # the capped backlog is still counted and still completes
    assert engine.queue_depth() == 3
    drain(engine, *a_reqs, *base_reqs)
    assert engine.queue_depth() == 0
    engine.shutdown()


def test_weighted_shares_pop_order_pin(artifacts):
    """WEIGHTED round-robin (ROADMAP item 3 follow-up): tenant-a at weight
    2 pops twice per rotation, INTERLEAVED — the smooth-WRR sequence for
    weights {base: 1, a: 2} with both backlogged is a, base, a, a, base, a
    (never a-a back to back at a rotation boundary, never base starved)."""
    engine = make_engine(
        adapters={"tenant-a": artifacts["tenant-a"][0]},
        adapter_weights={"tenant-a": 2},
    )
    assert engine.adapter_weights == {"tenant-a": 2}
    for _ in range(4):
        engine.submit(PROMPT, max_new_tokens=2, adapter="tenant-a")
        engine.submit([9, 9, 9], max_new_tokens=2)
    order = [engine._pop_pending().adapter_idx for _ in range(6)]
    assert order == [1, 0, 1, 1, 0, 1]  # idx 1 = tenant-a, idx 0 = base
    engine.shutdown()


def test_weighted_shares_uniform_is_plain_round_robin(artifacts):
    """Default (no weights) must reproduce the historical rotation: two
    backlogged tenants alternate strictly."""
    engine = make_engine(adapters={"tenant-a": artifacts["tenant-a"][0]})
    for _ in range(3):
        engine.submit(PROMPT, max_new_tokens=2, adapter="tenant-a")
        engine.submit([9, 9, 9], max_new_tokens=2)
    order = [engine._pop_pending().adapter_idx for _ in range(6)]
    assert order in ([0, 1, 0, 1, 0, 1], [1, 0, 1, 0, 1, 0])
    engine.shutdown()


def test_weighted_shares_validation(artifacts):
    from prime_tpu.serve.adapters import parse_adapter_weights

    with pytest.raises(ValueError, match="name=K"):
        parse_adapter_weights("broken")
    with pytest.raises(ValueError, match=">= 1"):
        parse_adapter_weights("a=0")
    with pytest.raises(ValueError, match="duplicate"):
        parse_adapter_weights("a=1,a=2")
    # weights without a bank are a loud construction error
    with pytest.raises(ValueError, match="bank"):
        make_engine(adapter_weights={"tenant-a": 2})
    # an unknown tenant name is a loud construction error too
    with pytest.raises(KeyError):
        make_engine(
            adapters={"tenant-a": artifacts["tenant-a"][0]},
            adapter_weights={"nope": 2},
        )


def test_weighted_shares_env_wiring(monkeypatch, artifacts):
    monkeypatch.setenv("PRIME_SERVE_ADAPTER_WEIGHTS", "tenant-a=3,base=2")
    engine = make_engine(adapters={"tenant-a": artifacts["tenant-a"][0]})
    assert engine.adapter_weights == {"tenant-a": 3, "base": 2}
    assert engine._fair_weights == {0: 2, 1: 3}
    assert engine.stats()["adapter_weights"] == {"tenant-a": 3, "base": 2}
    engine.shutdown()


def test_env_wiring(monkeypatch, artifacts):
    path = artifacts["tenant-a"][0]
    monkeypatch.setenv("PRIME_SERVE_ADAPTERS", f"tenant-a={path}")
    monkeypatch.setenv("PRIME_SERVE_ADAPTER_MAX_INFLIGHT", "3")
    engine = make_engine()
    assert engine.adapter_bank is not None
    assert engine.adapter_bank.adapter_names == ("tenant-a",)
    assert engine.adapter_max_inflight == 3
    stats = engine.stats()
    assert stats["adapters_loaded"] == 1 and stats["adapters"] == ["tenant-a"]
    engine.shutdown()
    # kwarg beats env
    monkeypatch.setenv("PRIME_SERVE_ADAPTERS", "tenant-a=/nonexistent")
    engine = make_engine(adapters={"tenant-a": path})
    assert engine.adapter_bank is not None
    engine.shutdown()


# ---- obs ---------------------------------------------------------------------


def test_adapter_token_and_ttft_metrics(artifacts):
    engine = make_engine(
        adapters={"tenant-a": artifacts["tenant-a"][0]},
    )
    run_one(engine, PROMPT, n=6, adapter="tenant-a")
    run_one(engine, [7, 8, 9], n=4)
    snap = engine.registry.snapshot()
    tokens = {
        s["labels"]["adapter"]: s["value"]
        for s in snap["serve_adapter_tokens_total"]["series"]
    }
    assert tokens == {"tenant-a": 6.0, "base": 4.0}
    ttft = {
        s["labels"]["adapter"]: s["count"]
        for s in snap["serve_adapter_ttft_seconds"]["series"]
    }
    assert ttft == {"tenant-a": 1, "base": 1}
    assert engine.registry.values()["serve_adapters_loaded"] == 1.0
    engine.shutdown()


def test_bankless_engine_has_no_adapter_series():
    engine = make_engine()
    run_one(engine, [5, 6, 7], n=4)
    snap = engine.registry.snapshot()
    assert snap["serve_adapter_tokens_total"]["series"] == []
    engine.shutdown()


# ---- server + fleet ----------------------------------------------------------


def test_server_model_registry_and_fleet_adapter_affinity(artifacts):
    """E2E over real HTTP: /v1/models lists adapters, unknown models 404
    with the authoritative list, /healthz advertises the bank, and the
    router narrows adapter traffic to the replica holding the adapter
    (fleet_adapter_routed_total pinned)."""
    import time

    import httpx

    from prime_tpu.loadgen.backends import NumericTokenizer
    from prime_tpu.serve.engine import EngineBackend
    from prime_tpu.serve.fleet import serve_fleet
    from prime_tpu.serve.server import InferenceServer

    base_engine = make_engine(prefix_cache_mb=8)
    lora_engine = make_engine(
        prefix_cache_mb=8,
        adapters={"tenant-a": artifacts["tenant-a"][0]},
    )
    for e in (base_engine, lora_engine):
        e.start()
    s0 = InferenceServer(
        "m", EngineBackend(base_engine, NumericTokenizer()), port=0
    ).start()
    s1 = InferenceServer(
        "m", EngineBackend(lora_engine, NumericTokenizer()), port=0
    ).start()
    router = serve_fleet([s0.url, s1.url], poll_interval=0.2, model_id="m")
    try:
        assert httpx.get(f"{s1.url}/healthz").json().get("adapters") == ["tenant-a"]
        assert "adapters" not in httpx.get(f"{s0.url}/healthz").json()
        models = [m["id"] for m in httpx.get(f"{s1.url}/v1/models").json()["data"]]
        assert models == ["m", "tenant-a"]
        r = httpx.post(
            f"{s1.url}/v1/chat/completions",
            json={"model": "nope", "messages": [{"role": "user", "content": "5"}]},
        )
        assert r.status_code == 404 and "tenant-a" in r.json()["error"]["message"]
        # wait for the poller to learn the advertisement
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if any(
                replica.adapters
                for replica in router.membership.replicas.values()
            ):
                break
            time.sleep(0.05)
        prompt = " ".join(str(i) for i in range(5, 41))
        for _ in range(3):
            r = httpx.post(
                f"{router.url}/v1/chat/completions",
                json={
                    "model": "tenant-a",
                    "messages": [{"role": "user", "content": prompt}],
                    "max_tokens": 4,
                },
                timeout=120,
            )
            assert r.status_code == 200, r.text
            assert r.json()["model"] == "tenant-a"
        stats = router.stats()
        assert stats["adapter_routed"] == {"tenant-a": 3}
        # every adapter request landed on the adapter-holding replica
        lora_id = [
            rid for rid, rep in router.membership.replicas.items()
            if rep.adapters
        ][0]
        served = stats["requests_by_replica"].get(lora_id, {})
        assert sum(served.values()) == 3
    finally:
        router.stop()
        s0.stop()
        s1.stop()


def test_membership_parses_adapter_advertisement_tolerantly():
    from prime_tpu.serve.digest import parse_adapters
    from prime_tpu.serve.fleet.membership import FleetMembership, Replica

    membership = FleetMembership()
    replica = Replica("http://127.0.0.1:1")
    for junk in (None, 7, "x", {"a": 1}, [1, 2], ["", "x" * 1000]):
        membership.apply_health(replica, {"adapters": junk}, 200)
        assert replica.adapters == frozenset()
    membership.apply_health(replica, {"adapters": ["a", "b", 3, "a"]}, 200)
    assert replica.adapters == frozenset({"a", "b"})
    assert parse_adapters(["ok"] * 5000) == frozenset({"ok"})


def test_balancer_adapter_affinity_unit():
    from prime_tpu.serve.fleet.balancer import PrefixAffinityBalancer
    from prime_tpu.serve.fleet.membership import FleetMembership

    membership = FleetMembership()
    r1 = membership.add("http://127.0.0.1:1")
    r2 = membership.add("http://127.0.0.1:2")
    for r in (r1, r2):
        r.state = "ready"
    r2.adapters = frozenset({"tenant-a"})
    balancer = PrefixAffinityBalancer(membership)
    prompt = "x" * 256
    # adapter traffic narrows to the holder, whatever the ring says
    for _ in range(4):
        pick = balancer.pick(prompt, adapter="tenant-a")
        assert pick is not None and pick.replica.id == r2.id
        assert pick.adapter_routed
    # base traffic is unaffected; unknown adapters degrade to the full pool
    pick = balancer.pick(prompt)
    assert pick is not None and not pick.adapter_routed
    pick = balancer.pick(prompt, adapter="unknown")
    assert pick is not None and not pick.adapter_routed
    # the holder excluded: adapter affinity cannot resurrect it
    pick = balancer.pick(prompt, {r2.id}, adapter="tenant-a")
    assert pick is not None and pick.replica.id == r1.id


# ---- loadgen integration -----------------------------------------------------


def test_scenario_row_adapter_split(artifacts):
    """EngineTarget honors PlannedRequest.adapter and scenario_row splits
    tokens/TTFT per adapter from the labeled families."""
    from prime_tpu.loadgen.backends import EngineTarget
    from prime_tpu.loadgen.report import scenario_row
    from prime_tpu.loadgen.runner import run_schedule
    from prime_tpu.loadgen.scenario import Phase, Scenario, build_schedule

    scenario = Scenario(
        "mix", 3,
        (
            Phase(
                kind="mixed", n=4, tenants=2, prompt_tokens=20,
                max_new_tokens=4, adapters=("base", "tenant-a"),
            ),
        ),
        vocab=CONFIG.vocab_size,
    )
    schedule = build_schedule(scenario)
    engine = make_engine(
        adapters={"tenant-a": artifacts["tenant-a"][0]},
    )
    try:
        result = run_schedule(
            schedule, EngineTarget(engine), scenario="mix", seed=3,
            time_scale=0.0,
        )
        row = scenario_row(result)
    finally:
        engine.shutdown()
    split = row["adapters"]
    assert set(split) == {"base", "tenant-a"}
    assert split["base"]["tokens"] == split["tenant-a"]["tokens"] == 8
    assert split["tenant-a"]["ttft_s"]["p50"] is not None
    assert json.dumps(row)  # the row stays JSON-serializable


def test_router_model_alias_rewrites_forwarded_body(artifacts):
    """--model-alias placement must also REWRITE the forwarded body: the
    replica resolves the model field against its own adapter ids, not the
    router-side alias — and a base alias must serve the base model."""
    import httpx

    from prime_tpu.loadgen.backends import NumericTokenizer
    from prime_tpu.serve.engine import EngineBackend
    from prime_tpu.serve.fleet.router import FleetRouter
    from prime_tpu.serve.server import InferenceServer

    engine = make_engine(
        prefix_cache_mb=8,
        adapters={"tenant-a": artifacts["tenant-a"][0]},
    )
    engine.start()
    srv = InferenceServer(
        "m", EngineBackend(engine, NumericTokenizer()), port=0
    ).start()
    router = FleetRouter(
        [srv.url], poll_interval=0.2, model_id="m",
        model_registry={"fancy": "tenant-a", "plain": None},
    ).start()
    try:
        for alias, served in (("fancy", "tenant-a"), ("plain", "m")):
            r = httpx.post(
                f"{router.url}/v1/chat/completions",
                json={
                    "model": alias,
                    "messages": [{"role": "user", "content": "5 6 7 8"}],
                    "max_tokens": 4,
                },
                timeout=120,
            )
            assert r.status_code == 200, (alias, r.text)
            assert r.json()["model"] == served
        # the adapter really served the aliased request
        tokens = {
            s["labels"]["adapter"]: s["value"]
            for s in engine.registry.snapshot()["serve_adapter_tokens_total"]["series"]
        }
        assert tokens.get("tenant-a", 0) > 0
    finally:
        router.stop()
        srv.stop()


def test_serve_model_rejects_adapters_with_weight_quant():
    from prime_tpu.serve.server import serve_model

    with pytest.raises(ValueError, match="weight-quant"):
        serve_model(
            "tiny-test", continuous=True, weight_quant=True,
            adapters={"a": "/nonexistent"}, port=0,
        )
