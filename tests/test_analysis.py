"""prime-lint (prime_tpu/analysis) — fixture tests per rule, waiver/pragma
suppression, the catalog-mode exposition lint, and the real-tree gate.

Each checker is driven through an in-memory Project so the fixtures are
visible next to their assertions; the final tests run the full suite over
the actual repo and assert it is clean modulo the checked-in baseline —
the same contract CI's `analysis` job enforces via
`python -m prime_tpu.analysis --check`.
"""

from __future__ import annotations

import textwrap
from pathlib import Path

import pytest

from prime_tpu.analysis import (
    DEFAULT_BASELINE,
    apply_baseline,
    jit_boundary,
    knob_registry,
    load_baseline,
    lock_discipline,
    obs_contract,
    run_checks,
)
from prime_tpu.analysis.core import Project, Waiver, _parse_toml

REPO_ROOT = Path(__file__).resolve().parent.parent


def project(src: str, path: str = "prime_tpu/serve/mod.py", docs: dict | None = None):
    return Project({path: textwrap.dedent(src)}, docs=docs)


def rules_of(findings):
    return [f.rule for f in findings]


# ---- lock-discipline --------------------------------------------------------


LOCKED_CLASS = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._n = 0

    def add(self, x):
        with self._lock:
            self._items.append(x)
            self._n += 1
"""


def test_lock_unlocked_read_is_flagged():
    findings = lock_discipline.check(
        project(LOCKED_CLASS + "\n    def peek(self):\n        return self._items[-1]\n")
    )
    assert [f.symbol for f in findings] == ["C._items"]
    assert findings[0].rule == "lock-discipline"


def test_lock_clean_class_passes():
    findings = lock_discipline.check(
        project(
            LOCKED_CLASS
            + "\n    def peek(self):\n        with self._lock:\n            return self._items[-1]\n"
        )
    )
    assert findings == []


def test_lock_held_docstring_helper_is_recognized():
    src = LOCKED_CLASS + '''
    def _drop(self):
        """Remove the tail. Caller holds the lock."""
        self._items.pop()
'''
    assert lock_discipline.check(project(src)) == []


def test_lock_threadsafe_containers_exempt():
    src = """
    import queue, threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._q = queue.Queue()

        def push(self, x):
            with self._lock:
                self._q.put(x)

        def pop(self):
            return self._q.get()
    """
    assert lock_discipline.check(project(src)) == []


def test_lock_nested_def_under_with_is_not_locked():
    # a closure defined under the lock runs later, when the lock is free
    src = LOCKED_CLASS + """
    def make_reader(self):
        with self._lock:
            def reader():
                return self._items[-1]
        return reader
"""
    findings = lock_discipline.check(project(src))
    assert [f.symbol for f in findings] == ["C._items"]


def test_lock_outer_self_alias_nested_class():
    # the serve server idiom: outer = self handed to a nested handler class
    src = """
    import threading

    class S:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0
            outer = self

            class Handler:
                def inc(self):
                    with outer._lock:
                        outer._count += 1

                def bad_read(self):
                    return outer._count

            self.handler_cls = Handler

        def snapshot(self):
            return self._count
    """
    findings = lock_discipline.check(project(src))
    assert sorted((f.symbol, f.rule) for f in findings) == [
        ("S._count", "lock-discipline"),
        ("S._count", "lock-discipline"),
    ]
    # both the nested handler's unlocked read and the method read are hit
    labels = sorted(f.message.split(" touches")[0] for f in findings)
    assert labels == ["S.__init__.bad_read", "S.snapshot"]


def test_pragma_suppresses_any_rule_centrally():
    # pragmas are applied once in run_checks, for every checker uniformly
    src = LOCKED_CLASS + (
        "\n    def peek(self):\n"
        "        return self._items[-1]  # prime-lint: ignore[lock-discipline] benign\n"
    )
    assert run_checks(project(src), ["lock"]) == []
    knob = """
    import os

    def f():
        return os.environ.get("PRIME_X")  # prime-lint: ignore[knob-direct-read, knob-undocumented] legacy
    """
    doc = "| env | CLI flag | default |\n|---|---|---|\n"
    assert (
        run_checks(project(knob, docs={"docs/architecture.md": doc}), ["knobs"]) == []
    )


# ---- jit boundary -----------------------------------------------------------


JIT_CLASS = """
import jax, time

class E:
    def _make(self):
        def run(params, state):
            return state
        return jax.jit(run, donate_argnums=(1,))

    def setup(self):
        self._fn = self._make()
"""


def test_jit_purity_flags_host_state():
    src = """
    import jax, time

    def builder():
        def run(x):
            t = time.monotonic()
            print(x)
            return x
        return jax.jit(run)
    """
    findings = jit_boundary.check(project(src))
    offenders = {f.symbol for f in findings}
    assert offenders == {"run:time.monotonic", "run:print"}
    assert all(f.rule == "jit-purity" for f in findings)


def test_jit_purity_decorated_partial():
    src = """
    import jax, os
    from functools import partial

    @partial(jax.jit, static_argnums=(1,))
    def run(x, n):
        if os.environ.get("PRIME_X"):
            return x
        return x + n
    """
    findings = [f for f in jit_boundary.check(project(src)) if f.rule == "jit-purity"]
    assert [f.symbol for f in findings] == ["run:os.environ.get"]


def test_jit_purity_obs_layer_flagged_and_pure_fn_clean():
    src = """
    import jax

    class E:
        def _make(self):
            def run(params, state):
                self._m_tokens.inc()
                return state
            return jax.jit(run)

        def _make_pure(self):
            def pure(params, state):
                return params + state
            return jax.jit(pure)
    """
    findings = jit_boundary.check(project(src))
    assert [f.symbol for f in findings] == ["run:self._m_tokens"]


def test_jit_donation_use_after_donate():
    src = JIT_CLASS + """
    def step(self, state):
        out = self._fn(self.params, state)
        return state
"""
    findings = [f for f in jit_boundary.check(project(src)) if f.rule == "jit-donation"]
    assert [f.symbol for f in findings] == ["step:state"]


def test_jit_donation_rebind_clears():
    src = JIT_CLASS + """
    def step(self, state):
        state = self._fn(self.params, state)
        return state
"""
    assert [f for f in jit_boundary.check(project(src)) if f.rule == "jit-donation"] == []


def test_jit_donation_self_attr_tainted():
    src = JIT_CLASS + """
    def step(self):
        out = self._fn(self.params, self._state)
        return self._state
"""
    findings = [f for f in jit_boundary.check(project(src)) if f.rule == "jit-donation"]
    assert [f.symbol for f in findings] == ["step:self._state"]


def test_jit_donation_local_jit_binding():
    src = """
    import jax

    def caller(g, state):
        f = jax.jit(g, donate_argnums=(0,))
        out = f(state)
        return state
    """
    findings = [f for f in jit_boundary.check(project(src)) if f.rule == "jit-donation"]
    assert [f.symbol for f in findings] == ["caller:state"]


# ---- obs contract -----------------------------------------------------------


OBS_DOC = """
## Metrics catalog

| metric | type | labels |
|---|---|---|
| `serve_good_total` | counter | — |
| `serve_stale_total` | counter | — |
| `serve_kind_seconds` | gauge | — |

### Span catalog

| span | meaning |
|---|---|
| `serve.good` | fine |
| `serve.stale` | row without a code site |
"""

OBS_SRC = """
class E:
    def __init__(self, r, TRACER):
        self._a = r.counter("serve_good_total", "ok")
        self._b = r.counter("serve_missing_total", "no doc row")
        self._c = r.histogram("serve_kind_seconds", "doc says gauge")
        with TRACER.span("serve.good"):
            pass
        TRACER.emit("serve.undocumented", 1.0)
"""


def test_obs_contract_bidirectional():
    p = project(OBS_SRC, docs={"docs/observability.md": OBS_DOC})
    findings = obs_contract.check(p)
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f.symbol)
    assert by_rule == {
        "obs-metric-undocumented": ["serve_missing_total"],
        "obs-metric-stale": ["serve_stale_total"],
        "obs-metric-kind-drift": ["serve_kind_seconds"],
        "obs-span-undocumented": ["serve.undocumented"],
        "obs-span-stale": ["serve.stale"],
    }


def test_obs_contract_missing_doc():
    findings = obs_contract.check(project(OBS_SRC, docs={}))
    assert rules_of(findings) == ["obs-catalog-missing"]


def test_obs_doc_fences_do_not_swallow_prose():
    doc = OBS_DOC + '\n```json\n{"name": "serve.x"}\n```\nand `serve.undocumented` in prose\n'
    p = project(OBS_SRC, docs={"docs/observability.md": doc})
    assert "obs-span-undocumented" not in rules_of(obs_contract.check(p))


def test_load_metrics_catalog():
    catalog = obs_contract.load_metrics_catalog(OBS_DOC)
    assert catalog == {
        "serve_good_total": "counter",
        "serve_stale_total": "counter",
        "serve_kind_seconds": "gauge",
    }


def test_exposition_lint_catalog_mode():
    from prime_tpu.obs.metrics import lint_prometheus_text

    catalog = {"a_total": "counter", "b_seconds": "histogram"}
    ok = "# HELP a_total help\n# TYPE a_total counter\na_total 1\n"
    assert lint_prometheus_text(ok, catalog=catalog) == []
    # type drift vs catalog
    drift = "# HELP a_total h\n# TYPE a_total gauge\na_total 1\n"
    assert any("documents counter" in p for p in lint_prometheus_text(drift, catalog=catalog))
    # exposed family the catalog has never heard of
    unknown = "# HELP x_total h\n# TYPE x_total counter\nx_total 1\n"
    assert any("absent from the metrics catalog" in p for p in lint_prometheus_text(unknown, catalog=catalog))
    # cataloged family exposed without HELP
    nohelp = "# TYPE a_total counter\na_total 1\n"
    assert any("without a HELP line" in p for p in lint_prometheus_text(nohelp, catalog=catalog))
    # no catalog -> classic behavior, none of the above fire
    assert lint_prometheus_text(nohelp) == []


# ---- knob registry ----------------------------------------------------------


KNOB_DOC = """
## Environment knobs

| env | CLI flag | default | meaning |
|---|---|---|---|
| `PRIME_GOOD_FLAG` | — | on | documented and consistent |
| `PRIME_STALE_KNOB` | — | unset | row without any code mention |
| `PRIME_DRIFTY` | — | 5 | code default disagrees |
| `PRIME_PAIRED` | `--paired` | 7 | CLI flag default disagrees |
"""

KNOB_SRC = """
import os
from prime_tpu.core.config import env_flag, env_int

GOOD_DEFAULT = True

def f():
    a = env_flag("PRIME_GOOD_FLAG", GOOD_DEFAULT)
    b = env_int("PRIME_DRIFTY", 9)
    c = env_int("PRIME_PAIRED", 7)
    d = env_int("PRIME_UNDOCUMENTED", 0)
    e = os.environ.get("PRIME_DIRECT")
    return a, b, c, d, e
"""

KNOB_CLI = """
import click

@click.option("--paired", type=int, default=3)
def cmd(paired):
    return paired
"""


def test_knob_registry_rules():
    p = Project(
        {
            "prime_tpu/serve/mod.py": textwrap.dedent(KNOB_SRC),
            "prime_tpu/commands/x.py": textwrap.dedent(KNOB_CLI),
        },
        docs={"docs/architecture.md": KNOB_DOC},
    )
    findings = knob_registry.check(p)
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, set()).add(f.symbol)
    assert by_rule == {
        "knob-direct-read": {"PRIME_DIRECT"},
        "knob-undocumented": {"PRIME_UNDOCUMENTED", "PRIME_DIRECT"},
        "knob-stale-doc": {"PRIME_STALE_KNOB"},
        "knob-default-drift": {"PRIME_DRIFTY", "PRIME_PAIRED"},
    }


def test_knob_module_constant_resolution_and_env_write_ok():
    src = """
    import os
    from prime_tpu.core.config import env_int

    DEFAULT = 4

    def f():
        os.environ["PRIME_CHILD"] = "1"   # a write is not a read
        return env_int("PRIME_OK", DEFAULT)
    """
    doc = """
| env | CLI flag | default | meaning |
|---|---|---|---|
| `PRIME_OK` | — | 4 | fine |
| `PRIME_CHILD` | — | unset | exported for children |
"""
    p = project(src, docs={"docs/architecture.md": doc})
    assert knob_registry.check(p) == []


# ---- baseline / waivers -----------------------------------------------------


def test_waiver_suppresses_and_stale_is_reported():
    findings = lock_discipline.check(
        project(LOCKED_CLASS + "\n    def peek(self):\n        return self._items[-1]\n")
    )
    waivers = [
        Waiver("lock-discipline", "prime_tpu/serve/mod.py", "C._items", "ok"),
        Waiver("lock-discipline", "prime_tpu/serve/mod.py", "C._gone", "stale"),
    ]
    active, waived, stale = apply_baseline(findings, waivers)
    assert active == [] and len(waived) == 1
    assert [w.symbol for w in stale] == ["C._gone"]


def test_baseline_requires_reason(tmp_path):
    bad = tmp_path / "baseline.toml"
    bad.write_text('[[waiver]]\nrule = "x"\npath = "y"\nsymbol = "z"\n')
    with pytest.raises(ValueError, match="missing required"):
        load_baseline(bad)


def test_fallback_toml_parser(monkeypatch):
    import prime_tpu.utils.compat as compat

    monkeypatch.setattr(compat, "TOMLLIB_AVAILABLE", False)
    text = DEFAULT_BASELINE.read_text()
    data = _parse_toml(text, "baseline.toml")
    assert all(
        {"rule", "path", "symbol", "reason"} <= set(w) for w in data.get("waiver", [])
    )
    with pytest.raises(ValueError, match="unsupported TOML"):
        _parse_toml("[table]\nkey = 3\n", "x.toml")


# ---- the real tree ----------------------------------------------------------


def test_real_tree_clean_modulo_baseline():
    """The CI `analysis` job's contract: the repo has no non-waived findings
    and no stale waivers. A checker regression (fixture tests above) and a
    tree regression both fail here."""
    findings = run_checks(Project.from_root(REPO_ROOT))
    waivers = load_baseline(DEFAULT_BASELINE)
    active, _waived, stale = apply_baseline(findings, waivers)
    assert active == [], "non-waived findings:\n" + "\n".join(
        f.render() for f in active
    )
    assert stale == [], "stale waivers: " + ", ".join(w.symbol for w in stale)


def test_real_tree_fixture_violation_fails_check(tmp_path):
    """`--check` exits non-zero the moment a violation is introduced."""
    from prime_tpu.analysis.__main__ import main

    pkg = tmp_path / "prime_tpu"
    pkg.mkdir()
    (pkg / "bad.py").write_text(
        "import os\n\ndef f():\n    return os.environ.get('PRIME_PLANTED')\n"
    )
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "architecture.md").write_text("| env | CLI flag | default |\n|---|---|---|\n")
    (docs / "observability.md").write_text("## Metrics catalog\n")
    rc = main(["--check", "--root", str(tmp_path), "--no-baseline"])
    assert rc == 1
    assert main(["--root", str(tmp_path), "--no-baseline"]) == 0  # report mode


def test_cli_rules_subset_leaves_other_waivers_dormant():
    """--rules obs must not report the lock-discipline baseline waiver as
    stale just because the lock checker never ran (regression)."""
    from prime_tpu.analysis.__main__ import main

    assert main(["--check", "--root", str(REPO_ROOT), "--rules", "obs"]) == 0


def test_cli_github_format(tmp_path, capsys):
    from prime_tpu.analysis.__main__ import main

    pkg = tmp_path / "prime_tpu"
    pkg.mkdir()
    (pkg / "bad.py").write_text(
        "import os\nX = os.environ.get('PRIME_PLANTED')\n"
    )
    (tmp_path / "docs").mkdir()
    rc = main(["--check", "--root", str(tmp_path), "--format", "github", "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "::error file=prime_tpu/bad.py" in out
    assert "prime-lint[knob-direct-read]" in out
