"""Eval endpoint aliasing + launch preflights (VERDICT r3 missing #2, weak #6).

Reference behavior being matched (verifiers_bridge.py:823-897): alias
resolution from configs/endpoints.toml, model-id validation, and a 1-token
billing probe that 402s BEFORE anything is provisioned — plus the hosted
polish items: local-only flags hard-fail with --hosted, and log polling
tolerates the startup window where the log endpoint 404s.
"""

import json

import pytest
from click.testing import CliRunner

import prime_tpu.commands._deps as deps
from prime_tpu.commands.main import cli
from prime_tpu.testing import FakeControlPlane


@pytest.fixture
def fake(monkeypatch):
    fake = FakeControlPlane()
    monkeypatch.setattr(deps, "transport_override", fake.transport)
    monkeypatch.setenv("PRIME_API_KEY", "test-key")
    monkeypatch.setenv("PRIME_BASE_URL", "https://api.fake")
    monkeypatch.setenv("PRIME_INFERENCE_URL", "https://inference.fake/v1")
    return fake


@pytest.fixture
def runner():
    return CliRunner()


@pytest.fixture
def no_poll_wait(monkeypatch):
    import prime_tpu.commands.evals as ev_cmd

    monkeypatch.setattr(ev_cmd, "POLL_INTERVAL_S", 0)


# -- alias resolution ----------------------------------------------------------


def test_alias_table_resolution(tmp_path):
    from prime_tpu.evals.endpoints import EvalPreflightError, resolve_endpoint_alias

    table = tmp_path / "endpoints.toml"
    table.write_text(
        '[smoke]\nmodel = "llama3-8b"\nbase_url = "https://inference.fake/v1/"\n'
        '[rename-only]\nmodel = "tiny-test"\n'
    )
    hit = resolve_endpoint_alias("smoke", table)
    assert hit.model == "llama3-8b"
    assert hit.base_url == "https://inference.fake/v1"  # trailing / stripped
    rename = resolve_endpoint_alias("rename-only", table)
    assert rename.model == "tiny-test" and rename.base_url is None
    assert resolve_endpoint_alias("unknown-model", table) is None
    # implicit default path missing -> no aliasing; EXPLICIT path missing ->
    # error (a typo'd --endpoints-path must not silently skip aliasing)
    assert resolve_endpoint_alias("whatever") is None
    with pytest.raises(EvalPreflightError, match="does not exist"):
        resolve_endpoint_alias("whatever", tmp_path / "absent.toml")

    # malformed entries must raise, not silently fall through
    table.write_text("[broken]\nbase_url = 'https://x'\n")
    with pytest.raises(EvalPreflightError, match="model"):
        resolve_endpoint_alias("broken", table)
    table.write_text("not [valid toml")
    with pytest.raises(EvalPreflightError, match="Malformed"):
        resolve_endpoint_alias("anything", table)


def test_endpoint_backed_eval_through_api_generator(runner, fake, tmp_path, no_poll_wait):
    """An alias with a base_url runs the whole eval pipeline against the
    remote OpenAI-compatible endpoint (ApiGenerator) — no local weights."""
    table = tmp_path / "endpoints.toml"
    table.write_text('[smoke]\nmodel = "llama3-8b"\nbase_url = "https://inference.fake/v1"\n')
    result = runner.invoke(
        cli,
        [
            "eval", "run", "synthetic-arith", "-m", "smoke", "-n", "4",
            "--no-push", "--endpoints-path", str(table),
            "--output-dir", str(tmp_path / "runs"), "--output", "json",
        ],
    )
    assert result.exit_code == 0, result.output
    payload = json.loads(result.output[result.output.index("{"):])
    assert payload["metrics"]["num_samples"] == 4
    run_dir = payload["runDir"]
    rows = [
        json.loads(line)
        for line in open(f"{run_dir}/results.jsonl")
        if line.strip()
    ]
    # the fake endpoint echoes the prompt — proof generation went remote
    assert all(r["completion"].startswith("echo: ") for r in rows)


def test_endpoint_backed_eval_rejects_local_runner_flags(runner, fake, tmp_path):
    table = tmp_path / "endpoints.toml"
    table.write_text('[smoke]\nmodel = "llama3-8b"\nbase_url = "https://inference.fake/v1"\n')
    result = runner.invoke(
        cli,
        [
            "eval", "run", "synthetic-arith", "-m", "smoke", "--kv-quant",
            "--endpoints-path", str(table),
        ],
    )
    assert result.exit_code != 0
    assert "--kv-quant" in result.output


def test_endpoint_backed_eval_fails_fast_on_402(runner, fake, tmp_path):
    fake.misc_plane.payment_required = True
    table = tmp_path / "endpoints.toml"
    table.write_text('[smoke]\nmodel = "llama3-8b"\nbase_url = "https://inference.fake/v1"\n')
    result = runner.invoke(
        cli,
        ["eval", "run", "synthetic-arith", "-m", "smoke", "--endpoints-path", str(table)],
    )
    assert result.exit_code != 0
    assert "balance" in result.output


# -- hosted preflights ---------------------------------------------------------


def test_hosted_402_fails_before_submission(runner, fake, no_poll_wait):
    """The billing probe 402s -> the run aborts and NO hosted eval was ever
    created on the platform."""
    fake.misc_plane.payment_required = True
    result = runner.invoke(cli, ["eval", "run", "gsm8k", "-m", "llama3-8b", "--hosted"])
    assert result.exit_code != 0
    assert "balance" in result.output
    assert fake.evals_plane.hosted == {}


def test_hosted_invalid_model_fails_before_submission(runner, fake, no_poll_wait):
    result = runner.invoke(cli, ["eval", "run", "gsm8k", "-m", "not-a-model", "--hosted"])
    assert result.exit_code != 0
    assert "Invalid model" in result.output
    assert fake.evals_plane.hosted == {}


def test_hosted_alias_resolves_then_preflights(runner, fake, tmp_path, no_poll_wait):
    """--hosted with a rename alias: the PLATFORM model id is submitted."""
    table = tmp_path / "endpoints.toml"
    table.write_text('[prod]\nmodel = "llama3-70b"\n')
    result = runner.invoke(
        cli,
        [
            "eval", "run", "gsm8k", "-m", "prod", "--hosted",
            "--endpoints-path", str(table), "--output", "json",
        ],
    )
    assert result.exit_code == 0, result.output
    run = json.loads(result.output[result.output.index("{"):])
    assert run["model"] == "llama3-70b"


def test_hosted_rejects_base_url_alias(runner, fake, tmp_path):
    """--hosted runs on the platform; an alias pinned to an endpoint must
    conflict loudly, not silently evaluate a different deployment."""
    table = tmp_path / "endpoints.toml"
    table.write_text('[ep]\nmodel = "llama3-8b"\nbase_url = "https://foreign/v1"\n')
    result = runner.invoke(
        cli,
        ["eval", "run", "gsm8k", "-m", "ep", "--hosted", "--endpoints-path", str(table)],
    )
    assert result.exit_code != 0
    assert "base_url" in result.output and "--hosted" in result.output
    assert fake.evals_plane.hosted == {}


def test_preflight_timeout_warns_and_continues(monkeypatch, fake):
    """APIClient wraps httpx timeouts into APITimeoutError — the preflight
    must treat that as 'still warming up', not 'invalid model'."""
    import prime_tpu.commands._deps as deps_mod
    from prime_tpu.core.exceptions import APITimeoutError
    from prime_tpu.evals import endpoints as ep_mod

    monkeypatch.setenv("PRIME_API_KEY", "test-key")

    class TimeoutClient:
        def retrieve_model(self, model):
            raise APITimeoutError("GET /models timed out")

        def chat_completion(self, *a, **k):
            raise APITimeoutError("POST /chat/completions timed out")

    monkeypatch.setattr(ep_mod, "_preflight_client", lambda base: TimeoutClient())
    warnings: list[str] = []
    ep_mod.validate_model("llama3-8b", warn=warnings.append)
    ep_mod.preflight_billing("llama3-8b", warn=warnings.append)
    assert len(warnings) == 2 and all("Timed out" in w for w in warnings)
    del deps_mod


def test_hosted_rejects_local_only_flags(runner, fake):
    """Local-only flags are a hard error with --hosted, not a warning
    (a user who asked for int8 KV must not silently get different physics)."""
    result = runner.invoke(
        cli,
        ["eval", "run", "gsm8k", "-m", "llama3-8b", "--hosted", "--kv-quant", "--speculative"],
    )
    assert result.exit_code != 0
    assert "--kv-quant" in result.output and "--speculative" in result.output
    assert fake.evals_plane.hosted == {}


# -- hosted log polling tolerance ----------------------------------------------


def test_hosted_log_startup_404s_tolerated(runner, fake, no_poll_wait):
    """Logs 404 for the first fetches (runner not attached yet): the poll
    loop waits instead of crashing, then completes normally."""
    fake.evals_plane.hosted_log_startup_404s = 2
    fake.evals_plane.hosted_complete_after = 4
    result = runner.invoke(
        cli, ["eval", "run", "gsm8k", "-m", "llama3-8b", "--hosted", "--output", "json"]
    )
    assert result.exit_code == 0, result.output
    run = json.loads(result.output[result.output.index("{"):])
    assert run["status"] == "COMPLETED"
    assert "waiting for the hosted eval" in result.output


def test_hosted_log_404_past_window_raises(runner, fake, no_poll_wait, monkeypatch):
    import prime_tpu.commands.evals as ev_cmd

    monkeypatch.setattr(ev_cmd, "LOG_STARTUP_MAX_POLLS", 1)
    fake.evals_plane.hosted_log_startup_404s = 10**6
    fake.evals_plane.hosted_complete_after = 10**6
    result = runner.invoke(cli, ["eval", "run", "gsm8k", "-m", "llama3-8b", "--hosted"])
    assert result.exit_code != 0


def test_eval_logs_follow_tolerates_startup(runner, fake, no_poll_wait):
    import httpx

    resp = fake.handle(
        httpx.Request(
            "POST",
            "https://api.fake/api/v1/evals/hosted",
            headers={"Authorization": "Bearer test-key"},
            content=json.dumps({"env": "e", "model": "m"}).encode(),
        )
    )
    hid = resp.json()["hostedId"]
    fake.evals_plane.hosted_log_startup_404s = 2
    fake.evals_plane.hosted_complete_after = 5  # outlive the 404 window
    result = runner.invoke(cli, ["eval", "logs", hid, "--follow"])
    assert result.exit_code == 0, result.output
    assert "hosted eval step" in result.output
